"""Benchmark: one full scheduling round on the device (TPU when available).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "...", "vs_baseline": N}

Baseline: the reference guards a production round with
maxSchedulingDuration=5s (config/scheduler/config.yaml:83) at
"tens of thousands of nodes / millions of queued jobs" scale.
vs_baseline = 5.0 / measured_round_seconds (higher is better).
"""

import json
import os
import sys
import time

N_NODES = int(os.environ.get("BENCH_NODES", 5000))
N_JOBS = int(os.environ.get("BENCH_JOBS", 100_000))
N_QUEUES = int(os.environ.get("BENCH_QUEUES", 10))
# Running preemptible jobs (exercises eviction + fair preemption paths).
N_RUNNING = int(os.environ.get("BENCH_RUNNING", 0))


def build_inputs():
    import numpy as np

    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
    from armada_tpu.snapshot.round import build_round_snapshot
    from armada_tpu.solver.kernel_prep import prep_device_round

    cfg = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5 if N_RUNNING else 1.0,
        # Fast mode: batch the multi-queue sweep (set-exact vs the serial
        # loop when everything fits; see SchedulingConfig.enable_fast_fill).
        enable_fast_fill=os.environ.get("BENCH_FAST_FILL", "1") == "1",
    )
    rng = np.random.default_rng(0)
    nodes = [
        NodeSpec(
            id=f"node-{i:05d}",
            pool="default",
            total_resources={"cpu": "32", "memory": "256Gi"},
        )
        for i in range(N_NODES)
    ]
    queues = [QueueSpec(f"queue-{i:02d}", 1.0) for i in range(N_QUEUES)]
    cpus = rng.choice([1, 2, 4, 8], size=N_JOBS)
    qidx = rng.integers(0, N_QUEUES, size=N_JOBS)
    queued = [
        JobSpec(
            id=f"job-{i:07d}",
            queue=f"queue-{qidx[i]:02d}",
            priority_class="low",
            requests={"cpu": str(int(cpus[i])), "memory": f"{int(cpus[i]) * 2}Gi"},
            submitted_ts=float(i),
        )
        for i in range(N_JOBS)
    ]
    from armada_tpu.core.types import RunningJob

    # Running jobs all in one hog queue (over fair share -> evicted and
    # mostly rescheduled, driving the eviction + fair-preemption machinery).
    running = [
        RunningJob(
            job=JobSpec(
                id=f"run-{i:07d}",
                queue="queue-00",
                priority_class="low",
                requests={"cpu": "2", "memory": "4Gi"},
                submitted_ts=float(-N_RUNNING + i),
            ),
            node_id=f"node-{i % N_NODES:05d}",
            scheduled_at_priority=1000,
        )
        for i in range(N_RUNNING)
    ]
    global _last_inputs
    _last_inputs = (cfg, "default", nodes, queues, running, queued)
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    return prep_device_round(snap)


def main():
    from armada_tpu.core.resources import ensure_native
    from armada_tpu.utils.platform import ensure_healthy_backend

    ensure_native()  # C++ quantity parser (one-time build on fresh checkouts)
    ensure_healthy_backend()

    t_setup = time.time()
    dev = build_inputs()
    setup_s = time.time() - t_setup

    # Steady-state host cost: the service re-snapshots the SAME job/node
    # objects every cycle, so the second build (spec row caches warm) is
    # the per-cycle number; the first includes imports + input synthesis.
    from armada_tpu.snapshot.round import build_round_snapshot
    from armada_tpu.solver.kernel_prep import prep_device_round as _prep

    cfg, pool, nodes, queues, running, queued = _last_inputs
    t0 = time.time()
    snap = build_round_snapshot(cfg, pool, nodes, queues, running, queued)
    warm_snapshot_s = time.time() - t0
    t0 = time.time()
    dev = _prep(snap)
    warm_prep_s = time.time() - t0

    import jax

    from armada_tpu.solver.kernel import solve_round

    platform = jax.devices()[0].platform
    # Host->device transfer measured apart from the solve: production
    # overlaps the next round's upload with event I/O (AsyncRunner), and
    # on this rig the transfer rides a network tunnel, not PCIe.
    import numpy as _np

    t0 = time.time()
    dev_resident = jax.tree_util.tree_map(
        lambda x: jax.device_put(x) if isinstance(x, _np.ndarray) else x, dev
    )
    jax.block_until_ready(
        [x for x in jax.tree_util.tree_leaves(dev_resident)
         if hasattr(x, "block_until_ready")]
    )
    h2d_s = time.time() - t0

    t0 = time.time()
    out = solve_round(dev_resident)  # compile + run
    compile_s = time.time() - t0

    t0 = time.time()
    out = solve_round(dev_resident)
    round_s = time.time() - t0

    from armada_tpu.utils import platform as plat

    scheduled = int(out["scheduled_mask"].sum())
    result = {
        "metric": (
            f"scheduling_round_latency({N_JOBS} jobs x {N_NODES} nodes, "
            f"{N_QUEUES} queues, burst-limited, {platform})"
        ),
        "value": round(round_s, 4),
        "unit": "s",
        "vs_baseline": round(5.0 / round_s, 2),
        "extra": {
            "scheduled_jobs": scheduled,
            "compile_s": round(compile_s, 1),
            # setup_s includes imports + synthetic input generation; the
            # warm numbers are the real per-cycle host cost.
            "snapshot_build_s": round(setup_s, 1),
            "warm_snapshot_s": round(warm_snapshot_s, 3),
            "warm_prep_s": round(warm_prep_s, 3),
            "h2d_s": round(h2d_s, 3),
            "round_with_h2d_s": round(round_s + h2d_s, 3),
            "loops": int(out["num_loops"]),
            "platform_probe": plat.last_probe_report.get("reason", ""),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
