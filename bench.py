"""Benchmark: full scheduling rounds on the device (TPU when available).

Runs TWO configs and prints ONE JSON line (the flagship):

  1. tracking: 100k jobs x 5k nodes  — like-for-like vs earlier rounds,
     reported under extra.tracking_100k.
  2. flagship: 1M jobs x 50k nodes   — the north-star config
     (BASELINE.json: one round < 1s on v5e-8; the reference guards a
     production round with maxSchedulingDuration=5s,
     config/scheduler/config.yaml:83, at "tens of thousands of nodes /
     millions of queued jobs" scale). vs_baseline = 5.0 / round_seconds.

The platform the numbers were measured on is part of the metric string and
extra.platform_probe records why (e.g. TPU tunnel probe failures).

Env overrides: BENCH_JOBS/BENCH_NODES/BENCH_QUEUES/BENCH_RUNNING pick a
single custom config instead; BENCH_FLAGSHIP=0 skips the 1M x 50k run;
BENCH_FAST_FILL=0 runs the serial parity-mode fill.
"""

import json
import os
import sys
import time

N_QUEUES = int(os.environ.get("BENCH_QUEUES", 10))
# Running preemptible jobs (exercises eviction + fair preemption paths).
N_RUNNING = int(os.environ.get("BENCH_RUNNING", 0))


def build_inputs(n_jobs, n_nodes):
    import numpy as np

    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, RunningJob

    cfg = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5 if N_RUNNING else 1.0,
        # Fast mode: batch the multi-queue sweep (set-exact vs the serial
        # loop when everything fits; see SchedulingConfig.enable_fast_fill).
        enable_fast_fill=os.environ.get("BENCH_FAST_FILL", "1") == "1",
    )
    rng = np.random.default_rng(0)
    nodes = [
        NodeSpec(
            id=f"node-{i:05d}",
            pool="default",
            total_resources={"cpu": "32", "memory": "256Gi"},
        )
        for i in range(n_nodes)
    ]
    queues = [QueueSpec(f"queue-{i:02d}", 1.0) for i in range(N_QUEUES)]
    cpus = rng.choice([1, 2, 4, 8], size=n_jobs)
    qidx = rng.integers(0, N_QUEUES, size=n_jobs)
    queued = [
        JobSpec(
            id=f"job-{i:07d}",
            queue=f"queue-{qidx[i]:02d}",
            priority_class="low",
            requests={"cpu": str(int(cpus[i])), "memory": f"{int(cpus[i]) * 2}Gi"},
            submitted_ts=float(i),
        )
        for i in range(n_jobs)
    ]
    # Running jobs all in one hog queue (over fair share -> evicted and
    # mostly rescheduled, driving the eviction + fair-preemption machinery).
    running = [
        RunningJob(
            job=JobSpec(
                id=f"run-{i:07d}",
                queue="queue-00",
                priority_class="low",
                requests={"cpu": "2", "memory": "4Gi"},
                submitted_ts=float(-N_RUNNING + i),
            ),
            node_id=f"node-{i % n_nodes:05d}",
            scheduled_at_priority=1000,
        )
        for i in range(N_RUNNING)
    ]
    return cfg, "default", nodes, queues, running, queued


def run_config(n_jobs, n_nodes):
    """One cold + one warm cycle at (n_jobs, n_nodes); returns timings."""
    import jax
    import numpy as _np

    from armada_tpu.snapshot.round import build_round_snapshot
    from armada_tpu.solver.kernel import solve_round
    from armada_tpu.solver.kernel_prep import prep_device_round

    t_setup = time.time()
    inputs = build_inputs(n_jobs, n_nodes)
    snap = build_round_snapshot(*inputs)
    dev = prep_device_round(snap)
    setup_s = time.time() - t_setup

    # Steady-state host cost: the service re-snapshots the SAME job/node
    # objects every cycle, so the second build (spec row caches warm) is
    # the per-cycle number; the first includes input synthesis.
    t0 = time.time()
    snap = build_round_snapshot(*inputs)
    warm_snapshot_s = time.time() - t0
    t0 = time.time()
    dev = prep_device_round(snap)
    warm_prep_s = time.time() - t0

    # Host->device transfer measured apart from the solve: production
    # overlaps the next round's upload with event I/O (AsyncRunner), and
    # on this rig the transfer rides a network tunnel, not PCIe.
    t0 = time.time()
    dev_resident = jax.tree_util.tree_map(
        lambda x: jax.device_put(x) if isinstance(x, _np.ndarray) else x, dev
    )
    jax.block_until_ready(
        [x for x in jax.tree_util.tree_leaves(dev_resident)
         if hasattr(x, "block_until_ready")]
    )
    h2d_s = time.time() - t0

    t0 = time.time()
    out = solve_round(dev_resident)  # compile + run
    compile_s = time.time() - t0

    t0 = time.time()
    out = solve_round(dev_resident)
    round_s = time.time() - t0

    return {
        "round_s": round(round_s, 4),
        "scheduled_jobs": int(out["scheduled_mask"].sum()),
        "loops": int(out["num_loops"]),
        "compile_s": round(compile_s, 1),
        "snapshot_build_s": round(setup_s, 1),
        "warm_snapshot_s": round(warm_snapshot_s, 3),
        "warm_prep_s": round(warm_prep_s, 3),
        "h2d_s": round(h2d_s, 3),
        "round_with_h2d_s": round(round_s + h2d_s, 3),
    }


def main():
    from armada_tpu.core.resources import ensure_native
    from armada_tpu.utils.platform import ensure_healthy_backend

    ensure_native()  # C++ quantity parser (one-time build on fresh checkouts)
    ensure_healthy_backend()

    import jax

    from armada_tpu.utils import platform as plat

    platform = jax.devices()[0].platform

    custom = any(
        k in os.environ
        for k in ("BENCH_JOBS", "BENCH_NODES", "BENCH_QUEUES", "BENCH_RUNNING")
    )
    if custom:
        n_jobs = int(os.environ.get("BENCH_JOBS", 100_000))
        n_nodes = int(os.environ.get("BENCH_NODES", 5000))
        flag = run_config(n_jobs, n_nodes)
        tracking = None
    else:
        n_jobs, n_nodes = 1_000_000, 50_000
        tracking = run_config(100_000, 5000)
        if os.environ.get("BENCH_FLAGSHIP", "1") == "1":
            flag = run_config(n_jobs, n_nodes)
        else:
            flag, (n_jobs, n_nodes) = tracking, (100_000, 5000)
            tracking = None

    extra = dict(flag)
    round_s = extra.pop("round_s")
    extra["platform_probe"] = plat.last_probe_report.get("reason", "")
    if tracking is not None:
        extra["tracking_100k"] = tracking
    result = {
        "metric": (
            f"scheduling_round_latency({n_jobs} jobs x {n_nodes} nodes, "
            f"{N_QUEUES} queues, burst-limited, {platform})"
        ),
        "value": round_s,
        "unit": "s",
        "vs_baseline": round(5.0 / round_s, 2),
        "extra": extra,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
