"""Benchmark: full scheduling rounds on the device (TPU when available).

The headline metric is the WARM END-TO-END CYCLE on the flagship config —
what a production scheduler pays per round at steady state: apply last
round's leases + fresh submissions to the resident `IncrementalRound`
state, assemble the snapshot, prep the device tensors (PrepCache), upload,
and solve. This is the number to compare against the reference's 5s
`maxSchedulingDuration` guard (config/scheduler/config.yaml:83); the
round-4 headline measured the solve alone and hid a 5.5s host rebuild.

Configs (one JSON line printed, flagship as the headline):

  1. tracking: 100k jobs x 5k nodes  — like-for-like vs earlier rounds,
     reported under extra.tracking_100k.
  2. flagship: 1M jobs x 50k nodes   — the north-star config
     (BASELINE.json: one round < 1s on v5e-8). vs_baseline = 5.0 / value.
  3. burst_50k: flagship with the scheduling burst raised to 50k jobs per
     round — the regime where batched fast-fill and while_loop trip
     counts actually matter (reference operating point:
     config/scheduler/config.yaml:101-108). Under extra.burst_50k.

The platform the numbers were measured on is part of the metric string and
extra.platform_probe records why (e.g. the TPU tunnel relay being down —
docs/tpu_tunnel_postmortem.md).

Env overrides: BENCH_JOBS/BENCH_NODES/BENCH_QUEUES/BENCH_RUNNING pick a
single custom config instead (BENCH_BURST raises its per-round
scheduling burst — the forced-rewindow regime at custom scale); BENCH_FLAGSHIP=0 skips the 1M x 50k runs;
BENCH_BURST50K=0 skips the burst run; BENCH_FAST_FILL=0 runs the serial
parity-mode fill; BENCH_WARM_CYCLES sets the warm-sample count (>=2,
default 5); BENCH_ROUND_BUDGET_S runs every solve through the
budget-aware chunked driver (maxSchedulingDuration) and reports
truncation — the burst_50k config with BENCH_ROUND_BUDGET_S=5 is the
round-deadline acceptance scenario; BENCH_HOT_WINDOW sets the per-queue
hot-window compaction size (0 disables; default: 2x the fill window);
BENCH_FILL_WINDOW sets batch_fill_window (wide windows amortize the
per-group candidate sort, the dominant per-loop cost at 50k nodes);
ARMADA_TPU_KERNEL_PATH picks the solve kernel path (default here:
"blocked" — the fused scoring body + radix-threshold selection from
armada_tpu/ops/pallas_kernels.py; =lax reproduces the pre-kernel bench
for the A/B, =pallas runs the pallas interpret path, =native engages
real-TPU pallas + the ICI ring winner exchange) and the resolved path
lands under extra.kernels;
BENCH_TUNED=<tuned.json> applies the tools/autotune.py profile matching
this host's target signature (hot window + budgeted chunk stride) to
every config — the A/B against the static defaults is just the same
bench run with and without the variable; the effective (possibly tuned)
parameters are always recorded under extra.params so artifacts are
self-describing either way; BENCH_SPANS=<path> exports every measured
warm cycle's phase spans as OTLP-JSON lines (tools/trace2perfetto.py
renders the run in Perfetto).

The LAST stdout line is always one JSON object with an "ok" flag — on
any failure it carries ok=false and the error instead of silently dying
mid-run, so artifact parsers (tools/bench_trend.py, tools/bench_gate.py)
never see a half-written result.
"""

import json
import os
import time

# The XLA CPU AOT loader logs a full machine-feature dump per
# cache-entry mismatch ("could lead to ... SIGILL"), flooding bench
# tails. The compile-cache key now includes the effective XLA target
# features (utils/platform.py) so mismatched entries miss instead of
# load; the residual one-time warnings are log noise, not signal.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

N_QUEUES = int(os.environ.get("BENCH_QUEUES", 10))
# Running preemptible jobs (exercises eviction + fair preemption paths).
N_RUNNING = int(os.environ.get("BENCH_RUNNING", 0))


def resolve_fill_window(fill_window=None) -> int:
    """The effective batch_fill_window: BENCH_FILL_WINDOW env override,
    else the per-config value, else 2048. One resolution shared by
    build_inputs and run_config's hot-window sizing so the '~2x the fill
    window' invariant cannot drift between the two sites."""
    return int(os.environ.get("BENCH_FILL_WINDOW", fill_window or 2048))


def tuned_params():
    """The BENCH_TUNED profile entry matching this host's target
    signature, as a TunedParams, or None (no profile / no match).
    Resolved once per process."""
    global _TUNED
    if _TUNED is not _UNSET:
        return _TUNED
    _TUNED = None
    path = os.environ.get("BENCH_TUNED")
    if path:
        from armada_tpu.autotune import TunedParams, TuningStore, current_target

        store = TuningStore()
        store.merge_json(path)
        entry = store.lookup(current_target(), "default")
        if entry is None:
            print(f"# BENCH_TUNED: no entry in {path} matches this target; "
                  "running static defaults")
        else:
            _TUNED = TunedParams.from_dict(entry["params"])
            print(f"# BENCH_TUNED: applying {entry['params']} "
                  f"(source={entry.get('source')})")
    return _TUNED


_UNSET = object()
_TUNED = _UNSET


def build_inputs(n_jobs, n_nodes, burst=None, fill_window=None):
    import numpy as np

    from armada_tpu.core.config import (
        PriorityClass,
        RateLimits,
        SchedulingConfig,
    )
    from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, RunningJob

    kw = {}
    if burst:
        kw["rate_limits"] = RateLimits(
            maximum_scheduling_rate=float(burst),
            maximum_scheduling_burst=burst,
            maximum_per_queue_scheduling_burst=burst,
        )
    cfg = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5 if N_RUNNING else 1.0,
        # Fast mode: batch the multi-queue sweep (set-exact vs the serial
        # loop when everything fits; see SchedulingConfig.enable_fast_fill).
        enable_fast_fill=os.environ.get("BENCH_FAST_FILL", "1") == "1",
        # Wide fill windows amortize the per-group best-fit candidate
        # sort (the dominant per-loop cost at 50k+ nodes) over more
        # placements per loop; burst drains in ~3 merged loops at 2048.
        # The tracking config keeps the historical 512 (like-for-like).
        batch_fill_window=resolve_fill_window(fill_window),
        **kw,
    )
    rng = np.random.default_rng(0)
    nodes = [
        NodeSpec(
            id=f"node-{i:05d}",
            pool="default",
            total_resources={"cpu": "32", "memory": "256Gi"},
        )
        for i in range(n_nodes)
    ]
    queues = [QueueSpec(f"queue-{i:02d}", 1.0) for i in range(N_QUEUES)]
    cpus = rng.choice([1, 2, 4, 8], size=n_jobs)
    qidx = rng.integers(0, N_QUEUES, size=n_jobs)
    queued = [
        JobSpec(
            id=f"job-{i:07d}",
            queue=f"queue-{qidx[i]:02d}",
            priority_class="low",
            requests={"cpu": str(int(cpus[i])), "memory": f"{int(cpus[i]) * 2}Gi"},
            submitted_ts=float(i),
        )
        for i in range(n_jobs)
    ]
    # Running jobs all in one hog queue (over fair share -> evicted and
    # mostly rescheduled, driving the eviction + fair-preemption machinery).
    running = [
        RunningJob(
            job=JobSpec(
                id=f"run-{i:07d}",
                queue="queue-00",
                priority_class="low",
                requests={"cpu": "2", "memory": "4Gi"},
                submitted_ts=float(-N_RUNNING + i),
            ),
            node_id=f"node-{i % n_nodes:05d}",
            scheduled_at_priority=1000,
        )
        for i in range(N_RUNNING)
    ]
    return cfg, "default", nodes, queues, running, queued


def _put(dev):
    import jax
    import numpy as np

    from armada_tpu.observe import note_up

    # Transfer ledger: the explicit warm-cycle upload — what a
    # device-resident round (ROADMAP 1) would mostly eliminate.
    note_up(dev, site="bench.put")
    out = jax.tree_util.tree_map(
        lambda x: jax.device_put(x) if isinstance(x, np.ndarray) else x, dev
    )
    jax.block_until_ready(
        [
            x
            for x in jax.tree_util.tree_leaves(out)
            if hasattr(x, "block_until_ready")
        ]
    )
    return out


def _emit_cycle_spans(tracer, config_name, timings, profile):
    """One warm cycle -> a span tree with the measured phase durations
    (delta apply / device prep / h2d / solve, plus the solve profile's
    segments when the host-driven driver ran)."""
    end_ns = time.time_ns()
    cycle_s = timings["cycle_s"]
    start_ns = end_ns - int(cycle_s * 1e9)
    transfer = timings.get("transfer") or {}
    compiles = transfer.get("compiles") or {}
    parent = tracer.add_span(
        "bench.warm_cycle",
        start_unix_ns=start_ns,
        duration_s=cycle_s,
        config=config_name,
        scheduled_jobs=timings["scheduled_jobs"],
        loops=timings["loops"],
        # The cost ledger on the cycle span: the Perfetto view answers
        # "churn or solve" without leaving the timeline.
        transfer_bytes_up=int(transfer.get("bytes_up", 0)),
        transfer_bytes_down=int(transfer.get("bytes_down", 0)),
        transfer_donated_buffers=int(transfer.get("donated_buffers", 0)),
        xla_compiles=int(compiles.get("compiles", 0)),
    )
    from armada_tpu.utils.tracing import add_segment_spans

    at = start_ns
    for phase in ("delta_s", "prep_s", "h2d_s", "solve_s"):
        dur = float(timings[phase])
        tracer.add_span(
            f"bench.{phase[:-2]}",
            start_unix_ns=at,
            duration_s=dur,
            parent=parent,
        )
        if phase == "solve_s" and profile:
            add_segment_spans(tracer, parent, at, profile)
        at += int(dur * 1e9)


def run_config(n_jobs, n_nodes, burst=None, mesh=None, fill_window=None,
               hot_window=None, trace_path=None, span_tracer=None):
    """Cold build, one shape-settling warm cycle, then >=5 measured warm
    cycles (BENCH_WARM_CYCLES): the headline is the MEDIAN cycle with its
    spread (min/max + IQR), not a single sample — a single warm cycle can
    land on a GC pause or a padded-shape recompile and misreport by 2x."""
    import numpy as np

    from armada_tpu.core.types import JobSpec
    from armada_tpu.snapshot.incremental import IncrementalRound
    from armada_tpu.solver.kernel import solve_round as _single_solve
    from armada_tpu.solver.kernel_prep import pad_device_round

    budget_s = float(os.environ.get("BENCH_ROUND_BUDGET_S", 0) or 0) or None
    raw_window = os.environ.get("BENCH_HOT_WINDOW")
    tuned = tuned_params()
    chunk_loops = 1
    # Historical bench behavior: no engagement floor (window choice is
    # per bench config). A tuned profile overrides the WHOLE vector,
    # floor included — the A/B must measure exactly what production
    # would run, not a floor-stripped variant of it.
    window_min_slots = 0
    applied_tuned = False
    if raw_window is not None:
        hot_window = int(raw_window)
    elif hot_window is None:
        if tuned is not None:
            # BENCH_TUNED profile — only for configs that don't pin
            # their own window (tracking keeps its historical fixed
            # parameters for like-for-like comparability).
            hot_window = tuned.hot_window_slots
            window_min_slots = tuned.hot_window_min_slots
            chunk_loops = tuned.chunk_loops
            applied_tuned = True
        else:
            # 2x the fill window: one gather covers ~two merged fill loops.
            hot_window = 2 * resolve_fill_window(fill_window)
    sharded = None
    if mesh:
        # mesh is a spec: int (1D chip count) or "HxC" (two-level
        # hosts x chips hierarchy, parallel/multihost.py). The sharded
        # solve is one fused program (no hot-window chunking — the
        # tracked sharded-round-budget gap).
        from armada_tpu.parallel.mesh import pad_nodes
        from armada_tpu.parallel.multihost import resolve_solver

        from armada_tpu.ops import pallas_kernels as _pk

        sharded = resolve_solver(mesh, kernel_path=_pk.resolve_kernel_path())

        def solve_round(dev, rows=None):
            return sharded(pad_nodes(dev, sharded.n_shards))
    else:
        # Single-device driver: hot-window compaction when the round is
        # big enough to pay (solver/hotwindow.py), the budget-aware
        # chunked pass 1 when BENCH_ROUND_BUDGET_S is set, the fused
        # program otherwise — all in solver/kernel.solve_round. The
        # min-slots floor is 0 (window choice is per bench config)
        # UNLESS a BENCH_TUNED profile supplied the full vector, floor
        # included — the A/B must measure what production would run.
        def solve_round(dev, rows=None):
            # rows (the live-job count) trims the warm-cycle readback to
            # the unpadded decision prefix; bench_gate holds the booked
            # bytes_down under its transfer budget.
            return _single_solve(
                dev, budget_s=budget_s, chunk_loops=chunk_loops,
                window=hot_window or None,
                window_min_slots=window_min_slots,
                readback_rows=rows,
            )

    t_setup = time.time()
    inputs = build_inputs(n_jobs, n_nodes, burst=burst, fill_window=fill_window)
    inc = IncrementalRound(*inputs)
    setup_s = time.time() - t_setup

    # Device-resident round state (armada_tpu/snapshot/residency.py):
    # the default warm cycle keeps the padded DeviceRound on device and
    # delta-syncs it, the way the scheduler's "resident" snapshot mode
    # runs. BENCH_RESIDENT=0 restores the legacy re-upload-every-cycle
    # path (the before/after axis for the transfer ledger). The sharded
    # solve re-pads and re-places the node axis per round, so mesh runs
    # always re-upload.
    resident = None
    if sharded is None and os.environ.get("BENCH_RESIDENT", "1") not in ("0", "false"):
        from armada_tpu.snapshot.residency import ResidentRound

        resident = ResidentRound()

    t0 = time.time()
    if resident is not None:
        dev = resident.device_round(inc)  # full reset upload, cold
    else:
        dev = _put(pad_device_round(inc.device_round()))
    h2d_cold_s = time.time() - t0
    t0 = time.time()
    out = solve_round(dev)  # compile + run on the padded flagship shape
    compile_s = time.time() - t0

    next_id = 0

    def warm_cycle(out):
        """One steady-state cycle: lease last round's decisions, take new
        submissions, re-solve. Returns (timings, out)."""
        nonlocal next_id
        snap = inc.snapshot()
        J = snap.num_jobs
        sched = np.flatnonzero(np.asarray(out["scheduled_mask"])[:J])
        assigned = np.asarray(out["assigned_node"])[:J]
        prio = np.asarray(out["scheduled_priority"])[:J]
        leases = [
            (
                str(snap.job_ids[j]),
                snap.node_ids[int(assigned[j])],
                int(prio[j]),
                1.0,
            )
            for j in sched
        ]
        new_jobs = [
            JobSpec(
                id=f"cycle-{next_id + i:08d}",
                queue=f"queue-{i % N_QUEUES:02d}",
                priority_class="low",
                requests={"cpu": "2", "memory": "4Gi"},
                submitted_ts=3e6 + next_id + i,
            )
            for i in range(len(leases))
        ]
        next_id += len(leases)
        # Round observatory (armada_tpu/observe): one transfer ledger +
        # compile-telemetry delta per warm cycle, so every artifact
        # carries extra.transfer — bytes up/down, donated buffers, and
        # the warm-cycle compile count (which must be ZERO: a compile
        # here is the silent-warm-recompile failure mode).
        from armada_tpu.observe import TELEMETRY, round_ledger

        comp0 = TELEMETRY.snapshot()
        with round_ledger() as led:
            t0 = time.time()
            inc.bind(leases)
            inc.add_jobs(new_jobs)
            delta_s = time.time() - t0
            t0 = time.time()
            if resident is not None:
                # Delta sync into the persistent device buffers: prep
                # (inc.device_round), diff against the host mirror, and
                # the scatter upload are one fused step, booked as h2d.
                dev = resident.device_round(inc)
                dev_h = resident.host_round()
                prep_s = 0.0
                h2d_s = time.time() - t0
            else:
                dev = inc.device_round()
                prep_s = time.time() - t0
                t0 = time.time()
                dev_h = pad_device_round(dev)
                dev = _put(dev_h)
                h2d_s = time.time() - t0
            t0 = time.time()
            out = solve_round(dev, rows=snap.num_jobs + len(new_jobs))
            solve_s = time.time() - t0
        # Round admission firewall (armada_tpu/solver/validate.py): time
        # the host-side invariant sweep the scheduler runs before every
        # commit. Measured OUTSIDE the cycle window (it overlaps the next
        # round's delta phase in production) but reported so bench_gate
        # can hold its cost under 5% of solve time.
        from armada_tpu.solver.validate import validate_round

        t0 = time.time()
        try:
            validate_round(
                {k: np.asarray(v) for k, v in out.items()
                 if k not in ("profile", "truncated")},
                dev=dev_h,
            )
        except Exception:  # noqa: BLE001 - bench measures cost, not verdicts
            pass
        validate_s = time.time() - t0
        timings = {
            "delta_s": round(delta_s, 3),
            "prep_s": round(prep_s, 3),
            "h2d_s": round(h2d_s, 3),
            "solve_s": round(solve_s, 3),
            "validate_s": round(validate_s, 4),
            "cycle_s": round(delta_s + prep_s + h2d_s + solve_s, 4),
            "scheduled_jobs": int(np.asarray(out["scheduled_mask"]).sum()),
            "loops": int(out["num_loops"]),
            "transfer": {
                **led.as_dict(),
                "compiles": TELEMETRY.delta_since(comp0),
            },
        }
        if "truncated" in out:
            timings["round_truncated"] = bool(out["truncated"])
        if "profile" in out:
            # Per-segment solve profile (setup / pass-1 / gather /
            # finish wall clock + loop mix) from the host-driven driver.
            timings["segments"] = out["profile"]
        if span_tracer is not None:
            # BENCH_SPANS: the cycle and its component phases as
            # post-hoc spans — tools/trace2perfetto.py renders the whole
            # bench run as a Perfetto timeline.
            _emit_cycle_spans(
                span_tracer, f"{n_jobs}x{n_nodes}", timings,
                out.get("profile"),
            )
        return timings, out

    first, out = warm_cycle(out)  # may pay a shape-change compile once
    n_warm = max(2, int(os.environ.get("BENCH_WARM_CYCLES", 5)))
    samples = []
    for _ in range(n_warm):
        warm, out = warm_cycle(out)
        samples.append(warm)

    # Fairness observatory (armada_tpu/observe/fairness.py): the last
    # measured cycle's share ledger — Jain index + max regret land in
    # extra.fairness so tools/bench_trend.py tracks fairness alongside
    # speed. Computed OUTSIDE the measured window and outside any
    # transfer ledger (the O(J) result readback must not book into
    # extra.transfer or the cycle time).
    fairness_extra = {}
    try:
        from armada_tpu.observe.fairness import ledger_from_device_round

        snap_f = inc.snapshot()
        block = ledger_from_device_round(
            pad_device_round(inc.device_round()),
            {k: np.asarray(v) for k, v in out.items()
             if k not in ("profile", "truncated")},
            snap_f.num_jobs,
            snap_f.num_queues,
        )
        fairness_extra["fairness"] = {
            "jain": block["ledger"]["jain"],
            "max_regret": block["ledger"]["max_regret"],
            "preemptions_attributed": len(block["preemptions"]),
            "policy": block["ledger"].get("policy", "drf"),
        }
    except Exception as e:  # noqa: BLE001 - advisory, never fails the bench
        fairness_extra["fairness"] = {"error": f"{e.__class__.__name__}: {e}"}

    import statistics

    times = sorted(s["cycle_s"] for s in samples)
    median = statistics.median(times)
    q1, _, q3 = statistics.quantiles(times, n=4, method="inclusive")
    # The reported component breakdown comes from the median-cycle sample
    # (closest to the reported headline), spread from all samples.
    rep = min(samples, key=lambda s: abs(s["cycle_s"] - median))
    residency_extra = {}
    if resident is not None and resident.last_sync:
        # Self-describing artifact: which snapshot path produced the
        # headline (resident delta vs full reset) and the warm upload it
        # booked — tools/bench_trend.py shows this as the residency
        # column, tools/bench_gate.py holds bytes_up under the budget.
        residency_extra["residency"] = {
            "mode": str(resident.last_sync.get("mode")),
            "bytes_up": (rep.get("transfer") or {}).get("bytes_up"),
            "permuted": bool(resident.last_sync.get("permuted")),
        }
    mesh_extra = {}
    if sharded is not None:
        shape = sharded.mesh_shape
        hosts, chips = shape if len(shape) == 2 else (1, shape[0])
        mesh_extra["mesh"] = {
            "hosts": hosts,
            "chips": chips,
            # Trace-time accounting of the executed program's collectives
            # (solver/dist.CollectiveStats): sites + bytes per execution
            # by fabric level; multiply by `loops` for per-cycle totals.
            "collectives": (
                (sharded.last_stats or sharded.stats).as_dict()
                if sharded.stats
                else None
            ),
        }
    trace_extra = {}
    if trace_path:
        # Flight recorder (armada_tpu/trace): one extra, UNMEASURED warm
        # cycle appended to the .atrace bundle — the recorded round is
        # exactly the steady-state solve the headline median describes,
        # replayable forever by tools/replay_gate.py. The recorder
        # replaces any stale bundle at the path.
        from armada_tpu.trace import TraceRecorder

        snap = inc.snapshot()
        dev_np = pad_device_round(inc.device_round())
        out_rec = solve_round(_put(dev_np))
        solver_info = {"backend": "kernel", "mesh": str(mesh) if mesh else None,
                       "window": hot_window or 0, "budget": bool(budget_s),
                       "resident": resident is not None}
        with TraceRecorder(
            trace_path, source="bench", config=inputs[0],
            seeds={"workload_seed": 0},
            meta={"n_jobs": n_jobs, "n_nodes": n_nodes, "burst": burst},
        ) as rec:
            rec.record_round(
                pool="default", dev=dev_np,
                decisions={k: np.asarray(v) for k, v in out_rec.items()
                           if k not in ("profile", "truncated")},
                num_jobs=snap.num_jobs, num_queues=snap.num_queues,
                config=inputs[0], solver=solver_info,
                truncated=bool(out_rec.get("truncated", False)),
                profile=out_rec.get("profile"),
            )
        # Marker consumed by tools/bench_trend.py — set ONLY when THIS
        # run recorded the bundle (a stale file from an earlier revision
        # must not be advertised as this artifact's trace).
        trace_extra["trace_path"] = os.path.basename(trace_path)
    params_extra = {}
    if sharded is None:
        # The EFFECTIVE solver parameters this config ran with (possibly
        # tuned via BENCH_TUNED) — artifacts are self-describing, and
        # tools/bench_trend.py shows the vector across rounds. Mesh runs
        # record none: the sharded solve takes no window/chunk vector,
        # so claiming one was in effect would make the artifact lie.
        params_extra["params"] = {
            "hot_window_slots": int(hot_window or 0),
            "hot_window_min_slots": int(window_min_slots),
            "chunk_loops": int(chunk_loops),
            "fill_window": resolve_fill_window(fill_window),
            "tuned": applied_tuned,
        }
    return {
        **mesh_extra,
        **trace_extra,
        **params_extra,
        **fairness_extra,
        **residency_extra,
        "cycle_s": round(median, 4),
        **{k: v for k, v in rep.items() if k != "cycle_s"},
        "warm_cycles_measured": len(times),
        "cycle_s_min": round(times[0], 4),
        "cycle_s_max": round(times[-1], 4),
        "cycle_s_iqr": round(q3 - q1, 4),
        "cycle_s_samples": [round(x, 4) for x in times],
        "compile_s": round(compile_s, 1),
        "cold_build_s": round(setup_s, 1),
        "cold_h2d_s": round(h2d_cold_s, 3),
        "first_warm_cycle_s": first["cycle_s"],
    }


def main():
    """Run the bench matrix; ALWAYS prints one final JSON line.

    Success: the full result with ok=true. Any exception: ok=false with
    the error and whatever sub-results completed, so downstream parsers
    get a parseable (if partial) artifact instead of a truncated tail."""
    partial = {}
    try:
        result = _run_matrix(partial)
        result["ok"] = True
    # KeyboardInterrupt/SystemExit propagate: a deliberate cancellation
    # is not a bench failure and must not mint an ok=false artifact.
    except Exception as e:  # noqa: BLE001 - the artifact IS the report
        import traceback

        result = {
            "metric": "warm_cycle_end_to_end",
            "value": None,
            "unit": "s",
            "ok": False,
            "error": f"{e.__class__.__name__}: {e}",
            "traceback": traceback.format_exc().splitlines()[-6:],
            # Sub-results that completed before the failure (e.g. the
            # tracking run when the burst config OOMs) stay usable by
            # tools/bench_trend.py / bench_gate.py.
            "extra": partial,
        }
    print(json.dumps(result), flush=True)
    if not result["ok"]:
        raise SystemExit(1)


def _run_matrix(partial=None):
    # BENCH_MESH spellings: "8" (1D, 8 chips on one host) or "2x4"
    # (two-level hosts x chips hierarchy, parallel/multihost.py).
    raw_mesh = os.environ.get("BENCH_MESH", "0").lower()
    if "x" in raw_mesh:
        hosts, chips = (int(t) for t in raw_mesh.lower().split("x", 1))
        mesh, n_mesh_devices = raw_mesh, hosts * chips
    else:
        n_mesh_devices = int(raw_mesh or 0)
        mesh = n_mesh_devices or None
    if mesh:
        # Virtual multi-device mesh on the host platform: must be set
        # before the first jax import. (On a real multi-chip TPU slice,
        # drop BENCH_MESH's XLA override and the sharded path uses the
        # actual devices.)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_mesh_devices}"
        )
        os.environ["JAX_PLATFORMS"] = "cpu"

    from armada_tpu.core.resources import ensure_native
    from armada_tpu.utils.platform import ensure_healthy_backend

    ensure_native()  # C++ quantity parser (one-time build on fresh checkouts)
    ensure_healthy_backend()

    import jax

    from armada_tpu.utils import platform as plat

    platform = jax.devices()[0].platform

    # Solve-kernel path (armada_tpu/ops/pallas_kernels.py): the bench
    # defaults to the blocked path — the fused scoring body plus the
    # radix-threshold top-B selection that replaces the per-fill-loop
    # lexsort, the measured CPU win. ARMADA_TPU_KERNEL_PATH is the A/B
    # lever: =lax reproduces the pre-kernel bench exactly, =pallas runs
    # the same body under pl.pallas_call (interpret mode off-TPU),
    # =native adds the ICI ring winner exchange on real hardware.
    from armada_tpu.ops import pallas_kernels as _pk

    os.environ.setdefault(_pk.PATH_ENV, "blocked")
    kernel_path = _pk.resolve_kernel_path("blocked")

    custom = any(
        k in os.environ
        for k in ("BENCH_JOBS", "BENCH_NODES", "BENCH_QUEUES", "BENCH_RUNNING")
    )
    if partial is None:
        partial = {}
    # Flight recorder (off by default): BENCH_TRACE=<path> (or =1 for
    # BENCH_trace.atrace next to the BENCH_r*.json artifacts) records the
    # flagship/custom config's warm cycle to an .atrace bundle.
    trace_path = os.environ.get("BENCH_TRACE") or None
    if trace_path == "1":
        trace_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_trace.atrace"
        )
    # BENCH_SPANS=<path>: export every measured warm cycle's phase spans
    # as OTLP-JSON lines; tools/trace2perfetto.py converts the file into
    # a Perfetto-loadable timeline of the bench run.
    span_tracer = None
    spans_path = os.environ.get("BENCH_SPANS") or None
    if spans_path:
        from armada_tpu.utils.tracing import OtlpJsonFileExporter, Tracer

        open(spans_path, "w").close()  # one bench run = one span file
        span_tracer = Tracer(
            exporter=OtlpJsonFileExporter(
                spans_path, service_name="armada-tpu-bench"
            ),
            export_every=256,
        )
    tracking = burst50k = None
    if custom:
        n_jobs = int(os.environ.get("BENCH_JOBS", 100_000))
        n_nodes = int(os.environ.get("BENCH_NODES", 5000))
        # BENCH_BURST raises the per-round scheduling burst on the
        # custom config (the burst_50k regime at custom scale — the
        # autotune A/B's forced-rewindow scenario).
        burst = int(os.environ.get("BENCH_BURST", 0) or 0) or None
        flag = run_config(n_jobs, n_nodes, burst=burst, mesh=mesh,
                          trace_path=trace_path, span_tracer=span_tracer)
    else:
        n_jobs, n_nodes = 1_000_000, 50_000
        # Like-for-like vs earlier rounds: the historical 512 fill
        # window, no hot-window compaction (a 100k round cannot
        # amortize the host-driven driver's fixed overhead).
        tracking = run_config(
            100_000, 5000, mesh=mesh, fill_window=512, hot_window=0,
            span_tracer=span_tracer,
        )
        partial["tracking_100k"] = tracking
        if os.environ.get("BENCH_FLAGSHIP", "1") == "1":
            flag = run_config(n_jobs, n_nodes, mesh=mesh, trace_path=trace_path,
                              span_tracer=span_tracer)
            partial["flagship"] = flag
            if os.environ.get("BENCH_BURST50K", "1") == "1":
                burst50k = run_config(
                    n_jobs, n_nodes, burst=50_000, mesh=mesh,
                    span_tracer=span_tracer,
                )
                partial["burst_50k"] = burst50k
        else:
            flag, (n_jobs, n_nodes) = tracking, (100_000, 5000)
            tracking = None
    if span_tracer is not None:
        span_tracer.flush()

    extra = dict(flag)
    cycle_s = extra.pop("cycle_s")
    extra["platform"] = platform
    # Which kernel path produced the headline (artifacts are self-
    # describing): resolved path + the block geometry the pallas path
    # would run with at the headline node count. tools/bench_trend.py
    # shows this as the kernels column.
    extra["kernels"] = _pk.kernel_info(kernel_path, n_nodes)
    if mesh:
        extra["mesh_devices"] = n_mesh_devices
    extra["platform_probe"] = plat.last_probe_report.get("reason", "")
    if tracking is not None:
        extra["tracking_100k"] = tracking
    if burst50k is not None:
        extra["burst_50k"] = burst50k
    return {
        "metric": (
            f"warm_cycle_end_to_end({n_jobs} jobs x {n_nodes} nodes, "
            f"{N_QUEUES} queues, burst-limited, {platform})"
        ),
        "value": cycle_s,
        "unit": "s",
        "vs_baseline": round(5.0 / cycle_s, 2),
        "extra": extra,
    }


if __name__ == "__main__":
    main()
