"""Round-deadline guardrails (maxSchedulingDuration): budget-aware fill
loops, partial-placement commit, oracle parity on the placed subset,
resume across cycles, and truncation backpressure."""

import numpy as np
import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver


def _inputs(n_jobs=96, n_nodes=8, n_queues=3):
    cfg = SchedulingConfig(
        # Serial fill: every placement is its own while-loop iteration, so
        # a tiny budget truncates mid-stream deterministically.
        batch_fill_window=0,
    )
    nodes = [
        NodeSpec(
            id=f"n{i:03d}",
            pool="default",
            total_resources={"cpu": "16", "memory": "64Gi"},
        )
        for i in range(n_nodes)
    ]
    queues = [QueueSpec(f"q{i}", 1.0) for i in range(n_queues)]
    queued = [
        JobSpec(
            id=f"j{i:04d}",
            queue=f"q{i % n_queues}",
            requests={"cpu": "1", "memory": "1Gi"},
            submitted_ts=float(i),
        )
        for i in range(n_jobs)
    ]
    return cfg, nodes, queues, queued


def _solve_snap(cfg, nodes, queues, queued, budget_s=None):
    snap = build_round_snapshot(cfg, "default", nodes, queues, [], queued)
    dev = pad_device_round(prep_device_round(snap))
    out = solve_round(dev, budget_s=budget_s)
    J = snap.num_jobs
    return snap, {
        "assigned_node": np.asarray(out["assigned_node"])[:J],
        "scheduled_mask": np.asarray(out["scheduled_mask"])[:J],
        "truncated": out.get("truncated", False),
        "num_loops": int(out["num_loops"]),
    }


def test_config_round_deadline_keys():
    cfg = SchedulingConfig.from_dict(
        {"maxSchedulingDuration": 5.0, "truncatedRoundsBackpressure": 4}
    )
    assert cfg.max_scheduling_duration_s == 5.0
    assert cfg.truncated_rounds_backpressure == 4
    from armada_tpu.core.config import validate_config

    with pytest.raises(ValueError):
        validate_config(
            SchedulingConfig(max_scheduling_duration_s=-1.0)
        )
    with pytest.raises(ValueError):
        validate_config(
            SchedulingConfig(truncated_rounds_backpressure=0)
        )


def test_kernel_truncated_round_is_prefix_of_full_round():
    """A budgeted round commits a subset of the full round's placements
    with IDENTICAL node assignments (the decision stream is a prefix),
    and the full round stays oracle-parity."""
    cfg, nodes, queues, queued = _inputs()
    snap, full = _solve_snap(cfg, nodes, queues, queued, budget_s=None)
    oracle = ReferenceSolver(snap).solve()
    assert (oracle.assigned_node == full["assigned_node"]).all()

    _, cut = _solve_snap(cfg, nodes, queues, queued, budget_s=1e-6)
    assert cut["truncated"]
    placed = np.flatnonzero(cut["scheduled_mask"])
    assert 1 <= len(placed) < int(full["scheduled_mask"].sum())
    # Placed subset: scheduled by the full round too, on the same node —
    # hence oracle-parity on the placed subset.
    assert full["scheduled_mask"][placed].all()
    assert (
        cut["assigned_node"][placed] == full["assigned_node"][placed]
    ).all()
    assert (
        cut["assigned_node"][placed] == oracle.assigned_node[placed]
    ).all()
    assert cut["num_loops"] < full["num_loops"]


def test_kernel_generous_budget_matches_unbudgeted():
    # Same shape as the truncation test: shares its compiled programs.
    cfg, nodes, queues, queued = _inputs()
    _, full = _solve_snap(cfg, nodes, queues, queued, budget_s=None)
    _, budgeted = _solve_snap(cfg, nodes, queues, queued, budget_s=120.0)
    assert not budgeted["truncated"]
    assert (budgeted["scheduled_mask"] == full["scheduled_mask"]).all()
    assert (budgeted["assigned_node"] == full["assigned_node"]).all()


def test_oracle_deadline_truncates_and_is_prefix():
    cfg, nodes, queues, queued = _inputs()
    snap = build_round_snapshot(cfg, "default", nodes, queues, [], queued)
    full = ReferenceSolver(snap).solve()
    cut = ReferenceSolver(snap).solve(budget_s=1e-6)
    assert cut.truncated and cut.termination_reason == "round_truncated"
    placed = np.flatnonzero(cut.scheduled_mask)
    assert 1 <= len(placed) < int(full.scheduled_mask.sum())
    assert full.scheduled_mask[placed].all()
    assert (cut.assigned_node[placed] == full.assigned_node[placed]).all()


def _scheduler_with_jobs(n_jobs, budget_s):
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import make_nodes
    from armada_tpu.services.scheduler import (
        ExecutorHeartbeat,
        SchedulerService,
    )
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig(
        max_scheduling_duration_s=budget_s,
        truncated_rounds_backpressure=2,
        batch_fill_window=0,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("q0", 1.0))
    jobs = [
        JobSpec(
            id=f"d{i:04d}",
            queue="q0",
            jobset="s",
            requests={"cpu": "1", "memory": "1Gi"},
            submitted_ts=float(i),
        )
        for i in range(n_jobs)
    ]
    submit.submit("q0", "s", jobs, now=0.0)
    sched.report_executor(
        ExecutorHeartbeat(
            name="e0",
            pool="default",
            nodes=make_nodes("e0", count=4, cpu="32", memory="256Gi"),
            last_seen=0.0,
        )
    )
    return sched


def test_scheduler_truncated_rounds_resume_and_trip_backpressure():
    """End to end on the service: every budget-starved round commits a
    partial placement and reports round_truncated; successive cycles
    resume from the truncation point until the backlog drains; repeated
    truncation trips per-pool backpressure, and a clean round clears it."""
    from armada_tpu.jobdb import JobState

    sched = _scheduler_with_jobs(24, budget_s=1e-6)
    leased_counts = []
    truncated_rounds = 0
    for cycle in range(200):
        sched.cycle(now=float(cycle))
        report = sched.reports.latest_reports().get("default")
        if report is not None and report.termination_reason == "round_truncated":
            truncated_rounds += 1
        txn = sched.jobdb.read_txn()
        queued = len(txn.queued_jobs(sort=False))
        leased_counts.append(24 - queued)
        if queued == 0:
            break
    assert leased_counts[-1] == 24, "backlog never drained"
    # Starved rounds each made partial progress (resume across cycles).
    assert truncated_rounds >= 2
    assert len(leased_counts) > 2
    # Backpressure tripped during the truncation streak...
    assert truncated_rounds >= sched.round_pressure.threshold
    # ...and one clean (fully drained) round afterwards clears it. (`now`
    # stays inside the executor timeout so the heartbeat is still live.)
    sched.cycle(now=float(len(leased_counts) + 1))
    ok, reason = sched.round_pressure.check()
    assert ok, reason
    # All leases are real jobdb state.
    txn = sched.jobdb.read_txn()
    assert sum(1 for j in txn.all_jobs() if j.state == JobState.LEASED) == 24


def test_scheduler_no_budget_reports_untruncated():
    sched = _scheduler_with_jobs(6, budget_s=0.0)
    sched.cycle(now=0.0)
    report = sched.reports.latest_reports().get("default")
    assert report is not None
    assert report.termination_reason != "round_truncated"
    ok, _ = sched.round_pressure.check()
    assert ok


def _evicting_inputs(n_queued=64, n_running=24, n_nodes=8):
    """Running preemptible jobs in one hog queue over its fair share plus
    queued work from others: pass 1 starts by evicting the hog's jobs, so
    truncation mid-pass exercises the evicted-rebind rescue."""
    from armada_tpu.core.config import PriorityClass
    from armada_tpu.core.types import RunningJob

    cfg = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
        batch_fill_window=0,
    )
    nodes = [
        NodeSpec(
            id=f"n{i:03d}",
            pool="default",
            total_resources={"cpu": "16", "memory": "64Gi"},
        )
        for i in range(n_nodes)
    ]
    queues = [QueueSpec(f"q{i}", 1.0) for i in range(3)]
    running = [
        RunningJob(
            job=JobSpec(
                id=f"r{i:04d}",
                queue="q0",
                priority_class="low",
                requests={"cpu": "2", "memory": "4Gi"},
                submitted_ts=float(-n_running + i),
            ),
            node_id=f"n{i % n_nodes:03d}",
            scheduled_at_priority=1000,
        )
        for i in range(n_running)
    ]
    queued = [
        JobSpec(
            id=f"j{i:04d}",
            queue=f"q{1 + i % 2}",
            priority_class="low",
            requests={"cpu": "1", "memory": "1Gi"},
            submitted_ts=float(i),
        )
        for i in range(n_queued)
    ]
    return cfg, nodes, queues, running, queued


def test_oracle_truncation_with_evictions_never_over_preempts():
    """Truncating a round that evicted running jobs must not preempt work
    the full round would have kept: the rescue pass rebinds every evicted
    job whose pinned node still fits it (truncated preemptions are a
    subset of the full round's)."""
    cfg, nodes, queues, running, queued = _evicting_inputs()
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    full = ReferenceSolver(snap).solve()
    cut = ReferenceSolver(snap).solve(budget_s=1e-6)
    assert cut.truncated
    cut_preempted = set(np.flatnonzero(cut.preempted_mask))
    full_preempted = set(np.flatnonzero(full.preempted_mask))
    assert cut_preempted <= full_preempted
    # Queued placements remain a prefix with identical assignments.
    placed = np.flatnonzero(cut.scheduled_mask)
    assert full.scheduled_mask[placed].all()
    assert (cut.assigned_node[placed] == full.assigned_node[placed]).all()
    # And evicted jobs that rebound really are still on their own node.
    for j in np.flatnonzero(snap.job_is_running):
        if j not in cut_preempted:
            assert cut.assigned_node[j] == snap.job_node[j]


@pytest.mark.slow
def test_kernel_truncation_with_evictions_never_over_preempts():
    """Kernel variant of the rescue-pass contract (slow: compiles the
    chunked programs for the eviction-shaped round)."""
    cfg, nodes, queues, running, queued = _evicting_inputs()
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    dev = pad_device_round(prep_device_round(snap))
    J = snap.num_jobs
    full = solve_round(dev)
    cut = solve_round(dev, budget_s=1e-6)
    assert cut["truncated"]
    cut_pre = set(np.flatnonzero(np.asarray(cut["preempted_mask"])[:J]))
    full_pre = set(np.flatnonzero(np.asarray(full["preempted_mask"])[:J]))
    assert cut_pre <= full_pre
    placed = np.flatnonzero(np.asarray(cut["scheduled_mask"])[:J])
    assert np.asarray(full["scheduled_mask"])[:J][placed].all()
    assert (
        np.asarray(cut["assigned_node"])[:J][placed]
        == np.asarray(full["assigned_node"])[:J][placed]
    ).all()
