"""Retry node anti-affinity + cordoned queues, solver parity + end-to-end."""

import numpy as np

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver


def nodes(n=2):
    return [
        NodeSpec(id=f"n{i}", pool="default",
                 total_resources={"cpu": "8", "memory": "32Gi"})
        for i in range(n)
    ]


def job(i, **kw):
    return JobSpec(id=f"j{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"},
                   submitted_ts=float(i), **kw)


def both(cfg, ns, qs, queued, **kw):
    snap = build_round_snapshot(cfg, "default", ns, qs, [], queued, **kw)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all()
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    return snap, oracle


def test_excluded_nodes_respected():
    # n0 excluded for j0: must land on n1 (n0 is best-fit otherwise since
    # both are identical and n0 has lower id rank)
    snap, res = both(
        SchedulingConfig(), nodes(2), [QueueSpec("q")], [job(0)],
        excluded_nodes={"j0": ["n0"]},
    )
    assert res.scheduled_mask[0]
    assert snap.node_ids[res.assigned_node[0]] == "n1"


def test_all_nodes_excluded_blocks():
    snap, res = both(
        SchedulingConfig(), nodes(2), [QueueSpec("q")], [job(0)],
        excluded_nodes={"j0": ["n0", "n1"]},
    )
    assert res.scheduled_mask.sum() == 0


def test_cordoned_queue_blocks_new_jobs():
    snap, res = both(
        SchedulingConfig(),
        nodes(2),
        [QueueSpec("q"), QueueSpec("open")],
        [job(0), job(1).with_(queue="open")],
        cordoned_queues={"q"},
    )
    j0 = snap.job_ids.index("j0")
    j1 = snap.job_ids.index("j1")
    assert not res.scheduled_mask[j0]
    assert res.scheduled_mask[j1]


def test_e2e_failed_node_retry_avoids_node():
    """An executor-timeout retry must not land on the failed node."""
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.jobdb import JobState
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
        executor_timeout_s=10.0,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("team"))
    ex_a = FakeExecutor("ex-a", log, sched,
                        nodes=make_nodes("ex-a", count=1, cpu="8"), pool="default")
    ex_b = FakeExecutor("ex-b", log, sched,
                        nodes=make_nodes("ex-b", count=1, cpu="8"), pool="default")
    submit.submit("team", "s", [job(0).with_(queue="team")], now=0.0)
    ex_a.tick(0.0)
    ex_b.tick(0.0)
    sched.cycle(now=1.0)
    first_node = sched.jobdb.get("j0").latest_run.node_id

    # the executor that got the job goes silent; the other keeps beating
    survivor = ex_b if first_node.startswith("ex-a") else ex_a
    survivor.tick(11.5)
    sched.cycle(now=12.0)  # expiry -> requeue with failed node recorded
    sched.cycle(now=12.5)  # reschedule
    j = sched.jobdb.get("j0")
    assert j.state in (JobState.LEASED, JobState.RUNNING)
    second_node = j.latest_run.node_id
    assert second_node != first_node
    assert first_node in j.failed_nodes


def _wait(predicate, timeout=10.0, interval=0.05):
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_cli_node_cordon_respected_by_next_round():
    """`armadactl node cordon` round-trips through the control plane
    (binoculars -> executor -> next heartbeat) and the NEXT round's
    snapshot refuses the node; uncordon restores it. Only the happy
    path via binoculars.set_cordon was exercised before."""
    from armada_tpu.clients.cli import main
    from armada_tpu.services.server import ControlPlane

    plane = ControlPlane(
        SchedulingConfig(),
        cycle_period=0.05,
        fake_executors=[{"name": "ex", "nodes": 2, "cpu": "8",
                         "runtime": 1e6}],
    ).start()
    try:
        main(["--server", plane.address, "queue", "create", "team"])
        # Cordon node 0 through the CLI; the next round must place on
        # node 1 only.
        main(["--server", plane.address, "node", "cordon",
              "ex-node-00000"])
        assert _wait(
            lambda: plane.executors[0].nodes[0].unschedulable
        )
        from armada_tpu.services.grpc_api import ApiClient

        client = ApiClient(plane.address)
        client.submit_jobs(
            "team", "s",
            [{"requests": {"cpu": "2", "memory": "1Gi"}} for _ in range(2)],
        )

        def both_leased_off_node0():
            jobs = [
                j for j in plane.scheduler.jobdb.read_txn().all_jobs()
                if j.latest_run is not None
            ]
            return len(jobs) == 2 and all(
                j.latest_run.node_id == "ex-node-00001" for j in jobs
            )

        assert _wait(both_leased_off_node0)
        # Uncordon: new work may land on node 0 again.
        main(["--server", plane.address, "node", "uncordon",
              "ex-node-00000"])
        assert _wait(
            lambda: not plane.executors[0].nodes[0].unschedulable
        )
        client.submit_jobs(
            "team", "s2",
            [{"requests": {"cpu": "6", "memory": "1Gi"}}],
        )

        def third_on_node0():
            jobs = [
                j for j in plane.scheduler.jobdb.read_txn().all_jobs()
                if j.latest_run is not None
                and j.latest_run.node_id == "ex-node-00000"
            ]
            return len(jobs) == 1

        assert _wait(third_on_node0)
    finally:
        plane.stop()


def test_cli_executor_cordon_event_log_round_trip():
    """`armadactl executor cordon` is event-sourced: the NEXT round's
    snapshot takes no new placements there (nodes stay, unschedulable),
    and a fresh scheduler replaying the same log materializes the
    cordon."""
    from armada_tpu.clients.cli import main
    from armada_tpu.services.grpc_api import ApiClient
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.server import ControlPlane

    config = SchedulingConfig()
    plane = ControlPlane(
        config,
        cycle_period=0.05,
        fake_executors=[
            {"name": "ex-a", "nodes": 1, "cpu": "8", "runtime": 1e6},
            {"name": "ex-b", "nodes": 1, "cpu": "8", "runtime": 1e6},
        ],
    ).start()
    try:
        main(["--server", plane.address, "queue", "create", "team"])
        main(["--server", plane.address, "executor", "cordon", "ex-a"])
        assert _wait(
            lambda: "ex-a" in plane.scheduler.cordoned_executors
        )
        client = ApiClient(plane.address)
        client.submit_jobs(
            "team", "s",
            [{"requests": {"cpu": "2", "memory": "1Gi"}} for _ in range(2)],
        )

        def both_on_ex_b():
            jobs = [
                j for j in plane.scheduler.jobdb.read_txn().all_jobs()
                if j.latest_run is not None
            ]
            return len(jobs) == 2 and all(
                j.latest_run.executor == "ex-b" for j in jobs
            )

        assert _wait(both_on_ex_b)
        # Event-log round trip: a brand-new scheduler replaying the same
        # log (a restarted/standby leader) holds the cordon too.
        replica = SchedulerService(config, plane.log)
        assert "ex-a" in replica.cordoned_executors
        main(["--server", plane.address, "executor", "uncordon", "ex-a"])
        assert _wait(
            lambda: "ex-a" not in plane.scheduler.cordoned_executors
        )
        replica2 = SchedulerService(config, plane.log)
        assert "ex-a" not in replica2.cordoned_executors
    finally:
        plane.stop()


def test_e2e_cordoned_queue():
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.jobdb import JobState
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig()
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("frozen"), cordoned=True)
    ex = FakeExecutor("ex", log, sched, nodes=make_nodes("ex", count=2, cpu="8"))
    submit.submit("frozen", "s", [job(0).with_(queue="frozen")], now=0.0)
    ex.tick(0.0)
    sched.cycle(now=1.0)
    assert sched.jobdb.get("j0").state == JobState.QUEUED
    # uncordon -> schedules
    submit.update_queue("frozen", cordoned=False)
    sched.cycle(now=2.0)
    assert sched.jobdb.get("j0").state == JobState.LEASED
