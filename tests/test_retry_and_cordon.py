"""Retry node anti-affinity + cordoned queues, solver parity + end-to-end."""

import numpy as np

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver


def nodes(n=2):
    return [
        NodeSpec(id=f"n{i}", pool="default",
                 total_resources={"cpu": "8", "memory": "32Gi"})
        for i in range(n)
    ]


def job(i, **kw):
    return JobSpec(id=f"j{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"},
                   submitted_ts=float(i), **kw)


def both(cfg, ns, qs, queued, **kw):
    snap = build_round_snapshot(cfg, "default", ns, qs, [], queued, **kw)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all()
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    return snap, oracle


def test_excluded_nodes_respected():
    # n0 excluded for j0: must land on n1 (n0 is best-fit otherwise since
    # both are identical and n0 has lower id rank)
    snap, res = both(
        SchedulingConfig(), nodes(2), [QueueSpec("q")], [job(0)],
        excluded_nodes={"j0": ["n0"]},
    )
    assert res.scheduled_mask[0]
    assert snap.node_ids[res.assigned_node[0]] == "n1"


def test_all_nodes_excluded_blocks():
    snap, res = both(
        SchedulingConfig(), nodes(2), [QueueSpec("q")], [job(0)],
        excluded_nodes={"j0": ["n0", "n1"]},
    )
    assert res.scheduled_mask.sum() == 0


def test_cordoned_queue_blocks_new_jobs():
    snap, res = both(
        SchedulingConfig(),
        nodes(2),
        [QueueSpec("q"), QueueSpec("open")],
        [job(0), job(1).with_(queue="open")],
        cordoned_queues={"q"},
    )
    j0 = snap.job_ids.index("j0")
    j1 = snap.job_ids.index("j1")
    assert not res.scheduled_mask[j0]
    assert res.scheduled_mask[j1]


def test_e2e_failed_node_retry_avoids_node():
    """An executor-timeout retry must not land on the failed node."""
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.jobdb import JobState
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
        executor_timeout_s=10.0,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("team"))
    ex_a = FakeExecutor("ex-a", log, sched,
                        nodes=make_nodes("ex-a", count=1, cpu="8"), pool="default")
    ex_b = FakeExecutor("ex-b", log, sched,
                        nodes=make_nodes("ex-b", count=1, cpu="8"), pool="default")
    submit.submit("team", "s", [job(0).with_(queue="team")], now=0.0)
    ex_a.tick(0.0)
    ex_b.tick(0.0)
    sched.cycle(now=1.0)
    first_node = sched.jobdb.get("j0").latest_run.node_id

    # the executor that got the job goes silent; the other keeps beating
    survivor = ex_b if first_node.startswith("ex-a") else ex_a
    survivor.tick(11.5)
    sched.cycle(now=12.0)  # expiry -> requeue with failed node recorded
    sched.cycle(now=12.5)  # reschedule
    j = sched.jobdb.get("j0")
    assert j.state in (JobState.LEASED, JobState.RUNNING)
    second_node = j.latest_run.node_id
    assert second_node != first_node
    assert first_node in j.failed_nodes


def test_e2e_cordoned_queue():
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.jobdb import JobState
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig()
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("frozen"), cordoned=True)
    ex = FakeExecutor("ex", log, sched, nodes=make_nodes("ex", count=2, cpu="8"))
    submit.submit("frozen", "s", [job(0).with_(queue="frozen")], now=0.0)
    ex.tick(0.0)
    sched.cycle(now=1.0)
    assert sched.jobdb.get("j0").state == JobState.QUEUED
    # uncordon -> schedules
    submit.update_queue("frozen", cordoned=False)
    sched.cycle(now=2.0)
    assert sched.jobdb.get("j0").state == JobState.LEASED
