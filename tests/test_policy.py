"""Pluggable fairness policies (solver/policy.py): spec/config round
trips, host/device entitlement parity (extreme-weight ULP bounds,
weight monotonicity, zero-weight/zero-total guards), kernel-vs-oracle
parity under every policy, DRF bit-exactness against a pre-policy
recorded fixture, header policy pinning in the replayer, the policy
A/B harness, and the control-plane flip path (divergence gate,
event sourcing, checkpoint restore, what-if payers)."""

import dataclasses
import os

import numpy as np
import pytest

from armada_tpu.core.config import (
    PriorityClass,
    SchedulingConfig,
    validate_config,
)
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver import policy
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver

from test_kernel_parity import PREEMPT_CFG, assert_parity, rand_scenario

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "sim_steady.atrace"
)

NON_DRF = ("proportional", "priority", "deadline")


def _cfg(kind, base=PREEMPT_CFG, **kw):
    return dataclasses.replace(base, fairness_policy_default=kind, **kw)


# ---------------------------------------------------------------------------
# spec + config round trips
# ---------------------------------------------------------------------------


def test_spec_normalization_round_trips():
    assert policy.normalize_spec("drf") == ("drf",)
    assert policy.normalize_spec(["proportional"]) == ("proportional",)
    assert policy.normalize_spec("deadline") == ("deadline", 2.0, 3600.0)
    assert policy.normalize_spec(("deadline", 1, 60)) == ("deadline", 1.0, 60.0)
    assert policy.spec_to_str(("deadline", 1.0, 60.0)) == (
        "deadline(boost=1,horizon=60)"
    )
    for s in ("drf", "proportional", "priority"):
        assert policy.spec_to_str(policy.normalize_spec(s)) == s
    with pytest.raises(ValueError, match="unknown fairness policy"):
        policy.normalize_spec("lottery")
    with pytest.raises(ValueError, match="boost"):
        policy.normalize_spec(("deadline", -1.0))
    with pytest.raises(ValueError, match="horizon"):
        policy.normalize_spec(("deadline", 1.0, 0.0))
    with pytest.raises(ValueError, match="takes no parameters"):
        policy.normalize_spec(("priority", 3.0))


def test_config_block_round_trip_and_rejection():
    d = {
        "priorityClasses": {"d": {"priority": 1000, "preemptible": True}},
        "defaultPriorityClassName": "d",
        "fairnessPolicy": {
            "default": "proportional",
            "pools": {"gpu": "deadline", "cpu": "drf"},
            "deadlineBoost": 3.0,
            "deadlineHorizonSeconds": 120.0,
        },
    }
    cfg = SchedulingConfig.from_dict(d)
    assert cfg.fairness_policy_default == "proportional"
    assert cfg.fairness_policy_pools == {"gpu": "deadline", "cpu": "drf"}
    assert cfg.fairness_deadline_boost == 3.0
    assert cfg.fairness_deadline_horizon_s == 120.0
    validate_config(cfg)
    assert policy.spec_from_config(cfg, "gpu") == ("deadline", 3.0, 120.0)
    assert policy.spec_from_config(cfg, "cpu") == ("drf",)
    assert policy.spec_from_config(cfg, "other") == ("proportional",)

    # A typo must not silently schedule under the wrong objective.
    bad = dataclasses.replace(cfg, fairness_policy_pools={"gpu": "lottery"})
    with pytest.raises(ValueError, match="unknown fairness policy"):
        validate_config(bad)
    # Market pools price off the DRF dominant share: pinned to drf.
    market = dataclasses.replace(cfg, market_driven=True)
    with pytest.raises(ValueError, match="market-driven"):
        validate_config(market)


# ---------------------------------------------------------------------------
# entitlement math: ULP parity, monotonicity, degenerate guards
# ---------------------------------------------------------------------------


def _ulp_close(a, b, ulps=4):
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    tol = ulps * np.spacing(np.maximum(np.abs(a), np.abs(b)))
    return (np.abs(a - b) <= tol).all()


@pytest.mark.parametrize(
    # drf (the bit-exactness anchor) and one weight-driven policy run
    # tier-1; the remaining kinds ride the exhaustive sweep.
    "kind",
    ["drf", "proportional"]
    + [pytest.param(k, marks=pytest.mark.slow)
       for k in ("priority", "deadline")],
)
def test_extreme_weight_waterfill_kernel_ulp(kind):
    """Extreme weight spreads (1e-6 .. 1e6) through the REAL solve: the
    jit entitlement (fair/capped/uncapped) must stay within 4 ULP of
    the host mirror under every policy."""
    cfg = _cfg(kind)
    nodes = [
        NodeSpec(id=f"n{i}", pool="default",
                 total_resources={"cpu": "16", "memory": "64Gi"})
        for i in range(3)
    ]
    # weight = 1/priority_factor: spread entitlement across 12 orders of
    # magnitude, the accumulation regime where a reordered float sum
    # would blow far past a few ULP.
    factors = [1e6, 1e3, 1.0, 1e-3, 1e-6]
    queues = [QueueSpec(f"q{i}", f) for i, f in enumerate(factors)]
    queued = [
        JobSpec(
            id=f"j{i:03d}", queue=f"q{i % len(queues)}",
            requests={"cpu": "2", "memory": "2Gi"},
            submitted_ts=float(i),
            annotations={policy.DEADLINE_ANNOTATION: str(100.0 + 31.0 * i)},
        )
        for i in range(15)
    ]
    snap = build_round_snapshot(cfg, "default", nodes, queues, [], queued)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    Q = snap.num_queues
    # The decision-driving entitlements (fair share, demand-capped —
    # they set budgets and protected fractions) must stay within 4 ULP.
    # The uncapped diagnostic accumulates `share * (unallocated -
    # spare)` per waterfill pass, where the host mirror sums weights in
    # name order but the jit form uses jnp.sum's reduction order: at a
    # 1e12 weight spread the low bits legitimately drift a few more ULP
    # (replay bit-exactness is unaffected — it compares device against
    # device).
    for key, ulps in (
        ("fair_share", 4),
        ("demand_capped_fair_share", 4),
        ("uncapped_fair_share", 16),
    ):
        dev_v = np.asarray(out[key])[:Q]
        host_v = np.asarray(getattr(oracle, key))
        assert _ulp_close(dev_v, host_v, ulps=ulps), (
            f"{kind}/{key}: {dev_v} vs {host_v}"
        )


@pytest.mark.parametrize("kind", ("drf",) + NON_DRF)
def test_entitlement_weight_monotonicity(kind):
    """Raising one queue's weight must never lower its uncapped
    entitlement, under every policy."""
    rng = np.random.default_rng(7)
    names = [f"q{i}" for i in range(6)]
    deadlines = np.array([50.0, np.inf, 10.0, 400.0, np.inf, 90.0])
    for _ in range(20):
        weights = rng.uniform(0.01, 10.0, size=6)
        demand = rng.uniform(0.0, 0.7, size=6)
        spec = policy.normalize_spec(kind)
        _, _, unc_before = policy.policy_fair_shares(
            spec, names, weights, demand, queue_deadline=deadlines
        )
        for i in range(6):
            bumped = weights.copy()
            bumped[i] *= 4.0
            _, _, unc_after = policy.policy_fair_shares(
                spec, names, bumped, demand, queue_deadline=deadlines
            )
            assert unc_after[i] >= unc_before[i] - 1e-12, (
                f"{kind}: queue {i} entitlement fell "
                f"{unc_before[i]} -> {unc_after[i]} on a weight raise"
            )


@pytest.mark.parametrize("kind", ("drf",) + NON_DRF)
def test_zero_weight_and_zero_total_guards(kind):
    """All-zero weights yield all-zero (finite) shares; a zero-resource
    pool (total_is_zero) treats every demand as 1.0; an individual
    zero-weight queue holds no entitlement — under every policy."""
    names = ["a", "b", "c"]
    spec = policy.normalize_spec(kind)
    dl = np.array([10.0, np.inf, 30.0])

    fs, capped, unc = policy.policy_fair_shares(
        spec, names, np.zeros(3), np.full(3, 0.5), queue_deadline=dl
    )
    for v in (fs, capped, unc):
        assert np.isfinite(v).all() and (v == 0.0).all(), (kind, v)

    fs, capped, unc = policy.policy_fair_shares(
        spec, names, np.array([1.0, 0.0, 1.0]), np.full(3, 0.9),
        total_is_zero=True, queue_deadline=dl,
    )
    assert np.isfinite(fs).all() and np.isfinite(capped).all()
    assert fs[1] == 0.0 and unc[1] == 0.0, (
        f"{kind}: zero-weight queue holds entitlement {unc[1]}"
    )


def test_proportional_cost_sums_resource_fractions():
    total = np.array([10.0, 20.0])
    mult = np.ones(2)
    alloc = np.array([[5.0, 10.0], [0.0, 0.0]])
    drf_cost = policy.policy_cost(("drf",), alloc, total, mult)
    prop_cost = policy.policy_cost(("proportional",), alloc, total, mult)
    np.testing.assert_allclose(drf_cost, [0.5, 0.0])
    np.testing.assert_allclose(prop_cost, [1.0, 0.0])


# ---------------------------------------------------------------------------
# kernel vs oracle parity under every policy
# ---------------------------------------------------------------------------


def _stamp_deadlines(queued):
    return [
        dataclasses.replace(
            j,
            annotations={policy.DEADLINE_ANNOTATION: str(100.0 + 37.0 * i)},
        )
        if i % 3 != 2
        else j
        for i, j in enumerate(queued)
    ]


@pytest.mark.parametrize("kind", NON_DRF)
@pytest.mark.parametrize(
    # Seed 0 for every policy stays tier-1 (each policy spec is its own
    # compiled program, so one seed already exercises the full solve);
    # the remaining seeds are the exhaustive sweep.
    "seed",
    [0] + [pytest.param(s, marks=pytest.mark.slow) for s in range(1, 4)],
)
def test_kernel_oracle_parity_under_policy(kind, seed):
    rng = np.random.default_rng(1000 + seed)
    nodes, queues, running, queued = rand_scenario(rng, with_running=True)
    if kind == "deadline":
        queued = _stamp_deadlines(queued)
    assert_parity(
        _cfg(kind), nodes, queues, running, queued,
        label=f"policy={kind} seed={seed}",
    )


# ---------------------------------------------------------------------------
# DRF bit-exactness vs a pre-policy recorded corpus
# ---------------------------------------------------------------------------


def test_drf_replay_bit_exact_on_prepolicy_fixture():
    """The DRF spec adds no key and keeps the original cost measure, so
    solving a PRE-policy recorded round reproduces its decision stream
    bit for bit (the replay-gate invariant, in-suite)."""
    from armada_tpu.trace.recorder import DECISION_KEYS
    from armada_tpu.trace.replayer import load_trace

    trace = load_trace(FIXTURE)
    checked = 0
    for rec in trace.rounds[:3]:
        if rec.truncated:
            continue
        dev = rec.device_round()
        # Compat decode: a pre-policy bundle reads as the DRF spec.
        assert dev.fairness_policy == ("drf",)
        out = solve_round(dev)
        recorded = rec.decisions()
        for key in DECISION_KEYS:
            if key not in recorded:
                continue
            got = np.asarray(out[key])
            want = np.asarray(recorded[key])
            assert got.shape == want.shape, key
            # Byte comparison: bit-exact including NaN payloads (the
            # spot_price scalar is NaN on non-market pools).
            assert got.tobytes() == want.tobytes(), (
                f"round {rec.raw['i']} {key}: DRF replay diverged"
            )
            checked += 1
    assert checked > 0


# ---------------------------------------------------------------------------
# replayer header pinning + the A/B escape hatch
# ---------------------------------------------------------------------------


def _record_tiny_bundle(path, cfg, pool="default"):
    from armada_tpu.trace.recorder import TraceRecorder

    nodes = [NodeSpec(id="n0", pool=pool,
                      total_resources={"cpu": "8", "memory": "16Gi"})]
    queues = [QueueSpec("q")]
    queued = [
        JobSpec(id=f"j{i}", queue="q",
                requests={"cpu": "2", "memory": "1Gi"}, submitted_ts=float(i))
        for i in range(3)
    ]
    snap = build_round_snapshot(cfg, pool, nodes, queues, [], queued)
    dev = pad_device_round(prep_device_round(snap))
    out = {k: np.asarray(v) for k, v in solve_round(dev).items()}
    with TraceRecorder(str(path), config=cfg) as rec:
        rec.record_round(
            pool=pool, dev=dev, decisions=out,
            num_jobs=snap.num_jobs, num_queues=snap.num_queues,
            config=cfg, cycle=0,
        )
    return str(path)


def test_replayer_refuses_cross_policy_unless_explicit_ab(tmp_path):
    from armada_tpu.trace.replayer import (
        CrossPolicyMismatch,
        diff_traces,
        load_trace,
        trace_policies,
    )

    # Pre-policy bundles read as all-DRF (satellite: header pinning).
    assert trace_policies(load_trace(FIXTURE)) == {
        "default": "drf", "pools": {},
    }

    a = _record_tiny_bundle(tmp_path / "a.atrace", _cfg("drf"))
    b = _record_tiny_bundle(
        tmp_path / "b.atrace",
        dataclasses.replace(
            PREEMPT_CFG, fairness_policy_pools={"default": "proportional"}
        ),
    )
    ta, tb = load_trace(a), load_trace(b)
    assert trace_policies(tb)["pools"] == {"default": "proportional"}
    with pytest.raises(CrossPolicyMismatch, match="policy_ab"):
        diff_traces(ta, tb)
    # The explicit A/B escape hatch stamps both policies on the result.
    result = diff_traces(ta, tb, allow_cross_policy=True)
    assert result["cross_policy"] is True
    assert result["policy_a"] != result["policy_b"]
    # Same-policy bundles diff normally (and bit-exactly with selves).
    self_diff = diff_traces(ta, load_trace(a))
    assert self_diff["ok"] and not self_diff.get("cross_policy")


# ---------------------------------------------------------------------------
# the policy A/B harness (tier-1 smoke on the recorded fixture)
# ---------------------------------------------------------------------------


def test_policy_ab_smoke_on_steady_fixture():
    from armada_tpu.trace.policy_ab import (
        DEFAULT_CANDIDATES,
        ab_compare,
        render_ab,
    )

    result = ab_compare(
        [FIXTURE], DEFAULT_CANDIDATES,
        solver="LOCAL", allow_foreign=True, max_rounds=3,
    )
    cards = result["policies"]
    assert set(cards) == {
        "drf", "proportional", "priority", "deadline(boost=2,horizon=3600)",
    }
    for name, card in cards.items():
        assert card["rounds"] == 3, name
        assert 0.0 <= card["jain_min"] <= card["jain_mean"] <= 1.0, name
        assert card["queues"], name
    # Proportional prices the SUM of resource fractions: delivered
    # shares must differ from the DRF scorecard on this corpus.
    drf_delivered = {
        q: s["mean_delivered"] for q, s in cards["drf"]["queues"].items()
    }
    prop_delivered = {
        q: s["mean_delivered"]
        for q, s in cards["proportional"]["queues"].items()
    }
    assert drf_delivered != prop_delivered
    rendered = render_ab(result)
    assert "proportional" in rendered and "per-queue delivered" in rendered


# ---------------------------------------------------------------------------
# control plane: divergence gate, event sourcing, checkpoint restore
# ---------------------------------------------------------------------------


def _scheduler(cfg=None, log=None, checkpoint=None):
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.scheduler import SchedulerService

    cfg = cfg or SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = log if log is not None else InMemoryEventLog()
    return SchedulerService(cfg, log, checkpoint=checkpoint), log


def test_policy_flip_gate_event_and_checkpoint_restore():
    sched, log = _scheduler()
    assert sched.fairness_policy("default") == "drf"

    # Divergence gate: a non-DRF flip without shadow evidence refuses.
    with pytest.raises(ValueError, match="shadow scorecard"):
        sched.set_fairness_policy("default", "proportional")
    with pytest.raises(ValueError, match="unknown fairness policy"):
        sched.set_fairness_policy("default", "lottery", force=True)

    sched.note_policy_shadow("default", "proportional", {"jain_mean": 0.99})
    sched.set_fairness_policy("default", "proportional")
    assert sched.fairness_policy("default") == "proportional"
    # The flip materializes into the config every snapshot/prep seam
    # reads, and is event-sourced as a control-plane event.
    assert sched.config.fairness_policy_pools["default"] == "proportional"
    from armada_tpu.events.model import FairnessPolicyChange

    events = [
        ev
        for e in log.read(0, 10**6)
        for ev in e.sequence.events
        if isinstance(ev, FairnessPolicyChange)
    ]
    assert events and events[-1].policy == "proportional"

    # Checkpoint restore: a bounded restart keeps the flipped pool.
    cursor, state = sched.checkpoint_state()
    assert state["fairness_policy_overrides"] == {"default": "proportional"}
    from armada_tpu.events import InMemoryEventLog

    sched2, _ = _scheduler(log=InMemoryEventLog(), checkpoint=(cursor, state))
    assert sched2.fairness_policy("default") == "proportional"
    assert sched2.config.fairness_policy_pools["default"] == "proportional"
    # Pre-policy checkpoints (no key) restore to the file config.
    old_state = {k: v for k, v in state.items()
                 if k != "fairness_policy_overrides"}
    sched3, _ = _scheduler(
        log=InMemoryEventLog(), checkpoint=(cursor, old_state)
    )
    assert sched3.fairness_policy("default") == "drf"

    # Clearing reverts to the file config and is itself event-sourced.
    sched.set_fairness_policy("default", None)
    assert sched.fairness_policy("default") == "drf"
    assert "default" not in sched.fairness_policy_overrides


def test_policy_change_event_applies_on_replica_sync():
    """A follower materializes the flip from the event log alone (the
    leader's in-process setter never ran there)."""
    from armada_tpu.events import EventSequence
    from armada_tpu.events.model import (
        CONTROL_PLANE_JOBSET,
        FairnessPolicyChange,
    )

    sched, log = _scheduler()
    log.publish(EventSequence.of(
        "", CONTROL_PLANE_JOBSET,
        FairnessPolicyChange(created=1.0, pool="default", policy="priority"),
    ))
    sched.ingester.sync()
    assert sched.fairness_policy("default") == "priority"
    log.publish(EventSequence.of(
        "", CONTROL_PLANE_JOBSET,
        FairnessPolicyChange(created=2.0, pool="default", cleared=True),
    ))
    sched.ingester.sync()
    assert sched.fairness_policy("default") == "drf"


def test_market_pool_refuses_non_drf_flip():
    cfg = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
        market_driven=True,
    )
    sched, _ = _scheduler(cfg=cfg)
    with pytest.raises(ValueError, match="market-driven"):
        sched.set_fairness_policy("default", "proportional", force=True)


# ---------------------------------------------------------------------------
# surfaces: report string, mechanism phrases, whatif payers
# ---------------------------------------------------------------------------


def test_report_string_names_active_policy():
    from armada_tpu.services.reports import RoundReport

    rep = RoundReport(pool="p", started=0.0, finished=1.0, num_jobs=0,
                      num_nodes=0, fairness_policy="proportional")
    assert "fairness policy: proportional" in rep.report_string()


def test_mechanism_phrase_names_active_policy():
    from armada_tpu.observe import mechanism_phrase

    assert "DRF rebalance" in mechanism_phrase("fairness")
    assert "proportional-fairness rebalance" in mechanism_phrase(
        "fairness", "proportional"
    )
    assert "deadline-aware rebalance" in mechanism_phrase(
        "fairness", "deadline(boost=2,horizon=3600)"
    )
    # Non-fairness mechanisms keep their phrases regardless of policy.
    assert mechanism_phrase("urgency", "priority") == mechanism_phrase(
        "urgency"
    )


def test_whatif_policy_flip_fairness_delta_names_payers():
    """A what-if `policy=priority` rollout on a contended pool must
    re-solve under the candidate objective and name which queues pay
    (Plan.fairness_delta)."""
    from armada_tpu.core.types import QueueSpec as QS
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService
    from armada_tpu.whatif import WhatIfService, mutations_from_dicts

    cfg = SchedulingConfig(
        priority_classes={
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.0,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(cfg, log)
    submit = SubmitService(cfg, log, scheduler=sched)
    # Weights close enough that the DRF waterfill gives BOTH queues
    # capacity at baseline (heavy 2/3, light 1/3 of 4 slots) — strict
    # priority then hands the whole pool to the heavier queue, so the
    # flip has a payer to name.
    submit.create_queue(QS("heavy"))           # weight 1
    submit.create_queue(QS("light", 2.0))      # weight 0.5: pays first
    ex = FakeExecutor("ex", log, sched,
                      nodes=make_nodes("ex", count=2, cpu="8"),
                      runtime_for=lambda jid: 1e9)
    jobs = []
    for i in range(6):
        jobs.append(JobSpec(
            id=f"h{i}", queue="heavy", jobset="s",
            requests={"cpu": "4", "memory": "1Gi"}, submitted_ts=float(i),
        ))
    submit.submit("heavy", "s", jobs, now=0.0)
    light = [JobSpec(
        id=f"l{i}", queue="light", jobset="s",
        requests={"cpu": "4", "memory": "1Gi"}, submitted_ts=float(10 + i),
    ) for i in range(6)]
    submit.submit("light", "s", light, now=0.0)

    def cycle(t):
        ex.tick(t)
        sched.cycle(now=t)
        ex.tick(t)

    cycle(0.0)
    wi = WhatIfService(sched)
    sched.attach_whatif(wi)
    cycle(1.0)  # capture the fork seam with both queues live

    plan = wi.plan(
        mutations_from_dicts([{"kind": "policy", "policy": "priority"}]),
        rounds=3,
    )
    delta = plan.fairness_delta
    assert delta, "contended pool must produce a fairness delta"
    assert "light" in delta["queues"] and "heavy" in delta["queues"]
    # Strict priority hands the pool to the heavier queue: the
    # low-weight queue pays for the flip.
    assert "light" in delta["payers"], delta
    assert (
        delta["queues"]["heavy"]["delta_delivered"]
        >= -1e-9
    ), delta

    # Unknown candidate policies refuse at mutation decode time.
    with pytest.raises(ValueError, match="unknown fairness policy"):
        wi.plan(
            mutations_from_dicts([{"kind": "policy", "policy": "lottery"}]),
            rounds=1,
        )
