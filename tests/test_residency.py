"""Device-resident round state (armada_tpu/snapshot/residency.py).

The correctness contract is bit-exactness by construction: a warm cycle
that delta-scatters into the persistent device buffers must hand the
solver the SAME bits a fresh pad_device_round upload would have, so
every decision stream, fairness ledger and loop count is identical to
the rebuild path. Proven here at three scales:

  - unit: delta sync vs fresh upload on a lifecycle delta sequence
    (adds, binds, slot-table reshuffles), including the cached
    same-generation re-entry booking ZERO transfer bytes;
  - regrow: a submission burst past the padded pow2 capacity resets the
    residency (one full upload) and stays bit-exact;
  - system: a chaos sim (executor crash + partition windows, a queue
    cordon window, a staged executor drain) run under
    snapshot_mode="rebuild" and snapshot_mode="resident" produces
    identical fleet histories AND bit-identical flight-recorder bundles
    (solver inputs, decisions, fairness — trace.replayer.diff_traces'
    `resident_drift` divergence kind stays empty).

Plus the seams around the tentpole: the transfer ledger books zero
upload for an already-device-resident tree through both solve_round
paths (the headline bytes_up number must be honest), and what-if
planning keeps working while rounds run resident (the fork seam skips
incremental rounds; the planner's jobdb fork covers it).
"""

import numpy as np
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, RunningJob
from armada_tpu.observe import round_ledger
from armada_tpu.snapshot.incremental import IncrementalRound
from armada_tpu.snapshot.residency import ResidentRound
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round

QUEUES = [QueueSpec("q-a", 1.0), QueueSpec("q-b", 2.0)]

DECISION_KEYS = (
    "assigned_node",
    "scheduled_priority",
    "scheduled_mask",
    "preempted_mask",
    "fair_share",
    "demand_capped_fair_share",
    "uncapped_fair_share",
    "num_loops",
    "spot_price",
)


def make_config(**kw):
    return SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        **kw,
    )


def make_nodes(n=8):
    return [
        NodeSpec(
            id=f"node-{i:03d}",
            pool="default",
            labels={"zone": f"z{i % 2}"},
            total_resources={"cpu": "16", "memory": "64Gi"},
        )
        for i in range(n)
    ]


def job(i, queue="q-a", cpu=2, pc="low"):
    return JobSpec(
        id=f"job-{i:04d}",
        queue=queue,
        priority_class=pc,
        requests={"cpu": str(cpu), "memory": f"{cpu * 2}Gi"},
        submitted_ts=float(i),
    )


def assert_same_bits(resident, inc):
    """Every materialized resident device leaf must equal the fresh
    padded round bit-for-bit (through the same dtype canonicalization
    the upload path applies), and the drift check must agree."""
    import dataclasses

    fresh = pad_device_round(inc.device_round())
    dev = resident._dev
    for f in dataclasses.fields(fresh):
        want = getattr(fresh, f.name)
        got = getattr(dev, f.name)
        if isinstance(want, np.ndarray) and want.ndim >= 1:
            got = np.asarray(got)
            if want.dtype != got.dtype:  # x64-off canonicalization
                want = want.astype(got.dtype)
            assert want.shape == got.shape, f.name
            assert want.tobytes() == got.tobytes(), f.name
    assert resident.check_drift() == []


def lease_some(inc, out, n):
    """Bind the first n of last round's scheduled decisions."""
    snap = inc.snapshot()
    J = snap.num_jobs
    sched = np.flatnonzero(np.asarray(out["scheduled_mask"])[:J])[:n]
    assigned = np.asarray(out["assigned_node"])[:J]
    prio = np.asarray(out["scheduled_priority"])[:J]
    inc.bind(
        [
            (
                str(snap.job_ids[j]),
                snap.node_ids[int(assigned[j])],
                int(prio[j]),
                1.0,
            )
            for j in sched
        ]
    )


def test_delta_sync_bit_exact_and_solve_identical():
    """Warm-cycle delta syncs (including a lease-driven slot-table
    reshuffle) keep device == fresh upload bit-for-bit, and the solver
    run on the resident tree reproduces the rebuild decisions exactly."""
    cfg = make_config()
    inc = IncrementalRound(
        cfg, "default", make_nodes(8), QUEUES, [],
        [job(i, queue="q-a" if i % 2 else "q-b", cpu=1 + i % 3)
         for i in range(40)],
    )
    resident = ResidentRound()
    with round_ledger() as led:
        dev = resident.device_round(inc)
    assert resident.last_sync["mode"] == "reset"
    assert led.as_dict()["bytes_up"] > 0
    assert_same_bits(resident, inc)

    out = solve_round(dev)
    # Cycle: lease a handful (reshuffles the slot table between the
    # running and queued segments) and submit fresh work.
    lease_some(inc, out, 6)
    inc.add_jobs([job(100 + i) for i in range(4)])
    inc.set_round_params(global_rate_tokens=1e9)
    with round_ledger() as led:
        dev = resident.device_round(inc)
    sync = resident.last_sync
    assert sync["mode"] == "delta"
    assert sync["permuted"], "leases must reshuffle the slot table"
    assert led.as_dict()["bytes_up"] == sync["bytes_up"] > 0
    assert_same_bits(resident, inc)

    # The resident tree and a fresh upload must solve to identical bits.
    out_res = solve_round(dev)
    out_fresh = solve_round(pad_device_round(inc.device_round()))
    for k in DECISION_KEYS:
        np.testing.assert_array_equal(
            np.asarray(out_res[k]), np.asarray(out_fresh[k]), err_msg=k
        )

    # Same-generation re-entry (ladder retries, shadow probes) returns
    # the committed tree and books NOTHING.
    with round_ledger() as led:
        again = resident.device_round(inc)
    assert again is dev
    assert led.as_dict()["bytes_up"] == 0


def test_delta_cheaper_than_reset():
    """The point of the tentpole: a small-delta warm cycle uploads far
    less than the full round (here < 1/4 of the reset bytes)."""
    cfg = make_config()
    inc = IncrementalRound(
        cfg, "default", make_nodes(16), QUEUES, [],
        [job(i, queue="q-a" if i % 2 else "q-b") for i in range(400)],
    )
    resident = ResidentRound()
    resident.device_round(inc)
    reset_bytes = resident.last_sync["bytes_up"]
    inc.add_jobs([job(9000)])
    inc.set_round_params(global_rate_tokens=1e9)
    resident.device_round(inc)
    assert resident.last_sync["mode"] == "delta"
    assert resident.last_sync["bytes_up"] < reset_bytes / 4


def test_slot_overflow_regrows_and_resets():
    """A burst past the padded pow2 capacity changes the padded shapes:
    the residency must reset (full re-upload into regrown buffers) and
    stay bit-exact, then resume delta cycles on the new shapes."""
    cfg = make_config()
    inc = IncrementalRound(
        cfg, "default", make_nodes(8), QUEUES, [],
        [job(i) for i in range(40)],
    )
    resident = ResidentRound()
    dev0 = resident.device_round(inc)
    J0 = int(np.asarray(dev0.job_req).shape[0])

    # Overflow: enough new jobs to cross the pow2 job/slot boundary.
    inc.add_jobs([job(1000 + i) for i in range(J0)])
    inc.set_round_params(global_rate_tokens=1e9)
    with round_ledger() as led:
        dev1 = resident.device_round(inc)
    assert int(np.asarray(dev1.job_req).shape[0]) > J0
    assert resident.last_sync["mode"] == "reset"
    assert led.as_dict()["bytes_up"] == resident.last_sync["bytes_up"]
    assert_same_bits(resident, inc)

    # Delta cycles resume on the regrown buffers.
    inc.add_jobs([job(5000)])
    inc.set_round_params(global_rate_tokens=1e9)
    resident.device_round(inc)
    assert resident.last_sync["mode"] == "delta"
    assert_same_bits(resident, inc)


def test_drift_detection_and_reset():
    """A corrupted device buffer is caught by check_drift; reset()
    drops the resident state so the next sync is a fresh upload."""
    import jax

    cfg = make_config()
    inc = IncrementalRound(
        cfg, "default", make_nodes(4), QUEUES, [], [job(i) for i in range(8)]
    )
    resident = ResidentRound()
    resident.device_round(inc)
    assert resident.check_drift() == []
    poisoned = np.asarray(resident._dev.job_prio).copy()
    poisoned[0] += 1
    resident._dev.job_prio = jax.device_put(poisoned)
    assert resident.check_drift() == ["job_prio"]
    resident.reset()
    resident.device_round(inc)
    assert resident.last_sync["mode"] == "reset"
    assert resident.check_drift() == []


def test_ledger_books_zero_upload_for_resident_tree():
    """kernel.solve_round must count only true host->device transfers:
    an already-device-resident tree books ZERO bytes_up through BOTH
    the fused and the host-driven (budgeted) paths, on repeat solves
    too — the headline residency number depends on it."""
    import jax

    cfg = make_config()
    inc = IncrementalRound(
        cfg, "default", make_nodes(4), QUEUES, [], [job(i) for i in range(8)]
    )
    dev_host = pad_device_round(inc.device_round())
    dev_jax = jax.device_put(dev_host)
    jax.block_until_ready(jax.tree_util.tree_leaves(dev_jax))

    # Host tree: the dispatch upload books.
    with round_ledger() as led:
        solve_round(dev_host)
    assert led.as_dict()["bytes_up"] > 0

    for _ in range(2):  # fused path, repeat solves
        with round_ledger() as led:
            solve_round(dev_jax)
        books = led.as_dict()
        assert books["bytes_up"] == 0, books
        assert books["bytes_down"] > 0  # results still book
    with round_ledger() as led:  # host-driven (budgeted) path
        solve_round(dev_jax, budget_s=60.0)
    assert led.as_dict()["bytes_up"] == 0

    # And the ResidentRound tree IS such a tree.
    resident = ResidentRound()
    dev = resident.device_round(inc)
    with round_ledger() as led:
        out = solve_round(dev)
    assert led.as_dict()["bytes_up"] == 0
    out_fresh = solve_round(dev_host)
    for k in DECISION_KEYS:
        np.testing.assert_array_equal(
            np.asarray(out[k]), np.asarray(out_fresh[k]), err_msg=k
        )


# ---------------------------------------------------------------------------
# system-level: chaos sim differential + what-if during residency
# ---------------------------------------------------------------------------

SIM_CFG = SchedulingConfig(
    priority_classes={
        "high": PriorityClass("high", 30000, preemptible=False),
        "low": PriorityClass("low", 1000, preemptible=True),
    },
    default_priority_class="low",
    protected_fraction_of_fair_share=0.5,
)


def _chaos_sim(snapshot_mode, trace_path):
    """One chaos run: crash + partition fault windows from a seeded
    plan, a deterministic queue-cordon window, and a staged executor
    drain — all on the virtual clock, so both runs see identical
    sequences. Returns the SimResult-derived history."""
    from armada_tpu.services.chaos import FaultPlan, FaultSpec
    from armada_tpu.sim import (
        ClusterSpec,
        JobTemplate,
        QueueSpecSim,
        Simulator,
        WorkloadSpec,
    )
    from armada_tpu.sim.simulator import NodeTemplate, ShiftedExponential

    plan = FaultPlan(
        [
            FaultSpec("executor_crash", "c2", start=400.0, duration=300.0),
            FaultSpec("network_partition", "c1", start=900.0, duration=250.0),
            FaultSpec("lease_timeout", "c2", start=1400.0, duration=200.0),
        ],
        seed=11,
    )
    sim = Simulator(
        [
            ClusterSpec(
                "c1",
                node_templates=(
                    NodeTemplate(count=4, cpu="16", memory="64Gi",
                                 labels={"zone": "a"}),
                ),
            ),
            ClusterSpec(
                "c2",
                node_templates=(
                    NodeTemplate(count=4, cpu="16", memory="64Gi",
                                 labels={"zone": "b"}),
                ),
            ),
        ],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "steady",
                    job_templates=(
                        JobTemplate(id="long", number=24, cpu="2",
                                    memory="4Gi",
                                    runtime=ShiftedExponential(minimum=300.0)),
                    ),
                ),
                QueueSpecSim(
                    "bursty",
                    priority_factor=2.0,
                    job_templates=(
                        JobTemplate(id="gangs", number=16, cpu="4",
                                    memory="4Gi", gang_cardinality=4,
                                    submit_time=50.0,
                                    runtime=ShiftedExponential(minimum=120.0)),
                        JobTemplate(id="urgent", number=8, cpu="2",
                                    memory="2Gi", priority_class="high",
                                    submit_time=100.0,
                                    runtime=ShiftedExponential(minimum=60.0)),
                    ),
                ),
            )
        ),
        config=SIM_CFG,
        backend="kernel",
        snapshot_mode=snapshot_mode,
        seed=0,
        fault_plan=plan,
        trace_path=trace_path,
        max_time=4000.0,
    )

    # Deterministic operator actions on the virtual clock: a queue
    # cordon window and a staged drain of executor c2, injected through
    # the same cycle seam both runs share.
    orig_cycle = sim.scheduler.cycle
    started = {"drain": False}

    def cycle(now):
        if 600.0 <= now < 1000.0:
            sim.scheduler.cordoned_queues.add("steady")
        else:
            sim.scheduler.cordoned_queues.discard("steady")
        if now >= 1800.0 and not started["drain"]:
            sim.scheduler.drains.start("c2", deadline_s=400.0)
            started["drain"] = True
        return orig_cycle(now=now)

    sim.scheduler.cycle = cycle
    res = sim.run()
    return {
        "states": {k: v.value for k, v in res.events_by_job.items()},
        "placements": res.placements,
        "preemptions": res.preemptions,
        "finished": res.finished_jobs,
        "cycles": res.cycles,
    }


def test_chaos_sim_differential_resident_vs_rebuild(tmp_path):
    """The headline correctness gate: a long chaos sim (crashes,
    partitions, a cordon window, a staged drain) run with rebuilt
    snapshots and with device-resident delta rounds must produce the
    SAME fleet history, and the recorded flight-trace bundles must be
    bit-identical round by round — solver inputs, decision streams and
    fairness ledgers (diff_traces' resident_drift kind stays empty)."""
    from armada_tpu.trace.replayer import diff_traces, load_trace

    trace_a = str(tmp_path / "incremental.atrace")
    trace_b = str(tmp_path / "resident.atrace")
    rebuild = _chaos_sim("rebuild", None)
    incremental = _chaos_sim("incremental", trace_a)
    resident = _chaos_sim("resident", trace_b)

    # End-to-end: all three snapshot paths agree on the fleet history.
    for other in (incremental, resident):
        assert rebuild["finished"] == other["finished"]
        assert rebuild["preemptions"] == other["preemptions"]
        assert rebuild["states"] == other["states"]
        assert rebuild["placements"] == other["placements"]
    # sanity: the chaos actually landed and work still finished
    assert rebuild["finished"] >= 40

    # Bit-exactness: the delta-scattered resident rounds vs the SAME
    # incremental lifecycle re-uploaded fresh each cycle. (A rebuilt
    # round orders rows canonically, so it is only comparable at the
    # decision level above, not byte level.)
    report = diff_traces(load_trace(trace_a), load_trace(trace_b))
    assert report["pairs"] > 10
    assert report["unmatched"] == []
    assert report["divergences"] == {}, report["results"]
    assert report["ok"]


def test_diff_traces_flags_injected_drift(tmp_path):
    """diff_traces is a real gate, not a rubber stamp: a perturbed
    decision stream in one bundle classifies as resident_drift."""
    from armada_tpu.trace import TraceRecorder
    from armada_tpu.trace.replayer import diff_traces, load_trace

    cfg = make_config()
    inc = IncrementalRound(
        cfg, "default", make_nodes(4), QUEUES, [], [job(i) for i in range(8)]
    )
    snap = inc.snapshot()
    dev = pad_device_round(inc.device_round())
    out = {
        k: np.asarray(v)
        for k, v in solve_round(dev).items()
        if k not in ("profile", "truncated")
    }
    paths = []
    for tag, mutate in (("a", False), ("b", True)):
        decisions = {k: v.copy() for k, v in out.items()}
        if mutate:
            decisions["scheduled_mask"] = decisions["scheduled_mask"].copy()
            decisions["scheduled_mask"][0] = ~decisions["scheduled_mask"][0]
        path = str(tmp_path / f"{tag}.atrace")
        with TraceRecorder(path, source="test", config=cfg) as rec:
            rec.record_round(
                pool="default", dev=dev, decisions=decisions,
                num_jobs=snap.num_jobs, num_queues=snap.num_queues,
                config=cfg, cycle=1,
            )
        paths.append(path)
    report = diff_traces(load_trace(paths[0]), load_trace(paths[1]))
    assert not report["ok"]
    assert report["divergences"] == {"resident_drift": 1}
    (div,) = report["results"][0]["divergences"]
    assert div["key"] == "scheduled_mask"


def test_whatif_plan_during_residency():
    """Fork-during-residency parity: with rounds running device-resident
    (incremental), the round seam skips ForkCapture and the planner
    falls back to a jobdb fork — plans must still work and predict the
    same placements a rebuild-mode scheduler predicts from the same
    state."""
    from armada_tpu.core.types import QueueSpec as QS
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes as mk
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService
    from armada_tpu.whatif import WhatIfService, mutations_from_dicts

    def build(snapshot_mode):
        log = InMemoryEventLog()
        sched = SchedulerService(
            SIM_CFG, log, backend="kernel", snapshot_mode=snapshot_mode
        )
        submit = SubmitService(SIM_CFG, log, scheduler=sched)
        submit.create_queue(QS("team"))
        ex = FakeExecutor("ex-a", log, sched, nodes=mk("ex-a", count=2, cpu="8"))
        jobs = [
            JobSpec(id=f"j{i}", queue="team", jobset="s",
                    requests={"cpu": "4", "memory": "1Gi"},
                    submitted_ts=float(i))
            for i in range(4)
        ]
        submit.submit("team", "s", jobs, now=0.0)
        wi = WhatIfService(sched)
        sched.attach_whatif(wi)
        for t in (0.0, 1.0, 2.0):
            ex.tick(t)
            sched.cycle(now=t)
            ex.tick(t)
        return sched, wi

    sched_r, wi_r = build("resident")
    # Rounds ran resident: the capture seam must have skipped them.
    assert sched_r.fork_capture is not None
    assert sched_r.fork_capture.latest("pool") is None

    plans = {}
    for name, wi in (("resident", wi_r), ("rebuild", build("rebuild")[1])):
        plan = wi.plan(
            mutations_from_dicts(
                [{"kind": "inject_gang", "queue": "team",
                  "gang_cardinality": 2, "cpu": "4", "memory": "1Gi"}]
            ),
            rounds=4,
        )
        (gang,) = plan.injected
        plans[name] = {
            "feasible": gang["feasible"],
            "eta": gang["eta_rounds"],
            # "cycle" differs by fork source (captured round vs live
            # jobdb fork) — the state the plan saw must not.
            "baseline": {k: v for k, v in plan.baseline.items()
                         if k != "cycle"},
            "free": plan.headroom["pool"]["free"],
        }
    assert plans["resident"] == plans["rebuild"]


def test_scheduler_engages_resident_and_counts_modes():
    """snapshot_mode="auto" engages residency on kernel pools: the
    per-pool ResidentRound appears, warm cycles book delta-sized
    uploads, and scheduler_snapshot_mode_total counts the mode used."""
    from armada_tpu.core.types import QueueSpec as QS
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes as mk
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    log = InMemoryEventLog()
    sched = SchedulerService(SIM_CFG, log, backend="kernel")
    submit = SubmitService(SIM_CFG, log, scheduler=sched)
    submit.create_queue(QS("team"))
    ex = FakeExecutor("ex-a", log, sched, nodes=mk("ex-a", count=2, cpu="8"))
    submit.submit(
        "team", "s",
        [JobSpec(id=f"j{i}", queue="team", jobset="s",
                 requests={"cpu": "2", "memory": "1Gi"}, submitted_ts=float(i))
         for i in range(6)],
        now=0.0,
    )
    for t in (0.0, 1.0, 2.0, 3.0):
        ex.tick(t)
        sched.cycle(now=t)
        ex.tick(t)
    assert "default" in sched._resident
    resident = sched._resident["default"]
    assert resident.last_sync["mode"] in ("reset", "delta")
    assert resident.check_drift() == []
    if sched.metrics is not None and sched.metrics.registry is not None:
        counts = {}
        for metric in sched.metrics.registry.collect():
            if metric.name == "scheduler_snapshot_mode_total":
                for s in metric.samples:
                    if s.name.endswith("_total"):
                        counts[s.labels["mode"]] = s.value
        assert counts.get("resident", 0) >= 1
