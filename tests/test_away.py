"""Home/away scheduling: away node types (well-known taint sets) tried at
reduced priority after home scheduling fails (nodedb.go:487-595), with
kernel/oracle parity."""

import numpy as np

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.priorities import AwayNodeType, PriorityClass
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, Taint, Toleration
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver

# "gpu" nodes are tainted; gpu jobs tolerate natively at high priority;
# cpu jobs may run away on gpu nodes at low priority.
AWAY_CFG = SchedulingConfig(
    priority_classes={
        "gpu-native": PriorityClass("gpu-native", 30000, preemptible=False),
        "cpu": PriorityClass(
            "cpu",
            10000,
            preemptible=True,
            away_node_types=(AwayNodeType(priority=500, well_known_node_type="gpu-node"),),
        ),
    },
    default_priority_class="cpu",
    well_known_node_types={
        "gpu-node": (Taint("gpu", "true", "NoSchedule"),),
    },
)


def nodes(n_cpu=1, n_gpu=2):
    out = [
        NodeSpec(id=f"cpu-{i}", pool="default",
                 total_resources={"cpu": "8", "memory": "32Gi"})
        for i in range(n_cpu)
    ]
    out += [
        NodeSpec(id=f"gpu-{i}", pool="default",
                 taints=(Taint("gpu", "true", "NoSchedule"),),
                 total_resources={"cpu": "16", "memory": "64Gi"})
        for i in range(n_gpu)
    ]
    return out


def both(cfg, ns, queues, running, queued):
    snap = build_round_snapshot(cfg, "default", ns, queues, running, queued)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all(), (
        oracle.assigned_node, out["assigned_node"][:J]
    )
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    assert (oracle.preempted_mask == out["preempted_mask"][:J]).all()
    assert (oracle.scheduled_priority == out["scheduled_priority"][:J]).all()
    return snap, oracle


def cpu_job(i, cpu="4"):
    return JobSpec(id=f"c{i}", queue="q", priority_class="cpu",
                   requests={"cpu": cpu, "memory": "1Gi"}, submitted_ts=float(i))


def test_away_overflow_onto_tainted_nodes():
    # 1 cpu node (8 cpu) + 2 gpu nodes; 4 cpu jobs x 4 cpu: two land home,
    # two overflow away onto gpu nodes at the away priority 500.
    queued = [cpu_job(i) for i in range(4)]
    snap, res = both(AWAY_CFG, nodes(), [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 4
    placements = {snap.job_ids[j]: snap.node_ids[res.assigned_node[j]]
                  for j in range(4)}
    home = [j for j, n in placements.items() if n.startswith("cpu-")]
    away = [j for j, n in placements.items() if n.startswith("gpu-")]
    assert len(home) == 2 and len(away) == 2
    for jid in away:
        j = snap.job_ids.index(jid)
        assert res.scheduled_priority[j] == 500  # bound at away priority


def test_native_jobs_preempt_away_jobs():
    # An away cpu job (bound at 500) is urgency-preempted by a native gpu
    # job (30000) when the gpu node fills.
    running = []
    # away job occupying the only gpu node (bound at away priority 500)
    from armada_tpu.core.types import RunningJob

    running = [
        RunningJob(
            job=JobSpec(id="away0", queue="q", priority_class="cpu",
                        requests={"cpu": "12", "memory": "1Gi"},
                        tolerations=(Toleration(key="gpu", value="true"),)),
            node_id="gpu-0",
            scheduled_at_priority=500,
        )
    ]
    native = JobSpec(id="native0", queue="q", priority_class="gpu-native",
                     requests={"cpu": "12", "memory": "1Gi"},
                     tolerations=(Toleration(key="gpu", value="true"),),
                     submitted_ts=10.0)
    snap, res = both(
        AWAY_CFG, nodes(n_cpu=0, n_gpu=1), [QueueSpec("q")], running, [native]
    )
    n = snap.job_ids.index("native0")
    a = snap.job_ids.index("away0")
    assert res.scheduled_mask[n]
    assert res.preempted_mask[a]  # the away squatter was pushed off


def test_no_away_when_home_fits():
    queued = [cpu_job(0, cpu="2")]
    snap, res = both(AWAY_CFG, nodes(), [QueueSpec("q")], [], queued)
    assert snap.node_ids[res.assigned_node[0]].startswith("cpu-")
    assert res.scheduled_priority[0] == 10000  # home priority


def test_away_disabled_without_well_known_taints():
    cfg = SchedulingConfig(
        priority_classes={
            "cpu": PriorityClass(
                "cpu", 10000, preemptible=True,
                away_node_types=(AwayNodeType(500, "missing-type"),),
            ),
        },
        default_priority_class="cpu",
    )
    queued = [cpu_job(0, cpu="12")]  # only fits gpu nodes
    snap, res = both(cfg, nodes(n_cpu=1, n_gpu=1), [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 0  # no away capability granted


# ---------------------------------------------------------------------------
# Cross-pool away nodes (scheduling_algo.go:421-504, nodedb.go:506-595):
# pool "cpu-pool" borrows "gpu-pool" nodes; borrowed jobs account under the
# phantom "<queue>-away" bucket in gpu-pool's round and evict before home
# queues suffer.
# ---------------------------------------------------------------------------

from armada_tpu.core.config import PoolConfig  # noqa: E402
from armada_tpu.core.types import RunningJob as _RJ  # noqa: E402

CROSS_CFG = SchedulingConfig(
    priority_classes={
        "gpu-native": PriorityClass("gpu-native", 30000, preemptible=False),
        "cpu": PriorityClass(
            "cpu",
            10000,
            preemptible=True,
            away_node_types=(
                AwayNodeType(priority=500, well_known_node_type="gpu-node"),
            ),
        ),
    },
    default_priority_class="cpu",
    well_known_node_types={"gpu-node": (Taint("gpu", "true", "NoSchedule"),)},
    pools=(
        PoolConfig(name="cpu-pool", away_pools=("gpu-pool",)),
        PoolConfig(name="gpu-pool"),
    ),
)


def cross_nodes(n_cpu=1, n_gpu=2):
    out = [
        NodeSpec(id=f"cpu-{i}", pool="cpu-pool",
                 total_resources={"cpu": "8", "memory": "32Gi"})
        for i in range(n_cpu)
    ]
    out += [
        NodeSpec(id=f"gpu-{i}", pool="gpu-pool",
                 taints=(Taint("gpu", "true", "NoSchedule"),),
                 total_resources={"cpu": "16", "memory": "64Gi"})
        for i in range(n_gpu)
    ]
    return out


def cross_both(pool, ns, queues, running, queued):
    snap = build_round_snapshot(CROSS_CFG, pool, ns, queues, running, queued)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all()
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    assert (oracle.preempted_mask == out["preempted_mask"][:J]).all()
    assert (oracle.scheduled_priority == out["scheduled_priority"][:J]).all()
    return snap, oracle


def test_cross_pool_borrowing():
    """cpu-pool's round includes gpu-pool's nodes; overflow cpu jobs land
    on them at the away priority."""
    queued = [
        JobSpec(id=f"c{i}", queue="q", priority_class="cpu",
                requests={"cpu": "4", "memory": "1Gi"}, submitted_ts=float(i))
        for i in range(4)
    ]
    snap, res = cross_both("cpu-pool", cross_nodes(), [QueueSpec("q")], [], queued)
    assert set(snap.node_ids) == {"cpu-0", "gpu-0", "gpu-1"}
    assert res.scheduled_mask.sum() == 4
    away = [
        j for j in range(4)
        if snap.node_ids[res.assigned_node[j]].startswith("gpu-")
    ]
    assert len(away) == 2
    for j in away:
        assert res.scheduled_priority[j] == 500


def test_cross_pool_away_bucket_and_eviction():
    """gpu-pool's round sees borrowed cpu jobs under 'q-away' (weight of
    home queue, zero demand) and evicts them for native work."""
    running = [
        _RJ(
            job=JobSpec(id=f"away{i}", queue="q", priority_class="cpu",
                        requests={"cpu": "12", "memory": "1Gi"},
                        tolerations=(Toleration(key="gpu", value="true"),)),
            node_id=f"gpu-{i}",
            scheduled_at_priority=500,
            away=True,
        )
        for i in range(2)
    ]
    native = [
        JobSpec(id=f"n{i}", queue="gq", priority_class="gpu-native",
                requests={"cpu": "12", "memory": "1Gi"},
                tolerations=(Toleration(key="gpu", value="true"),),
                submitted_ts=10.0 + i)
        for i in range(2)
    ]
    ns = cross_nodes(n_cpu=0, n_gpu=2)
    snap, res = cross_both(
        "gpu-pool", ns, [QueueSpec("q"), QueueSpec("gq")], running, native
    )
    # Phantom fairness bucket exists with the home queue's weight and no
    # demand; the away allocation sits under it.
    assert "q-away" in snap.queue_names
    a_row = snap.queue_names.index("q-away")
    q_row = snap.queue_names.index("q")
    assert snap.queue_weight[a_row] == snap.queue_weight[q_row]
    assert (snap.queue_demand[a_row] == 0).all()
    assert snap.queue_allocated[a_row][0] > 0  # cpu of the borrowed jobs
    for i in range(2):
        j = list(snap.job_ids).index(f"away{i}")
        assert snap.job_queue[j] == a_row
        assert res.preempted_mask[j]
    for i in range(2):
        j = list(snap.job_ids).index(f"n{i}")
        assert res.scheduled_mask[j]


def test_cross_pool_unbound_away_pressure_only():
    """Away jobs on nodes outside this round contribute allocation under
    the phantom bucket but are never candidates (never preempted)."""
    running = [
        _RJ(
            job=JobSpec(id="faraway", queue="q", priority_class="cpu",
                        requests={"cpu": "12", "memory": "1Gi"}),
            node_id="not-a-node-here",
            scheduled_at_priority=500,
            away=True,
        )
    ]
    native = [
        JobSpec(id="n0", queue="gq", priority_class="gpu-native",
                requests={"cpu": "12", "memory": "1Gi"},
                tolerations=(Toleration(key="gpu", value="true"),),
                submitted_ts=10.0)
    ]
    ns = cross_nodes(n_cpu=0, n_gpu=1)
    snap, res = cross_both(
        "gpu-pool", ns, [QueueSpec("q"), QueueSpec("gq")], running, native
    )
    j = list(snap.job_ids).index("faraway")
    a_row = snap.queue_names.index("q-away")
    assert snap.job_queue[j] == a_row
    assert not res.preempted_mask[j]
    assert snap.queue_allocated[a_row][0] > 0
    assert res.scheduled_mask[list(snap.job_ids).index("n0")]


def test_cross_pool_service_end_to_end():
    """Full control plane: cpu jobs spill onto the gpu executor via
    cpu-pool's round (run.pool == cpu-pool); native gpu work then preempts
    the borrowers in gpu-pool's round; pool-restricted queued jobs only
    appear in their pools' rounds."""
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.jobdb import JobState
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    log = InMemoryEventLog()
    sched = SchedulerService(CROSS_CFG, log)
    submit = SubmitService(CROSS_CFG, log, scheduler=sched)
    cpu_exec = FakeExecutor(
        "cpu-cluster", log, sched,
        nodes=make_nodes("cpu-cluster", count=1, cpu="8", memory="32Gi",
                         pool="cpu-pool"),
        pool="cpu-pool",
    )
    gpu_exec = FakeExecutor(
        "gpu-cluster", log, sched,
        nodes=make_nodes("gpu-cluster", count=1, cpu="16", memory="64Gi",
                         pool="gpu-pool",
                         taints=(Taint("gpu", "true", "NoSchedule"),)),
        pool="gpu-pool",
    )
    submit.create_queue(QueueSpec("q"))
    # 4x4cpu cpu-pool jobs: 2 fit the cpu node, 2 borrow the gpu node.
    submit.submit(
        "q", "s",
        [
            JobSpec(id=f"c{i}", queue="q", priority_class="cpu",
                    pools=("cpu-pool",),
                    requests={"cpu": "4", "memory": "1Gi"})
            for i in range(4)
        ],
        now=0.0,
    )
    cpu_exec.tick(0.0)
    gpu_exec.tick(0.0)
    sched.cycle(now=1.0)
    txn = sched.jobdb.read_txn()
    borrowed = [
        jid for jid in ("c0", "c1", "c2", "c3")
        if txn.get(jid).latest_run.executor == "gpu-cluster"
    ]
    assert len(borrowed) == 2
    for jid in borrowed:
        run = txn.get(jid).latest_run
        assert run.pool == "cpu-pool"  # run pool = scheduling round's pool
        assert run.scheduled_at_priority == 500
    # Native gpu work arrives: borrowers get preempted in gpu-pool's round.
    submit.submit(
        "q", "s",
        [
            JobSpec(id=f"g{i}", queue="q", priority_class="gpu-native",
                    pools=("gpu-pool",),
                    tolerations=(Toleration(key="gpu", value="true"),),
                    requests={"cpu": "8", "memory": "1Gi"})
            for i in range(2)
        ],
        now=2.0,
    )
    cpu_exec.tick(2.0)
    gpu_exec.tick(2.0)
    sched.cycle(now=3.0)
    txn = sched.jobdb.read_txn()
    assert all(
        txn.get(f"g{i}").latest_run is not None
        and txn.get(f"g{i}").latest_run.executor == "gpu-cluster"
        for i in range(2)
    )
    preempted = [jid for jid in borrowed if txn.get(jid).state == JobState.PREEMPTED
                 or txn.get(jid).state == JobState.QUEUED]
    assert len(preempted) == 2


def test_cross_pool_no_same_cycle_double_booking():
    """Within one cycle, a node leased by an earlier pool's round must not
    be double-booked by a later round (pool rounds share nodes via away
    pools; earlier rounds' leases bind as pending runs)."""
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    log = InMemoryEventLog()
    sched = SchedulerService(CROSS_CFG, log)
    submit = SubmitService(CROSS_CFG, log, scheduler=sched)
    # No cpu nodes at all: every cpu job must borrow the single gpu node.
    gpu_exec = FakeExecutor(
        "gpu-cluster", log, sched,
        nodes=make_nodes("gpu-cluster", count=1, cpu="16", memory="64Gi",
                         pool="gpu-pool",
                         taints=(Taint("gpu", "true", "NoSchedule"),)),
        pool="gpu-pool",
    )
    submit.create_queue(QueueSpec("q"))
    # cpu-pool round (sorted first) borrows 12 of 16 cpus; the gpu-pool
    # round in the SAME cycle must see only 4 left for its native job.
    submit.submit(
        "q", "s",
        [
            JobSpec(id=f"c{i}", queue="q", priority_class="cpu",
                    pools=("cpu-pool",),
                    requests={"cpu": "6", "memory": "1Gi"})
            for i in range(2)
        ]
        + [
            JobSpec(id="g0", queue="q", priority_class="gpu-native",
                    pools=("gpu-pool",),
                    tolerations=(Toleration(key="gpu", value="true"),),
                    requests={"cpu": "6", "memory": "1Gi"})
        ],
        now=0.0,
    )
    gpu_exec.tick(0.0)
    sched.cycle(now=1.0)
    txn = sched.jobdb.read_txn()
    leased = [j for j in ("c0", "c1", "g0") if txn.get(j).latest_run is not None]
    total_cpu = sum(6 for _ in leased)
    # 16-cpu node: at most 2 of the three 6-cpu jobs fit concurrently —
    # never 18/16. (Preemption may bump a borrower in the gpu round, but
    # the set of live leases must fit.)
    live = [
        j for j in leased
        if txn.get(j).state.name in ("LEASED", "PENDING", "RUNNING")
    ]
    assert sum(6 for _ in live) <= 16, f"double-booked: {live}"
