"""Home/away scheduling: away node types (well-known taint sets) tried at
reduced priority after home scheduling fails (nodedb.go:487-595), with
kernel/oracle parity."""

import numpy as np

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.priorities import AwayNodeType, PriorityClass
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, Taint, Toleration
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver

# "gpu" nodes are tainted; gpu jobs tolerate natively at high priority;
# cpu jobs may run away on gpu nodes at low priority.
AWAY_CFG = SchedulingConfig(
    priority_classes={
        "gpu-native": PriorityClass("gpu-native", 30000, preemptible=False),
        "cpu": PriorityClass(
            "cpu",
            10000,
            preemptible=True,
            away_node_types=(AwayNodeType(priority=500, well_known_node_type="gpu-node"),),
        ),
    },
    default_priority_class="cpu",
    well_known_node_types={
        "gpu-node": (Taint("gpu", "true", "NoSchedule"),),
    },
)


def nodes(n_cpu=1, n_gpu=2):
    out = [
        NodeSpec(id=f"cpu-{i}", pool="default",
                 total_resources={"cpu": "8", "memory": "32Gi"})
        for i in range(n_cpu)
    ]
    out += [
        NodeSpec(id=f"gpu-{i}", pool="default",
                 taints=(Taint("gpu", "true", "NoSchedule"),),
                 total_resources={"cpu": "16", "memory": "64Gi"})
        for i in range(n_gpu)
    ]
    return out


def both(cfg, ns, queues, running, queued):
    snap = build_round_snapshot(cfg, "default", ns, queues, running, queued)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all(), (
        oracle.assigned_node, out["assigned_node"][:J]
    )
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    assert (oracle.preempted_mask == out["preempted_mask"][:J]).all()
    assert (oracle.scheduled_priority == out["scheduled_priority"][:J]).all()
    return snap, oracle


def cpu_job(i, cpu="4"):
    return JobSpec(id=f"c{i}", queue="q", priority_class="cpu",
                   requests={"cpu": cpu, "memory": "1Gi"}, submitted_ts=float(i))


def test_away_overflow_onto_tainted_nodes():
    # 1 cpu node (8 cpu) + 2 gpu nodes; 4 cpu jobs x 4 cpu: two land home,
    # two overflow away onto gpu nodes at the away priority 500.
    queued = [cpu_job(i) for i in range(4)]
    snap, res = both(AWAY_CFG, nodes(), [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 4
    placements = {snap.job_ids[j]: snap.node_ids[res.assigned_node[j]]
                  for j in range(4)}
    home = [j for j, n in placements.items() if n.startswith("cpu-")]
    away = [j for j, n in placements.items() if n.startswith("gpu-")]
    assert len(home) == 2 and len(away) == 2
    for jid in away:
        j = snap.job_ids.index(jid)
        assert res.scheduled_priority[j] == 500  # bound at away priority


def test_native_jobs_preempt_away_jobs():
    # An away cpu job (bound at 500) is urgency-preempted by a native gpu
    # job (30000) when the gpu node fills.
    running = []
    # away job occupying the only gpu node (bound at away priority 500)
    from armada_tpu.core.types import RunningJob

    running = [
        RunningJob(
            job=JobSpec(id="away0", queue="q", priority_class="cpu",
                        requests={"cpu": "12", "memory": "1Gi"},
                        tolerations=(Toleration(key="gpu", value="true"),)),
            node_id="gpu-0",
            scheduled_at_priority=500,
        )
    ]
    native = JobSpec(id="native0", queue="q", priority_class="gpu-native",
                     requests={"cpu": "12", "memory": "1Gi"},
                     tolerations=(Toleration(key="gpu", value="true"),),
                     submitted_ts=10.0)
    snap, res = both(
        AWAY_CFG, nodes(n_cpu=0, n_gpu=1), [QueueSpec("q")], running, [native]
    )
    n = snap.job_ids.index("native0")
    a = snap.job_ids.index("away0")
    assert res.scheduled_mask[n]
    assert res.preempted_mask[a]  # the away squatter was pushed off


def test_no_away_when_home_fits():
    queued = [cpu_job(0, cpu="2")]
    snap, res = both(AWAY_CFG, nodes(), [QueueSpec("q")], [], queued)
    assert snap.node_ids[res.assigned_node[0]].startswith("cpu-")
    assert res.scheduled_priority[0] == 10000  # home priority


def test_away_disabled_without_well_known_taints():
    cfg = SchedulingConfig(
        priority_classes={
            "cpu": PriorityClass(
                "cpu", 10000, preemptible=True,
                away_node_types=(AwayNodeType(500, "missing-type"),),
            ),
        },
        default_priority_class="cpu",
    )
    queued = [cpu_job(0, cpu="12")]  # only fits gpu nodes
    snap, res = both(cfg, nodes(n_cpu=1, n_gpu=1), [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 0  # no away capability granted
