"""Batched-fill semantics.

Exact mode (batch_fill_window, default on) is covered by the whole parity
suite: the kernel must match the serial oracle bit-for-bit.

Fast mode (enable_fast_fill) batches a multi-queue sweep per iteration:
the scheduled job SET and every queue-level accounting output must match
the serial loop whenever each batched job fits without preemption; node
assignments may legitimately differ (greedy per-queue packing vs
attempt-interleaved). These tests assert set parity on capacity-ample
scenarios, physical invariants everywhere, and that the loop count
actually collapses (the point of the fast path)."""

import dataclasses

import numpy as np
import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round

from test_kernel_parity import PREEMPT_CFG, rand_scenario


def solve_both(cfg, nodes, queues, running, queued):
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    dev = pad_device_round(prep_device_round(snap))
    serial = solve_round(dataclasses.replace(dev, fast_fill=False))
    fast = solve_round(dataclasses.replace(dev, fast_fill=True))
    return snap, serial, fast


def assert_no_overcommit(snap, out):
    """Physical invariant: per-node usage of bound jobs never exceeds the
    node totals (scheduled + running-not-preempted).

    Members of mixed-priority-class gangs are excluded: such gangs can
    transiently overcommit for one round in the reference too (the
    documented faithful edge case in docs/parity.md — the serial loop
    exhibits the identical overcommit on the same scenarios)."""
    J, N = snap.num_jobs, snap.num_nodes
    mixed_gang_member = np.zeros(J, dtype=bool)
    for g in range(snap.num_gangs):
        members = snap.gang_members[
            snap.gang_member_offsets[g] : snap.gang_member_offsets[g + 1]
        ]
        if len(members) > 1 and len(set(snap.job_priority[members])) > 1:
            mixed_gang_member[members] = True
    usage = np.zeros((N, snap.factory.num_resources), dtype=np.int64)
    bound = (
        (out["scheduled_mask"][:J])
        | (snap.job_is_running & ~out["preempted_mask"][:J])
    ) & ~mixed_gang_member
    req_fit = snap.job_req_fit()
    for j in np.flatnonzero(bound):
        n = int(out["assigned_node"][j])
        if 0 <= n < N:
            usage[n] += req_fit[j]
    assert (usage <= snap.node_total).all(), "node overcommit"


def assert_set_parity(snap, serial, fast, label=""):
    J = snap.num_jobs
    s_set = serial["scheduled_mask"][:J]
    f_set = fast["scheduled_mask"][:J]
    mism = np.flatnonzero(s_set != f_set)
    detail = [(snap.job_ids[j], bool(s_set[j]), bool(f_set[j])) for j in mism[:10]]
    assert (s_set == f_set).all(), f"{label}: scheduled-set mismatch {detail}"
    assert (
        serial["preempted_mask"][:J] == fast["preempted_mask"][:J]
    ).all(), label
    np.testing.assert_allclose(
        serial["demand_capped_fair_share"],
        fast["demand_capped_fair_share"],
        rtol=1e-12,
        err_msg=label,
    )


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.slow
def test_fast_fill_set_parity_queued_only(seed):
    rng = np.random.default_rng(1000 + seed)
    nodes, queues, running, queued = rand_scenario(
        rng, with_running=False, with_gangs=True
    )
    snap, serial, fast = solve_both(PREEMPT_CFG, nodes, queues, [], queued)
    assert_set_parity(snap, serial, fast, f"seed={seed}")
    assert_no_overcommit(snap, fast)


@pytest.mark.parametrize("seed", range(6, 10))
def test_fast_fill_invariants_with_running(seed):
    # With evictions in play the fast path can legitimately re-order
    # preemption-dependent attempts; assert physical invariants only.
    rng = np.random.default_rng(1000 + seed)
    nodes, queues, running, queued = rand_scenario(rng, with_running=True)
    snap, serial, fast = solve_both(PREEMPT_CFG, nodes, queues, running, queued)
    assert_no_overcommit(snap, fast)
    assert_no_overcommit(snap, serial)


def test_fast_fill_collapses_loops():
    """The point of fast mode: a many-queue backlog of identical singletons
    schedules in a handful of iterations, not one per job."""
    cfg = SchedulingConfig()
    nodes = [
        NodeSpec(
            id=f"n{i:03d}",
            pool="default",
            total_resources={"cpu": "32", "memory": "256Gi"},
        )
        for i in range(20)
    ]
    queues = [QueueSpec(f"q{i}", 1.0) for i in range(4)]
    queued = [
        JobSpec(
            id=f"j{i:04d}",
            queue=f"q{i % 4}",
            requests={"cpu": "1", "memory": "1Gi"},
            submitted_ts=float(i),
        )
        for i in range(400)
    ]
    snap, serial, fast = solve_both(cfg, nodes, queues, [], queued)
    assert fast["scheduled_mask"].sum() == serial["scheduled_mask"].sum() == 400
    assert_set_parity(snap, serial, fast, "collapse")
    assert int(serial["num_loops"]) >= 400
    assert int(fast["num_loops"]) <= 12, f"fast loops {fast['num_loops']}"


@pytest.mark.slow
def test_fast_fill_respects_burst_caps():
    cfg = SchedulingConfig()
    cfg = dataclasses.replace(
        cfg, rate_limits=dataclasses.replace(cfg.rate_limits, maximum_scheduling_burst=37)
    )
    nodes = [
        NodeSpec(
            id="n0", pool="default", total_resources={"cpu": "500", "memory": "500Gi"}
        )
    ]
    queued = [
        JobSpec(
            id=f"j{i:04d}",
            queue=f"q{i % 3}",
            requests={"cpu": "1", "memory": "1Gi"},
            submitted_ts=float(i),
        )
        for i in range(120)
    ]
    queues = [QueueSpec(f"q{i}") for i in range(3)]
    snap, serial, fast = solve_both(cfg, nodes, queues, [], queued)
    assert int(fast["scheduled_mask"].sum()) == 37
    assert_set_parity(snap, serial, fast, "burst")


@pytest.mark.slow
def test_fast_fill_heterogeneous_stream():
    """Mixed scheduling keys WITHIN each queue's stream (random sizes, so
    same-key runs average ~1.3 slots): the heterogeneous window must batch
    across key changes — set parity, invariants, and a loop count far
    below the number of scheduled jobs."""
    rng = np.random.default_rng(7)
    cfg = SchedulingConfig()
    nodes = [
        NodeSpec(
            id=f"n{i:03d}",
            pool="default",
            total_resources={"cpu": "32", "memory": "256Gi"},
        )
        for i in range(100)
    ]
    queues = [QueueSpec(f"q{i}", 1.0) for i in range(4)]
    sizes = rng.choice([1, 2, 4, 8], size=600)
    queued = [
        JobSpec(
            id=f"j{i:04d}",
            queue=f"q{i % 4}",
            requests={"cpu": str(int(sizes[i])), "memory": f"{int(sizes[i])}Gi"},
            submitted_ts=float(i),
        )
        for i in range(600)
    ]
    snap, serial, fast = solve_both(cfg, nodes, queues, [], queued)
    assert_set_parity(snap, serial, fast, "hetero-stream")
    assert_no_overcommit(snap, fast)
    # 600 mixed-key jobs over 4 queues: a run-length-limited fill needs
    # ~100+ iterations; the heterogeneous window needs a handful.
    assert int(fast["num_loops"]) <= 12, f"fast loops {fast['num_loops']}"


@pytest.mark.slow
def test_fast_fill_group_cap_cut():
    """More distinct keys than fill_group_max in one window: the window is
    cut, extra keys batch next iteration — still set-exact."""
    import dataclasses as _dc

    cfg = _dc.replace(SchedulingConfig(), fill_group_max=3)
    nodes = [
        NodeSpec(
            id=f"n{i:03d}",
            pool="default",
            total_resources={"cpu": "64", "memory": "512Gi"},
        )
        for i in range(12)
    ]
    queues = [QueueSpec("q0", 1.0), QueueSpec("q1", 1.0)]
    # 8 distinct cpu sizes cycling -> every window holds > 3 keys.
    queued = [
        JobSpec(
            id=f"j{i:04d}",
            queue=f"q{i % 2}",
            requests={"cpu": str(1 + (i % 8)), "memory": "1Gi"},
            submitted_ts=float(i),
        )
        for i in range(160)
    ]
    snap, serial, fast = solve_both(cfg, nodes, queues, [], queued)
    assert_set_parity(snap, serial, fast, "group-cap")
    assert_no_overcommit(snap, fast)


def test_fast_fill_heterogeneous_queues():
    """Queues with different request shapes: the merged order is still the
    serial order (closed-form costs), set parity must hold."""
    cfg = SchedulingConfig()
    nodes = [
        NodeSpec(
            id=f"n{i:02d}",
            pool="default",
            total_resources={"cpu": "64", "memory": "512Gi"},
        )
        for i in range(8)
    ]
    queues = [QueueSpec("small", 1.0), QueueSpec("big", 2.0), QueueSpec("mid", 1.0)]
    queued = (
        [
            JobSpec(id=f"s{i:03d}", queue="small", requests={"cpu": "1", "memory": "2Gi"}, submitted_ts=float(i))
            for i in range(60)
        ]
        + [
            JobSpec(id=f"b{i:03d}", queue="big", requests={"cpu": "8", "memory": "16Gi"}, submitted_ts=float(i))
            for i in range(30)
        ]
        + [
            JobSpec(id=f"m{i:03d}", queue="mid", requests={"cpu": "3", "memory": "4Gi"}, submitted_ts=float(i))
            for i in range(40)
        ]
    )
    snap, serial, fast = solve_both(cfg, nodes, queues, [], queued)
    assert_set_parity(snap, serial, fast, "hetero")
    assert_no_overcommit(snap, fast)
    assert int(fast["num_loops"]) < int(serial["num_loops"]) // 4


@pytest.mark.slow
def test_fast_fill_batches_evicted_rebinds():
    """Preemption-heavy round: a hog queue's running jobs are evicted for
    balance and mostly rebind to their nodes. The evicted-window fast path
    must batch those pinned rebinds — set parity (including preemptions),
    invariants, and a loop count far below the evictee count."""
    from armada_tpu.core.types import RunningJob

    n_nodes, n_running, n_queued = 50, 400, 200
    nodes = [
        NodeSpec(
            id=f"n{i:03d}",
            pool="default",
            total_resources={"cpu": "32", "memory": "256Gi"},
        )
        for i in range(n_nodes)
    ]
    queues = [QueueSpec(f"q{i}", 1.0) for i in range(4)]
    running = [
        RunningJob(
            job=JobSpec(
                id=f"run-{i:05d}",
                queue="q0",  # hog queue: over fair share -> evicted
                requests={"cpu": "2", "memory": "4Gi"},
                submitted_ts=float(-n_running + i),
            ),
            node_id=f"n{i % n_nodes:03d}",
            scheduled_at_priority=1000,
        )
        for i in range(n_running)
    ]
    queued = [
        JobSpec(
            id=f"j{i:05d}",
            queue=f"q{1 + i % 3}",
            requests={"cpu": str(1 + i % 3), "memory": "2Gi"},
            submitted_ts=float(i),
        )
        for i in range(n_queued)
    ]
    cfg = dataclasses.replace(
        PREEMPT_CFG, protected_fraction_of_fair_share=0.5
    )
    snap, serial, fast = solve_both(cfg, nodes, queues, running, queued)
    assert_set_parity(snap, serial, fast, "evicted-rebind")
    assert_no_overcommit(snap, fast)
    assert (
        np.asarray(serial["preempted_mask"])
        == np.asarray(fast["preempted_mask"])
    ).all(), "preemption outcomes diverge"
    # Rebinds for pinned jobs land on the SAME node in both modes.
    J = snap.num_jobs
    rb = snap.job_is_running & ~np.asarray(fast["preempted_mask"])[:J]
    assert (
        np.asarray(serial["assigned_node"])[:J][rb]
        == np.asarray(fast["assigned_node"])[:J][rb]
    ).all()
    # 400 evictees + 200 queued mixed keys: serial needs 600+ loops; the
    # window path needs tens.
    assert int(fast["num_loops"]) < int(serial["num_loops"]) / 5, (
        f"fast {fast['num_loops']} vs serial {serial['num_loops']}"
    )


@pytest.mark.slow
def test_fast_fill_evicted_rebind_capacity_cut():
    """An evicted window where later rebinds no longer fit (queued work
    from another queue got the capacity first in merged order): the window
    cuts at the first failure and outcomes still match the serial loop."""
    from armada_tpu.core.types import RunningJob

    # One small node fully occupied by evictees; a competing queue's big
    # queued jobs contend for the same capacity.
    nodes = [
        NodeSpec(id="n0", pool="default",
                 total_resources={"cpu": "8", "memory": "32Gi"}),
        NodeSpec(id="n1", pool="default",
                 total_resources={"cpu": "8", "memory": "32Gi"}),
    ]
    queues = [QueueSpec("hog", 1.0), QueueSpec("fresh", 1.0)]
    running = [
        RunningJob(
            job=JobSpec(
                id=f"run-{i}", queue="hog",
                requests={"cpu": "2", "memory": "4Gi"},
                submitted_ts=float(-8 + i),
            ),
            node_id=f"n{i % 2}",
            scheduled_at_priority=1000,
        )
        for i in range(8)
    ]
    queued = [
        JobSpec(
            id=f"j{i}", queue="fresh",
            requests={"cpu": "4", "memory": "8Gi"},
            submitted_ts=float(i),
        )
        for i in range(4)
    ]
    cfg = dataclasses.replace(
        PREEMPT_CFG, protected_fraction_of_fair_share=0.0
    )
    snap, serial, fast = solve_both(cfg, nodes, queues, running, queued)
    assert_set_parity(snap, serial, fast, "evicted-cut")
    assert_no_overcommit(snap, fast)
    assert (
        np.asarray(serial["preempted_mask"])
        == np.asarray(fast["preempted_mask"])
    ).all()


def test_lookback_bounds_batched_fill_runs():
    """Past-lookback slots are never batchable: the fill fast path places
    whole run prefixes without per-slot lookback checks, so eligibility
    must stop at the horizon even when the size-shrink is skipped
    (stopYieldingNewJobsIfLimitHit semantics on every path)."""
    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
    from armada_tpu.snapshot.round import build_round_snapshot
    from armada_tpu.solver.kernel import solve_round
    from armada_tpu.solver.kernel_prep import (
        pad_device_round,
        prep_device_round,
    )
    from armada_tpu.solver.reference import ReferenceSolver

    cfg = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
        max_queue_lookback=5,
        batch_fill_window=512,  # plain batched fill (fast_fill off)
    )
    nodes = [
        NodeSpec(id=f"n{i}", pool="default",
                 total_resources={"cpu": "64", "memory": "256Gi"})
        for i in range(2)
    ]
    # 8 identical batchable jobs: _pow2(5) == _pow2(8), so the shrink is
    # skipped and the lookback bound must come from run eligibility.
    queued = [
        JobSpec(id=f"lb-{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"},
                submitted_ts=float(i))
        for i in range(8)
    ]
    snap = build_round_snapshot(cfg, "default", nodes, [QueueSpec("q")], [],
                                queued)
    dev = prep_device_round(snap)
    assert not dev.slot_batchable[5:8].any()
    out = solve_round(pad_device_round(dev))
    J = snap.num_jobs
    assert int(out["scheduled_mask"][:J].sum()) == 5  # horizon enforced
    oracle = ReferenceSolver(snap).solve()
    import numpy as np

    assert np.array_equal(oracle.scheduled_mask, out["scheduled_mask"][:J])
