"""Network-partition chaos, lease fencing, and anti-entropy reconciliation.

Covers the wire-level fault layer (services/netchaos.py), the fencing
protocol (grpc_api FAILED_PRECONDITION on stale tokens + ExecutorSync),
the executor agent's lease-TTL/orphan-candidate behavior, the ingester's
stale-run guards (one terminal outcome per job), FileLeaseLeader fencing
under interleaved takeover, and a real-socket end-to-end partition test
through a live ControlPlane.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

from armada_tpu.services.chaos import (
    ExponentialBackoff,
    FaultPlan,
    FaultSpec,
    VirtualClock,
)
from armada_tpu.services.netchaos import ChaosProxy


# ---------------------------------------------------------------- helpers


def echo_server():
    """A TCP echo upstream; returns (port, close)."""
    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    ls.bind(("127.0.0.1", 0))
    ls.listen(16)

    def pump(conn):
        try:
            while True:
                data = conn.recv(65536)
                if not data:
                    return
                conn.sendall(data)
        except OSError:
            pass
        finally:
            conn.close()

    def serve():
        while True:
            try:
                conn, _ = ls.accept()
            except OSError:
                return
            threading.Thread(target=pump, args=(conn,), daemon=True).start()

    threading.Thread(target=serve, daemon=True).start()
    return ls.getsockname()[1], ls.close


def start_proxy(plan, clock):
    port, close_upstream = echo_server()
    proxy = ChaosProxy("e0", "127.0.0.1", port, plan, clock=clock)
    proxy.start()
    return proxy, close_upstream


def connect(proxy):
    sock = socket.create_connection(("127.0.0.1", proxy._listen_port), 2.0)
    sock.settimeout(2.0)
    return sock


def roundtrip(sock, payload=b"ping"):
    sock.sendall(payload)
    got = b""
    while len(got) < len(payload):
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed")
        got += chunk
    return got


# ------------------------------------------------------------ ChaosProxy


def test_proxy_forwards_cleanly():
    clock = VirtualClock()
    proxy, close = start_proxy(FaultPlan([]), clock)
    try:
        sock = connect(proxy)
        assert roundtrip(sock, b"hello") == b"hello"
        sock.close()
        assert proxy.bytes_forwarded >= 10  # both directions
    finally:
        proxy.stop()
        close()


def test_partition_severs_live_and_new_connections_then_heals():
    clock = VirtualClock()
    plan = FaultPlan([FaultSpec("network_partition", "e0", 100.0, 100.0)])
    proxy, close = start_proxy(plan, clock)
    try:
        sock = connect(proxy)
        assert roundtrip(sock) == b"ping"
        # Sever: the reaper tears the LIVE connection down mid-stream.
        clock.now = 150.0
        deadline = time.time() + 2.0
        severed = False
        while time.time() < deadline:
            try:
                sock.sendall(b"x")
                if sock.recv(65536) == b"":
                    severed = True
                    break
            except OSError:
                severed = True
                break
            time.sleep(0.02)
        assert severed, "live connection survived the partition window"
        # New connections are refused for the window: the listener is
        # DOWN, so the kernel answers ECONNREFUSED (clean — clients'
        # reconnect machinery handles it like any dead endpoint).
        with pytest.raises(OSError):
            fresh = connect(proxy)
            try:
                fresh.sendall(b"y")
                if fresh.recv(65536) == b"":
                    raise ConnectionError("severed")
            finally:
                fresh.close()
        # Heal: the wire works again (the accept loop rebinds its
        # listener within one poll interval).
        clock.now = 250.0
        deadline = time.time() + 2.0
        while True:
            try:
                healed = connect(proxy)
                break
            except OSError:
                assert time.time() < deadline, "listener never came back"
                time.sleep(0.05)
        assert roundtrip(healed, b"back") == b"back"
        healed.close()
        assert proxy.connections_severed >= 1
    finally:
        proxy.stop()
        close()


def test_rst_resets_connections():
    clock = VirtualClock(now=50.0)
    plan = FaultPlan([FaultSpec("network_rst", "e0", 0.0, 100.0)])
    proxy, close = start_proxy(plan, clock)
    try:
        # Accept-path RST: the reset may land during connect itself (the
        # proxy RSTs as fast as it accepts) or on the first interaction —
        # every image of it is an OSError, never a clean exchange.
        with pytest.raises(OSError):
            sock = connect(proxy)
            try:
                sock.sendall(b"x")
                if sock.recv(65536) == b"":
                    raise ConnectionResetError("closed")
            finally:
                sock.close()
    finally:
        proxy.stop()
        close()


def test_blackhole_swallows_without_closing():
    clock = VirtualClock()
    plan = FaultPlan([FaultSpec("network_blackhole", "e0", 10.0, 100.0)])
    proxy, close = start_proxy(plan, clock)
    try:
        sock = connect(proxy)
        assert roundtrip(sock) == b"ping"  # pre-window: clean
        clock.now = 50.0
        sock.sendall(b"lost")
        sock.settimeout(0.5)
        with pytest.raises(TimeoutError):
            sock.recv(65536)  # no reply, no close: a routing black hole
        sock.close()
        assert proxy.bytes_blackholed >= 4
    finally:
        proxy.stop()
        close()


def test_delay_adds_latency():
    clock = VirtualClock(now=50.0)
    plan = FaultPlan(
        [FaultSpec("network_delay", "e0", 0.0, 100.0, param=0.25)]
    )
    proxy, close = start_proxy(plan, clock)
    try:
        sock = connect(proxy)
        started = time.time()
        assert roundtrip(sock) == b"ping"
        # Request and reply chunks each eat the delay at least once.
        assert time.time() - started >= 0.25
        sock.close()
    finally:
        proxy.stop()
        close()


def test_generate_network_kinds_deterministic():
    kinds = ("network_partition", "network_rst")
    a = FaultPlan.generate(9, 500.0, executors=["e0"], kinds=kinds)
    b = FaultPlan.generate(9, 500.0, executors=["e0"], kinds=kinds)
    assert a.faults == b.faults
    assert {f.kind for f in a.faults} == set(kinds)
    assert all(f.target == "e0" for f in a.faults)
    # The default mix stays network-free: pre-existing seeded soaks keep
    # their schedules.
    assert not any(
        f.kind.startswith("network")
        for f in FaultPlan.generate(9, 500.0, executors=["e0"]).faults
    )


# ------------------------------------------------------ backoff budget


def test_backoff_budget_capped_at_lease_ttl():
    b = ExponentialBackoff(base_s=1.0, cap_s=8.0, seed=3, budget_s=5.0)
    total = 0.0
    for _ in range(50):
        total += b.next_delay()
        if b.exhausted:
            break
    assert total <= 5.0 + 1e-9
    assert b.exhausted
    # Past the budget: flat base_s polling, never longer sleeps.
    assert b.next_delay() == 1.0
    b.reset()
    assert not b.exhausted and b.spent_s == 0.0


# ------------------------------------------- ingester stale-run guards


def _mk_sched_stack(**cfg_kw):
    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.scheduler import SchedulerService

    config = SchedulingConfig(
        priority_classes={
            "default": PriorityClass("default", 1000, preemptible=True),
        },
        default_priority_class="default",
        enable_assertions=True,
        **cfg_kw,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    return config, log, sched


def _publish(log, queue, jobset, *events):
    from armada_tpu.events import EventSequence

    log.publish(EventSequence.of(queue, jobset, *events))


def test_stale_run_success_after_requeue_single_terminal_outcome():
    """The acceptance scenario: a requeued job whose OLD run reports
    success after the partition heals must resolve to exactly one
    terminal outcome — the requeue wins, the new run's outcome lands."""
    from armada_tpu.core.types import JobSpec
    from armada_tpu.events import (
        JobRequeued,
        JobRunErrors,
        JobRunLeased,
        JobRunRunning,
        JobRunSucceeded,
        JobSucceeded,
        SubmitJob,
    )
    from armada_tpu.jobdb import JobState
    from armada_tpu.jobdb.jobdb import RunState

    _, log, sched = _mk_sched_stack()
    spec = JobSpec(id="j-1", queue="q", jobset="s",
                   requests={"cpu": "1", "memory": "1Gi"})
    _publish(log, "q", "s", SubmitJob(created=0.0, job=spec))
    _publish(log, "q", "s",
             JobRunLeased(created=1.0, job_id="j-1", run_id="run-old",
                          executor="e0", node_id="n0", pool="default"))
    _publish(log, "q", "s",
             JobRunRunning(created=2.0, job_id="j-1", run_id="run-old"))
    # Partition: the scheduler expires the run and requeues the job.
    _publish(log, "q", "s",
             JobRunErrors(created=10.0, job_id="j-1", run_id="run-old",
                          error="executor e0 timed out", retryable=True),
             JobRequeued(created=10.0, job_id="j-1"))
    sched.ingester.sync()
    job = sched.jobdb.get("j-1")
    assert job.state == JobState.QUEUED
    assert job.latest_run.state == RunState.FAILED

    # Heal: the zombie's stale success echoes in. Both events must drop.
    _publish(log, "q", "s",
             JobRunSucceeded(created=12.0, job_id="j-1", run_id="run-old"),
             JobSucceeded(created=12.0, job_id="j-1"))
    sched.ingester.sync()
    job = sched.jobdb.get("j-1")
    assert job.state == JobState.QUEUED, "stale success resurrected the job"
    assert job.latest_run.state == RunState.FAILED

    # Re-leased ordering: the stale success may also land AFTER the
    # requeue was re-leased (run-new live). It must not mark the job
    # SUCCEEDED out from under the active run — success is run-anchored.
    _publish(log, "q", "s",
             JobRunLeased(created=15.0, job_id="j-1", run_id="run-tmp",
                          executor="e1", node_id="n1", pool="default"))
    _publish(log, "q", "s",
             JobRunSucceeded(created=16.0, job_id="j-1", run_id="run-old"),
             JobSucceeded(created=16.0, job_id="j-1"))
    sched.ingester.sync()
    job = sched.jobdb.get("j-1")
    assert job.state == JobState.LEASED, (
        "stale success terminated a job with a live re-leased run"
    )
    assert job.latest_run.id == "run-tmp"
    # (Fail run-tmp + requeue so the canonical path below proceeds.)
    _publish(log, "q", "s",
             JobRunErrors(created=17.0, job_id="j-1", run_id="run-tmp",
                          error="executor e1 timed out", retryable=True),
             JobRequeued(created=17.0, job_id="j-1"))

    # The NEW attempt's outcome is the one terminal outcome.
    _publish(log, "q", "s",
             JobRunLeased(created=20.0, job_id="j-1", run_id="run-new",
                          executor="e1", node_id="n1", pool="default"))
    _publish(log, "q", "s",
             JobRunRunning(created=21.0, job_id="j-1", run_id="run-new"))
    _publish(log, "q", "s",
             JobRunSucceeded(created=30.0, job_id="j-1", run_id="run-new"),
             JobSucceeded(created=30.0, job_id="j-1"))
    sched.ingester.sync()
    job = sched.jobdb.get("j-1")
    assert job.state == JobState.SUCCEEDED
    assert job.latest_run.id == "run-new"
    assert [r.state for r in job.runs] == [
        RunState.FAILED,
        RunState.FAILED,
        RunState.SUCCEEDED,
    ]
    sched.jobdb.read_txn().assert_valid()


def test_stale_running_cannot_resurrect_expired_run():
    from armada_tpu.core.types import JobSpec
    from armada_tpu.events import (
        JobRequeued,
        JobRunErrors,
        JobRunLeased,
        JobRunRunning,
        SubmitJob,
    )
    from armada_tpu.jobdb import JobState

    _, log, sched = _mk_sched_stack()
    spec = JobSpec(id="j-2", queue="q", jobset="s",
                   requests={"cpu": "1", "memory": "1Gi"})
    _publish(log, "q", "s", SubmitJob(created=0.0, job=spec))
    _publish(log, "q", "s",
             JobRunLeased(created=1.0, job_id="j-2", run_id="r0",
                          executor="e0", node_id="n0", pool="default"))
    _publish(log, "q", "s",
             JobRunErrors(created=5.0, job_id="j-2", run_id="r0",
                          error="executor e0 timed out", retryable=True),
             JobRequeued(created=5.0, job_id="j-2"))
    _publish(log, "q", "s",
             JobRunRunning(created=6.0, job_id="j-2", run_id="r0"))
    sched.ingester.sync()
    job = sched.jobdb.get("j-2")
    assert job.state == JobState.QUEUED, "zombie run came back RUNNING"


# --------------------------------------------- fencing: scheduler + API


def _lease_one_job(log, sched, executor="e0", job_id="jf-1"):
    """Heartbeat + submit + cycle so `executor` holds one leased run."""
    from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
    from armada_tpu.events import SubmitJob
    from armada_tpu.services.scheduler import ExecutorHeartbeat
    from armada_tpu.services.submit import SubmitService

    submit = SubmitService(sched.config, log, scheduler=sched)
    if "q" not in sched.queues:
        submit.create_queue(QueueSpec("q"))
    nodes = [
        NodeSpec(id=f"{executor}-n0", name=f"{executor}-n0",
                 executor=executor, pool="default",
                 total_resources={"cpu": "8", "memory": "32Gi"})
    ]
    sched.report_executor(
        ExecutorHeartbeat(name=executor, pool="default", nodes=nodes,
                          last_seen=0.0)
    )
    submit.submit("q", "s", [
        JobSpec(id=job_id, queue="q", jobset="s",
                requests={"cpu": "1", "memory": "1Gi"})
    ], now=0.0)
    sched.cycle(now=1.0)
    return submit


def test_expiry_bumps_fence_and_stale_calls_are_rejected():
    from armada_tpu.jobdb import JobState
    from armada_tpu.services.grpc_api import ApiServer, FencedError

    _, log, sched = _mk_sched_stack(executor_timeout_s=30.0)
    _lease_one_job(log, sched)
    assert sched.jobdb.get("jf-1").state == JobState.LEASED
    assert sched.executor_fence("e0") == 0

    # No heartbeat past the timeout: runs expire, fence bumps.
    sched.cycle(now=40.0)
    assert sched.executor_fence("e0") == 1
    assert "e0" in sched.fence_breached
    assert sched.jobdb.get("jf-1").state == JobState.QUEUED

    api = ApiServer(None, sched, None, log)
    # Stale lease exchange: FAILED_PRECONDITION, never reaches the inner
    # handler (the heartbeat map must not resurrect).
    with pytest.raises(FencedError):
        api._executor_lease(
            {"executor": "e0", "fence_token": 0, "nodes": []}
        )
    assert "e0" not in sched.executors
    # Stale report: same rejection.
    with pytest.raises(FencedError):
        api._report_events(
            {"executor": "e0", "fence_token": 0, "events": []}
        )
    # Current-fence calls pass.
    reply = api._executor_lease(
        {"executor": "e0", "fence_token": 1, "nodes": []}
    )
    assert reply["fence_token"] == 1
    assert reply["lease_ttl_s"] == sched.config.executor_lease_ttl_s
    # Tokenless calls pass too (pre-fencing clients, in-process callers).
    api._report_events({"events": []})


def test_fence_survives_event_replay():
    """Fences are event-sourced: a fresh scheduler replaying the same log
    rebuilds the same fence map (restart/failover safety) — and a breach
    CLEARED by an ExecutorSync stays cleared across the replay (no
    standing 'awaiting post-fence sync' false alarm)."""
    from armada_tpu.services.scheduler import SchedulerService

    config, log, sched = _mk_sched_stack(executor_timeout_s=30.0)
    _lease_one_job(log, sched)
    sched.cycle(now=40.0)
    assert sched.executor_fence("e0") == 1
    assert "e0" in sched.fence_breached

    standby = SchedulerService(config, log, backend="oracle")
    assert standby.executor_fence("e0") == 1
    assert "e0" in standby.fence_breached  # not yet synced: alarm stands

    sched.note_executor_synced("e0")  # the ExecutorSync's breach clear
    assert "e0" not in sched.fence_breached
    restarted = SchedulerService(config, log, backend="oracle")
    assert restarted.executor_fence("e0") == 1
    assert "e0" not in restarted.fence_breached, (
        "log replay resurrected a healed fence breach"
    )


def test_executor_sync_classifies_zombie_duplicate_kept_orphaned():
    from armada_tpu.events import JobRunLeased, JobRunPending
    from armada_tpu.jobdb import JobState
    from armada_tpu.services.grpc_api import ApiServer

    _, log, sched = _mk_sched_stack(executor_timeout_s=30.0)
    submit = _lease_one_job(log, sched, job_id="jz-1")

    # jz-1: expire (requeue) -> its old run is a ZOMBIE on the agent.
    old_run = sched.jobdb.get("jz-1").latest_run.id
    sched.cycle(now=40.0)
    assert sched.jobdb.get("jz-1").state == JobState.QUEUED

    # jd-1: expire, then re-lease to another executor -> the agent's old
    # run is a DUPLICATE.
    from armada_tpu.core.types import JobSpec

    submit.submit("q", "s", [
        JobSpec(id="jd-1", queue="q", jobset="s",
                requests={"cpu": "1", "memory": "1Gi"})
    ], now=41.0)
    _publish(log, "q", "s",
             JobRunLeased(created=42.0, job_id="jd-1", run_id="dup-old",
                          executor="e0", node_id="e0-n0", pool="default"))
    _publish(log, "q", "s",
             JobRunLeased(created=43.0, job_id="jd-1", run_id="dup-new",
                          executor="e1", node_id="e1-n0", pool="default"))
    # jk-1: live PENDING run on e0 the agent still holds -> KEPT; and
    # jo-1: live PENDING run on e0 the agent LOST -> ORPHANED.
    for jid, rid in (("jk-1", "keep-r"), ("jo-1", "orph-r")):
        submit.submit("q", "s", [
            JobSpec(id=jid, queue="q", jobset="s",
                    requests={"cpu": "1", "memory": "1Gi"})
        ], now=44.0)
        _publish(log, "q", "s",
                 JobRunLeased(created=45.0, job_id=jid, run_id=rid,
                              executor="e0", node_id="e0-n0",
                              pool="default"))
        _publish(log, "q", "s",
                 JobRunPending(created=46.0, job_id=jid, run_id=rid))
    sched.ingester.sync()

    api = ApiServer(None, sched, None, log)
    reply = api._executor_sync({
        "executor": "e0",
        "runs": [
            {"run_id": old_run, "job_id": "jz-1", "phase": "running"},
            {"run_id": "dup-old", "job_id": "jd-1", "phase": "running"},
            {"run_id": "keep-r", "job_id": "jk-1", "phase": "pending"},
            {"run_id": "totally-unknown", "job_id": "", "phase": "running"},
        ],
    })
    killed = {k["run_id"]: k["reason"] for k in reply["kill_runs"]}
    assert old_run in killed and "requeued" in killed[old_run]
    assert "dup-old" in killed and "superseded" in killed["dup-old"]
    assert "totally-unknown" in killed
    assert reply["kept_run_ids"] == ["keep-r"]
    assert reply["orphaned_run_ids"] == ["orph-r"]
    assert reply["fence_token"] == sched.executor_fence("e0")
    assert "e0" not in sched.fence_breached  # sync clears the breach

    # The orphan's failure event landed: the job requeues next cycle.
    # (Keep both executors heartbeating so the expiry sweep stays out of
    # the way and only the failed-run path acts.)
    from armada_tpu.services.scheduler import ExecutorHeartbeat

    for name in ("e0", "e1"):
        sched.report_executor(
            ExecutorHeartbeat(name=name, pool="default", nodes=[],
                              last_seen=49.0)
        )
    sched.ingester.sync()
    sched.cycle(now=50.0)
    from armada_tpu.jobdb import JobState as JS

    assert sched.jobdb.get("jo-1").state == JS.QUEUED
    # The kept job is untouched.
    assert sched.jobdb.get("jk-1").state == JS.PENDING


def test_fenced_executor_checker_advisory():
    from armada_tpu.services.health import FencedExecutorChecker

    _, log, sched = _mk_sched_stack(executor_timeout_s=30.0)
    checker = FencedExecutorChecker(sched)
    ok, detail = checker.check()
    assert ok and "no fenced executors" in detail
    _lease_one_job(log, sched)
    sched.cycle(now=40.0)
    ok, detail = checker.check()
    assert ok  # advisory: never fails liveness
    assert "e0" in detail and "post-fence sync" in detail


# -------------------------------------------- agent lease TTL + resync


class StubClient:
    """In-process client speaking the agent's `_call` surface."""

    def __init__(self):
        self.calls = []
        self.lease_reply = {
            "leases": [],
            "cancel_runs": [],
            "active_runs": [],
            "store_healthy": True,
            "fence_token": 0,
            "lease_ttl_s": 10.0,
        }
        self.sync_reply = {
            "fence_token": 0,
            "kill_runs": [],
            "kept_run_ids": [],
            "orphaned_run_ids": [],
        }
        self.fail_lease_with = None

    def _call(self, method, req):
        self.calls.append((method, req))
        if method == "ExecutorLease":
            if self.fail_lease_with is not None:
                exc, self.fail_lease_with = self.fail_lease_with, None
                raise exc
            return dict(self.lease_reply)
        if method == "ExecutorSync":
            return dict(self.sync_reply)
        return {}


def _mk_agent(client, ttl=None):
    from armada_tpu.services.executor_agent import ExecutorAgent, _PodRuntime

    return ExecutorAgent(
        client,
        "e0",
        nodes=[{"id": "e0-n0",
                "total_resources": {"cpu": "8", "memory": "32Gi"}}],
        runtime=_PodRuntime(runtime_s=1000.0),
        lease_ttl_s=ttl,
    )


def _lease(run_id="r1", job_id="j1"):
    from armada_tpu.utils.compress import compress_obj

    return {
        "run_id": run_id,
        "job_id": job_id,
        "queue": "q",
        "jobset": "s",
        "node_id": "e0-n0",
        "spec": compress_obj({"requests": {"cpu": "1"}}),
    }


def test_agent_adopts_server_lease_ttl_and_defers_work_after_expiry():
    client = StubClient()
    agent = _mk_agent(client, ttl=None)
    client.lease_reply["leases"] = [_lease()]
    agent.tick(now=0.0)
    assert agent.lease_ttl_s == 10.0  # adopted from the reply
    assert "r1" in agent.runtime.pods

    # TTL expires with no successful exchange between 0 and 20: the next
    # exchange defers NEW leases and runs the anti-entropy sync first.
    client.lease_reply["leases"] = [_lease("r2", "j2")]
    assert agent.lease_expired(20.0)
    agent.tick(now=20.0)
    methods = [m for m, _ in client.calls]
    assert "ExecutorSync" in methods
    assert "r2" not in agent.runtime.pods, "expired lease accepted new work"
    assert not agent.orphan_candidates  # cleared by the sync
    # Next clean tick accepts it (unacked leases re-send).
    agent.tick(now=21.0)
    assert "r2" in agent.runtime.pods


def test_agent_recovers_from_fence_rejection_with_sync_and_retry():
    from armada_tpu.services.grpc_api import FencedError

    client = StubClient()
    agent = _mk_agent(client, ttl=0)  # TTL disabled: isolate the fence path
    client.lease_reply["leases"] = [_lease()]
    agent.tick(now=0.0)
    assert "r1" in agent.runtime.pods

    # Server fenced us: next lease is rejected; the sync kills the zombie
    # and hands over the new token; the retried exchange carries it.
    client.fail_lease_with = FencedError("stale fence")
    client.sync_reply = {
        "fence_token": 3,
        "kill_runs": [{"run_id": "r1", "job_id": "j1", "reason": "requeued"}],
        "kept_run_ids": [],
        "orphaned_run_ids": [],
    }
    agent.tick(now=5.0)
    assert agent.fence_token == 3
    assert "r1" not in agent.runtime.pods, "zombie pod survived the sync"
    lease_calls = [r for m, r in client.calls if m == "ExecutorLease"]
    assert lease_calls[-1]["fence_token"] == 3
    assert agent.syncs == 1


def test_agent_marks_orphan_candidates_when_partitioned():
    client = StubClient()
    agent = _mk_agent(client, ttl=10.0)
    client.lease_reply["leases"] = [_lease()]
    agent.tick(now=0.0)
    agent.mark_orphan_candidates()  # what run() does once the TTL lapses
    assert agent.orphan_candidates == {"r1"}
    # Pods keep running — the server may still own them.
    assert "r1" in agent.runtime.pods


# ------------------------------------- FileLeaseLeader interleaved race


from armada_tpu.services.leader import FileLeaseLeader


class RacingLeader(FileLeaseLeader):
    """FileLeaseLeader whose FIRST read returns a pre-captured stale
    snapshot — the deterministic image of two candidates reading the
    expired lease before either writes."""

    def arm(self):
        self._stale_view = FileLeaseLeader._read(self)

    def _read(self):
        view = getattr(self, "_stale_view", None)
        if view is not None:
            self._stale_view = None
            return view
        return FileLeaseLeader._read(self)


def test_file_lease_interleaved_takeover_exactly_one_validates(tmp_path):
    from armada_tpu.services.leader import LeaderToken

    path = str(tmp_path / "lease")
    stale_ts = time.time() - 1000.0
    with open(path, "w") as f:
        f.write(f"dead-holder\n{stale_ts}\n5\n")

    b = RacingLeader(path, lease_duration=15.0, identity="cand-b")
    c = RacingLeader(path, lease_duration=15.0, identity="cand-c")
    # Both candidates observe the SAME expired lease (fence 5) ...
    b.arm()
    c.arm()
    # ... then race the takeover: B writes fence 6 and confirms; C —
    # still acting on its stale read — overwrites with fence 6 too. The
    # later writer's file survives.
    assert b.try_acquire_or_renew() is True
    assert c.try_acquire_or_renew() is True
    token_b = LeaderToken(leader=True, id=f"{b.identity}:{b._epoch}")
    token_c = LeaderToken(leader=True, id=f"{c.identity}:{c._epoch}")

    validations = [b.validate(token_b), c.validate(token_c)]
    assert validations.count(True) == 1, (
        "interleaved takeover must leave exactly one valid leader"
    )
    assert validations == [False, True]  # the surviving file is C's
    # And B cannot renew into C's fresh lease.
    assert b.try_acquire_or_renew() is False

    # Direct fence-mismatch branch: holder matches but the file's fence
    # moved on (another takeover happened behind our back).
    with open(path, "w") as f:
        f.write(f"cand-c\n{time.time()}\n99\n")
    assert c.validate(token_c) is False


# ------------------------------------------- partition soak (tier-1 cut)


@pytest.mark.chaos
def test_partition_soak_subset_deterministic():
    """Seeded partition plans through the simulator: anti-entropy fires,
    fences bump, every job terminates exactly once, and the final jobdb
    digest is bit-identical per seed (seeds chosen to exercise both the
    duplicate and zombie resolution paths; tools/chaos_soak.py runs the
    full 20)."""
    from tools.chaos_soak import run_plan

    for seed in (3, 7):
        first = run_plan(seed, "oracle", 24)
        second = run_plan(seed, "oracle", 24)
        assert first["digest"] == second["digest"]
        assert first["finished"] == first["total"]
        assert first["fences"], "no executor was fenced under partition"
        assert first["anti_entropy"], "anti-entropy never resolved a run"
        assert second["anti_entropy"] == first["anti_entropy"]


# --------------------------------------- real sockets, end to end


@pytest.mark.chaos
def test_real_socket_partition_fencing_and_heal():
    """The acceptance scenario on REAL sockets: an executor agent speaks
    gRPC to a live ControlPlane through a ChaosProxy; the wire is
    severed until the scheduler expires + fences the executor; after the
    heal, the executor's stale-fenced lease AND report calls are
    rejected with FAILED_PRECONDITION; the agent's anti-entropy sync
    tears down the zombie pod and rejoins, and the job resolves to
    exactly one terminal outcome."""
    import grpc

    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.jobdb import JobState
    from armada_tpu.services.executor_agent import ExecutorAgent, _PodRuntime
    from armada_tpu.services.grpc_api import ApiClient
    from armada_tpu.services.server import ControlPlane

    config = SchedulingConfig(
        priority_classes={
            "default": PriorityClass("default", 1000, preemptible=True),
        },
        default_priority_class="default",
        executor_timeout_s=1.0,
        executor_lease_ttl_s=30.0,  # fence path, not the TTL path
        enable_assertions=True,
    )
    plane = ControlPlane(config, cycle_period=0.05).start()
    clock = VirtualClock()
    plan = FaultPlan(
        [FaultSpec("network_partition", "agent-a", 100.0, 100.0)]
    )
    proxy = ChaosProxy(
        "agent-a", "127.0.0.1", plane.grpc_port, plan, clock=clock
    )
    proxy.start()
    try:
        client = ApiClient(proxy.address)
        agent = ExecutorAgent(
            client,
            "agent-a",
            # Two nodes: the post-partition retry carries anti-affinity
            # against the failed attempt's node, so the re-lease needs a
            # second one to land on.
            nodes=[
                {"id": f"agent-a-n{i}",
                 "total_resources": {"cpu": "8", "memory": "32Gi"}}
                for i in range(2)
            ],
            runtime=_PodRuntime(runtime_s=1.0),
        )
        client.create_queue("q")
        client.submit_jobs("q", "s", [
            {"id": "net-1", "requests": {"cpu": "1", "memory": "1Gi"}}
        ])

        deadline = time.time() + 10.0
        while time.time() < deadline and "net-1" not in {
            p["job_id"] for p in agent.runtime.pods.values()
        }:
            agent.tick()
            time.sleep(0.05)
        assert any(
            p["job_id"] == "net-1" for p in agent.runtime.pods.values()
        ), "agent never received the lease"
        old_fence = agent.fence_token

        # ---- sever the wire mid-lease ----
        clock.now = 150.0
        with pytest.raises(Exception):
            for _ in range(20):
                agent.tick()
                time.sleep(0.05)

        # The scheduler expires the silent executor and bumps its fence.
        deadline = time.time() + 10.0
        while (
            time.time() < deadline
            and plane.scheduler.executor_fence("agent-a") == 0
        ):
            time.sleep(0.05)
        assert plane.scheduler.executor_fence("agent-a") == 1
        assert plane.scheduler.jobdb.get("net-1").state == JobState.QUEUED

        # ---- heal ----
        clock.now = 250.0

        # THE acceptance assertion: stale-fenced lease and report calls
        # are rejected FAILED_PRECONDITION over the real socket. The
        # channel may still be reconnecting for a moment after the heal
        # (UNAVAILABLE) — keep calling until the listener answers.
        def assert_fenced(method, req):
            deadline = time.time() + 10.0
            while True:
                with pytest.raises(grpc.RpcError) as exc_info:
                    client._call(method, req)
                code = exc_info.value.code()
                if code == grpc.StatusCode.FAILED_PRECONDITION:
                    return
                assert code == grpc.StatusCode.UNAVAILABLE, code
                assert time.time() < deadline, (
                    f"{method} never reached the healed server"
                )
                time.sleep(0.1)

        assert_fenced("ExecutorLease", {
            "executor": "agent-a",
            "pool": "default",
            "nodes": [],
            "acked_run_ids": [],
            "fence_token": old_fence,
        })
        assert_fenced("ReportEvents", {
            "executor": "agent-a", "fence_token": old_fence, "events": [],
        })

        # The agent recovers on its own: fenced tick -> sync -> retry.
        deadline = time.time() + 10.0
        while agent.syncs == 0 and time.time() < deadline:
            try:
                agent.tick()
            except grpc.RpcError:
                time.sleep(0.1)
        assert agent.fence_token == 1
        assert agent.syncs >= 1
        assert not any(
            p["job_id"] == "net-1" for p in agent.runtime.pods.values()
        ), "zombie pod survived the anti-entropy sync"

        # And the job completes exactly once through the healed wire.
        deadline = time.time() + 15.0
        while (
            time.time() < deadline
            and plane.scheduler.jobdb.get("net-1").state
            != JobState.SUCCEEDED
        ):
            agent.tick()
            time.sleep(0.05)
        job = plane.scheduler.jobdb.get("net-1")
        assert job.state == JobState.SUCCEEDED
        from armada_tpu.jobdb.jobdb import RunState

        terminal_ok = [r for r in job.runs if r.state == RunState.SUCCEEDED]
        assert len(terminal_ok) == 1, "job succeeded on more than one run"
        plane.scheduler.jobdb.read_txn().assert_valid()
    finally:
        proxy.stop()
        plane.stop()
