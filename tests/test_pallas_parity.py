"""Pallas solve-kernel parity: interpret-mode kernels vs the lax path.

The armada_tpu/ops/pallas_kernels.py contract asserted on CPU:

- `fill_take` (radix-threshold top-B selection) is index-for-index equal
  to the stable single-key `jnp.lexsort` it replaces, masked sentinel
  tail included.
- `winner_reduce` (tree winner exchange) equals the host lexicographic
  argmin, first-index tie-break included.
- Full mixed-fleet rounds solve bit-exactly on every kernel path, under
  LOCAL, the hot-window driver, and the 2x4 two-level HierarchicalDist —
  and the hierarchical pallas run books its fabric cost model
  (pallas call/block/VMEM counts, winner-exchange steps + DMA bytes)
  into CollectiveStats so the ICI ring's cost is asserted where the
  hardware isn't.

Every pallas kernel here runs under interpret=True (no TPU attached in
tier-1); the native path is covered by tools/pallas_probe.py on real
hardware (docs/known_gaps.yaml: pallas-ici-native).
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from armada_tpu.ops import pallas_kernels as pk

N_NODES, N_JOBS = 48, 192

DECISION_KEYS = (
    "assigned_node", "scheduled_priority", "scheduled_mask",
    "preempted_mask", "fair_share", "demand_capped_fair_share",
    "uncapped_fair_share", "num_loops", "spot_price",
)


def _decisions(out):
    return {
        k: np.asarray(v) for k, v in out.items()
        if k not in ("profile", "truncated")
    }


def _assert_equal(name, got, want):
    for k, v in want.items():
        assert np.array_equal(np.asarray(got[k]), v, equal_nan=True), (
            f"{name}: {k} diverged"
        )


@pytest.fixture(scope="module")
def fleet():
    """(name, padded DeviceRound, lax decisions) per mixed-fleet round —
    the lax baseline is solved once and shared by every parity case."""
    from armada_tpu.parallel.scenarios import mixed_fleet_rounds
    from armada_tpu.solver.kernel import solve_round
    from armada_tpu.solver.kernel_prep import (
        pad_device_round,
        prep_device_round,
    )

    rounds = []
    for name, snap in mixed_fleet_rounds(N_NODES, N_JOBS):
        dev = pad_device_round(prep_device_round(snap))
        assert dev.kernel_path == "lax"
        rounds.append((name, dev, _decisions(solve_round(dev))))
    return rounds


# ---------------------------------------------------------------------------
# Primitive parity
# ---------------------------------------------------------------------------


def test_fill_take_matches_lexsort():
    """Radix-threshold selection == stable lexsort top-B, including the
    masked-sentinel tail when fewer than B candidates are valid."""
    rng = np.random.default_rng(3)
    for n, want, span in ((512, 64, 2**40), (1024, 256, 2**20), (64, 64, 8)):
        keys = rng.integers(0, span, size=n, dtype=np.int64)
        # Mask a random suffix-weighted subset to the int64 sentinel the
        # fill path uses for infeasible slots (duplicates included: span
        # 8 forces heavy key collisions through the stable-order path).
        dead = rng.random(n) < 0.4
        keys = np.where(dead, pk._I64_SENTINEL, keys)
        jk = jnp.asarray(keys)
        take, taken = pk.fill_take(jk, want, nbits=63)
        ref = jnp.lexsort((jk,))[:want]
        np.testing.assert_array_equal(np.asarray(take), np.asarray(ref))
        np.testing.assert_array_equal(
            np.asarray(taken), np.asarray(keys)[np.asarray(ref)]
        )


def test_winner_reduce_matches_host_argmin():
    """Tree winner exchange == host lexicographic argmin. The production
    contract makes the minimum unique: the LAST key is the globally
    unique node rank, so the reduction's association order can never
    matter — mirrored here with duplicate-heavy leading keys and a
    permutation as the final key."""
    rng = np.random.default_rng(5)
    for p, span in ((8, 1000), (16, 3), (5, 2), (1, 10)):
        keys = [jnp.asarray(rng.integers(0, span, size=p, dtype=np.int32))
                for _ in range(2)]
        keys.append(jnp.asarray(rng.permutation(p).astype(np.int32)))
        found = jnp.asarray(rng.random(p) < 0.6)
        gids = jnp.arange(p, dtype=jnp.int32) + 7
        wgid, wfound = pk.winner_reduce(keys, found, gids)
        rows = np.stack([np.asarray(k) for k in keys], axis=1)
        alive = np.flatnonzero(np.asarray(found))
        if alive.size == 0:
            assert not bool(wfound)
            continue
        # np.lexsort treats the LAST tuple entry as primary.
        order = np.lexsort(tuple(rows[alive].T[::-1]))
        assert bool(wfound)
        assert int(wgid) == int(np.asarray(gids)[alive[order[0]]])


def test_winner_reduce_none_found():
    keys = [jnp.zeros(4, jnp.int32)]
    wgid, wfound = pk.winner_reduce(
        keys, jnp.zeros(4, bool), jnp.arange(4, dtype=jnp.int32)
    )
    assert not bool(wfound)


# ---------------------------------------------------------------------------
# Path selection plumbing
# ---------------------------------------------------------------------------


def test_resolve_kernel_path(monkeypatch):
    monkeypatch.delenv(pk.PATH_ENV, raising=False)
    assert pk.resolve_kernel_path("blocked") == "blocked"
    # Unknown config values fall back instead of raising.
    assert pk.resolve_kernel_path("tpuv9") == "lax"
    # native demotes to pallas interpret off-hardware (no TPU in tier-1).
    assert pk.resolve_kernel_path("native") == "pallas"
    # Env is the A/B lever and beats config.
    monkeypatch.setenv(pk.PATH_ENV, "pallas")
    assert pk.resolve_kernel_path("lax") == "pallas"
    monkeypatch.setenv(pk.PATH_ENV, "bogus")
    assert pk.resolve_kernel_path("blocked") == "blocked"


def test_config_rejects_unknown_kernel_path():
    from armada_tpu.core.config import SchedulingConfig, validate_config

    with pytest.raises(ValueError, match="solveKernelPath"):
        validate_config(SchedulingConfig(solve_kernel_path="fused9000"))


def test_failover_ladder_gets_kernel_rung():
    """A configured non-lax path is its own rung above plain LOCAL, so a
    poisoned pallas executable demotes to the lax graph like any other
    rung failure; a lax config keeps the historical ladder."""
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.solver.failover import build_ladder

    labels = [r.label for r in build_ladder(
        "kernel", None, SchedulingConfig(solve_kernel_path="pallas")
    )]
    assert labels == ["local:pallas", "LOCAL", "hotwindow:64", "oracle"]
    labels = [r.label for r in build_ladder(
        "kernel", None, SchedulingConfig()
    )]
    assert labels == ["LOCAL", "hotwindow:64", "oracle"]


def test_trace_codec_defaults_kernel_path():
    """Pre-pallas .atrace bundles decode with kernel_path='lax' (every
    recorded round ran the lax graph)."""
    from armada_tpu.trace.codec import (
        decode_device_round,
        encode_device_round,
    )
    from armada_tpu.parallel.scenarios import home_away_round
    from armada_tpu.solver.kernel_prep import (
        pad_device_round,
        prep_device_round,
    )

    dev = pad_device_round(prep_device_round(home_away_round(16, 32)))
    doc = encode_device_round(dev)
    doc.pop("kernel_path")
    assert decode_device_round(doc).kernel_path == "lax"


# ---------------------------------------------------------------------------
# Round parity: LOCAL / hotwindow / hierarchical mesh
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("path", ["blocked", "pallas"])
def test_local_round_parity(fleet, path):
    """Every mixed-fleet round solves bit-exactly on the blocked and
    pallas-interpret paths under the LOCAL single-device driver."""
    from armada_tpu.solver.kernel import solve_round

    for name, dev, want in fleet:
        got = solve_round(dataclasses.replace(dev, kernel_path=path))
        _assert_equal(f"{name}/{path}", _decisions(got), want)


def test_hotwindow_round_parity(fleet):
    """The hot-window compacted driver takes the same kernel-path seam:
    pallas-interpret under a forced small window == the lax path under
    the same window."""
    from armada_tpu.solver.kernel import solve_round

    name, dev, _ = fleet[0]
    want = _decisions(solve_round(dev, window=4, window_min_slots=0))
    got = solve_round(
        dataclasses.replace(dev, kernel_path="pallas"),
        window=4, window_min_slots=0,
    )
    _assert_equal(f"{name}/hotwindow:4", _decisions(got), want)


def test_hierarchical_2x4_parity_and_fabric_stats(fleet):
    """The 2x4 two-level HierarchicalDist with the pallas winner
    exchange solves bit-exactly vs the single-device lax baseline, and
    the run books the fabric cost model: pallas call/block/VMEM counts
    and the winner exchange's step count + DMA bytes, alongside the
    existing per-level ici/dcn gather accounting."""
    from armada_tpu.parallel.mesh import pad_nodes
    from armada_tpu.parallel.multihost import resolve_solver

    run = resolve_solver("2x4", kernel_path="pallas")
    per_round = {}
    for name, dev, want in fleet:
        got = run(pad_nodes(
            dataclasses.replace(dev, kernel_path="pallas"), run.n_shards
        ))
        _assert_equal(f"{name}/2x4:pallas", _decisions(got), want)
        # last_stats describes the program THIS round executed (market
        # compiles a different program than home_away).
        per_round[name] = (run.last_stats or run.stats).as_dict()
    for name, stats in per_round.items():
        assert stats["selects"] > 0, name
        # Winner exchange: log2(pow2(hosts)) tree steps per select, each
        # moving (1 + n_keys + 1) int32 lanes of row payload.
        assert stats["pallas_calls"] > 0, name
        assert stats["ring_steps"] > 0, name
        assert stats["ring_bytes"] > 0, name
        # The pallas winner exchange replaces the host-level
        # all_gather+argmin sites; chip-level ICI gathers still book.
        assert stats["ici_bytes"] > 0, name
    # Fused scoring blocks ran as pallas calls with VMEM-resident blocks
    # wherever the round fills (market rounds run with batch_window=0 and
    # never enter the fill loop, so only home_away books score blocks).
    stats = per_round["home_away"]
    assert stats["pallas_blocks"] > 0
    assert stats["pallas_vmem_bytes"] > 0


# ---------------------------------------------------------------------------
# Round readback trim
# ---------------------------------------------------------------------------


def test_readback_trim_bit_exact(fleet):
    """solve_round(readback_rows=J) downloads only the decision prefix
    but re-expands to the padded shape with the exact pad fills, so
    every consumer sees bit-identical arrays to the full readback."""
    from armada_tpu.solver.kernel import solve_round

    name, dev, want = fleet[0]
    rows = int(np.flatnonzero(
        np.asarray(dev.job_queue) >= 0
    ).size) or dev.job_queue.shape[0]
    got = solve_round(dev, readback_rows=min(rows, 7))
    out = _decisions(got)
    for k in want:
        assert out[k].shape == want[k].shape, k
    _assert_equal(f"{name}/readback", out, want)
