"""Round observatory (armada_tpu/observe): transfer-ledger accounting,
compile/retrace telemetry, and the structured-log trace-id join.

The ledger asserts EXACTLY on a tiny round with known array shapes —
expected bytes are recomputed independently in the test by summing the
host arrays' nbytes — under the fused LOCAL kernel, the hot-window
compacted driver (donated buffers must be booked), and the "2x4"
two-level mesh placement path. Warm cycles must report ZERO
traces/compiles after the first solve (the steady state the
device-resident-round refactor will be judged against), and trace
replay must classify a compile on an already-replayed round shape as a
`retrace` divergence.
"""

import dataclasses
import json
import logging

import numpy as np
import pytest

import jax

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, RunningJob
from armada_tpu.observe import (
    TELEMETRY,
    TransferLedger,
    note_down,
    note_up,
    round_ledger,
    tree_transfer_size,
)
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import (
    DeviceRound,
    pad_device_round,
    prep_device_round,
)


def _tiny_round(n_jobs=120, n_running=12, bw=4):
    """A small mixed round (hog queue over fair share, a few running
    jobs) big enough that window=4 compaction engages at
    window_min_slots=0."""
    cfg = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
        batch_fill_window=bw,
    )
    nodes = [
        NodeSpec(
            id=f"n{i:03d}", pool="default",
            total_resources={"cpu": "16", "memory": "64Gi"},
        )
        for i in range(10)
    ]
    queues = [QueueSpec(f"q{i}", 1.0) for i in range(3)]
    rng = np.random.default_rng(7)
    queued = [
        JobSpec(
            id=f"j{i:04d}", queue=f"q{i % 3}", priority_class="low",
            requests={"cpu": str(int(rng.choice([1, 2])))},
            submitted_ts=float(i),
        )
        for i in range(n_jobs)
    ]
    running = [
        RunningJob(
            job=JobSpec(
                id=f"r{i:04d}", queue="q0", priority_class="low",
                requests={"cpu": "2"}, submitted_ts=float(-n_running + i),
            ),
            node_id=f"n{i % 10:03d}",
            scheduled_at_priority=1000,
        )
        for i in range(n_running)
    ]
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    return pad_device_round(prep_device_round(snap))


def _host_bytes(dev) -> tuple[int, int]:
    """Independent recomputation of the upload the ledger must book:
    (bytes, arrays) over the DeviceRound's np.ndarray fields."""
    nbytes = arrays = 0
    for f in dataclasses.fields(DeviceRound):
        v = getattr(dev, f.name)
        if isinstance(v, np.ndarray):
            nbytes += v.nbytes
            arrays += 1
    return nbytes, arrays


# ---------------------------------------------------------------------------
# Ledger unit behavior


def test_ledger_nesting_and_host_only_filter():
    """Notes book into EVERY active ledger on the stack; note_up counts
    only host (np.ndarray) leaves — an already-on-device array is not a
    transfer."""
    host = np.zeros(10, np.int64)  # 80 bytes
    on_device = jax.device_put(np.zeros(4, np.int32))
    with round_ledger() as outer:
        with round_ledger() as inner:
            note_up({"h": host, "d": on_device})
            note_down([np.zeros(3, np.float64)])  # 24 bytes
        # Outer keeps booking after the inner scope closed.
        note_up(host)
    assert inner.bytes_up == 80 and inner.arrays_up == 1
    assert inner.bytes_down == 24 and inner.arrays_down == 1
    assert outer.bytes_up == 160 and outer.arrays_up == 2
    # Outside any ledger the notes are no-ops, not errors.
    note_up(host)
    assert outer.bytes_up == 160


def test_tree_transfer_size_matches_numpy_nbytes():
    dev = _tiny_round(n_jobs=24, n_running=0)
    expected_bytes, expected_arrays = _host_bytes(dev)
    got_bytes, got_arrays = tree_transfer_size(dev, host_only=True)
    assert (got_bytes, got_arrays) == (expected_bytes, expected_arrays)


# ---------------------------------------------------------------------------
# Exact accounting through the solvers


def test_transfer_ledger_exact_local():
    """Fused LOCAL solve: bytes_up is exactly the host DeviceRound,
    bytes_down exactly the materialized output dict."""
    dev = _tiny_round()
    expected_up, expected_arrays = _host_bytes(dev)
    with round_ledger() as led:
        out = solve_round(dev)
    assert led.bytes_up == expected_up
    assert led.arrays_up == expected_arrays
    expected_down = sum(
        v.nbytes for v in out.values() if isinstance(v, np.ndarray)
    )
    assert led.bytes_down == expected_down
    assert led.arrays_down == sum(
        1 for v in out.values() if isinstance(v, np.ndarray)
    )
    # The fused path donates nothing — the split must say so.
    assert led.donated_buffers == 0 and led.donated_bytes == 0


def test_transfer_ledger_exact_hotwindow_with_donations():
    """Host-driven compacted solve: same exact bytes_up, and the chunk
    carries + scatter-back donations are booked on the donated side
    (with profile['transfer'] carrying the solve's own complete view)."""
    dev = _tiny_round()
    expected_up, expected_arrays = _host_bytes(dev)
    with round_ledger() as led:
        out = solve_round(dev, window=4, window_min_slots=0)
    assert out["profile"]["compacted"] is True
    assert led.bytes_up == expected_up
    assert led.arrays_up == expected_arrays
    # Compaction donates the pass-1 carries and the scatter-back target.
    assert led.donated_buffers > 0
    assert led.donated_bytes > 0
    transfer = out["profile"]["transfer"]
    assert transfer["bytes_up"] == expected_up
    assert transfer["donated_buffers"] == led.donated_buffers
    assert transfer["bytes_down"] == led.bytes_down > 0


@pytest.mark.slow
def test_transfer_ledger_exact_mesh_2x4():
    """Two-level mesh placement: place_round books exactly the padded
    host tree's arrays as uploads. Slow-marked like the other 2x4
    variants (the sharded compile dominates): LOCAL + hotwindow above
    keep the ledger contract tier-1."""
    from armada_tpu.parallel.mesh import pad_nodes
    from armada_tpu.parallel.multihost import resolve_solver

    run = resolve_solver("2x4")
    dev = pad_nodes(_tiny_round(), run.n_shards)
    expected_up, expected_arrays = _host_bytes(dev)
    with round_ledger() as led:
        out = run(dev)
    jax.block_until_ready(out)
    assert led.bytes_up == expected_up
    assert led.arrays_up == expected_arrays


# ---------------------------------------------------------------------------
# Compile telemetry


def test_warm_cycle_zero_retraces_after_first_solve():
    """The acceptance invariant for warm cycles: after the first solve
    of a padded shape, re-solving the same shape traces and compiles
    NOTHING — under both the fused and the compacted drivers."""
    assert TELEMETRY.install()
    dev = _tiny_round()
    for kwargs in ({}, {"window": 4, "window_min_slots": 0}):
        solve_round(dev, **kwargs)  # warm (possibly compiles)
        snap0 = TELEMETRY.snapshot()
        solve_round(dev, **kwargs)
        delta = TELEMETRY.delta_since(snap0)
        assert delta["traces"] == 0, (kwargs, delta)
        assert delta["compiles"] == 0, (kwargs, delta)
        assert delta["compile_seconds"] == 0.0, (kwargs, delta)


def test_replay_flags_warm_shape_retrace_as_divergence(tmp_path):
    """A solver that retraces on an already-replayed round signature
    must classify as a `retrace` divergence (the silent-warm-recompile
    failure mode); the unperturbed replay of the same bundle is clean."""
    from armada_tpu.trace import TraceRecorder, load_trace, replay_trace
    from armada_tpu.trace import replayer as replayer_mod

    dev = _tiny_round(n_jobs=24, n_running=0)
    out = solve_round(dev)
    path = str(tmp_path / "warm.atrace")
    with TraceRecorder(path, source="test") as rec:
        for i in range(2):  # two rounds, identical shape signature
            rec.record_round(
                pool="default", dev=dev,
                decisions={k: np.asarray(v) for k, v in out.items()
                           if k != "profile"},
                num_jobs=24, num_queues=3,
            )
    trace = load_trace(path)
    clean = replay_trace(trace, solvers=("LOCAL",))
    assert clean["ok"], clean
    assert "retrace" not in clean["divergences"]

    # A candidate whose jit caches are cleared per solve retraces every
    # round — round 2 hits an already-seen signature and must trip.
    orig = replayer_mod.replay_solver

    def cold_solver(spec, header=None):
        label, solve = orig(spec, header)

        def cold(dev_):
            jax.clear_caches()
            return solve(dev_)

        return label, cold

    replayer_mod.replay_solver = cold_solver
    try:
        report = replay_trace(trace, solvers=("LOCAL",))
    finally:
        replayer_mod.replay_solver = orig
    assert report["divergences"].get("retrace", 0) >= 1, report


# ---------------------------------------------------------------------------
# Structured logging joins the trace


def test_scheduler_cycle_log_line_carries_round_trace_id():
    """A scheduling-round log record rendered by the JSON formatter
    carries the SAME trace id as the round span open around it — log
    lines join the job-journey correlation."""
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService
    from armada_tpu.utils.logging import _JsonFormatter
    from armada_tpu.utils.tracing import Tracer

    log = InMemoryEventLog()
    sched = SchedulerService(SchedulingConfig(), log)
    tracer = Tracer()
    sched.attach_tracer(tracer)
    submit = SubmitService(SchedulingConfig(), log, scheduler=sched)
    submit.create_queue(QueueSpec("q"))
    ex = FakeExecutor("ex", log, sched,
                      nodes=make_nodes("ex", count=2, cpu="8"),
                      runtime_for=lambda jid: 60.0)
    submit.submit("q", "s", [
        JobSpec(id="obs-1", queue="q",
                requests={"cpu": "1", "memory": "1Gi"}, submitted_ts=0.0),
    ], now=0.0)

    records = []
    handler = logging.Handler()
    handler.emit = lambda record: records.append(
        _JsonFormatter().format(record)
    )
    logger = logging.getLogger("armada_tpu.scheduler")
    logger.addHandler(handler)
    try:
        ex.tick(0.0)
        sched.cycle(now=0.0)
    finally:
        logger.removeHandler(handler)

    round_spans = [s for s in tracer.finished if s.name == "scheduler.round"]
    assert round_spans, "no round span recorded"
    docs = [json.loads(r) for r in records]
    round_lines = [
        d for d in docs if "scheduling round complete" in d.get("msg", "")
    ]
    assert round_lines, docs
    assert round_lines[0]["trace_id"] == round_spans[0].trace_id
    assert round_lines[0]["pool"] == "default"
    assert round_lines[0]["level"] == "INFO"
