"""Sync vs async scheduling runners (runner/{sync,async}.go seam)."""

import time

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import JobSpec, QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.jobdb import JobState
from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
from armada_tpu.services.runner import AsyncRunner, SyncRunner
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService


def test_async_runner_state_machine():
    r = AsyncRunner()
    assert r.idle
    started = time.time()
    r.submit(lambda: (time.sleep(0.2), "result")[1])
    assert time.time() - started < 0.1  # submit returns immediately
    assert not r.idle
    assert r.poll() is None  # still running
    assert r.wait(5.0)
    assert r.poll() == "result"
    assert r.idle


def test_async_runner_surfaces_errors():
    r = AsyncRunner()

    def boom():
        raise RuntimeError("solve failed")

    r.submit(boom)
    r.wait(5.0)
    try:
        r.poll()
        assert False, "expected error"
    except RuntimeError as e:
        assert "solve failed" in str(e)
    assert r.idle  # recovered


def _stack(runner):
    config = SchedulingConfig()
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, runner=runner)
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("q"))
    ex = FakeExecutor("ex", log, sched, nodes=make_nodes("ex", count=2, cpu="8"))
    return sched, submit, ex


def test_async_scheduling_end_to_end():
    sched, submit, ex = _stack(AsyncRunner())
    submit.submit(
        "q", "s",
        [JobSpec(id=f"j{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"})
         for i in range(4)],
        now=0.0,
    )
    ex.tick(0.0)
    # Cycle 1 kicks off the background solve; results land on a later cycle.
    sched.cycle(now=1.0)
    sched.runner.wait(10.0)
    sched.cycle(now=2.0)
    txn = sched.jobdb.read_txn()
    leased = [j for j in txn.all_jobs() if j.state == JobState.LEASED]
    assert len(leased) == 4


def test_sync_and_async_agree():
    results = {}
    for name, runner in [("sync", SyncRunner()), ("async", AsyncRunner())]:
        sched, submit, ex = _stack(runner)
        submit.submit(
            "q", "s",
            [JobSpec(id=f"j{i}", queue="q",
                     requests={"cpu": "2", "memory": "1Gi"}, submitted_ts=i)
             for i in range(6)],
            now=0.0,
        )
        ex.tick(0.0)
        for t in (1.0, 2.0, 3.0):
            sched.cycle(now=t)
            if hasattr(sched.runner, "wait"):
                sched.runner.wait(10.0)
        sched.cycle(now=4.0)
        txn = sched.jobdb.read_txn()
        results[name] = {
            j.id: (j.state.value, j.latest_run.node_id if j.latest_run else "")
            for j in txn.all_jobs()
        }
    assert results["sync"] == results["async"]
