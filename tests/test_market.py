"""Market-driven scheduling: bid-price ordering, market eviction, spot price
(experimental in the reference, scheduling_algo.go:795-813;
MarketJobPriorityComparer / market_iterator.go). Kernel/oracle parity."""

import numpy as np

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, RunningJob
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver

MKT = SchedulingConfig(
    priority_classes={"m": PriorityClass("m", 1000, preemptible=True)},
    default_priority_class="m",
    market_driven=True,
    spot_price_cutoff=0.5,
)


def node(cpu="8"):
    return NodeSpec(id="n0", pool="default",
                    total_resources={"cpu": cpu, "memory": "32Gi"})


def bid_job(i, bid, queue="q", cpu="2", **kw):
    return JobSpec(id=f"j{i}", queue=queue, requests={"cpu": cpu, "memory": "1Gi"},
                   submitted_ts=float(i), bid_prices={"default": bid}, **kw)


def both(cfg, nodes, queues, running, queued):
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all()
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    assert (oracle.preempted_mask == out["preempted_mask"][:J]).all()
    k_spot = float(out["spot_price"])
    if oracle.spot_price is None:
        assert np.isnan(k_spot)
    else:
        assert abs(k_spot - oracle.spot_price) < 1e-9
    return snap, oracle


def test_highest_bids_win():
    # 8 cpu; four 2-cpu jobs with bids 10,1,5,7 -> 10,7,5,1 all fit; add a
    # fifth low bid that doesn't
    queued = [bid_job(0, 10.0), bid_job(1, 1.0), bid_job(2, 5.0),
              bid_job(3, 7.0), bid_job(4, 0.5)]
    snap, res = both(MKT, [node()], [QueueSpec("q")], [], queued)
    scheduled = {snap.job_ids[j] for j in np.flatnonzero(res.scheduled_mask)}
    assert scheduled == {"j0", "j2", "j3", "j1"}  # top 4 bids
    assert not res.scheduled_mask[snap.job_ids.index("j4")]


def test_market_preempts_lower_bids():
    # node full of running low-bid jobs; higher-bid arrivals displace them
    running = [
        RunningJob(job=bid_job(i, 1.0), node_id="n0", scheduled_at_priority=1000)
        for i in range(4)
    ]
    queued = [bid_job(10 + i, 9.0) for i in range(2)]
    snap, res = both(MKT, [node()], [QueueSpec("q")], running, queued)
    assert res.scheduled_mask.sum() == 2  # both high bids on
    assert res.preempted_mask.sum() == 2  # two low bids pushed off


def test_spot_price_set_at_cutoff():
    # cutoff 0.5 of 8 cpu: bids descending 9,8,7,6 at 2 cpu each. Cost is
    # 0.25 after the first, exactly 0.5 after the second (not strictly
    # above), 0.75 after the third -> the third job (bid 7) sets the price.
    queued = [bid_job(i, 9.0 - i) for i in range(4)]
    snap, res = both(MKT, [node()], [QueueSpec("q")], [], queued)
    assert res.spot_price == 7.0


def test_non_preemptible_running_always_wins():
    # A running non-preemptible job carries an effectively infinite price:
    # market eviction still evicts it, but it always reschedules first.
    cfg = SchedulingConfig(
        priority_classes={
            "solid": PriorityClass("solid", 1000, preemptible=False),
            "m": PriorityClass("m", 1000, preemptible=True),
        },
        default_priority_class="m",
        market_driven=True,
    )
    running = [
        RunningJob(
            job=JobSpec(id="solid0", queue="q", priority_class="solid",
                        requests={"cpu": "6", "memory": "1Gi"},
                        bid_prices={"default": 0.1}),
            node_id="n0",
            scheduled_at_priority=1000,
        )
    ]
    queued = [bid_job(1, 999.0, cpu="6")]
    snap, res = both(cfg, [node()], [QueueSpec("q")], running, queued)
    assert res.preempted_mask.sum() == 0  # the non-preemptible job survived
    solid = snap.job_ids.index("solid0")
    assert res.assigned_node[solid] == 0


def test_equal_bid_prefers_running():
    # Anti-churn: at equal price a running job keeps its slot over a queued
    # job submitted earlier (market_iterator.go:218-222).
    running = [
        RunningJob(job=bid_job(0, 5.0, cpu="6"), node_id="n0",
                   scheduled_at_priority=1000)
    ]
    queued = [bid_job(1, 5.0, cpu="6").with_(submitted_ts=0.0)]
    snap, res = both(MKT, [node()], [QueueSpec("q")], running, queued)
    assert res.preempted_mask.sum() == 0
    assert res.assigned_node[snap.job_ids.index("j0")] == 0
    assert not res.scheduled_mask[snap.job_ids.index("j1")]


def test_two_queues_price_order_interleaves():
    queued = [bid_job(0, 3.0, queue="a"), bid_job(1, 9.0, queue="b"),
              bid_job(2, 6.0, queue="a"), bid_job(3, 1.0, queue="b")]
    snap, res = both(
        MKT, [node(cpu="6")], [QueueSpec("a"), QueueSpec("b")], [], queued
    )
    scheduled = {snap.job_ids[j] for j in np.flatnonzero(res.scheduled_mask)}
    # capacity 6 cpu = 3 jobs: bids 9, 6, 3 win across queues
    assert scheduled == {"j1", "j2", "j0"}
