"""Market-driven scheduling: bid-price ordering, market eviction, spot price
(experimental in the reference, scheduling_algo.go:795-813;
MarketJobPriorityComparer / market_iterator.go). Kernel/oracle parity."""

import numpy as np

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, RunningJob
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver

MKT = SchedulingConfig(
    priority_classes={"m": PriorityClass("m", 1000, preemptible=True)},
    default_priority_class="m",
    market_driven=True,
    spot_price_cutoff=0.5,
)


def node(cpu="8"):
    return NodeSpec(id="n0", pool="default",
                    total_resources={"cpu": cpu, "memory": "32Gi"})


def bid_job(i, bid, queue="q", cpu="2", **kw):
    return JobSpec(id=f"j{i}", queue=queue, requests={"cpu": cpu, "memory": "1Gi"},
                   submitted_ts=float(i), bid_prices={"default": bid}, **kw)


def both(cfg, nodes, queues, running, queued):
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all()
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    assert (oracle.preempted_mask == out["preempted_mask"][:J]).all()
    k_spot = float(out["spot_price"])
    if oracle.spot_price is None:
        assert np.isnan(k_spot)
    else:
        assert abs(k_spot - oracle.spot_price) < 1e-9
    return snap, oracle


def test_highest_bids_win():
    # 8 cpu; four 2-cpu jobs with bids 10,1,5,7 -> 10,7,5,1 all fit; add a
    # fifth low bid that doesn't
    queued = [bid_job(0, 10.0), bid_job(1, 1.0), bid_job(2, 5.0),
              bid_job(3, 7.0), bid_job(4, 0.5)]
    snap, res = both(MKT, [node()], [QueueSpec("q")], [], queued)
    scheduled = {snap.job_ids[j] for j in np.flatnonzero(res.scheduled_mask)}
    assert scheduled == {"j0", "j2", "j3", "j1"}  # top 4 bids
    assert not res.scheduled_mask[snap.job_ids.index("j4")]


def test_market_preempts_lower_bids():
    # node full of running low-bid jobs; higher-bid arrivals displace them
    running = [
        RunningJob(job=bid_job(i, 1.0), node_id="n0", scheduled_at_priority=1000)
        for i in range(4)
    ]
    queued = [bid_job(10 + i, 9.0) for i in range(2)]
    snap, res = both(MKT, [node()], [QueueSpec("q")], running, queued)
    assert res.scheduled_mask.sum() == 2  # both high bids on
    assert res.preempted_mask.sum() == 2  # two low bids pushed off


def test_spot_price_set_at_cutoff():
    # cutoff 0.5 of 8 cpu: bids descending 9,8,7,6 at 2 cpu each. Cost is
    # 0.25 after the first, exactly 0.5 after the second (not strictly
    # above), 0.75 after the third -> the third job (bid 7) sets the price.
    queued = [bid_job(i, 9.0 - i) for i in range(4)]
    snap, res = both(MKT, [node()], [QueueSpec("q")], [], queued)
    assert res.spot_price == 7.0


def test_non_preemptible_running_always_wins():
    # A running non-preemptible job carries an effectively infinite price:
    # market eviction still evicts it, but it always reschedules first.
    cfg = SchedulingConfig(
        priority_classes={
            "solid": PriorityClass("solid", 1000, preemptible=False),
            "m": PriorityClass("m", 1000, preemptible=True),
        },
        default_priority_class="m",
        market_driven=True,
    )
    running = [
        RunningJob(
            job=JobSpec(id="solid0", queue="q", priority_class="solid",
                        requests={"cpu": "6", "memory": "1Gi"},
                        bid_prices={"default": 0.1}),
            node_id="n0",
            scheduled_at_priority=1000,
        )
    ]
    queued = [bid_job(1, 999.0, cpu="6")]
    snap, res = both(cfg, [node()], [QueueSpec("q")], running, queued)
    assert res.preempted_mask.sum() == 0  # the non-preemptible job survived
    solid = snap.job_ids.index("solid0")
    assert res.assigned_node[solid] == 0


def test_equal_bid_prefers_running():
    # Anti-churn: at equal price a running job keeps its slot over a queued
    # job submitted earlier (market_iterator.go:218-222).
    running = [
        RunningJob(job=bid_job(0, 5.0, cpu="6"), node_id="n0",
                   scheduled_at_priority=1000)
    ]
    queued = [bid_job(1, 5.0, cpu="6").with_(submitted_ts=0.0)]
    snap, res = both(MKT, [node()], [QueueSpec("q")], running, queued)
    assert res.preempted_mask.sum() == 0
    assert res.assigned_node[snap.job_ids.index("j0")] == 0
    assert not res.scheduled_mask[snap.job_ids.index("j1")]


def test_two_queues_price_order_interleaves():
    queued = [bid_job(0, 3.0, queue="a"), bid_job(1, 9.0, queue="b"),
              bid_job(2, 6.0, queue="a"), bid_job(3, 1.0, queue="b")]
    snap, res = both(
        MKT, [node(cpu="6")], [QueueSpec("a"), QueueSpec("b")], [], queued
    )
    scheduled = {snap.job_ids[j] for j in np.flatnonzero(res.scheduled_mask)}
    # capacity 6 cpu = 3 jobs: bids 9, 6, 3 win across queues
    assert scheduled == {"j1", "j2", "j0"}


def test_idealised_vs_realised_value():
    """idealised_value.go:23: on a market pool, the idealised value prices
    the round as if the pool were one mega node with static requirements
    ignored. A high-bid job too big for any single node contributes to the
    idealised value but not the realised one (the expectation gap)."""
    from armada_tpu.solver.idealised import (
        calculate_idealised_value,
        value_by_queue,
    )

    nodes = [
        NodeSpec(id=f"n{i}", pool="default",
                 total_resources={"cpu": "8", "memory": "32Gi"})
        for i in range(2)
    ]
    queues = [QueueSpec("q", 1.0)]
    # j0 needs 12 cpu: fits no node, fits the 16-cpu mega node. j1/j2 fit.
    queued = [
        bid_job(0, 10.0, cpu="12"),
        bid_job(1, 2.0, cpu="4"),
        bid_job(2, 1.0, cpu="4"),
    ]
    snap = build_round_snapshot(MKT, "default", nodes, queues, [], queued)
    result = ReferenceSolver(snap).solve()
    unit = {"cpu": "1"}

    def solve_fn(s):
        res = ReferenceSolver(s).solve()
        return {"scheduled_mask": res.scheduled_mask}

    realised = value_by_queue(snap, result.scheduled_mask, unit)
    idealised = calculate_idealised_value(
        MKT, "default", nodes, queues, [], queued, solve_fn, unit
    )
    # Realised: j1 (2.0 x 4) + j2 (1.0 x 4) = 12; j0 doesn't fit anywhere.
    assert realised["q"] == 12.0
    # Idealised: j0 (10 x 12) + j1 (2 x 4) = 128 on the 16-cpu mega node
    # (j2 no longer fits behind the higher-value j0).
    assert idealised["q"] == 128.0
    assert idealised["q"] > realised["q"]


def test_idealised_value_ignores_static_requirements():
    """Selectors that match no node are ignored on the mega node
    (StaticRequirementsIgnoringIterator)."""
    from armada_tpu.solver.idealised import calculate_idealised_value

    nodes = [node()]
    queues = [QueueSpec("q", 1.0)]
    queued = [
        bid_job(0, 5.0, cpu="2",
                node_selector={"zone": "nowhere"}),
    ]

    def solve_fn(s):
        res = ReferenceSolver(s).solve()
        return {"scheduled_mask": res.scheduled_mask}

    idealised = calculate_idealised_value(
        MKT, "default", nodes, queues, [], queued, solve_fn, {"cpu": "1"}
    )
    assert idealised["q"] == 10.0  # 5.0 bid x 2 cpu units


def test_scheduler_service_reports_values():
    """The service wires idealised/realised value into reports + the
    report string on market pools."""
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    log = InMemoryEventLog()
    sched = SchedulerService(MKT, log, backend="oracle")
    submit = SubmitService(MKT, log, scheduler=sched)
    FakeExecutor("c", log, sched,
                 nodes=make_nodes("c", count=2, cpu="8", memory="32Gi"),
                 runtime_for=lambda j: 100.0).tick(0.0)
    submit.create_queue(QueueSpec("q"))
    submit.submit(
        "q", "s1",
        [JobSpec(id=f"j{i}", queue="", requests={"cpu": "4", "memory": "1Gi"},
                 bid_prices={"default": 2.0})
         for i in range(3)],
        now=0.0,
    )
    sched.cycle(now=1.0)
    rep = sched.reports.latest_reports()["default"]
    qr = rep.queues["q"]
    assert qr.realised_value == 3 * 2.0 * 4  # three 4-cpu jobs at bid 2.0
    assert qr.idealised_value >= qr.realised_value
    assert "idealisedValue" in rep.report_string()
