"""Bench artifact tooling: the regression gate and the trend printer
must parse every historical BENCH_r*.json schema (r03 has no `parsed`
block; burst_50k only exists from r05) and gate correctly on fixtures.
Fast tier-1 smoke — no bench run, fixture dicts only."""

import json
import os
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, os.path.join(REPO, "tools"))

from bench_gate import (  # noqa: E402
    extract_metrics,
    gate,
    latest_baseline,
    parse_artifact,
    residency_gate,
)

NEW_SCHEMA = {
    "rc": 0,
    "tail": "...",
    "parsed": {
        "value": 3.0,
        "extra": {
            "solve_s": 2.3,
            # Headline per-segment solve profile (hot-window round on):
            # pass1/gather gate alongside the cycle times.
            "segments": {"pass1_s": 2.0, "gather_s": 0.2, "setup_s": 0.05},
            # Effective solver parameters (autotune round on).
            "params": {"hot_window_slots": 4096, "chunk_loops": 1,
                       "fill_window": 2048, "tuned": False},
            # Round-observatory cost ledger (observatory round on):
            # bytes up/down + warm-cycle compile delta gate alongside
            # the cycle times.
            "transfer": {"bytes_up": 2048, "arrays_up": 61,
                         "bytes_down": 512, "arrays_down": 9,
                         "donated_bytes": 0, "donated_buffers": 0,
                         "compiles": {"traces": 0, "compiles": 0,
                                      "compile_seconds": 0.0}},
            "tracking_100k": {"cycle_s": 0.27},
            "burst_50k": {"cycle_s": 18.7},
        },
    },
}
# r03-era artifact: no parsed block, the bench line only in the tail.
OLD_SCHEMA = {
    "rc": 0,
    "tail": 'noise\n{"value": 1.2, "extra": {"solve_s": 0.9}}\n',
}
BROKEN = {"rc": 1, "tail": "Traceback (most recent call last)..."}
FAILED_RUN = {"rc": 1, "parsed": {"ok": False, "error": "boom"}}


def test_parse_both_schemas():
    new = extract_metrics(parse_artifact(NEW_SCHEMA))
    assert new == {"warm": 3.0, "tracking": 0.27, "burst": 18.7,
                   "pass1": 2.0, "gather": 0.2,
                   "bytes_up": 2048.0, "bytes_down": 512.0,
                   "compiles": 0.0}
    # Old artifacts predate extra.segments / extra.transfer: those
    # metrics are None, never a crash or a phantom gate.
    old = extract_metrics(parse_artifact(OLD_SCHEMA))
    assert old == {"warm": 1.2, "tracking": None, "burst": None,
                   "pass1": None, "gather": None,
                   "bytes_up": None, "bytes_down": None, "compiles": None}
    assert all(v is None for v in extract_metrics(parse_artifact(BROKEN)).values())
    # ok=false parsed blocks are failures, not baselines.
    assert parse_artifact(FAILED_RUN) is None


def test_gate_passes_within_threshold_and_fails_on_regression():
    base = {"warm": 3.0, "tracking": 0.27, "burst": 18.7}
    ok_current = {"warm": 3.2, "tracking": 0.28, "burst": 9.0}
    regressions, notes = gate(ok_current, base, threshold=1.15)
    assert not regressions and sum("OK" in n for n in notes) == 3
    bad_current = {"warm": 4.0, "tracking": 0.28, "burst": 9.0}
    regressions, _ = gate(bad_current, base, threshold=1.15)
    assert len(regressions) == 1 and regressions[0].startswith("warm")


def test_gate_skips_incomparable_metrics():
    """Old baselines without burst/segment numbers must not gate them."""
    base = {"warm": 1.2, "tracking": None, "burst": None}
    regressions, notes = gate(
        {"warm": 1.0, "tracking": 0.3, "burst": 50.0, "pass1": 9.0}, base, 1.15
    )
    assert not regressions
    assert sum("not comparable" in n for n in notes) == 7


def test_gate_per_segment_medians():
    """A pass-1 or gather regression inside the solve gates on its own,
    even when the end-to-end cycle stays within threshold; a segment
    missing on either side (old artifacts) never gates."""
    base = extract_metrics(parse_artifact(NEW_SCHEMA))
    ok = dict(base, warm=3.1, pass1=2.1, gather=0.21)
    regressions, _ = gate(ok, base, threshold=1.15)
    assert not regressions
    bad = dict(base, pass1=4.0)  # cycle unchanged, pass 1 doubled
    regressions, _ = gate(bad, base, threshold=1.15)
    assert len(regressions) == 1 and regressions[0].startswith("pass1")
    # Sub-ms segment baselines are floored: doubling 0.4ms of gather is
    # scheduler noise, not a regression.
    tiny = dict(base, gather=0.0009)
    regressions, _ = gate(dict(tiny, gather=0.002), tiny, threshold=1.15)
    assert not regressions
    # Old baseline without segments: current segments report as
    # incomparable, never gate.
    old = extract_metrics(parse_artifact(OLD_SCHEMA))
    regressions, notes = gate(dict(base, warm=old["warm"]), old, threshold=1.15)
    assert not regressions
    assert sum("not comparable" in n for n in notes) >= 2


def test_gate_cli_fails_on_crashed_bench(tmp_path):
    """A crashed bench (ok=false, value null) must NOT read as a green
    gate: no extractable current-side metric exits 2."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(NEW_SCHEMA))
    current = tmp_path / "current.json"
    current.write_text(json.dumps({"value": None, "ok": False, "error": "boom"}))
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
            "--current", str(current), "--baseline-dir", str(tmp_path),
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr


def test_latest_baseline_skips_unusable(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(OLD_SCHEMA))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(NEW_SCHEMA))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(BROKEN))
    (tmp_path / "BENCH_r04.json").write_text("not json at all")
    path, metrics = latest_baseline(str(tmp_path))
    assert path.endswith("BENCH_r02.json")
    assert metrics["burst"] == 18.7


def test_gate_cli_on_fixtures(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(NEW_SCHEMA))
    current = tmp_path / "current.json"
    current.write_text(json.dumps({"value": 2.9, "extra": {
        "tracking_100k": {"cycle_s": 0.26}, "burst_50k": {"cycle_s": 8.0}}}))
    cmd = [
        sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
        "--current", str(current), "--baseline-dir", str(tmp_path),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    current.write_text(json.dumps({"value": 99.0, "extra": {}}))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    assert proc.returncode == 1 and "REGRESSION warm" in proc.stdout


def test_trend_handles_every_checked_in_artifact(tmp_path):
    """tools/bench_trend.py prints a row per artifact without crashing —
    on fixtures covering all schema generations AND on the repo's real
    BENCH_r*.json set."""
    for name, doc in (
        ("BENCH_r01.json", OLD_SCHEMA),
        ("BENCH_r02.json", BROKEN),
        ("BENCH_r03.json", NEW_SCHEMA),
    ):
        (tmp_path / name).write_text(json.dumps(doc))
    for target in (str(tmp_path), REPO):
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "bench_trend.py"),
                "--dir", target,
            ],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "BENCH_r01.json" in proc.stdout


def test_trend_tolerates_and_shows_whatif_block(tmp_path):
    """Artifacts carrying the new extra.whatif block (shadow-solve plan
    stats from the what-if planner) render a whatif column; artifacts
    without it print '-' and the gate ignores the block entirely."""
    with_whatif = json.loads(json.dumps(NEW_SCHEMA))
    with_whatif["parsed"]["extra"]["whatif"] = {
        "plans": 3, "plan_s": 0.42,
    }
    bare_marker = json.loads(json.dumps(NEW_SCHEMA))
    bare_marker["parsed"]["extra"]["whatif"] = {"enabled": True}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(OLD_SCHEMA))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(with_whatif))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(bare_marker))
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "bench_trend.py"),
            "--dir", str(tmp_path),
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "whatif" in proc.stdout
    lines = {l.split()[0]: l for l in proc.stdout.splitlines() if "BENCH_" in l}
    assert "3@0.42s" in lines["BENCH_r02.json"]
    assert lines["BENCH_r03.json"].split()[-7] == "yes"  # whatif column
    # The gate's metric extraction is unaffected by the extra block.
    assert extract_metrics(parse_artifact(with_whatif))["warm"] == 3.0


def test_trend_tolerates_and_shows_frontdoor_block(tmp_path):
    """Artifacts carrying the extra.frontdoor SLO block
    (tools/frontdoor_soak.py --out) render a frontdoor column —
    p99/max-lag, '!' on a breached gate; old artifacts print '-'."""
    with_fd = json.loads(json.dumps(NEW_SCHEMA))
    with_fd["parsed"]["extra"]["frontdoor"] = {
        "p99_ms": 17.0, "max_lag": 13, "ok": True,
    }
    breached = json.loads(json.dumps(NEW_SCHEMA))
    breached["parsed"]["extra"]["frontdoor"] = {
        "p99_ms": 300.0, "max_lag": 5000, "ok": False,
    }
    bare = json.loads(json.dumps(NEW_SCHEMA))
    bare["parsed"]["extra"]["frontdoor"] = {"enabled": True}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(OLD_SCHEMA))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(with_fd))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(breached))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(bare))
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "bench_trend.py"),
            "--dir", str(tmp_path),
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "frontdoor" in proc.stdout
    lines = {l.split()[0]: l for l in proc.stdout.splitlines() if "BENCH_" in l}
    assert lines["BENCH_r01.json"].rstrip().endswith("-")
    assert "17ms/13" in lines["BENCH_r02.json"]
    assert "300ms/5000!" in lines["BENCH_r03.json"]
    assert lines["BENCH_r04.json"].split()[-6] == "yes"  # frontdoor column
    # The gate's metric extraction is unaffected by the extra block.
    assert extract_metrics(parse_artifact(with_fd))["warm"] == 3.0


def test_trend_tolerates_and_shows_fairness_block(tmp_path):
    """Artifacts carrying extra.fairness (the fairness observatory's
    headline Jain index + max regret) render a fairness column
    (jJAIN/rREGRET); pre-fairness artifacts print '-' and the gate
    ignores the block entirely."""
    with_fair = json.loads(json.dumps(NEW_SCHEMA))
    with_fair["parsed"]["extra"]["fairness"] = {
        "jain": 0.9876, "max_regret": 0.125, "preemptions_attributed": 2,
    }
    bare = json.loads(json.dumps(NEW_SCHEMA))
    bare["parsed"]["extra"]["fairness"] = {"error": "boom"}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(OLD_SCHEMA))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(with_fair))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(bare))
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "bench_trend.py"),
            "--dir", str(tmp_path),
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fairness" in proc.stdout
    lines = {l.split()[0]: l for l in proc.stdout.splitlines() if "BENCH_" in l}
    assert lines["BENCH_r01.json"].rstrip().endswith("-")
    assert "j0.988/r0.125" in lines["BENCH_r02.json"]
    assert lines["BENCH_r03.json"].split()[-3] == "yes"  # fairness column
    # The gate's metric extraction is unaffected by the extra block.
    assert extract_metrics(parse_artifact(with_fair))["warm"] == 3.0


def test_gate_transfer_ledger_and_compiles(tmp_path):
    """extra.transfer gates: bytes up/down regress past the threshold
    factor, the warm-cycle compile count regresses on ANY increase
    (zero compiles is the warm steady state), and artifacts without
    the block (pre-observatory) report incomparable, never gate."""
    base = extract_metrics(parse_artifact(NEW_SCHEMA))
    ok = dict(base, bytes_up=2100.0, bytes_down=520.0, compiles=0.0)
    regressions, _ = gate(ok, base, threshold=1.15)
    assert not regressions
    # Byte blowup inside the threshold-passing cycle gates on its own.
    churny = dict(base, bytes_up=base["bytes_up"] * 3)
    regressions, _ = gate(churny, base, threshold=1.15)
    assert len(regressions) == 1 and regressions[0].startswith("bytes_up")
    # One compile in a warm cycle gates regardless of how fast it was.
    recompiled = dict(base, compiles=1.0)
    regressions, _ = gate(recompiled, base, threshold=1.15)
    assert len(regressions) == 1 and regressions[0].startswith("compiles")
    # Pre-observatory baseline: transfer metrics incomparable, no gate.
    old = extract_metrics(parse_artifact(OLD_SCHEMA))
    regressions, notes = gate(dict(base, warm=old["warm"]), old, 1.15)
    assert not regressions
    assert sum("not comparable" in n for n in notes) >= 3


def test_trend_shows_transfer_column(tmp_path):
    """The trend table renders the cost ledger (bytes up/down + compile
    count) for artifacts that record extra.transfer; older artifacts
    print '-'."""
    churn = json.loads(json.dumps(NEW_SCHEMA))
    churn["parsed"]["extra"]["transfer"] = {
        "bytes_up": 3 * 1024 ** 3, "bytes_down": 5 * 1024 ** 2,
        "compiles": {"compiles": 2},
    }
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(OLD_SCHEMA))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(NEW_SCHEMA))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(churn))
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "bench_trend.py"),
            "--dir", str(tmp_path),
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "transfer" in proc.stdout
    lines = {l.split()[0]: l for l in proc.stdout.splitlines() if "BENCH_" in l}
    assert lines["BENCH_r01.json"].rstrip().endswith("-")
    assert "2.0K/512B,c0" in lines["BENCH_r02.json"]
    assert "3.0G/5.0M,c2" in lines["BENCH_r03.json"]


def test_residency_budget_gate(tmp_path):
    """--residency-budget-mb is an ABSOLUTE gate on the warm cycle's
    booked upload: under budget passes, over budget regresses, and —
    because passing the flag asserts residency is measured — a current
    artifact with no extra.transfer.bytes_up regresses too. Without the
    flag the gate is inert on every schema."""
    parsed = parse_artifact(NEW_SCHEMA)  # bytes_up: 2048
    regressions, notes = residency_gate(parsed, None)
    assert not regressions and not notes
    regressions, notes = residency_gate(parsed, 1.0)
    assert not regressions and sum("OK residency" in n for n in notes) == 1
    regressions, _ = residency_gate(parsed, 0.001)  # 2048B > 0.001MB
    assert len(regressions) == 1 and regressions[0].startswith("residency")
    # Artifact that cannot prove its upload size fails the asserted gate.
    regressions, _ = residency_gate(parse_artifact(OLD_SCHEMA), 1.0)
    assert len(regressions) == 1 and "no extra.transfer.bytes_up" in regressions[0]
    # The mode lands in the gate line when the artifact records it.
    with_mode = json.loads(json.dumps(NEW_SCHEMA))
    with_mode["parsed"]["extra"]["residency"] = {
        "mode": "delta", "bytes_up": 2048, "permuted": True,
    }
    _, notes = residency_gate(parse_artifact(with_mode), 1.0)
    assert any("mode=delta" in n for n in notes)


def test_residency_budget_gate_cli(tmp_path):
    """End-to-end: the flag turns a green run red when the warm upload
    blows the absolute budget, independent of the baseline compare."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(NEW_SCHEMA))
    current = tmp_path / "current.json"
    current.write_text(json.dumps(NEW_SCHEMA["parsed"]))
    cmd = [
        sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
        "--current", str(current), "--baseline-dir", str(tmp_path),
    ]
    proc = subprocess.run(cmd + ["--residency-budget-mb", "1.0"],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK residency" in proc.stdout
    proc = subprocess.run(cmd + ["--residency-budget-mb", "0.001"],
                          capture_output=True, text=True)
    assert proc.returncode == 1 and "REGRESSION residency" in proc.stdout


def test_trend_shows_residency_column(tmp_path):
    """Artifacts carrying extra.residency (device-resident round state)
    render mode@MBup; artifacts without the block print '-'."""
    delta = json.loads(json.dumps(NEW_SCHEMA))
    delta["parsed"]["extra"]["residency"] = {
        "mode": "delta", "bytes_up": 13_400_000, "permuted": True,
    }
    bare = json.loads(json.dumps(NEW_SCHEMA))
    bare["parsed"]["extra"]["residency"] = {"mode": "reset"}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(OLD_SCHEMA))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(delta))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(bare))
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "bench_trend.py"),
            "--dir", str(tmp_path),
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "residency" in proc.stdout
    lines = {l.split()[0]: l for l in proc.stdout.splitlines() if "BENCH_" in l}
    assert lines["BENCH_r01.json"].rstrip().endswith("-")
    assert "delta@13.4MB" in lines["BENCH_r02.json"]
    assert lines["BENCH_r03.json"].split()[-4] == "reset"  # residency column
    # The gate's metric extraction is unaffected by the extra block.
    assert extract_metrics(parse_artifact(delta))["warm"] == 3.0


def test_trend_shows_effective_params_column(tmp_path):
    """The trend table carries the effective solver-parameter vector
    (window/chunk, starred when tuned) for artifacts that record it and
    '-' for older schemas."""
    tuned = json.loads(json.dumps(NEW_SCHEMA))
    tuned["parsed"]["extra"]["params"] = {
        "hot_window_slots": 8192, "chunk_loops": 4, "tuned": True,
    }
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(OLD_SCHEMA))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(NEW_SCHEMA))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(tuned))
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "bench_trend.py"),
            "--dir", str(tmp_path),
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "win/chunk" in proc.stdout
    lines = {l.split()[0]: l for l in proc.stdout.splitlines() if "BENCH_" in l}
    assert "4096/1" in lines["BENCH_r02.json"]
    assert "8192/4*" in lines["BENCH_r03.json"]
    assert "4096" not in lines["BENCH_r01.json"]
