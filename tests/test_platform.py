"""Platform guard: the axon-tunnel relay preflight.

The tunnel plugin blocks forever inside PJRT_Client_Create when its
loopback relay is down (docs/tpu_tunnel_postmortem.md); the preflight must
settle liveness at TCP speed, both ways.
"""

import socket
import threading

from armada_tpu.utils.platform import relay_preflight


def test_preflight_down(monkeypatch):
    # Nothing listens on these ports in the test env (and if something
    # did, AXON_POOL_SVC_OVERRIDE steers us to a dead name).
    monkeypatch.setenv("AXON_POOL_SVC_OVERRIDE", "127.0.0.1")
    alive, detail = relay_preflight(timeout=0.2)
    if alive:
        # A real relay is up on this host — preflight must say so.
        assert "listening" in detail
    else:
        assert "relay down" in detail
        assert "8083" in detail and "8082" in detail


def test_preflight_up(monkeypatch):
    # Stand up a throwaway listener on one of the relay ports' host —
    # bind an ephemeral port and monkeypatch the port list instead of
    # requiring 8083 to be free.
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    monkeypatch.setenv("AXON_POOL_SVC_OVERRIDE", "127.0.0.1")
    import armada_tpu.utils.platform as plat

    monkeypatch.setattr(plat, "_RELAY_PORTS", (port,))
    accepted = []

    def accept():
        try:
            conn, _ = srv.accept()
            accepted.append(1)
            conn.close()
        except OSError:
            pass

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    alive, detail = relay_preflight(timeout=1.0)
    srv.close()
    assert alive and f":{port}" in detail
