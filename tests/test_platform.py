"""Platform guard: the axon-tunnel relay preflight.

The tunnel plugin blocks forever inside PJRT_Client_Create when its
loopback relay is down (docs/tpu_tunnel_postmortem.md); the preflight must
settle liveness at TCP speed, both ways.
"""

import socket

import armada_tpu.utils.platform as plat
from armada_tpu.utils.platform import relay_preflight


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_preflight_down(monkeypatch):
    # A port that was just released: connecting to it is refused.
    monkeypatch.setenv("AXON_POOL_SVC_OVERRIDE", "127.0.0.1")
    monkeypatch.setattr(plat, "_RELAY_PORTS", (_free_port(),))
    alive, detail = relay_preflight(timeout=0.5)
    assert not alive
    assert "relay down" in detail


def test_preflight_up(monkeypatch):
    # The TCP handshake completes from the kernel listen backlog; no
    # accept() needed.
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    monkeypatch.setenv("AXON_POOL_SVC_OVERRIDE", "127.0.0.1")
    monkeypatch.setattr(plat, "_RELAY_PORTS", (port,))
    alive, detail = relay_preflight(timeout=1.0)
    srv.close()
    assert alive and f":{port}" in detail
