"""Churn soak: a control plane under sustained mixed load — submissions,
cancels, reprioritisations, executor loss, cordons — with jobdb invariants
asserted every cycle and conservation checks at the end. The closest thing
to a chaos test that stays deterministic enough for CI."""

import zlib

import numpy as np

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.jobdb import JobState
from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService


def test_churn_soak():
    rng = np.random.default_rng(42)
    config = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
        executor_timeout_s=20.0,
        enable_assertions=True,  # jobdb invariants every cycle
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    for i in range(3):
        submit.create_queue(QueueSpec(f"q{i}", 1.0 + i % 2))

    executors = [
        FakeExecutor(
            f"ex-{i}", log, sched,
            nodes=make_nodes(f"ex-{i}", count=4, cpu="16", memory="64Gi"),
            runtime_for=lambda job_id: 15.0 + (zlib.crc32(job_id.encode()) % 20),
        )
        for i in range(3)
    ]

    submitted: list[str] = []
    cancelled: set[str] = set()
    jid = 0
    t = 0.0
    dead_executor = None
    overcommitted_since: dict[str, int] = {}
    overcommit_cycles_total = 0

    for step in range(120):
        t += 2.0
        # churn: submissions
        if rng.random() < 0.7:
            q = f"q{int(rng.integers(0, 3))}"
            n = int(rng.integers(1, 5))
            jobs = []
            gang = None
            # Gang members must agree on priority class (the submit-side
            # member-agreement validator mirrors gang_validator.go); pin
            # one class per gang, randomize only for singletons.
            gang_pc = None
            if rng.random() < 0.2:
                gang = Gang(id=f"soak-gang-{step}", cardinality=n)
                gang_pc = str(rng.choice(["low", "low", "high"]))
            for _ in range(n):
                jobs.append(
                    JobSpec(
                        id=f"soak-{jid:05d}",
                        queue=q,
                        priority_class=gang_pc
                        or str(rng.choice(["low", "low", "high"])),
                        requests={
                            "cpu": str(int(rng.choice([1, 2, 4]))),
                            "memory": f"{int(rng.choice([1, 2]))}Gi",
                        },
                        gang=gang,
                    )
                )
                jid += 1
            ids = submit.submit(q, f"set-{step % 5}", jobs, now=t)
            submitted += ids
        # churn: cancels
        if submitted and rng.random() < 0.15:
            victim = submitted[int(rng.integers(0, len(submitted)))]
            job = sched.jobdb.get(victim)
            if job is not None and not job.state.terminal:
                submit.cancel_job(job.queue, job.jobset, victim)
                cancelled.add(victim)
        # churn: reprioritise
        if submitted and rng.random() < 0.1:
            victim = submitted[int(rng.integers(0, len(submitted)))]
            job = sched.jobdb.get(victim)
            if job is not None:
                submit.reprioritise_job(job.queue, job.jobset, victim, -1)
        # churn: an executor dies for a while at step 40, returns at 60
        if step == 40:
            dead_executor = executors.pop(0)
        if step == 60 and dead_executor is not None:
            executors.append(dead_executor)
            dead_executor = None

        for ex in executors:
            ex.tick(t)
        sched.cycle(now=t)  # asserts jobdb invariants internally

        # Capacity tracking: mixed-priority-class gangs can transiently
        # overcommit a node for one cycle (a faithful reproduction of the
        # reference's two-pass round: gang-completion re-evicts
        # non-preemptible members which re-bind over lows; the NEXT round's
        # oversubscription evictor repairs it — see docs/parity.md). Assert
        # that any overcommit disappears within two subsequent cycles.
        txn = sched.jobdb.read_txn()
        used: dict[str, int] = {}
        for job in txn.leased_jobs():
            run = job.latest_run
            if run and run.node_id:
                mc = int(float(job.spec.requests["cpu"]) * 1000)
                used[run.node_id] = used.get(run.node_id, 0) + mc
        over_now = {n for n, mc in used.items() if mc > 16000}
        overcommit_cycles_total += len(over_now)
        for node in overcommitted_since:
            overcommitted_since[node] += 1
        for node in over_now:
            overcommitted_since.setdefault(node, 0)
        for node in list(overcommitted_since):
            if node not in over_now:
                del overcommitted_since[node]
        lingering = {n: c for n, c in overcommitted_since.items() if c >= 3}
        assert not lingering, f"unrepaired oversubscription: {lingering}"
        # A flapping bug (over/clean/over/...) would evade the episode
        # check above; the transient edge is rare, so the total number of
        # node-cycles spent overcommitted must stay small.
        assert overcommit_cycles_total <= 12, overcommit_cycles_total

    # drain: no more churn, let everything finish
    for _ in range(60):
        t += 5.0
        for ex in executors:
            ex.tick(t)
        sched.cycle(now=t)

    # steady state: strict capacity on every node
    txn = sched.jobdb.read_txn()
    used = {}
    for job in txn.leased_jobs():
        run = job.latest_run
        if run and run.node_id:
            mc = int(float(job.spec.requests["cpu"]) * 1000)
            used[run.node_id] = used.get(run.node_id, 0) + mc
    for node, mc in used.items():
        assert mc <= 16000, f"steady-state oversubscription on {node}: {mc}"

    txn = sched.jobdb.read_txn()
    states: dict[str, int] = {}
    stuck = []
    for job in txn.all_jobs():
        states[job.state.value] = states.get(job.state.value, 0) + 1
        if not job.state.terminal and job.state != JobState.QUEUED:
            stuck.append((job.id, job.state.value))
    # conservation: every submitted job is accounted for
    assert sum(states.values()) == len(submitted)
    # nothing left mid-flight after the drain
    assert not stuck, f"stuck jobs: {stuck[:10]}"
    # cancels took effect
    for jid_ in cancelled:
        assert sched.jobdb.get(jid_).state.value in ("cancelled", "succeeded")
    # the system did real work
    assert states.get("succeeded", 0) > len(submitted) * 0.5, states
