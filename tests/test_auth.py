"""Auth chain + permission mapping (common/auth/{multi,basic,oidc,
permissions}.go) and its enforcement on the gRPC surface."""

import time

import grpc
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.services import auth as A
from armada_tpu.services.auth import (
    AuthError,
    Authorizer,
    BasicAuth,
    MultiAuth,
    PermissionDenied,
    Principal,
    QueuePermission,
    TokenAuth,
    make_token,
)
from armada_tpu.services.grpc_api import ApiClient, ApiServer
from armada_tpu.services.queryapi import QueryApi
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService

SECRET = "test-signing-secret"


def test_basic_auth():
    auth = BasicAuth({"alice": {"password": "pw", "groups": ["devs"]}})
    import base64

    md = {"authorization": "Basic " + base64.b64encode(b"alice:pw").decode()}
    p = auth.authenticate(md)
    assert p.name == "alice" and "devs" in p.groups
    bad = {"authorization": "Basic " + base64.b64encode(b"alice:no").decode()}
    with pytest.raises(AuthError):
        auth.authenticate(bad)
    assert auth.authenticate({}) is None  # wrong shape: pass to next


def test_token_auth_roundtrip_and_expiry():
    auth = TokenAuth(SECRET)
    token = make_token(SECRET, "bob", groups=["ops"], exp=time.time() + 60)
    p = auth.authenticate({"authorization": f"Bearer {token}"})
    assert p.name == "bob" and "ops" in p.groups
    expired = make_token(SECRET, "bob", exp=time.time() - 1)
    with pytest.raises(AuthError):
        auth.authenticate({"authorization": f"Bearer {expired}"})
    forged = token[:-4] + "AAAA"
    with pytest.raises(AuthError):
        auth.authenticate({"authorization": f"Bearer {forged}"})


def test_multi_auth_first_match_wins():
    multi = MultiAuth(
        [
            BasicAuth({"alice": {"password": "pw"}}),
            TokenAuth(SECRET),
        ]
    )
    token = make_token(SECRET, "bob")
    assert multi.authenticate({"authorization": f"Bearer {token}"}).name == "bob"
    with pytest.raises(AuthError):
        multi.authenticate({})  # nothing matches, nothing anonymous


def test_authorizer_global_and_queue():
    az = Authorizer(permission_groups={A.SUBMIT_ANY_JOBS: ["submitters"]})
    admin = Principal("root", frozenset({"admin"}))
    submitter = Principal("s", frozenset({"submitters"}))
    rando = Principal("r", frozenset())
    az.authorize_global(admin, A.CREATE_QUEUE)
    az.authorize_global(submitter, A.SUBMIT_ANY_JOBS)
    with pytest.raises(PermissionDenied):
        az.authorize_global(rando, A.SUBMIT_ANY_JOBS)

    class Q:
        owners = ("owner-user",)
        permissions = (QueuePermission(subjects=("teammates",), verbs=("submit",)),)
        spec = QueueSpec("team")

    az.authorize_queue(Principal("owner-user"), "submit", Q(), A.SUBMIT_ANY_JOBS)
    az.authorize_queue(
        Principal("t", frozenset({"teammates"})), "submit", Q(), A.SUBMIT_ANY_JOBS
    )
    with pytest.raises(PermissionDenied):
        az.authorize_queue(
            Principal("t", frozenset({"teammates"})), "cancel", Q(),
            A.CANCEL_ANY_JOBS,
        )
    with pytest.raises(PermissionDenied):
        az.authorize_queue(rando, "submit", Q(), A.SUBMIT_ANY_JOBS)


@pytest.fixture()
def served():
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    query = QueryApi(sched.jobdb)
    server = ApiServer(
        submit,
        sched,
        query,
        log,
        auth=MultiAuth([TokenAuth(SECRET)]),
        authorizer=Authorizer(
            permission_groups={
                A.SUBMIT_ANY_JOBS: ["submitters"],
                A.CREATE_QUEUE: ["queue-admins"],
                A.EXECUTE_JOBS: ["executors"],
            }
        ),
    )
    grpc_server, port = server.serve(port=0)
    yield submit, port
    grpc_server.stop(0)


def _client(port, **kw):
    return ApiClient(f"127.0.0.1:{port}", **kw)


def test_unauthenticated_writes_rejected(served):
    submit, port = served
    anon = _client(port)
    with pytest.raises(grpc.RpcError) as e:
        anon.submit_jobs("team", "s", [{"id": "x", "requests": {"cpu": "1"}}])
    assert e.value.code() == grpc.StatusCode.UNAUTHENTICATED
    with pytest.raises(grpc.RpcError) as e:
        anon._call("CreateQueue", {"name": "team"})
    assert e.value.code() == grpc.StatusCode.UNAUTHENTICATED


def test_permission_denied_without_grant(served):
    submit, port = served
    peon = _client(port, token=make_token(SECRET, "peon"))
    with pytest.raises(grpc.RpcError) as e:
        peon._call("CreateQueue", {"name": "team"})
    assert e.value.code() == grpc.StatusCode.PERMISSION_DENIED


def test_authorized_flow_and_queue_grants(served):
    submit, port = served
    admin = _client(port, token=make_token(SECRET, "root", groups=["admin"]))
    admin._call("CreateQueue", {"name": "team"})
    # Grant alice queue-level submit directly in the registry.
    q = submit.get_queue("team")
    q.permissions = (QueuePermission(subjects=("alice",), verbs=("submit",)),)

    alice = _client(port, token=make_token(SECRET, "alice"))
    ids = alice.submit_jobs(
        "team", "s", [{"id": "j1", "requests": {"cpu": "1", "memory": "1Gi"}}]
    )
    assert ids == ["j1"]
    with pytest.raises(grpc.RpcError) as e:
        alice.cancel_jobs("team", "s", ["j1"])  # no cancel grant
    assert e.value.code() == grpc.StatusCode.PERMISSION_DENIED

    submitter = _client(
        port, token=make_token(SECRET, "subby", groups=["submitters"])
    )
    ids = submitter.submit_jobs(
        "team", "s", [{"id": "j2", "requests": {"cpu": "1", "memory": "1Gi"}}]
    )
    assert ids == ["j2"]


# ---------------------------------------------------------------------------
# RS256 / JWKS verification (auth/oidc.go analogue) + TLS listeners
# (internal/common/grpc TLS config analogue).
# ---------------------------------------------------------------------------


def _rsa_keypair():
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    return key, key.public_key()


def test_jwks_rs256_roundtrip_and_failures(tmp_path):
    pytest.importorskip("cryptography")
    import json as _json

    from armada_tpu.services.auth import (
        JwksTokenAuth,
        jwks_of,
        make_rs256_token,
    )

    key, pub = _rsa_keypair()
    jwks = jwks_of(pub, kid="kid-a")
    auth = JwksTokenAuth(jwks=jwks)
    tok = make_rs256_token(key, "alice", groups=("devs",), kid="kid-a")
    p = auth.authenticate({"authorization": f"Bearer {tok}"})
    assert p.name == "alice" and "devs" in p.groups and p.auth_method == "jwks"

    # Tampered payload -> bad signature.
    head, body, sig = tok.split(".")
    evil = A._b64url(_json.dumps({"sub": "mallory", "iss": "armada-tpu"}).encode())
    with pytest.raises(AuthError):
        auth.authenticate({"authorization": f"Bearer {head}.{evil}.{sig}"})

    # Wrong issuer / expiry.
    with pytest.raises(AuthError):
        auth.authenticate(
            {"authorization": "Bearer "
             + make_rs256_token(key, "a", iss="other", kid="kid-a")}
        )
    with pytest.raises(AuthError):
        auth.authenticate(
            {"authorization": "Bearer "
             + make_rs256_token(key, "a", exp=time.time() - 5, kid="kid-a")}
        )

    # A different keypair's token -> rejected.
    other_key, _ = _rsa_keypair()
    with pytest.raises(AuthError):
        auth.authenticate(
            {"authorization": "Bearer "
             + make_rs256_token(other_key, "a", kid="kid-a")}
        )

    # HS256 tokens are not this authenticator's shape: it defers (None),
    # so MultiAuth can chain RS256 + HS256 side by side.
    hs = make_token(SECRET, "bob")
    assert auth.authenticate({"authorization": f"Bearer {hs}"}) is None
    chain = MultiAuth([auth, TokenAuth(SECRET)])
    assert chain.authenticate({"authorization": f"Bearer {hs}"}).name == "bob"
    assert chain.authenticate({"authorization": f"Bearer {tok}"}).name == "alice"


def test_jwks_file_rotation(tmp_path):
    pytest.importorskip("cryptography")
    import json as _json

    from armada_tpu.services.auth import (
        JwksTokenAuth,
        jwks_of,
        make_rs256_token,
    )

    key1, pub1 = _rsa_keypair()
    key2, pub2 = _rsa_keypair()
    path = tmp_path / "jwks.json"
    path.write_text(_json.dumps(jwks_of(pub1, kid="k1")))
    auth = JwksTokenAuth(jwks_file=str(path))
    tok1 = make_rs256_token(key1, "alice", kid="k1")
    assert auth.authenticate({"authorization": f"Bearer {tok1}"}).name == "alice"

    # Rotate the file: new kid verifies after reload, old key is gone.
    import os

    path.write_text(_json.dumps(jwks_of(pub2, kid="k2")))
    os.utime(path, (time.time() + 2, time.time() + 2))
    tok2 = make_rs256_token(key2, "carol", kid="k2")
    assert auth.authenticate({"authorization": f"Bearer {tok2}"}).name == "carol"
    with pytest.raises(AuthError):
        auth.authenticate({"authorization": f"Bearer {tok1}"})


def _self_signed(tmp_path):
    """Self-signed localhost cert via cryptography; returns (cert, key)."""
    import datetime
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName("localhost"),
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_file = tmp_path / "tls.crt"
    key_file = tmp_path / "tls.key"
    cert_file.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_file.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(cert_file), str(key_file)


def test_grpc_tls_roundtrip(tmp_path):
    pytest.importorskip("cryptography")
    cert_file, key_file = _self_signed(tmp_path)
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    server = ApiServer(submit, sched, QueryApi(sched.jobdb), log)
    grpc_server, port = server.serve(port=0, tls=(cert_file, key_file))
    try:
        client = ApiClient(f"localhost:{port}", ca_cert=cert_file)
        client.create_queue("tls-q", priority_factor=1.0)
        queues = client.list_queues()
        assert any(q["name"] == "tls-q" for q in queues)
        # Plaintext against the TLS port must fail.
        plain = ApiClient(f"localhost:{port}")
        with pytest.raises(grpc.RpcError):
            plain.list_queues()
    finally:
        grpc_server.stop(0)


def test_rest_gateway_tls(tmp_path):
    pytest.importorskip("cryptography")
    import json as _json
    import ssl
    import urllib.request

    cert_file, key_file = _self_signed(tmp_path)
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    from armada_tpu.services.rest_gateway import RestGateway

    gw = RestGateway(
        submit, sched, QueryApi(sched.jobdb), log, port=0,
        tls=(cert_file, key_file),
    )
    try:
        ctx = ssl.create_default_context(cafile=cert_file)
        with urllib.request.urlopen(
            f"https://localhost:{gw.port}/api/v1/queues", context=ctx, timeout=5
        ) as resp:
            assert resp.status == 200
            assert "queues" in _json.loads(resp.read())
    finally:
        gw.stop()
