"""Auth chain + permission mapping (common/auth/{multi,basic,oidc,
permissions}.go) and its enforcement on the gRPC surface."""

import time

import grpc
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.services import auth as A
from armada_tpu.services.auth import (
    AuthError,
    Authorizer,
    BasicAuth,
    MultiAuth,
    PermissionDenied,
    Principal,
    QueuePermission,
    TokenAuth,
    make_token,
)
from armada_tpu.services.grpc_api import ApiClient, ApiServer
from armada_tpu.services.queryapi import QueryApi
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService

SECRET = "test-signing-secret"


def test_basic_auth():
    auth = BasicAuth({"alice": {"password": "pw", "groups": ["devs"]}})
    import base64

    md = {"authorization": "Basic " + base64.b64encode(b"alice:pw").decode()}
    p = auth.authenticate(md)
    assert p.name == "alice" and "devs" in p.groups
    bad = {"authorization": "Basic " + base64.b64encode(b"alice:no").decode()}
    with pytest.raises(AuthError):
        auth.authenticate(bad)
    assert auth.authenticate({}) is None  # wrong shape: pass to next


def test_token_auth_roundtrip_and_expiry():
    auth = TokenAuth(SECRET)
    token = make_token(SECRET, "bob", groups=["ops"], exp=time.time() + 60)
    p = auth.authenticate({"authorization": f"Bearer {token}"})
    assert p.name == "bob" and "ops" in p.groups
    expired = make_token(SECRET, "bob", exp=time.time() - 1)
    with pytest.raises(AuthError):
        auth.authenticate({"authorization": f"Bearer {expired}"})
    forged = token[:-4] + "AAAA"
    with pytest.raises(AuthError):
        auth.authenticate({"authorization": f"Bearer {forged}"})


def test_multi_auth_first_match_wins():
    multi = MultiAuth(
        [
            BasicAuth({"alice": {"password": "pw"}}),
            TokenAuth(SECRET),
        ]
    )
    token = make_token(SECRET, "bob")
    assert multi.authenticate({"authorization": f"Bearer {token}"}).name == "bob"
    with pytest.raises(AuthError):
        multi.authenticate({})  # nothing matches, nothing anonymous


def test_authorizer_global_and_queue():
    az = Authorizer(permission_groups={A.SUBMIT_ANY_JOBS: ["submitters"]})
    admin = Principal("root", frozenset({"admin"}))
    submitter = Principal("s", frozenset({"submitters"}))
    rando = Principal("r", frozenset())
    az.authorize_global(admin, A.CREATE_QUEUE)
    az.authorize_global(submitter, A.SUBMIT_ANY_JOBS)
    with pytest.raises(PermissionDenied):
        az.authorize_global(rando, A.SUBMIT_ANY_JOBS)

    class Q:
        owners = ("owner-user",)
        permissions = (QueuePermission(subjects=("teammates",), verbs=("submit",)),)
        spec = QueueSpec("team")

    az.authorize_queue(Principal("owner-user"), "submit", Q(), A.SUBMIT_ANY_JOBS)
    az.authorize_queue(
        Principal("t", frozenset({"teammates"})), "submit", Q(), A.SUBMIT_ANY_JOBS
    )
    with pytest.raises(PermissionDenied):
        az.authorize_queue(
            Principal("t", frozenset({"teammates"})), "cancel", Q(),
            A.CANCEL_ANY_JOBS,
        )
    with pytest.raises(PermissionDenied):
        az.authorize_queue(rando, "submit", Q(), A.SUBMIT_ANY_JOBS)


@pytest.fixture()
def served():
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    query = QueryApi(sched.jobdb)
    server = ApiServer(
        submit,
        sched,
        query,
        log,
        auth=MultiAuth([TokenAuth(SECRET)]),
        authorizer=Authorizer(
            permission_groups={
                A.SUBMIT_ANY_JOBS: ["submitters"],
                A.CREATE_QUEUE: ["queue-admins"],
                A.EXECUTE_JOBS: ["executors"],
            }
        ),
    )
    grpc_server, port = server.serve(port=0)
    yield submit, port
    grpc_server.stop(0)


def _client(port, **kw):
    return ApiClient(f"127.0.0.1:{port}", **kw)


def test_unauthenticated_writes_rejected(served):
    submit, port = served
    anon = _client(port)
    with pytest.raises(grpc.RpcError) as e:
        anon.submit_jobs("team", "s", [{"id": "x", "requests": {"cpu": "1"}}])
    assert e.value.code() == grpc.StatusCode.UNAUTHENTICATED
    with pytest.raises(grpc.RpcError) as e:
        anon._call("CreateQueue", {"name": "team"})
    assert e.value.code() == grpc.StatusCode.UNAUTHENTICATED


def test_permission_denied_without_grant(served):
    submit, port = served
    peon = _client(port, token=make_token(SECRET, "peon"))
    with pytest.raises(grpc.RpcError) as e:
        peon._call("CreateQueue", {"name": "team"})
    assert e.value.code() == grpc.StatusCode.PERMISSION_DENIED


def test_authorized_flow_and_queue_grants(served):
    submit, port = served
    admin = _client(port, token=make_token(SECRET, "root", groups=["admin"]))
    admin._call("CreateQueue", {"name": "team"})
    # Grant alice queue-level submit directly in the registry.
    q = submit.get_queue("team")
    q.permissions = (QueuePermission(subjects=("alice",), verbs=("submit",)),)

    alice = _client(port, token=make_token(SECRET, "alice"))
    ids = alice.submit_jobs(
        "team", "s", [{"id": "j1", "requests": {"cpu": "1", "memory": "1Gi"}}]
    )
    assert ids == ["j1"]
    with pytest.raises(grpc.RpcError) as e:
        alice.cancel_jobs("team", "s", ["j1"])  # no cancel grant
    assert e.value.code() == grpc.StatusCode.PERMISSION_DENIED

    submitter = _client(
        port, token=make_token(SECRET, "subby", groups=["submitters"])
    )
    ids = submitter.submit_jobs(
        "team", "s", [{"id": "j2", "requests": {"cpu": "1", "memory": "1Gi"}}]
    )
    assert ids == ["j2"]
