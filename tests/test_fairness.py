"""Fairness observatory (armada_tpu/observe/fairness.py).

The per-round share ledger must be internally consistent (delivered
shares sum to the pool allocation) with entitlements bit-exact against
the solver/drf.py water-filling oracle; every round preemption must
carry exactly one attributed aggressor (and its attribution must reach
the job timeline — no preemption from any producer may land as
"unknown"); the starvation detector must fire for a weight-starved
queue and stay silent in a balanced control run; the offline
tools/fairness_report.py scorecard over the recorded `.atrace` of the
same sim must equal the live one; a tampered recorded fairness block
must trip the replayer's `fairness_ledger` divergence; and the drf.py
numpy water-filling must bit-match the kernel's jitted fixed-point on
its edge cases (zero-weight queue, all-demand-below-entitlement,
10-iteration cap, zero-total pool).
"""

import json
import os
import sys

import numpy as np
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.observe.fairness import (
    FairnessTracker,
    aggregate_scorecard,
    jain_index,
)
from armada_tpu.solver import drf

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, os.path.join(REPO, "tools"))

CONTENTION_CFG = dict(
    priority_classes={
        "low": PriorityClass("low", 1000, preemptible=True),
        "pinned": PriorityClass("pinned", 30000, preemptible=False),
    },
    default_priority_class="low",
    protected_fraction_of_fair_share=0.5,
)


def contention_sim(*, backend="kernel", trace_path=None, starved=False,
                   max_time=300.0):
    """Deterministic 3-queue contention sim on a 2-node fleet: qa fills
    the pool first, qb contends from t=30 (forcing DRF rebalance
    preemptions), qc either competes at equal weight (balanced control)
    or at a tiny weight behind non-preemptible hogs (starved=True)."""
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    long = ShiftedExponential(minimum=500.0)
    hog_class = "pinned" if starved else "low"
    queues = (
        QueueSpecSim(
            name="qa",
            job_templates=(
                JobTemplate(id="a", number=4, cpu="4",
                            priority_class=hog_class, runtime=long),
            ),
        ),
        QueueSpecSim(
            name="qb",
            job_templates=(
                JobTemplate(id="b", number=4, cpu="4", submit_time=30.0,
                            priority_class=hog_class, runtime=long),
            ),
        ),
        QueueSpecSim(
            name="qc",
            # weight = 1/priority_factor: 20.0 → weight 0.05, the
            # weight-starved victim.
            priority_factor=20.0 if starved else 1.0,
            job_templates=(
                JobTemplate(id="c", number=4, cpu="4", submit_time=60.0,
                            runtime=long),
            ),
        ),
    )
    return Simulator(
        [ClusterSpec(name="c", node_templates=(NodeTemplate(count=2, cpu="8"),))],
        WorkloadSpec(queues=queues),
        config=SchedulingConfig(**CONTENTION_CFG),
        backend=backend,
        cycle_interval=10.0,
        max_time=max_time,
        trace_path=trace_path,
    )


def tap_fairness(sim):
    """Collect every decorated fairness block the scheduler feeds the
    tracker, in round order."""
    blocks = []
    orig = sim.scheduler.fairness.observe_round

    def tap(pool, fairness, **kw):
        blocks.append(
            {
                "ledger": json.loads(json.dumps(fairness["ledger"])),
                "preemptions": json.loads(
                    json.dumps(fairness["preemptions"])
                ),
            }
        )
        return orig(pool, fairness, **kw)

    sim.scheduler.fairness.observe_round = tap
    return blocks


# ---------------------------------------------------------------------------
# drf.py water-filling edge cases vs the kernel's jitted fixed-point
# (satellite: bit-parity numpy vs JAX).
# ---------------------------------------------------------------------------


def _jax_fair_shares(weights, demand_costs, total_is_zero):
    import jax.numpy as jnp

    from armada_tpu.solver.kernel import _fair_shares

    fs, capped, uncapped = _fair_shares(
        jnp.asarray(np.asarray(weights, np.float64)),
        jnp.asarray(np.asarray(demand_costs, np.float64)),
        jnp.asarray(bool(total_is_zero)),
    )
    return np.asarray(fs), np.asarray(capped), np.asarray(uncapped)


def _assert_waterfill_parity(weights, demand_costs, total_is_zero=False,
                             uncapped_ulp=0):
    """fair_share and capped must ALWAYS be bitwise identical (they are
    recorded decision keys the replay gate pins). uncapped accumulates
    `unc + share*(unallocated - spare)` once per iteration, which XLA
    legally contracts into an FMA — on ladders deep enough to run many
    iterations the jitted result can sit 1 ULP off any pure-numpy
    evaluation, so those cases pass `uncapped_ulp` (production parity
    of the recorded uncapped stream is still asserted bit-exact by the
    kernel-parity and replay suites on real rounds)."""
    names = [f"q{i:02d}" for i in range(len(weights))]
    want = drf.update_fair_shares(
        names, np.asarray(weights, np.float64),
        np.asarray(demand_costs, np.float64), total_is_zero,
    )
    got = _jax_fair_shares(weights, demand_costs, total_is_zero)
    for name, w, g in zip(("fair_share", "capped", "uncapped"), want, got):
        if name == "uncapped" and uncapped_ulp:
            tol = uncapped_ulp * np.spacing(
                np.maximum(np.abs(w), np.abs(g))
            )
            assert np.all(np.abs(w - g) <= tol), (
                f"uncapped beyond {uncapped_ulp} ULP: numpy {w} != jax {g}"
            )
            continue
        assert np.array_equal(w, g), (
            f"{name}: numpy {w} != jax {g} for weights={weights} "
            f"demand={demand_costs} total_is_zero={total_is_zero}"
        )


def test_waterfill_zero_weight_queue():
    # A zero-weight queue holds no entitlement and releases nothing.
    _assert_waterfill_parity([1.0, 0.0, 2.0], [0.5, 0.5, 0.5])
    _assert_waterfill_parity([1.0, 0.0, 2.0], [0.1, 0.9, 0.05])


def test_waterfill_all_demand_below_entitlement():
    # Everyone achieves in iteration 1; the loop must terminate on
    # total_weight == 0 with capped == demand for every queue.
    weights = [1.0, 1.0, 1.0, 1.0]
    demand = [0.01, 0.02, 0.03, 0.04]
    _assert_waterfill_parity(weights, demand)
    names = [f"q{i:02d}" for i in range(4)]
    _, capped, _ = drf.update_fair_shares(
        names, np.asarray(weights), np.asarray(demand), False
    )
    assert np.array_equal(capped, np.asarray(demand))


def test_waterfill_iteration_cap_hit(monkeypatch):
    """A demand ladder that still has >1% unallocated after 10
    iterations: the numpy loop and the jitted while_loop must cut at
    the same iteration and agree bitwise.

    Construction: strongly dominant power-of-4 weights (weight sums are
    sums of distinct powers of two — exact in any accumulation order,
    so numpy's name-ordered loop and the vectorized kernel cannot
    drift) with demands chosen so exactly ONE queue achieves per
    iteration, releasing ~3/4 of the remaining pool each time:
    unallocated decays ~0.75^k and is still > 0.01 at iteration 10."""
    Q = 12
    weights = 4.0 ** np.arange(Q - 1, -1, -1)
    names = [f"q{i:02d}" for i in range(Q)]
    demand = np.full(Q, 2.0)
    capped = np.zeros(Q)
    achieved = np.zeros(Q, bool)
    unalloc = 1.0
    for it in range(Q):
        tw = weights[~achieved].sum()
        inc = np.where(achieved, 0.0, (weights / tw) * unalloc)
        capped = capped + inc
        # Queue `it` (the dominant unachieved one) achieves exactly at
        # this iteration: demand just above its PREVIOUS capped value.
        demand[it] = capped[it] - inc[it] + 1e-6
        spare = capped[it] - demand[it]
        capped[it] = demand[it]
        achieved[it] = True
        unalloc = spare
    # Prove the 10-iteration cap binds: one extra iteration changes the
    # answer (i.e. the loop exited on the cap, not on convergence).
    _, capped10, _ = drf.update_fair_shares(names, weights, demand, False)
    monkeypatch.setattr(drf, "MAX_ITERATIONS", 11)
    _, capped11, _ = drf.update_fair_shares(names, weights, demand, False)
    monkeypatch.setattr(drf, "MAX_ITERATIONS", 10)
    assert not np.array_equal(capped10, capped11), (
        "ladder did not hit the 10-iteration cap"
    )
    _assert_waterfill_parity(weights, demand, uncapped_ulp=4)


def test_waterfill_zero_total_pool():
    # Zero-resource pool: every demand share reads 1.0
    # (scheduling.go:257-259) — nobody achieves, shares stay pure
    # weight ratios.
    _assert_waterfill_parity([1.0, 3.0], [0.0, 0.0], total_is_zero=True)
    names = ["a", "b"]
    fs, capped, _ = drf.update_fair_shares(
        names, np.asarray([1.0, 3.0]), np.asarray([0.0, 0.0]), True
    )
    assert np.allclose(capped, fs)


def test_jain_index_bounds():
    assert jain_index([]) == 1.0
    assert jain_index([0.5, 0.5, 0.5]) == pytest.approx(1.0)
    lopsided = jain_index([1.0, 0.0, 0.0])
    assert lopsided == pytest.approx(1.0 / 3.0)


# ---------------------------------------------------------------------------
# The deterministic 3-queue contention sim: ledger consistency,
# oracle-exact entitlements, one aggressor per preemption, offline
# identity (the acceptance scenario).
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def contention_run(tmp_path_factory):
    trace_path = str(
        tmp_path_factory.mktemp("fairness") / "contention.atrace"
    )
    sim = contention_sim(backend="kernel", trace_path=trace_path)
    blocks = tap_fairness(sim)
    result = sim.run()
    return sim, result, blocks, trace_path


def test_ledger_consistency_and_oracle_entitlement(contention_run):
    from armada_tpu.trace import load_trace

    sim, result, blocks, trace_path = contention_run
    assert result.preemptions > 0, "contention sim produced no preemptions"
    trace = load_trace(trace_path)
    rounds_with_preemptions = 0
    for rec in trace.rounds:
        dev = rec.device_round()
        decisions = rec.decisions()
        block = rec.raw["fairness"]
        J, Q = rec.num_jobs, rec.num_queues
        ledger, preempts = block["ledger"], block["preemptions"]
        # Delivered shares sum to the pool allocation: the per-queue
        # delivered vectors add up to exactly the resources of every
        # placed job.
        jq = np.asarray(dev.job_queue)[:J]
        placed = (np.asarray(decisions["assigned_node"])[:J] >= 0) & (jq >= 0)
        want_total = (
            np.asarray(dev.job_req, np.float64)[:J][placed].sum(axis=0)
            if placed.any()
            else np.zeros(dev.job_req.shape[1])
        )
        got_total = np.asarray(ledger["delivered_total"])
        assert np.array_equal(want_total, got_total)
        per_queue = np.asarray(
            [row["delivered"] for row in ledger["queues"]]
        ).sum(axis=0)
        assert np.array_equal(per_queue, got_total)
        # Entitlement matches the drf.py oracle bit-exactly: recompute
        # the water-filling from the round's own constrained demand.
        constrained = np.minimum(
            np.asarray(dev.queue_demand_pc, np.float64),
            np.asarray(dev.queue_pc_limit, np.float64),
        ).sum(axis=1)
        demand_costs = drf.unweighted_cost(
            constrained, dev.total_resources, dev.drf_multipliers
        )
        names = (rec.raw.get("ids") or {}).get("queues") or [
            f"q{i}" for i in range(Q)
        ]
        _, capped, uncapped = drf.update_fair_shares(
            list(names),
            np.asarray(dev.queue_weight)[:Q],
            demand_costs[:Q],
            bool((np.asarray(dev.total_resources) == 0).all()),
        )
        for q, row in enumerate(ledger["queues"]):
            assert row["entitlement"] == capped[q]
            assert row["uncapped"] == uncapped[q]
        # Every preemption in the round has exactly one attributed
        # aggressor.
        victims = np.flatnonzero(
            np.asarray(decisions["preempted_mask"], bool)[:J]
        )
        assert len(preempts) == len(victims)
        assert sorted(p["job"] for p in preempts) == sorted(
            int(v) for v in victims
        )
        for p in preempts:
            assert p["mechanism"] in ("fairness", "urgency")
            assert p["aggressor_queue"] >= 0 or p["aggressor_job"] >= 0
        rounds_with_preemptions += bool(len(preempts))
    assert rounds_with_preemptions > 0


def test_offline_scorecard_matches_live_sim(contention_run, capsys):
    """The acceptance identity: tools/fairness_report.py over the
    recorded .atrace computes the exact scorecard the live run served
    (same doubles — both sides are the canonical ledger, decorated with
    the same queue-name vocabulary)."""
    import fairness_report

    sim, _result, blocks, trace_path = contention_run
    live = aggregate_scorecard(blocks)
    rc = fairness_report.main(["--json", trace_path])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip())
    offline = doc["scorecard"]
    live = json.loads(json.dumps(live))
    assert offline == live
    # And the rendered form mentions every queue.
    rc = fairness_report.main([trace_path])
    out = capsys.readouterr().out
    assert rc == 0
    for q in ("qa", "qb", "qc"):
        assert q in out


def test_preemptions_attributed_in_events_and_timeline(contention_run):
    """Round preemption events carry their aggressor attribution into
    the job timeline ("preempted by queue B ... under DRF rebalance")."""
    sim, result, _blocks, _path = contention_run
    preempted_entries = []
    for jid, j in sim.scheduler.timeline._jobs.items():
        for _ts, kind, detail in j.entries:
            if kind == "preempted":
                preempted_entries.append((jid, detail))
    assert preempted_entries
    for jid, detail in preempted_entries:
        assert detail and detail != "unknown", (jid, detail)
        assert "preempted by queue " in detail or "scheduler round" in detail
        assert "under " in detail


def test_starvation_alert_fires_for_weight_starved_queue():
    sim = contention_sim(backend="oracle", starved=True, max_time=250.0)
    sim.run()
    tracker = sim.scheduler.fairness
    snap = tracker.snapshot()
    alert_queues = {a["queue"] for a in snap["alerts"]}
    assert "qc" in alert_queues, snap["alerts"]
    doc = snap["pools"]["default"]
    rows = {r["queue"]: r for r in doc["ledger"]["queues"]}
    assert rows["qc"]["starved"]
    assert rows["qc"]["starved_rounds"] >= tracker.k_rounds
    assert rows["qc"]["regret"] > 0
    # The triple separates starved from capped-by-demand: qc's demand
    # exceeds what it was delivered.
    assert rows["qc"]["demand_share"] > rows["qc"]["delivered_share"]


def test_starvation_silent_in_balanced_control():
    """The control run: the same 3 queues with demand that fits their
    entitlements — every queue is delivered its share, no starvation
    streak ever arms, the alert stays silent."""
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    sim = Simulator(
        [ClusterSpec(name="c", node_templates=(NodeTemplate(count=2, cpu="8"),))],
        WorkloadSpec(
            queues=tuple(
                QueuesSpec
                for QueuesSpec in (
                    QueueSpecSim(
                        name=q,
                        job_templates=(
                            JobTemplate(
                                id="j", number=1, cpu="4",
                                submit_time=float(i * 10),
                                runtime=ShiftedExponential(minimum=400.0),
                            ),
                        ),
                    )
                    for i, q in enumerate(("qa", "qb", "qc"))
                )
            )
        ),
        config=SchedulingConfig(**CONTENTION_CFG),
        backend="oracle",
        cycle_interval=10.0,
        max_time=200.0,
    )
    sim.run()
    snap = sim.scheduler.fairness.snapshot()
    assert snap["alerts"] == []
    doc = snap["pools"]["default"]
    for row in doc["ledger"]["queues"]:
        assert not row.get("alerting"), row
        assert row["regret"] == pytest.approx(0.0, abs=1e-9), row


def test_fair_share_triple_metrics_exported():
    """Satellite: uncapped entitlement + demand share export alongside
    the existing demand-capped scheduler_queue_fair_share."""
    from armada_tpu.services.metrics import (
        HAVE_PROMETHEUS,
        SchedulerMetrics,
    )

    if not HAVE_PROMETHEUS:
        pytest.skip("prometheus_client unavailable")
    sim = contention_sim(backend="oracle", max_time=150.0)
    m = SchedulerMetrics()
    sim.scheduler.attach_metrics(m)
    sim.run()
    body = m.render().decode()
    doc = sim.scheduler.fairness.latest("default")
    rows = {r["queue"]: r for r in doc["ledger"]["queues"]}
    for family in (
        "scheduler_queue_fair_share_uncapped",
        "scheduler_queue_demand_share",
        "scheduler_fairness_regret",
        "scheduler_fairness_starved_rounds",
    ):
        for q in rows:
            assert f'{family}{{pool="default",queue="{q}"}}' in body, family
    assert 'scheduler_fairness_jain{pool="default"}' in body
    # The gauge values mirror the tracker's latest ledger.
    for line in body.splitlines():
        if line.startswith('scheduler_queue_demand_share{pool="default"'):
            q = line.split('queue="')[1].split('"')[0]
            assert float(line.rsplit(" ", 1)[1]) == pytest.approx(
                rows[q]["demand_share"]
            )


def test_replayer_trips_on_tampered_fairness_block(contention_run):
    from armada_tpu.trace import load_trace, replay_trace

    _sim, _result, _blocks, trace_path = contention_run
    clean = replay_trace(load_trace(trace_path), solvers=("LOCAL",),
                         flag_retraces=False)
    assert clean["ok"], clean["divergences"]
    tampered = load_trace(trace_path)
    victim = next(r for r in tampered.rounds if r.raw.get("fairness"))
    victim.raw["fairness"]["ledger"]["queues"][0]["delivered_share"] += 0.25
    report = replay_trace(tampered, solvers=("LOCAL",), flag_retraces=False)
    assert report["divergences"].get("fairness_ledger", 0) >= 1, report


def test_no_unknown_preemption_reason_in_chaos_sim(tmp_path):
    """Satellite: under chaos (executor crash mid-run) plus contention
    preemptions plus a staged drain, NO JobRunPreempted from any
    producer lands in the timeline without attribution."""
    from armada_tpu.services.chaos import FaultPlan, FaultSpec

    sim = contention_sim(backend="oracle", max_time=400.0)
    sim.fault_plan = None  # the plan below rides the executors directly
    plan = FaultPlan(
        [FaultSpec("executor_crash", "c", start=110.0, duration=30.0)]
    )
    for ex in sim.executors:
        ex.fault_plan = plan
    # A staged drain mid-run exercises the drain-preemption producer.
    drained = {"started": False}
    orig_cycle = sim.scheduler.cycle

    def cycle(now=None):
        if not drained["started"] and (now or 0) >= 80.0:
            drained["started"] = True
            sim.scheduler.drains.start("c", deadline_s=20.0)
        return orig_cycle(now=now)

    sim.scheduler.cycle = cycle
    result = sim.run()
    assert result.preemptions > 0
    preempted = []
    for jid, j in sim.scheduler.timeline._jobs.items():
        for _ts, kind, detail in j.entries:
            if kind == "preempted":
                preempted.append((jid, detail))
    assert preempted
    unknown = [(jid, d) for jid, d in preempted if not d or d == "unknown"]
    assert not unknown, unknown


def test_fairness_tracker_multiwindow_needs_both_conditions():
    """Both conditions must gate independently: a short starved burst
    under K rounds never alerts (fast fails); a fresh K-streak right
    after healthy history stays silent too (slow fails: under half of
    the 4K window is starved); only sustained starvation fires; and
    recovery clears the alert state."""
    tracker = FairnessTracker(k_rounds=3)
    assert tracker.window == 12

    def block(starved):
        return {
            "ledger": {
                "queues": [
                    {
                        "queue": "q",
                        "weight": 1.0,
                        "fair_share": 0.5,
                        "entitlement": 0.5,
                        "uncapped": 0.5,
                        "demand_share": 0.8,
                        "delivered_share": 0.1 if starved else 0.5,
                        "regret": 0.4 if starved else 0.0,
                        "starved": starved,
                        "delivered": [],
                    }
                ],
                "jain": 1.0,
                "max_regret": 0.4 if starved else 0.0,
                "delivered_total": [],
            },
            "preemptions": [],
        }

    for i in range(2):  # 2 < K: fast condition fails, silent
        doc = tracker.observe_round("p", block(True), now=float(i))
    assert not doc["alerts"]
    doc = tracker.observe_round("p", block(False), now=2.0)
    assert doc["ledger"]["queues"][0]["starved_rounds"] == 0
    for i in range(3):  # a fresh K-streak: fast passes...
        doc = tracker.observe_round("p", block(True), now=3.0 + i)
    # ...but only 5 of the 12-round window is starved: slow fails,
    # still silent — the condition the vacuous 2K window could never
    # exercise.
    assert doc["ledger"]["queues"][0]["starved_rounds"] == 3
    assert not doc["alerts"]
    # Starvation sustains: once half the window's capacity is starved
    # (6 of 12), the alert fires.
    doc = tracker.observe_round("p", block(True), now=6.0)
    assert doc["alerts"] and doc["alerts"][0]["queue"] == "q"
    assert tracker.snapshot()["alerts"]
    doc = tracker.observe_round("p", block(False), now=7.0)
    assert not doc["alerts"]
    assert not tracker.snapshot()["alerts"]


def test_fairness_tracker_clears_state_for_vanished_queue():
    """A queue that leaves the round (drained/deleted — the snapshot
    only carries queues with jobs) stops starving by definition: its
    alert and streak clear instead of paging forever."""
    tracker = FairnessTracker(k_rounds=2)  # window 8: fires at 4 starved

    def block(queues):
        return {
            "ledger": {
                "queues": [
                    {
                        "queue": q,
                        "weight": 1.0,
                        "fair_share": 0.5,
                        "entitlement": 0.5,
                        "uncapped": 0.5,
                        "demand_share": 0.8,
                        "delivered_share": 0.1,
                        "regret": 0.4,
                        "starved": True,
                        "delivered": [],
                    }
                    for q in queues
                ],
                "jain": 1.0,
                "max_regret": 0.4,
                "delivered_total": [],
            },
            "preemptions": [],
        }

    for i in range(4):
        tracker.observe_round("p", block(["doomed"]), now=float(i))
    assert tracker.snapshot()["alerts"]
    # The queue disappears from the round entirely.
    tracker.observe_round("p", block(["other"]), now=4.0)
    alerts = tracker.snapshot()["alerts"]
    assert all(a["queue"] != "doomed" for a in alerts), alerts


def test_fairness_report_rpc_lookout_and_cli(capsys):
    """FairnessReport over a real gRPC socket (raw client + `armadactl
    fairness` rendering) and GET /api/fairness serve the tracker's
    document; a pool with no rounds is NOT_FOUND."""
    import urllib.request

    import grpc

    from armada_tpu.services.grpc_api import ApiClient, ApiServer
    from armada_tpu.services.lookout_http import LookoutHttpServer

    sim = contention_sim(backend="oracle", max_time=150.0)
    sim.run()
    api = ApiServer(sim.submit, sim.scheduler, None, sim.log)
    server, port = api.serve(0)
    try:
        client = ApiClient(f"127.0.0.1:{port}")
        doc = client.fairness_report()
        assert "default" in doc["pools"]
        rows = {
            r["queue"]: r
            for r in doc["pools"]["default"]["ledger"]["queues"]
        }
        assert set(rows) == {"qa", "qb", "qc"}
        scoped = client.fairness_report(pool="default")
        assert set(scoped["pools"]) == {"default"}
        with pytest.raises(grpc.RpcError) as err:
            client.fairness_report(pool="nope")
        assert err.value.code() == grpc.StatusCode.NOT_FOUND
        from armada_tpu.clients.cli import main as cli_main

        cli_main(["--server", f"127.0.0.1:{port}", "fairness"])
        out = capsys.readouterr().out
        assert "pool default" in out and "jain" in out
        for q in ("qa", "qb", "qc"):
            assert f"queue {q}" in out
        cli_main(["--server", f"127.0.0.1:{port}", "fairness", "--json"])
        parsed = json.loads(capsys.readouterr().out)
        assert "default" in parsed["pools"]
    finally:
        server.stop(None)
    http = LookoutHttpServer(None, sim.scheduler, None, port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/api/fairness"
        ) as resp:
            doc = json.loads(resp.read())
        assert "default" in doc["pools"]
    finally:
        http.stop()


def test_whatif_plan_reports_fairness_delta():
    """A drain plan reports which queues pay: Plan.fairness_delta
    carries per-queue baseline vs planned delivered shares."""
    from armada_tpu.whatif import WhatIfService

    sim = contention_sim(backend="oracle", max_time=150.0)
    wi = WhatIfService(sim.scheduler)
    sim.whatif = wi
    sim.scheduler.attach_whatif(wi)
    sim.run()
    plan = wi.plan_drain("c", rounds=3, deadline_s=0.0)
    delta = plan.fairness_delta
    assert delta, "plan carried no fairness delta"
    assert set(delta["queues"]) >= {"qa", "qb"}
    for row in delta["queues"].values():
        assert {"baseline_delivered", "planned_delivered",
                "delta_delivered"} <= row.keys()
    assert "payers" in delta and "planned_jain" in delta
    assert "fairness_delta" in plan.to_dict()
    # Draining the only executor zeroes delivered shares: every queue
    # that held capacity pays.
    assert delta["payers"], delta
    rendered = plan.render()
    assert "who pays" in rendered
