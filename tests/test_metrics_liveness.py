"""Metric liveness: every labeled metric family in SchedulerMetrics
gets samples from a short sim (dead/never-set families fail loudly), and
serve_metrics binds ephemeral ports."""

import urllib.request

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.services.metrics import (
    HAVE_PROMETHEUS,
    SchedulerMetrics,
    serve_metrics,
)

pytestmark = pytest.mark.skipif(
    not HAVE_PROMETHEUS, reason="prometheus_client unavailable"
)


def test_serve_metrics_port_zero_returns_bound_port():
    """Port 0 binds an ephemeral port and returns it, so tests stop
    hard-coding (and racing for) fixed ports; the text endpoint serves
    the exposition format."""
    m = SchedulerMetrics()
    server, port = serve_metrics(m, 0)
    try:
        assert port > 0
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ) as resp:
            body = resp.read()
            assert resp.headers["Content-Type"].startswith("text/plain")
        # Exposition text names every registered family, including the
        # job-journey additions.
        for family in (
            b"scheduler_job_rounds_to_schedule",
            b"scheduler_job_queue_wait_seconds",
            b"scheduler_unschedulable_reason_total",
            b"scheduler_cycle_seconds",
        ):
            assert family in body, family
    finally:
        server.shutdown()


# Labeled families legitimately silent in this test's sims — each needs a
# mode the short oracle run does not exercise. The test asserts these stay
# sample-FREE here, so an entry whose feature lands in the sim path must
# be removed (the list cannot rot into hiding dead metrics).
EXEMPT_LABELED = {
    # market mode only
    "scheduler_queue_idealised_value",
    "scheduler_queue_realised_value",
    "scheduler_indicative_gang_price",
    "scheduler_indicative_gang_schedulable",
    # sharded-solve (mesh) only
    "scheduler_solve_mesh_extent",
    "scheduler_solve_collective_sites",
    "scheduler_solve_collective_bytes",
    "scheduler_shard_solve_seconds",
    # partition / fencing chaos only (tests/test_netchaos.py covers)
    "scheduler_fence_rejections",
    "scheduler_executor_fence",
    "scheduler_executor_reconnects",
    "scheduler_anti_entropy_resolutions",
    # solver-fault chaos only (tests/test_chaos.py solver soak subset and
    # tests/test_solver_selfheal.py cover; scheduler_solver_rung_state is
    # NOT exempt — the ladder gauge refreshes every round, faults or not)
    "scheduler_round_rejected",
    "scheduler_solver_failover",
    # replay gate only (tests/test_trace_replay.py covers)
    "scheduler_trace_replay_divergences",
    # round-deadline truncation only (tests/test_round_deadline.py)
    "scheduler_rounds_truncated",
    # preemption rounds only (tests/test_fill.py etc. cover)
    "scheduler_jobs_preempted",
    "scheduler_jobs_preempted_by_type",
    # preemption rounds only (tests/test_fairness.py covers attribution)
    "scheduler_preemption_attributed",
    # device-resident buffer corruption only — never ticks in a healthy
    # run by design (tests/test_residency.py covers drift detection;
    # scheduler_snapshot_mode_total is NOT exempt — every round counts
    # the path that carried it)
    "scheduler_resident_drift",
}

# Front-door families are exempt from the sim sweep BY PREFIX (the sim
# publishes directly; the front door is off) — every one of them is
# liveness-asserted instead by test_frontdoor_families_live_after_short_soak
# below, which auto-covers families added later.
FRONTDOOR_PREFIX = "frontdoor_"

# UNLABELED families legitimately untouched by this test's sims — each
# needs a mode the short oracle run does not exercise. Same anti-rot
# contract as EXEMPT_LABELED: the test asserts these stay UNTOUCHED
# here, so an entry whose feature lands in the sim path must be removed.
EXEMPT_UNLABELED = {
    # partition/heal chaos only (tests/test_netchaos.py covers)
    "scheduler_executor_reconnect_seconds",
    # sharded-solve (mesh) only
    "scheduler_solve_dcn_scalars_per_select",
}


def _instrument_unlabeled(m: SchedulerMetrics) -> dict:
    """Wrap every UNLABELED metric's mutators (inc/dec/set/observe) with
    counting shims, returning {family: call_count}. Unlabeled metrics
    always render a zero-valued sample, so rendered output cannot
    distinguish 'set to 0 every cycle' from 'registered and never
    wired' (exactly how scheduler_cycle_seconds sat dead in sims for
    four PRs while the ControlPlane loop observed it) — counting the
    mutator CALLS can."""
    touched: dict = {}
    for attr, metric in vars(m).items():
        if getattr(metric, "_labelnames", None):
            continue
        collect = getattr(metric, "collect", None)
        if collect is None:
            continue
        family = next(iter(collect())).name
        touched.setdefault(family, 0)
        for method_name in ("inc", "dec", "set", "observe"):
            orig = getattr(metric, method_name, None)
            if orig is None:
                continue

            def shim(*a, _orig=orig, _f=family, _t=touched, **k):
                _t[_f] += 1
                return _orig(*a, **k)

            setattr(metric, method_name, shim)
    return touched


def _labeled_sample_counts(m: SchedulerMetrics) -> dict:
    """family name -> sample count, for every LABELED metric attribute
    (unlabeled metrics always render a zero-valued sample, so presence
    tells nothing; labeled ones render samples only once .labels() was
    actually exercised — exactly the dead-wiring signal)."""
    counts = {}
    for attr, metric in vars(m).items():
        labelnames = getattr(metric, "_labelnames", None)
        if not labelnames:
            continue
        for family in metric.collect():
            counts[family.name] = counts.get(family.name, 0) + len(
                family.samples
            )
    return counts


def test_every_labeled_family_live_after_short_sim(tmp_path):
    """A short oracle sim (fitting jobs + a can-never-fit job for the
    unschedulable path + an attached flight recorder) must put samples
    in every labeled family except the explicitly exempted mode-gated
    ones — catching families that are registered but never set (the
    seed shipped scheduler_snapshot_build_seconds exactly that way)."""
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )
    from armada_tpu.trace import TraceRecorder

    sim = Simulator(
        [ClusterSpec(name="c", node_templates=(NodeTemplate(count=4, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    name="qa",
                    job_templates=(
                        JobTemplate(
                            id="fit", number=6, cpu="2",
                            # t>0: time-in-state observation treats a
                            # zero previous-state timestamp as unknown.
                            submit_time=5.0,
                            runtime=ShiftedExponential(minimum=20.0),
                        ),
                    ),
                ),
                QueueSpecSim(
                    name="qb",
                    job_templates=(
                        # Never fits: every round reports it unschedulable.
                        JobTemplate(id="huge", number=1, cpu="999"),
                    ),
                ),
            )
        ),
        backend="oracle",
        cycle_interval=10.0,
        max_time=200.0,
        trace_path=str(tmp_path / "liveness.atrace"),
    )
    m = SchedulerMetrics()
    touched = _instrument_unlabeled(m)
    sim.scheduler.attach_metrics(m)
    # SLO layer (services/slo.py): cycle latency + first-lease queue
    # wait feed the tracker on the virtual clock; burn/compliance
    # gauges refresh per cycle.
    from armada_tpu.services.slo import SLOTracker

    sim.scheduler.attach_slo(SLOTracker(metrics=m))
    sim.run()
    # Round-observatory wiring (scheduler._note_transfer): the oracle
    # sim never runs the kernel's device solve, so drive the wiring
    # itself with ledger/compile payloads of the shape _solve emits —
    # the transfer gauges/counters and xla counters prove they are
    # connected (the _note_solve_profile pattern below).
    sim.scheduler._note_transfer(
        "default",
        {"bytes_up": 4096, "arrays_up": 61, "bytes_down": 512,
         "arrays_down": 9, "donated_bytes": 2048, "donated_buffers": 12},
        {"traces": 3, "compiles": 1, "compile_seconds": 0.5,
         "cache_hits": 1, "cache_misses": 1},
    )
    # The solve-profile wiring (scheduler._note_solve_profile) is fed by
    # the kernel's host-driven driver; exercise the wiring itself with a
    # profile dict of the shape solver/kernel.solve_round emits so the
    # profile gauges/histograms prove they are connected without a jit
    # compile in this tier-1 test.
    sim.scheduler._note_solve_profile(
        "default",
        {
            "setup_s": 0.01, "pass1_s": 0.1, "gather_s": 0.02,
            "finish_s": 0.01, "gang_loops": 1, "fill_loops": 2,
            "merged_fill_loops": 3, "rewindows": 1, "window_slots": 4096,
            "compacted": True,
        },
    )
    # Same contract for the solve-kernel info gauge: kernel-path
    # selection (ops/pallas_kernels.py) only happens on the device solve
    # path, so drive the wiring itself with the path string
    # _attempt_round reports in solver_info["kernel"].
    sim.scheduler._note_solve_kernel("default", "blocked")
    # Same contract for the autotune surface (armada_tpu/autotune): the
    # oracle sim never runs the kernel's host-driven driver, so drive
    # the controller wiring itself with a profile of the shape
    # solve_round emits — the scheduler_autotune_* families prove they
    # are connected (an adoption must fire the adjustments counter, the
    # params gauges update on every observation).
    from armada_tpu.autotune import AutotuneController

    ctl = AutotuneController(
        SchedulingConfig(
            hot_window_slots=8, hot_window_min_slots=0,
            autotune_enabled=True, autotune_hysteresis_rounds=1,
            autotune_min_window_slots=4, autotune_max_window_slots=64,
        )
    )
    sim.scheduler.attach_autotune(ctl)
    adopted = ctl.observe_round(
        "default",
        {"compacted": True, "rewindows": 8, "gather_s": 0.01,
         "pass1_s": 0.2},
        metrics=m,
    )
    assert adopted is not None and adopted["direction"] == "grow"
    # What-if planner surface (armada_tpu/whatif): one plan against the
    # sim's scheduler puts samples in whatif_plans_total /
    # whatif_plan_seconds (and whatif_queue_depth), and a tiny staged
    # drain on a two-executor harness drives drain_jobs_preempted_total
    # / drain_jobs_completed_total through the REAL event path.
    from armada_tpu.core.types import JobSpec, QueueSpec
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService
    from armada_tpu.whatif import WhatIfService, mutations_from_dicts

    wi = WhatIfService(sim.scheduler, metrics=m)
    plan = wi.plan(
        mutations_from_dicts(
            [{"kind": "inject_gang", "queue": "qa", "gang_cardinality": 2,
              "cpu": "2"}]
        ),
        rounds=2,
    )
    assert plan.injected
    from armada_tpu.core.config import PriorityClass

    drain_cfg = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    dlog = InMemoryEventLog()
    dsched = SchedulerService(drain_cfg, dlog)
    dsubmit = SubmitService(drain_cfg, dlog, scheduler=dsched)
    dsubmit.create_queue(QueueSpec("q"))
    # `fast` completes AFTER the drain's first step (t=10) but inside
    # its deadline (t=25): counted as a voluntary completion.
    runtimes = {"fast": 12.0}
    rt = lambda jid: runtimes.get(jid, 1e9)  # noqa: E731
    dex_a = FakeExecutor("dex-a", dlog, dsched,
                         nodes=make_nodes("dex-a", count=1, cpu="8"),
                         runtime_for=rt)
    dex_b = FakeExecutor("dex-b", dlog, dsched,
                         nodes=make_nodes("dex-b", count=1, cpu="8"),
                         runtime_for=rt)
    dsubmit.submit("q", "s", [
        JobSpec(id="fast", queue="q", requests={"cpu": "2", "memory": "1Gi"},
                submitted_ts=0.0),
        JobSpec(id="slow", queue="q", requests={"cpu": "2", "memory": "1Gi"},
                submitted_ts=1.0),
    ], now=0.0)

    def dcycle(t):
        for ex in (dex_a, dex_b):
            ex.tick(t)
        dsched.cycle(now=t)
        for ex in (dex_a, dex_b):
            ex.tick(t)

    dcycle(0.0)
    executor = dsched.jobdb.get("slow").latest_run.executor
    dsched.drains.start(executor, deadline_s=15.0, metrics=m)
    for k in range(1, 6):
        dcycle(10.0 * k)
    status = dsched.drains.status(executor)
    assert status["preempted"], status
    counts = _labeled_sample_counts(m)
    dead = sorted(
        name for name, n in counts.items()
        if n == 0 and name not in EXEMPT_LABELED
        and not name.startswith(FRONTDOOR_PREFIX)
    )
    assert not dead, f"labeled metric families never set by the sim: {dead}"
    live_exempt = sorted(
        name for name, n in counts.items()
        if n > 0 and name in EXEMPT_LABELED
    )
    assert not live_exempt, (
        "exempted families now get samples in the sim — remove them from "
        f"EXEMPT_LABELED so they stay guarded: {live_exempt}"
    )
    # Unlabeled audit: every unlabeled family's mutators must have been
    # CALLED during the sweep (rendered zero-samples can't distinguish
    # dead wiring from a genuine zero — scheduler_cycle_seconds sat
    # registered-but-dead in sims exactly that way), except the
    # explicitly mode-gated exemptions, which must stay untouched so
    # the list cannot rot.
    dead_unlabeled = sorted(
        family for family, calls in touched.items()
        if calls == 0 and family not in EXEMPT_UNLABELED
    )
    assert not dead_unlabeled, (
        f"unlabeled metric families never mutated by the sim sweep: "
        f"{dead_unlabeled}"
    )
    touched_exempt = sorted(
        family for family, calls in touched.items()
        if calls > 0 and family in EXEMPT_UNLABELED
    )
    assert not touched_exempt, (
        "exempted unlabeled families are now mutated in the sim — "
        f"remove them from EXEMPT_UNLABELED: {touched_exempt}"
    )
    # Every family (labeled or not) appears in the rendered exposition.
    rendered = m.render().decode()
    for attr, metric in vars(m).items():
        for family in getattr(metric, "collect", lambda: [])():
            assert family.name in rendered, family.name


def test_frontdoor_families_live_after_short_soak():
    """Every labeled frontdoor_* family must carry samples after a short
    front-door soak: admitted + shed (tenant flood), a deadline drop at
    the gate, and a pump that delivers and observes shard lag. New
    frontdoor_* families are auto-covered — register one and leave it
    unwired and this test fails."""
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.core.types import QueueSpec
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.frontdoor import (
        AdmissionError,
        DeadlineExpired,
        FrontDoor,
        TenantAdmission,
    )
    from armada_tpu.services.grpc_api import ApiServer
    from armada_tpu.services.submit import SubmitService

    m = SchedulerMetrics()
    log = InMemoryEventLog()
    admission = TenantAdmission(
        tenant_rate=5.0, tenant_burst=5.0, metrics=m
    )
    fd = FrontDoor(log, num_shards=2, admission=admission, metrics=m)
    submit = SubmitService(SchedulingConfig(), log, frontdoor=fd)
    submit.create_queue(QueueSpec("hot"))
    api = ApiServer(submit, None, None, log, frontdoor=fd)
    job = {"requests": {"cpu": "1", "memory": "1Gi"}}
    shed = 0
    for k in range(12):  # burst 5: the flood sheds the tail
        try:
            api._submit_jobs(
                {"queue": "hot", "jobset": f"js{k % 3}", "jobs": [job]}
            )
        except AdmissionError:
            shed += 1
    assert shed > 0
    import time as _t

    with pytest.raises(DeadlineExpired):
        api._submit_jobs(
            {"queue": "hot", "jobset": "js0", "jobs": [job],
             "deadline_ts": _t.time() - 1.0}
        )
    fd.pump()
    counts = _labeled_sample_counts(m)
    frontdoor_families = {
        name for name in counts if name.startswith(FRONTDOOR_PREFIX)
    }
    assert frontdoor_families, "no frontdoor_* families registered"
    dead = sorted(
        name for name in frontdoor_families if counts[name] == 0
    )
    assert not dead, (
        f"frontdoor_* families never set by the soak: {dead}"
    )


def test_fairness_policy_info_gauge_follows_flip():
    """scheduler_fairness_policy_info is an info-style gauge: the active
    policy's (pool, policy) series reads 1 and, on a flip, the previous
    policy's series drops to 0 instead of freezing — a dashboard keyed
    on ==1 must follow the flip."""
    from armada_tpu.observe.fairness import FairnessTracker

    m = SchedulerMetrics()
    tracker = FairnessTracker()

    def value(policy):
        for fam in m.fairness_policy_info.collect():
            for s in fam.samples:
                if s.labels.get("pool") == "p" and (
                    s.labels.get("policy") == policy
                ):
                    return s.value
        return None

    tracker.observe_round("p", {"ledger": {"queues": [], "jain": 1.0}},
                          metrics=m)
    assert value("drf") == 1.0

    tracker.observe_round(
        "p",
        {"ledger": {"queues": [], "jain": 1.0, "policy": "proportional"}},
        metrics=m,
    )
    assert value("proportional") == 1.0
    assert value("drf") == 0.0


def test_solve_kernel_info_gauge_follows_flip():
    """scheduler_solve_kernel_info is an info-style gauge: the kernel
    path the pool's last committed round ran reads 1 and, on a flip
    (config change or a failover demotion off a poisoned pallas/blocked
    executable), the stale path's series drops to 0 instead of
    freezing — a dashboard keyed on ==1 must follow the demotion."""
    from armada_tpu.services.scheduler import SchedulerService

    m = SchedulerMetrics()

    class Host:
        metrics = m

    host = Host()

    def value(path):
        for fam in m.solve_kernel_info.collect():
            for s in fam.samples:
                if s.labels.get("pool") == "p" and (
                    s.labels.get("path") == path
                ):
                    return s.value
        return None

    SchedulerService._note_solve_kernel(host, "p", "pallas")
    assert value("pallas") == 1.0

    # Failover demotion: the ladder fell off the local:pallas rung onto
    # plain LOCAL, which forces the lax graph.
    SchedulerService._note_solve_kernel(host, "p", "lax")
    assert value("lax") == 1.0
    assert value("pallas") == 0.0
