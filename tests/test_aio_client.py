"""Asyncio client against a live in-process control plane
(client/python asyncio_client.py parity: same surface as the sync client,
multiplexed watches on one loop)."""

import asyncio

import pytest

from armada_tpu.clients.aio import AsyncApiClient
from armada_tpu.core.config import SchedulingConfig
from armada_tpu.services.server import ControlPlane


@pytest.fixture
def plane():
    plane = ControlPlane(
        SchedulingConfig(),
        grpc_port=0,
        cycle_period=0.2,
        fake_executors=[{"name": "fx", "nodes": 2, "cpu": "8"}],
    )
    plane.start()
    yield plane
    plane.stop()


def test_async_client_end_to_end(plane):
    async def run():
        client = AsyncApiClient(f"127.0.0.1:{plane.grpc_port}")
        try:
            await client.create_queue("aq", priority_factor=2.0)
            queues = await client.list_queues()
            assert any(q["name"] == "aq" for q in queues)
            ids = await client.submit_jobs(
                "aq", "ajs", [{"requests": {"cpu": "1", "memory": "1Gi"}}] * 2
            )
            assert len(ids) == 2

            # Two watches multiplexed on one loop: both see the submits.
            async def first_events(n):
                events = []
                async for e in client.watch_jobset("aq", "ajs", watch=False):
                    events.append(e)
                    if len(events) >= n:
                        break
                return events

            ev1, ev2 = await asyncio.gather(first_events(2), first_events(2))
            assert {e["type"] for e in ev1} == {"SubmitJob"}
            assert {e["type"] for e in ev2} == {"SubmitJob"}

            # The query view catches up on the next scheduler cycle.
            rows = {"total": 0}
            for _ in range(50):
                rows = await client.get_jobs(
                    filters=[{"field": "queue", "value": "aq"}], take=10
                )
                if rows["total"] == 2:
                    break
                await asyncio.sleep(0.1)
            assert rows["total"] == 2
            await client.cancel_jobs("aq", "ajs", job_ids=[ids[0]])
            report = await client.scheduling_report()
            assert isinstance(report, str)
        finally:
            await client.close()

    asyncio.run(run())
