"""Floating resources: pool-capped non-node resources (licenses etc.),
docs/floating_resources.md in the reference."""

import numpy as np

from armada_tpu.core.config import FloatingResource, PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver

CFG = SchedulingConfig(
    floating_resources=(
        FloatingResource(
            "example.com/license", "1", {"default": {"example.com/license": "4"}}
        ),
    ),
)


def nodes(n=2):
    return [
        NodeSpec(
            id=f"n{i}", pool="default", total_resources={"cpu": "32", "memory": "128Gi"}
        )
        for i in range(n)
    ]


def lic_job(i, licenses="1"):
    return JobSpec(
        id=f"j{i:03d}",
        queue="q",
        requests={"cpu": "1", "memory": "1Gi", "example.com/license": licenses},
        submitted_ts=float(i),
    )


def test_floating_cap_enforced_oracle():
    # 8 jobs x 1 license, pool cap 4 -> exactly 4 schedule
    snap = build_round_snapshot(
        CFG, "default", nodes(), [QueueSpec("q")], [], [lic_job(i) for i in range(8)]
    )
    res = ReferenceSolver(snap).solve()
    assert res.scheduled_mask.sum() == 4
    lic = snap.factory.index_of("example.com/license")
    assert snap.total_resources[lic] == 4
    assert snap.floating_mask[lic]


def test_floating_does_not_block_node_fit():
    # licenses are not node resources: a job requesting one fits on a node
    snap = build_round_snapshot(
        CFG, "default", nodes(1), [QueueSpec("q")], [], [lic_job(0)]
    )
    res = ReferenceSolver(snap).solve()
    assert res.scheduled_mask.sum() == 1


def test_non_floating_jobs_unaffected():
    plain = [
        JobSpec(id=f"p{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"},
                submitted_ts=float(i))
        for i in range(10)
    ]
    snap = build_round_snapshot(CFG, "default", nodes(), [QueueSpec("q")], [], plain)
    res = ReferenceSolver(snap).solve()
    assert res.scheduled_mask.sum() == 10


def test_floating_parity_kernel_vs_oracle():
    jobs = [lic_job(i) for i in range(8)] + [
        JobSpec(id=f"p{i}", queue="q", requests={"cpu": "2", "memory": "2Gi"},
                submitted_ts=100.0 + i)
        for i in range(5)
    ]
    snap = build_round_snapshot(CFG, "default", nodes(), [QueueSpec("q")], [], jobs)
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all()
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    assert oracle.scheduled_mask.sum() == 4 + 5
