"""Deterministic fault injection: plan semantics, degradation primitives,
and a small tier-1 soak slice (tools/chaos_soak.py runs the full 20-plan
version)."""

import pytest

from armada_tpu.services.chaos import (
    ChaosLeader,
    CircuitBreaker,
    ExponentialBackoff,
    FaultPlan,
    FaultSpec,
    VirtualClock,
)


# ---------------------------------------------------------------- FaultPlan


def test_fault_plan_windows_and_counts():
    plan = FaultPlan(
        [
            FaultSpec("executor_crash", "c0", start=10.0, duration=5.0),
            FaultSpec("torn_log_write", "*", start=0.0, count=2),
        ]
    )
    assert plan.active("executor_crash", "c0", 9.9) is None
    assert plan.active("executor_crash", "c0", 10.0) is not None
    assert plan.active("executor_crash", "c0", 14.9) is not None
    assert plan.active("executor_crash", "c0", 15.0) is None
    assert plan.active("executor_crash", "c1", 12.0) is None  # wrong target
    # Point faults consume their count.
    assert plan.fire("torn_log_write", "log", 1.0) is not None
    assert plan.fire("torn_log_write", "log", 2.0) is not None
    assert plan.fire("torn_log_write", "log", 3.0) is None


def test_fault_plan_generate_deterministic():
    a = FaultPlan.generate(7, 1000.0, executors=["e0", "e1"])
    b = FaultPlan.generate(7, 1000.0, executors=["e0", "e1"])
    assert a.faults == b.faults
    c = FaultPlan.generate(8, 1000.0, executors=["e0", "e1"])
    assert a.faults != c.faults
    assert all(f.kind in set("""executor_crash executor_hang lease_slow
        lease_timeout torn_log_write leader_flap""".split()) for f in a.faults)


def test_fault_plan_rejects_unknown_kind():
    with pytest.raises(ValueError):
        FaultPlan([FaultSpec("split_brain")])


# ------------------------------------------------- degradation primitives


def test_exponential_backoff_jitter_and_cap():
    b = ExponentialBackoff(base_s=1.0, cap_s=8.0, seed=3)
    delays = [b.next_delay() for _ in range(6)]
    assert all(0.0 <= d <= 8.0 for d in delays)
    assert delays[0] <= 1.0 and delays[1] <= 2.0 and delays[2] <= 4.0
    # Seeded: the schedule replays exactly after reset.
    b.reset()
    assert [b.next_delay() for _ in range(6)] == delays


def test_circuit_breaker_state_machine():
    cb = CircuitBreaker(failure_threshold=2, cooldown_s=10.0)
    assert cb.allow("e0", now=0.0)
    cb.record_failure("e0", now=0.0)
    assert cb.allow("e0", now=0.0)  # one failure: still closed
    cb.record_failure("e0", now=1.0)
    assert cb.state("e0", 1.0) == "open"
    assert not cb.allow("e0", now=2.0)
    # Half-open after cooldown: exactly one probe allowed.
    assert cb.allow("e0", now=11.5)
    assert not cb.allow("e0", now=11.6)
    cb.record_failure("e0", now=11.7)  # probe failed: re-open
    assert not cb.allow("e0", now=12.0)
    assert cb.allow("e0", now=22.0)  # next cooldown, next probe
    cb.record_success("e0")
    assert cb.state("e0") == "closed"
    assert cb.allow("e0")
    # Keys are independent.
    assert cb.allow("e1")


def test_chaos_leader_flap_gates_token_and_validate():
    from armada_tpu.services.leader import StandaloneLeader

    clock = VirtualClock()
    plan = FaultPlan([FaultSpec("leader_flap", "leader", 100.0, 50.0)])
    leader = ChaosLeader(StandaloneLeader(), plan, clock=clock)
    clock.now = 10.0
    token = leader.get_token()
    assert token.leader and leader.validate(token)
    clock.now = 120.0  # mid-flap: deposed, and the old token is invalid
    assert not leader.get_token().leader
    assert not leader.validate(token)
    clock.now = 160.0  # flap over
    assert leader.get_token().leader


def test_lease_breaker_on_server_lease_path():
    """Repeated failing exchanges open the per-executor circuit; an open
    circuit fast-fails the RPC (wire-agnostic, the agent's backoff
    absorbs it); a later success closes it."""
    from armada_tpu.services.chaos import CircuitOpenError
    from armada_tpu.services.grpc_api import ApiServer

    api = ApiServer(None, None, None, None)
    api.lease_breaker.cooldown_s = 60.0

    calls = {"n": 0}

    def boom(req):
        calls["n"] += 1
        raise RuntimeError("malformed heartbeat")

    api._executor_lease_inner = boom
    for _ in range(3):
        with pytest.raises(RuntimeError):
            api._executor_lease({"executor": "bad"})
    # Circuit open: the handler is never reached.
    with pytest.raises(CircuitOpenError):
        api._executor_lease({"executor": "bad"})
    assert calls["n"] == 3
    # Half-open probe after cooldown: a success closes the circuit.
    api.lease_breaker.cooldown_s = 0.0
    api._executor_lease_inner = lambda req: {"leases": []}
    assert api._executor_lease({"executor": "bad"}) == {"leases": []}
    assert api.lease_breaker.state("bad") == "closed"
    # Other executors were never affected.
    assert api.lease_breaker.allow("good")


# ----------------------------------------------------- simulator integration


@pytest.mark.chaos
def test_sim_executor_crash_recovers_all_jobs():
    """A crash window mid-run loses the executor's pods; recovery
    reconciliation + retries still finish every job, deterministically."""
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    def build():
        plan = FaultPlan(
            [FaultSpec("executor_crash", "cl0", start=50.0, duration=100.0)]
        )
        return Simulator(
            [ClusterSpec(name="cl0", node_templates=(NodeTemplate(count=5),))],
            WorkloadSpec(
                queues=(
                    QueueSpecSim(
                        name="q0",
                        job_templates=(
                            JobTemplate(
                                id="t",
                                number=8,
                                cpu="2",
                                memory="4Gi",
                                runtime=ShiftedExponential(minimum=60.0),
                            ),
                        ),
                    ),
                )
            ),
            SchedulingConfig(
                enable_assertions=True, executor_timeout_s=60.0, max_retries=6
            ),
            backend="oracle",
            seed=5,
            cycle_interval=10.0,
            max_time=4000.0,
            fault_plan=plan,
        )

    r1 = build().run()
    assert r1.finished_jobs == r1.total_jobs == 8
    r2 = build().run()
    assert r2.events_by_job == r1.events_by_job
    assert r2.placements == r1.placements


@pytest.mark.chaos
def test_soak_subset_deterministic():
    """Two full soak plans (crashes, hangs, lease faults, leader flaps,
    torn log tails on a real file-backed log) with the determinism
    check — the tier-1 slice of tools/chaos_soak.py."""
    from tools.chaos_soak import run_plan

    for seed in (0, 3):
        first = run_plan(seed, "oracle", 24)
        second = run_plan(seed, "oracle", 24)
        assert first["digest"] == second["digest"]
        assert first["finished"] == first["total"]
        assert first["faults_fired"] > 0  # chaos actually landed


@pytest.mark.chaos
def test_solver_fault_soak_subset():
    """One solver-fault soak plan (kernel backend: raise / hang /
    NaN-poison / wrong-placement windows through the failover ladder and
    the admission firewall) with the determinism check — the tier-1
    slice of `tools/chaos_soak.py --solver-faults`. run_solver_plan
    itself asserts containment: every planned fault fired, nothing
    invalid committed, all jobs terminal, every rejection left a
    postmortem bundle that replays DIVERGED offline."""
    from tools.chaos_soak import run_solver_plan

    first = run_solver_plan(0, 24)
    second = run_solver_plan(0, 24, replay=False)
    assert first["digest"] == second["digest"]
    assert first["finished"] == first["total"] == 24
    assert all(
        first["injected"].get(k)
        for k in ("solver_raise", "solver_hang", "solver_nan_poison",
                  "solver_wrong_placement")
    )
    assert first["bundles_replayed"] == len(first["rejections"]) >= 2
    causes = {fo["cause"] for fo in first["failovers"]}
    assert {"raise", "hang", "validation"} <= causes
