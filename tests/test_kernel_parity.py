"""Placement parity: the jitted JAX kernel must reproduce the Python oracle
exactly (which in turn mirrors the Go reference). Randomized scenario sweep
plus directed cases for each mechanism."""

import numpy as np
import pytest

from armada_tpu.core.config import PriorityClass, RateLimits, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, NodeSpec, QueueSpec, RunningJob, Taint, Toleration
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver

PREEMPT_CFG = SchedulingConfig(
    priority_classes={
        "high": PriorityClass("high", 30000, preemptible=False),
        "low": PriorityClass("low", 1000, preemptible=True),
    },
    default_priority_class="low",
    protected_fraction_of_fair_share=0.5,
)


def assert_parity(cfg, nodes, queues, running, queued, label="", **snap_kw):
    snap = build_round_snapshot(
        cfg, "default", nodes, queues, running, queued, **snap_kw
    )
    oracle = ReferenceSolver(snap).solve()
    # Padded shapes: scenarios share compiled programs across tests.
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J, Q = snap.num_jobs, snap.num_queues
    out = {
        k: v[:J] if k.startswith(("assigned", "scheduled", "preempted")) else v[:Q]
        for k, v in out.items()
        if k not in ("num_loops", "spot_price")
    }
    o_nodes = oracle.assigned_node
    k_nodes = out["assigned_node"]
    mism = np.flatnonzero(o_nodes != k_nodes)
    detail = [
        (snap.job_ids[j], int(o_nodes[j]), int(k_nodes[j])) for j in mism[:10]
    ]
    assert (o_nodes == k_nodes).all(), f"{label}: node mismatch {detail}"
    assert (oracle.scheduled_mask == out["scheduled_mask"]).all(), label
    assert (oracle.preempted_mask == out["preempted_mask"]).all(), label
    np.testing.assert_allclose(
        oracle.demand_capped_fair_share,
        out["demand_capped_fair_share"],
        rtol=1e-12,
        err_msg=label,
    )
    return snap, oracle, out


def rand_scenario(rng, with_running=False, with_gangs=True, n_queues=3,
                  with_affinity=False):
    n_nodes = int(rng.integers(2, 8))
    nodes = []
    for i in range(n_nodes):
        cpu = int(rng.choice([8, 16, 32, 64]))
        mem = cpu * 4
        labels = {}
        taints = ()
        if rng.random() < 0.3:
            labels["zone"] = str(rng.choice(["a", "b"]))
        if rng.random() < 0.2:
            taints = (Taint("special", "true"),)
        nodes.append(
            NodeSpec(
                id=f"node-{i:03d}",
                pool="default",
                labels=labels,
                taints=taints,
                total_resources={"cpu": str(cpu), "memory": f"{mem}Gi"},
            )
        )
    queues = [QueueSpec(f"q{i}", float(rng.choice([1.0, 1.0, 2.0]))) for i in range(n_queues)]

    running = []
    jid = 0
    if with_running:
        for _ in range(int(rng.integers(0, 10))):
            node = nodes[int(rng.integers(0, n_nodes))]
            pc = str(rng.choice(["low", "low", "high"]))
            running.append(
                RunningJob(
                    job=JobSpec(
                        id=f"run-{jid:04d}",
                        queue=f"q{int(rng.integers(0, n_queues))}",
                        priority_class=pc,
                        requests={
                            "cpu": str(int(rng.choice([1, 2, 4]))),
                            "memory": f"{int(rng.choice([1, 2, 4]))}Gi",
                        },
                        submitted_ts=float(jid),
                        tolerations=(Toleration(key="special", value="true"),),
                    ),
                    node_id=node.id,
                    scheduled_at_priority=1000 if pc == "low" else 30000,
                )
            )
            jid += 1

    queued = []
    n_jobs = int(rng.integers(5, 30))
    g = 0
    while len(queued) < n_jobs:
        q = f"q{int(rng.integers(0, n_queues))}"
        cpu = int(rng.choice([1, 2, 4, 8]))
        kw = {}
        if rng.random() < 0.25:
            kw["tolerations"] = (Toleration(key="special", value="true"),)
        if rng.random() < 0.2:
            kw["node_selector"] = {"zone": str(rng.choice(["a", "b"]))}
        if with_affinity and rng.random() < 0.2:
            from armada_tpu.core.types import Affinity, MatchExpression, NodeSelectorTerm

            op = str(rng.choice(["In", "NotIn", "Exists"]))
            kw["affinity"] = Affinity(
                terms=(
                    NodeSelectorTerm(
                        expressions=(
                            MatchExpression("zone", op, ("a",)),
                        )
                    ),
                )
            )
        if with_gangs and rng.random() < 0.2:
            card = int(rng.integers(2, 5))
            gang = Gang(id=f"gang-{g}", cardinality=card)
            g += 1
            for _ in range(card):
                queued.append(
                    JobSpec(
                        id=f"job-{jid:04d}",
                        queue=q,
                        priority_class=str(rng.choice(["low", "high"])),
                        requests={"cpu": str(cpu), "memory": f"{cpu}Gi"},
                        submitted_ts=float(jid),
                        gang=gang,
                        **kw,
                    )
                )
                jid += 1
        else:
            queued.append(
                JobSpec(
                    id=f"job-{jid:04d}",
                    queue=q,
                    priority_class=str(rng.choice(["low", "high"])),
                    requests={"cpu": str(cpu), "memory": f"{cpu}Gi"},
                    submitted_ts=float(jid),
                    **kw,
                )
            )
            jid += 1
    return nodes, queues, running, queued


@pytest.mark.parametrize("seed", range(12))
def test_parity_queued_only(seed):
    rng = np.random.default_rng(seed)
    nodes, queues, running, queued = rand_scenario(rng, with_running=False)
    assert_parity(PREEMPT_CFG, nodes, queues, [], queued, f"seed={seed}")


@pytest.mark.parametrize("seed", range(12, 24))
def test_parity_with_running(seed):
    rng = np.random.default_rng(seed)
    nodes, queues, running, queued = rand_scenario(rng, with_running=True)
    assert_parity(PREEMPT_CFG, nodes, queues, running, queued, f"seed={seed}")


@pytest.mark.parametrize("seed", range(24, 30))
def test_parity_with_affinity_mix(seed):
    rng = np.random.default_rng(seed)
    nodes, queues, running, queued = rand_scenario(
        rng, with_running=True, with_affinity=True
    )
    assert_parity(PREEMPT_CFG, nodes, queues, running, queued, f"seed={seed}")


def test_parity_rate_limited():
    cfg = SchedulingConfig(rate_limits=RateLimits(maximum_scheduling_burst=3))
    nodes = [
        NodeSpec(id="n0", pool="default", total_resources={"cpu": "32", "memory": "128Gi"})
    ]
    queued = [
        JobSpec(id=f"j{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"}, submitted_ts=i)
        for i in range(10)
    ]
    assert_parity(cfg, nodes, [QueueSpec("q")], [], queued, "rate")


def test_parity_round_fraction():
    cfg = SchedulingConfig(maximum_resource_fraction_to_schedule={"cpu": 0.25})
    nodes = [
        NodeSpec(id="n0", pool="default", total_resources={"cpu": "32", "memory": "128Gi"})
    ]
    queued = [
        JobSpec(id=f"j{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"}, submitted_ts=i)
        for i in range(20)
    ]
    assert_parity(cfg, nodes, [QueueSpec("q")], [], queued, "fraction")


def test_parity_lookback():
    cfg = SchedulingConfig(max_queue_lookback=4)
    nodes = [
        NodeSpec(id="n0", pool="default", total_resources={"cpu": "32", "memory": "128Gi"})
    ]
    queued = [
        JobSpec(id=f"j{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"}, submitted_ts=i)
        for i in range(10)
    ]
    assert_parity(cfg, nodes, [QueueSpec("q")], [], queued, "lookback")


def test_parity_eviction_rebalance():
    nodes = [
        NodeSpec(id="n0", pool="default", total_resources={"cpu": "32", "memory": "128Gi"})
    ]
    running = [
        RunningJob(
            job=JobSpec(
                id=f"r{i}",
                queue="hog",
                priority_class="low",
                requests={"cpu": "4", "memory": "4Gi"},
                submitted_ts=i,
            ),
            node_id="n0",
            scheduled_at_priority=1000,
        )
        for i in range(8)
    ]
    queued = [
        JobSpec(
            id=f"j{i}",
            queue="newbie",
            priority_class="low",
            requests={"cpu": "4", "memory": "4Gi"},
            submitted_ts=100 + i,
        )
        for i in range(8)
    ]
    assert_parity(
        PREEMPT_CFG,
        nodes,
        [QueueSpec("hog"), QueueSpec("newbie")],
        running,
        queued,
        "rebalance",
    )


def test_parity_urgency_preemption():
    nodes = [
        NodeSpec(id="n0", pool="default", total_resources={"cpu": "32", "memory": "128Gi"})
    ]
    running = [
        RunningJob(
            job=JobSpec(
                id=f"r{i}",
                queue="b",
                priority_class="low",
                requests={"cpu": "8", "memory": "8Gi"},
                submitted_ts=i,
            ),
            node_id="n0",
            scheduled_at_priority=1000,
        )
        for i in range(4)
    ]
    queued = [
        JobSpec(
            id="high0",
            queue="a",
            priority_class="high",
            requests={"cpu": "8", "memory": "8Gi"},
            submitted_ts=100,
        )
    ]
    assert_parity(
        PREEMPT_CFG, nodes, [QueueSpec("a"), QueueSpec("b")], running, queued, "urgency"
    )


def test_parity_gang_uniformity():
    # Two zones; zone-b can host the whole gang, zone-a cannot. The
    # uniformity search must place all members in one zone.
    nodes = [
        NodeSpec(id="a0", pool="default", labels={"zone": "a"},
                 total_resources={"cpu": "16", "memory": "64Gi"}),
        NodeSpec(id="b0", pool="default", labels={"zone": "b"},
                 total_resources={"cpu": "32", "memory": "128Gi"}),
        NodeSpec(id="b1", pool="default", labels={"zone": "b"},
                 total_resources={"cpu": "32", "memory": "128Gi"}),
    ]
    gang = Gang(id="g", cardinality=3, node_uniformity_label="zone")
    queued = [
        JobSpec(id=f"g{i}", queue="q", requests={"cpu": "16", "memory": "16Gi"},
                submitted_ts=i, gang=gang)
        for i in range(3)
    ]
    snap, oracle, out = assert_parity(
        SchedulingConfig(), nodes, [QueueSpec("q")], [], queued, "uniformity"
    )
    assert oracle.scheduled_mask.sum() == 3
    placed = {snap.node_ids[n] for n in oracle.assigned_node[:3]}
    assert placed <= {"b0", "b1"}  # all in zone b


def test_parity_gang_uniformity_impossible():
    # No single zone fits the gang -> nothing scheduled, singleton proceeds.
    nodes = [
        NodeSpec(id=f"{z}0", pool="default", labels={"zone": z},
                 total_resources={"cpu": "16", "memory": "64Gi"})
        for z in ("a", "b")
    ]
    gang = Gang(id="g", cardinality=3, node_uniformity_label="zone")
    queued = [
        JobSpec(id=f"g{i}", queue="q", requests={"cpu": "8", "memory": "8Gi"},
                submitted_ts=i, gang=gang)
        for i in range(3)
    ] + [JobSpec(id="solo", queue="q", requests={"cpu": "2", "memory": "2Gi"},
                 submitted_ts=10)]
    snap, oracle, out = assert_parity(
        SchedulingConfig(), nodes, [QueueSpec("q")], [], queued, "uniformity-fail"
    )
    assert oracle.scheduled_mask.sum() == 1  # only the singleton


def test_parity_gang_uniformity_unknown_label():
    # Uniformity label no node carries: the gang must never schedule.
    nodes = [
        NodeSpec(id=f"n{i}", pool="default",
                 total_resources={"cpu": "32", "memory": "128Gi"})
        for i in range(2)
    ]
    gang = Gang(id="g", cardinality=2, node_uniformity_label="rack")
    queued = [
        JobSpec(id=f"g{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"},
                submitted_ts=i, gang=gang)
        for i in range(2)
    ]
    snap, oracle, out = assert_parity(
        SchedulingConfig(), nodes, [QueueSpec("q")], [], queued, "uniformity-unknown"
    )
    assert oracle.scheduled_mask.sum() == 0


def test_parity_gang_atomicity():
    nodes = [
        NodeSpec(id=f"n{i}", pool="default", total_resources={"cpu": "32", "memory": "128Gi"})
        for i in range(2)
    ]
    gang = Gang(id="g", cardinality=3)
    queued = [
        JobSpec(
            id=f"g{i}",
            queue="q",
            requests={"cpu": "20", "memory": "20Gi"},
            submitted_ts=i,
            gang=gang,
        )
        for i in range(3)
    ] + [
        JobSpec(id="s0", queue="q", requests={"cpu": "4", "memory": "4Gi"}, submitted_ts=10)
    ]
    assert_parity(SchedulingConfig(), nodes, [QueueSpec("q")], [], queued, "gang")
