"""Short-job penalty: anti-churn cost for recently finished short jobs
(scheduling/short_job_penalty.go), solver parity + scheduler wiring."""

import numpy as np

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver


def test_penalty_shifts_candidate_order_with_parity():
    # One 4-cpu node, two queues each with two 2-cpu jobs. Without penalty,
    # interleaved a,b. With a penalty on queue a worth 2 cpu, b goes first
    # and gets both slots before a's cost catches up.
    cfg = SchedulingConfig()
    nodes = [NodeSpec(id="n0", pool="default",
                      total_resources={"cpu": "4", "memory": "16Gi"})]
    queued = [
        JobSpec(id=f"a{i}", queue="a", requests={"cpu": "2", "memory": "1Gi"},
                submitted_ts=i) for i in range(2)
    ] + [
        JobSpec(id=f"b{i}", queue="b", requests={"cpu": "2", "memory": "1Gi"},
                submitted_ts=10 + i) for i in range(2)
    ]
    queues = [QueueSpec("a"), QueueSpec("b")]

    def run(penalty):
        snap = build_round_snapshot(
            cfg, "default", nodes, queues, [], queued,
            short_job_penalty=penalty,
        )
        oracle = ReferenceSolver(snap).solve()
        out = solve_round(pad_device_round(prep_device_round(snap)))
        J = snap.num_jobs
        assert (oracle.assigned_node == out["assigned_node"][:J]).all()
        assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
        return snap, oracle

    snap, no_pen = run(None)
    scheduled_plain = {snap.job_ids[j] for j in np.flatnonzero(no_pen.scheduled_mask)}
    assert scheduled_plain == {"a0", "b0"}  # interleaved, one each

    # Penalty worth 3 cpu: queue a's proposed cost stays strictly above b's
    # (2cpu penalty would tie at the second pick and the name tie-break
    # would still admit a0).
    snap, with_pen = run({"a": {"cpu": "3"}})
    scheduled_pen = {snap.job_ids[j] for j in np.flatnonzero(with_pen.scheduled_mask)}
    assert scheduled_pen == {"b0", "b1"}  # queue a costed ahead, b fills node


def test_scheduler_computes_penalties_from_short_runs():
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
        short_job_penalty_s=300.0,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("churny"))
    ex = FakeExecutor(
        "ex", log, sched, nodes=make_nodes("ex", count=1, cpu="8"),
        runtime_for=lambda job_id: 5.0,  # short jobs
    )
    submit.submit(
        "churny", "s",
        [JobSpec(id="short0", queue="churny", requests={"cpu": "2", "memory": "1Gi"})],
        now=0.0,
    )
    ex.tick(0.0)
    sched.cycle(now=1.0)
    ex.tick(1.5)  # running
    ex.tick(7.0)  # finished after ~5s < 300s window
    sched.ingester.sync()
    txn = sched.jobdb.read_txn()
    penalties = sched._short_job_penalties(txn, "default", now=10.0)
    assert "churny" in penalties
    assert penalties["churny"]["cpu"] == 2
    # window passed: no penalty
    assert sched._short_job_penalties(txn, "default", now=500.0) == {}
