"""Kernel <-> oracle parity at realistic scale (hundreds of nodes,
hundreds-to-thousands of jobs) — tie-breaking and ordering bugs that tiny
2-8-node scenarios (test_kernel_parity.rand_scenario) cannot expose:
resolution-rounded best-fit key collisions across many near-identical
nodes, deep queue interleavings, protected-share boundaries under load.

The default run covers 128-256 nodes; set ARMADA_TPU_BIG_PARITY=1 to add
a 1000-node x 2000-job sweep (several minutes of oracle time — the oracle
is deliberately sequential Python).
"""

import os

import numpy as np
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import (
    Gang,
    JobSpec,
    NodeSpec,
    QueueSpec,
    RunningJob,
    Taint,
    Toleration,
)
from tests.test_kernel_parity import assert_parity

CFG = SchedulingConfig(
    priority_classes={
        "high": PriorityClass("high", 30000, preemptible=False),
        "low": PriorityClass("low", 1000, preemptible=True),
    },
    default_priority_class="low",
    protected_fraction_of_fair_share=0.5,
)


def big_scenario(seed, n_nodes, n_jobs, n_queues=6, running_fraction=0.3):
    """Production-shaped population: few node flavors (so best-fit keys
    collide constantly), mixed selectors/taints/gangs, a running base load."""
    rng = np.random.default_rng(seed)
    nodes = []
    for i in range(n_nodes):
        flavor = i % 3
        cpu = [16, 32, 64][flavor]
        labels = {"zone": ["a", "b"][i % 2]}
        taints = (Taint("special", "true"),) if i % 11 == 0 else ()
        nodes.append(
            NodeSpec(
                id=f"node-{i:05d}",
                pool="default",
                total_resources={"cpu": str(cpu), "memory": f"{cpu * 4}Gi"},
                labels=labels,
                taints=taints,
            )
        )
    queues = [QueueSpec(f"q{i}", 1.0 + (i % 3)) for i in range(n_queues)]

    running = []
    jid = 0
    n_running = int(n_nodes * running_fraction)
    for i in range(n_running):
        node = nodes[int(rng.integers(0, n_nodes))]
        pc = "low" if rng.random() < 0.8 else "high"
        running.append(
            RunningJob(
                job=JobSpec(
                    id=f"run-{jid:05d}",
                    queue=f"q{int(rng.integers(0, n_queues))}",
                    priority_class=pc,
                    requests={
                        "cpu": str(int(rng.choice([2, 4, 8]))),
                        "memory": f"{int(rng.choice([2, 4, 8]))}Gi",
                    },
                    submitted_ts=float(jid),
                    tolerations=(Toleration(key="special", value="true"),),
                ),
                node_id=node.id,
                scheduled_at_priority=1000 if pc == "low" else 30000,
            )
        )
        jid += 1

    queued = []
    g = 0
    while len(queued) < n_jobs:
        q = f"q{int(rng.integers(0, n_queues))}"
        cpu = int(rng.choice([1, 2, 4, 8, 16]))
        kw = {}
        roll = rng.random()
        if roll < 0.15:
            kw["tolerations"] = (Toleration(key="special", value="true"),)
        elif roll < 0.3:
            kw["node_selector"] = {"zone": str(rng.choice(["a", "b"]))}
        if rng.random() < 0.1:
            card = int(rng.integers(2, 6))
            gang = Gang(id=f"gang-{g}", cardinality=card)
            g += 1
            for _ in range(card):
                queued.append(
                    JobSpec(
                        id=f"job-{jid:05d}",
                        queue=q,
                        priority_class="low",
                        requests={"cpu": str(cpu), "memory": f"{cpu}Gi"},
                        submitted_ts=float(jid),
                        gang=gang,
                        **kw,
                    )
                )
                jid += 1
        else:
            queued.append(
                JobSpec(
                    id=f"job-{jid:05d}",
                    queue=q,
                    priority_class=str(rng.choice(["low", "low", "high"])),
                    requests={"cpu": str(cpu), "memory": f"{cpu}Gi"},
                    submitted_ts=float(jid),
                    **kw,
                )
            )
            jid += 1
    return nodes, queues, running, queued


@pytest.mark.parametrize("seed,n_nodes,n_jobs", [(1, 128, 400), (2, 256, 600)])
def test_scale_parity(seed, n_nodes, n_jobs):
    nodes, queues, running, queued = big_scenario(seed, n_nodes, n_jobs)
    snap, oracle, out = assert_parity(
        CFG, nodes, queues, running, queued, label=f"scale-{seed}"
    )
    # The scenario must actually exercise the machinery at scale.
    assert oracle.scheduled_mask.sum() > n_jobs * 0.2


@pytest.mark.skipif(
    not os.environ.get("ARMADA_TPU_BIG_PARITY"),
    reason="1000-node sweep: minutes of sequential oracle time; "
    "set ARMADA_TPU_BIG_PARITY=1",
)
def test_thousand_node_parity():
    nodes, queues, running, queued = big_scenario(7, 1000, 2000, n_queues=10)
    assert_parity(CFG, nodes, queues, running, queued, label="scale-1000")
