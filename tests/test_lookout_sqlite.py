"""SqliteLookoutStore: differential vs the in-memory view, persistent
restart-without-replay, and the retention pruner."""

import dataclasses

from armada_tpu.core.types import JobSpec
from armada_tpu.events import (
    CancelJob,
    CancelJobSet,
    EventSequence,
    InMemoryEventLog,
    JobErrors,
    JobRequeued,
    JobRunLeased,
    JobRunPreempted,
    JobRunRunning,
    JobRunSucceeded,
    JobSucceeded,
    ReprioritiseJob,
    SubmitJob,
)
from armada_tpu.services.lookout_ingester import LookoutStore
from armada_tpu.services.lookout_sqlite import SqliteLookoutStore
from armada_tpu.services.queryapi import JobFilter, Order, QueryApi


def publish_lifecycle(log):
    """A stream covering every event the view materializes."""
    now = 100.0

    def job(i, queue="qa", jobset="s1"):
        return JobSpec(
            id=f"lk-{i:03d}",
            queue=queue,
            jobset=jobset,
            requests={"cpu": "1", "memory": "1Gi"},
            annotations={"team": f"t{i % 2}"},
            submitted_ts=now + i,
        )

    log.publish(
        EventSequence.of(
            "qa", "s1", *[SubmitJob(created=100.0 + i, job=job(i)) for i in range(6)]
        )
    )
    log.publish(
        EventSequence.of(
            "qb",
            "s2",
            *[
                SubmitJob(created=110.0 + i, job=job(10 + i, "qb", "s2"))
                for i in range(4)
            ],
        )
    )
    # Leases + running for the first few.
    leases = [
        JobRunLeased(
            created=120.0,
            job_id=f"lk-{i:03d}",
            run_id=f"run-{i:03d}",
            executor="ex1",
            node_id=f"n{i}",
            pool="default",
        )
        for i in range(4)
    ]
    log.publish(EventSequence.of("qa", "s1", *leases))
    log.publish(
        EventSequence.of(
            "qa",
            "s1",
            *[
                JobRunRunning(created=130.0, job_id=f"lk-{i:03d}", run_id=f"run-{i:03d}")
                for i in range(4)
            ],
        )
    )
    # One success, one run-success + job-success, one preempt + requeue,
    # one failure; a cancel, a reprioritise, a jobset cancel.
    log.publish(
        EventSequence.of(
            "qa",
            "s1",
            JobRunSucceeded(created=140.0, job_id="lk-000", run_id="run-000"),
            JobSucceeded(created=140.0, job_id="lk-000"),
            JobRunPreempted(
                created=141.0, job_id="lk-001", run_id="run-001", reason="evicted"
            ),
            JobRequeued(created=141.5, job_id="lk-001"),
            JobErrors(created=142.0, job_id="lk-002", error="oom killed"),
            CancelJob(created=143.0, job_id="lk-004"),
            ReprioritiseJob(created=143.5, job_id="lk-005", priority=7),
        )
    )
    log.publish(EventSequence.of("qb", "s2", CancelJobSet(created=150.0)))


def row_key(row):
    d = dataclasses.asdict(row)
    d["runs"] = [dataclasses.asdict(r) if not isinstance(r, dict) else r
                 for r in row.runs]
    return d


def test_differential_vs_in_memory(tmp_path):
    log = InMemoryEventLog()
    publish_lifecycle(log)
    ram = LookoutStore(log)
    ram.sync()
    sq = SqliteLookoutStore(log, str(tmp_path / "lk.db"))
    sq.sync()

    ram_rows = {r.job_id: row_key(r) for r in ram.all_rows()}
    sq_rows = {r.job_id: row_key(r) for r in sq.all_rows()}
    assert ram_rows == sq_rows

    # The full query surface answers identically.
    q_ram, q_sq = QueryApi(lookout=ram), QueryApi(lookout=sq)
    for flt, order in [
        ([JobFilter("queue", "qa")], Order("submitted", "asc")),
        ([JobFilter("state", "cancelled")], Order("submitted", "desc")),
        ([], Order("last_transition", "desc")),
    ]:
        rows_ram, tot_ram = q_ram.get_jobs(flt, order, 0, 50)
        rows_sq, tot_sq = q_sq.get_jobs(flt, order, 0, 50)
        assert tot_ram == tot_sq
        assert [r.job_id for r in rows_ram] == [r.job_id for r in rows_sq]
    assert q_ram.group_jobs("state", []) == q_sq.group_jobs("state", [])
    assert row_key(sq.get("lk-001")) == row_key(ram.get("lk-001"))
    assert sq.get_run("run-001").termination_reason == "evicted"
    sq.close()


def test_restart_without_replay(tmp_path):
    path = str(tmp_path / "lk.db")
    log = InMemoryEventLog()
    publish_lifecycle(log)
    sq = SqliteLookoutStore(log, path)
    sq.sync()
    cursor = sq.cursor
    n = sq.count()
    assert n == 10
    sq.close()

    # Reopen: cursor persisted — nothing to replay.
    sq2 = SqliteLookoutStore(log, path)
    assert sq2.cursor == cursor
    assert sq2.sync() == 0
    assert sq2.count() == n

    # New events apply incrementally from the suffix only.
    log.publish(
        EventSequence.of(
            "qa",
            "s1",
            SubmitJob(
                created=200.0,
                job=JobSpec(id="lk-new", queue="qa", jobset="s1",
                            requests={"cpu": "1"}),
            ),
        )
    )
    assert sq2.sync() == 1
    assert sq2.get("lk-new") is not None
    sq2.close()


def test_pruner(tmp_path):
    log = InMemoryEventLog()
    publish_lifecycle(log)
    sq = SqliteLookoutStore(log, str(tmp_path / "lk.db"))
    sq.sync()
    # Terminal rows: lk-000 succeeded@140, lk-002 failed@142, lk-004
    # cancelled@143, and the 4 qb rows cancelled@150. lk-001 requeued
    # (active) must survive any cutoff.
    dropped = sq.prune(older_than=145.0)
    assert dropped == 3
    assert sq.get("lk-000") is None
    assert sq.get_run("run-000") is None  # run index cleaned
    assert sq.get("lk-001") is not None  # active survives
    dropped2 = sq.prune(older_than=1e9)
    assert dropped2 == 4  # the jobset-cancelled qb rows
    assert sq.get("lk-001") is not None
    assert sq.count() == 3  # lk-001 (queued), lk-003 (running), lk-005
    sq.close()


def test_broadside_sqlite_backend_smoke():
    from armada_tpu.clients.broadside import BroadsideConfig, Runner

    cfg = BroadsideConfig(
        backend="sqlite", duration_s=1.0, ingest_actors=1, query_actors=1,
        batch=20,
    )
    report = Runner(cfg).run()
    assert report["backend"] == "sqlite"
    assert report["ingest"]["ops"] > 0
    assert report["get_jobs"]["ops"] > 0
