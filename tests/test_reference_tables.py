"""Table cases ported from the Go reference's scheduler tests.

Each test mirrors a named case in
/root/reference/internal/scheduler/scheduling/preempting_queue_scheduler_test.go
(TestPreemptingQueueScheduler) — same fixtures (32-cpu/256Gi nodes,
1cpu/4Gi jobs, priority classes 0-3 with 3 non-preemptible, prefer-large
ordering ON, protected fraction 0 unless the case sets it), same
multi-round structure (scheduled jobs become running for the next round,
preempted ones leave), and the same expected scheduled/preempted index
sets per (queue, round). Every round asserts ORACLE==KERNEL parity on top
of the Go-expected outcome, so these tables pin all three implementations
together."""

import numpy as np
import pytest

from armada_tpu.core.config import PriorityClass, RateLimits, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, NodeSpec, QueueSpec, RunningJob, Toleration

from test_kernel_parity import assert_parity

# testfixtures.TestPriorityClasses (testfixtures.go:77-105); the away/market
# classes are exercised by test_away.py / test_market.py.
REF_PCS = {
    "priority-0": PriorityClass("priority-0", 0, preemptible=True),
    "priority-1": PriorityClass("priority-1", 1, preemptible=True),
    "priority-2": PriorityClass("priority-2", 2, preemptible=True),
    "priority-2-non-preemptible": PriorityClass(
        "priority-2-non-preemptible", 2, preemptible=False
    ),
    "priority-3": PriorityClass("priority-3", 3, preemptible=False),
}


def ref_config(**kw):
    """testfixtures.TestSchedulingConfig (testfixtures.go:216-239)."""
    base = dict(
        priority_classes=dict(REF_PCS),
        default_priority_class="priority-3",
        protected_fraction_of_fair_share=0.0,
        enable_prefer_large_job_ordering=True,
        # TestSchedulingConfig sets NO round cap (unlimited); our default
        # caps a round at 100% of the cluster, which would stop urgency
        # preemption from transiently oversubscribing.
        maximum_resource_fraction_to_schedule={},
        dominant_resource_fairness_resources={
            "cpu": 1.0,
            "memory": 1.0,
            "nvidia.com/gpu": 1.0,
        },
        indexed_resources={
            "cpu": "1",
            "memory": "128Mi",
            "nvidia.com/gpu": "1",
        },
        rate_limits=RateLimits(
            maximum_scheduling_burst=10**9,
            maximum_per_queue_scheduling_burst=10**9,
        ),
    )
    base.update(kw)
    return SchedulingConfig(**base)


def n32_nodes(n, cordoned=()):
    """testfixtures.N32CpuNodes."""
    return [
        NodeSpec(
            id=f"node-{i:03d}",
            pool="default",
            total_resources={"cpu": "32", "memory": "256Gi"},
            unschedulable=(i in cordoned),
        )
        for i in range(n)
    ]


_LARGE_TOL = (Toleration(key="largeJobsOnly", value="true"),)


class Harness:
    """Multi-round runner mirroring the Go test loop: each round adds new
    queued jobs, schedules (oracle==kernel parity asserted), then binds
    scheduled jobs as running and removes preempted ones."""

    def __init__(self, cfg, nodes, factors, initial_running=None):
        self.cfg = cfg
        self.nodes = nodes
        # QueueSpec takes the priorityFactor directly (weight = 1/factor
        # derives inside, core/types.py QueueSpec.weight).
        self.queues = [QueueSpec(q, f) for q, f in sorted(factors.items())]
        # Rate-limit token bucket carried across rounds (1s per round, the
        # Go harness's clock step).
        limits = cfg.rate_limits
        self.rate_tokens = float(limits.maximum_scheduling_burst)
        self.running: dict[str, RunningJob] = {}
        self.backlog: list[JobSpec] = []
        self.round_jobs: dict[tuple, list[str]] = {}
        self.ts = 0.0
        self.round_no = 0
        self._jid = 0
        for node_idx, jobs in (initial_running or {}).items():
            for pc, n_jobs in jobs:
                for _ in range(n_jobs):
                    spec = self._job("__init__", pc, {"cpu": "1", "memory": "4Gi"})
                    self.running[spec.id] = RunningJob(
                        job=spec,
                        node_id=self.nodes[node_idx].id,
                        scheduled_at_priority=REF_PCS[pc].priority,
                    )

    def _job(self, queue, pc, requests, gang=None, tolerations=()):
        self.ts += 1.0
        self._jid += 1
        return JobSpec(
            id=f"j-{self._jid:05d}",
            queue=queue,
            priority_class=pc,
            requests=dict(requests),
            submitted_ts=self.ts,
            gang=gang,
            tolerations=tuple(tolerations),
        )

    def add(self, queue, pc, n, cpu=1, mem_gi=4, gang=False, large_tol=False,
            per_job_pc=None):
        """N{cpu}Cpu{mem}GiJobs(queue, pc, n); gang=True wraps all n in one
        gang (WithGangAnnotationsJobs). Returns this batch's job ids."""
        g = None
        if gang:
            g = Gang(id=f"gang-{self.round_no}-{queue}-{self._jid}", cardinality=n)
        ids = []
        for i in range(n):
            pc_i = per_job_pc[i] if per_job_pc else pc
            spec = self._job(
                queue,
                pc_i,
                {"cpu": str(cpu), "memory": f"{mem_gi}Gi"},
                gang=g,
                tolerations=_LARGE_TOL if large_tol else (),
            )
            self.backlog.append(spec)
            ids.append(spec.id)
        self.round_jobs.setdefault((queue, self.round_no), []).extend(ids)
        return ids

    def run_round(self, expect_sched=None, expect_preempt=None, cordon=()):
        """expect_sched: {queue: [indices into that queue's jobs added THIS
        round]}; expect_preempt: {queue: {round: [indices]}}. None = assert
        nothing scheduled/preempted."""
        if cordon:
            import dataclasses

            self.nodes = [
                dataclasses.replace(n, unschedulable=True) if i in cordon else n
                for i, n in enumerate(self.nodes)
            ]
        limits = self.cfg.rate_limits
        if self.round_no > 0:
            self.rate_tokens = min(
                self.rate_tokens + limits.maximum_scheduling_rate * 1.0,
                float(limits.maximum_scheduling_burst),
            )
        snap, oracle, out = assert_parity(
            self.cfg,
            self.nodes,
            self.queues,
            list(self.running.values()),
            list(self.backlog),
            f"round {self.round_no}",
            global_rate_tokens=self.rate_tokens,
        )
        idx_of = {jid: j for j, jid in enumerate(snap.job_ids)}

        scheduled_ids = {
            snap.job_ids[j] for j in np.flatnonzero(oracle.scheduled_mask)
        }
        preempted_ids = {
            snap.job_ids[j] for j in np.flatnonzero(oracle.preempted_mask)
        }

        want_sched = set()
        for q, indices in (expect_sched or {}).items():
            ids = self.round_jobs[(q, self.round_no)]
            want_sched.update(ids[i] for i in indices)
        want_preempt = set()
        for q, by_round in (expect_preempt or {}).items():
            for r, indices in by_round.items():
                ids = self.round_jobs[(q, r)]
                want_preempt.update(ids[i] for i in indices)

        assert scheduled_ids == want_sched, (
            f"round {self.round_no}: scheduled {sorted(scheduled_ids)} != "
            f"expected {sorted(want_sched)}"
        )
        assert preempted_ids == want_preempt, (
            f"round {self.round_no}: preempted {sorted(preempted_ids)} != "
            f"expected {sorted(want_preempt)}"
        )

        # Bind: scheduled queued jobs become running; preempted leave.
        # Unscheduled queued jobs are DISCARDED — the Go harness submits a
        # fresh JobsByQueue batch each round and only running jobs persist.
        self.rate_tokens = max(0.0, self.rate_tokens - len(scheduled_ids))
        for jid in preempted_ids:
            self.running.pop(jid, None)
        for spec in self.backlog:
            if spec.id in scheduled_ids:
                j = idx_of[spec.id]
                self.running[spec.id] = RunningJob(
                    job=spec,
                    node_id=snap.node_ids[int(oracle.assigned_node[j])],
                    scheduled_at_priority=int(oracle.scheduled_priority[j]),
                )
        self.backlog = []
        self.round_no += 1
        return snap, oracle


def rng(n):
    return list(range(n))


def test_balancing_three_queues():
    """Go: 'balancing three queues'."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1, "C": 1})
    h.add("A", "priority-0", 32)
    h.run_round({"A": rng(32)})
    h.add("B", "priority-0", 32)
    h.run_round({"B": rng(16)}, {"A": {0: list(range(16, 32))}})
    h.add("C", "priority-0", 10)
    h.run_round(
        {"C": rng(10)},
        {"A": {0: list(range(11, 16))}, "B": {1: list(range(11, 16))}},
    )
    h.add("A", "priority-0", 1)
    h.add("B", "priority-0", 1)
    h.add("C", "priority-0", 1)
    h.run_round()  # steady state


def test_balancing_two_queues_weighted():
    """Go: 'balancing two queues weighted' (A factor 2, B factor 1)."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 2, "B": 1})
    h.add("A", "priority-0", 32)
    h.run_round({"A": rng(32)})
    h.add("B", "priority-0", 32)
    h.run_round({"B": rng(21)}, {"A": {0: list(range(11, 32))}})
    h.add("A", "priority-0", 1)
    h.add("B", "priority-0", 1)
    h.run_round()


def test_dont_preempt_unknown_queue():
    """Go: "don't prempt jobs where we don't know the queue"."""
    h = Harness(
        ref_config(),
        n32_nodes(1),
        {"A": 1},
        initial_running={0: [("priority-1", 8)]},
    )
    h.add("A", "priority-1", 32)
    h.run_round({"A": rng(24)})


def test_avoid_preemption_when_not_improving_fairness():
    """Go: 'avoid preemption when not improving fairness' (+ reverse)."""
    for first, second in (("A", "B"), ("B", "A")):
        h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
        h.add(first, "priority-0", 32)
        h.run_round({first: rng(32)})
        h.add(second, "priority-0", 1, cpu=32, mem_gi=256, large_tol=True)
        h.run_round()  # whole-node job may not preempt: no fairness gain


def test_preemption_when_improving_fairness():
    """Go: 'preemption when improving fairness'."""
    h = Harness(ref_config(), n32_nodes(2), {"A": 1, "B": 1})
    h.add("A", "priority-0", 64)
    h.run_round({"A": rng(64)})
    h.add("B", "priority-0", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"B": [0]}, {"A": {0: list(range(32, 64))}})


def test_reschedule_onto_same_node():
    """Go: 'reschedule onto same node' (+ reverse order)."""
    for first, second in (("A", "B"), ("B", "A")):
        h = Harness(ref_config(), n32_nodes(2), {"A": 1, "B": 1})
        h.add(first, "priority-0", 32)
        h.run_round({first: rng(32)})
        h.add(second, "priority-0", 32)
        h.run_round({second: rng(32)})
        h.run_round()  # empty: nothing changes


def test_urgency_preemption_gangs():
    """Go: 'urgency-based preemption - gangs'."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
    h.add("A", "priority-0", 32, gang=True)
    h.add("B", "priority-1", 32, gang=True)
    h.run_round({"B": rng(32)})
    h.run_round()


def test_urgency_preemption_stability():
    """Go: 'urgency-based preemption stability'."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
    h.add("A", "priority-2", 33)
    h.run_round({"A": rng(32)})
    h.add("B", "priority-3", 1)
    h.run_round({"B": [0]}, {"A": {0: [31]}})
    h.add("A", "priority-2", 1)
    h.run_round()
    h.run_round()


def test_avoid_urgency_preemption_when_possible():
    """Go: 'avoid urgency-based preemption when possible'."""
    h = Harness(ref_config(), n32_nodes(2), {"A": 1})
    h.add("A", "priority-0", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]})
    h.add("A", "priority-1", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]})  # second node, no preemption


def test_preempt_in_order_of_priority():
    """Go: 'preempt in order of priority'."""
    h = Harness(ref_config(), n32_nodes(2), {"A": 1})
    h.add("A", "priority-1", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]})
    h.add("A", "priority-0", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]})
    h.add("A", "priority-2", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]}, {"A": {1: [0]}})  # the priority-0 one goes


def test_avoid_urgency_preemption_cross_queue():
    """Go: 'avoid urgency-based preemption when possible cross-queue'."""
    h = Harness(ref_config(), n32_nodes(3), {"A": 1, "B": 1, "C": 1, "D": 1})
    h.add("A", "priority-1", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]})
    h.add("B", "priority-0", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"B": [0]})
    h.add("C", "priority-2", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"C": [0]})
    h.add("D", "priority-3", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"D": [0]}, {"B": {1: [0]}})  # lowest priority preempted


def test_gang_preemption():
    """Go: 'gang preemption' — preempting one member preempts the gang."""
    h = Harness(ref_config(), n32_nodes(2), {"A": 1, "B": 1, "C": 1})
    h.add("A", "priority-0", 16)
    h.add("B", "priority-0", 16)
    h.run_round({"A": rng(16), "B": rng(16)})
    h.add("C", "priority-0", 32, gang=True)
    h.run_round({"C": rng(32)})
    h.add("A", "priority-1", 17)
    h.run_round({"A": rng(17)}, {"C": {1: rng(32)}})


def test_gang_preemption_avoid_cascading():
    """Go: 'gang preemption avoid cascading preemption'."""
    h = Harness(ref_config(), n32_nodes(3), {"A": 1, "B": 1})
    h.add("A", "priority-1", 33, gang=True)
    h.run_round({"A": rng(33)})
    h.add(
        "A",
        "priority-1",
        32,
        gang=True,
        per_job_pc=["priority-1"] * 31 + ["priority-0"],
    )
    h.run_round({"A": rng(32)})
    h.add("B", "priority-1", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"B": [0]}, {"A": {1: rng(32)}})


def test_rescheduled_jobs_dont_count_towards_burst():
    """Go: "rescheduled jobs don't count towards global scheduling rate
    limit" (rate=2/s, burst=5; ~1s between rounds refills 2 tokens, spent
    on NEW jobs only — the 5 rescheduled evictees are free)."""
    cfg = ref_config(
        rate_limits=RateLimits(
            maximum_scheduling_rate=2.0,
            maximum_scheduling_burst=5,
            maximum_per_queue_scheduling_burst=10**9,
        )
    )
    h = Harness(cfg, n32_nodes(1), {"A": 1})
    h.add("A", "priority-0", 10)
    h.run_round({"A": rng(5)})
    h.add("A", "priority-0", 10)
    h.run_round({"A": rng(2)})


def test_rescheduled_jobs_dont_count_towards_lookback():
    """Go: "rescheduled jobs don't count towards maxQueueLookback"."""
    h = Harness(ref_config(max_queue_lookback=5), n32_nodes(1), {"A": 1})
    h.add("A", "priority-0", 2)
    h.run_round({"A": rng(2)})
    h.add("A", "priority-0", 10)
    h.run_round({"A": rng(5)})


def test_rescheduled_jobs_dont_count_towards_round_fraction():
    """Go: "rescheduled jobs don't count towards
    MaximumClusterFractionToSchedule" (5/32 cpu per round)."""
    h = Harness(
        ref_config(maximum_resource_fraction_to_schedule={"cpu": 5.0 / 32.0}),
        n32_nodes(1),
        {"A": 1},
    )
    h.add("A", "priority-0", 10)
    h.run_round({"A": rng(6)})
    h.add("A", "priority-0", 10)
    h.run_round({"A": rng(6)})


def test_priority_class_preemption_two_classes():
    """Go: 'priority class preemption two classes'."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1})
    h.add("A", "priority-0", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]})
    h.add("A", "priority-1", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]}, {"A": {0: [0]}})


def test_priority_class_preemption_cross_queue():
    """Go: 'priority class preemption cross-queue'."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
    h.add("A", "priority-0", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [0]})
    h.add("B", "priority-1", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"B": [0]}, {"A": {0: [0]}})


def test_priority_class_preemption_not_scheduled():
    """Go: 'priority class preemption not scheduled' — a job scheduled
    earlier in the round is displaced by a higher-PC job, ending the round
    unscheduled (not preempted: it never ran)."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1})
    h.add("A", "priority-0", 1, cpu=32, mem_gi=256, large_tol=True)
    h.add("A", "priority-1", 1, cpu=32, mem_gi=256, large_tol=True)
    h.run_round({"A": [1]})


def test_priority_class_preemption_through_multiple_levels():
    """Go: 'priority class preemption through multiple levels'."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1, "C": 1})
    h.add("A", "priority-0", 16)
    h.add("B", "priority-1", 16)
    h.run_round({"A": rng(16), "B": rng(16)})
    h.add("C", "priority-2", 17)
    # B's preempted member is its LAST (index 15): the evicted members
    # reschedule in stream order until capacity runs out
    # (preempting_queue_scheduler_test.go:1003-1010).
    h.run_round(
        {"C": rng(17)},
        {"A": {0: rng(16)}, "B": {0: [15]}},
    )


def test_maximum_resource_fraction_per_queue():
    """Go: 'MaximumResourceFractionPerQueue' — per-PC cumulative caps."""
    pcs = {
        name: PriorityClass(
            name,
            pc.priority,
            preemptible=pc.preemptible,
            maximum_resource_fraction_per_queue={
                "priority-0": {"cpu": 1.0 / 32.0},
                "priority-1": {"cpu": 2.0 / 32.0},
                "priority-2": {"cpu": 3.0 / 32.0},
                "priority-3": {"cpu": 4.0 / 32.0},
            }[name]
            if name
            in ("priority-0", "priority-1", "priority-2", "priority-3")
            else {},
        )
        for name, pc in REF_PCS.items()
    }
    h = Harness(
        ref_config(priority_classes=pcs), n32_nodes(1), {"A": 1}
    )
    h.add("A", "priority-0", 32)
    h.add("A", "priority-1", 32)
    h.add("A", "priority-2", 32)
    h.add("A", "priority-3", 32)
    h.add("A", "priority-0", 32)
    h.run_round({"A": [0, 32, 33, 64, 65, 66, 96, 97, 98, 99]})
    h.add("A", "priority-0", 1)
    h.run_round()


def test_queued_jobs_not_preempted_cross_queue():
    """Go: 'Queued jobs are not preempted cross queue' (+ variants)."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
    h.add("A", "priority-0", 32)
    h.add("B", "priority-1", 32)
    h.run_round({"B": rng(32)})
    h.run_round()

    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
    h.add("A", "priority-0", 32)
    h.add("B", "priority-1", 31)
    h.run_round({"A": [0], "B": rng(31)})
    h.run_round()

    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
    h.add("A", "priority-0", 32)
    h.add("B", "priority-3", 32)
    h.run_round({"B": rng(32)})
    h.run_round()


def test_queued_jobs_not_preempted_cross_queue_multiple_rounds():
    """Go: 'Queued jobs are not preempted cross queue multiple rounds'."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
    h.add("A", "priority-1", 16)
    h.run_round({"A": rng(16)})
    h.add("A", "priority-0", 16)
    h.add("B", "priority-1", 32)
    h.run_round({"B": rng(16)})
    h.run_round()


def test_oversubscribed_eviction_does_not_evict_non_preemptible():
    """Go: 'Oversubscribed eviction does not evict non-preemptible'."""
    h = Harness(ref_config(), n32_nodes(2), {"A": 1, "B": 1})
    h.add("A", "priority-2", 1, cpu=16, mem_gi=128)
    h.add("A", "priority-2-non-preemptible", 3, cpu=16, mem_gi=128)
    h.run_round({"A": rng(4)})
    h.add("B", "priority-3", 1, cpu=16, mem_gi=128)
    h.add("B", "priority-2-non-preemptible", 1, cpu=16, mem_gi=128)
    h.run_round({"B": [0]}, {"A": {0: [0]}})
    h.run_round()


def test_cordoning_prevents_new_jobs_not_rescheduling():
    """Go: 'Cordoning prevents scheduling new jobs but not re-scheduling
    running jobs'."""
    h = Harness(ref_config(), n32_nodes(1), {"A": 1, "B": 1})
    h.add("A", "priority-1", 1)
    h.run_round({"A": [0]})
    h.add("B", "priority-1", 1)
    h.run_round(cordon=[0])  # B blocked; A's job survives re-scheduling
    h.add("B", "priority-1", 1)
    h.run_round()
    h.run_round()


def test_protected_fraction_of_fair_share():
    """Go: 'ProtectedFractionOfFairShare' (=1.0)."""
    h = Harness(
        ref_config(protected_fraction_of_fair_share=1.0),
        n32_nodes(1),
        {"A": 1, "B": 1, "C": 1},
    )
    h.add("A", "priority-0", 10)
    h.run_round({"A": rng(10)})
    h.add("B", "priority-3", 22)
    h.run_round({"B": rng(22)})
    h.add("C", "priority-0", 1)
    h.run_round()  # A is within protected share: C cannot displace
    h.run_round()


def test_protected_fraction_of_fair_share_at_limit():
    """Go: 'ProtectedFractionOfFairShare at limit' (=0.5, A factor 0.5)."""
    h = Harness(
        ref_config(protected_fraction_of_fair_share=0.5),
        n32_nodes(1),
        {"A": 0.5, "B": 1, "C": 1},
    )
    h.add("A", "priority-0", 8)
    h.run_round({"A": rng(8)})
    h.add("B", "priority-3", 24)
    h.run_round({"B": rng(24)})
    h.add("C", "priority-0", 1)
    h.run_round()
    h.run_round()
