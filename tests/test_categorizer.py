"""Run-error regex categorizer (internal/executor/categorizer)."""

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.jobdb.ingest import categorize_error


def test_categorize_rules():
    rules = SchedulingConfig().error_categories
    assert categorize_error("container killed: Out Of Memory", rules) == "oom"
    assert categorize_error("request timed out after 30s", rules) == "timeout"
    assert categorize_error("executor ex-a timed out", rules) == "lost-executor"
    assert categorize_error("Failed to pull image foo:latest", rules) == "image-pull"
    assert categorize_error("mystery explosion", rules) == "uncategorised"
    assert categorize_error("", rules) == ""


def test_category_lands_in_jobdb_and_query():
    from armada_tpu.core.types import JobSpec, QueueSpec
    from armada_tpu.events import (
        EventSequence,
        InMemoryEventLog,
        JobRunErrors,
        JobRunLeased,
        SubmitJob,
    )
    from armada_tpu.services.queryapi import QueryApi
    from armada_tpu.services.scheduler import SchedulerService

    config = SchedulingConfig()
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    log.publish(EventSequence.of("q", "s", SubmitJob(
        created=0.0, job=JobSpec(id="j1", queue="q", jobset="s",
                                 requests={"cpu": "1"}))))
    log.publish(EventSequence.of("q", "s", JobRunLeased(
        created=1.0, job_id="j1", run_id="r1", executor="e", node_id="n",
        pool="p", scheduled_at_priority=1000)))
    log.publish(EventSequence.of("q", "s", JobRunErrors(
        created=2.0, job_id="j1", run_id="r1",
        error="OOMKilled: out of memory", retryable=False)))
    sched.ingester.sync()
    assert sched.jobdb.get("j1").error_category == "oom"
    rows, _ = QueryApi(sched.jobdb).get_jobs()
    assert rows[0].error_category == "oom"
