"""Staleness guard for docs/architecture.md "Known gaps".

The gaps list rotted twice (it kept claiming a JSON-only executor wire
and a ~330-line UI long after both were obsolete). This test makes the
list self-verifying: every listed gap carries a `gap:<id>` marker mapped
here to a detector that answers "does the claimed-missing feature exist
now?". A gap whose feature EXISTS fails the suite (stale claim); a
marker with no detector fails too (unguarded claim); and the obsolete
claims that prompted this guard must stay gone.
"""

import os
import re

DOC = os.path.join(os.path.dirname(__file__), "..", "docs", "architecture.md")


def _gaps_section() -> str:
    with open(DOC) as f:
        text = f.read()
    m = re.search(r"## Known gaps.*?(?=\n## |\Z)", text, re.DOTALL)
    assert m, "docs/architecture.md lost its 'Known gaps' section"
    return m.group(0)


def _feature_exists_kubernetes() -> bool:
    # A kubelet/kube-api integration would import the kubernetes client.
    root = os.path.join(os.path.dirname(__file__), "..", "armada_tpu")
    for dirpath, _, files in os.walk(root):
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(dirpath, name)) as f:
                if re.search(r"^\s*(import|from) kubernetes", f.read(), re.M):
                    return True
    return False


def _feature_exists_rich_lookout_ui() -> bool:
    # The gap claims "a fraction of the surface" of a 22.6k-line app:
    # consider it closed once the UI grows past a few thousand lines.
    path = os.path.join(
        os.path.dirname(__file__), "..", "armada_tpu", "services",
        "lookout_ui.py",
    )
    with open(path) as f:
        return sum(1 for _ in f) > 5000


def _feature_exists_cpp_grpc() -> bool:
    client_dir = os.path.join(os.path.dirname(__file__), "..", "native", "client")
    if not os.path.isdir(client_dir):
        return False
    for dirpath, _, files in os.walk(client_dir):
        for name in files:
            if name.endswith((".cpp", ".cc", ".h", ".hpp")):
                with open(os.path.join(dirpath, name), errors="replace") as f:
                    if "grpc::" in f.read():
                        return True
    return False


def _feature_exists_scala_client() -> bool:
    return os.path.isdir(
        os.path.join(os.path.dirname(__file__), "..", "client", "scala")
    )


def _feature_exists_sharded_budget() -> bool:
    # Closed once the mesh solve takes a budget (chunked pass 1).
    path = os.path.join(
        os.path.dirname(__file__), "..", "armada_tpu", "parallel", "mesh.py"
    )
    with open(path) as f:
        return "budget" in f.read()


def _feature_exists_network_chaos() -> bool:
    path = os.path.join(
        os.path.dirname(__file__), "..", "armada_tpu", "services", "chaos.py"
    )
    with open(path) as f:
        src = f.read()
    return "network_partition" in src


DETECTORS = {
    "kubernetes": _feature_exists_kubernetes,
    "lookout-ui-surface": _feature_exists_rich_lookout_ui,
    "cpp-client-grpc": _feature_exists_cpp_grpc,
    "scala-client": _feature_exists_scala_client,
    "sharded-round-budget": _feature_exists_sharded_budget,
    "chaos-network": _feature_exists_network_chaos,
}


def test_every_gap_is_guarded_and_current():
    section = _gaps_section()
    markers = re.findall(r"<!-- gap:([a-z0-9-]+) -->", section)
    assert markers, "Known gaps entries must carry <!-- gap:<id> --> markers"
    unguarded = [m for m in markers if m not in DETECTORS]
    assert not unguarded, (
        f"gaps {unguarded} have no staleness detector in test_docs_gaps.py; "
        "add one so the claim can't rot"
    )
    stale = [m for m in markers if DETECTORS[m]()]
    assert not stale, (
        f"gaps {stale} claim features that now exist — "
        "update docs/architecture.md 'Known gaps'"
    )


def test_obsolete_claims_stay_gone():
    """The two claims that rotted must not reappear."""
    section = _gaps_section().lower()
    assert not re.search(
        r"executor (wire|lease/heartbeat payloads).{0,60}json", section
    ), (
        "the executor wire has a protobuf schema (ProtoExecutorClient); "
        "a JSON-only executor-wire claim is stale"
    )
    assert "~330" not in section, "the stale UI line count is back"


def test_gap_markers_match_prose():
    """Every bullet in the gaps list carries a marker (no unmarked,
    therefore unguarded, claims sneak in)."""
    section = _gaps_section()
    bullets = [
        line
        for line in section.splitlines()
        if line.startswith("- ")
    ]
    unmarked = [b for b in bullets if "<!-- gap:" not in b]
    assert not unmarked, f"gap bullets without markers: {unmarked}"
