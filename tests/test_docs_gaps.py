"""Staleness guard for docs/architecture.md "Known gaps".

The gaps list rotted twice (it kept claiming a JSON-only executor wire
and a ~330-line UI long after both were obsolete), so the section is now
GENERATED from the tracked checklist docs/known_gaps.yaml
(tools/gen_known_gaps.py) and this suite makes the checklist itself
self-verifying:

  - the rendered section must match the doc byte-for-byte (no hand
    edits, no drift);
  - every OPEN gap carries a feature detector answering "does the
    claimed-missing feature exist now?" — a gap whose feature exists
    fails (stale claim), a gap with no detector fails (unguarded);
  - every OPEN gap names its future closer test; if that test already
    exists AND passes, the suite fails — flip the gap to closed;
  - every CLOSED gap's closer test must exist (the evidence that closed
    it cannot silently vanish).
"""

import os
import re
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..")
DOC = os.path.join(REPO, "docs", "architecture.md")

sys.path.insert(0, os.path.join(REPO, "tools"))
from gen_known_gaps import SECTION_RE, load_gaps, render  # noqa: E402

GAPS = load_gaps()


def _gaps_section() -> str:
    with open(DOC) as f:
        text = f.read()
    m = SECTION_RE.search(text)
    assert m, "docs/architecture.md lost its 'Known gaps' section"
    return m.group(0)


def _feature_exists_kubernetes() -> bool:
    # A kubelet/kube-api integration would import the kubernetes client.
    root = os.path.join(REPO, "armada_tpu")
    for dirpath, _, files in os.walk(root):
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(dirpath, name)) as f:
                if re.search(r"^\s*(import|from) kubernetes", f.read(), re.M):
                    return True
    return False


def _feature_exists_rich_lookout_ui() -> bool:
    # The gap claims "a fraction of the surface" of a 22.6k-line app:
    # consider it closed once the UI grows past a few thousand lines.
    path = os.path.join(REPO, "armada_tpu", "services", "lookout_ui.py")
    with open(path) as f:
        return sum(1 for _ in f) > 5000


def _feature_exists_cpp_grpc() -> bool:
    client_dir = os.path.join(REPO, "native", "client")
    if not os.path.isdir(client_dir):
        return False
    for dirpath, _, files in os.walk(client_dir):
        for name in files:
            if name.endswith((".cpp", ".cc", ".h", ".hpp")):
                with open(os.path.join(dirpath, name), errors="replace") as f:
                    if "grpc::" in f.read():
                        return True
    return False


def _feature_exists_scala_client() -> bool:
    return os.path.isdir(os.path.join(REPO, "client", "scala"))


def _feature_exists_sharded_budget() -> bool:
    # Closed once the mesh solve takes a budget (chunked pass 1).
    path = os.path.join(REPO, "armada_tpu", "parallel", "mesh.py")
    with open(path) as f:
        return "budget" in f.read()


def _feature_exists_window_autotune() -> bool:
    # Closed once something adapts the hot-window size at runtime: an
    # autotune hook in the solver or a config switch for it.
    solver = os.path.join(REPO, "armada_tpu", "solver")
    for name in os.listdir(solver):
        if name.endswith(".py"):
            with open(os.path.join(solver, name)) as f:
                if "autotune" in f.read().lower():
                    return True
    with open(os.path.join(REPO, "armada_tpu", "core", "config.py")) as f:
        return "autotune" in f.read().lower()


def _feature_exists_native_ring_test() -> bool:
    # Closed once the native ICI ring runs on real hardware in a test:
    # the promoted form of tools/pallas_probe.py's native smoke.
    path = os.path.join(REPO, "tests", "test_pallas_parity.py")
    if not os.path.exists(path):
        return False
    with open(path) as f:
        return "def test_native_ring_on_hardware(" in f.read()


DETECTORS = {
    "kubernetes": _feature_exists_kubernetes,
    "lookout-ui-surface": _feature_exists_rich_lookout_ui,
    "cpp-client-grpc": _feature_exists_cpp_grpc,
    "scala-client": _feature_exists_scala_client,
    "sharded-round-budget": _feature_exists_sharded_budget,
    "hot-window-autotune": _feature_exists_window_autotune,
    "pallas-ici-native": _feature_exists_native_ring_test,
}


def _closer_exists(closer: str) -> bool:
    """Does the pytest node id point at an existing test function?
    Handles both module-level ids (file::test) and class-based ones
    (file::Class::test — the def is indented, the class must exist)."""
    parts = closer.split("::")
    path, func = parts[0], parts[-1]
    full = os.path.join(REPO, path)
    if not os.path.exists(full):
        return False
    with open(full) as f:
        src = f.read()
    for cls in parts[1:-1]:
        if re.search(rf"^class {re.escape(cls)}\b", src, re.M) is None:
            return False
    return re.search(rf"^[ \t]*def {re.escape(func)}\(", src, re.M) is not None


def test_doc_matches_checklist():
    """The doc section is exactly the YAML rendering — regenerate with
    `python tools/gen_known_gaps.py --write` after editing the YAML."""
    assert _gaps_section().rstrip("\n") == render(GAPS), (
        "docs/architecture.md 'Known gaps' drifted from "
        "docs/known_gaps.yaml; rerun tools/gen_known_gaps.py --write"
    )


def test_every_open_gap_is_guarded_and_current():
    open_ids = [g["id"] for g in GAPS if g["status"] == "open"]
    assert open_ids, "no open gaps tracked — suspicious for this repo"
    unguarded = [i for i in open_ids if i not in DETECTORS]
    assert not unguarded, (
        f"open gaps {unguarded} have no staleness detector in "
        "test_docs_gaps.py; add one so the claim can't rot"
    )
    stale = [i for i in open_ids if DETECTORS[i]()]
    assert not stale, (
        f"gaps {stale} claim features that now exist — flip them to "
        "closed in docs/known_gaps.yaml"
    )


def test_closed_gaps_name_existing_tests():
    missing = [
        g["id"]
        for g in GAPS
        if g["status"] == "closed" and not _closer_exists(g["closer"])
    ]
    assert not missing, (
        f"closed gaps {missing} name closer tests that do not exist — "
        "the evidence that closed them has rotted"
    )


def test_open_gaps_closers_not_already_passing():
    """An open gap whose named closer test exists and PASSES is a rotted
    claim: the feature landed but the checklist wasn't flipped."""
    landed = [g for g in GAPS if g["status"] == "open" and _closer_exists(g["closer"])]
    for g in landed:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", g["closer"], "-q", "--no-header"],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=600,
        )
        # Exit 0 alone is not "passes": a slow-marked closer is SKIPPED
        # by the nested run (conftest policy) and pytest still exits 0 —
        # only an actual "N passed" report proves the claim rotted.
        passed = proc.returncode == 0 and re.search(
            r"\b[1-9]\d* passed", proc.stdout
        )
        assert not passed, (
            f"open gap {g['id']}: closer {g['closer']} exists and passes — "
            "flip it to closed in docs/known_gaps.yaml"
        )


def test_obsolete_claims_stay_gone():
    """The two claims that rotted must not reappear."""
    section = _gaps_section().lower()
    assert not re.search(
        r"executor (wire|lease/heartbeat payloads).{0,60}json", section
    ), (
        "the executor wire has a protobuf schema (ProtoExecutorClient); "
        "a JSON-only executor-wire claim is stale"
    )
    assert "~330" not in section, "the stale UI line count is back"


def test_gap_markers_match_prose():
    """Every bullet in the gaps list carries a marker (no unmarked,
    therefore unguarded, claims sneak in)."""
    section = _gaps_section()
    bullets = [line for line in section.splitlines() if line.startswith("- ")]
    assert bullets, "Known gaps section lost its bullets"
    unmarked = [
        b
        for b in bullets
        if "<!-- gap:" not in b and "<!-- closed-gap:" not in b
    ]
    assert not unmarked, f"gap bullets without markers: {unmarked}"


def test_metrics_inventory_matches_registry():
    """docs/metrics.md is GENERATED from SchedulerMetrics
    (tools/gen_metrics_doc.py): every registered family must appear in
    the doc with its registered type/labels/help, and no documented
    family may outlive its registration — same anti-rot contract as the
    known-gaps section."""
    from gen_metrics_doc import DOC_PATH, render

    assert os.path.exists(DOC_PATH), (
        "docs/metrics.md missing; run tools/gen_metrics_doc.py --write"
    )
    with open(DOC_PATH) as f:
        current = f.read()
    assert current == render(), (
        "docs/metrics.md is stale vs services/metrics.SchedulerMetrics; "
        "run tools/gen_metrics_doc.py --write"
    )
