"""The five tracked benchmark configs from BASELINE.md, run through the
discrete-event simulator on the real scheduling path (kernel-backed where it
matters). Mirrors the reference's simulator testdata
(internal/scheduler/simulator/testdata/clusters/cpu_1_1_100.yaml,
workloads/basicWorkload.yaml)."""

from armada_tpu.core.config import FloatingResource, PriorityClass, SchedulingConfig
from armada_tpu.core.types import Taint
from armada_tpu.sim import (
    ClusterSpec,
    JobTemplate,
    QueueSpecSim,
    Simulator,
    WorkloadSpec,
)
from armada_tpu.sim.simulator import NodeTemplate, ShiftedExponential


def test_config1_reference_binpack():
    """#1: 1 cluster, 1 queue, CPU jobs x 100 32-core nodes (the reference
    cpu_1_1_100 + basicWorkload shape, scaled to 1k jobs per BASELINE)."""
    sim = Simulator(
        [
            ClusterSpec(
                "cpu-01",
                node_templates=(NodeTemplate(count=100, cpu="32", memory="1024Gi"),),
            )
        ],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "A",
                    priority_factor=1.0,
                    job_templates=(
                        JobTemplate(
                            id="basic",
                            number=1000,
                            cpu="1",
                            memory="10Gi",
                            priority_class="armada-default",
                            jobset="job-set",
                            runtime=ShiftedExponential(minimum=300.0),
                        ),
                    ),
                ),
            )
        ),
    )
    res = sim.run()
    assert res.finished_jobs == 1000
    # 3200 cores / 1000 one-core jobs: single wave, makespan ~ runtime
    assert res.makespan < 600
    assert res.preemptions == 0


def test_config2_multi_queue_drf():
    """#2: 10 weighted queues, mixed CPU/mem requests, fair division."""
    queues = tuple(
        QueueSpecSim(
            f"q{i}",
            priority_factor=1.0 if i < 5 else 2.0,
            job_templates=(
                JobTemplate(
                    id="mixed",
                    number=100,
                    cpu=str(1 + i % 3),
                    memory=f"{4 * (1 + i % 2)}Gi",
                    runtime=ShiftedExponential(minimum=120.0),
                ),
            ),
        )
        for i in range(10)
    )
    sim = Simulator(
        [ClusterSpec("c", node_templates=(NodeTemplate(count=20, cpu="16", memory="64Gi"),))],
        WorkloadSpec(queues=queues),
        backend="kernel",
        max_time=100_000.0,
    )
    # Weighted fair division: after the first contended round, queues with
    # priority_factor 1.0 (weight 1) must hold at least as much cpu as
    # priority_factor 2.0 queues (weight 1/2).
    for ex in sim.executors:
        ex.tick(0.0)
    t, q, js, jobs = sim._pending_submissions[0]
    for t_, q_, js_, jobs_ in sim._pending_submissions:
        sim.submit.submit(q_, js_, jobs_, now=0.0)
    sim._pending_submissions = []
    sim.scheduler.cycle(now=0.0)
    txn = sim.scheduler.jobdb.read_txn()
    cpu_by_queue = {}
    for j in txn.leased_jobs():
        millis = int(float(j.spec.requests["cpu"]) * 1000)
        cpu_by_queue[j.queue] = cpu_by_queue.get(j.queue, 0) + millis
    heavy = [cpu_by_queue.get(f"q{i}", 0) for i in range(5)]  # weight 1
    light = [cpu_by_queue.get(f"q{i}", 0) for i in range(5, 10)]  # weight 1/2
    assert min(heavy) >= max(light), (heavy, light)

    res = sim.run()
    assert res.finished_jobs == 1000


def test_config3_gang_128way():
    """#3: all-or-nothing job sets up to 128-way gangs."""
    sim = Simulator(
        [ClusterSpec("c", node_templates=(NodeTemplate(count=32, cpu="16", memory="64Gi"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "gangs",
                    job_templates=(
                        JobTemplate(
                            id="g128",
                            number=128,
                            cpu="4",
                            memory="4Gi",
                            gang_cardinality=128,
                            runtime=ShiftedExponential(minimum=60.0),
                        ),
                        JobTemplate(
                            id="g8",
                            number=64,
                            cpu="2",
                            memory="2Gi",
                            gang_cardinality=8,
                            submit_time=10.0,
                            runtime=ShiftedExponential(minimum=30.0),
                        ),
                    ),
                ),
            )
        ),
        backend="kernel",
        max_time=20_000.0,
    )
    res = sim.run()
    assert res.finished_jobs == 128 + 64
    assert res.preemptions == 0


def test_config4_preemption_priority_classes():
    """#4: urgency-based eviction under oversubscription."""
    cfg = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
    )
    sim = Simulator(
        [ClusterSpec("c", node_templates=(NodeTemplate(count=4, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "batch",
                    job_templates=(
                        JobTemplate(id="long", number=32, cpu="1", memory="1Gi",
                                    runtime=ShiftedExponential(minimum=5000.0)),
                    ),
                ),
                QueueSpecSim(
                    "urgent",
                    job_templates=(
                        JobTemplate(id="hi", number=16, cpu="1", memory="1Gi",
                                    priority_class="high", submit_time=60.0,
                                    runtime=ShiftedExponential(minimum=60.0)),
                    ),
                ),
            )
        ),
        config=cfg,
        max_time=30_000.0,
    )
    res = sim.run()
    urgent_done = sum(
        1 for jid, s in res.events_by_job.items()
        if jid.startswith("urgent") and s.value == "succeeded"
    )
    assert urgent_done == 16
    assert res.preemptions > 0


def test_config5_multicluster_taints_floating():
    """#5: 10 clusters, node taints + selectors + floating resources."""
    cfg = SchedulingConfig(
        floating_resources=(
            FloatingResource(
                "example.com/license", "1",
                {"default": {"example.com/license": "8"}},
            ),
        ),
    )
    clusters = [
        ClusterSpec(
            f"cluster-{i:02d}",
            node_templates=(
                NodeTemplate(
                    count=5,
                    cpu="16",
                    memory="64Gi",
                    labels={"zone": "a" if i < 5 else "b"},
                    taints=(Taint("special", "true"),) if i == 9 else (),
                ),
            ),
        )
        for i in range(10)
    ]
    sim = Simulator(
        clusters,
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "multi",
                    job_templates=(
                        JobTemplate(id="plain", number=200, cpu="1", memory="1Gi",
                                    runtime=ShiftedExponential(minimum=60.0)),
                        JobTemplate(id="zoned", number=50, cpu="1", memory="1Gi",
                                    node_selector={"zone": "b"},
                                    runtime=ShiftedExponential(minimum=60.0)),
                        # 20 licensed jobs against a pool cap of 8: at least
                        # 3 waves of 60s even though cpu is plentiful.
                        JobTemplate(id="lic", number=20, cpu="1", memory="1Gi",
                                    gpu="0",
                                    runtime=ShiftedExponential(minimum=60.0)),
                    ),
                ),
            )
        ),
        config=cfg,
        max_time=20_000.0,
    )
    # Inject the license request (JobTemplate has no floating field yet).
    for i, (t, q, js, jobs) in enumerate(sim._pending_submissions):
        sim._pending_submissions[i] = (
            t, q, js,
            [
                j.with_(requests={**j.requests, "example.com/license": "1"})
                if j.id.startswith("multi-lic")
                else j
                for j in jobs
            ],
        )
    res = sim.run()
    assert res.finished_jobs == 270
    for jid, node in res.placements.items():
        cluster_idx = int(node.split("-")[1])
        # zoned jobs only ran in zone-b clusters (5..9)
        if "zoned" in jid:
            assert cluster_idx >= 5, (jid, node)
        # nothing tolerates cluster-09's taint: no job may land there
        assert cluster_idx != 9, (jid, node)
    # license cap 8 over 20 jobs x 60s: at least 3 waves
    assert res.makespan >= 3 * 60.0 - 1
