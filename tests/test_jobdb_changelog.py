"""JobDb changelog: the serial-delta feed behind incremental scheduling
cycles (changed_since semantics, deletion stamps, compaction truncation,
checkpoint-restore resync)."""

from armada_tpu.core.types import JobSpec
from armada_tpu.jobdb import JobDb
from armada_tpu.jobdb.jobdb import Job, JobState


def _put(db, jid, state=JobState.QUEUED):
    txn = db.write_txn()
    txn.upsert(Job(spec=JobSpec(id=jid, queue="q", requests={"cpu": "1"}),
                   state=state))
    txn.commit()


def test_changed_since_dedup_and_order():
    db = JobDb()
    base = db.serial
    _put(db, "a")
    _put(db, "b")
    _put(db, "a")  # a changes again: deduped, still reported once
    changed = db.changed_since(base)
    # Oldest-first with dedup keeps first-occurrence order: a, b.
    assert changed == ["a", "b"]
    mid = db.serial
    _put(db, "c")
    assert db.changed_since(mid) == ["c"]
    assert db.changed_since(db.serial) == []


def test_deletions_are_stamped():
    db = JobDb()
    _put(db, "a")
    mark = db.serial
    txn = db.write_txn()
    txn.delete("a")
    txn.commit()
    assert db.changed_since(mark) == ["a"]
    assert db.get("a") is None


def test_compaction_truncates_history():
    db = JobDb()
    # Force many writes against few live jobs so the changelog compacts
    # (threshold max(65536, 2*len(jobs))).
    db._changelog = [(i, f"x{i % 4}") for i in range(1, 70000)]
    db.serial = 70000
    _put(db, "fresh")
    assert db._changelog_start > 0
    # A watermark older than the retained history returns None (resync).
    assert db.changed_since(0) is None
    # A recent watermark still answers.
    assert db.changed_since(db.serial - 1) == ["fresh"]


def test_load_resets_history():
    db = JobDb()
    _put(db, "a")
    dump = db.dump()
    db2 = JobDb()
    db2.load(dump)
    # No history before the checkpoint: consumers must resync.
    assert db2.changed_since(0) is None
    assert db2.changed_since(db2.serial) == []
    _put(db2, "b")
    assert db2.changed_since(dump["serial"]) == ["b"]
