"""Generic ingest pipeline + per-jobset event-stream index
(common/ingest/ingestion_pipeline.go; eventingester/store/eventstore.go)."""

import time

from armada_tpu.core.types import JobSpec
from armada_tpu.events import (
    EventSequence,
    InMemoryEventLog,
    JobRunLeased,
    SubmitJob,
)
from armada_tpu.events.pipeline import IngestPipeline
from armada_tpu.services.event_index import EventStreamIndex


def submit(log, queue, jobset, job_id, created=1.0):
    log.publish(
        EventSequence.of(
            queue,
            jobset,
            SubmitJob(
                created=created,
                job=JobSpec(id=job_id, queue=queue, jobset=jobset,
                            requests={"cpu": "1"}),
            ),
        )
    )


def test_pipeline_batches_and_advances_cursor():
    log = InMemoryEventLog()
    batches = []
    pipe = IngestPipeline(
        log,
        convert=lambda entries: [e.offset for e in entries],
        sink=batches.append,
        batch_size=3,
    )
    for i in range(7):
        submit(log, "q", "js", f"j{i}")
    assert pipe.lag_events == 7
    applied = pipe.sync()
    assert applied == 7
    assert [len(b) for b in batches] == [3, 3, 1]
    assert pipe.cursor == log.end_offset and pipe.lag_events == 0
    # Idempotent on drained log.
    assert pipe.sync() == 0


def test_pipeline_merge_hook():
    log = InMemoryEventLog()
    merged = []
    pipe = IngestPipeline(
        log,
        convert=lambda entries: [(e.sequence.queue, 1) for e in entries],
        merge=lambda ops: {
            q: sum(n for qq, n in ops if qq == q) for q, _ in ops
        },
        sink=merged.append,
        batch_size=100,
    )
    for i in range(4):
        submit(log, "qa" if i % 2 else "qb", "js", f"j{i}")
    pipe.sync()
    assert merged == [{"qa": 2, "qb": 2}]


def test_pipeline_time_batching_holds_partial_batches():
    log = InMemoryEventLog()
    batches = []
    pipe = IngestPipeline(
        log,
        convert=lambda entries: list(entries),
        sink=batches.append,
        batch_size=10,
        max_batch_delay_s=0.1,
    )
    submit(log, "q", "js", "j0")
    assert pipe.sync() == 0  # held: batch not full, delay not elapsed
    assert batches == []
    time.sleep(0.12)
    assert pipe.sync() == 1  # delay elapsed: partial batch flushes
    assert len(batches) == 1


def test_event_index_partitions_streams():
    log = InMemoryEventLog()
    index = EventStreamIndex(log)
    for i in range(5):
        submit(log, "q", "js-a", f"a{i}", created=float(i))
    for i in range(3):
        submit(log, "q", "js-b", f"b{i}", created=float(i))
    index.sync()
    assert index.lag_events == 0
    a = index.read_from("q", "js-a", 0)
    b = index.read_from("q", "js-b", 0)
    assert len(a) == 5 and len(b) == 3
    assert all(seq.jobset == "js-a" for _, seq in a)
    # Resume from a mid-stream cursor: only later offsets return.
    mid = a[2][0] + 1
    assert [off for off, _ in index.read_from("q", "js-a", mid)] == [
        off for off, _ in a[3:]
    ]
    # Unknown jobset: None — the watch path must fall back to the log
    # scan, because "not indexed" never means "no events exist".
    assert index.read_from("q", "nope", 0) is None


def test_event_index_idempotent_replay():
    log = InMemoryEventLog()
    index = EventStreamIndex(log)
    submit(log, "q", "js", "j0")
    index.sync()
    # Simulate at-least-once replay: rewind the cursor and re-sync.
    index._pipeline.cursor = 0
    index.sync()
    assert len(index.read_from("q", "js", 0)) == 1


def test_event_index_retention_prune():
    log = InMemoryEventLog()
    index = EventStreamIndex(log)
    submit(log, "q", "old", "j0", created=10.0)
    submit(log, "q", "new", "j1", created=100.0)
    log.publish(
        EventSequence.of(
            "q", "new",
            JobRunLeased(created=110.0, job_id="j1", run_id="r1",
                         executor="e", node_id="n", pool="p"),
        )
    )
    index.sync()
    assert index.prune(older_than=50.0) == 1
    # Pruned jobset reads as unknown (None), NOT empty: watchers fall back
    # to the log, which still holds the history.
    assert index.read_from("q", "old", 0) is None
    # A surviving jobset has pre-watermark offsets, so it stays
    # authoritative from zero.
    assert len(index.read_from("q", "new", 0)) == 2


def test_event_index_pruned_then_recreated_jobset_defers_to_log():
    log = InMemoryEventLog()
    index = EventStreamIndex(log)
    submit(log, "q", "js", "j0", created=10.0)
    index.sync()
    assert index.prune(older_than=50.0) == 1
    # The jobset comes back to life: the index re-creates the key with
    # only the new offset...
    submit(log, "q", "js", "j1", created=100.0)
    index.sync()
    # ...so a read from before the prune watermark must defer to the log
    # (None), never serve an amputated history.
    assert index.read_from("q", "js", 0) is None
    # Reads past the watermark serve from the index.
    later = index.read_from("q", "js", index._pruned_through)
    assert later is not None and len(later) == 1


def test_watch_uses_index_end_to_end():
    """The full stack's watch path serves from the index."""
    from armada_tpu.services.grpc_api import connect
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.services.server import ControlPlane

    plane = ControlPlane(SchedulingConfig(), grpc_port=0)
    try:
        client = connect(f"127.0.0.1:{plane.grpc_port}")
        client.create_queue("q")
        ids = client.submit_jobs(
            "q", "js", [{"requests": {"cpu": "1", "memory": "1Gi"}}]
        )
        events = list(client.watch_jobset("q", "js", watch=False))
        assert any(
            e["type"] == "SubmitJob" and e.get("job_id") == ids[0]
            for e in events
        )
        assert plane.event_index.read_from("q", "js", 0)
    finally:
        plane.stop()
