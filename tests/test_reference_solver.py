"""Behavioral tests for the Python oracle, mirroring the scenario families of
the reference's scheduler tests (preempting_queue_scheduler_test.go,
queue_scheduler_test.go, nodedb_test.go)."""

import numpy as np
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, NodeSpec, QueueSpec, RunningJob
from armada_tpu.snapshot.round import NO_NODE, build_round_snapshot
from armada_tpu.solver.reference import ReferenceSolver


def cfg(**kw):
    return SchedulingConfig(**kw)


def nodes(n, cpu="32", mem="256Gi", pool="default", **kw):
    return [
        NodeSpec(
            id=f"node-{i:03d}",
            pool=pool,
            total_resources={"cpu": cpu, "memory": mem},
            **kw,
        )
        for i in range(n)
    ]


def job(i, queue="q", cpu="1", mem="1Gi", **kw):
    return JobSpec(
        id=f"job-{i:04d}",
        queue=queue,
        requests={"cpu": cpu, "memory": mem},
        submitted_ts=float(i),
        **kw,
    )


def solve(config, ns, qs, running, queued, **kw):
    snap = build_round_snapshot(config, "default", ns, qs, running, queued)
    return snap, ReferenceSolver(snap, **kw).solve()


def test_all_jobs_fit():
    snap, res = solve(cfg(), nodes(2), [QueueSpec("q")], [], [job(i) for i in range(10)])
    assert res.scheduled_mask.sum() == 10
    assert (res.assigned_node[res.scheduled_mask] >= 0).all()


def test_capacity_limit():
    # 1 node x 32 cpu; 40 jobs x 1 cpu -> 32 scheduled
    snap, res = solve(cfg(), nodes(1), [QueueSpec("q")], [], [job(i) for i in range(40)])
    assert res.scheduled_mask.sum() == 32


def test_first_in_queue_order():
    # queue order = priority then submit time: urgent job beats earlier ones
    queued = [job(i) for i in range(32)] + [job(99).with_(priority=-1)]
    snap, res = solve(cfg(), nodes(1), [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 32
    j_urgent = snap.job_ids.index("job-0099")
    assert res.scheduled_mask[j_urgent]


def test_drf_fair_split_two_queues():
    # 2 queues, equal weight, 1 node x 32 cpu, 32+ jobs each -> 16/16
    queued = [job(i, queue="a") for i in range(32)] + [
        job(100 + i, queue="b") for i in range(32)
    ]
    snap, res = solve(cfg(), nodes(1), [QueueSpec("a"), QueueSpec("b")], [], queued)
    by_queue = {}
    for j in np.flatnonzero(res.scheduled_mask):
        q = int(snap.job_queue[j])
        by_queue[q] = by_queue.get(q, 0) + 1
    assert by_queue == {0: 16, 1: 16}


def test_weighted_queues():
    # priority_factor 1 vs 3: weight 1 vs 1/3 -> 24/8 split of 32 cores
    queued = [job(i, queue="a") for i in range(32)] + [
        job(100 + i, queue="b") for i in range(32)
    ]
    snap, res = solve(
        cfg(), nodes(1), [QueueSpec("a", 1.0), QueueSpec("b", 3.0)], [], queued
    )
    by_queue = {}
    for j in np.flatnonzero(res.scheduled_mask):
        q = int(snap.job_queue[j])
        by_queue[q] = by_queue.get(q, 0) + 1
    assert by_queue[0] == 24 and by_queue[1] == 8


def test_undemanding_queue_share_redistributed():
    # queue a wants only 4; queue b unlimited -> b gets the rest
    queued = [job(i, queue="a") for i in range(4)] + [
        job(100 + i, queue="b") for i in range(40)
    ]
    snap, res = solve(cfg(), nodes(1), [QueueSpec("a"), QueueSpec("b")], [], queued)
    by_queue = {}
    for j in np.flatnonzero(res.scheduled_mask):
        q = int(snap.job_queue[j])
        by_queue[q] = by_queue.get(q, 0) + 1
    assert by_queue == {0: 4, 1: 28}


def test_gang_all_or_nothing_failure():
    # gang of 3 x 20 cpu on 2x32 nodes: only one per node, 2 < 3 -> none
    g = Gang(id="g1", cardinality=3)
    queued = [job(i, cpu="20", gang=g) for i in range(3)]
    snap, res = solve(cfg(), nodes(2), [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 0


def test_gang_success():
    g = Gang(id="g1", cardinality=3)
    queued = [job(i, cpu="16", gang=g) for i in range(3)]
    snap, res = solve(cfg(), nodes(3), [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 3


def test_gang_failure_does_not_block_singletons():
    g = Gang(id="g1", cardinality=2)
    queued = [job(0, cpu="20", gang=g), job(1, cpu="20", gang=g), job(2, cpu="4")]
    snap, res = solve(cfg(), nodes(1), [QueueSpec("q")], [], queued)
    # gang (40 cpu) cannot fit on 32-cpu node; the singleton still schedules
    assert res.scheduled_mask.sum() == 1
    j2 = snap.job_ids.index("job-0002")
    assert res.scheduled_mask[j2]


PREEMPT_CFG = cfg(
    priority_classes={
        "high": PriorityClass("high", 30000, preemptible=False),
        "low": PriorityClass("low", 1000, preemptible=True),
    },
    default_priority_class="high",
    protected_fraction_of_fair_share=1.0,
)


def test_urgency_preemption():
    # node full of preemptible low-prio from queue b; high-prio queued job
    # from queue a preempts via urgency
    running = [
        RunningJob(
            job=job(i, queue="b", cpu="8", priority_class="low"),
            node_id="node-000",
            scheduled_at_priority=1000,
        )
        for i in range(4)
    ]
    queued = [job(100, queue="a", cpu="8", priority_class="high")]
    snap, res = solve(
        PREEMPT_CFG, nodes(1), [QueueSpec("a"), QueueSpec("b")], running, queued
    )
    assert res.scheduled_mask.sum() == 1
    # exactly one low job preempted to make room (fair-share eviction may
    # reshuffle but capacity forces >= 1 preemption)
    assert res.preempted_mask.sum() >= 1
    total_cpu = snap.factory.index_of("cpu")
    # node not oversubscribed at the end: bound jobs' cpu <= 32
    bound = [
        j
        for j in range(snap.num_jobs)
        if res.assigned_node[j] == 0
    ]
    assert sum(int(snap.job_req[j][total_cpu]) for j in bound) <= 32000


def test_non_preemptible_not_evicted():
    running = [
        RunningJob(
            job=job(i, queue="b", cpu="8", priority_class="high"),
            node_id="node-000",
            scheduled_at_priority=30000,
        )
        for i in range(4)
    ]
    queued = [job(100, queue="a", cpu="8", priority_class="high")]
    snap, res = solve(
        PREEMPT_CFG, nodes(1), [QueueSpec("a"), QueueSpec("b")], running, queued
    )
    assert res.preempted_mask.sum() == 0
    assert res.scheduled_mask.sum() == 0


def test_protected_fair_share_prevents_eviction():
    # queue b holds half the cluster = exactly its fair share -> protected
    protected = cfg(
        priority_classes={
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=1.0,
    )
    running = [
        RunningJob(
            job=job(i, queue="b", cpu="8", priority_class="low"),
            node_id="node-000",
            scheduled_at_priority=1000,
        )
        for i in range(2)
    ]
    queued = [job(100 + i, queue="a", cpu="8", priority_class="low") for i in range(2)]
    snap, res = solve(
        protected, nodes(1), [QueueSpec("a"), QueueSpec("b")], running, queued
    )
    # b is at 16/32 = its fair share; not above it -> no preemption
    assert res.preempted_mask.sum() == 0
    assert res.scheduled_mask.sum() == 2


def test_fair_share_eviction_rebalances():
    # queue b hogs the whole node with preemptible jobs; queue a arrives:
    # eviction + rescheduling splits 50/50
    balance = cfg(
        priority_classes={"low": PriorityClass("low", 1000, preemptible=True)},
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
    )
    running = [
        RunningJob(
            job=job(i, queue="b", cpu="4", priority_class="low"),
            node_id="node-000",
            scheduled_at_priority=1000,
        )
        for i in range(8)
    ]
    queued = [job(100 + i, queue="a", cpu="4", priority_class="low") for i in range(8)]
    snap, res = solve(
        balance, nodes(1), [QueueSpec("a"), QueueSpec("b")], running, queued
    )
    assert res.scheduled_mask.sum() == 4
    assert res.preempted_mask.sum() == 4


def test_rate_limit_burst():
    from armada_tpu.core.config import RateLimits

    limited = cfg(rate_limits=RateLimits(maximum_scheduling_burst=5))
    snap, res = solve(limited, nodes(2), [QueueSpec("q")], [], [job(i) for i in range(10)])
    assert res.scheduled_mask.sum() == 5


def test_per_round_resource_fraction():
    frac = cfg(maximum_resource_fraction_to_schedule={"cpu": 0.25})
    # 32 cpu node, cap 8 cpu per round -> 8 one-cpu jobs, the check allows
    # the round to stop once exceeded
    snap, res = solve(frac, nodes(1), [QueueSpec("q")], [], [job(i) for i in range(20)])
    assert res.scheduled_mask.sum() == 9  # limit checked before gang: overshoot by 1
    assert res.termination_reason == "maximum resources scheduled"


def test_node_selector_restricts_placement():
    ns = nodes(2)
    ns[1] = NodeSpec(
        id="node-001",
        pool="default",
        labels={"zone": "west"},
        total_resources={"cpu": "32", "memory": "256Gi"},
    )
    queued = [job(0, node_selector={"zone": "west"})]
    snap, res = solve(cfg(), ns, [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 1
    assert snap.node_ids[res.assigned_node[snap.job_ids.index("job-0000")]] == "node-001"


def test_best_fit_prefers_smaller_node():
    ns = [
        NodeSpec(id="big", pool="default", total_resources={"cpu": "64", "memory": "256Gi"}),
        NodeSpec(id="small", pool="default", total_resources={"cpu": "8", "memory": "64Gi"}),
    ]
    snap, res = solve(cfg(), ns, [QueueSpec("q")], [], [job(0, cpu="2")])
    # best-fit: node with least allocatable first
    assert snap.node_ids[res.assigned_node[0]] == "small"


def test_evicted_job_returns_home():
    # eviction happens (unprotected), but there's room for everyone:
    # all evicted jobs reschedule onto their original node; nothing preempted
    balance = cfg(
        priority_classes={"low": PriorityClass("low", 1000, preemptible=True)},
        default_priority_class="low",
        protected_fraction_of_fair_share=0.1,
    )
    running = [
        RunningJob(
            job=job(i, queue="b", cpu="4", priority_class="low"),
            node_id="node-001",
            scheduled_at_priority=1000,
        )
        for i in range(4)
    ]
    snap, res = solve(balance, nodes(2), [QueueSpec("b")], running, [])
    assert res.preempted_mask.sum() == 0
    for j in range(4):
        assert snap.node_ids[res.assigned_node[j]] == "node-001"


def test_incomplete_gang_never_schedules():
    g = Gang(id="g1", cardinality=5)
    queued = [job(i, gang=g) for i in range(3)]
    snap, res = solve(cfg(), nodes(2), [QueueSpec("q")], [], queued)
    assert res.scheduled_mask.sum() == 0


def test_non_preemptible_blocks_higher_priority_overpack():
    # Node saturated by non-preemptible low-priority jobs: a higher-priority
    # job must NOT urgency-preempt past them (priorityCutoffFor semantics,
    # nodedb.go:1017-1032) — nothing can be evicted, so nothing schedules.
    mixed = cfg(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low-solid": PriorityClass("low-solid", 1000, preemptible=False),
        },
        default_priority_class="high",
    )
    running = [
        RunningJob(
            job=job(i, queue="b", cpu="8", priority_class="low-solid"),
            node_id="node-000",
            scheduled_at_priority=1000,
        )
        for i in range(4)
    ]
    queued = [job(100, queue="a", cpu="8", priority_class="high")]
    snap, res = solve(mixed, nodes(1), [QueueSpec("a"), QueueSpec("b")], running, queued)
    assert res.scheduled_mask.sum() == 0
    assert res.preempted_mask.sum() == 0


def test_queue_lookback_limit():
    limited = cfg(max_queue_lookback=5)
    snap, res = solve(limited, nodes(1), [QueueSpec("q")], [], [job(i) for i in range(20)])
    assert res.scheduled_mask.sum() == 5
