"""Solver autopilot (armada_tpu/autotune): offline corpus tuning,
the online hill-climb controller, and the persisted tuning store.

Tier-1 keeps the committed-fixture smoke fast (tiny candidate grid over
tests/fixtures/sim_steady.atrace, both via the library and the
tools/autotune.py CLI); the full default-grid search rides the slow
marker. The store round-trips through services/checkpoint.CheckpointStore
across a simulated restart, and a kernel-backend sim proves the
scheduler actually ADOPTS the restored vector (the flight-recorder
bundle's per-round solver info carries the tuned window).
"""

import json
import os
import subprocess
import sys
import warnings

import pytest

from armada_tpu.autotune import (
    AutotuneController,
    TunedParams,
    TuningStore,
    current_target,
    default_grid,
    make_entry,
    target_digest,
    tune_corpus,
    workload_fingerprint,
)
from armada_tpu.core.config import SchedulingConfig, validate_config
from armada_tpu.trace import load_trace

REPO = os.path.join(os.path.dirname(__file__), "..")
FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "sim_steady.atrace")


# ---- satellite: config validation of the engagement floor ------------


def test_config_warns_on_unreachable_engagement_floor():
    """hotWindowSlots > 0 whose pow2 bucket can never engage at the
    hotWindowMinSlots floor (2*Ws >= floor even for one queue) warns:
    the operator configured a window that is silently dead exactly
    where they told it to start working."""
    with pytest.warns(UserWarning, match="cannot engage"):
        validate_config(
            SchedulingConfig(hot_window_slots=4096, hot_window_min_slots=4096)
        )
    # The kernel clamps the window up to the fill-window lookahead, so
    # a small window with a big fill window is dead at the floor too.
    with pytest.warns(UserWarning, match="cannot engage"):
        validate_config(
            SchedulingConfig(
                hot_window_slots=128, hot_window_min_slots=512,
                batch_fill_window=512,
            )
        )
    # The shipped defaults (4096 window, 512k floor) are sound, as is a
    # disabled floor (tests run with min_slots=0 deliberately).
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        validate_config(SchedulingConfig())
        validate_config(
            SchedulingConfig(hot_window_slots=4096, hot_window_min_slots=0)
        )


def test_config_autotune_knobs_parse_and_validate():
    cfg = SchedulingConfig.from_dict(
        {
            "autotuneEnabled": True,
            "autotuneProfile": "/tmp/tuned.json",
            "autotuneHysteresisRounds": 5,
            "autotuneMinWindowSlots": 128,
            "autotuneMaxWindowSlots": 8192,
        }
    )
    assert cfg.autotune_enabled is True
    assert cfg.autotune_profile == "/tmp/tuned.json"
    assert cfg.autotune_hysteresis_rounds == 5
    validate_config(cfg)
    with pytest.raises(ValueError, match="autotuneMaxWindowSlots"):
        validate_config(
            SchedulingConfig(
                autotune_min_window_slots=1024, autotune_max_window_slots=64
            )
        )


# ---- offline tuner ---------------------------------------------------


def test_offline_tuner_fixture_corpus_smoke():
    """Tier-1 smoke: a tiny candidate grid over the committed fixture
    corpus tunes in seconds, every candidate (baseline included)
    replays bit-exact, and the selected entry is keyed by this host's
    target signature + the corpus's workload fingerprint."""
    trace = load_trace(FIXTURE)
    report = tune_corpus(
        [trace],
        [TunedParams(2, 0, 1), TunedParams(4, 0, 1)],
        repeats=1,
        allow_foreign=True,  # sound: the fixture pins x64 exact costs
    )
    assert report["ok"], report["results"]
    assert report["rounds"] >= 2
    assert all(r["bit_exact"] for r in report["results"])
    # Baseline measured alongside the grid, from the bundle header.
    assert report["baseline"]["label"] == "baseline"
    assert report["baseline"]["params"]["hot_window_slots"] == 4096
    selected = report["selected"]
    assert selected is not None
    assert selected["target"] == target_digest(current_target())
    assert selected["workload"] == workload_fingerprint([trace])
    assert selected["pool"] == "default"
    assert selected["tuned_s"] is not None


def test_offline_tuner_cli_smoke(tmp_path):
    """tools/autotune.py over the committed corpus: exit 0, writes a
    tuning-store profile this host can look up."""
    out = tmp_path / "tuned.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("BENCH_MESH", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "autotune.py"),
            FIXTURE, "--windows", "2,4", "--min-slots", "0",
            "--repeats", "1", "--allow-foreign", "--out", str(out),
            "--json",
        ],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["ok"] and report["selected"] is not None
    store = TuningStore()
    assert store.merge_json(str(out)) == 1
    entry = store.lookup(current_target(), "default")
    assert entry is not None and entry["source"] == "offline"
    params = TunedParams.from_dict(entry["params"])
    assert params.hot_window_slots in (2, 4, 4096)


def test_offline_tuner_refuses_unusable_corpus(tmp_path):
    """A corpus with no replayable rounds (or an unreadable bundle)
    exits 2 — unusable, distinct from a divergence failure (1)."""
    bogus = tmp_path / "empty.atrace"
    from armada_tpu.trace import TraceRecorder

    rec = TraceRecorder(str(bogus), source="test")
    rec._write_header(None)  # header-only bundle: no rounds
    rec.close()
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "autotune.py"),
            str(bogus), "--windows", "2",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "no replayable rounds" in proc.stdout


@pytest.mark.slow
def test_offline_tuner_full_default_grid():
    """The full default grid (pow2 windows around the shipped 4096 with
    the shipped floor) over the fixture corpus: slower, but every
    candidate must still be bit-exact."""
    trace = load_trace(FIXTURE)
    report = tune_corpus(
        [trace], default_grid(), repeats=2, allow_foreign=True
    )
    assert report["ok"], report["results"]
    assert len(report["results"]) == len(default_grid()) + 1


# ---- online controller ----------------------------------------------


def _controller(**overrides):
    kwargs = dict(
        hot_window_slots=8,
        hot_window_min_slots=0,
        # Small fill window so the kernel lookahead (which floors the
        # shrink moves) sits below the test's min bound.
        batch_fill_window=2,
        autotune_enabled=True,
        autotune_hysteresis_rounds=2,
        autotune_min_window_slots=4,
        autotune_max_window_slots=16,
    )
    kwargs.update(overrides)
    return AutotuneController(SchedulingConfig(**kwargs))


GROW = {"compacted": True, "rewindows": 9, "gather_s": 0.02, "pass1_s": 0.5}
SHRINK = {"compacted": True, "rewindows": 0, "gather_s": 0.3, "pass1_s": 0.1}
STEADY = {"compacted": True, "rewindows": 1, "gather_s": 0.05, "pass1_s": 0.5}


def test_online_hysteresis_cooldown_and_bounds():
    ctl = _controller()
    assert ctl.params_for("default") == TunedParams(8, 0, 1)
    # One starved round is not a signal (hysteresis = 2)...
    assert ctl.observe_round("default", GROW) is None
    # ...and a steady round in between resets the streak.
    assert ctl.observe_round("default", STEADY) is None
    assert ctl.observe_round("default", GROW) is None
    adopted = ctl.observe_round("default", GROW)
    assert adopted["direction"] == "grow"
    assert adopted["from"] == 8 and adopted["to"] == 16
    assert ctl.params_for("default").hot_window_slots == 16
    # Cooldown: the two rounds after an adoption are absorbed.
    assert ctl.observe_round("default", GROW) is None
    assert ctl.observe_round("default", GROW) is None
    # At the max bound, a grow signal adopts nothing.
    assert ctl.observe_round("default", GROW) is None
    assert ctl.observe_round("default", GROW) is None
    assert ctl.params_for("default").hot_window_slots == 16
    # Shrink path halves down to the min bound, never below.
    for _ in range(16):
        ctl.observe_round("default", SHRINK)
    assert ctl.params_for("default").hot_window_slots == 4
    directions = [a["direction"] for a in ctl.adoptions]
    assert directions == ["grow", "shrink", "shrink"]
    # Every adoption persisted to the store as an online entry.
    entry = ctl.store.lookup(current_target(), "default")
    assert entry["source"] == "online"
    assert entry["params"]["hot_window_slots"] == 4


def test_online_disengaged_rounds_recover_toward_the_floor():
    """A window the rounds never engage (e.g. grown past the kernel's
    2*Q*Ws < S geometry — no compacted profile can ever arrive to say
    'shrink') shrinks back toward the floor with the same hysteresis;
    at the floor, disengaged rounds adopt nothing; a compacted round
    resets the streak; a disabled controller ignores everything."""
    ctl = _controller()  # window 8, floor 4, hysteresis 2
    assert ctl.observe_round("default", None) is None
    adopted = ctl.observe_round("default", {"compacted": False})
    assert adopted is not None and adopted["direction"] == "shrink"
    assert adopted["signal"]["disengaged"] is True
    assert ctl.params_for("default").hot_window_slots == 4
    for _ in range(6):  # at the floor: never adopts, never goes below
        assert ctl.observe_round("default", None) is None
    assert ctl.params_for("default").hot_window_slots == 4
    ctl2 = _controller()
    assert ctl2.observe_round("default", None) is None
    assert ctl2.observe_round("default", STEADY) is None  # resets streak
    assert ctl2.observe_round("default", None) is None
    assert ctl2.observe_round("default", None) is not None
    off = AutotuneController(SchedulingConfig())
    assert off.params_for("default") is None
    assert off.observe_round("default", dict(GROW)) is None


def test_online_controller_pools_are_independent():
    ctl = _controller(autotune_hysteresis_rounds=1)
    ctl.observe_round("a", GROW)
    assert ctl.params_for("a").hot_window_slots == 16
    assert ctl.params_for("b").hot_window_slots == 8


def test_online_bounds_never_move_against_the_signal():
    """Clamping must not invert the climb: a window below the min bound
    shrinks nowhere (never UP to the bound), grows by one doubling (not
    a jump past the bound), and a store-seeded window above the max
    bound never 'grows' downward."""
    ctl = _controller(autotune_hysteresis_rounds=1, hot_window_slots=16)
    st = ctl._state("p")
    st.params = TunedParams(2, 0, 1)  # below autotune_min_window_slots=4
    assert ctl.observe_round("p", SHRINK) is None
    assert ctl.params_for("p").hot_window_slots == 2
    adopted = ctl.observe_round("p", GROW)
    assert adopted["to"] == 4  # one doubling, not min*2=8
    st.params = TunedParams(64, 0, 1)  # above autotune_max_window_slots=16
    st.cooldown = 0
    assert ctl.observe_round("p", GROW) is None
    assert ctl.params_for("p").hot_window_slots == 64


def test_online_shrink_floors_at_the_kernel_lookahead():
    """The kernel runs Ws = pow2(max(window, fill-window lookahead)):
    shrinking the configured window below the lookahead is a no-op the
    profile can never confirm, so the climb stops there instead of
    marching to the min bound adopting ineffective moves."""
    ctl = _controller(
        batch_fill_window=512, autotune_hysteresis_rounds=1,
        hot_window_slots=2048, autotune_min_window_slots=4,
        autotune_max_window_slots=1 << 14,
    )
    assert ctl.window_floor == 512
    ctl.observe_round("p", SHRINK)
    assert ctl.params_for("p").hot_window_slots == 1024
    st = ctl._state("p")
    st.cooldown = 0
    ctl.observe_round("p", SHRINK)
    assert ctl.params_for("p").hot_window_slots == 512
    st.cooldown = 0
    # At the lookahead: no further (ineffective) shrink is adopted.
    assert ctl.observe_round("p", SHRINK) is None
    assert ctl.params_for("p").hot_window_slots == 512
    # Market mode has a 1-slot lookahead: only the operator bound floors.
    market = _controller(market_driven=True, autotune_min_window_slots=4)
    assert market.window_floor == 4


# ---- persisted store + restart adoption -----------------------------


def test_store_lookup_prefers_pool_workload_and_recency():
    store = TuningStore()
    t = current_target()
    store.put(make_entry(TunedParams(1024), target=t, workload="w",
                         pool="*", created=100.0))
    store.put(make_entry(TunedParams(2048), target=t, workload="live",
                         pool="default", created=50.0))
    # Pool-specific beats the newer wildcard...
    assert store.lookup(t, "default")["params"]["hot_window_slots"] == 2048
    assert store.lookup(t, "other")["params"]["hot_window_slots"] == 1024
    # ...and a foreign target matches nothing.
    assert store.lookup({"host_cpu": "feedface", "xla": "x", "x64": True},
                        "default") is None
    # Two profiles for different workloads coexist (distinct keys); a
    # caller that KNOWS its workload fingerprint gets the exact match,
    # one that doesn't gets the newest.
    store.put(make_entry(TunedParams(512), target=t, workload="burst",
                         pool="*", created=200.0))
    assert len(store) == 3
    assert store.lookup(t, "other")["params"]["hot_window_slots"] == 512
    assert store.lookup(t, "other", workload="w")["params"][
        "hot_window_slots"] == 1024


def test_operator_profile_outranks_checkpointed_online_entries(tmp_path):
    """The config-named profile is the operator's override: merged with
    operator=True it outranks a newer pool-specific online adoption —
    but the flag never survives a checkpoint round-trip, so a boot
    WITHOUT the config reverts to normal ranking."""
    t = current_target()
    store = TuningStore()
    # A wildcard offline profile, as tools/autotune.py writes it.
    profile = TuningStore()
    profile.put(make_entry(TunedParams(4096), target=t, workload="w",
                           pool="*", created=100.0))
    path = str(tmp_path / "tuned.json")
    profile.to_json(path)
    # Checkpoint-restored online adoption: pool-specific AND newer.
    store.put(make_entry(TunedParams(64), target=t, workload="live",
                         pool="default", source="online", created=900.0))
    store.merge_json(path, operator=True)
    assert store.lookup(t, "default")["params"]["hot_window_slots"] == 4096
    # Round-trip through a checkpoint: the flag is stripped, the online
    # pool-specific entry wins again (the config no longer asserts it).
    restored = TuningStore()
    restored.load(store.dump())
    assert restored.lookup(t, "default")["params"]["hot_window_slots"] == 64


def test_offline_tuner_rejects_mixed_config_corpus(tmp_path):
    """Bundles recorded under different scheduling configs cannot share
    one baseline — the tuner refuses instead of mis-baselining."""
    from armada_tpu.trace.replayer import Trace

    trace = load_trace(FIXTURE)
    other = Trace(
        path="other", rounds=trace.rounds,
        header=dict(trace.header, config_fingerprint="deadbeef"),
    )
    with pytest.raises(ValueError, match="different scheduling configs"):
        tune_corpus([trace, other], [TunedParams(2, 0, 1)],
                    allow_foreign=True)


def test_tuning_store_checkpoint_roundtrip_across_restart(tmp_path):
    """The store survives a simulated restart through CheckpointStore
    (crc-guarded tmp+fsync+rename), and a fresh controller adopts the
    restored vector at its first parameter resolution."""
    from armada_tpu.services.checkpoint import CheckpointStore

    store = TuningStore()
    store.put(
        make_entry(
            TunedParams(7, 0, 2), target=current_target(),
            workload="test-corpus", pool="default", source="offline",
            baseline_s=1.0, tuned_s=0.5,
        )
    )
    ck = CheckpointStore(str(tmp_path / "checkpoints"))
    ck.save("autotune", 0, store.dump())

    # ---- "restart": nothing shared but the checkpoint directory.
    cursor, state = CheckpointStore(str(tmp_path / "checkpoints")).load(
        "autotune"
    )
    restored = TuningStore()
    restored.load(state)
    assert len(restored) == 1
    ctl = AutotuneController(
        SchedulingConfig(autotune_enabled=True), store=restored
    )
    assert ctl.params_for("default") == TunedParams(7, 0, 2)
    # A corrupt/absent checkpoint degrades to config defaults.
    fresh = TuningStore()
    fresh.load({"format": 999, "entries": {"x": {}}})
    assert len(fresh) == 0


def test_scheduler_adopts_restored_store_after_restart(tmp_path):
    """End to end across the restart seam: seed a tuned vector, persist
    it, reload it into a fresh controller, and drive a kernel-backend
    sim — every recorded round's solver info must carry the tuned
    window (the scheduler solved with the store's vector, not the
    static config)."""
    from armada_tpu.services.checkpoint import CheckpointStore
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    ck = CheckpointStore(str(tmp_path / "checkpoints"))
    seeded = TuningStore()
    seeded.put(
        make_entry(TunedParams(7, 0, 1), target=current_target(),
                   workload="corpus", pool="default", source="offline")
    )
    ck.save("autotune", 0, seeded.dump())

    # ---- restart: fresh store + controller from the checkpoint only.
    restored = TuningStore()
    restored.load(ck.load("autotune")[1])
    cfg = SchedulingConfig(autotune_enabled=True)
    ctl = AutotuneController(cfg, store=restored)
    trace_path = str(tmp_path / "adopted.atrace")
    sim = Simulator(
        [ClusterSpec(name="c", node_templates=(NodeTemplate(count=2, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    name="q",
                    job_templates=(
                        JobTemplate(
                            id="fit", number=4, cpu="2",
                            runtime=ShiftedExponential(minimum=20.0),
                        ),
                    ),
                ),
            )
        ),
        config=cfg,
        backend="kernel",
        cycle_interval=10.0,
        max_time=150.0,
        trace_path=trace_path,
        autotune=ctl,
    )
    res = sim.run()
    assert res.finished_jobs == 4
    trace = load_trace(trace_path)
    assert trace.rounds, "no rounds recorded"
    for rec in trace.rounds:
        assert rec.raw["solver"]["autotuned"] is True
        assert rec.raw["solver"]["window"] == 7
