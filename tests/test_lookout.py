"""Lookout: independent materialized view + query depth + HTTP surface
(internal/lookoutingester, internal/lookout/repository, lookoutui)."""

import json
import urllib.request

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
from armada_tpu.services.lookout_http import LookoutHttpServer
from armada_tpu.services.lookout_ingester import LookoutStore
from armada_tpu.services.queryapi import JobFilter, QueryApi
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService


def _stack():
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    executor = FakeExecutor(
        "c", log, sched,
        nodes=make_nodes("c", count=4, cpu="8", memory="32Gi"),
        runtime_for=lambda j: 5.0,
    )
    lookout = LookoutStore(log, error_rules=config.error_categories)
    return config, log, sched, submit, executor, lookout


def _drive(sched, submit, executor, lookout, n=6):
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "set1",
        [JobSpec(id=f"j{i}", queue="", requests={"cpu": "1", "memory": "1Gi"})
         for i in range(n)],
        now=0.0,
    )
    executor.tick(0.0)
    sched.cycle(now=1.0)
    executor.tick(2.0)
    executor.tick(3.0)
    executor.tick(9.0)  # runtime 5s -> succeed
    sched.cycle(now=10.0)
    lookout.sync()


def test_lookout_view_is_independent_and_lag_tracked():
    config, log, sched, submit, executor, lookout = _stack()
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "set1",
        [JobSpec(id="j0", queue="", requests={"cpu": "1", "memory": "1Gi"})],
        now=0.0,
    )
    assert lookout.lag_events > 0  # not synced yet: lag visible
    lookout.sync()
    assert lookout.lag_events == 0
    row = lookout.get("j0")
    assert row is not None and row.state == "queued"
    # The scheduler's jobdb was never consulted: the view stands alone.
    assert lookout.rows["j0"].queue == "team"


def test_lookout_lifecycle_and_query_depth():
    config, log, sched, submit, executor, lookout = _stack()
    _drive(sched, submit, executor, lookout)
    q = QueryApi(lookout=lookout)
    rows, total = q.get_jobs([JobFilter("queue", "team")])
    assert total == 6
    assert all(r.state == "succeeded" for r in rows)
    assert all(r.runtime_s > 0 for r in rows)
    groups = q.group_jobs(
        "jobset", aggregates=["state_counts", "runtime_avg", "last_transition_max"]
    )
    assert groups[0]["count"] == 6
    assert groups[0]["aggregates"]["state_counts"] == {"succeeded": 6}
    assert groups[0]["aggregates"]["runtime_avg"] > 0
    details = q.job_details("j0")
    assert details["runs"] and details["runs"][-1]["state"] == "succeeded"
    assert details["requests"] == {"cpu": "1", "memory": "1Gi"}


def test_lookout_error_drilldown():
    config, log, sched, submit, executor, lookout = _stack()
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "set1",
        [JobSpec(id="j0", queue="", requests={"cpu": "1", "memory": "1Gi"})],
        now=0.0,
    )
    from armada_tpu.events import EventSequence, JobErrors

    log.publish(
        EventSequence.of(
            "team", "set1",
            JobErrors(created=1.0, job_id="j0", error="oom killed: container"),
        )
    )
    lookout.sync()
    q = QueryApi(lookout=lookout)
    errors = q.get_job_errors()
    assert len(errors) == 1
    assert errors[0]["error_category"] == "oom"
    assert q.job_details("j0")["state"] == "failed"


def test_lookout_http_endpoints():
    config, log, sched, submit, executor, lookout = _stack()
    _drive(sched, submit, executor, lookout)
    q = QueryApi(lookout=lookout)
    server = LookoutHttpServer(q, sched, submit, port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/api/jobs?queue=team") as r:
            data = json.loads(r.read())
            assert data["total"] == 6
        with urllib.request.urlopen(base + "/api/details/j0") as r:
            data = json.loads(r.read())
            assert data["job_id"] == "j0" and data["runs"]
        with urllib.request.urlopen(base + "/api/errors") as r:
            assert json.loads(r.read())["errors"] == []
        with urllib.request.urlopen(base + "/") as r:
            assert b"lookout" in r.read()
    finally:
        server.stop()


def test_lookout_pruner():
    config, log, sched, submit, executor, lookout = _stack()
    _drive(sched, submit, executor, lookout)
    assert lookout.prune(older_than=100.0) == 6
    assert lookout.all_rows() == []


def test_query_match_types_and_annotations():
    """The reference's full filter-operator set (lookout/model/model.go:8-16,
    querybuilder.go:616-650): contains, gt/lt/gte/lte, exists, plus
    annotation-keyed filters."""
    config, log, sched, submit, executor, lookout = _stack()
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "set1",
        [
            JobSpec(
                id=f"job-{i}", queue="",
                requests={"cpu": "1", "memory": "1Gi"},
                priority=i,
                annotations={"owner": f"user-{i % 2}"} if i < 4 else {},
            )
            for i in range(6)
        ],
        now=float(0),
    )
    lookout.sync()
    q = QueryApi(lookout=lookout)

    _, n = q.get_jobs([JobFilter("job_id", "ob-3", match="contains")])
    assert n == 1
    _, n = q.get_jobs([JobFilter("priority", 3, match="greaterThan")])
    assert n == 2
    _, n = q.get_jobs([JobFilter("priority", 3, match="lessThanOrEqualTo")])
    assert n == 4
    _, n = q.get_jobs([JobFilter("priority", 5, match="greaterThanOrEqualTo")])
    assert n == 1
    _, n = q.get_jobs([JobFilter("owner", match="exists", is_annotation=True)])
    assert n == 4
    _, n = q.get_jobs(
        [JobFilter("owner", "user-1", match="exact", is_annotation=True)]
    )
    assert n == 2
    _, n = q.get_jobs([JobFilter("priority", [1, 2, 9], match="anyOf")])
    assert n == 2

    # Annotation grouping: rows missing the key are excluded (the
    # implicit exists-filter, querybuilder.go:273).
    groups = q.group_jobs("owner", group_by_annotation=True)
    assert sorted(g["name"] for g in groups) == ["user-0", "user-1"]
    assert all(g["count"] == 2 for g in groups)

    # Reference-style aggregate specs (aggregates.go) + ordering by name.
    groups = q.group_jobs(
        "owner", group_by_annotation=True,
        aggregates=[
            {"field": "priority", "type": "max"},
            {"field": "priority", "type": "average"},
            "state_counts",
        ],
        order_by="name", direction="asc",
    )
    assert groups[0]["name"] == "user-0"
    assert groups[0]["aggregates"]["priority_max"] == 2
    assert groups[0]["aggregates"]["priority_average"] == 1.0
    assert groups[0]["aggregates"]["state_counts"] == {"queued": 2}

    # Group pagination.
    page = q.group_jobs(
        "job_id", order_by="name", direction="asc", skip=2, take=2
    )
    assert [g["name"] for g in page] == ["job-2", "job-3"]


def test_run_drilldowns_error_debug_termination():
    """Run-level drilldown surface (getjobrunerror.go,
    getjobrundebugmessage.go, getjobrunschedulerterminationreason.go)."""
    from armada_tpu.events import (
        EventSequence,
        JobRunErrors,
        JobRunLeased,
        JobRunPreempted,
    )

    config, log, sched, submit, executor, lookout = _stack()
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "set1",
        [JobSpec(id=f"j{i}", queue="", requests={"cpu": "1", "memory": "1Gi"})
         for i in range(2)],
        now=0.0,
    )
    log.publish(
        EventSequence.of(
            "team", "set1",
            JobRunLeased(created=1.0, job_id="j0", run_id="r0",
                         executor="c", node_id="n0"),
            JobRunErrors(created=2.0, job_id="j0", run_id="r0",
                         error="oom killed", retryable=False,
                         debug='{"phase": "running", "exit_code": 137}'),
        )
    )
    log.publish(
        EventSequence.of(
            "team", "set1",
            JobRunLeased(created=1.0, job_id="j1", run_id="r1",
                         executor="c", node_id="n1"),
            JobRunPreempted(created=3.0, job_id="j1", run_id="r1",
                            reason="preempted by queue weights"),
        )
    )
    lookout.sync()
    q = QueryApi(lookout=lookout)
    assert q.get_job_run_error("r0") == "oom killed"
    assert "exit_code" in q.get_job_run_debug_message("r0")
    assert q.get_job_run_termination_reason("r1") == "preempted by queue weights"
    assert q.get_job_run_error("missing") == ""
    # The details drawer carries the same per-run fields.
    runs = q.job_details("j0")["runs"]
    assert runs[0]["debug"] and runs[0]["error"] == "oom killed"


def test_lookout_http_rich_query_surface():
    """HTTP-level getJobs/groupJobs semantics: JSON filter param,
    order/direction/skip/take, annotation group-by with aggregates,
    run drilldown routes, fair-share view."""
    from armada_tpu.events import EventSequence, JobRunErrors, JobRunLeased

    config, log, sched, submit, executor, lookout = _stack()
    _drive(sched, submit, executor, lookout)
    # One failed run with a debug dump for the drilldown route.
    submit.submit(
        "team", "set2",
        [JobSpec(id="jx", queue="", requests={"cpu": "1", "memory": "1Gi"},
                 annotations={"team": "alpha"})],
        now=20.0,
    )
    log.publish(
        EventSequence.of(
            "team", "set2",
            JobRunLeased(created=21.0, job_id="jx", run_id="rx",
                         executor="c", node_id="n0"),
            JobRunErrors(created=22.0, job_id="jx", run_id="rx",
                         error="disk pressure", retryable=True,
                         debug='{"phase": "pending"}'),
        )
    )
    lookout.sync()
    q = QueryApi(lookout=lookout)
    server = LookoutHttpServer(q, sched, submit, port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"

        def jget(path):
            with urllib.request.urlopen(base + path) as r:
                return json.loads(r.read())

        # JSON filters: contains + annotation exact.
        filters = json.dumps(
            [{"field": "job_id", "value": "j", "match": "contains"}]
        )
        data = jget(f"/api/jobs?filters={urllib.parse.quote(filters)}"
                    "&order=submitted&direction=asc&skip=2&take=3")
        assert data["total"] == 7
        assert len(data["jobs"]) == 3
        assert data["jobs"][0]["job_id"] == "j2"  # asc from skip=2

        ann = json.dumps([{"field": "team", "value": "alpha",
                           "match": "exact", "isAnnotation": True}])
        data = jget(f"/api/jobs?filters={urllib.parse.quote(ann)}")
        assert data["total"] == 1 and data["jobs"][0]["job_id"] == "jx"

        # groupJobs over annotation with reference-style aggregates.
        aggs = json.dumps([{"field": "submitted", "type": "min"},
                           "state_counts"])
        data = jget("/api/groups?by=team&byAnnotation=1"
                    f"&aggregates={urllib.parse.quote(aggs)}")
        assert data["groups"][0]["name"] == "alpha"
        assert data["groups"][0]["aggregates"]["submitted_min"] == 20.0
        # retryable run error without a terminal JobErrors: still leased.
        assert data["groups"][0]["aggregates"]["state_counts"] == {"leased": 1}

        # Run drilldowns.
        assert jget("/api/runs/rx/error")["message"] == "disk pressure"
        assert "phase" in jget("/api/runs/rx/debug")["message"]
        assert jget("/api/runs/rx/termination")["message"] == ""

        # Fair-share view exists and covers the pool's queues.
        pools = jget("/api/fairshare")["pools"]
        assert "default" in pools
        assert any(r["queue"] == "team" for r in pools["default"])
    finally:
        server.stop()


def test_ui_mutation_endpoints():
    """The UI's cancel/reprioritize POSTs (the reference UI's submitApi
    actions) flow through the submission service into the view."""
    import json as _json
    import urllib.request

    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.core.types import JobSpec, QueueSpec
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.lookout_http import LookoutHttpServer
    from armada_tpu.services.lookout_ingester import LookoutStore
    from armada_tpu.services.queryapi import QueryApi
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    store = LookoutStore(log)
    server = LookoutHttpServer(QueryApi(lookout=store), sched, submit, port=0)
    try:
        submit.create_queue(QueueSpec("ui-q"))
        submit.submit(
            "ui-q", "s1",
            [JobSpec(id=f"ui-{i}", queue="ui-q", requests={"cpu": "1"})
             for i in range(3)],
            now=0.0,
        )
        store.sync()

        def post(path, body):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}{path}",
                data=_json.dumps(body).encode(),
                headers={
                    "Content-Type": "application/json",
                    # CSRF gate: mutations require the custom header a
                    # cross-origin form cannot set.
                    "X-Requested-With": "armada-lookout",
                },
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                return _json.loads(resp.read())

        out = post("/api/reprioritize",
                   {"queue": "ui-q", "jobset": "s1", "job_ids": ["ui-0"],
                    "priority": 7})
        assert out == {"reprioritized": 1}
        out = post("/api/cancel",
                   {"queue": "ui-q", "jobset": "s1", "job_ids": ["ui-1"]})
        assert out == {"cancelled": 1}
        out = post("/api/cancel", {"queue": "ui-q", "jobset": "s1"})
        assert out == {"cancelled": "jobset"}
        store.sync()
        assert store.get("ui-0").priority == 7
        assert store.get("ui-1").state == "cancelled"
        assert store.get("ui-2").state == "cancelled"
    finally:
        server.stop()


def test_ui_mutations_reject_csrf_shapes():
    """Cross-origin form-style POSTs (no custom header / text-plain body)
    are rejected; only the UI's fetch shape passes."""
    import json as _json
    import urllib.error
    import urllib.request

    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.lookout_http import LookoutHttpServer
    from armada_tpu.services.lookout_ingester import LookoutStore
    from armada_tpu.services.queryapi import QueryApi
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    server = LookoutHttpServer(
        QueryApi(lookout=LookoutStore(log)), sched, submit, port=0
    )
    try:
        body = _json.dumps({"queue": "q", "jobset": "s"}).encode()
        for headers in (
            {"Content-Type": "text/plain"},
            {"Content-Type": "application/json"},  # header missing
        ):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/api/cancel",
                data=body, headers=headers, method="POST",
            )
            try:
                urllib.request.urlopen(req, timeout=5)
                raise AssertionError("CSRF-shaped POST was accepted")
            except urllib.error.HTTPError as e:
                assert e.code == 403
    finally:
        server.stop()


def test_ui_logs_endpoint():
    """GET /api/logs/<job_id> routes through binoculars to the executor
    (the reference UI's container-log fetch)."""
    import json as _json
    import urllib.request

    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.core.types import JobSpec, QueueSpec
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.binoculars import BinocularsService
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.lookout_http import LookoutHttpServer
    from armada_tpu.services.lookout_ingester import LookoutStore
    from armada_tpu.services.queryapi import QueryApi
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    executor = FakeExecutor(
        "lg", log, sched, nodes=make_nodes("lg", count=1, cpu="8",
                                           memory="32Gi"),
        runtime_for=lambda job_id: 60.0,
    )
    store = LookoutStore(log)
    server = LookoutHttpServer(
        QueryApi(lookout=store), sched, submit, port=0,
        binoculars=BinocularsService(sched, [executor]),
    )
    try:
        submit.create_queue(QueueSpec("lg-q"))
        submit.submit("lg-q", "s1",
                      [JobSpec(id="lg-0", queue="lg-q",
                               requests={"cpu": "1"})], now=0.0)
        executor.tick(0.0)
        sched.cycle(now=1.0)
        executor.tick(1.5)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/api/logs/lg-0?tail=10",
            timeout=5,
        ) as resp:
            data = _json.loads(resp.read())
        assert data["job_id"] == "lg-0"
        assert isinstance(data["lines"], list) and data["lines"]
    finally:
        server.stop()
