"""Lookout: independent materialized view + query depth + HTTP surface
(internal/lookoutingester, internal/lookout/repository, lookoutui)."""

import json
import urllib.request

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
from armada_tpu.services.lookout_http import LookoutHttpServer
from armada_tpu.services.lookout_ingester import LookoutStore
from armada_tpu.services.queryapi import JobFilter, QueryApi
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService


def _stack():
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    executor = FakeExecutor(
        "c", log, sched,
        nodes=make_nodes("c", count=4, cpu="8", memory="32Gi"),
        runtime_for=lambda j: 5.0,
    )
    lookout = LookoutStore(log, error_rules=config.error_categories)
    return config, log, sched, submit, executor, lookout


def _drive(sched, submit, executor, lookout, n=6):
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "set1",
        [JobSpec(id=f"j{i}", queue="", requests={"cpu": "1", "memory": "1Gi"})
         for i in range(n)],
        now=0.0,
    )
    executor.tick(0.0)
    sched.cycle(now=1.0)
    executor.tick(2.0)
    executor.tick(3.0)
    executor.tick(9.0)  # runtime 5s -> succeed
    sched.cycle(now=10.0)
    lookout.sync()


def test_lookout_view_is_independent_and_lag_tracked():
    config, log, sched, submit, executor, lookout = _stack()
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "set1",
        [JobSpec(id="j0", queue="", requests={"cpu": "1", "memory": "1Gi"})],
        now=0.0,
    )
    assert lookout.lag_events > 0  # not synced yet: lag visible
    lookout.sync()
    assert lookout.lag_events == 0
    row = lookout.get("j0")
    assert row is not None and row.state == "queued"
    # The scheduler's jobdb was never consulted: the view stands alone.
    assert lookout.rows["j0"].queue == "team"


def test_lookout_lifecycle_and_query_depth():
    config, log, sched, submit, executor, lookout = _stack()
    _drive(sched, submit, executor, lookout)
    q = QueryApi(lookout=lookout)
    rows, total = q.get_jobs([JobFilter("queue", "team")])
    assert total == 6
    assert all(r.state == "succeeded" for r in rows)
    assert all(r.runtime_s > 0 for r in rows)
    groups = q.group_jobs(
        "jobset", aggregates=["state_counts", "runtime_avg", "last_transition_max"]
    )
    assert groups[0]["count"] == 6
    assert groups[0]["aggregates"]["state_counts"] == {"succeeded": 6}
    assert groups[0]["aggregates"]["runtime_avg"] > 0
    details = q.job_details("j0")
    assert details["runs"] and details["runs"][-1]["state"] == "succeeded"
    assert details["requests"] == {"cpu": "1", "memory": "1Gi"}


def test_lookout_error_drilldown():
    config, log, sched, submit, executor, lookout = _stack()
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "set1",
        [JobSpec(id="j0", queue="", requests={"cpu": "1", "memory": "1Gi"})],
        now=0.0,
    )
    from armada_tpu.events import EventSequence, JobErrors

    log.publish(
        EventSequence.of(
            "team", "set1",
            JobErrors(created=1.0, job_id="j0", error="oom killed: container"),
        )
    )
    lookout.sync()
    q = QueryApi(lookout=lookout)
    errors = q.get_job_errors()
    assert len(errors) == 1
    assert errors[0]["error_category"] == "oom"
    assert q.job_details("j0")["state"] == "failed"


def test_lookout_http_endpoints():
    config, log, sched, submit, executor, lookout = _stack()
    _drive(sched, submit, executor, lookout)
    q = QueryApi(lookout=lookout)
    server = LookoutHttpServer(q, sched, submit, port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/api/jobs?queue=team") as r:
            data = json.loads(r.read())
            assert data["total"] == 6
        with urllib.request.urlopen(base + "/api/details/j0") as r:
            data = json.loads(r.read())
            assert data["job_id"] == "j0" and data["runs"]
        with urllib.request.urlopen(base + "/api/errors") as r:
            assert json.loads(r.read())["errors"] == []
        with urllib.request.urlopen(base + "/") as r:
            assert b"lookout" in r.read()
    finally:
        server.stop()


def test_lookout_pruner():
    config, log, sched, submit, executor, lookout = _stack()
    _drive(sched, submit, executor, lookout)
    assert lookout.prune(older_than=100.0) == 6
    assert lookout.all_rows() == []
