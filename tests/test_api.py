"""API layer tests: gRPC server+client, query API, reports, submit checker,
leader election — driven through the assembled ControlPlane."""

import time

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.services.server import ControlPlane
from armada_tpu.services.grpc_api import ApiClient
from armada_tpu.services.leader import FileLeaseLeader


@pytest.fixture()
def plane():
    p = ControlPlane(
        SchedulingConfig(),
        cycle_period=0.05,
        fake_executors=[{"name": "fake-a", "nodes": 4, "cpu": "16", "runtime": 5.0}],
    ).start()
    yield p
    p.stop()


@pytest.fixture()
def client(plane):
    return ApiClient(plane.address)


def _wait(predicate, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


JOB = {"requests": {"cpu": "2", "memory": "2Gi"}}


def test_queue_crud(client):
    client.create_queue("team", priority_factor=2.0)
    q = client.get_queue("team")
    assert q["priority_factor"] == 2.0
    client.update_queue("team", priority_factor=3.0)
    assert client.get_queue("team")["priority_factor"] == 3.0
    assert any(q["name"] == "team" for q in client.list_queues())
    client.delete_queue("team")
    with pytest.raises(Exception):
        client.get_queue("team")


def test_submit_and_lifecycle_over_grpc(client, plane):
    client.create_queue("team")
    ids = client.submit_jobs("team", "set1", [dict(JOB) for _ in range(4)])
    assert len(ids) == 4
    def in_state(job_id, *states):
        j = plane.scheduler.jobdb.get(job_id)
        return j is not None and j.state.value in states

    assert _wait(lambda: all(in_state(j, "running", "succeeded") for j in ids))
    rows = client.get_jobs(filters=[{"field": "queue", "value": "team"}])
    assert rows["total"] == 4
    groups = client.group_jobs("state")
    assert sum(g["count"] for g in groups) == 4
    assert _wait(lambda: all(in_state(j, "succeeded") for j in ids), timeout=20)


def test_watch_stream(client, plane):
    client.create_queue("team")
    ids = client.submit_jobs("team", "watched", [dict(JOB)])
    seen = []
    for event in client.watch_jobset("team", "watched", watch=False):
        seen.append(event["type"])
    assert "SubmitJob" in seen
    # After scheduling, a re-read shows the lease
    def past_queued():
        j = plane.scheduler.jobdb.get(ids[0])
        return j is not None and j.state.value != "queued"

    _wait(past_queued)
    seen = [e["type"] for e in client.watch_jobset("team", "watched", watch=False)]
    assert "JobRunLeased" in seen


def test_cancel_over_grpc(client, plane):
    client.create_queue("team")
    # a job that can never fit keeps queued until cancelled
    ids = client.submit_jobs(
        "team", "set2", [{"requests": {"cpu": "999", "memory": "1Gi"}}]
    )
    client.cancel_jobs("team", "set2", job_ids=ids)

    def cancelled():
        j = plane.scheduler.jobdb.get(ids[0])
        return j is not None and j.state.value == "cancelled"

    assert _wait(cancelled)


def test_scheduling_report(client, plane):
    client.create_queue("team")
    client.submit_jobs("team", "set3", [dict(JOB) for _ in range(2)])
    assert _wait(lambda: "team" in client.scheduling_report())
    report = client.queue_report("team")
    assert "fairShare" in report and "scheduled=" in report
    # Per-job success context (reports/repository.go job reports): a
    # scheduled job's report names its node and priority.
    jobs = client.get_jobs(filters=[{"field": "queue", "value": "team"}])
    assert _wait(
        lambda: "scheduled: pool=" in client.job_report(
            jobs["jobs"][0]["job_id"]
        )
    )


def test_submit_checker_rejects_impossible():
    p = ControlPlane(
        SchedulingConfig(),
        cycle_period=0.05,
        fake_executors=[{"name": "fake-a", "nodes": 2, "cpu": "8"}],
        enable_submit_check=True,
    ).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("team")
        # let the executor heartbeat register
        _wait(lambda: len(p.scheduler.executors) > 0)
        with pytest.raises(Exception) as exc:
            client.submit_jobs(
                "team", "set1", [{"requests": {"cpu": "64", "memory": "1Gi"}}]
            )
        assert "never schedule" in str(exc.value)
        ids = client.submit_jobs("team", "set1", [dict(JOB)])
        assert len(ids) == 1
    finally:
        p.stop()


def test_binoculars_logs_and_cordon(client, plane):
    client.create_queue("bino")
    ids = client.submit_jobs("bino", "set-b", [dict(JOB)])

    def running():
        j = plane.scheduler.jobdb.get(ids[0])
        return j is not None and j.state.value == "running"

    assert _wait(running)
    lines = client.get_job_logs(ids[0])
    assert lines and "fake-a" in lines[0]
    # cordon the node the job runs on; next heartbeats mark it unschedulable
    node_id = plane.scheduler.jobdb.get(ids[0]).latest_run.node_id
    client.cordon_node(node_id)
    assert _wait(
        lambda: any(
            n.id == node_id and n.unschedulable
            for hb in plane.scheduler.executors.values()
            for n in hb.nodes
        )
    )
    client.cordon_node(node_id, uncordon=True)


def test_priority_override(client, plane):
    client.create_queue("ovr", priority_factor=1.0)
    client.set_priority_override("ovr", 5.0)
    assert client.list_priority_overrides() == {"ovr": 5.0}
    # effective queue weight now 1/5
    eff = plane.scheduler._effective_queue("ovr")
    assert eff.priority_factor == 5.0
    client.set_priority_override("ovr", None)
    assert client.list_priority_overrides() == {}


def test_lookout_http(plane, client):
    import json as _json
    import urllib.request

    from armada_tpu.services.lookout_http import LookoutHttpServer

    import urllib.error

    lk = LookoutHttpServer(plane.query, plane.scheduler, plane.submit, 0)
    try:
        client.create_queue("web")
        ids = client.submit_jobs("web", "web-set", [dict(JOB) for _ in range(3)])
        assert _wait(lambda: plane.scheduler.jobdb.get(ids[0]) is not None)
        base = f"http://127.0.0.1:{lk.port}"
        jobs = _json.load(urllib.request.urlopen(f"{base}/api/jobs?queue=web"))
        assert jobs["total"] == 3
        groups = _json.load(urllib.request.urlopen(f"{base}/api/groups?by=state"))
        assert sum(g["count"] for g in groups["groups"]) >= 3
        detail = _json.load(urllib.request.urlopen(f"{base}/api/job/{ids[0]}"))
        assert detail["spec"]["id"] == ids[0]
        html = urllib.request.urlopen(base).read().decode()
        assert "armada-tpu" in html and "lookout" in html
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/api/job/nope")
        assert exc.value.code == 404
    finally:
        lk.stop()


def test_remote_executor_agent():
    """Full lease protocol over real gRPC: a remote agent (no in-process
    executor) heartbeats, receives leases, runs pods, reports lifecycle."""
    from armada_tpu.services.executor_agent import ExecutorAgent, _PodRuntime

    p = ControlPlane(SchedulingConfig(), cycle_period=0.05).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("remote")
        agent = ExecutorAgent(
            ApiClient(p.address),
            "remote-exec",
            nodes=[
                {"id": f"rn-{i}", "total_resources": {"cpu": "8", "memory": "32Gi"}}
                for i in range(2)
            ],
            runtime=_PodRuntime(runtime_s=1.0),
        )
        agent.tick()  # register nodes
        ids = client.submit_jobs(
            "remote", "rset", [{"requests": {"cpu": "2", "memory": "1Gi"}} for _ in range(3)]
        )

        def all_in(*states):
            return all(
                (j := p.scheduler.jobdb.get(i)) is not None and j.state.value in states
                for i in ids
            )

        assert _wait(lambda: all_in("leased") or all_in("leased", "pending", "running"))
        agent.tick()  # pick up leases -> pods created -> pending
        assert _wait(lambda: all_in("pending", "running"))
        agent.tick()  # running
        deadline = time.time() + 15
        while time.time() < deadline and not all_in("succeeded"):
            agent.tick()
            time.sleep(0.2)
        assert all_in("succeeded")
        # run/node info round-tripped through the protocol
        run = p.scheduler.jobdb.get(ids[0]).latest_run
        assert run.executor == "remote-exec"
        assert run.node_id.startswith("rn-")
    finally:
        p.stop()


def test_executor_agent_restart_reconciliation():
    """An agent restart loses its pods; the protocol's active-run
    reconciliation reports them failed and the scheduler retries."""
    from armada_tpu.services.executor_agent import ExecutorAgent, _PodRuntime

    p = ControlPlane(SchedulingConfig(), cycle_period=0.05).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("rr")
        agent = ExecutorAgent(
            ApiClient(p.address),
            "rr-exec",
            nodes=[
                {"id": f"rr-{i}", "total_resources": {"cpu": "8", "memory": "32Gi"}}
                for i in range(2)
            ],
            runtime=_PodRuntime(runtime_s=300.0),
        )
        agent.tick()
        (jid,) = client.submit_jobs("rr", "s", [{"requests": {"cpu": "2", "memory": "1Gi"}}])
        assert _wait(lambda: (j := p.scheduler.jobdb.get(jid)) and j.state.value == "leased")
        agent.tick()  # pod created -> pending
        agent.tick()  # running
        assert _wait(lambda: p.scheduler.jobdb.get(jid).state.value == "running")
        first_run = p.scheduler.jobdb.get(jid).latest_run.id

        # "restart": fresh agent, empty runtime and acks
        agent2 = ExecutorAgent(
            ApiClient(p.address), "rr-exec", nodes=agent.nodes,
            runtime=_PodRuntime(runtime_s=1.0),
        )
        agent2.tick()  # reconciliation reports the run failed

        def retried():
            j = p.scheduler.jobdb.get(jid)
            return (
                j is not None
                and j.num_attempts >= 2
                and j.latest_run.id != first_run
                and j.state.value in ("leased", "pending", "running", "succeeded")
            )

        assert _wait(retried, timeout=15)
        j = p.scheduler.jobdb.get(jid)
        assert first_run not in {r.id for r in j.runs if r.state.value != "failed"}
        # second attempt completes on the new agent
        deadline = time.time() + 15
        while time.time() < deadline and p.scheduler.jobdb.get(jid).state.value != "succeeded":
            agent2.tick()
            time.sleep(0.2)
        assert p.scheduler.jobdb.get(jid).state.value == "succeeded"
    finally:
        p.stop()


def test_file_lease_leader(tmp_path):
    path = str(tmp_path / "lease")
    a = FileLeaseLeader(path, lease_duration=0.5, identity="a")
    b = FileLeaseLeader(path, lease_duration=0.5, identity="b")
    assert a()
    assert not b()  # a holds the lease
    token = a.get_token()
    assert a.validate(token)
    time.sleep(0.6)  # lease expires
    assert b()  # b takes over
    assert not a.validate(token)


def test_cli_against_server(plane, capsys, tmp_path):
    from armada_tpu.clients.cli import main

    main(["--server", plane.address, "queue", "create", "cli-q"])
    jobfile = tmp_path / "jobs.yaml"
    jobfile.write_text(
        """
queue: cli-q
jobSetId: cli-set
jobs:
  - priority: 0
    count: 3
    requests:
      cpu: "1"
      memory: 1Gi
"""
    )
    main(["--server", plane.address, "submit", str(jobfile)])
    out = capsys.readouterr().out
    job_ids = [line for line in out.splitlines() if line.startswith("job-")]
    assert len(job_ids) == 3
    # ingestion happens on the next cycle
    assert _wait(lambda: plane.scheduler.jobdb.get(job_ids[0]) is not None)
    main(["--server", plane.address, "jobs", "--queue", "cli-q"])
    out = capsys.readouterr().out
    assert '"total": 3' in out
    main(["--server", plane.address, "report", "scheduling"])


def test_cordon_executor_over_grpc(client, plane):
    client.cordon_executor("fake-a")
    assert "fake-a" in plane.scheduler.cordoned_executors
    client.cordon_executor("fake-a", uncordon=True)
    assert "fake-a" not in plane.scheduler.cordoned_executors


def test_whatif_rpcs_both_wires(client, plane):
    """WhatIf/PlanDrain/ExecuteDrain work over the JSON wire AND the
    binary-protobuf wire, and the planner's backlog cap maps to
    RESOURCE_EXHAUSTED."""
    import grpc

    from armada_tpu.services.grpc_api import ProtoApiClient

    client.create_queue("wiq")
    client.submit_jobs("wiq", "s", [dict(JOB) for _ in range(2)])
    _wait(lambda: plane.scheduler.jobdb.read_txn().leased_jobs())
    # JSON wire: inject-gang plan with a structured outcome.
    out = client.what_if(
        [{"kind": "inject_gang", "queue": "wiq", "gang_cardinality": 2,
          "cpu": "1", "memory": "1Gi"}],
        rounds=3,
    )
    assert out["plan"]["injected"][0]["eta_rounds"] == 1
    assert "injected" in out["rendered"]
    # Proto wire: same method table, same plan shape.
    pclient = ProtoApiClient(plane.address)
    pout = pclient.what_if(
        [{"kind": "inject_gang", "queue": "wiq", "gang_cardinality": 2,
          "cpu": "1", "memory": "1Gi"}],
        rounds=3,
    )
    assert pout["plan"]["injected"][0]["eta_rounds"] == 1
    # Drain dry-run over both wires agrees on the preempted set.
    dj = client.plan_drain("fake-a", deadline_s=0.0, rounds=6)
    dp = pclient.plan_drain("fake-a", deadline_s=0.0, rounds=6)
    assert (
        dj["plan"]["drain"]["preempted"] == dp["plan"]["drain"]["preempted"]
    )
    # Backlog cap: a zero-depth planner rejects with RESOURCE_EXHAUSTED.
    plane.whatif.queue_depth = 0
    try:
        with pytest.raises(grpc.RpcError) as err:
            client.what_if(
                [{"kind": "inject_gang", "queue": "wiq",
                  "gang_cardinality": 1, "cpu": "1"}]
            )
        assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
    finally:
        plane.whatif.queue_depth = 8


def test_follower_proxies_reports_to_leader(tmp_path):
    """File-lease HA: a follower answers report RPCs by proxying to the
    leader's advertised address (the reference proxies reports over the
    Lease-holder connection); with the leader gone it falls back to its
    local view instead of failing."""
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.core.types import JobSpec, QueueSpec
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.grpc_api import ApiClient, ApiServer
    from armada_tpu.services.queryapi import QueryApi
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    path = str(tmp_path / "lease")
    config = SchedulingConfig()

    def build(identity):
        log = InMemoryEventLog()
        elector = FileLeaseLeader(path, lease_duration=30.0, identity=identity)
        sched = SchedulerService(config, log, backend="oracle",
                                 is_leader=elector)
        submit = SubmitService(config, log, scheduler=sched)
        api = ApiServer(submit, sched, QueryApi(sched.jobdb), log)
        server, port = api.serve(0)
        elector.advertise = f"127.0.0.1:{port}"
        return log, elector, sched, submit, server, port

    log_a, el_a, sched_a, submit_a, srv_a, port_a = build("a")
    assert el_a()  # a acquires (and writes its advertise on next renew)
    assert el_a()  # renew persists the advertise line
    log_b, el_b, sched_b, submit_b, srv_b, port_b = build("b")
    assert not el_b()  # b is a follower

    try:
        # Only the LEADER runs a round (the follower's reports are empty).
        submit_a.create_queue(QueueSpec("team"))
        FakeExecutor("c", log_a, sched_a,
                     nodes=make_nodes("c", count=2, cpu="8", memory="32Gi"),
                     runtime_for=lambda j: 100.0).tick(0.0)
        submit_a.submit(
            "team", "s1",
            [JobSpec(id="j0", queue="",
                     requests={"cpu": "1", "memory": "1Gi"})],
            now=0.0,
        )
        sched_a.cycle(now=1.0)
        assert "team" in sched_a.reports.scheduling_report()
        assert "team" not in sched_b.reports.scheduling_report()

        # The follower's RPC answer carries the leader's report.
        client_b = ApiClient(f"127.0.0.1:{port_b}")
        rep = client_b._call("SchedulingReport", {})["report"]
        assert "team" in rep

        # Leader gone: the follower serves its own (empty) view rather
        # than erroring.
        srv_a.stop(grace=0)
        rep = client_b._call("SchedulingReport", {})["report"]
        assert "team" not in rep
    finally:
        srv_b.stop(grace=0)
