"""Job-journey observability: cross-process trace propagation (W3C
traceparent over gRPC metadata -> EventSequence), the per-job timeline
ledger (services/job_timeline.py), and its query surfaces (JobTrace RPC,
armadactl job-trace, lookout /api/jobtrace)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.services.grpc_api import ApiClient
from armada_tpu.services.job_timeline import JobTimelineStore
from armada_tpu.services.server import ControlPlane
from armada_tpu.utils.tracing import TRACER, parse_traceparent


def _wait(predicate, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ---- timeline store unit behavior -----------------------------------


def test_timeline_aggregates_unschedulable_rounds():
    """Per-round reasons fold into bounded per-reason aggregates: 10k
    pending rounds cost reason buckets, not 10k entries."""
    from armada_tpu.events import JobRunLeased, SubmitJob
    from armada_tpu.core.types import JobSpec
    from armada_tpu.events.model import EventSequence

    store = JobTimelineStore()
    seq = EventSequence.of(
        "team", "s1",
        traceparent="00-" + "ab" * 16 + "-" + "cd" * 8 + "-01",
    )
    store.observe_event(
        SubmitJob(created=100.0, job=JobSpec(id="j1", queue="team", jobset="s1")),
        seq,
    )
    for i in range(11):
        store.note_round_reasons(
            "default", 110.0 + i, {"j1": "insufficient-capacity"}
        )
    for i in range(3):
        store.note_round_reasons("default", 130.0 + i, {"j1": "fair-share"})
    store.observe_event(
        JobRunLeased(created=221.0, job_id="j1", run_id="r1",
                     executor="ex", node_id="node-281", pool="default"),
        None,
    )
    doc = store.get("j1")
    assert doc["rounds_unschedulable"] == 14
    assert doc["reasons"]["insufficient-capacity"]["count"] == 11
    assert doc["reasons"]["fair-share"]["count"] == 3
    assert doc["trace_id"] == "ab" * 16
    assert len(doc["entries"]) == 2  # submitted + leased, not 14 rounds
    rendered = store.render("j1")
    assert "14 rounds unschedulable" in rendered
    assert "insufficient-capacity ×11" in rendered
    assert "fair-share ×3" in rendered
    assert "node-281" in rendered
    assert "trace " + "ab" * 16 in rendered
    # The unschedulable summary renders between submit and lease.
    lines = rendered.splitlines()
    assert lines.index(
        next(l for l in lines if "rounds unschedulable" in l)
    ) > lines.index(next(l for l in lines if "submitted" in l))


def test_timeline_bounded_eviction_prefers_terminal_then_leased():
    from armada_tpu.events import JobRunLeased, JobSucceeded, SubmitJob
    from armada_tpu.core.types import JobSpec

    store = JobTimelineStore(max_jobs=3)
    for jid in ("pending", "leased", "done"):
        store.observe_event(
            SubmitJob(created=1.0, job=JobSpec(id=jid, queue="q")), None
        )
    store.observe_event(
        JobRunLeased(created=2.0, job_id="leased", run_id="r"), None
    )
    store.observe_event(JobSucceeded(created=2.0, job_id="done"), None)
    # Terminal journeys go first...
    store.observe_event(
        SubmitJob(created=3.0, job=JobSpec(id="j4", queue="q")), None
    )
    assert store.get("done") is None
    # ...then ones that at least reached a lease...
    store.observe_event(
        SubmitJob(created=4.0, job=JobSpec(id="j5", queue="q")), None
    )
    assert store.get("leased") is None
    # ...and an all-pending ledger keeps the LONG-pending journeys,
    # leaving the newest job untracked instead.
    store.observe_event(
        SubmitJob(created=5.0, job=JobSpec(id="j6", queue="q")), None
    )
    assert store.get("j6") is None
    assert store.get("pending") is not None
    assert store.get("j4") is not None and store.get("j5") is not None
    # has_leased gates the first-lease-only metrics.
    store.observe_event(
        JobRunLeased(created=6.0, job_id="pending", run_id="r2"), None
    )
    assert store.has_leased("pending") and not store.has_leased("j4")


def test_timeline_entry_cap_keeps_terminal_visible():
    from armada_tpu.events import JobErrors, JobRequeued, SubmitJob
    from armada_tpu.core.types import JobSpec

    store = JobTimelineStore(max_entries=4)
    store.observe_event(
        SubmitJob(created=0.0, job=JobSpec(id="j1", queue="q")), None
    )
    for i in range(10):
        store.observe_event(JobRequeued(created=1.0 + i, job_id="j1"), None)
    store.observe_event(
        JobErrors(created=99.0, job_id="j1", error="max retries"), None
    )
    doc = store.get("j1")
    assert len(doc["entries"]) == 4
    assert doc["entries"][-1]["kind"] == "failed"


# ---- cross-process propagation (the socket acceptance test) ---------


def test_one_trace_id_spans_submit_to_lease_over_grpc():
    """One trace id follows a job across real gRPC: the client's
    traceparent metadata reaches the server interceptor (asserted via
    the server-side rpc span it opens), the submit EventSequence carries
    it, the scheduler continues it onto the lease, and the remote
    executor agent echoes it on the run lifecycle reports."""
    from armada_tpu.services.executor_agent import ExecutorAgent, _PodRuntime

    p = ControlPlane(SchedulingConfig(), cycle_period=0.05).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("team")
        agent = ExecutorAgent(
            ApiClient(p.address),
            "trace-exec",
            nodes=[{"id": "tn-0",
                    "total_resources": {"cpu": "8", "memory": "32Gi"}}],
            runtime=_PodRuntime(runtime_s=0.5),
        )
        agent.tick()
        with TRACER.span("test.submit") as client_span:
            ids = client.submit_jobs(
                "team", "traced",
                [{"requests": {"cpu": "2", "memory": "1Gi"}}],
            )
            trace_id = client_span.trace_id
        jid = ids[0]

        def done():
            agent.tick()
            j = p.scheduler.jobdb.get(jid)
            return j is not None and j.state.value == "succeeded"

        assert _wait(done)
        # Interceptor metadata: the server span opened around the
        # SubmitJobs handler joined the CLIENT's trace — the traceparent
        # crossed the socket.
        rpc_spans = [
            s for s in TRACER.finished
            if s.name == "rpc.SubmitJobs" and s.trace_id == trace_id
        ]
        assert rpc_spans, "no server-side rpc span joined the client trace"
        assert rpc_spans[0].span_id != client_span.span_id
        # The journey ledger recorded the same trace id...
        assert p.scheduler.timeline.get(jid)["trace_id"] == trace_id
        # ...and every hop's published events carry it: submit (client ->
        # server), lease (scheduler round), run lifecycle (executor agent
        # echoing over ReportEvents — a second real gRPC hop).
        by_event = {}
        for entry in p.log.read(0, 10**6):
            for ev in entry.sequence.events:
                named = getattr(ev, "job_id", "") == jid or (
                    getattr(ev, "job", None) is not None and ev.job.id == jid
                )
                if named:
                    by_event.setdefault(type(ev).__name__, set()).add(
                        entry.sequence.traceparent
                    )
        for name in ("SubmitJob", "JobRunLeased", "JobRunPending",
                     "JobRunRunning", "JobRunSucceeded"):
            parsed = {parse_traceparent(tp) for tp in by_event[name]}
            assert {p_[0] for p_ in parsed if p_} == {trace_id}, (
                name, by_event[name]
            )
        # The JobTrace RPC surfaces it.
        trace = client.job_trace(jid)
        assert trace["journey"]["trace_id"] == trace_id
        assert trace_id in trace["rendered"]
    finally:
        p.stop()


# ---- multi-round unschedulable history + CLI/HTTP surfaces ----------


@pytest.fixture()
def stuck_plane():
    """A control plane with a job that can never fit: every oracle round
    reports it unschedulable, building a multi-round history."""
    p = ControlPlane(
        SchedulingConfig(),
        cycle_period=0.05,
        fake_executors=[{"name": "small", "nodes": 2, "cpu": "8"}],
    ).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("team")
        (jid,) = client.submit_jobs(
            "team", "stuck", [{"requests": {"cpu": "999", "memory": "1Gi"}}]
        )
        assert _wait(
            lambda: p.scheduler.timeline.rounds_unschedulable(jid) >= 3
        )
        yield p, client, jid
    finally:
        p.stop()


def test_job_trace_cli_renders_multiround_history(stuck_plane, capsys):
    from armada_tpu.clients.cli import main

    p, client, jid = stuck_plane
    main(["--server", p.address, "job-trace", jid])
    out = capsys.readouterr().out
    assert "rounds unschedulable" in out
    assert "job does not fit on any node ×" in out
    assert "submitted" in out
    # --json prints the raw journey record
    main(["--server", p.address, "job-trace", jid, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["rounds_unschedulable"] >= 3
    assert doc["reasons"]["job does not fit on any node"]["count"] >= 3


def test_job_trace_query_and_lookout_http(stuck_plane):
    from armada_tpu.services.lookout_http import LookoutHttpServer

    p, client, jid = stuck_plane
    # queryapi surface
    trace = p.query.job_trace(jid)
    assert trace["journey"]["rounds_unschedulable"] >= 3
    # lookout HTTP surface
    lk = LookoutHttpServer(p.query, p.scheduler, p.submit, 0)
    try:
        doc = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{lk.port}/api/jobtrace/{jid}"
        ))
        assert doc["journey"]["job_id"] == jid
        assert "rounds unschedulable" in doc["rendered"]
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{lk.port}/api/jobtrace/ghost"
            )
        assert exc.value.code == 404
    finally:
        lk.stop()


def test_job_trace_unknown_job_is_not_found(stuck_plane):
    import grpc

    p, client, jid = stuck_plane
    with pytest.raises(grpc.RpcError) as exc:
        client.job_trace("no-such-job")
    assert exc.value.code() == grpc.StatusCode.NOT_FOUND


# ---- round report reason aggregation (satellite) --------------------


def test_round_report_top_reasons_match_job_reason_map():
    """QueueReport.top_reasons is exactly the histogram of the round's
    per-job reason map, per queue, on a mixed-fleet round (fitting jobs,
    no-fit jobs, two queues)."""
    from armada_tpu.core.types import JobSpec, QueueSpec
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig()
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("qa"))
    submit.create_queue(QueueSpec("qb"))
    FakeExecutor(
        "c", log, sched,
        nodes=make_nodes("c", count=2, cpu="8", memory="32Gi"),
        runtime_for=lambda j: 1000.0,
    ).tick(0.0)

    def job(i, queue, cpu):
        return JobSpec(id=f"{queue}-{i}", queue="",
                       requests={"cpu": cpu, "memory": "1Gi"})

    submit.submit("qa", "s", [job(i, "qa", "999") for i in range(3)], now=0.0)
    submit.submit(
        "qb", "s",
        [job(0, "qb", "999"), job(1, "qb", "999"), job(2, "qb", "1")],
        now=0.0,
    )
    sched.cycle(now=1.0)
    report = sched.reports.latest_reports()["default"]
    assert report.job_reasons, "expected unschedulable jobs in the round"
    # Rebuild the per-queue histogram from the per-job map and compare.
    txn = sched.jobdb.read_txn()
    expected: dict = {}
    for job_id, reason in report.job_reasons.items():
        queue = txn.get(job_id).queue
        expected.setdefault(queue, {})
        expected[queue][reason] = expected[queue].get(reason, 0) + 1
    actual = {
        name: dict(qr.top_reasons)
        for name, qr in report.queues.items()
        if qr.top_reasons
    }
    assert actual == expected
    assert expected["qa"] == {"job does not fit on any node": 3}
    assert expected["qb"]["job does not fit on any node"] == 2
    # The queue report surfaces the counts.
    rendered = sched.reports.queue_report("qa")
    assert "3 jobs: job does not fit on any node" in rendered
    # And the journey ledger absorbed the same history.
    assert sched.timeline.rounds_unschedulable("qa-0") == 1
