"""Run↔node reconciliation (scheduling/reconciliation.go, consumed at
scheduling_algo.go:293-398): leased runs are validated against
executor-reported nodes each cycle."""

from armada_tpu.core.config import PoolConfig, PriorityClass, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.jobdb import JobState
from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
from armada_tpu.services.scheduler import ExecutorHeartbeat, SchedulerService
from armada_tpu.services.submit import SubmitService


def mk_stack(run_reconciliation=True, preemptible=True):
    config = SchedulingConfig(
        pools=(
            PoolConfig(name="default", run_reconciliation=run_reconciliation),
        ),
        priority_classes={
            "default": PriorityClass("default", 1000, preemptible=preemptible),
        },
        default_priority_class="default",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    executor = FakeExecutor(
        "cluster-a",
        log,
        sched,
        nodes=make_nodes("cluster-a", count=3, cpu="16", memory="64Gi"),
        runtime_for=lambda job_id: 1e9,
    )
    return config, log, sched, submit, executor


def job(i, **kw):
    return JobSpec(
        id=f"job-{i:04d}", queue="", requests={"cpu": "2", "memory": "4Gi"}, **kw
    )


def _lease_all(sched, submit, executor, jobs):
    submit.create_queue(QueueSpec("team"))
    submit.submit("team", "set1", jobs, now=0.0)
    executor.tick(0.0)
    sched.cycle(now=1.0)
    executor.tick(1.5)  # ack leases, start pods
    sched.cycle(now=2.0)


def test_gang_on_deleted_node_preempted_gang_aware():
    config, log, sched, submit, executor = mk_stack()
    gang = Gang(id="g0", cardinality=2)
    jobs = [job(0, gang=gang), job(1, gang=gang), job(2)]
    _lease_all(sched, submit, executor, jobs)
    txn = sched.jobdb.read_txn()
    leased = {j.id: j for j in txn.leased_jobs()}
    assert len(leased) == 3
    gang_nodes = {leased["job-0000"].latest_run.node_id}

    # The node hosting gang member 0 disappears from the heartbeat.
    hb = sched.executors["cluster-a"]
    surviving = [n for n in hb.nodes if n.id not in gang_nodes]
    sched.report_executor(
        ExecutorHeartbeat(
            name="cluster-a", pool="default", nodes=surviving, last_seen=3.0
        )
    )
    sched.cycle(now=3.0)
    txn = sched.jobdb.read_txn()
    # Both gang members preempted (gang-aware), then rescheduled or queued;
    # they must not still be leased to the vanished node.
    for jid in ("job-0000", "job-0001"):
        j = txn.get(jid)
        run = j.latest_run
        assert (
            j.state == JobState.QUEUED
            or j.state == JobState.PREEMPTED
            or (run is not None and run.node_id not in gang_nodes)
        ), (jid, j.state, run)
    preempted_runs = [
        j for jid in ("job-0000", "job-0001")
        for j in [txn.get(jid)]
        if any(r.state.value == "preempted" for r in j.runs)
    ]
    assert len(preempted_runs) == 2, "gang members not both preempted"


def test_non_gang_on_deleted_node_only_warned():
    config, log, sched, submit, executor = mk_stack()
    jobs = [job(0)]
    _lease_all(sched, submit, executor, jobs)
    txn = sched.jobdb.read_txn()
    j = txn.get("job-0000")
    node = j.latest_run.node_id
    hb = sched.executors["cluster-a"]
    sched.report_executor(
        ExecutorHeartbeat(
            name="cluster-a",
            pool="default",
            nodes=[n for n in hb.nodes if n.id != node],
            last_seen=3.0,
        )
    )
    seqs = sched._reconcile_runs(3.0)
    assert seqs == []  # logged, not preempted (checkJobsOnDeletedNodes)


def test_pool_change_invalidates_any_job():
    config, log, sched, submit, executor = mk_stack()
    jobs = [job(0)]
    _lease_all(sched, submit, executor, jobs)
    txn = sched.jobdb.read_txn()
    j = txn.get("job-0000")
    # The whole executor moves pools: the leased run's node now reports a
    # different pool than the run was scheduled into.
    hb = sched.executors["cluster-a"]
    sched.report_executor(
        ExecutorHeartbeat(
            name="cluster-a", pool="gpu-pool", nodes=hb.nodes, last_seen=3.0
        )
    )
    seqs = sched._reconcile_runs(3.0)
    assert len(seqs) == 1
    assert "moved from pool" in seqs[0].events[0].reason


def test_disabled_reconciliation_is_noop():
    config, log, sched, submit, executor = mk_stack(run_reconciliation=False)
    gang = Gang(id="g0", cardinality=2)
    _lease_all(sched, submit, executor, [job(0, gang=gang), job(1, gang=gang)])
    sched.report_executor(
        ExecutorHeartbeat(name="cluster-a", pool="default", nodes=[], last_seen=3.0)
    )
    assert sched._reconcile_runs(3.0) == []
