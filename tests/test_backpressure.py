"""Store backpressure (services/backpressure.py — the reference's etcd
health monitoring, common/etcdhealth + executor/application.go:63-101):
submissions shed and executors pause pod creation while the event store
is over capacity or its views lag too far."""

import time

import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, QueueSpec
from armada_tpu.events.file_log import FileEventLog
from armada_tpu.services.backpressure import StoreHealthMonitor
from armada_tpu.services.executor_agent import ExecutorAgent, _PodRuntime
from armada_tpu.services.grpc_api import ApiClient
from armada_tpu.services.server import ControlPlane
from armada_tpu.services.submit import SubmissionError

CFG = SchedulingConfig(
    priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
    default_priority_class="d",
)


def test_monitor_size_fraction(tmp_path):
    from armada_tpu.events import EventSequence, SubmitJob

    log = FileEventLog(str(tmp_path / "log"))
    mon = StoreHealthMonitor(
        log, capacity_bytes=4000, fraction_of_capacity_limit=0.5,
        check_interval_s=0.0,
    )
    assert mon.check() == (True, "")
    for i in range(20):
        log.publish(
            EventSequence.of(
                "q", "s",
                SubmitJob(
                    created=float(i),
                    job=JobSpec(id=f"j{i}", queue="q",
                                requests={"cpu": "1", "memory": "1Gi"}),
                ),
            )
        )
    healthy, reason = mon.check()
    assert not healthy and "storeSizeExceeded" in reason


def test_monitor_ingest_lag():
    from armada_tpu.events import InMemoryEventLog

    log = InMemoryEventLog()
    mon = StoreHealthMonitor(
        log, max_ingest_lag_events=10, check_interval_s=0.0
    )
    lag = {"n": 0}
    mon.add_lag_source("view", lambda: lag["n"])
    assert mon.check()[0]
    lag["n"] = 50
    healthy, reason = mon.check()
    assert not healthy and "ingestLagExceeded" in reason and "view" in reason
    lag["n"] = 0
    assert mon.check()[0]


def test_submission_shed_and_executor_pause(tmp_path):
    """Over-capacity store: submissions are rejected and agents stop
    creating pods for new leases; both recover when pressure clears."""
    import dataclasses

    config = dataclasses.replace(
        CFG, store_capacity_bytes=100_000_000,
        store_fraction_of_capacity_limit=0.9,
    )
    plane = ControlPlane(
        config, cycle_period=3600, data_dir=str(tmp_path / "data")
    ).start()
    try:
        plane.store_health.check_interval_s = 0.0
        client = ApiClient(plane.address)
        client.create_queue("bq")
        jid = client.submit_jobs(
            "bq", "bs",
            [{"requests": {"cpu": "1", "memory": "1Gi"}}],
        )[0]
        plane.scheduler.ingester.sync()

        agent = ExecutorAgent(
            ApiClient(plane.address), "bp-exec",
            nodes=[{"id": "b0", "total_resources": {"cpu": "8", "memory": "32Gi"}}],
            runtime=_PodRuntime(runtime_s=60.0),
        )
        agent.tick(0.0)
        plane.scheduler.cycle(now=1.0)

        # Pressure on: shrink the quota so the existing log exceeds it.
        plane.store_health.capacity_bytes = 10

        with pytest.raises(SubmissionError, match="store backpressure"):
            plane.submit.submit(
                "bq", "bs",
                [JobSpec(id="shed", queue="",
                         requests={"cpu": "1", "memory": "1Gi"})],
                now=2.0,
            )
        # gRPC surface translates the rejection.
        with pytest.raises(Exception, match="store backpressure"):
            client.submit_jobs(
                "bq", "bs", [{"requests": {"cpu": "1", "memory": "1Gi"}}]
            )

        # The agent receives the lease but defers pod creation.
        agent.tick(2.0)
        assert jid not in {
            p["job_id"] for p in agent.runtime.pods.values()
        }
        assert not agent.acked

        # Pressure off: the re-sent lease is created on the next tick.
        plane.store_health.capacity_bytes = 100_000_000
        agent.tick(3.0)
        assert jid in {p["job_id"] for p in agent.runtime.pods.values()}

        # Submissions flow again.
        plane.submit.submit(
            "bq", "bs",
            [JobSpec(id="after", queue="",
                     requests={"cpu": "1", "memory": "1Gi"})],
            now=4.0,
        )
    finally:
        plane.stop()
