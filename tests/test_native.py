"""Native C++ quantity parser: bit-exact equivalence with the Fraction path,
fuzzed over the full k8s quantity grammar."""

import math
from fractions import Fraction

import numpy as np
import pytest

from armada_tpu.core.resources import ResourceListFactory, parse_quantity

native = pytest.importorskip("_armada_native")


def py_scale(value, scale: int, ceil: bool) -> int:
    scaled = parse_quantity(value) / (Fraction(10) ** scale)
    value = int(math.ceil(scaled) if ceil else math.floor(scaled))
    return min(max(value, -(2**63)), 2**63 - 1)  # saturating, like native


SAMPLES = [
    "0", "1", "42", "100m", "1500m", "0.5", "0.0001", "2.75",
    "1Ki", "2Mi", "1.5Gi", "3Ti", "7Pi", "1Ei",
    "1k", "250M", "3G", "2T", "1P", "5E",
    "2e3", "1e-3", "2.5e2", "1E3", "5e0",
    "123456789", "999999999999", "0.001", "16Gi", "128Gi", "100Mi",
    7, 1000, 0.25, 3.5, "-5", "-100m", "  8  ",
]


@pytest.mark.parametrize("scale", [-3, 0, 3, 8])
@pytest.mark.parametrize("ceil", [True, False])
def test_samples_match_fraction_path(scale, ceil):
    for value in SAMPLES:
        expected = py_scale(value, scale, ceil)
        got = native.parse_quantity(value, scale, ceil)
        assert got == expected, (value, scale, ceil, got, expected)


def test_fuzz_random_quantities():
    rng = np.random.default_rng(0)
    suffixes = ["", "m", "k", "M", "G", "Ki", "Mi", "Gi", "Ti", "n", "u"]
    for _ in range(3000):
        mant = rng.integers(0, 10**9)
        frac = rng.integers(0, 1000)
        suffix = suffixes[rng.integers(0, len(suffixes))]
        s = f"{mant}.{frac:03d}{suffix}" if rng.random() < 0.5 else f"{mant}{suffix}"
        scale = int(rng.choice([-3, 0, 3]))
        ceil = bool(rng.random() < 0.5)
        assert native.parse_quantity(s, scale, ceil) == py_scale(s, scale, ceil), s


def test_invalid_inputs_raise():
    for bad in ["", "abc", "1.2.3", "12X", "e3", "--1"]:
        with pytest.raises(ValueError):
            native.parse_quantity(bad, 0, True)


def test_batch_and_encode_requests():
    f = ResourceListFactory.create(
        [("memory", "1"), ("cpu", "1m"), ("nvidia.com/gpu", "1")]
    )
    reqs = [
        {"cpu": "2", "memory": "4Gi"},
        {"cpu": "500m", "memory": "1.5Gi", "nvidia.com/gpu": "1"},
        {},
        {"unknown/thing": "7", "cpu": "1"},
    ]
    got = f.encode_requests_batch(reqs, ceil=True)
    expected = np.stack([f.from_map(r, ceil=True) for r in reqs])
    assert (got == expected).all()


def test_batch_speed_sanity():
    import time

    f = ResourceListFactory.create([("memory", "1"), ("cpu", "1m")])
    reqs = [{"cpu": "1500m", "memory": "16Gi"}] * 50_000
    t0 = time.time()
    f.encode_requests_batch(reqs, ceil=True)
    native_t = time.time() - t0
    # 50k jobs in well under a second (the Fraction path takes ~5s)
    assert native_t < 1.0, f"native batch too slow: {native_t:.2f}s"
