"""Differential tests: IncrementalRound must reach solve decisions
identical to a fresh build_round_snapshot at every point of a delta
sequence — adds, binds, removals, unbinds, gang completion across cycles.
"""

import numpy as np
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import (
    Gang,
    JobSpec,
    NodeSpec,
    QueueSpec,
    RunningJob,
    Taint,
    Toleration,
)
from armada_tpu.snapshot.incremental import (
    IncrementalRound,
    SnapshotRebuildRequired,
)
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round


def make_config(**kw):
    return SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        **kw,
    )


def make_nodes(n=8):
    nodes = []
    for i in range(n):
        taints = (Taint("gpu", "true", "NoSchedule"),) if i % 4 == 3 else ()
        labels = {"zone": f"z{i % 2}", "disk": "ssd" if i % 2 else "hdd"}
        nodes.append(
            NodeSpec(
                id=f"node-{i:03d}",
                pool="default",
                taints=taints,
                labels=labels,
                total_resources={"cpu": "16", "memory": "64Gi"},
            )
        )
    return nodes


def job(i, queue="q-a", cpu=2, pc="low", prio=0, sel=None, tol=False, gang=None):
    return JobSpec(
        id=f"job-{i:04d}",
        queue=queue,
        priority=prio,
        priority_class=pc,
        requests={"cpu": str(cpu), "memory": f"{cpu * 2}Gi"},
        node_selector=sel or {},
        tolerations=(Toleration("gpu", "Equal", "true", "NoSchedule"),)
        if tol
        else (),
        gang=gang,
        submitted_ts=float(i),
    )


QUEUES = [QueueSpec("q-a", 1.0), QueueSpec("q-b", 2.0)]


def solve_ids(snap, dev):
    """Solve and decode to comparable, row-order-independent structures."""
    out = solve_round(pad_device_round(dev))
    J = snap.num_jobs
    sched = {}
    for j in np.flatnonzero(np.asarray(out["scheduled_mask"][:J])):
        sched[str(snap.job_ids[j])] = (
            snap.node_ids[int(out["assigned_node"][j])],
            int(out["scheduled_priority"][j]),
        )
    preempted = {
        str(snap.job_ids[j])
        for j in np.flatnonzero(np.asarray(out["preempted_mask"][:J]))
    }
    Q = snap.num_queues
    fs = np.asarray(out["fair_share"][:Q])
    return sched, preempted, fs


class Mirror:
    """Python-object mirror of the incremental state, driving fresh builds."""

    def __init__(self, cfg, nodes, running, queued):
        self.cfg = cfg
        self.nodes = nodes
        self.running = {r.job.id: r for r in running}
        self.queued = {j.id: j for j in queued}

    def fresh(self):
        return build_round_snapshot(
            self.cfg,
            "default",
            self.nodes,
            QUEUES,
            list(self.running.values()),
            list(self.queued.values()),
        )

    def add(self, jobs):
        for j in jobs:
            self.queued[j.id] = j

    def bind(self, leases):
        for jid, nid, prio, ts in leases:
            self.running[jid] = RunningJob(
                job=self.queued.pop(jid),
                node_id=nid,
                scheduled_at_priority=prio,
                leased_ts=ts,
            )

    def unbind(self, ids):
        for jid in ids:
            self.queued[jid] = self.running.pop(jid).job

    def remove(self, ids):
        for jid in ids:
            self.running.pop(jid, None)
            self.queued.pop(jid, None)


def assert_same_decisions(inc, mirror):
    snap_i = inc.snapshot()
    dev_i = inc.device_round()
    snap_f = mirror.fresh()
    dev_f = prep_device_round(snap_f)
    s_i, p_i, fs_i = solve_ids(snap_i, dev_i)
    s_f, p_f, fs_f = solve_ids(snap_f, dev_f)
    assert s_i == s_f
    assert p_i == p_f
    np.testing.assert_allclose(fs_i, fs_f, rtol=1e-12)
    # Accounting parity, mapped by id (row orders differ).
    ids_f = list(snap_f.job_ids)
    rows_i = [inc._id_to_row[i] for i in ids_f]
    np.testing.assert_array_equal(snap_i.job_req[rows_i], snap_f.job_req)
    np.testing.assert_array_equal(snap_i.job_queue[rows_i], snap_f.job_queue)
    np.testing.assert_array_equal(
        snap_i.job_is_running[rows_i], snap_f.job_is_running
    )
    np.testing.assert_array_equal(snap_i.job_priority[rows_i], snap_f.job_priority)
    np.testing.assert_array_equal(snap_i.queue_allocated, snap_f.queue_allocated)
    np.testing.assert_array_equal(snap_i.queue_demand, snap_f.queue_demand)
    np.testing.assert_array_equal(snap_i.allocatable, snap_f.allocatable)
    # Node identity of bound jobs.
    for k, r in zip(range(len(ids_f)), rows_i):
        nf = snap_f.job_node[k]
        ni = snap_i.job_node[r]
        if nf >= 0 or ni >= 0:
            assert snap_i.node_ids[ni] == snap_f.node_ids[nf]
    # Relative within-queue order among live jobs must match.
    of = np.argsort(snap_f.job_order)
    oi = np.argsort(snap_i.job_order[rows_i])
    seq_f = [ids_f[int(j)] for j in of]
    seq_i = [ids_f[int(j)] for j in oi]
    assert seq_f == seq_i


def test_lifecycle_differential():
    cfg = make_config()
    nodes = make_nodes(8)
    running = [
        RunningJob(job=job(900 + i, cpu=4), node_id=f"node-{i:03d}",
                   scheduled_at_priority=1000, leased_ts=float(i))
        for i in range(2)
    ]
    queued = [job(i, queue="q-a" if i % 2 else "q-b", cpu=1 + i % 3,
                  sel={"zone": "z0"} if i % 5 == 0 else None,
                  tol=i % 7 == 0) for i in range(40)]
    inc = IncrementalRound(cfg, "default", nodes, QUEUES, running, queued)
    mirror = Mirror(cfg, nodes, running, queued)
    assert_same_decisions(inc, mirror)

    # Cycle 1: submit more work, including a gang that stays incomplete.
    gang = Gang(id="g1", cardinality=3)
    new1 = [job(100 + i, cpu=2, gang=gang) for i in range(2)]
    new1 += [job(120 + i, queue="q-b", cpu=1, prio=-1) for i in range(5)]
    inc.add_jobs(new1)
    mirror.add(new1)
    assert_same_decisions(inc, mirror)

    # Cycle 2: the gang completes; bind a few of last round's decisions.
    new2 = [job(102, cpu=2, gang=gang)]
    inc.add_jobs(new2)
    mirror.add(new2)
    snap = inc.snapshot()
    dev = inc.device_round()
    sched, _, _ = solve_ids(snap, dev)
    leases = [
        (jid, nid, prio, 50.0) for jid, (nid, prio) in sorted(sched.items())[:6]
    ]
    inc.bind(leases)
    mirror.bind(leases)
    assert_same_decisions(inc, mirror)

    # Cycle 3: some running jobs finish, some queued are cancelled.
    done = [leases[0][0], leases[1][0], "job-0003", "job-0010"]
    inc.remove_jobs(done)
    mirror.remove(done)
    assert_same_decisions(inc, mirror)

    # Cycle 4: a running job is preempted back to queued.
    back = [leases[2][0]]
    inc.unbind(back)
    mirror.unbind(back)
    assert_same_decisions(inc, mirror)

    # Cycle 5: row reuse — new submits land in tombstoned rows.
    new3 = [job(200 + i, queue="q-b", cpu=3) for i in range(6)]
    inc.add_jobs(new3)
    mirror.add(new3)
    assert_same_decisions(inc, mirror)


def test_market_lifecycle():
    cfg = make_config(market_driven=True)
    nodes = make_nodes(4)
    queued = [
        JobSpec(
            id=f"bid-{i:03d}",
            queue="q-a" if i % 2 else "q-b",
            priority_class="low",
            requests={"cpu": "2", "memory": "4Gi"},
            submitted_ts=float(i),
            bid_prices={"default": {"queued": 1.0 + i * 0.25, "running": 2.0 + i * 0.25}},
        )
        for i in range(12)
    ]
    inc = IncrementalRound(cfg, "default", nodes, QUEUES, [], queued)
    mirror = Mirror(cfg, nodes, [], queued)
    assert_same_decisions(inc, mirror)

    snap = inc.snapshot()
    sched, _, _ = solve_ids(snap, inc.device_round())
    leases = [(jid, nid, p, 9.0) for jid, (nid, p) in sorted(sched.items())[:3]]
    inc.bind(leases)
    mirror.bind(leases)
    assert_same_decisions(inc, mirror)

    # Market unbind restores the queued-phase bid.
    inc.unbind([leases[0][0]])
    mirror.unbind([leases[0][0]])
    assert_same_decisions(inc, mirror)


def test_vocab_miss_raises():
    cfg = make_config()
    nodes = make_nodes(4)
    queued = [job(i) for i in range(4)]
    inc = IncrementalRound(cfg, "default", nodes, QUEUES, [], queued)
    # "disk" exists on nodes but was never referenced -> not interned.
    with pytest.raises(SnapshotRebuildRequired):
        inc.add_jobs([job(50, sel={"disk": "ssd"})])
    # Unknown queue.
    with pytest.raises(SnapshotRebuildRequired):
        inc.add_jobs([JobSpec(id="x", queue="nope", requests={"cpu": "1"})])
    # A selector on a key no node carries is NOT a rebuild (impossible job).
    inc.add_jobs([job(51, sel={"ghost": "v"})])
    snap = inc.snapshot()
    assert not snap.job_possible[inc._id_to_row["job-0051"]]


def test_failed_batch_leaves_state_untouched():
    cfg = make_config()
    nodes = make_nodes(2)
    queued = [job(i) for i in range(4)]
    inc = IncrementalRound(cfg, "default", nodes, QUEUES, [], queued)
    size0, free0, gen0 = inc._size, list(inc._free), inc._gen
    # Duplicate ids WITHIN one batch must raise, not leak a ghost row.
    dup = [job(50), job(50)]
    with pytest.raises(SnapshotRebuildRequired):
        inc.add_jobs(dup)
    assert (inc._size, inc._free, inc._gen) == (size0, free0, gen0)
    assert "job-0050" not in inc._id_to_row
    # A malformed quantity raises before any mutation.
    bad = JobSpec(id="bad", queue="q-a", requests={"memory": "4GiBB"})
    with pytest.raises(Exception):
        inc.add_jobs([job(51), bad])
    assert (inc._size, inc._free, inc._gen) == (size0, free0, gen0)
    assert "job-0051" not in inc._id_to_row
    # State still fully functional.
    mirror = Mirror(cfg, nodes, [], queued)
    assert_same_decisions(inc, mirror)


def test_key_group_compaction():
    cfg = make_config()
    nodes = make_nodes(2)
    inc = IncrementalRound(cfg, "default", nodes, QUEUES, [], [job(0)])
    mirror = Mirror(cfg, nodes, [], [job(0)])
    # Churn 1500 distinct request shapes through the state; without
    # compaction num_key_groups would exceed 1500.
    for wave in range(3):
        batch = [
            JobSpec(
                id=f"w{wave}-{i}",
                queue="q-a",
                requests={"cpu": "1", "memory": f"{1000 + wave * 500 + i}Ki"},
                submitted_ts=float(i),
            )
            for i in range(500)
        ]
        inc.add_jobs(batch)
        mirror.add(batch)
        ids = [j.id for j in batch[:400]]
        inc.remove_jobs(ids)
        mirror.remove(ids)
    assert inc._num_key_groups < 1500
    assert_same_decisions(inc, mirror)


def test_grow_past_capacity():
    cfg = make_config()
    nodes = make_nodes(2)
    queued = [job(i) for i in range(3)]
    inc = IncrementalRound(cfg, "default", nodes, QUEUES, [], queued)
    mirror = Mirror(cfg, nodes, [], queued)
    big = [job(1000 + i, cpu=1) for i in range(2000)]
    inc.add_jobs(big)
    mirror.add(big)
    assert inc._cap >= 2003
    assert_same_decisions(inc, mirror)
