"""Pod-issue machinery + utilisation reporting
(executor/podchecks/, executor/service/pod_issue_handler.go,
executor/utilisation/)."""

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.jobdb import JobState
from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
from armada_tpu.services.podchecks import (
    Action,
    ContainerStateCheck,
    EventCheck,
    PodChecker,
    PodChecksConfig,
    PodIssueHandler,
)
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService
from armada_tpu.services.utilisation import ALL_PRIORITIES, node_reports


def test_event_check_grace_and_action():
    checker = PodChecker(
        PodChecksConfig(
            events=(
                EventCheck(
                    regexp="ImagePullBackOff",
                    event_type="Warning",
                    grace_period_s=60.0,
                    action=Action.FAIL,
                ),
            )
        )
    )
    pod = {
        "phase": "pending",
        "created": 0.0,
        "last_change": 0.0,
        "node": "n0",
        "events": [{"type": "Warning", "message": "Back-off: ImagePullBackOff"}],
    }
    assert checker.get_action(pod, 30.0)[0] == Action.WAIT  # inside grace
    assert checker.get_action(pod, 61.0)[0] == Action.FAIL


def test_event_check_inverse_and_type():
    checker = PodChecker(
        PodChecksConfig(
            events=(
                EventCheck(
                    regexp="Scheduled",
                    event_type="Normal",
                    inverse=True,  # any Normal event NOT matching
                    grace_period_s=0.0,
                    action=Action.RETRY,
                ),
            )
        )
    )
    scheduled = {
        "phase": "pending", "created": 0.0, "last_change": 0.0, "node": "n0",
        "events": [{"type": "Normal", "message": "Scheduled on node"}],
    }
    other = {
        "phase": "pending", "created": 0.0, "last_change": 0.0, "node": "n0",
        "events": [{"type": "Normal", "message": "something odd"}],
    }
    warning = {
        "phase": "pending", "created": 0.0, "last_change": 0.0, "node": "n0",
        "events": [{"type": "Warning", "message": "something odd"}],
    }
    assert checker.get_action(scheduled, 1.0)[0] == Action.WAIT
    assert checker.get_action(other, 1.0)[0] == Action.RETRY
    assert checker.get_action(warning, 1.0)[0] == Action.WAIT  # type gate


def test_container_state_check():
    checker = PodChecker(
        PodChecksConfig(
            container_statuses=(
                ContainerStateCheck(
                    state="waiting",
                    reason_regexp="CreateContainerConfigError",
                    action=Action.FAIL,
                ),
            )
        )
    )
    pod = {
        "phase": "pending", "created": 0.0, "last_change": 0.0, "node": "n0",
        "containers": [{"state": "waiting", "reason": "CreateContainerConfigError"}],
    }
    assert checker.get_action(pod, 1.0)[0] == Action.FAIL


def test_node_assignment_and_no_update_deadlines():
    checker = PodChecker(
        PodChecksConfig(
            deadline_for_node_assignment_s=100.0, deadline_for_updates_s=200.0
        )
    )
    unassigned = {"phase": "pending", "created": 0.0, "last_change": 0.0, "node": ""}
    assert checker.get_action(unassigned, 50.0)[0] == Action.WAIT
    assert checker.get_action(unassigned, 150.0)[0] == Action.RETRY
    silent = {"phase": "pending", "created": 0.0, "last_change": 0.0, "node": "n0"}
    assert checker.get_action(silent, 150.0)[0] == Action.WAIT
    assert checker.get_action(silent, 250.0)[0] == Action.RETRY


def test_stuck_terminating_expiry():
    handler = PodIssueHandler(
        PodChecker(PodChecksConfig(stuck_terminating_expiry_s=10.0))
    )
    pods = {"r1": {"phase": "running", "created": 0.0, "node": "n0"}}
    handler.note_kill("r1", 100.0)
    assert handler.examine(pods, 105.0) == []  # inside grace
    issues = handler.examine(pods, 111.0)
    assert len(issues) == 1 and issues[0].get("force_delete")


def _stack(issue_for, checker=None):
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
        max_retries=2,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    executor = FakeExecutor(
        "c", log, sched,
        nodes=make_nodes("c", count=2, cpu="8", memory="32Gi"),
        runtime_for=lambda j: 1e9,
        pod_checker=checker,
        issue_for=issue_for,
    )
    return sched, submit, executor


def test_fatal_pod_issue_fails_job_end_to_end():
    checker = PodChecker(
        PodChecksConfig(
            events=(
                EventCheck(
                    regexp="InvalidImageName",
                    event_type="Warning",
                    grace_period_s=0.0,
                    action=Action.FAIL,
                ),
            )
        )
    )
    sched, submit, executor = _stack(
        issue_for=lambda job_id: {
            "blocked": True,
            "events": [{"type": "Warning", "message": "InvalidImageName: x"}],
        },
        checker=checker,
    )
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "s",
        [JobSpec(id="j0", queue="", requests={"cpu": "1", "memory": "1Gi"})],
        now=0.0,
    )
    executor.tick(0.0)
    sched.cycle(now=1.0)
    executor.tick(2.0)   # lease picked up; issue pod created
    executor.tick(3.0)   # issue actioned -> fatal run error reported
    sched.cycle(now=4.0)  # scheduler fails the job (retryable=False)
    job = sched.jobdb.read_txn().get("j0")
    assert job.state == JobState.FAILED, job.state
    assert "pod issue" in job.error


def test_retryable_pod_issue_requeues_job():
    checker = PodChecker(
        PodChecksConfig(
            events=(
                EventCheck(
                    regexp="Insufficient",
                    event_type="Warning",
                    grace_period_s=0.0,
                    action=Action.RETRY,
                ),
            )
        )
    )
    fail_once = {"done": False}

    def issue_for(job_id):
        if fail_once["done"]:
            return None
        fail_once["done"] = True
        return {
            "blocked": True,
            "events": [{"type": "Warning", "message": "Insufficient cpu"}],
        }

    sched, submit, executor = _stack(issue_for=issue_for, checker=checker)
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "s",
        [JobSpec(id="j0", queue="", requests={"cpu": "1", "memory": "1Gi"})],
        now=0.0,
    )
    executor.tick(0.0)
    sched.cycle(now=1.0)
    executor.tick(2.0)
    executor.tick(3.0)   # retryable issue reported
    sched.cycle(now=4.0)  # requeue
    sched.cycle(now=5.0)  # reschedule
    executor.tick(6.0)   # healthy pod this time
    executor.tick(7.0)
    job = sched.jobdb.read_txn().get("j0")
    assert job.state in (JobState.LEASED, JobState.PENDING, JobState.RUNNING)
    assert job.num_attempts == 2


def test_utilisation_node_reports():
    nodes = [{"id": "n0", "total_resources": {"cpu": "8", "memory": "32Gi"}}]
    reports = node_reports(
        nodes,
        {"n0": {"cpu": "2", "memory": "4Gi"}},
        {"n0": {"cpu": "1", "memory": "2Gi"}},
    )
    assert reports[0]["usage"]["cpu"] == "3"
    assert reports[0]["unallocatable_by_priority"][ALL_PRIORITIES]["cpu"] == "1"


def test_non_framework_usage_shrinks_allocatable_end_to_end():
    """A node sharing capacity with foreign pods must not be over-scheduled
    (cluster_utilisation.go allocatable computation)."""
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    executor = FakeExecutor(
        "c", log, sched,
        nodes=make_nodes("c", count=1, cpu="8", memory="32Gi"),
        runtime_for=lambda j: 1e9,
        non_framework_usage={"c-node-00000": {"cpu": "6", "memory": "24Gi"}},
    )
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "s",
        [
            JobSpec(id=f"j{i}", queue="", requests={"cpu": "2", "memory": "2Gi"})
            for i in range(4)
        ],
        now=0.0,
    )
    executor.tick(0.0)
    sched.cycle(now=1.0)
    txn = sched.jobdb.read_txn()
    leased = [j for j in txn.all_jobs() if j.state == JobState.LEASED]
    # Only 2 of 8 cpus remain after the foreign 6-cpu slice: one 2-cpu job.
    assert len(leased) == 1, [j.id for j in leased]
