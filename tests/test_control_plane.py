"""End-to-end control-plane tests: submit -> event log -> ingester -> jobdb
-> scheduler cycle -> leases -> fake executor -> completion. The hermetic
full-stack loop the reference gets from `mage dev:up fake-executor`."""

import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.jobdb import JobState
from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmissionError, SubmitService


def mk_stack(n_nodes=4, backend="oracle", **cfg_kw):
    config = SchedulingConfig(
        priority_classes={
            "default": PriorityClass("default", 1000, preemptible=True),
        },
        default_priority_class="default",
        **cfg_kw,
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend=backend)
    submit = SubmitService(config, log, scheduler=sched)
    executor = FakeExecutor(
        "cluster-a",
        log,
        sched,
        nodes=make_nodes("cluster-a", count=n_nodes, cpu="16", memory="64Gi"),
        runtime_for=lambda job_id: 10.0,
    )
    return config, log, sched, submit, executor


def job(i, cpu="2", mem="4Gi", **kw):
    return JobSpec(
        id=f"job-{i:04d}", queue="", requests={"cpu": cpu, "memory": mem}, **kw
    )


def test_submit_validation():
    _, _, _, submit, _ = mk_stack()
    submit.create_queue(QueueSpec("team"))
    with pytest.raises(SubmissionError):
        submit.submit("ghost-queue", "set1", [job(0)])
    with pytest.raises(SubmissionError):
        submit.submit("team", "set1", [job(1).with_(requests={})])
    with pytest.raises(SubmissionError):
        submit.submit("team", "set1", [job(2).with_(requests={"fancy/widget": "1"})])
    with pytest.raises(SubmissionError):
        submit.submit("team", "set1", [job(3).with_(priority_class="nope")])
    ids = submit.submit("team", "set1", [job(4)])
    assert ids == ["job-0004"]


def test_deduplication():
    _, _, sched, submit, _ = mk_stack()
    submit.create_queue(QueueSpec("team"))
    j = job(0, annotations={"armadaproject.io/deduplication-id": "once"})
    ids1 = submit.submit("team", "set1", [j])
    ids2 = submit.submit("team", "set1", [job(1, annotations={"armadaproject.io/deduplication-id": "once"})])
    assert ids1 == ids2
    sched.ingester.sync()
    assert len(sched.jobdb) == 1


def test_full_lifecycle():
    config, log, sched, submit, executor = mk_stack()
    submit.create_queue(QueueSpec("team"))
    submit.submit("team", "set1", [job(i) for i in range(8)], now=0.0)

    executor.tick(0.0)  # heartbeat so the scheduler knows the cluster
    sched.cycle(now=1.0)
    txn = sched.jobdb.read_txn()
    leased = [j for j in txn.all_jobs() if j.state == JobState.LEASED]
    assert len(leased) == 8
    assert all(j.latest_run.executor == "cluster-a" for j in leased)

    executor.tick(2.0)  # accepts leases, reports running
    sched.ingester.sync()
    txn = sched.jobdb.read_txn()
    assert all(j.state == JobState.RUNNING for j in txn.all_jobs())

    executor.tick(13.0)  # runtime 10s elapsed -> succeeded
    sched.ingester.sync()
    txn = sched.jobdb.read_txn()
    assert all(j.state == JobState.SUCCEEDED for j in txn.all_jobs())


def test_capacity_backlog_drains():
    config, log, sched, submit, executor = mk_stack(n_nodes=1)
    submit.create_queue(QueueSpec("team"))
    # 1 node x 16 cpu; 16 jobs x 4 cpu -> 4 at a time
    submit.submit("team", "set1", [job(i, cpu="4") for i in range(16)], now=0.0)
    t = 0.0
    done = 0
    for step in range(40):
        t += 5.0
        executor.tick(t)
        sched.cycle(now=t)
        txn = sched.jobdb.read_txn()
        done = sum(1 for j in txn.all_jobs() if j.state == JobState.SUCCEEDED)
        if done == 16:
            break
    assert done == 16, f"only {done} finished"


def test_cancel_job():
    config, log, sched, submit, executor = mk_stack()
    submit.create_queue(QueueSpec("team"))
    (jid,) = submit.submit("team", "set1", [job(0)], now=0.0)
    submit.cancel_job("team", "set1", jid)
    sched.ingester.sync()
    assert sched.jobdb.get(jid).state == JobState.CANCELLED
    # cancelled jobs never schedule
    executor.tick(1.0)
    sched.cycle(now=1.0)
    assert sched.jobdb.get(jid).state == JobState.CANCELLED


def test_reprioritise_changes_order():
    config, log, sched, submit, executor = mk_stack(n_nodes=1)
    submit.create_queue(QueueSpec("team"))
    ids = submit.submit("team", "set1", [job(i, cpu="16") for i in range(3)], now=0.0)
    submit.reprioritise_job("team", "set1", ids[2], -10)
    executor.tick(1.0)
    sched.cycle(now=1.0)
    txn = sched.jobdb.read_txn()
    # only one fits; the reprioritised job wins
    assert txn.get(ids[2]).state == JobState.LEASED
    assert txn.get(ids[0]).state == JobState.QUEUED


def test_executor_timeout_requeues():
    config, log, sched, submit, executor = mk_stack()
    submit.create_queue(QueueSpec("team"))
    (jid,) = submit.submit("team", "set1", [job(0)], now=0.0)
    executor.tick(0.0)
    sched.cycle(now=1.0)
    assert sched.jobdb.get(jid).state == JobState.LEASED
    # executor goes silent; timeout default 600s
    sched.cycle(now=700.0)
    j = sched.jobdb.get(jid)
    assert j.state == JobState.QUEUED
    assert j.num_attempts == 1


def test_gang_schedules_atomically_e2e():
    config, log, sched, submit, executor = mk_stack(n_nodes=4)
    submit.create_queue(QueueSpec("team"))
    gang = Gang(id="g1", cardinality=4)
    submit.submit(
        "team", "set1", [job(i, cpu="16", gang=gang) for i in range(4)], now=0.0
    )
    executor.tick(0.0)
    sched.cycle(now=1.0)
    txn = sched.jobdb.read_txn()
    states = {j.id: j.state for j in txn.all_jobs()}
    assert all(s == JobState.LEASED for s in states.values())
    # each on its own node (16 cpu each, nodes are 16 cpu)
    nodes = {j.latest_run.node_id for j in txn.all_jobs()}
    assert len(nodes) == 4


def test_multi_pool_scheduling():
    """Two executor pools; jobs schedule only onto their selector-matched
    pool, and each pool runs its own round (scheduling_algo.go:147-188)."""
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    cpu_exec = FakeExecutor(
        "cpu-cluster", log, sched,
        nodes=make_nodes("cpu-cluster", count=2, cpu="16", memory="64Gi",
                         labels={"kind": "cpu"}, pool="cpu-pool"),
        pool="cpu-pool",
    )
    gpu_exec = FakeExecutor(
        "gpu-cluster", log, sched,
        nodes=make_nodes("gpu-cluster", count=2, cpu="16", memory="64Gi",
                         labels={"kind": "gpu"}, pool="gpu-pool"),
        pool="gpu-pool",
    )
    submit.create_queue(QueueSpec("team"))
    submit.submit(
        "team", "s",
        [job(0, node_selector={"kind": "gpu"}), job(1, node_selector={"kind": "cpu"})],
        now=0.0,
    )
    cpu_exec.tick(0.0)
    gpu_exec.tick(0.0)
    sched.cycle(now=1.0)
    txn = sched.jobdb.read_txn()
    j0, j1 = txn.get("job-0000"), txn.get("job-0001")
    assert j0.latest_run.executor == "gpu-cluster"
    assert j0.latest_run.pool == "gpu-pool"
    assert j1.latest_run.executor == "cpu-cluster"
    assert j1.latest_run.pool == "cpu-pool"


def test_cancel_jobset():
    config, log, sched, submit, executor = mk_stack()
    submit.create_queue(QueueSpec("team"))
    submit.submit("team", "set1", [job(i) for i in range(3)], now=0.0)
    submit.submit("team", "set2", [job(10)], now=0.0)
    submit.cancel_jobset("team", "set1")
    sched.ingester.sync()
    txn = sched.jobdb.read_txn()
    assert sum(1 for j in txn.all_jobs() if j.state == JobState.CANCELLED) == 3
    assert txn.get("job-0010").state == JobState.QUEUED


def test_executor_cordon_diverts_placement():
    """Cordoning a whole executor removes its nodes from rounds; jobs go to
    the other cluster; uncordon restores it (executor settings cordon,
    scheduling_algo.go executor filters)."""
    config, log, sched, submit, ex_a = mk_stack(n_nodes=2)
    ex_b = FakeExecutor(
        "cluster-b", log, sched,
        nodes=make_nodes("cluster-b", count=2, cpu="16", memory="64Gi"),
        runtime_for=lambda job_id: 10.0,
    )
    submit.create_queue(QueueSpec("q"))
    sched.set_executor_cordon("cluster-a", True)
    t = 0.0
    submit.submit("q", "s", [job(i) for i in range(4)], now=t)
    for _ in range(3):
        t += 1.0
        ex_a.tick(t)
        ex_b.tick(t)
        sched.cycle(now=t)
    txn = sched.jobdb.read_txn()
    placed = [j.latest_run.executor for j in txn.all_jobs() if j.latest_run]
    assert placed and all(e == "cluster-b" for e in placed)
    # uncordon: new work can land on cluster-a again
    sched.set_executor_cordon("cluster-a", False)
    submit.submit("q", "s2", [job(100 + i, cpu="14") for i in range(4)], now=t)
    for _ in range(3):
        t += 1.0
        ex_a.tick(t)
        ex_b.tick(t)
        sched.cycle(now=t)
    txn = sched.jobdb.read_txn()
    placed = {j.latest_run.executor for j in txn.all_jobs() if j.latest_run}
    assert "cluster-a" in placed


def test_lagging_executor_skipped():
    """An executor sitting on too many unacknowledged leases is excluded
    from new rounds until it acks (maxUnacknowledgedJobsPerExecutor,
    scheduling_algo.go:1049-1066)."""
    config, log, sched, submit, ex_a = mk_stack(
        n_nodes=2, max_unacknowledged_jobs_per_executor=2
    )
    submit.create_queue(QueueSpec("q"))
    t = 1.0
    ex_a.tick(t)  # heartbeat so nodes register
    submit.submit("q", "s", [job(i, cpu="1", mem="1Gi") for i in range(6)], now=t)
    # cycle WITHOUT executor ticks: leases pile up unacknowledged
    sched.cycle(now=t)
    txn = sched.jobdb.read_txn()
    leased = [j for j in txn.all_jobs() if j.state == JobState.LEASED]
    assert len(leased) == 6
    # more work arrives; the lagging executor must be skipped entirely
    submit.submit("q", "s2", [job(10 + i, cpu="1", mem="1Gi") for i in range(2)], now=t + 1)
    sched.cycle(now=t + 1)
    txn = sched.jobdb.read_txn()
    still_queued = [j for j in txn.all_jobs() if j.state == JobState.QUEUED]
    assert len(still_queued) == 2
    # the executor acks (ticks): leases progress, next round can place again
    t += 2.0
    ex_a.tick(t)
    sched.cycle(now=t)
    txn = sched.jobdb.read_txn()
    assert all(j.state != JobState.QUEUED for j in txn.all_jobs())


def test_metrics_rendered():
    """Headline prometheus metrics are populated by a cycle, including the
    skipped-executors gauge (metrics.go / cycle_metrics.go families)."""
    from armada_tpu.services.metrics import SchedulerMetrics

    config, log, sched, submit, ex = mk_stack(n_nodes=2)
    metrics = SchedulerMetrics()
    if metrics.registry is None:
        return  # prometheus_client unavailable
    sched.attach_metrics(metrics)
    submit.create_queue(QueueSpec("q"))
    # a cordon on an unregistered executor must NOT count as skipped
    sched.set_executor_cordon("ghost-exec", True)
    sched.set_executor_cordon("cluster-a", True)
    t = 1.0
    ex.tick(t)
    submit.submit("q", "s", [job(i) for i in range(3)], now=t)
    sched.cycle(now=t)
    text = metrics.render().decode()
    assert "scheduler_skipped_executors 1.0" in text
    sched.set_executor_cordon("cluster-a", False)
    t += 1.0
    ex.tick(t)
    sched.cycle(now=t)
    text = metrics.render().decode()
    assert "scheduler_skipped_executors 0.0" in text
    assert 'scheduler_queue_fair_share{pool="default",queue="q"}' in text
    assert 'scheduler_jobs_scheduled_total{pool="default",queue="q"} 3.0' in text
    assert 'scheduler_solve_seconds_count{pool="default"}' in text


def test_gang_contexts_in_reports():
    """Gang-level scheduling context (context/gang.go detail): the round
    report carries per-gang all-or-nothing outcomes, surfaced in the
    scheduling and queue report strings."""
    from armada_tpu.core.config import PriorityClass, SchedulingConfig
    from armada_tpu.core.types import Gang, JobSpec, QueueSpec
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService

    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    FakeExecutor("c", log, sched,
                 nodes=make_nodes("c", count=2, cpu="8", memory="32Gi"),
                 runtime_for=lambda j: 100.0).tick(0.0)
    submit.create_queue(QueueSpec("gq"))
    fits = Gang(id="fits", cardinality=2)
    too_big = Gang(id="too-big", cardinality=2)
    submit.submit(
        "gq", "s1",
        [JobSpec(id=f"a{i}", queue="", gang=fits,
                 requests={"cpu": "2", "memory": "2Gi"}) for i in range(2)]
        + [JobSpec(id=f"b{i}", queue="", gang=too_big,
                   requests={"cpu": "7", "memory": "2Gi"}) for i in range(2)],
        now=0.0,
    )
    sched.cycle(now=1.0)
    rep = sched.reports.latest_reports()["default"]
    assert rep.gang_contexts[("gq", "fits")].startswith("scheduled 2/2")
    # 7-cpu x2 on two 8-cpu nodes with the 2-cpu gang placed: second
    # member can't fit -> all-or-nothing failure.
    assert rep.gang_contexts[("gq", "too-big")].startswith("not scheduled")
    assert "gang fits" in sched.reports.queue_report("gq")
    assert "gang too-big" in sched.reports.scheduling_report()


def test_incremental_cycle_respects_pool_restriction():
    """Delta-applied submits honor JobSpec.pools eligibility exactly like
    the full rebuild (incremental snapshot path, single-pool kernel)."""
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="kernel",
                             snapshot_mode="incremental")
    submit = SubmitService(config, log, scheduler=sched)
    executor = FakeExecutor(
        "c1", log, sched,
        nodes=make_nodes("c1", count=2, cpu="8", memory="32Gi"),
        runtime_for=lambda job_id: 100.0,
    )
    submit.create_queue(QueueSpec("q"))
    submit.submit("q", "s", [job(0)], now=0.0)
    executor.tick(0.0)
    sched.cycle(now=1.0)  # builds the incremental state
    assert sched.jobdb.read_txn().get("job-0000").latest_run is not None
    # Now a delta-applied submit restricted to another pool: must NOT be
    # leased here, exactly like the rebuild path would filter it.
    submit.submit("q", "s", [job(1, pools=("gpu-pool",)), job(2)], now=2.0)
    executor.tick(2.0)
    sched.cycle(now=3.0)
    txn = sched.jobdb.read_txn()
    assert txn.get("job-0002").latest_run is not None  # eligible: leased
    assert txn.get("job-0001").latest_run is None  # restricted: untouched
    assert txn.get("job-0001").state == JobState.QUEUED
