"""Differential simulation: the same workload simulated with the oracle
backend and the kernel backend must produce identical fleet histories
(states AND placements). This is the whole-system analogue of the per-round
parity suite — any drift in eviction, ordering, binding or event derivation
shows up here."""

import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.sim import (
    ClusterSpec,
    JobTemplate,
    QueueSpecSim,
    Simulator,
    WorkloadSpec,
)
from armada_tpu.sim.simulator import NodeTemplate, ShiftedExponential

CFG = SchedulingConfig(
    priority_classes={
        "high": PriorityClass("high", 30000, preemptible=False),
        "low": PriorityClass("low", 1000, preemptible=True),
    },
    default_priority_class="low",
    protected_fraction_of_fair_share=0.5,
)


def run(backend, seed, mesh=None, snapshot_mode="auto"):
    sim = Simulator(
        [
            ClusterSpec(
                "c1",
                node_templates=(
                    NodeTemplate(count=6, cpu="16", memory="64Gi",
                                 labels={"zone": "a"}),
                    NodeTemplate(count=4, cpu="32", memory="128Gi",
                                 labels={"zone": "b"}),
                ),
            )
        ],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "steady",
                    job_templates=(
                        JobTemplate(id="long", number=40, cpu="2", memory="4Gi",
                                    runtime=ShiftedExponential(minimum=300.0)),
                    ),
                ),
                QueueSpecSim(
                    "bursty",
                    priority_factor=2.0,
                    job_templates=(
                        JobTemplate(id="gangs", number=24, cpu="4", memory="4Gi",
                                    gang_cardinality=8, submit_time=50.0,
                                    runtime=ShiftedExponential(minimum=120.0)),
                        JobTemplate(id="urgent", number=10, cpu="2", memory="2Gi",
                                    priority_class="high", submit_time=100.0,
                                    runtime=ShiftedExponential(minimum=60.0)),
                    ),
                ),
                QueueSpecSim(
                    "zoned",
                    job_templates=(
                        JobTemplate(id="pin", number=12, cpu="1", memory="1Gi",
                                    node_selector={"zone": "b"}, submit_time=30.0,
                                    runtime=ShiftedExponential(minimum=90.0,
                                                               tail_mean=30.0)),
                    ),
                ),
            )
        ),
        config=CFG,
        backend=backend,
        mesh=mesh,
        snapshot_mode=snapshot_mode,
        seed=seed,
        max_time=5000.0,
    )
    res = sim.run()
    return {
        "states": {k: v.value for k, v in res.events_by_job.items()},
        "placements": res.placements,
        "preemptions": res.preemptions,
        "finished": res.finished_jobs,
    }


@pytest.mark.parametrize(
    "seed", [0, pytest.param(7, marks=pytest.mark.slow)]
)
def test_full_simulation_differential(seed):
    oracle = run("oracle", seed)
    kernel = run("kernel", seed)
    assert oracle["finished"] == kernel["finished"]
    assert oracle["preemptions"] == kernel["preemptions"]
    assert oracle["states"] == kernel["states"]
    assert oracle["placements"] == kernel["placements"]
    # sanity: the scenario actually exercises the interesting paths
    assert oracle["finished"] >= 74


def test_full_simulation_differential_sharded():
    """The node-sharded product backend (SchedulerService mesh=...) must
    reproduce the single-device kernel history exactly — the whole-system
    analogue of the per-round shard parity suite."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 (virtual) devices")
    kernel = run("kernel", 0)
    sharded = run("kernel", 0, mesh=4)
    assert kernel["finished"] == sharded["finished"]
    assert kernel["preemptions"] == sharded["preemptions"]
    assert kernel["states"] == sharded["states"]
    assert kernel["placements"] == sharded["placements"]


@pytest.mark.slow
def test_full_simulation_differential_two_level_mesh():
    """The two-level (hosts, chips) backend (SchedulerService
    mesh="2x4", parallel/multihost.py) must reproduce the single-device
    kernel history exactly — the whole-system analogue of the per-round
    hierarchy parity suite (tests/test_multihost.py). Slow-marked: the
    per-round 2D parity signal is tier-1 there; this adds only the
    service-loop plumbing, at ~2min of virtual-device wall clock."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    kernel = run("kernel", 0)
    two_level = run("kernel", 0, mesh="2x4")
    assert kernel["finished"] == two_level["finished"]
    assert kernel["preemptions"] == two_level["preemptions"]
    assert kernel["states"] == two_level["states"]
    assert kernel["placements"] == two_level["placements"]


def test_full_simulation_differential_incremental_snapshots():
    """O(delta) incremental service cycles (jobdb changelog ->
    IncrementalRound) must reproduce the full-rebuild kernel history
    exactly — the whole-system proof for the serial-based delta sync."""
    rebuild = run("kernel", 0, snapshot_mode="rebuild")
    incremental = run("kernel", 0, snapshot_mode="incremental")
    assert rebuild["finished"] == incremental["finished"]
    assert rebuild["preemptions"] == incremental["preemptions"]
    assert rebuild["states"] == incremental["states"]
    assert rebuild["placements"] == incremental["placements"]
