"""Flight recorder: round-trace capture + deterministic replay
(armada_tpu/trace, tools/replay_gate.py).

A round recorded from a whole-sim service run must replay bit-exactly
— identical placements, evictions, fair shares, and pass-1 loop stream
— under the fused LOCAL kernel, the "2x4" HierarchicalDist mesh, and
hot-window compaction (the 2x4 and hot-window variants ride the slow
marker; LOCAL is tier-1). The recorder must not lose the mixed-fleet
fields (away pools, market bids, gang membership) the dryrun scenarios
exercise, a bundle recorded on a foreign target must refuse to replay,
and the replay gate must trip on a deliberately perturbed kernel while
passing HEAD.

Regenerate the committed fixture after a DeviceRound schema change:

    python tests/test_trace_replay.py --regen
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import (
    DeviceRound,
    pad_device_round,
    prep_device_round,
)
from armada_tpu.trace import (
    TraceRecorder,
    TraceTargetMismatch,
    check_target,
    load_trace,
    replay_trace,
)
from armada_tpu.trace.codec import decode_record, encode_record

REPO = os.path.join(os.path.dirname(__file__), "..")
FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "sim_steady.atrace")


def record_sim_trace(path, *, backend="kernel", max_rounds=None, max_time=1500.0):
    """A small whole-sim service run (the test_sim_differential pattern:
    steady queue + gang bursts on a shared fleet) with the flight
    recorder attached; returns the SimResult."""
    from armada_tpu.sim import (
        ClusterSpec,
        JobTemplate,
        QueueSpecSim,
        Simulator,
        WorkloadSpec,
    )
    from armada_tpu.sim.simulator import NodeTemplate, ShiftedExponential

    cfg = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
        batch_fill_window=2,
    )
    sim = Simulator(
        [
            ClusterSpec(
                "c1",
                node_templates=(NodeTemplate(count=6, cpu="16", memory="64Gi"),),
            )
        ],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "steady",
                    job_templates=(
                        JobTemplate(
                            id="long", number=24, cpu="2", memory="4Gi",
                            runtime=ShiftedExponential(minimum=200.0),
                        ),
                    ),
                ),
                QueueSpecSim(
                    "bursty",
                    job_templates=(
                        JobTemplate(
                            id="gangs", number=8, cpu="4", memory="4Gi",
                            gang_cardinality=4, submit_time=50.0,
                            runtime=ShiftedExponential(minimum=100.0),
                        ),
                    ),
                ),
            )
        ),
        config=cfg,
        backend=backend,
        seed=0,
        max_time=max_time,
        trace_path=path,
    )
    if max_rounds is not None:
        sim.trace_recorder.max_rounds = max_rounds
    return sim.run()


@pytest.fixture(scope="module")
def sim_trace(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("trace") / "sim.atrace")
    res = record_sim_trace(path)
    assert res.finished_jobs > 0
    return path


def test_fixture_replays_bit_exact_local():
    """Tier-1 smoke on the COMMITTED fixture bundle: bit-exact LOCAL
    replay of real recorded rounds. allow_foreign is sound here — the
    header pins x64 exact-cost mode, whose int64/float64 decisions are
    host-independent (check_target still refuses any x64 mismatch)."""
    assert os.path.getsize(FIXTURE) < 100_000, "fixture must stay tiny"
    trace = load_trace(FIXTURE)
    assert trace.header["target"]["x64"] is True
    assert trace.header["source"] == "sim"
    report = replay_trace(trace, solvers=("LOCAL",), allow_foreign=True)
    assert report["ok"], report["divergences"]
    assert report["rounds"] >= 2
    # Non-vacuous: the fixture carries a round that actually scheduled.
    scheduled = sum(
        int(np.asarray(r.decisions()["scheduled_mask"]).sum())
        for r in trace.rounds
    )
    assert scheduled > 0


def test_recorded_sim_rounds_replay_bit_exact_local(sim_trace):
    """Rounds recorded live from the service loop replay bit-exactly
    under the fused LOCAL kernel — placements, evictions, shares, AND
    the pass-1 loop stream (compare_round checks num_loops)."""
    trace = load_trace(sim_trace)
    assert len(trace.rounds) >= 5
    assert trace.header["seeds"] == {"workload_seed": 0}
    assert trace.header["config_fingerprint"]
    report = replay_trace(trace, solvers=("LOCAL",))
    assert report["ok"], report["divergences"]
    assert report["rounds"] == len(trace.rounds)


@pytest.mark.slow
def test_recorded_sim_rounds_replay_two_level_mesh(sim_trace):
    """The same recorded rounds re-solved on the 2x4 HierarchicalDist
    mesh must match the recorded decision stream bit-for-bit."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    trace = load_trace(sim_trace)
    report = replay_trace(trace, solvers=("2x4",), max_rounds=4)
    assert report["ok"], report["divergences"]
    assert report["rounds"] == 4


@pytest.mark.slow
def test_recorded_sim_rounds_replay_hot_window(sim_trace):
    """Hot-window compaction on vs off over recorded rounds: both must
    reproduce the recorded decisions and loop stream."""
    trace = load_trace(sim_trace)
    report = replay_trace(trace, solvers=("hotwindow:2", "LOCAL"), max_rounds=4)
    assert report["ok"], report["divergences"]


def test_oracle_recorded_trace_replays_on_kernel(tmp_path):
    """Record once from an ORACLE-backed service, replay on the kernel:
    the bundle's DeviceRound is the same device prep, so the kernel's
    decisions must match the oracle's (the parity contract, now via the
    trace seam; oracle spot/loop accounting is skipped by the compare)."""
    path = str(tmp_path / "oracle.atrace")
    record_sim_trace(path, backend="oracle", max_rounds=6, max_time=400.0)
    trace = load_trace(path)
    assert trace.rounds and trace.rounds[0].backend == "oracle"
    report = replay_trace(trace, solvers=("LOCAL",))
    assert report["ok"], report["divergences"]


def test_mixed_fleet_fields_round_trip(tmp_path):
    """Away/market pools and gang membership survive a recorded trace:
    every DeviceRound field decodes bit-identical for the dryrun
    scenario set (home/away borrowed tainted nodes, market bids, mixed
    2/4/8 gangs), and the decoded round re-solves bit-exactly."""
    from armada_tpu.parallel.scenarios import mixed_fleet_rounds

    for label, snap in mixed_fleet_rounds(24, 96):
        snap = dataclasses.replace(
            snap, config=dataclasses.replace(snap.config, batch_fill_window=4)
        )
        dev = pad_device_round(prep_device_round(snap))
        out = solve_round(dev)
        path = str(tmp_path / f"{label}.atrace")
        with TraceRecorder(path, source="test", config=snap.config) as rec:
            rec.record_round(
                pool=snap.pool, dev=dev, decisions=out,
                num_jobs=snap.num_jobs, num_queues=snap.num_queues,
                config=snap.config, solver={"backend": "kernel"},
                ids={"jobs": list(snap.job_ids)},
            )
        trace = load_trace(path)
        dev2 = trace.rounds[0].device_round()
        for f in dataclasses.fields(DeviceRound):
            a, b = getattr(dev, f.name), getattr(dev2, f.name)
            if isinstance(a, tuple) or not hasattr(a, "shape"):
                assert a == b, f"{label}: {f.name} changed type/value"
            else:
                a, b = np.asarray(a), np.asarray(b)
                assert a.dtype == b.dtype, f"{label}: {f.name} dtype drifted"
                assert np.array_equal(a, b), f"{label}: {f.name} not bit-exact"
        # The mixed-fleet signal is actually present in the bundle.
        if label == "home_away":
            assert bool(dev2.has_away)
            assert np.asarray(dev2.pc_away_count).any(), "away tables lost"
            assert np.asarray(dev2.node_taints).any(), "borrowed gpu taints lost"
            assert (np.asarray(dev2.slot_count) > 1).any(), "gangs lost"
        if label == "market":
            assert bool(dev2.market_driven)
            assert np.asarray(dev2.job_bid).any(), "market bids lost"
        report = replay_trace(trace, solvers=("LOCAL",))
        assert report["ok"], (label, report["divergences"])


def test_foreign_target_refuses_with_clear_error(tmp_path, sim_trace):
    """A bundle whose target signature names a different host must
    refuse to replay (stale-compiled decisions), and an x64-mode
    mismatch must refuse even with allow_foreign."""
    trace = load_trace(sim_trace)
    foreign = dict(trace.header)
    foreign["target"] = dict(foreign["target"], host_cpu="feedface00000000")
    with pytest.raises(TraceTargetMismatch, match="different host"):
        check_target(foreign)
    check_target(foreign, allow_foreign=True)  # explicit override works
    wrong_mode = dict(trace.header)
    wrong_mode["target"] = dict(wrong_mode["target"], x64=False)
    with pytest.raises(TraceTargetMismatch, match="x64"):
        check_target(wrong_mode, allow_foreign=True)
    # End to end through a tampered file: replay_trace refuses too.
    tampered = tmp_path / "foreign.atrace"
    with open(sim_trace) as f, open(tampered, "w") as out:
        for i, line in enumerate(f):
            record = decode_record(line)
            if i == 0:
                record["target"]["host_cpu"] = "feedface00000000"
            out.write(encode_record(record) + "\n")
    with pytest.raises(TraceTargetMismatch):
        replay_trace(load_trace(str(tampered)))


def test_second_recording_session_replaces_bundle(tmp_path, sim_trace):
    """A new recorder on an existing path REPLACES the bundle (one
    bundle = one session); a hand-concatenated multi-session file is
    refused rather than replayed under the first session's header."""
    from armada_tpu.trace import TraceFormatError

    rec0 = load_trace(sim_trace).rounds[0]
    path = tmp_path / "b.atrace"
    for _ in range(2):
        with TraceRecorder(str(path), source="test") as recorder:
            recorder.record_round(
                pool="default", dev=rec0.device_round(),
                decisions=rec0.decisions(), num_jobs=rec0.num_jobs,
                num_queues=rec0.num_queues,
            )
    assert len(load_trace(str(path)).rounds) == 1  # replaced, not merged
    doubled = tmp_path / "doubled.atrace"
    doubled.write_text(path.read_text() * 2)
    with pytest.raises(TraceFormatError, match="second header"):
        load_trace(str(doubled))


def test_truncated_rounds_are_skipped(sim_trace):
    """A budget-truncated decision stream is a wall-clock-dependent
    prefix, not a deterministic replay target: skipped, not compared."""
    trace = load_trace(sim_trace)
    for rec in trace.rounds:
        rec.raw["truncated"] = True
    report = replay_trace(trace, solvers=("LOCAL",))
    assert report["rounds"] == 0
    assert report["skipped"] == len(trace.rounds)


def test_replay_divergence_metrics_counter(sim_trace):
    """The replayer surfaces divergences through the scheduler metrics
    registry (scheduler_trace_replay_divergences by kind), and the
    recorder's capture counters moved during the sim recording."""
    from armada_tpu.services.metrics import HAVE_PROMETHEUS, SchedulerMetrics

    if not HAVE_PROMETHEUS:
        pytest.skip("prometheus_client unavailable")
    trace = load_trace(sim_trace)
    metrics = SchedulerMetrics()
    report = replay_trace(
        trace, solvers=("LOCAL",), max_rounds=2, perturb="tiebreak",
        metrics=metrics,
    )
    assert not report["ok"]
    rendered = metrics.render().decode()
    assert 'scheduler_trace_replay_divergences_total{kind="placement"}' in rendered
    # Capture counters: re-record one decoded round with metrics attached.
    rec0 = trace.rounds[0]
    with TraceRecorder(os.devnull, source="test") as recorder:
        recorder.record_round(
            pool="default", dev=rec0.device_round(),
            decisions=rec0.decisions(), num_jobs=rec0.num_jobs,
            num_queues=rec0.num_queues, metrics=metrics,
        )
    rendered = metrics.render().decode()
    assert 'scheduler_trace_rounds_recorded_total{pool="default"}' in rendered
    assert "scheduler_trace_bytes_written_total" in rendered


def test_replay_gate_cli(sim_trace, tmp_path):
    """tools/replay_gate.py: exit 0 on HEAD, non-zero on a deliberately
    perturbed kernel, 2 on an unusable bundle."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("BENCH_MESH", None)
    gate = os.path.join(REPO, "tools", "replay_gate.py")

    clean = subprocess.run(
        [sys.executable, gate, sim_trace, "--max-rounds", "2", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    summary = json.loads(clean.stdout.strip().splitlines()[-1])["summary"]
    assert summary["ok"] and summary["rounds"] == 2

    perturbed = subprocess.run(
        [sys.executable, gate, sim_trace, "--max-rounds", "2",
         "--perturb", "tiebreak"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900,
    )
    assert perturbed.returncode == 1, perturbed.stdout + perturbed.stderr
    assert "DIVERGED" in perturbed.stdout

    bogus = tmp_path / "not_a_trace.atrace"
    bogus.write_text("this is not a bundle\n")
    broken = subprocess.run(
        [sys.executable, gate, str(bogus)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900,
    )
    assert broken.returncode == 2, broken.stdout + broken.stderr


if __name__ == "__main__":
    # Fixture regeneration: record a short sim trace and trim it to the
    # first rounds so the committed bundle stays well under 100 KB.
    if "--regen" in sys.argv:
        os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
        if os.path.exists(FIXTURE):
            os.remove(FIXTURE)
        tmp = FIXTURE + ".tmp"
        if os.path.exists(tmp):
            os.remove(tmp)
        record_sim_trace(tmp, max_rounds=6)
        os.replace(tmp, FIXTURE)
        print(f"wrote {FIXTURE} ({os.path.getsize(FIXTURE)} bytes)")
    else:
        print(__doc__)
