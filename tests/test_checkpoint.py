"""Bounded restart: view checkpoints + log compaction
(services/checkpoint.py, FileEventLog.compact).

The reference restarts from materialized Postgres views with serials
(database/migrations/001_initialize_schema.up.sql, scheduler.go:441) and
prunes history (lookout pruner, Pulsar retention). Here the same bound:
recover = checkpoint + suffix replay, and segments below every view's
checkpoint are deleted. The strongest assertion: after compaction a full
replay is IMPOSSIBLE, so a correct restart proves checkpoint recovery."""

import os
import time

import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, QueueSpec
from armada_tpu.events import EventSequence, SubmitJob
from armada_tpu.events.file_log import (
    CompactedLogError,
    FileEventLog,
)
from armada_tpu.services.server import ControlPlane


def _seq(i):
    return EventSequence.of(
        "q", f"set-{i % 4}",
        SubmitJob(
            created=float(i),
            job=JobSpec(id=f"j{i:06d}", queue="q",
                        requests={"cpu": "1", "memory": "1Gi"}),
        ),
    )


def test_file_log_compaction(tmp_path):
    d = str(tmp_path / "log")
    log = FileEventLog(d, segment_size=10)
    for i in range(35):
        log.publish(_seq(i))
    assert log.start_offset == 0 and log.end_offset == 35
    assert len(log._segments()) == 4

    # Compact below 25: segments 0 and 1 (offsets 0..19) are removable.
    assert log.compact(25) == 2
    assert log.start_offset == 20
    assert log.end_offset == 35
    with pytest.raises(CompactedLogError):
        log.read(5)
    assert [e.offset for e in log.read(20, 3)] == [20, 21, 22]

    # Appends continue with global offsets; the active segment is safe.
    off = log.publish(_seq(99))
    assert off == 35
    assert log.compact(10**9) >= 1  # everything but the active segment
    assert log.start_offset == 30
    log.close()

    # Recovery from a compacted directory: base > 0, reads + appends work.
    log2 = FileEventLog(d, segment_size=10)
    assert log2.start_offset == 30
    assert log2.end_offset == 36
    assert [e.offset for e in log2.read(30, 2)] == [30, 31]
    assert log2.publish(_seq(100)) == 36
    with pytest.raises(CompactedLogError):
        log2.read(0)
    # Jobset reads clamp to the surviving suffix instead of raising.
    assert all(e.offset >= 30 for e in log2.read_jobset("q", "set-0"))
    log2.close()


def _plane(data_dir, **kw):
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    return ControlPlane(
        config,
        cycle_period=3600,  # loop never fires; cycles driven manually
        data_dir=data_dir,
        fake_executors=[{"name": "c", "nodes": 4, "cpu": "8", "runtime": 5.0}],
        **kw,
    )


def _drive(plane, t0=0.0, n_jobs=40):
    if "team" not in plane.submit.queues:
        plane.submit.create_queue(QueueSpec("team"))
    plane.submit.submit(
        "team", "set1",
        [JobSpec(id=f"job-{t0}-{i}", queue="",
                 requests={"cpu": "1", "memory": "1Gi"},
                 annotations={
                     "armadaproject.io/deduplication-id": f"dd-{t0}-{i}"
                 })
         for i in range(n_jobs)],
        now=t0,
    )
    ex = plane.executors[0]
    ex.tick(t0)
    plane.scheduler.cycle(now=t0 + 1)
    ex.tick(t0 + 2)
    ex.tick(t0 + 3)
    ex.tick(t0 + 9)  # runtime 5s: first leased batch succeeds
    plane.scheduler.cycle(now=t0 + 10)
    ex.tick(t0 + 11)
    ex.tick(t0 + 17)
    plane.scheduler.cycle(now=t0 + 18)
    plane.lookout_store.sync()
    plane.submit.sync()
    plane.event_index.sync()


def _state_fingerprint(plane):
    jobs = {
        j.id: (j.state.value, j.priority, len(j.runs))
        for j in plane.scheduler.jobdb.read_txn().all_jobs()
    }
    look = {
        r.job_id: (r.state, len(r.runs))
        for r in plane.lookout_store.all_rows()
    }
    queues = sorted(plane.submit.queues)
    return jobs, look, queues


def test_restart_from_checkpoint_after_compaction(tmp_path):
    """Checkpoint + compact so hard that full replay is impossible; the
    restarted plane must still reconstruct identical state (jobdb, lookout
    view, queue registry, dedup index) and keep serving."""
    d = str(tmp_path / "data")
    plane = _plane(d)
    # Small segments so compaction actually removes files.
    plane.log.segment_size = 16
    _drive(plane)
    before = _state_fingerprint(plane)
    end = plane.log.end_offset
    # While the event index references a jobset's offsets its checkpoint
    # pins compaction at that jobset's FIRST offset (watch streams read
    # bodies from the log); retention pruning releases the pin — the same
    # order the control-plane loop runs.
    assert plane.checkpoints.checkpoint_and_compact() == 0
    plane.event_index.prune(older_than=time.time() + 10**6)
    removed = plane.checkpoints.checkpoint_and_compact()
    assert removed > 0, "compaction removed nothing"
    assert plane.log.start_offset > 0
    plane.stop()

    plane2 = _plane(d)
    assert plane2.log.start_offset > 0  # history really is gone
    after = _state_fingerprint(plane2)
    assert after == before
    # Replay was suffix-only by construction (offsets below start raise).
    assert plane2.scheduler.ingester.cursor == plane2.log.end_offset

    # Dedup survives the restart: resubmitting the same dedup ids is a
    # no-op (no new jobs).
    n_before = len(plane2.scheduler.jobdb.read_txn().all_jobs())
    plane2.submit.submit(
        "team", "set1",
        [JobSpec(id=f"dup-{i}", queue="",
                 requests={"cpu": "1", "memory": "1Gi"},
                 annotations={
                     "armadaproject.io/deduplication-id": f"dd-0.0-{i}"
                 })
         for i in range(10)],
        now=100.0,
    )
    plane2.scheduler.ingester.sync()
    assert len(plane2.scheduler.jobdb.read_txn().all_jobs()) == n_before

    # And the plane still schedules new work end-to-end.
    _drive(plane2, t0=200.0, n_jobs=8)
    states = {
        j.state.value
        for j in plane2.scheduler.jobdb.read_txn().all_jobs()
        if j.id.startswith("job-200")
    }
    assert "succeeded" in states
    plane2.stop()


def test_kill9_after_checkpoint_replays_only_suffix(tmp_path):
    """No clean shutdown: state past the checkpoint comes from suffix
    replay, and the replayed-entry count is exactly end - checkpoint."""
    d = str(tmp_path / "data")
    plane = _plane(d)
    _drive(plane)
    plane.checkpoints.save_all()
    ckpt_cursor = plane.checkpoints.store.load("scheduler")[0]
    # More activity AFTER the checkpoint, then die without stop().
    plane.submit.submit(
        "team", "set2",
        [JobSpec(id=f"late-{i}", queue="",
                 requests={"cpu": "1", "memory": "1Gi"})
         for i in range(7)],
        now=50.0,
    )
    plane.log.flush()
    end = plane.log.end_offset
    fingerprint = None  # plane abandoned (simulated crash)

    plane2 = _plane(d)
    assert plane2.scheduler.ingester.cursor == plane2.log.end_offset
    txn = plane2.scheduler.jobdb.read_txn()
    assert all(
        txn.get(f"late-{i}") is not None and
        txn.get(f"late-{i}").state.value == "queued"
        for i in range(7)
    )
    # The checkpoint really was the starting point (not offset 0).
    assert ckpt_cursor > 0
    assert end - ckpt_cursor < 10  # suffix, not history
    plane2.stop()


@pytest.mark.skipif(
    os.environ.get("ARMADA_SCALE_TESTS") != "1",
    reason="1M-event restart bound: minutes; set ARMADA_SCALE_TESTS=1",
)
def test_restart_is_o_delta_at_1m_events(tmp_path):
    """VERDICT-scale bound: >=1M logged events, restart cost tracks the
    suffix (delta) size, not history."""
    d = str(tmp_path / "log")
    log = FileEventLog(d, segment_size=100_000, sync_every=10_000)
    from armada_tpu.services.checkpoint import (
        CheckpointManager,
        CheckpointStore,
    )
    from armada_tpu.services.lookout_ingester import LookoutStore

    store = LookoutStore(log)
    n = 1_000_000
    for i in range(n):
        log.publish(_seq(i))
    store.sync()
    mgr = CheckpointManager(CheckpointStore(str(tmp_path / "ck")), log)
    mgr.register("lookout", store)
    mgr.checkpoint_and_compact()
    assert log.start_offset >= n - 100_000
    # Post-checkpoint delta.
    for i in range(2_000):
        log.publish(_seq(n + i))
    log.close()

    t0 = time.time()
    log2 = FileEventLog(d, segment_size=100_000)
    store2 = LookoutStore(
        log2, checkpoint=CheckpointStore(str(tmp_path / "ck")).load("lookout")
    )
    replayed = store2.sync()
    restart_s = time.time() - t0
    assert len(store2.all_rows()) == n + 2_000
    # Bound: recovery touched only the suffix (<= one segment + delta).
    assert replayed * 1 <= 102_000
    print(f"\n[1M events] restart {restart_s:.2f}s, replayed {replayed}")
    log2.close()


def test_churn_with_rolling_compaction(tmp_path):
    """Generations of churn with checkpoint+compact after each: every
    restart recovers from checkpoint + suffix only (history is gone),
    state matches the live plane each generation, and the log's disk
    footprint stays bounded instead of growing with total history."""
    d = str(tmp_path / "data")
    plane = _plane(d)
    plane.log.segment_size = 32
    seg_counts = []
    for gen in range(4):
        _drive(plane, t0=1000.0 * gen, n_jobs=30)
        plane.event_index.prune(older_than=time.time() + 10**6)
        plane.checkpoints.checkpoint_and_compact()
        seg_counts.append(len(plane.log._segments()))
        before = _state_fingerprint(plane)
        plane.stop()

        plane = _plane(d)
        plane.log.segment_size = 32
        assert plane.log.start_offset > 0, f"gen {gen}: nothing compacted"
        assert _state_fingerprint(plane) == before, f"gen {gen} diverged"
    # Bounded: segments don't accumulate across generations (each
    # generation writes ~the same amount and compaction removes it).
    assert max(seg_counts) <= seg_counts[0] + 2, seg_counts
    plane.stop()


class _SimulatedCrash(BaseException):
    """Raised by the crash hook; BaseException so no advisory except
    Exception on the checkpoint path can accidentally swallow the
    'process died here' simulation."""


def test_checkpoint_crash_point_fuzz(tmp_path):
    """Crash at EVERY durability boundary of a checkpoint+compact pass —
    between tmp-write and rename, between per-view saves, between
    save_all and compaction — and restart over the directory. Recovery
    must never be torn: the restarted plane reconstructs exactly the
    pre-crash state at every crash point, and a subsequent clean pass
    completes."""
    # Enumerate the pass's crash sites with a recording (non-raising)
    # hook first, so the fuzz below covers each one exactly once.
    probe_dir = str(tmp_path / "probe")
    probe = _plane(probe_dir)
    probe.log.segment_size = 16
    _drive(probe, n_jobs=16)
    sites: list = []
    probe.checkpoints.store.crash_hook = sites.append
    probe.checkpoints.checkpoint_and_compact()
    probe.checkpoints.store.crash_hook = None  # stop() checkpoints too
    probe.stop()
    assert len(sites) > 5, sites  # per-view tmp/rename points + compact

    for k, site in enumerate(sites):
        d = str(tmp_path / f"crash-{k}")
        plane = _plane(d)
        plane.log.segment_size = 16
        _drive(plane, n_jobs=16)
        want = _state_fingerprint(plane)

        seen = {"n": 0}

        def hook(label, _k=k):
            if seen["n"] == _k:
                raise _SimulatedCrash(label)
            seen["n"] += 1

        plane.checkpoints.store.crash_hook = hook
        try:
            plane.checkpoints.checkpoint_and_compact()
        except _SimulatedCrash:
            pass
        else:
            raise AssertionError(f"crash hook {k} ({site}) never fired")
        plane.log.flush()
        # Plane abandoned (simulated kill -9 mid-checkpoint); restart.
        plane2 = _plane(d)
        plane2.lookout_store.sync()
        plane2.submit.sync()
        plane2.event_index.sync()
        assert _state_fingerprint(plane2) == want, f"torn at {site!r}"
        # No stale tmp survives recovery, and a clean pass completes.
        ckpt_dir = os.path.join(d, "checkpoints")
        assert not [
            f for f in os.listdir(ckpt_dir) if f.endswith(".tmp")
        ], f"stale tmp after crash at {site!r}"
        plane2.checkpoints.checkpoint_and_compact()
        assert _state_fingerprint(plane2) == want, f"post-pass at {site!r}"
        plane2.stop()
