"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh instead (mirrors how the driver dry-runs multichip code).
Must run before any test module imports jax-dependent code.

The machine's global environment injects a TPU-tunnel PJRT plugin (axon) at
interpreter startup which can hang backend discovery when the tunnel is
unhealthy; armada_tpu.utils.platform handles the scrub.
"""

import os
import sys

os.environ.setdefault("REPO_ROOT", os.path.dirname(os.path.dirname(__file__)))
sys.path.insert(0, os.environ["REPO_ROOT"])

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# x64 gives float64 cost arithmetic and int64 aggregates: exact parity with
# the host oracle. The TPU bench path runs with x64 off (float32 costs).
os.environ["JAX_ENABLE_X64"] = "1"

from armada_tpu.utils.platform import _force_cpu  # noqa: E402

_force_cpu()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

# Persistent compile cache: the solver kernel recompiles per padded shape,
# which dominates suite wall-clock on this 1-core box (and two full-suite
# runs have segfaulted inside XLA's CPU JIT after ~140 in-process
# compilations). Caching executables on disk makes repeat runs load
# instead of compile; clearing the in-process caches at module boundaries
# bounds the live JITed-code footprint that appears to trigger the crash.
from armada_tpu.utils.platform import compile_cache_dir  # noqa: E402

# Keyed by host-CPU-feature hash: AOT executables cached by one machine
# are never loaded on an incompatible host (cpu_aot_loader SIGILL hazard).
jax.config.update("jax_compilation_cache_dir", compile_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402


def pytest_sessionstart(session):
    """Fail fast on orphaned bytecode: a `__pycache__/mod.*.pyc` whose
    `mod.py` source is gone (a deleted or renamed module, e.g. the
    remnants of a discarded front-door attempt) still satisfies imports
    on this interpreter and can silently shadow the real tree. Delete
    the stale .pyc instead of exempting it here."""
    root = os.environ["REPO_ROOT"]
    orphans = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if not d.startswith(".")]
        if os.path.basename(dirpath) != "__pycache__":
            continue
        src_dir = os.path.dirname(dirpath)
        for name in filenames:
            if not name.endswith(".pyc"):
                continue
            module = name.split(".", 1)[0]
            if not os.path.exists(os.path.join(src_dir, module + ".py")):
                orphans.append(os.path.relpath(
                    os.path.join(dirpath, name), root
                ))
    if orphans:
        raise pytest.UsageError(
            "orphaned __pycache__ bytecode without a matching .py source "
            "(can shadow imports; delete them): " + ", ".join(sorted(orphans))
        )


@pytest.fixture(autouse=True, scope="module")
def _bound_jit_cache():
    yield
    jax.clear_caches()


def pytest_collection_modifyitems(config, items):
    """Exhaustive parity sweeps (random-scenario fleets, the full
    fast-fill matrix) run only with ARMADA_FULL_SUITE=1: the default
    suite keeps one representative per mechanism and finishes in
    minutes, the full sweep stays one env var away."""
    if os.environ.get("ARMADA_FULL_SUITE") == "1":
        return
    skip = pytest.mark.skip(reason="slow sweep; set ARMADA_FULL_SUITE=1")
    for item in items:
        if item.get_closest_marker("slow") is not None:
            item.add_marker(skip)
