"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh instead (mirrors how the driver dry-runs multichip code).
Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
