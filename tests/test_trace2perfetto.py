"""tools/trace2perfetto.py: OTLP span files and .atrace bundles convert
to valid Chrome trace-event JSON (Perfetto-loadable); the committed
fixture round-trips under --check so the converter cannot rot against
the trace codec."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import trace2perfetto  # noqa: E402

FIXTURE = os.path.join(REPO, "tests", "fixtures", "sim_steady.atrace")


def test_check_round_trips_committed_fixture():
    """The tier-1 gate: --check converts tests/fixtures/sim_steady.atrace
    and validates the output — in-process and via the CLI entrypoint."""
    assert trace2perfetto.check(FIXTURE) == 0
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace2perfetto.py"),
         "--check"],
        capture_output=True, text=True, env={**os.environ,
                                             "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    assert "ok:" in proc.stdout


def test_atrace_conversion_covers_every_round():
    from armada_tpu.trace import load_trace

    doc = trace2perfetto.convert([FIXTURE])
    assert trace2perfetto.validate(doc) == []
    rounds = len(load_trace(FIXTURE).rounds)
    slices = [e for e in doc["traceEvents"]
              if e.get("ph") == "X" and e.get("cat") == "round"]
    assert len(slices) == rounds
    # Slices are well-ordered per track (sequential layout).
    by_tid: dict = {}
    for e in slices:
        by_tid.setdefault(e["tid"], []).append(e)
    for events in by_tid.values():
        for a, b in zip(events, events[1:]):
            assert b["ts"] >= a["ts"]


def test_otlp_spans_convert_with_nesting_metadata(tmp_path):
    from armada_tpu.utils.tracing import OtlpJsonFileExporter, Tracer

    path = str(tmp_path / "spans.otlp.jsonl")
    tracer = Tracer(exporter=OtlpJsonFileExporter(path), export_every=100)
    with tracer.span("scheduler.round", pool="default") as outer:
        with tracer.span("solve.pass1"):
            pass
    tracer.flush()
    doc = trace2perfetto.convert([path])
    assert trace2perfetto.validate(doc) == []
    slices = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert set(slices) == {"scheduler.round", "solve.pass1"}
    # One track per trace id; child nested within the parent's interval.
    assert slices["solve.pass1"]["tid"] == slices["scheduler.round"]["tid"]
    assert slices["solve.pass1"]["ts"] >= slices["scheduler.round"]["ts"]
    assert slices["scheduler.round"]["args"]["pool"] == "default"
    assert slices["scheduler.round"]["args"]["trace_id"] == outer.trace_id


def test_twenty_round_sim_exports_loadable_timeline(tmp_path):
    """Acceptance: a 20-round sim run (flight recorder + span export)
    converts to Chrome trace-event JSON that json-round-trips, validates
    clean, and covers >= 20 rounds."""
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    atrace = str(tmp_path / "run.atrace")
    spans = str(tmp_path / "run.otlp.jsonl")
    sim = Simulator(
        [ClusterSpec(name="c", node_templates=(NodeTemplate(count=2, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    name="q",
                    job_templates=tuple(
                        # Staggered arrivals keep rounds busy for 20+
                        # cycles of the 10s virtual cycle interval.
                        JobTemplate(
                            id=f"t{i}", number=1, cpu="2",
                            submit_time=10.0 * i,
                            runtime=ShiftedExponential(minimum=60.0),
                        )
                        for i in range(22)
                    ),
                ),
            )
        ),
        backend="oracle",
        cycle_interval=10.0,
        max_time=600.0,
        trace_path=atrace,
        span_path=spans,
    )
    sim.run()
    doc = trace2perfetto.convert([atrace, spans])
    assert trace2perfetto.validate(doc) == []
    # Survives the encode/decode round trip Perfetto's loader performs.
    reloaded = json.loads(json.dumps(doc))
    rounds = [e for e in reloaded["traceEvents"]
              if e.get("ph") == "X" and e.get("cat") == "round"]
    assert len(rounds) >= 20
    # The span export contributed the scheduler's cycle/round spans too.
    names = {e.get("name") for e in reloaded["traceEvents"]}
    assert "scheduler.cycle" in names
    assert "scheduler.round" in names
    out = str(tmp_path / "out.json")
    assert trace2perfetto.main([atrace, spans, "-o", out]) == 0
    assert json.load(open(out))["traceEvents"]
