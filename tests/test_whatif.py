"""What-if planner: forked shadow solves, drain plan/apply parity,
planner isolation, fixture-trace bit-exactness, submit-checker epoch.

The acceptance contracts (ISSUE 10):
  - plan/apply parity: a drain dry-run's predicted outcome (preempted
    set, requeue placements, rounds-to-drain) is IDENTICAL to executing
    the same drain in a deterministic sim, gang-aware, under LOCAL and
    "2x4" mesh solver specs;
  - planner isolation: a concurrent what-if burst leaves live round
    metrics untouched and planner solves are bit-exact with the live
    kernel on an unmutated fork (replayer-style compare on the
    committed fixture trace).
"""

import os
import threading
import time

import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, QueueSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.jobdb import JobState
from armada_tpu.services.fake_executor import FakeExecutor, make_nodes
from armada_tpu.services.metrics import HAVE_PROMETHEUS, SchedulerMetrics
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService
from armada_tpu.whatif import (
    WhatIfBusyError,
    WhatIfService,
    fork_from_trace,
    mutation_from_dict,
    mutations_from_dicts,
)
from armada_tpu.whatif.planner import parity_check

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "sim_steady.atrace")

CONFIG = SchedulingConfig(
    priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
    default_priority_class="d",
)


def _harness(runtimes=None, *, nodes_a=2, nodes_b=2, cpu="8", config=None):
    """Scheduler + two fake executors + submit service on one log."""
    config = config or CONFIG
    log = InMemoryEventLog()
    sched = SchedulerService(config, log)
    submit = SubmitService(config, log, scheduler=sched)
    submit.create_queue(QueueSpec("team"))
    runtimes = runtimes or {}
    rt = lambda jid: runtimes.get(jid, 1e9)  # noqa: E731
    ex_a = FakeExecutor("ex-a", log, sched,
                        nodes=make_nodes("ex-a", count=nodes_a, cpu=cpu),
                        runtime_for=rt)
    ex_b = FakeExecutor("ex-b", log, sched,
                        nodes=make_nodes("ex-b", count=nodes_b, cpu=cpu),
                        runtime_for=rt)
    return log, sched, submit, ex_a, ex_b


def _cycle(sched, executors, t):
    for ex in executors:
        ex.tick(t)
    seqs = sched.cycle(now=t)
    for ex in executors:
        ex.tick(t)
    return seqs


def _job(i, cpu="4", gang=None, **kw):
    return JobSpec(
        id=f"j{i}", queue="team", jobset="s",
        requests={"cpu": cpu, "memory": "1Gi"},
        submitted_ts=float(i), gang=gang, **kw,
    )


# ---------------------------------------------------------------------------
# mutations vocabulary
# ---------------------------------------------------------------------------


def test_mutation_vocabulary_decodes_every_kind():
    kinds = [
        {"kind": "cordon_node", "name": "n0"},
        {"kind": "uncordon_node", "name": "n0"},
        {"kind": "remove_node", "name": "n0"},
        {"kind": "add_nodes", "count": 2, "cpu": "8"},
        {"kind": "cordon_executor", "name": "ex"},
        {"kind": "drain_executor", "name": "ex", "deadline_s": 5.0},
        {"kind": "inject_gang", "queue": "q", "gang_cardinality": 4,
         "cpu": "2"},
        {"kind": "inject_jobs", "queue": "q", "count": 3},
        {"kind": "scale_queue", "name": "q", "weight": 2.0},
    ]
    for d in kinds:
        m = mutation_from_dict(d)
        assert m.to_dict()["kind"] == (
            "inject_gang" if d["kind"] == "inject_jobs" else d["kind"]
        )
    with pytest.raises(ValueError, match="unknown mutation kind"):
        mutation_from_dict({"kind": "explode"})


def test_preempt_requeue_event_semantics():
    """JobRunPreempted(requeue=True) kills the RUN but returns the job
    to QUEUED; without the flag the job stays terminally PREEMPTED."""
    from armada_tpu.events import EventSequence, JobRunPreempted

    log, sched, submit, ex_a, ex_b = _harness()
    submit.submit("team", "s", [_job(0), _job(1)], now=0.0)
    _cycle(sched, [ex_a, ex_b], 0.0)
    for jid in ("j0", "j1"):
        assert sched.jobdb.get(jid).state in (
            JobState.LEASED, JobState.PENDING, JobState.RUNNING,
        )
    run0 = sched.jobdb.get("j0").latest_run
    run1 = sched.jobdb.get("j1").latest_run
    log.publish(EventSequence.of(
        "team", "s",
        JobRunPreempted(created=1.0, job_id="j0", run_id=run0.id,
                        reason="drain test", requeue=True),
        JobRunPreempted(created=1.0, job_id="j1", run_id=run1.id,
                        reason="classic"),
    ))
    sched.ingester.sync()
    j0, j1 = sched.jobdb.get("j0"), sched.jobdb.get("j1")
    assert j0.state == JobState.QUEUED
    assert j0.latest_run.state.value == "preempted"
    assert j1.state == JobState.PREEMPTED
    sched.jobdb.read_txn().assert_valid()


# ---------------------------------------------------------------------------
# planning: gang ETA, headroom, feasibility, live-state isolation
# ---------------------------------------------------------------------------


def test_inject_gang_eta_and_headroom():
    log, sched, submit, ex_a, ex_b = _harness()
    submit.submit("team", "s", [_job(i) for i in range(4)], now=0.0)
    _cycle(sched, [ex_a, ex_b], 0.0)
    wi = WhatIfService(sched)
    sched.attach_whatif(wi)
    _cycle(sched, [ex_a, ex_b], 1.0)  # captured fork with the seam

    plan = wi.plan(
        mutations_from_dicts(
            [{"kind": "inject_gang", "queue": "team",
              "gang_cardinality": 2, "cpu": "4", "memory": "1Gi"}]
        ),
        rounds=4,
    )
    (gang,) = plan.injected
    assert gang["feasible"] and gang["eta_rounds"] == 1
    assert gang["gang_cardinality"] == 2 and len(gang["nodes"]) >= 1
    free = plan.headroom["pool"]["free"]
    # 4 nodes x 8 cpu - 4 running x 4 - injected gang 2 x 4 = 8 left.
    assert free["cpu"] == 8.0
    assert plan.baseline["running"] == 4 and plan.baseline["queued"] == 0

    # An impossible gang carries the SubmitChecker's reason vocabulary
    # (same snapshot-build helper: services/submit_check.static_check).
    plan2 = wi.plan(
        mutations_from_dicts(
            [{"kind": "inject_gang", "queue": "team",
              "gang_cardinality": 2, "cpu": "999"}]
        ),
        rounds=2,
    )
    (gang2,) = plan2.injected
    assert not gang2["feasible"]
    assert gang2["eta_rounds"] is None
    assert "never schedulable" in gang2["reason"]


def test_whatif_leaves_live_state_untouched():
    """Shadow solves must not publish a single live event or flip any
    job state — the whole point of forking."""
    log, sched, submit, ex_a, ex_b = _harness()
    submit.submit("team", "s", [_job(i) for i in range(4)], now=0.0)
    _cycle(sched, [ex_a, ex_b], 0.0)
    wi = WhatIfService(sched)
    sched.attach_whatif(wi)
    _cycle(sched, [ex_a, ex_b], 1.0)
    before_offset = log.end_offset
    before_states = {
        j.id: j.state for j in sched.jobdb.read_txn().all_jobs()
    }
    wi.plan_drain("ex-a", deadline_s=0.0, rounds=6)
    wi.plan(
        mutations_from_dicts(
            [{"kind": "remove_node", "name": "ex-b-node-00000"}]
        ),
        rounds=3,
    )
    assert log.end_offset == before_offset
    assert {
        j.id: j.state for j in sched.jobdb.read_txn().all_jobs()
    } == before_states
    assert not sched.cordoned_executors


# ---------------------------------------------------------------------------
# drain: plan/apply parity (the acceptance contract)
# ---------------------------------------------------------------------------


def _drain_parity_case(solver, backend, mesh):
    """One deterministic drain scenario, predicted then executed.

    Fleet: ex-a 1x8cpu node, ex-b 2x8cpu nodes. A 2-member gang of
    8-cpu jobs spans ex-a and ex-b; a short job on ex-a completes
    voluntarily inside the deadline. Draining ex-a must: let the short
    job finish, preempt BOTH gang members (gang-aware — the ex-b member
    goes too, no stranded partial gang), and land the whole gang on
    ex-b's freed nodes.
    """
    runtimes = {"g0": 1e9, "g1": 1e9, "short": 25.0}
    log = InMemoryEventLog()
    sched = SchedulerService(CONFIG, log, backend=backend, mesh=mesh)
    submit = SubmitService(CONFIG, log, scheduler=sched)
    submit.create_queue(QueueSpec("team"))
    rt = lambda jid: runtimes.get(jid, 1e9)  # noqa: E731
    # 9-cpu nodes: an 8-cpu gang member + the 1-cpu short job share
    # ex-a's node (best-fit ties break toward the lexicographically
    # first node id, so `short` provably lands next to g0 on ex-a).
    ex_a = FakeExecutor("ex-a", log, sched,
                        nodes=make_nodes("ex-a", count=1, cpu="9"),
                        runtime_for=rt)
    ex_b = FakeExecutor("ex-b", log, sched,
                        nodes=make_nodes("ex-b", count=2, cpu="9"),
                        runtime_for=rt)
    gang = Gang(id="g", cardinality=2)
    jobs = [
        JobSpec(id="g0", queue="team", jobset="s",
                requests={"cpu": "8", "memory": "1Gi"}, gang=gang,
                submitted_ts=0.0),
        JobSpec(id="g1", queue="team", jobset="s",
                requests={"cpu": "8", "memory": "1Gi"}, gang=gang,
                submitted_ts=0.0),
        JobSpec(id="short", queue="team", jobset="s",
                requests={"cpu": "1", "memory": "1Gi"}, submitted_ts=1.0),
    ]
    submit.submit("team", "s", jobs, now=0.0)
    _cycle(sched, [ex_a, ex_b], 0.0)
    _cycle(sched, [ex_a, ex_b], 10.0)
    txn = sched.jobdb.read_txn()
    placements = {
        j.id: j.latest_run.node_id for j in txn.all_jobs() if j.latest_run
    }
    # The scenario's premise: the gang spans both executors.
    gang_execs = {placements["g0"][:4], placements["g1"][:4]}
    assert gang_execs == {"ex-a", "ex-b"}, placements

    wi = WhatIfService(sched)
    sched.attach_whatif(wi)
    remaining = {}
    for ex in (ex_a, ex_b):
        for run in ex.active.values():
            remaining[run.job_id] = run.finishes_at - 10.0
    predicted = wi.plan_drain(
        "ex-a",
        deadline_s=40.0,
        rounds=12,
        solver=solver,
        runtime_for=lambda jid: remaining.get(jid, 1e9),
    )
    pred = predicted.drain
    assert pred["done"], pred

    wi.execute_drain("ex-a", deadline_s=40.0)
    for k in range(1, 12):
        _cycle(sched, [ex_a, ex_b], 10.0 + 10.0 * k)
    actual = sched.drains.status("ex-a")
    assert actual["done"], actual
    for key in ("completed", "preempted", "blocked", "landings",
                "rounds_to_drain"):
        assert pred[key] == actual[key], (key, pred[key], actual[key])
    # Scenario shape: short completed voluntarily; the WHOLE gang was
    # preempted (including the ex-b member) and landed on ex-b.
    assert pred["completed"] == ["short"]
    assert pred["preempted"] == ["g0", "g1"]
    assert set(pred["landings"]) == {"g0", "g1"}
    assert all(n.startswith("ex-b") for n in pred["landings"].values())
    # No stranded partial gang: both members live again, off ex-a.
    txn = sched.jobdb.read_txn()
    for jid in ("g0", "g1"):
        job = txn.get(jid)
        assert job.state in (
            JobState.LEASED, JobState.PENDING, JobState.RUNNING,
        )
        assert job.latest_run.executor == "ex-b"
    return pred


def test_drain_plan_apply_parity_local():
    _drain_parity_case(solver="oracle", backend="oracle", mesh=None)


def test_drain_plan_apply_parity_local_kernel():
    _drain_parity_case(solver="LOCAL", backend="kernel", mesh=None)


@pytest.mark.slow
def test_drain_plan_apply_parity_mesh_2x4():
    _drain_parity_case(solver="2x4", backend="kernel", mesh="2x4")


def test_drain_reason_visible_in_job_trace():
    """Drain preemptions carry their reason into the job-journey
    timeline (`armadactl job-trace`)."""
    log, sched, submit, ex_a, ex_b = _harness()
    submit.submit("team", "s", [_job(0)], now=0.0)
    _cycle(sched, [ex_a, ex_b], 0.0)
    executor = sched.jobdb.get("j0").latest_run.executor
    sched.drains.start(executor, deadline_s=0.0)
    for k in range(1, 5):
        _cycle(sched, [ex_a, ex_b], 10.0 * k)
    rendered = sched.timeline.render("j0")
    assert "preempted" in rendered
    assert f"drain {executor}: deadline reached" in rendered
    # And the job landed on the other executor.
    assert sched.jobdb.get("j0").latest_run.executor != executor


# ---------------------------------------------------------------------------
# planner isolation: burst leaves live rounds untouched + backpressure
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not HAVE_PROMETHEUS, reason="prometheus unavailable")
def test_planner_isolation_burst():
    log, sched, submit, ex_a, ex_b = _harness()
    m = SchedulerMetrics()
    sched.attach_metrics(m)
    submit.submit("team", "s", [_job(i) for i in range(4)], now=0.0)
    _cycle(sched, [ex_a, ex_b], 0.0)
    wi = WhatIfService(sched, metrics=m, workers=1, queue_depth=2)
    sched.attach_whatif(wi)
    _cycle(sched, [ex_a, ex_b], 1.0)

    def live_solve_count():
        total = 0
        for family in m.solve_time.collect():
            for sample in family.samples:
                if sample.name.endswith("_count"):
                    total += sample.value
        return total

    solves_before = live_solve_count()
    results, errors = [], []

    def fire():
        try:
            results.append(
                wi.plan(
                    mutations_from_dicts(
                        [{"kind": "inject_gang", "queue": "team",
                          "gang_cardinality": 2, "cpu": "1"}]
                    ),
                    rounds=3,
                )
            )
        except WhatIfBusyError as e:
            errors.append(e)

    threads = [threading.Thread(target=fire) for _ in range(6)]
    cycle_times = []
    for th in threads:
        th.start()
    # Live rounds keep running mid-burst; their wall clock is recorded
    # by the live metrics only.
    for k in range(2, 6):
        t0 = time.monotonic()
        _cycle(sched, [ex_a, ex_b], float(k))
        cycle_times.append(time.monotonic() - t0)
    for th in threads:
        th.join()

    # Backpressure: a 6-deep burst on a 1-worker/2-queue planner must
    # shed some requests instead of queueing unboundedly...
    assert errors, "expected WhatIfBusyError from the bounded planner"
    assert results, "and still complete the admitted plans"
    # ...the queue drains back to idle...
    assert wi._pending == 0
    # ...and live round metrics saw ONLY the live cycles: planner
    # solves never touch scheduler_solve_* (each plan re-solves in its
    # private rollout scheduler with no metrics attached).
    assert live_solve_count() == solves_before + 4
    # The plan histogram recorded the admitted plans.
    plan_count = 0
    for family in m.whatif_plan_seconds.collect():
        for sample in family.samples:
            if sample.name.endswith("_count"):
                plan_count += sample.value
    assert plan_count == len(results)


# ---------------------------------------------------------------------------
# fixture-trace parity: planner solves are bit-exact with the live kernel
# ---------------------------------------------------------------------------


def test_fixture_fork_parity_local():
    """Tier-1 smoke: fork a recorded round from the committed fixture
    bundle and re-solve it UNMUTATED under LOCAL — the decision stream
    must be bit-exact (replayer-style compare)."""
    fork = fork_from_trace(FIXTURE, round_i=0, allow_foreign=True)
    report = parity_check(fork, "LOCAL")
    assert report["ok"], report["divergences"]
    assert report["num_jobs"] > 0


@pytest.mark.slow
def test_fixture_fork_parity_hotwindow():
    fork = fork_from_trace(FIXTURE, round_i=1, allow_foreign=True)
    report = parity_check(fork, "hotwindow:4")
    assert report["ok"], report["divergences"]


@pytest.mark.slow
def test_fixture_fork_parity_mesh_2x4():
    fork = fork_from_trace(FIXTURE, round_i=0, allow_foreign=True)
    report = parity_check(fork, "2x4")
    assert report["ok"], report["divergences"]


def test_trace_fork_device_cordon():
    """Device-level node cordon on a trace fork flips placements away
    from the cordoned node (the recorded round placed jobs there)."""
    import numpy as np

    from armada_tpu.whatif.fork import cordon_node_in_fork

    fork = fork_from_trace(FIXTURE, round_i=0, allow_foreign=True)
    rec = fork.trace_record
    ids = (rec.raw.get("ids") or {}).get("nodes")
    if not ids:
        pytest.skip("fixture carries no node id vocabulary")
    decisions = rec.decisions()
    assigned = np.asarray(decisions["assigned_node"])[: rec.num_jobs]
    used = [i for i in np.unique(assigned) if i >= 0]
    if not used:
        pytest.skip("recorded round placed nothing")
    victim = ids[int(used[0])]
    mutated = cordon_node_in_fork(fork, victim)
    report = parity_check(mutated, "LOCAL")
    # The mutated fork MUST diverge from the recorded decisions: the
    # victim node can no longer host its jobs.
    assert not report["ok"]


# ---------------------------------------------------------------------------
# satellite: SubmitChecker cache invalidation on executor cordon
# ---------------------------------------------------------------------------


def test_submit_checker_cordon_epoch():
    """Cordoning an executor is a fleet-epoch change: cached verdicts
    must invalidate, and the cordoned executor stops counting as
    feasible capacity."""
    from armada_tpu.services.submit_check import SubmitChecker

    log, sched, submit, ex_a, ex_b = _harness(nodes_a=1, nodes_b=1)
    # ex-a is the only executor with a big node; ex-b gets tiny nodes.
    ex_b.nodes = make_nodes("ex-b", count=1, cpu="1")
    _cycle(sched, [ex_a, ex_b], 0.0)
    checker = SubmitChecker(CONFIG, sched)
    big = [JobSpec(id="big", queue="team",
                   requests={"cpu": "8", "memory": "1Gi"})]
    assert checker.check(big).schedulable  # fits on ex-a; verdict cached
    # Cordon the only executor that can host it: the cached verdict must
    # NOT survive the fleet-epoch change.
    sched.set_executor_cordon("ex-a", True)
    result = checker.check(big)
    assert not result.schedulable
    assert "unschedulable" in result.reason
    # Uncordon: schedulable again (epoch flips back, cache rebuilt).
    sched.set_executor_cordon("ex-a", False)
    assert checker.check(big).schedulable
