"""Front door (armada_tpu/frontdoor): sharded ingest exactly-once under
chaos, per-tenant admission with RESOURCE_EXHAUSTED + retry-after on the
wire, deadline propagation, and the tier-1 slice of the frontdoor soak
(tools/frontdoor_soak.py runs the full gate)."""

import json
import time

import grpc
import pytest

from armada_tpu.core.types import JobSpec
from armada_tpu.events import InMemoryEventLog
from armada_tpu.events.model import EventSequence, SubmitJob
from armada_tpu.frontdoor import (
    AdmissionError,
    DeadlineExpired,
    FrontDoor,
    TenantAdmission,
    shard_of,
)
from armada_tpu.frontdoor.partition import ShardCrashed
from armada_tpu.jobdb import JobDb
from armada_tpu.jobdb.ingest import SchedulerIngester
from armada_tpu.services.chaos import FaultPlan, FaultSpec


def _seq(queue, jobset, job_id):
    return EventSequence.of(
        queue, jobset,
        SubmitJob(
            created=1.0,
            job=JobSpec(
                id=job_id, queue=queue, jobset=jobset,
                requests={"cpu": "1", "memory": "1Gi"},
            ),
        ),
    )


def _submit_ids(log):
    """job id -> SubmitJob occurrence count across the whole log."""
    counts = {}
    for entry in log.read(0, 10 ** 9):
        for event in entry.sequence.events:
            if isinstance(event, SubmitJob):
                counts[event.job.id] = counts.get(event.job.id, 0) + 1
    return counts


# ---- routing + ordered delivery ----


def test_shard_of_stable_and_spread():
    """crc32 routing: deterministic across processes (no salted hash),
    jobset-sticky, and spreads thousands of jobsets over every shard."""
    assert shard_of("q", "js", 4) == shard_of("q", "js", 4)
    used = {shard_of("q", f"js-{i}", 8) for i in range(2000)}
    assert used == set(range(8))
    # Different queues with the same jobset name are distinct keys.
    keys = {(shard_of(f"q{i}", "js", 1024)) for i in range(100)}
    assert len(keys) > 1


def test_sharded_ingest_preserves_jobset_order():
    """A jobset maps to one shard and its WAL delivers in offset order,
    so every jobset observes its submissions in order in the main log
    even with shards interleaving."""
    main = InMemoryEventLog()
    fd = FrontDoor(main, num_shards=4)
    for k in range(60):
        fd.append(_seq("qa", f"js{k % 7}", f"job-{k:03d}"))
    fd.pump()
    assert fd.max_lag() == 0
    per_jobset = {}
    for entry in main.read(0, 1000):
        per_jobset.setdefault(entry.sequence.jobset, []).append(
            entry.sequence.events[0].job.id
        )
    assert len(per_jobset) == 7
    for ids in per_jobset.values():
        assert ids == sorted(ids)


# ---- exactly-once across crash/restart (satellite: seeded chaos plan
# killing a shard ingester mid-batch + jobdb assert_valid) ----


@pytest.mark.chaos
def test_shard_ingester_crash_mid_batch_exactly_once(tmp_path):
    """A seeded plan crash-loops one shard's ingester MID-batch (entries
    already published past the durable cursor), then the front door is
    torn down and rebuilt over the same directories (hard process
    restart). No acked submit is lost, none is double-applied, and the
    materialized jobdb passes assert_valid."""
    queue, jobset = "team", "wave-1"
    idx = shard_of(queue, jobset, 3)
    plan = FaultPlan(
        [FaultSpec("executor_crash", f"shard-{idx}", start=0.0, count=3)],
        seed=7,
    )
    main = InMemoryEventLog()
    fd = FrontDoor(main, num_shards=3, directory=str(tmp_path), fault_plan=plan)
    acked = []
    for k in range(30):
        fd.append(_seq(queue, jobset, f"j{k:03d}"))
        acked.append(f"j{k:03d}")
    # Pump until the crash budget is consumed; each ShardCrashed is met
    # with an in-place restart from durable state.
    for _ in range(10):
        fd.pump()
    assert sum(s.restarts for s in fd.shards) == 3
    assert fd.max_lag() > 0 or fd.shards[idx].duplicates_suppressed > 0
    # Hard restart: a fresh FrontDoor over the same directories (the
    # previous instance simply stops being pumped, like a killed pod).
    fd2 = FrontDoor(main, num_shards=3, directory=str(tmp_path))
    for _ in range(10):
        fd2.pump()
    assert fd2.max_lag() == 0
    counts = _submit_ids(main)
    assert sorted(counts) == sorted(acked)
    assert all(c == 1 for c in counts.values()), {
        j: c for j, c in counts.items() if c != 1
    }
    # The redelivery window was actually exercised, not vacuously green.
    dups_suppressed = (
        fd.shards[idx].duplicates_suppressed
        + fd2.shards[idx].duplicates_suppressed
    )
    assert dups_suppressed > 0
    # Materialize into a jobdb exactly as the scheduler ingester does.
    jobdb = JobDb()
    SchedulerIngester(main, jobdb).sync()
    txn = jobdb.read_txn()
    txn.assert_valid()
    assert sorted(j.id for j in txn.all_jobs()) == sorted(acked)


def test_torn_wal_write_recovers_and_ack_is_durable(tmp_path):
    """torn_log_write chaos on the shard WAL: the append tears mid-
    record, recovery truncates, the retry lands — an ack only ever means
    durable bytes. A restarted front door delivers everything once."""
    queue, jobset = "t", "js"
    idx = shard_of(queue, jobset, 2)
    plan = FaultPlan(
        [FaultSpec("torn_log_write", f"shard-{idx}", start=0.0, count=3,
                   param=0.5)],
        seed=3,
    )
    main = InMemoryEventLog()
    fd = FrontDoor(main, num_shards=2, directory=str(tmp_path),
                   fault_plan=plan)
    for k in range(12):
        fd.append(_seq(queue, jobset, f"j{k}"))
    assert fd.shards[idx].wal.crashes == 3
    fd.close()
    # Process restart: recovery + delivery, exactly once.
    fd2 = FrontDoor(main, num_shards=2, directory=str(tmp_path))
    fd2.drain()
    counts = _submit_ids(main)
    assert len(counts) == 12 and all(c == 1 for c in counts.values())


def test_shard_partition_delays_but_never_drops(tmp_path):
    """network_partition on one shard: delivery pauses for the window
    (lag grows), resumes on heal; acked work is delayed, never lost."""
    queue, jobset = "t", "js"
    idx = shard_of(queue, jobset, 2)
    plan = FaultPlan(
        [FaultSpec("network_partition", f"shard-{idx}", start=0.0,
                   duration=100.0)],
        seed=1,
    )
    from armada_tpu.services.chaos import VirtualClock

    clock = VirtualClock(now=10.0)  # inside the window
    main = InMemoryEventLog()
    fd = FrontDoor(main, num_shards=2, directory=str(tmp_path),
                   fault_plan=plan, clock=clock)
    for k in range(8):
        fd.append(_seq(queue, jobset, f"j{k}"))
    fd.pump()
    assert fd.shards[idx].lag > 0 and not main.read(0, 10)
    clock.now = 150.0  # healed
    fd.pump()
    assert fd.max_lag() == 0
    assert len(_submit_ids(main)) == 8


def test_idle_shards_do_not_pin_compaction():
    """checkpoint_state: a shard the jobset keys never hit reports the
    log END (it has no redelivery window to protect), so the registered
    front-door checkpoint cursor advances and log compaction is never
    stalled at offset 0 by an idle shard."""
    main = InMemoryEventLog()
    fd = FrontDoor(main, num_shards=4)
    for k in range(10):
        fd.append(_seq("q", "one-jobset", f"j{k}"))  # one shard only
    fd.pump()
    cursor, state = fd.checkpoint_state()
    assert cursor == main.end_offset > 0
    # A shard with acked-but-undelivered work still holds the cursor at
    # its durably saved offset (the dedup window must survive).
    fd2 = FrontDoor(main, num_shards=1)
    fd2.append(_seq("q", "js", "lagging"))
    assert fd2.max_lag() == 1
    cursor2, _ = fd2.checkpoint_state()
    assert cursor2 == fd2.shards[0]._saved_main_offset


# ---- admission control ----


def test_tenant_rate_limit_sheds_with_retry_after():
    adm = TenantAdmission(tenant_rate=10.0, tenant_burst=5.0,
                          global_rate=1000.0, global_burst=1000.0)
    admitted = shed = 0
    retry_after = None
    for _ in range(20):
        try:
            adm.admit("hot", 1, now=0.0)
            admitted += 1
        except AdmissionError as e:
            shed += 1
            retry_after = e.retry_after_s
    assert admitted == 5 and shed == 15
    assert retry_after is not None and retry_after > 0
    # Tokens refill: the same tenant is admitted again later.
    adm.admit("hot", 1, now=1.0)
    # Another tenant's bucket was never touched by the flood.
    adm.admit("cold", 1, now=0.0)
    assert adm.shed.get("cold", 0) == 0


def test_global_rate_refunds_tenant_bucket():
    adm = TenantAdmission(tenant_rate=100.0, tenant_burst=100.0,
                          global_rate=10.0, global_burst=3.0)
    outcomes = []
    for _ in range(5):
        try:
            adm.admit("a", 1, now=0.0)
            outcomes.append("ok")
        except AdmissionError as e:
            outcomes.append(e.reason if hasattr(e, "reason") else str(e))
    assert outcomes[:3] == ["ok"] * 3
    assert adm.last_shed_reason["a"] == "globalRate"
    # The tenant bucket was refunded for globally shed requests: all
    # 100 tenant tokens minus the 3 admitted remain.
    assert adm._tenant["a"].tokens == pytest.approx(97.0)


def test_overload_sheds_quota_weighted_not_globally():
    """Downstream gate unhealthy: the hot low-quota tenant is shed hard
    while a high-quota tenant keeps ~its weighted share of the trickle —
    tenant-aware shedding, not a global slam."""

    class Gate:
        def check(self):
            return False, "ingestLagExceeded: scheduler-ingester behind"

    adm = TenantAdmission(
        overload_rate=6.0, downstream=Gate(),
        quota_of=lambda t: 2.0 if t == "vip" else 1.0,
    )
    results = {"vip": [0, 0], "noisy": [0, 0]}
    for tick in range(30):
        for tenant in ("vip", "noisy"):
            for _ in range(6):
                try:
                    adm.admit(tenant, 1, now=float(tick))
                    results[tenant][0] += 1
                except AdmissionError as e:
                    results[tenant][1] += 1
                    assert e.retry_after_s > 0
    assert results["vip"][0] > 1.5 * results["noisy"][0]
    assert results["noisy"][0] > 0  # trickle, not starvation
    assert adm.last_shed_reason["noisy"].startswith("overload:")


# ---- deadline propagation ----


def test_deadline_drops_early_never_half_applied(tmp_path):
    """An expired deadline at the enqueue drops the WHOLE batch before
    the WAL append: nothing acked, nothing in the WAL or main log, and
    dedup entries roll back so a retry re-publishes."""
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.core.types import QueueSpec
    from armada_tpu.services.submit import SubmitService

    main = InMemoryEventLog()
    fd = FrontDoor(main, num_shards=2, directory=str(tmp_path))
    submit = SubmitService(SchedulingConfig(), main, frontdoor=fd)
    submit.create_queue(QueueSpec("q"))
    job = JobSpec(
        id="", queue="q", requests={"cpu": "1", "memory": "1Gi"},
        annotations={"armadaproject.io/deduplication-id": "d-1"},
    )
    queue_events = main.end_offset  # queue CRUD goes direct
    with pytest.raises(DeadlineExpired):
        submit.submit("q", "js", [job], now=10.0, deadline_ts=5.0)
    assert fd.max_lag() == 0 and main.end_offset == queue_events
    assert fd.deadline_drops["enqueue"] == 1
    # The retry is NOT swallowed by a phantom dedup hit.
    ids = submit.submit("q", "js", [job], now=10.0, deadline_ts=20.0)
    fd.drain()
    assert _submit_ids(main)[ids[0]] == 1


# ---- the gRPC wire (satellite: clients honor retry-after) ----


@pytest.fixture(scope="module")
def overloaded_plane():
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.services.server import ControlPlane

    plane = ControlPlane(
        SchedulingConfig(
            frontdoor_shards=2,
            frontdoor_tenant_rate=5.0, frontdoor_tenant_burst=5.0,
            frontdoor_global_rate=1000.0, frontdoor_global_burst=1000.0,
        ),
        cycle_period=0.1,
        fake_executors=[{"name": "fx", "nodes": 4, "runtime": 1.0}],
        lookout_port=0,
    ).start()
    yield plane
    plane.stop()


JOB = {"requests": {"cpu": "1", "memory": "1Gi"}}


def test_shed_maps_to_resource_exhausted_with_retry_after(overloaded_plane):
    from armada_tpu.services.grpc_api import ApiClient

    client = ApiClient(overloaded_plane.address, retry_budget_s=0.0)
    client.create_queue("team-a")
    error = None
    for _ in range(12):  # burst 5: the flood must shed
        try:
            client.submit_jobs("team-a", "s1", [JOB])
        except grpc.RpcError as e:
            error = e
            break
    assert error is not None
    assert error.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
    md = dict(error.trailing_metadata() or ())
    assert float(md["retry-after"]) > 0
    assert "retry after" in (error.details() or "")


def test_client_honors_retry_after_with_bounded_backoff(overloaded_plane):
    """The satellite: ApiClient retries a shed submit after the server's
    retry-after with the bounded jittered ExponentialBackoff instead of
    raw-raising — mirroring the executor-agent lease path."""
    from armada_tpu.services.grpc_api import ApiClient

    client = ApiClient(overloaded_plane.address, retry_budget_s=15.0)
    client.create_queue("team-b")
    # Exhaust the burst, then the retrying call must ride through.
    for _ in range(12):
        try:
            client.submit_jobs("team-b", "sx", [JOB])
        except grpc.RpcError:
            break
    started = time.monotonic()
    ids = client.submit_jobs("team-b", "sx", [JOB])
    assert ids and time.monotonic() - started > 0.05
    # A zero budget still raw-raises (opt-out preserved).
    raw = ApiClient(overloaded_plane.address, retry_budget_s=0.0)
    with pytest.raises(grpc.RpcError):
        for _ in range(12):
            raw.submit_jobs("team-b", "sx", [JOB])


def test_proto_client_honors_retry_after(overloaded_plane):
    from armada_tpu.proto import armada_pb2 as pb
    from armada_tpu.services.grpc_api import ProtoApiClient

    client = ProtoApiClient(overloaded_plane.address, retry_budget_s=15.0)
    item = pb.JobSubmitRequestItem()
    item.requests["cpu"] = "1"
    item.requests["memory"] = "1Gi"
    ok = 0
    for _ in range(12):
        ids = client.submit_jobs("team-a", "sp", [item])
        ok += len(ids)
    # Every call eventually landed (retried through shed windows).
    assert ok == 12


def test_client_deadline_propagates_and_drops_early(overloaded_plane):
    """The client's gRPC deadline reaches the server's enqueue stage: a
    slow store (simulated by delaying the submit service) pushes the
    handler past the propagated deadline, so the WAL append is never
    made — the client times out against a server that dropped the work
    whole, not one that half-applied it."""
    from armada_tpu.services.grpc_api import ApiClient

    client = ApiClient(overloaded_plane.address, retry_budget_s=0.0)
    client.create_queue("team-c")
    before = overloaded_plane.log.end_offset
    drops_before = dict(overloaded_plane.frontdoor.deadline_drops)

    class SlowSubmit:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def submit(self, *args, **kwargs):
            time.sleep(0.4)  # the deadline expires while we "write"
            return self._inner.submit(*args, **kwargs)

    api = overloaded_plane.api
    api.submit = SlowSubmit(api.submit)
    try:
        with pytest.raises(grpc.RpcError) as info:
            client.submit_jobs("team-c", "sd", [JOB], deadline_s=0.1)
        assert info.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
        time.sleep(0.6)  # let the server-side handler run to its drop
    finally:
        api.submit = api.submit._inner
    assert (
        overloaded_plane.frontdoor.deadline_drops["enqueue"]
        > drops_before.get("enqueue", 0)
    )
    for entry in overloaded_plane.log.read(before, 1000):
        for event in entry.sequence.events:
            assert not isinstance(event, SubmitJob) or (
                entry.sequence.queue != "team-c"
            ), "expired submit was half-applied"


def test_expired_deadline_drops_at_the_gate_over_the_wire(overloaded_plane):
    """An already-expired deadline in the request is refused before ANY
    processing (stage \"gate\") with DEADLINE_EXCEEDED."""
    from armada_tpu.services.grpc_api import ApiClient

    client = ApiClient(overloaded_plane.address, retry_budget_s=0.0)
    client.create_queue("team-g")
    gate_before = overloaded_plane.frontdoor.deadline_drops.get("gate", 0)
    with pytest.raises(grpc.RpcError) as info:
        client._call(
            "SubmitJobs",
            {"queue": "team-g", "jobset": "sg", "jobs": [JOB],
             "deadline_ts": time.time() - 1.0},
        )
    assert info.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    assert "gate" in (info.value.details() or "")
    assert overloaded_plane.frontdoor.deadline_drops["gate"] > gate_before


def test_lookout_frontdoor_view(overloaded_plane):
    import urllib.request

    port = overloaded_plane.lookout.port
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/frontdoor"
    ) as resp:
        doc = json.loads(resp.read())
    assert {s["shard"] for s in doc["shards"]} == {0, 1}
    tenants = {t["tenant"]: t for t in doc.get("tenants", ())}
    assert tenants and any(t["shed"] for t in tenants.values())


# ---- whole-sim differential ----


def test_sim_differential_frontdoor_matches_direct():
    """The sharded front door only delays visibility (by at most one
    pump); the final sim outcome is identical to direct publish."""
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        Simulator,
        WorkloadSpec,
    )

    def run(frontdoor):
        sim = Simulator(
            [ClusterSpec(name="c",
                         node_templates=(NodeTemplate(count=4, cpu="8"),))],
            WorkloadSpec(queues=(
                QueueSpecSim(name="qa", job_templates=(
                    JobTemplate(id="a", number=8, cpu="2"),
                    JobTemplate(id="b", number=6, cpu="2", submit_time=30.0,
                                gang_cardinality=2),
                )),
            )),
            backend="oracle", cycle_interval=10.0, max_time=4000.0,
            frontdoor=frontdoor,
        )
        result = sim.run()
        return (result.finished_jobs, result.total_jobs, result.placements)

    direct = run(None)
    sharded = run(3)
    assert direct[0] == direct[1] == sharded[0] == sharded[1]
    assert direct[2] == sharded[2]


# ---- soak slices (tools/frontdoor_soak.py; the full gate is the tool) ----


def _small_cfg(**overrides):
    from tools.frontdoor_soak import DEFAULTS

    cfg = dict(DEFAULTS)
    cfg.update({"jobs": 800, "tenants": 24, "shards": 3,
                "nodes_per_executor": 8})
    cfg["slo"] = dict(DEFAULTS["slo"])
    cfg.update(overrides)
    return cfg


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1])
def test_frontdoor_soak_subset(seed):
    """Tier-1 slice of the committed soak: 2 seeds, small scale, full
    chaos plan (torn WAL writes, a shard partition, mid-batch ingester
    crashes, the tenant flood). The SLO gate must pass."""
    from tools.frontdoor_soak import run_soak

    doc = run_soak(seed, _small_cfg())
    assert doc["breaches"] == [], doc
    assert doc["lost"] == 0 and doc["duplicates"] == 0
    assert doc["flood_shed"] > 0
    assert doc["shard_restarts"] > 0 or doc["wal_crashes"] > 0


@pytest.mark.chaos
def test_frontdoor_soak_deterministic_outcome():
    """Same seed, same virtual-clock outcome (acked/shed/fault counts) —
    chaos failures stay reproducible from a one-line seed."""
    from tools.frontdoor_soak import run_soak

    keys = ("acked", "shed", "expired", "faults_fired", "shard_restarts",
            "dups_suppressed", "wal_crashes", "makespan")
    a = run_soak(0, _small_cfg())
    b = run_soak(0, _small_cfg())
    assert {k: a[k] for k in keys} == {k: b[k] for k in keys}


@pytest.mark.chaos
def test_frontdoor_soak_inject_loss_trips_gate():
    """A seeded fault that DROPS one acked WAL entry must trip the gate
    nonzero — the zero-lost-acks invariant is load-bearing, not
    decorative."""
    from tools.frontdoor_soak import main, run_soak

    doc = run_soak(0, _small_cfg(), inject_loss=True)
    assert any("lost" in b for b in doc["breaches"]), doc
    rc = main(["--jobs", "400", "--tenants", "12", "--inject-loss"])
    assert rc != 0


@pytest.mark.slow
def test_frontdoor_soak_full_scale():
    """The committed-config gate at 10x scale, two seeds (the ~10M-job
    configuration is the same harness with --jobs 10000000)."""
    from tools.frontdoor_soak import run_soak

    cfg = _small_cfg(jobs=40_000, tenants=1000, shards=6,
                     nodes_per_executor=24)
    for seed in (0, 1):
        doc = run_soak(seed, cfg)
        assert doc["breaches"] == [], doc
