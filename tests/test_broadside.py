"""Broadside load bench: in-process backend end-to-end + report shape
(internal/broadside/orchestrator lifecycle, metrics/output.go report)."""

import json

from armada_tpu.clients.broadside import (
    BroadsideConfig,
    InprocBackend,
    OpStats,
    Runner,
)


def test_opstats_reset_and_snapshot():
    s = OpStats("x")
    for ms in (1, 2, 3):
        s.record(ms / 1000.0, units=10)
    s.error()
    snap = s.snapshot(wall_s=1.0)
    assert snap["ops"] == 3 and snap["errors"] == 1
    assert snap["units"] == 30 and snap["units_per_s"] == 30.0
    assert snap["p50_ms"] == 2.0 and snap["max_ms"] == 3.0
    s.reset()
    assert s.snapshot(1.0)["ops"] == 0


def test_inproc_backend_lifecycle_mix():
    cfg = BroadsideConfig(batch=20)
    backend = InprocBackend()
    try:
        backend.submit_batch("broadside-0", "bs", 20, cfg)
        # Pump the store to convergence.
        while backend.lag_events() > 0:
            pass
        groups = {g["name"]: g["count"] for g in backend.group_jobs("broadside-0")}
        # 60% succeed, 10% fail, 5% cancel (->1 of 20), rest running.
        assert groups.get("succeeded") == 12
        assert groups.get("failed") == 2
        assert groups.get("cancelled") == 1
        assert sum(groups.values()) == 20
        rows = backend.get_jobs("broadside-0")
        assert len(rows) == 20
        details = backend.job_details(backend.recent_ids[0])
        assert details is not None and details["job_id"] == backend.recent_ids[0]
    finally:
        backend.teardown()


def test_runner_report_shape():
    cfg = BroadsideConfig(
        duration_s=0.8,
        ingest_actors=1,
        query_actors=2,
        batch=10,
        queues=2,
        seed_jobs=20,
        warmup_s=0.2,
    )
    report = Runner(cfg).run()
    assert report["backend"] == "inproc"
    for op in ("ingest", "get_jobs", "group_jobs", "job_details"):
        assert "ops" in report[op] and "errors" in report[op]
    assert report["ingest"]["errors"] == 0
    assert report["ingest"]["ops"] > 0 and report["ingest"]["units"] > 0
    assert report["get_jobs"]["ops"] > 0
    json.dumps(report)  # must be JSON-serializable as emitted by the CLI


def test_overlapping_fractions_stay_disjoint():
    # succeed+fail+cancel fractions summing past 1 must not emit
    # conflicting terminal events for one job id.
    from armada_tpu.clients.broadside import InprocBackend

    cfg = BroadsideConfig(
        batch=10, succeed_fraction=0.8, fail_fraction=0.5, cancel_fraction=0.5
    )
    backend = InprocBackend()
    try:
        backend.submit_batch("q-frac", "js-frac", 10, cfg)
        seen = {}
        for entry in backend.log.read(0, 10_000):
            for ev in entry.sequence.events:
                kind = type(ev).__name__
                if kind in ("JobSucceeded", "JobErrors", "CancelJob"):
                    assert ev.job_id not in seen, (
                        f"{ev.job_id}: {seen[ev.job_id]} then {kind}"
                    )
                    seen[ev.job_id] = kind
        # 8 succeed, fail clamped to 2, cancel clamped to 0.
        kinds = sorted(seen.values())
        assert kinds.count("JobSucceeded") == 8
        assert kinds.count("JobErrors") == 2
        assert kinds.count("CancelJob") == 0
    finally:
        backend.teardown()
