"""Real pod lifecycle behind the executor seam: SubprocessPodRuntime runs
leases as actual OS processes (executor/job/submit.go creates pods; the
seam is ClusterContext), and NodeInfoService derives per-node pools/types
(executor/node/node_group.go)."""

import time

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.services.executor_agent import (
    ExecutorAgent,
    SubprocessPodRuntime,
)
from armada_tpu.services.grpc_api import ApiClient
from armada_tpu.services.node_info import NodeInfoService
from armada_tpu.services.server import ControlPlane


def _wait(pred, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


# ---- node classification (node_group.go) ----


def test_node_info_pool_label_and_reserved_suffix():
    svc = NodeInfoService(cluster_pool="cluster-a")
    assert svc.get_pool({"id": "n0"}) == "cluster-a"
    assert (
        svc.get_pool({"id": "n1", "labels": {"armadaproject.io/pool": "gpu"}})
        == "gpu"
    )
    # Reservation taint appends the reserved suffix (node_group.go:91-93).
    reserved = {
        "id": "n2",
        "labels": {"armadaproject.io/pool": "gpu"},
        "taints": [
            {"key": "armadaproject.io/reservation", "value": "team-x"}
        ],
    }
    assert svc.get_pool(reserved) == "gpu-reserved"
    assert NodeInfoService(
        cluster_pool="c", reserved_node_pool_suffix=""
    ).get_pool(reserved) == "gpu"


def test_node_info_type_from_label_or_taints():
    svc = NodeInfoService(tolerated_taints=("gpu", "special"))
    assert svc.get_type({"id": "n0"}) == "none"
    assert (
        svc.get_type(
            {"id": "n1", "labels": {"armadaproject.io/node-type": "a100"}}
        )
        == "a100"
    )
    # Tolerated taints identify the type; untolerated ones do not.
    assert (
        svc.get_type(
            {
                "id": "n2",
                "taints": [
                    {"key": "special", "value": "true"},
                    {"key": "gpu", "value": "true"},
                    {"key": "unrelated", "value": "x"},
                ],
            }
        )
        == "gpu,special"
    )
    groups = svc.group_nodes_by_type(
        [
            {"id": "a", "taints": [{"key": "gpu", "value": "1"}]},
            {"id": "b", "taints": [{"key": "gpu", "value": "1"}]},
            {"id": "c"},
        ]
    )
    assert sorted(groups) == ["gpu", "none"]
    assert [n["id"] for n in groups["gpu"]] == ["a", "b"]


def test_per_node_pools_reach_the_scheduler():
    """A single cluster spanning two pools: each node schedules only in
    its own pool (scheduling_algo union semantics with per-node pools)."""
    p = ControlPlane(SchedulingConfig(), cycle_period=0.05).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("mix")
        agent = ExecutorAgent(
            ApiClient(p.address),
            "mixed-exec",
            nodes=[
                {"id": "cpu-0", "total_resources": {"cpu": "8", "memory": "32Gi"}},
                {
                    "id": "gpu-0",
                    "total_resources": {"cpu": "8", "memory": "32Gi"},
                    "labels": {"armadaproject.io/pool": "gpu"},
                },
            ],
            pool="default",
        )
        agent.tick()
        assert _wait(
            lambda: "mixed-exec" in p.scheduler.executors
            and {n.pool for n in p.scheduler.executors["mixed-exec"].nodes}
            == {"default", "gpu"}
        )
    finally:
        p.stop()


# ---- real processes (submit.go / cluster context seam) ----


def _submit(client, queue, command, memory="32Mi"):
    return client.submit_jobs(
        queue,
        "set1",
        [
            {
                "priority": 0,
                "requests": {"cpu": "1", "memory": memory},
                "command": command,
            }
        ],
    )[0]


def test_subprocess_pod_runs_real_process(tmp_path):
    marker = tmp_path / "ran.txt"
    p = ControlPlane(SchedulingConfig(), cycle_period=0.05).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("real")
        agent = ExecutorAgent(
            ApiClient(p.address),
            "real-exec",
            nodes=[{"id": "rn-0", "total_resources": {"cpu": "8", "memory": "32Gi"}}],
            runtime=SubprocessPodRuntime(),
        )
        jid = _submit(
            client, "real",
            ["/bin/sh", "-c", f"echo done > {marker}"],
        )
        assert _wait(lambda: (agent.tick(), marker.exists())[1])
        assert _wait(
            lambda: (
                agent.tick(),
                p.scheduler.jobdb.get(jid).state.value == "succeeded",
            )[1]
        )
        assert marker.read_text().strip() == "done"
    finally:
        p.stop()


def test_subprocess_pod_failure_reports_rc_and_debug():
    p = ControlPlane(SchedulingConfig(), cycle_period=0.05).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("fail")
        agent = ExecutorAgent(
            ApiClient(p.address),
            "fail-exec",
            nodes=[{"id": "fn-0", "total_resources": {"cpu": "8", "memory": "32Gi"}}],
            runtime=SubprocessPodRuntime(),
        )
        jid = _submit(
            client, "fail",
            ["/bin/sh", "-c", "echo boom >&2; exit 3"],
        )

        def failed():
            agent.tick()
            job = p.scheduler.jobdb.get(jid)
            return job is not None and job.error
        assert _wait(failed)
        job = p.scheduler.jobdb.get(jid)
        assert "rc=3" in job.error and "boom" in job.error
        # The lookout view carries the run's debug dump.
        p.lookout_store.sync()
        row = p.lookout_store.get(jid)
        assert row.runs and '"rc": 3' in row.runs[-1].debug
    finally:
        p.stop()


def test_subprocess_rlimit_enforces_memory_request():
    """The kernel, not a simulation, enforces the memory request: a job
    allocating far beyond its request dies on RLIMIT_AS."""
    import sys

    p = ControlPlane(SchedulingConfig(), cycle_period=0.05).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("oom")
        agent = ExecutorAgent(
            ApiClient(p.address),
            "oom-exec",
            nodes=[{"id": "on-0", "total_resources": {"cpu": "8", "memory": "32Gi"}}],
            runtime=SubprocessPodRuntime(),
        )
        jid = _submit(
            client, "oom",
            [sys.executable, "-c", "x = bytearray(256 * 1024 * 1024)"],
            memory="64Mi",
        )

        def failed():
            agent.tick()
            job = p.scheduler.jobdb.get(jid)
            return job is not None and job.error
        assert _wait(failed)
        assert "rc=" in p.scheduler.jobdb.get(jid).error
    finally:
        p.stop()


def test_services_and_ingresses_share_pod_lifecycle(tmp_path):
    """executor/job/submit.go:110-140: services and ingresses are created
    alongside the pod (owner-referenced) and garbage-collected with it —
    end to end through submit -> lease -> runtime."""
    p = ControlPlane(SchedulingConfig(), cycle_period=0.05).start()
    try:
        client = ApiClient(p.address)
        client.create_queue("svc")
        agent = ExecutorAgent(
            ApiClient(p.address),
            "svc-exec",
            nodes=[{"id": "sn-0", "total_resources": {"cpu": "8", "memory": "32Gi"}}],
            runtime=SubprocessPodRuntime(),
        )
        jid = client.submit_jobs(
            "svc", "s1",
            [
                {
                    "requests": {"cpu": "1", "memory": "32Mi"},
                    "command": ["/bin/sh", "-c", "sleep 2"],
                    "services": [{"type": "NodePort", "ports": [8080]}],
                    "ingresses": [
                        {"ports": [8080],
                         "annotations": [["nginx", "true"]],
                         "tls_enabled": True}
                    ],
                }
            ],
        )[0]

        def created():
            agent.tick()
            return bool(agent.runtime.objects.services)
        assert _wait(created)
        run_id = next(iter(agent.runtime.objects.services))
        svc = agent.runtime.objects.services[run_id][0]
        assert svc["type"] == "NodePort" and svc["ports"] == [8080]
        ing = agent.runtime.objects.ingresses[run_id][0]
        assert ing["annotations"] == {"nginx": "true"} and ing["tls_enabled"]

        # Pod completes -> owner-reference GC removes both objects.
        assert _wait(
            lambda: (
                agent.tick(),
                p.scheduler.jobdb.get(jid).state.value == "succeeded",
            )[1]
        )
        assert run_id not in agent.runtime.objects.services
        assert run_id not in agent.runtime.objects.ingresses
    finally:
        p.stop()
