"""Hot-window compaction parity (solver/hotwindow.py).

The compacted pass-1 solve — gather the per-queue head windows plus the
active evicted set, run the unchanged kernel machinery over the window
axes, scatter back at chunk boundaries, re-gather on REWINDOW — must be
BIT-EXACT with the uncompacted kernel. Windows here are deliberately
tiny (2-4 slots against multi-hundred-slot rounds) so every round is
forced through many mid-pass rewindows, and the loop STREAM (not just
the final placement) is asserted against the uncompacted segmented
driver, which shares its loop accounting.
"""

import dataclasses

import numpy as np
import pytest

import jax

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, NodeSpec, QueueSpec, RunningJob
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round

ARRAY_KEYS = (
    "assigned_node",
    "scheduled_priority",
    "scheduled_mask",
    "preempted_mask",
    "fair_share",
    "demand_capped_fair_share",
    "uncapped_fair_share",
    "spot_price",
)


def _assert_bit_exact(a, b, label):
    for k in ARRAY_KEYS:
        assert np.array_equal(
            np.asarray(a[k]), np.asarray(b[k]), equal_nan=True
        ), f"{label}: {k} diverges"


def _dev(fast_fill=False, n_running=24, n_jobs=120, bw=4, gangs=3,
         with_snap=False):
    """A round exercising eviction + fair preemption (one hog queue over
    fair share), gangs with and without uniformity constraints, and
    enough queued stream per queue that a tiny window must rewindow."""
    cfg = SchedulingConfig(
        priority_classes={
            "high": PriorityClass("high", 30000, preemptible=False),
            "low": PriorityClass("low", 1000, preemptible=True),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
        batch_fill_window=bw,
        enable_fast_fill=fast_fill,
    )
    nodes = [
        NodeSpec(
            id=f"n{i:03d}",
            pool="default",
            total_resources={"cpu": "16", "memory": "64Gi"},
            labels={"zone": "a" if i % 2 else "b"},
        )
        for i in range(10)
    ]
    queues = [QueueSpec(f"q{i}", 1.0) for i in range(3)]
    rng = np.random.default_rng(1)
    queued = [
        JobSpec(
            id=f"j{i:04d}",
            queue=f"q{i % 3}",
            requests={"cpu": str(int(rng.choice([1, 2])))},
            submitted_ts=float(i),
        )
        for i in range(n_jobs)
    ]
    for k in range(gangs):
        gg = Gang(
            id=f"gg{k}",
            cardinality=4,
            node_uniformity_label="zone" if k % 2 else "",
        )
        for m in range(4):
            queued.append(
                JobSpec(
                    id=f"gang{k}-{m}",
                    queue="q1",
                    requests={"cpu": "2"},
                    submitted_ts=200.0 + k * 4 + m,
                    gang=gg,
                )
            )
    running = [
        RunningJob(
            job=JobSpec(
                id=f"r{i:04d}",
                queue="q0",
                priority_class="low",
                requests={"cpu": "2"},
                submitted_ts=float(-100 + i),
            ),
            node_id=f"n{i % 10:03d}",
            scheduled_at_priority=1000,
        )
        for i in range(n_running)
    ]
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    dev = pad_device_round(prep_device_round(snap))
    return (dev, snap) if with_snap else dev


@pytest.mark.parametrize(
    "fast_fill",
    [
        # Serial fill rides the slow marker: its windowed fill_step path
        # is already covered tier-1 by the mixed-fleet scenarios.
        pytest.param(False, marks=pytest.mark.slow),
        True,
    ],
)
def test_compacted_solve_bit_exact_with_forced_rewindows(fast_fill):
    """Evictions, fair preemption, gangs, uniformity search — compacted
    vs fused placement is bit-exact, and the pass-1 loop stream matches
    the uncompacted segmented driver exactly (same num_loops) across
    many forced mid-pass rewindows."""
    dev = _dev(fast_fill=fast_fill)
    fused = solve_round(dev)
    segmented = solve_round(dev, profile=True)  # host-driven, uncompacted
    windowed = solve_round(dev, window=4, window_min_slots=0)
    prof = windowed["profile"]
    assert prof["compacted"], "window did not engage — test is vacuous"
    assert prof["rewindows"] >= 1, "no mid-pass rewindow exercised"
    _assert_bit_exact(fused, windowed, f"fast_fill={fast_fill}")
    _assert_bit_exact(fused, segmented, f"segmented fast_fill={fast_fill}")
    # Identical decision STREAM, not just identical outcomes: the
    # untruncated host-driven drivers run loop-for-loop with the fused
    # program (the rescue pass only compiles into truncated rounds).
    assert int(fused["num_loops"]) == int(windowed["num_loops"])
    assert int(fused["num_loops"]) == int(segmented["num_loops"])
    assert not segmented["profile"]["compacted"]


def test_window_smaller_than_one_gang():
    """A 4-wide gang is ONE slot, so a 1-slot window must still place it
    atomically (the window is in slots, not jobs) — including the
    uniformity-search gangs."""
    dev = _dev(fast_fill=False, bw=1)
    fused = solve_round(dev)
    windowed = solve_round(dev, window=1, window_min_slots=0)
    assert windowed["profile"]["compacted"]
    _assert_bit_exact(fused, windowed, "window<gang")


def test_compacted_solve_bit_exact_mixed_fleet_and_market():
    """The dryrun scenario set: away pools (borrowed tainted nodes) and
    a market round (price-ordered, fill disabled). batch_fill_window is
    shrunk so the tiny window genuinely truncates the streams."""
    from armada_tpu.parallel.scenarios import mixed_fleet_rounds

    for label, snap in mixed_fleet_rounds(24, 96):
        snap = dataclasses.replace(
            snap, config=dataclasses.replace(snap.config, batch_fill_window=4)
        )
        dev = pad_device_round(prep_device_round(snap))
        fused = solve_round(dev)
        windowed = solve_round(dev, window=2, window_min_slots=0)
        assert windowed["profile"]["compacted"], label
        _assert_bit_exact(fused, windowed, label)


def test_budgeted_window_truncates_to_prefix():
    """Round budget + compaction compose: a generous budget matches the
    unbudgeted solve, a tiny budget commits a prefix of it."""
    dev = _dev(fast_fill=True)
    full = solve_round(dev, window=4, window_min_slots=0)
    generous = solve_round(dev, window=4, window_min_slots=0, budget_s=120.0)
    assert not generous["truncated"]
    _assert_bit_exact(full, generous, "generous budget")
    cut = solve_round(dev, window=4, window_min_slots=0, budget_s=1e-6)
    assert cut["truncated"]
    placed = np.flatnonzero(cut["scheduled_mask"])
    assert np.asarray(full["scheduled_mask"])[placed].all()
    assert (
        np.asarray(cut["assigned_node"])[placed]
        == np.asarray(full["assigned_node"])[placed]
    ).all()


def test_tiny_round_disengages():
    """A round the window axes cannot shrink runs the fused program
    (profile reports compaction off; result identical)."""
    dev = _dev(fast_fill=False, n_jobs=12, n_running=0, gangs=0, bw=0)
    fused = solve_round(dev)
    windowed = solve_round(dev, window=2048, window_min_slots=0, profile=True)
    assert not windowed["profile"]["compacted"]
    _assert_bit_exact(fused, windowed, "disengaged")


def test_sim_differential_compacted_vs_uncompacted():
    """Whole-simulator differential (the test_sim_differential.py
    pattern, seed 0): the same workload driven through the service loop
    with compaction forced on (tiny fill window + tiny hot window, so
    real rounds gather/rewindow) must produce the identical fleet
    history — states, placements, preemptions — as compaction off."""
    from armada_tpu.sim import (
        ClusterSpec,
        JobTemplate,
        QueueSpecSim,
        Simulator,
        WorkloadSpec,
    )
    from armada_tpu.sim.simulator import NodeTemplate, ShiftedExponential

    def run(hot_window):
        cfg = SchedulingConfig(
            priority_classes={
                "high": PriorityClass("high", 30000, preemptible=False),
                "low": PriorityClass("low", 1000, preemptible=True),
            },
            default_priority_class="low",
            protected_fraction_of_fair_share=0.5,
            batch_fill_window=2,
            hot_window_slots=hot_window,
            hot_window_min_slots=0,
        )
        sim = Simulator(
            [
                ClusterSpec(
                    "c1",
                    node_templates=(
                        NodeTemplate(count=6, cpu="16", memory="64Gi"),
                    ),
                )
            ],
            WorkloadSpec(
                queues=(
                    QueueSpecSim(
                        "steady",
                        job_templates=(
                            JobTemplate(
                                id="long", number=24, cpu="2", memory="4Gi",
                                runtime=ShiftedExponential(minimum=200.0),
                            ),
                        ),
                    ),
                    QueueSpecSim(
                        "bursty",
                        job_templates=(
                            JobTemplate(
                                id="gangs", number=8, cpu="4", memory="4Gi",
                                gang_cardinality=4, submit_time=50.0,
                                runtime=ShiftedExponential(minimum=100.0),
                            ),
                        ),
                    ),
                )
            ),
            config=cfg,
            backend="kernel",
            seed=0,
            max_time=1500.0,
        )
        res = sim.run()
        return {
            "states": {k: v.value for k, v in res.events_by_job.items()},
            "placements": res.placements,
            "preemptions": res.preemptions,
            "finished": res.finished_jobs,
        }

    off = run(0)
    on = run(2)
    assert off == on
    assert off["finished"] > 0


def test_window_size_autotuning(tmp_path):
    """Closer for the hot-window-autotune gap: window sizing is no
    longer static config. OFFLINE, the tuner (armada_tpu/autotune)
    searches candidate windows over a recorded corpus, requiring
    bit-exact replay, and selects a vector; ONLINE, the controller
    grows a starved window (high rewindow rate) and shrinks an
    oversized one (gather-dominated, zero rewindows) with hysteresis —
    and every adopted window still solves bit-exactly, because the
    window is a perf-only knob by construction."""
    from armada_tpu.autotune import AutotuneController, TunedParams, tune_corpus
    from armada_tpu.autotune.controller import REWINDOW_HIGH
    from armada_tpu.trace import TraceRecorder, load_trace

    dev, snap = _dev(fast_fill=True, with_snap=True)
    fused = solve_round(dev)

    # ---- offline: record one real round, tune a tiny grid over it.
    path = str(tmp_path / "corpus.atrace")
    with TraceRecorder(path, source="test", config=snap.config) as rec:
        rec.record_round(
            pool=snap.pool, dev=dev, decisions=fused,
            num_jobs=snap.num_jobs, num_queues=snap.num_queues,
            config=snap.config, solver={"backend": "kernel"},
        )
    report = tune_corpus(
        [load_trace(path)],
        [TunedParams(2, 0, 1), TunedParams(8, 0, 1)],
        repeats=1,
    )
    assert report["ok"], report["results"]
    selected = TunedParams.from_dict(report["selected"]["params"])
    tuned_out = solve_round(
        dev,
        window=selected.hot_window_slots or None,
        window_min_slots=selected.hot_window_min_slots,
    )
    _assert_bit_exact(fused, tuned_out, "offline-selected")

    # ---- online: the hill-climb reacts to REAL solve profiles.
    ctl = AutotuneController(
        SchedulingConfig(
            hot_window_slots=2, hot_window_min_slots=0,
            batch_fill_window=2,  # lookahead floor below the test range
            autotune_enabled=True, autotune_hysteresis_rounds=2,
            autotune_min_window_slots=2, autotune_max_window_slots=64,
        )
    )
    starved = solve_round(dev, window=2, window_min_slots=0)["profile"]
    assert starved["compacted"]
    assert starved["rewindows"] >= REWINDOW_HIGH, starved
    assert ctl.observe_round("default", starved) is None  # hysteresis
    adopted = ctl.observe_round("default", starved)
    assert adopted is not None and adopted["direction"] == "grow"
    assert ctl.params_for("default").hot_window_slots == 4
    # An oversized window (gather dominates, nothing rewinds) shrinks
    # back — after the cooldown, with the same hysteresis.
    fat = {"compacted": True, "rewindows": 0, "gather_s": 0.3, "pass1_s": 0.1}
    observed = [ctl.observe_round("default", fat) for _ in range(4)]
    shrunk = [a for a in observed if a is not None]
    assert len(shrunk) == 1 and shrunk[0]["direction"] == "shrink"
    assert ctl.params_for("default").hot_window_slots == 2
    # The adopted window is still bit-exact with the fused kernel.
    adopted_out = solve_round(
        dev, window=ctl.params_for("default").hot_window_slots,
        window_min_slots=0,
    )
    _assert_bit_exact(fused, adopted_out, "online-adopted")


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)
def test_compacted_solve_matches_two_level_mesh():
    """Compaction composes with the node-sharded solve: the job/slot
    axes it compacts were never sharded, so the compacted single-device
    result must equal the 2x4 HierarchicalDist mesh solve bit-for-bit
    (both equal the fused single-device kernel)."""
    from armada_tpu.parallel.mesh import pad_nodes
    from armada_tpu.parallel.multihost import (
        hierarchical_sharded_solve,
        make_host_mesh,
    )
    from armada_tpu.parallel.scenarios import home_away_round

    snap = home_away_round(24, 64)
    snap = dataclasses.replace(
        snap, config=dataclasses.replace(snap.config, batch_fill_window=2)
    )
    dev = pad_nodes(pad_device_round(prep_device_round(snap)), 8)
    windowed = solve_round(dev, window=2, window_min_slots=0)
    assert windowed["profile"]["compacted"]
    mesh = hierarchical_sharded_solve(make_host_mesh(2, 4))(dev)
    _assert_bit_exact(windowed, mesh, "2x4-vs-window")
