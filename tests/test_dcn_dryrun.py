"""The multi-process DCN parity dryrun, wired as a slow-marked test.

tools/dcn_dryrun.py boots 2 REAL host processes (jax.distributed +
gloo) owning a (2 hosts, 4 chips) mesh and asserts bit-exact parity of
the two-level HierarchicalDist solve against the single-device solve on
the mixed-fleet scenarios. Each worker compiles its own sharded program
from scratch (separate processes, minutes on this box), so the run is
slow-marked: tier-1 stays fast, `ARMADA_FULL_SUITE=1` (or running the
tool directly) exercises genuine inter-process DCN traffic.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dcn_dryrun_2x4_parity():
    # A subprocess (not launcher.launch in-process): the coordinator must
    # not inherit this suite's initialized jax backend or its 8-device
    # XLA_FLAGS — the tool owns its workers' env end to end.
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_NUM_CPU_DEVICES")
    }
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "dcn_dryrun.py"),
            "--hosts", "2",
            "--chips", "4",
            "--nodes", "256",
            "--jobs", "1024",
            "--timeout", "1200",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=1500,
    )
    # The tool prints exactly one machine-readable JSON line on stdout.
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert lines, f"no JSON line on stdout; stderr tail: {proc.stderr[-2000:]}"
    result = json.loads(lines[-1])
    assert proc.returncode == 0 and result["ok"], (
        f"DCN dryrun failed: {json.dumps(result)[:4000]}"
    )
    assert not result["timed_out"]
    assert result["hosts"] == 2 and result["chips"] == 4
    # Every worker saw bit-exact parity on every round.
    for w in result["workers"]:
        assert w["ok"], w
        assert {r["round"] for r in w["rounds"]} == {"home_away", "market"}
        assert all(r["mismatch"] == [] for r in w["rounds"])
    # The measured DCN bill: one winner tuple per host per select.
    coll = result["collectives"]
    assert coll["n_hosts"] == 2 and coll["n_chips"] == 4
    assert 0 < coll["per_select_dcn_scalars"] < coll["per_select_ici_scalars"] * 2
    assert coll["dcn_bytes"] < coll["ici_bytes"]
