"""Self-healing solve path: the round admission firewall
(solver/validate.py) fuzzed over the recorded steady-state fixture, and
the solver backend failover ladder (solver/failover.py) unit-tested —
breaker lifecycle, terminal fallback, budget-bounded retries.

The fixture fuzz mirrors the solver-fault chaos corruptions
(services/chaos.SolverChaos): seeded NaN/inf poisoning and
wrong-placement perturbations over real recorded rounds, asserting each
mutation classifies as the RIGHT invariant — a misclassified rejection
would send an operator chasing the wrong failure mode from the
postmortem bundle's filename.
"""

import os
import types

import numpy as np
import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.solver.failover import FailoverLadder, build_ladder
from armada_tpu.solver.validate import (
    INVARIANTS,
    RoundViolation,
    validate_round,
)

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "sim_steady.atrace"
)


def _rounds():
    from armada_tpu.trace import load_trace

    trace = load_trace(FIXTURE)
    rounds = [r for r in trace.rounds if not r.truncated]
    assert rounds, "fixture carries no replayable rounds"
    return rounds


def _copy_decisions(rec) -> dict:
    return {k: np.array(v, copy=True) for k, v in rec.decisions().items()}


# ------------------------------------------------------ admission firewall


def test_firewall_admits_every_recorded_round():
    """Every committed round in the fixture passes the full invariant
    set — the firewall must never reject legitimate solver output."""
    for rec in _rounds():
        v = validate_round(
            _copy_decisions(rec), dev=rec.device_round(),
            num_jobs=rec.num_jobs,
        )
        assert v is None, f"round {rec['i']}: {v}"


def test_firewall_fuzz_classifies_corruption():
    """Seeded NaN/inf + wrong-placement mutations over the fixture's
    rounds: each corruption family must classify as its own invariant,
    on every round it applies to."""
    rng = np.random.default_rng(20260807)
    hit: set[str] = set()
    for rec in _rounds():
        dev = rec.device_round()
        J = rec.num_jobs
        N = int(np.asarray(dev.node_total).shape[0])
        running = np.asarray(dev.job_is_running, dtype=bool)[:J]

        def verdict(mutate) -> RoundViolation | None:
            d = _copy_decisions(rec)
            mutate(d)
            return validate_round(d, dev=dev, num_jobs=J)

        # NaN poison, the SolverChaos corruption verbatim.
        def nan_poison(d, _rng=rng):
            fs = d["fair_share"]
            fs.flat[int(_rng.integers(max(fs.size, 1)))] = np.nan

        v = verdict(nan_poison)
        assert v is not None and v.invariant == "nan_inf", v
        hit.add("nan_inf")

        # Inf in a share tensor is corruption too (an unguarded x/0).
        def inf_poison(d, _rng=rng):
            us = d["uncapped_fair_share"]
            us.flat[int(_rng.integers(max(us.size, 1)))] = np.inf

        v = verdict(inf_poison)
        assert v is not None and v.invariant == "nan_inf", v

        # Wrong placement: a scheduled job pointing outside the node
        # table (the SolverChaos perturbation `-2 - assigned`).
        j = int(rng.integers(J))

        def bad_node(d, j=j):
            d["scheduled_mask"][j] = True
            d["assigned_node"][j] = -2 - int(d["assigned_node"][j])

        v = verdict(bad_node)
        assert v is not None and v.invariant == "invalid_node", v
        hit.add("invalid_node")

        if N > 0:
            # One job, two bindings in one round.
            def both_bound(d, j=j):
                d["scheduled_mask"][j] = True
                d["assigned_node"][j] = 0
                d["preempted_mask"][j] = True

            v = verdict(both_bound)
            assert v is not None and v.invariant == "double_bound", v
            hit.add("double_bound")

            if running.any():
                r = int(np.flatnonzero(running)[0])

                def rebind_running(d, r=r):
                    d["scheduled_mask"][r] = True
                    d["assigned_node"][r] = 0
                    d["preempted_mask"][r] = False

                v = verdict(rebind_running)
                assert v is not None and v.invariant == "double_bound", v

        if (~running).any():
            q = int(np.flatnonzero(~running)[0])

            def victimless(d, q=q):
                d["preempted_mask"][q] = True
                d["scheduled_mask"][q] = False

            v = verdict(victimless)
            assert (
                v is not None and v.invariant == "preemption_victim"
            ), v
            hit.add("preemption_victim")

    assert {"nan_inf", "invalid_node", "double_bound",
            "preemption_victim"} <= hit


def test_firewall_gang_and_capacity_invariants():
    """gang_atomicity and node_over_capacity on a hand-built round (the
    fixture's steady rounds carry no conveniently torn gangs)."""
    dev = types.SimpleNamespace(
        job_is_running=np.array([True, True, False, False]),
        job_node=np.array([0, 0, -1, -1]),
        # one resource column; node 0 holds 4, node 1 holds 2
        node_total=np.array([[4], [2]]),
        job_req_fit=np.array([[2], [2], [2], [2]]),
        # slot 0: gang of jobs 0+1; slots for singletons 2, 3
        slot_members=np.array([[0, 1], [2, -1], [3, -1]]),
        slot_count=np.array([2, 1, 1]),
    )

    def decisions(**kw):
        d = {
            "assigned_node": np.array([0, 0, 0, 0]),
            "scheduled_mask": np.zeros(4, dtype=bool),
            "preempted_mask": np.zeros(4, dtype=bool),
            "fair_share": np.zeros(4),
            "demand_capped_fair_share": np.zeros(4),
            "uncapped_fair_share": np.zeros(4),
        }
        d.update(kw)
        return d

    assert validate_round(decisions(), dev=dev, num_jobs=4) is None

    # Torn gang eviction: one of two members preempted.
    v = validate_round(
        decisions(preempted_mask=np.array([True, False, False, False])),
        dev=dev, num_jobs=4,
    )
    assert v is not None and v.invariant == "gang_atomicity", v

    # Torn gang placement... but via the SCHEDULED mask: evict the whole
    # gang and re-place only half of it.
    v = validate_round(
        decisions(
            preempted_mask=np.array([True, True, False, False]),
            scheduled_mask=np.array([False, False, True, False]),
            assigned_node=np.array([0, 0, 1, 0]),
        ),
        dev=dev, num_jobs=4,
    )
    assert v is None  # gang fully evicted + singleton placed: legal

    # Overstuffed node: both queued singletons land on node 1 (cap 2)
    # next to nothing evicted — 4 > 2.
    v = validate_round(
        decisions(
            scheduled_mask=np.array([False, False, True, True]),
            assigned_node=np.array([0, 0, 1, 1]),
        ),
        dev=dev, num_jobs=4,
    )
    assert v is not None and v.invariant == "node_over_capacity", v

    # The same placement is legal once node 0's gang frees its capacity
    # on node 0 — and node 1 gets only one newcomer.
    v = validate_round(
        decisions(
            preempted_mask=np.array([True, True, False, False]),
            scheduled_mask=np.array([False, False, True, True]),
            assigned_node=np.array([0, 0, 1, 0]),
        ),
        dev=dev, num_jobs=4,
    )
    assert v is None, v


def test_firewall_fairness_ledger_invariant():
    ok = {"ledger": {"queues": [
        {"fair_share": 0.5, "delivered_share": 0.5, "regret": 0.0},
        {"fair_share": 0.5, "delivered_share": 0.4, "regret": 0.1},
    ]}}
    assert validate_round(
        {"assigned_node": np.zeros(0, dtype=int),
         "scheduled_mask": np.zeros(0, dtype=bool),
         "preempted_mask": np.zeros(0, dtype=bool),
         "fair_share": np.zeros(0),
         "demand_capped_fair_share": np.zeros(0),
         "uncapped_fair_share": np.zeros(0)},
        num_jobs=0, fairness=ok,
    ) is None
    bad = {"ledger": {"queues": [
        {"fair_share": float("nan"), "delivered_share": 0.5},
    ]}}
    v = validate_round(
        {"assigned_node": np.zeros(0, dtype=int),
         "scheduled_mask": np.zeros(0, dtype=bool),
         "preempted_mask": np.zeros(0, dtype=bool),
         "fair_share": np.zeros(0),
         "demand_capped_fair_share": np.zeros(0),
         "uncapped_fair_share": np.zeros(0)},
        num_jobs=0, fairness=bad,
    )
    assert v is not None and v.invariant == "fairness_ledger", v
    over = {"ledger": {"queues": [
        {"delivered_share": 0.7}, {"delivered_share": 0.7},
    ]}}
    v = validate_round(
        {"assigned_node": np.zeros(0, dtype=int),
         "scheduled_mask": np.zeros(0, dtype=bool),
         "preempted_mask": np.zeros(0, dtype=bool),
         "fair_share": np.zeros(0),
         "demand_capped_fair_share": np.zeros(0),
         "uncapped_fair_share": np.zeros(0)},
        num_jobs=0, fairness=over,
    )
    assert v is not None and v.invariant == "fairness_ledger", v


def test_invariant_names_are_closed():
    """Every invariant the firewall can emit is declared in INVARIANTS —
    the metric label set and the postmortem filenames key off it."""
    assert set(INVARIANTS) == {
        "nan_inf", "invalid_node", "double_bound", "preemption_victim",
        "gang_atomicity", "node_over_capacity", "fairness_ledger",
    }


# ------------------------------------------------------- failover ladder


def test_build_ladder_shapes():
    cfg = SchedulingConfig()
    kernel = build_ladder("kernel", None, cfg)
    assert [r.label for r in kernel] == ["LOCAL", "hotwindow:64", "oracle"]
    assert kernel[-1].kind == "oracle"
    meshed = build_ladder("kernel", "2x4", cfg)
    assert [r.label for r in meshed] == [
        "mesh:2x4", "LOCAL", "hotwindow:64", "oracle",
    ]
    oracle = build_ladder("oracle", None, cfg)
    assert [r.label for r in oracle] == ["oracle"]
    # The degraded-retry rung is a FIXED small window, independent of the
    # configured hot window: it must re-jit a DIFFERENT program than the
    # primary, or a poisoned executable would poison the retry too.
    big = SchedulingConfig(hot_window_slots=4096)
    assert build_ladder("kernel", None, big)[1].param == 64


def test_ladder_breaker_lifecycle():
    cfg = SchedulingConfig()
    ladder = FailoverLadder(
        build_ladder("kernel", None, cfg),
        failure_threshold=2, cooldown_rounds=3,
    )
    live, probes = ladder.plan(0)
    assert [r.label for r in live] == ["LOCAL", "hotwindow:64", "oracle"]
    assert probes == []
    # Two consecutive failures open LOCAL; it leaves the live list.
    ladder.record_failure("LOCAL", 0)
    ladder.record_failure("LOCAL", 1)
    assert ladder.state("LOCAL", 1) == "open"
    live, probes = ladder.plan(2)
    assert [r.label for r in live] == ["hotwindow:64", "oracle"]
    assert probes == []
    # After the cooldown the rung goes half-open: offered as a SHADOW
    # probe, still not live.
    live, probes = ladder.plan(5)
    assert [r.label for r in live] == ["hotwindow:64", "oracle"]
    assert [r.label for r in probes] == ["LOCAL"]
    # A clean probe restores it to the live ladder.
    ladder.record_success("LOCAL", 5)
    live, probes = ladder.plan(6)
    assert [r.label for r in live] == ["LOCAL", "hotwindow:64", "oracle"]
    assert probes == []
    # A FAILED probe re-opens for another full cooldown.
    ladder.record_failure("LOCAL", 6)
    ladder.record_failure("LOCAL", 7)
    live, probes = ladder.plan(8)
    assert [r.label for r in live] == ["hotwindow:64", "oracle"]
    _, probes = ladder.plan(11)
    assert [r.label for r in probes] == ["LOCAL"]
    ladder.record_failure("LOCAL", 11)
    live, probes = ladder.plan(12)
    assert [r.label for r in live] == ["hotwindow:64", "oracle"]
    assert probes == []


def test_ladder_terminal_rung_always_offered():
    """Even with EVERY breaker open — terminal included — the plan still
    offers the oracle: the ladder can reject a round, never strand it."""
    cfg = SchedulingConfig()
    ladder = FailoverLadder(
        build_ladder("kernel", None, cfg),
        failure_threshold=1, cooldown_rounds=100,
    )
    for rung in ("LOCAL", "hotwindow:64", "oracle"):
        ladder.record_failure(rung, 0)
        assert ladder.state(rung, 0) == "open"
    live, probes = ladder.plan(1)
    assert [r.label for r in live] == ["oracle"]
    assert probes == []
    snap = ladder.snapshot(1)
    assert [row["terminal"] for row in snap] == [False, False, True]
    assert all(row["state"] == "open" for row in snap)


def test_solve_budget_bounds_failover_retries(monkeypatch):
    """With the round budget exhausted, a failed primary does NOT walk
    the rest of the ladder — the round rejects and work stays queued."""
    import time as _time

    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.scheduler import SchedulerService

    cfg = SchedulingConfig()
    sched = SchedulerService(cfg, InMemoryEventLog(), backend="kernel")
    assert sched.failover is not None
    calls = []

    def failing_attempt(snap, rung, **kw):
        calls.append(rung.label)
        raise RuntimeError("injected solve fault")

    monkeypatch.setattr(sched, "_attempt_round", failing_attempt)
    snap = types.SimpleNamespace(pool="default")

    # No deadline: every live rung is tried before the round rejects.
    sched._round_deadline = None
    assert sched._solve(snap) is None
    assert calls == ["LOCAL", "hotwindow:64", "oracle"]

    # Deadline already blown: only the primary runs; retries are skipped.
    calls.clear()
    sched.failover = FailoverLadder(
        build_ladder("kernel", None, cfg)
    )  # fresh breakers
    sched._round_deadline = _time.monotonic() - 1.0
    assert sched._solve(snap) is None
    assert calls == ["LOCAL"]


def test_solve_failover_attribution(monkeypatch):
    """A round that fails over carries {from,to,cause} attribution, and
    the rejection/failover ledgers the doctor surfaces read are fed."""
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.scheduler import SchedulerService

    cfg = SchedulingConfig()
    sched = SchedulerService(cfg, InMemoryEventLog(), backend="kernel")
    sched._round_deadline = None

    def flaky_attempt(snap, rung, **kw):
        if rung.label == "LOCAL":
            raise RuntimeError("injected solve fault")
        return {"scheduled_mask": np.zeros(0, dtype=bool)}

    monkeypatch.setattr(sched, "_attempt_round", flaky_attempt)
    result = sched._solve(types.SimpleNamespace(pool="default"))
    assert result is not None
    assert result["failover"] == {
        "from": "LOCAL", "to": "hotwindow:64", "cause": "raise",
    }
    fo = list(sched.recent_failovers)
    assert fo and fo[-1]["from"] == "LOCAL"
    assert fo[-1]["to"] == "hotwindow:64" and fo[-1]["cause"] == "raise"
    doc = sched.doctor_report()
    assert doc["failover_enabled"] and doc["validation_enabled"]
    assert [row["rung"] for row in doc["ladder"]] == [
        "LOCAL", "hotwindow:64", "oracle",
    ]
    assert doc["ladder"][0]["consecutive_failures"] == 1
