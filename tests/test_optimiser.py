"""Fairness-optimising post-pass (scheduling/optimiser/).

The first test ports the Go table case named 'optimiser' from
preempting_queue_scheduler_test.go:174-217; the rest pin the pass's
gates: improvement threshold, per-round job bound, non-preemptible and
gang victims excluded."""

import numpy as np

from armada_tpu.core.config import OptimiserConfig, PriorityClass, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, NodeSpec, QueueSpec, RunningJob, Taint
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.optimiser import optimise_round
from armada_tpu.solver.reference import ReferenceSolver

from test_kernel_parity import assert_parity

CFG = SchedulingConfig(
    priority_classes={
        "priority-2": PriorityClass("priority-2", 2, preemptible=True),
        "priority-3": PriorityClass("priority-3", 3, preemptible=False),
    },
    default_priority_class="priority-2",
    protected_fraction_of_fair_share=1.0,
)

OPT = OptimiserConfig(enabled=True, min_fairness_improvement_pct=10.0)


def _nodes():
    # One tainted 32-cpu node (largeJobsOnly) + one untainted, as in the Go
    # case (NTainted32CpuNodes + N32CpuNodes).
    return [
        NodeSpec(
            id="tainted-0",
            pool="default",
            taints=(Taint("largeJobsOnly", "true"),),
            total_resources={"cpu": "32", "memory": "256Gi"},
        ),
        NodeSpec(
            id="node-0",
            pool="default",
            total_resources={"cpu": "32", "memory": "256Gi"},
        ),
    ]


def _solve(cfg, nodes, queues, running, queued, opt=None):
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    snap, oracle, out = assert_parity(cfg, nodes, queues, running, queued, "opt")
    result = {
        "assigned_node": oracle.assigned_node.copy(),
        "scheduled_mask": oracle.scheduled_mask.copy(),
        "preempted_mask": oracle.preempted_mask.copy(),
        "scheduled_priority": oracle.scheduled_priority.copy(),
        "demand_capped_fair_share": oracle.demand_capped_fair_share.copy(),
    }
    decisions = optimise_round(snap, result, opt) if opt else []
    return snap, result, decisions


def test_optimiser_go_table_case():
    """Go: 'optimiser' (preempting_queue_scheduler_test.go:174)."""
    nodes = _nodes()
    queues = [QueueSpec("A", 1.0), QueueSpec("B", 1.0)]

    # Round 1: A's 1-cpu job schedules (on the untainted node).
    snap, r1, _ = _solve(CFG, nodes, queues, [], [
        JobSpec(id="a0", queue="A", priority_class="priority-2",
                requests={"cpu": "1", "memory": "4Gi"}, submitted_ts=1.0),
    ])
    assert r1["scheduled_mask"].sum() == 1
    a_node = snap.node_ids[int(r1["assigned_node"][0])]
    assert a_node == "node-0"
    running = [
        RunningJob(
            job=JobSpec(id="a0", queue="A", priority_class="priority-2",
                        requests={"cpu": "1", "memory": "4Gi"}, submitted_ts=1.0),
            node_id=a_node,
            scheduled_at_priority=2,
        )
    ]
    b_job = JobSpec(id="b0", queue="B", priority_class="priority-2",
                    requests={"cpu": "32", "memory": "256Gi"}, submitted_ts=2.0)

    # Round 2: optimiser OFF — B's whole-node job cannot schedule (A is
    # protected; B tolerates no taint).
    snap, r2, _ = _solve(CFG, nodes, queues, running, [b_job])
    assert r2["scheduled_mask"].sum() == 0
    assert r2["preempted_mask"].sum() == 0

    # Round 3: optimiser ON — A's 1-cpu job is preempted for a ~3100%
    # fairness improvement, B schedules.
    snap, r3, decisions = _solve(CFG, nodes, queues, running, [b_job], opt=OPT)
    assert len(decisions) == 1
    j_b = snap.job_ids.index("b0")
    j_a = snap.job_ids.index("a0")
    assert r3["scheduled_mask"][j_b]
    assert r3["preempted_mask"][j_a]
    assert snap.node_ids[int(r3["assigned_node"][j_b])] == "node-0"


def test_optimiser_improvement_threshold():
    """No action when the fairness gain is below the threshold."""
    nodes = _nodes()
    queues = [QueueSpec("A", 1.0), QueueSpec("B", 1.0)]
    running = [
        RunningJob(
            job=JobSpec(id="a0", queue="A", priority_class="priority-2",
                        requests={"cpu": "1", "memory": "4Gi"}, submitted_ts=1.0),
            node_id="node-0",
            scheduled_at_priority=2,
        )
    ]
    b_job = JobSpec(id="b0", queue="B", priority_class="priority-2",
                    requests={"cpu": "32", "memory": "256Gi"}, submitted_ts=2.0)
    opt = OptimiserConfig(enabled=True, min_fairness_improvement_pct=10_000.0)
    snap, out, decisions = _solve(CFG, nodes, queues, running, [b_job], opt=opt)
    assert decisions == []
    j_a = snap.job_ids.index("a0")
    assert not out["preempted_mask"][j_a]


def test_optimiser_respects_jobs_per_round():
    nodes = [
        NodeSpec(id="n0", pool="default",
                 total_resources={"cpu": "4", "memory": "16Gi"})
    ]
    queues = [QueueSpec("A", 1.0), QueueSpec("B", 1.0)]
    running = [
        RunningJob(
            job=JobSpec(id=f"a{i}", queue="A", priority_class="priority-2",
                        requests={"cpu": "1", "memory": "1Gi"},
                        submitted_ts=float(i)),
            node_id="n0",
            scheduled_at_priority=2,
        )
        for i in range(4)
    ]
    queued = [
        JobSpec(id=f"b{i}", queue="B", priority_class="priority-2",
                requests={"cpu": "2", "memory": "2Gi"}, submitted_ts=10.0 + i)
        for i in range(2)
    ]
    opt = OptimiserConfig(enabled=True, maximum_jobs_per_round=1)
    snap, out, decisions = _solve(CFG, nodes, queues, running, queued, opt=opt)
    assert sum(len(d.scheduled) for d in decisions) <= 1


def test_optimiser_never_evicts_non_preemptible_or_gangs():
    nodes = [
        NodeSpec(id="n0", pool="default",
                 total_resources={"cpu": "4", "memory": "16Gi"})
    ]
    queues = [QueueSpec("A", 1.0), QueueSpec("B", 1.0)]
    gang = Gang(id="g", cardinality=2)
    running = [
        RunningJob(
            job=JobSpec(id="np0", queue="A", priority_class="priority-3",
                        requests={"cpu": "2", "memory": "2Gi"}, submitted_ts=1.0),
            node_id="n0",
            scheduled_at_priority=3,
        ),
        RunningJob(
            job=JobSpec(id="g0", queue="A", priority_class="priority-2",
                        requests={"cpu": "1", "memory": "1Gi"},
                        submitted_ts=2.0, gang=gang),
            node_id="n0",
            scheduled_at_priority=2,
        ),
        RunningJob(
            job=JobSpec(id="g1", queue="A", priority_class="priority-2",
                        requests={"cpu": "1", "memory": "1Gi"},
                        submitted_ts=3.0, gang=gang),
            node_id="n0",
            scheduled_at_priority=2,
        ),
    ]
    queued = [
        JobSpec(id="b0", queue="B", priority_class="priority-2",
                requests={"cpu": "2", "memory": "2Gi"}, submitted_ts=10.0)
    ]
    opt = OptimiserConfig(enabled=True)
    snap, out, decisions = _solve(CFG, nodes, queues, running, queued, opt=opt)
    # Only non-evictable work on the node: the optimiser must do nothing.
    assert decisions == []
