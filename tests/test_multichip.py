"""Multi-chip parity: the node-sharded shard_map solve must produce exactly
the single-device solve's outputs on the same snapshot.

Mirrors the reference's multi-cluster union semantics
(scheduling_algo.go:135-147): partitioning nodes across shards must not
change any placement. The 8-device CPU mesh stands in for an 8-chip slice
(conftest forces xla_force_host_platform_device_count=8)."""

import os

import numpy as np
import pytest

import jax

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import Gang, JobSpec, NodeSpec, QueueSpec, RunningJob
from armada_tpu.parallel.mesh import (
    make_node_mesh,
    node_sharded_solve,
    pad_nodes,
)
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round

from test_kernel_parity import PREEMPT_CFG, rand_scenario

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


@pytest.fixture(scope="module")
def mesh():
    return make_node_mesh(jax.devices()[:8])


@pytest.fixture(scope="module")
def sharded(mesh):
    return node_sharded_solve(mesh)


def assert_shard_parity(sharded, cfg, nodes, queues, running, queued, label=""):
    snap = build_round_snapshot(cfg, "default", nodes, queues, running, queued)
    # pow2 padding buckets shapes so scenarios share compiled programs.
    dev = pad_nodes(pad_device_round(prep_device_round(snap)), 8)
    single = solve_round(dev)
    multi = {k: np.asarray(v) for k, v in sharded(dev).items()}
    for k, v in single.items():
        assert np.array_equal(np.asarray(multi[k]), v, equal_nan=True), (
            f"{label}: {k} diverges between sharded and single-device"
        )
    return single


def _mixed_scenario(n_nodes=24, n_jobs=48, n_queues=3):
    nodes = [
        NodeSpec(
            id=f"node-{i:04d}",
            pool="default",
            total_resources={"cpu": "16", "memory": "64Gi"},
        )
        for i in range(n_nodes)
    ]
    queues = [QueueSpec(f"q{i}", 1.0 + (i % 2)) for i in range(n_queues)]
    running = [
        RunningJob(
            job=JobSpec(
                id=f"run-{i:05d}",
                queue=f"q{i % n_queues}",
                priority_class="low",
                requests={"cpu": "2", "memory": "4Gi"},
                submitted_ts=float(i),
            ),
            node_id=f"node-{i % n_nodes:04d}",
            scheduled_at_priority=1000,
        )
        for i in range(n_nodes * 3)
    ]
    gang = Gang(id="g0", cardinality=4)
    queued = [
        JobSpec(
            id=f"job-{i:05d}",
            queue=f"q{i % n_queues}",
            priority_class="low" if i % 3 else "high",
            requests={"cpu": str(1 + i % 4), "memory": f"{1 + i % 4}Gi"},
            submitted_ts=float(1000 + i),
            gang=gang if i < 4 else None,
        )
        for i in range(n_jobs)
    ]
    return nodes, queues, running, queued


def test_mixed_round_parity(sharded):
    """Evictions + gangs + two priority classes across 24 nodes/8 shards."""
    nodes, queues, running, queued = _mixed_scenario()
    out = assert_shard_parity(
        sharded, PREEMPT_CFG, nodes, queues, running, queued, "mixed"
    )
    assert out["scheduled_mask"].sum() > 0
    assert np.isfinite(out["demand_capped_fair_share"]).all()


@pytest.mark.slow
def test_uneven_shards_parity(sharded):
    """Node counts that do not divide the mesh exercise inert padding."""
    for n_nodes in (9, 13, 27):
        nodes, queues, running, queued = _mixed_scenario(
            n_nodes=n_nodes, n_jobs=24
        )
        assert_shard_parity(
            sharded, PREEMPT_CFG, nodes, queues, running, queued,
            f"uneven-{n_nodes}",
        )


@pytest.mark.slow
def test_random_scenarios_parity(sharded):
    """Random sweeps with running jobs, gangs, taints, selectors."""
    rng = np.random.default_rng(7)
    for i in range(6):
        nodes, queues, running, queued = rand_scenario(
            rng, with_running=True, with_gangs=True
        )
        assert_shard_parity(
            sharded, PREEMPT_CFG, nodes, queues, running, queued, f"rand-{i}"
        )


def test_fewer_nodes_than_shards(sharded):
    """4 nodes over 8 shards: half the shards hold only inert padding."""
    nodes, queues, running, queued = _mixed_scenario(n_nodes=4, n_jobs=12)
    assert_shard_parity(
        sharded, PREEMPT_CFG, nodes, queues, running, queued, "tiny"
    )


@pytest.mark.skipif(
    os.environ.get("ARMADA_SCALE_TESTS") != "1",
    reason="benchmark-scale sharded parity: minutes of compile; "
    "set ARMADA_SCALE_TESTS=1",
)
def test_benchmark_scale_parity(sharded):
    """Sharded vs single-device parity at the flagship bench's NODE extent
    (50k nodes over the 8-device mesh — the sharded axis), with 100k jobs
    (10x below the flagship's 1M: jobs are replicated, not sharded, so the
    shard layout is identical and the smaller extent keeps this CPU-mesh
    run in minutes). Also times both paths so regressions in the
    collective layout are visible in the test log."""
    import time

    from bench import build_inputs

    inputs = build_inputs(100_000, 50_000)
    snap = build_round_snapshot(*inputs)
    dev = pad_nodes(prep_device_round(snap), 8)

    t0 = time.time()
    single = solve_round(dev)
    single_compile = time.time() - t0
    t0 = time.time()
    single = solve_round(dev)
    single_s = time.time() - t0

    t0 = time.time()
    multi = sharded(dev)
    multi_compile = time.time() - t0
    t0 = time.time()
    multi = {k: np.asarray(v) for k, v in sharded(dev).items()}
    multi_s = time.time() - t0

    for k, v in single.items():
        assert np.array_equal(np.asarray(multi[k]), v, equal_nan=True), (
            f"scale: {k} diverges between sharded and single-device"
        )
    assert int(np.asarray(single["scheduled_mask"]).sum()) > 0
    print(
        f"\n[scale 100k x 50k] single: {single_s:.3f}s "
        f"(compile {single_compile:.0f}s)  sharded x8: {multi_s:.3f}s "
        f"(compile {multi_compile:.0f}s)"
    )
