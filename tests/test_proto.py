"""Binary protobuf wire format (proto/armada.proto): the codegen-client
surface mirroring pkg/api/submit.proto:356-401 and
pkg/armadaevents/events.proto:66-97, hosted on the same method table as
the JSON encoding (services/grpc_api.py PROTO_SERVICE)."""

import time

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import (
    Affinity,
    Gang,
    IngressConfig,
    JobSpec,
    MatchExpression,
    NodeSelectorTerm,
    ServiceConfig,
    Toleration,
)
from armada_tpu.events import EventSequence, JobRunErrors, SubmitJob
from armada_tpu.proto import (
    armada_pb2 as pb,
    job_spec_from_proto,
    job_spec_to_proto,
    sequence_from_proto,
    sequence_to_proto,
)
from armada_tpu.services.grpc_api import ProtoApiClient
from armada_tpu.services.server import ControlPlane

CFG = SchedulingConfig(
    priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
    default_priority_class="d",
)


def test_job_spec_proto_roundtrip():
    spec = JobSpec(
        id="j0",
        queue="q",
        jobset="s",
        priority=4,
        priority_class="d",
        requests={"cpu": "2", "memory": "4Gi"},
        node_selector={"zone": "a"},
        tolerations=(Toleration(key="gpu", operator="Equal", value="true",
                                effect="NoSchedule"),),
        affinity=Affinity(
            terms=(
                NodeSelectorTerm(
                    expressions=(
                        MatchExpression(key="rack", operator="In",
                                        values=("r1", "r2")),
                    )
                ),
            )
        ),
        gang=Gang(id="g0", cardinality=2, node_uniformity_label="rack"),
        submitted_ts=12.5,
        annotations={"owner": "x"},
        command=("/bin/true",),
        services=(ServiceConfig(type="Headless", ports=(8080, 9090)),),
        ingresses=(IngressConfig(ports=(8080,),
                                 annotations=(("nginx", "true"),),
                                 tls_enabled=True),),
    )
    back = job_spec_from_proto(job_spec_to_proto(spec))
    assert back == spec


def test_event_sequence_proto_roundtrip():
    seq = EventSequence.of(
        "q", "s",
        SubmitJob(
            created=1.0,
            job=JobSpec(id="j0", queue="q",
                        requests={"cpu": "1", "memory": "1Gi"}),
            deduplication_id="dd1",
        ),
        JobRunErrors(created=2.0, job_id="j0", run_id="r0",
                     error="boom", retryable=False, debug='{"rc": 1}'),
    )
    offset, back = sequence_from_proto(sequence_to_proto(17, seq))
    assert offset == 17
    assert back.queue == "q" and back.jobset == "s"
    assert back.events == seq.events

    # Wire-level: serialize + reparse.
    data = sequence_to_proto(17, seq).SerializeToString()
    offset2, back2 = sequence_from_proto(
        pb.EventSequenceEntry.FromString(data)
    )
    assert (offset2, back2.events) == (17, seq.events)


def test_proto_service_shares_the_method_table():
    """Submit/cancel/reprioritize over binary proto; effects visible to
    the JSON surface (one method table, two encodings)."""
    plane = ControlPlane(CFG, cycle_period=3600).start()
    try:
        client = ProtoApiClient(plane.address)
        from armada_tpu.core.types import QueueSpec

        plane.submit.create_queue(QueueSpec("pq"))
        item = pb.JobSubmitRequestItem(priority=1)
        item.requests["cpu"] = "1"
        item.requests["memory"] = "1Gi"
        item.annotations["via"] = "proto"
        item.command.extend(["/bin/true"])
        ids = client.submit_jobs("pq", "ps", [item, item])
        assert len(ids) == 2
        plane.scheduler.ingester.sync()
        job = plane.scheduler.jobdb.get(ids[0])
        assert job is not None
        assert job.spec.annotations["via"] == "proto"
        assert job.spec.command == ("/bin/true",)

        client.reprioritize_jobs("pq", "ps", [ids[0]], 9)
        plane.scheduler.ingester.sync()
        assert plane.scheduler.jobdb.get(ids[0]).priority == 9

        client.cancel_jobs("pq", "ps", job_ids=[ids[1]])
        plane.scheduler.ingester.sync()
        assert plane.scheduler.jobdb.get(ids[1]).state.value == "cancelled"
    finally:
        plane.stop()


def test_proto_watch_stream():
    """WatchJobSet over proto: EventSequenceEntry messages decode back to
    the exact model events the log holds."""
    plane = ControlPlane(CFG, cycle_period=3600).start()
    try:
        from armada_tpu.core.types import QueueSpec

        client = ProtoApiClient(plane.address)
        plane.submit.create_queue(QueueSpec("wq"))
        item = pb.JobSubmitRequestItem()
        item.requests["cpu"] = "1"
        item.requests["memory"] = "1Gi"
        ids = client.submit_jobs("wq", "ws", [item])

        got = []
        for offset, seq in client.watch_jobset("wq", "ws", follow=False):
            got.extend(seq.events)
        assert any(
            isinstance(e, SubmitJob) and e.job.id == ids[0] for e in got
        )
        # The decoded spec survives the oneof round trip.
        submit = next(e for e in got if isinstance(e, SubmitJob))
        assert submit.job.requests == {"cpu": "1", "memory": "1Gi"}
    finally:
        plane.stop()


def test_proto_submit_affinity_and_zero_priority():
    """Regressions: proto affinity maps through json_format's
    {"terms": [...]} shape, and default-valued fields (priority 0) behave
    identically to the JSON encoding."""
    plane = ControlPlane(CFG, cycle_period=3600).start()
    try:
        from armada_tpu.core.types import QueueSpec

        client = ProtoApiClient(plane.address)
        plane.submit.create_queue(QueueSpec("aq"))
        item = pb.JobSubmitRequestItem(priority=5)
        item.requests["cpu"] = "1"
        item.requests["memory"] = "1Gi"
        term = item.affinity.terms.add()
        term.expressions.add(key="zone", operator="In", values=["a", "b"])
        ids = client.submit_jobs("aq", "as", [item])
        plane.scheduler.ingester.sync()
        job = plane.scheduler.jobdb.get(ids[0])
        expr = job.spec.affinity.terms[0].expressions[0]
        assert (expr.key, expr.operator, expr.values) == ("zone", "In",
                                                          ("a", "b"))
        # Reprioritize to 0 (a proto3 default value) must work.
        client.reprioritize_jobs("aq", "as", ids, 0)
        plane.scheduler.ingester.sync()
        assert plane.scheduler.jobdb.get(ids[0]).priority == 0
    finally:
        plane.stop()


def test_codegen_bindings_current(tmp_path):
    """client/{java,csharp} are protoc output of proto/armada.proto; this
    guards against schema drift (regenerate per client/README.md)."""
    import pathlib
    import shutil
    import subprocess

    root = pathlib.Path(__file__).resolve().parents[1]
    if shutil.which("protoc") is None:
        import pytest

        pytest.skip("protoc not available")
    out = tmp_path / "gen"
    (out / "java").mkdir(parents=True)
    (out / "csharp").mkdir(parents=True)
    subprocess.run(
        [
            "protoc", f"--java_out={out}/java", f"--csharp_out={out}/csharp",
            "--proto_path", str(root / "proto"),
            str(root / "proto" / "armada.proto"),
        ],
        check=True,
    )
    for rel in ("java/armada_tpu/api/Armada.java", "csharp/Armada.cs"):
        fresh = (out / rel).read_text()
        committed = (root / "client" / rel).read_text()
        assert fresh == committed, f"client/{rel} is stale vs proto/armada.proto"
