"""The C++ client library (native/client — the Rust-client equivalent,
client/rust/src/{client,builder,auth}.rs) driven end-to-end against a live
control plane through the REST gateway (the grpc-gateway analogue)."""

import pathlib
import subprocess

import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.services.queryapi import QueryApi
from armada_tpu.services.rest_gateway import RestGateway
from armada_tpu.services.server import ControlPlane

ROOT = pathlib.Path(__file__).resolve().parents[1]
CLIENT_DIR = ROOT / "native" / "client"


@pytest.fixture(scope="module")
def demo_binary():
    try:
        subprocess.run(
            ["make", "-s"], cwd=CLIENT_DIR, check=True, capture_output=True
        )
    except subprocess.CalledProcessError as e:
        pytest.skip(f"C++ toolchain unavailable: {e.stderr.decode()[:200]}")
    return CLIENT_DIR / "client_demo"


@pytest.fixture(scope="module")
def plane_with_gateway():
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    plane = ControlPlane(
        config,
        cycle_period=0.05,
        fake_executors=[{"name": "cpp-exec", "nodes": 4, "cpu": "8", "runtime": 0.5}],
    ).start()
    gateway = RestGateway(
        plane.submit, plane.scheduler, plane.query, plane.log
    )
    yield plane, gateway
    gateway.stop()
    plane.stop()


def test_cpp_client_end_to_end(demo_binary, plane_with_gateway):
    plane, gateway = plane_with_gateway
    proc = subprocess.run(
        [str(demo_binary), "127.0.0.1", str(gateway.port)],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout} stderr={proc.stderr}"
    assert "5 jobs succeeded" in proc.stdout


def test_rest_gateway_auth_enforced(demo_binary):
    """With an auth chain configured, an unauthenticated C++ client gets
    401s and a bearer-token client works."""
    from armada_tpu.services import auth as A
    from armada_tpu.services.auth import Authorizer, MultiAuth, TokenAuth, make_token
    from armada_tpu.services.grpc_api import ApiServer

    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    plane = ControlPlane(
        config,
        cycle_period=0.05,
        fake_executors=[{"name": "cpp-exec2", "nodes": 4, "cpu": "8", "runtime": 0.5}],
    ).start()
    api = ApiServer(
        plane.submit, plane.scheduler, plane.query, plane.log,
        auth=MultiAuth([TokenAuth("cpp-secret")]),
        authorizer=Authorizer(),
    )
    gateway = RestGateway(
        plane.submit, plane.scheduler, plane.query, plane.log,
        auth=api.auth, authorizer=api.authorizer, api=api,
    )
    try:
        anon = subprocess.run(
            [str(demo_binary), "127.0.0.1", str(gateway.port)],
            capture_output=True, text=True, timeout=60,
        )
        assert anon.returncode == 1
        assert "401" in anon.stderr or "credentials" in anon.stderr

        token = make_token("cpp-secret", "cpp-user", groups=["admin"])
        authed = subprocess.run(
            [str(demo_binary), "127.0.0.1", str(gateway.port), token],
            capture_output=True, text=True, timeout=120,
        )
        assert authed.returncode == 0, authed.stderr
    finally:
        gateway.stop()
        plane.stop()


@pytest.fixture(scope="module")
def proto_binary():
    try:
        subprocess.run(
            ["make", "-s", "proto_demo"],
            cwd=CLIENT_DIR, check=True, capture_output=True,
        )
    except subprocess.CalledProcessError as e:
        pytest.skip(f"protoc/C++ toolchain unavailable: {e.stderr.decode()[:200]}")
    return CLIENT_DIR / "proto_demo"


def test_cpp_client_proto_wire_format(proto_binary, plane_with_gateway):
    """The C++ client submitting over binary protobuf (proto/armada.proto
    generated C++, linked against libprotobuf) — the codegen-client
    interop the reference's pkg/api protos provide. The demo also checks
    the proto-submitted jobs are visible over the JSON query surface."""
    plane, gateway = plane_with_gateway
    proc = subprocess.run(
        [str(proto_binary), "127.0.0.1", str(gateway.port)],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, f"stderr: {proc.stderr}\nstdout: {proc.stdout}"
    assert "OK" in proc.stdout
    assert proc.stdout.count("submitted job-") == 2
