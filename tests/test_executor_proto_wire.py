"""Executor wire over binary protobuf (the executorapi.proto role): an
ExecutorAgent speaking LeaseRequest/LeaseResponse + ReportEvents messages
drives the full job lifecycle against the live gRPC server."""

import time

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import Taint
from armada_tpu.events import InMemoryEventLog
from armada_tpu.jobdb import JobState
from armada_tpu.services.executor_agent import ExecutorAgent, _PodRuntime
from armada_tpu.services.grpc_api import (
    ApiClient,
    ApiServer,
    ProtoExecutorClient,
)
from armada_tpu.services.queryapi import QueryApi
from armada_tpu.services.scheduler import SchedulerService
from armada_tpu.services.submit import SubmitService


def test_proto_executor_lifecycle():
    config = SchedulingConfig(
        priority_classes={"d": PriorityClass("d", 1000, preemptible=True)},
        default_priority_class="d",
    )
    log = InMemoryEventLog()
    sched = SchedulerService(config, log, backend="oracle")
    submit = SubmitService(config, log, scheduler=sched)
    server = ApiServer(submit, sched, QueryApi(sched.jobdb), log)
    grpc_server, port = server.serve(port=0)
    try:
        client = ApiClient(f"127.0.0.1:{port}")
        client.create_queue("pw")
        agent = ExecutorAgent(
            ProtoExecutorClient(f"127.0.0.1:{port}"),
            "proto-exec",
            nodes=[
                {
                    "id": "pw-node-0",
                    "total_resources": {"cpu": "8", "memory": "32Gi"},
                    "labels": {"zone": "z1"},
                    "taints": [
                        {"key": "maint", "value": "true", "effect": "PreferNoSchedule"}
                    ],
                    "unallocatable_by_priority": {0: {"cpu": "1"}},
                }
            ],
            runtime=_PodRuntime(runtime_s=0.5),
        )
        agent.tick()  # register the node over the proto wire
        ids = client.submit_jobs(
            "pw", "s1",
            [{"requests": {"cpu": "2", "memory": "4Gi"},
              "annotations": {"team": "tpu"}}],
        )
        assert len(ids) == 1
        sched.cycle(now=time.time())
        agent.tick()  # lease arrives as JobLease with zlib spec bytes
        txn = sched.jobdb.read_txn()
        deadline = time.time() + 20
        state = None
        while time.time() < deadline:
            agent.tick()
            sched.cycle(now=time.time())
            job = sched.jobdb.read_txn().get(ids[0])
            state = job.state
            if state == JobState.SUCCEEDED:
                break
            time.sleep(0.1)
        assert state == JobState.SUCCEEDED
        run = sched.jobdb.read_txn().get(ids[0]).latest_run
        assert run.node_id == "pw-node-0"
        # The node report round-tripped through the proto maps: the
        # scheduler's heartbeat view carries labels/taints/unallocatable.
        hb = sched.executors["proto-exec"]
        node = hb.nodes[0]
        assert node.labels == {"zone": "z1"}
        assert node.taints == (Taint("maint", "true", "PreferNoSchedule"),)
        assert node.unallocatable_by_priority == {0: {"cpu": "1"}}
    finally:
        grpc_server.stop(0)
