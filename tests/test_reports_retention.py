"""SchedulingReportsRepository retention: the per-job report map stays
bounded under a long sim, and queries for evicted ids degrade with a
clear message instead of a KeyError (ISSUE 10 satellite)."""

from armada_tpu.services.reports import (
    QueueReport,
    RoundReport,
    SchedulingReportsRepository,
)


def _report(i, job_ids):
    rep = RoundReport(
        pool="default", started=float(i), finished=float(i) + 0.5,
        num_jobs=len(job_ids), num_nodes=4,
    )
    rep.queues["q"] = QueueReport(queue="q")
    for jid in job_ids:
        rep.job_contexts[jid] = f"scheduled: round {i}"
    return rep


def test_retained_jobs_bounds_memory_and_degrades_gracefully():
    repo = SchedulingReportsRepository(retained_jobs=50)
    for i in range(40):
        repo.record(_report(i, [f"job-{i}-{k}" for k in range(5)]))
    # 200 job entries pushed through a 50-entry budget: the repository
    # must stay bounded (eviction halves at the cap, so never > cap+batch).
    assert len(repo._job_reports) <= 55
    # The newest round's jobs are queryable...
    assert repo.job_report("job-39-0") == "scheduled: round 39"
    # ...an evicted early id degrades with the explicit no-report
    # message, not a KeyError.
    msg = repo.job_report("job-0-0")
    assert msg == "no report for job job-0-0"
    # Unknown ids get the same contract.
    assert repo.job_report("never-existed").startswith("no report for job")


def test_retention_under_long_sim():
    """End-to-end: a sim whose scheduler carries a tiny retained_jobs
    budget keeps the map bounded across the whole run, and every query
    path (hit, evicted, unknown) returns a string."""
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        ShiftedExponential,
        Simulator,
        WorkloadSpec,
    )

    sim = Simulator(
        [ClusterSpec(name="c", node_templates=(NodeTemplate(count=4, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    name="q",
                    job_templates=(
                        JobTemplate(
                            id="t", number=120, cpu="2",
                            runtime=ShiftedExponential(minimum=15.0),
                        ),
                        # A can-never-fit job keeps job_reasons flowing
                        # into the repository every single round.
                        JobTemplate(id="huge", number=1, cpu="999"),
                    ),
                ),
            )
        ),
        backend="oracle",
        cycle_interval=10.0,
        max_time=1200.0,
    )
    sim.scheduler.reports = SchedulingReportsRepository(retained_jobs=30)
    sim.run()
    repo = sim.scheduler.reports
    assert len(repo._job_reports) <= 40, len(repo._job_reports)
    # The perpetual unschedulable job's verdict survives (recorded every
    # round, so it is always among the newest entries).
    assert repo.job_report("q-huge-000000") == "job does not fit on any node"
    # An early finished job's id eventually evicts; the query is a
    # clear message either way, never an exception.
    assert isinstance(repo.job_report("q-t-000000"), str)
    assert isinstance(repo.scheduling_report(), str)
