"""Durable event log: crash-recovery, torn-tail truncation, full-state
reconstruction by replay (the checkpoint/resume model)."""

import json
import os

from armada_tpu.core.types import Gang, JobSpec, Toleration
from armada_tpu.events.file_log import FileEventLog
from armada_tpu.events.model import (
    EventSequence,
    JobRunLeased,
    JobRunRunning,
    SubmitJob,
)
from armada_tpu.jobdb import JobDb, JobState
from armada_tpu.jobdb.ingest import SchedulerIngester


def job(i):
    return JobSpec(
        id=f"j{i:03d}",
        queue="q",
        jobset="s",
        requests={"cpu": "1", "memory": "1Gi"},
        tolerations=(Toleration(key="k", value="v"),),
        gang=Gang(id="g", cardinality=2) if i % 2 == 0 else None,
        submitted_ts=float(i),
    )


def test_roundtrip_and_recovery(tmp_path):
    d = str(tmp_path / "log")
    log = FileEventLog(d)
    for i in range(10):
        log.publish(
            EventSequence.of("q", "s", SubmitJob(created=float(i), job=job(i)))
        )
    log.publish(
        EventSequence.of(
            "q", "s", JobRunLeased(created=99.0, job_id="j000", run_id="r1",
                                    executor="e", node_id="n", pool="p",
                                    scheduled_at_priority=1000)
        )
    )
    log.close()

    # Fresh process: replay everything.
    log2 = FileEventLog(d)
    assert log2.end_offset == 11
    entries = log2.read(0, 100)
    first = entries[0].sequence.events[0]
    assert isinstance(first, SubmitJob)
    assert first.job.id == "j000"
    assert first.job.gang.cardinality == 2
    assert first.job.tolerations[0].key == "k"
    lease = entries[10].sequence.events[0]
    assert isinstance(lease, JobRunLeased) and lease.node_id == "n"

    # Materialize a jobdb purely from the recovered log.
    db = JobDb()
    SchedulerIngester(log2, db).sync()
    assert len(db) == 10
    assert db.get("j000").state == JobState.LEASED


def test_torn_tail_truncated(tmp_path):
    d = str(tmp_path / "log")
    log = FileEventLog(d)
    for i in range(5):
        log.publish(EventSequence.of("q", "s", SubmitJob(created=0.0, job=job(i))))
    log.close()
    # Simulate a crash mid-write: append garbage half-record.
    seg = os.path.join(d, sorted(os.listdir(d))[0])
    with open(seg, "ab") as f:
        f.write(b'{"o": 5, "c": 123, "s": {"q": "q", "j"')
    log2 = FileEventLog(d)
    assert log2.end_offset == 5  # torn record dropped
    # And the segment is clean for new appends after recovery.
    log2.publish(EventSequence.of("q", "s", SubmitJob(created=9.0, job=job(9))))
    log2.close()
    log3 = FileEventLog(d)
    assert log3.end_offset == 6


def test_corrupt_crc_mid_log_refuses_to_start(tmp_path):
    import pytest

    from armada_tpu.events.file_log import CorruptLogError

    d = str(tmp_path / "log")
    log = FileEventLog(d)
    for i in range(3):
        log.publish(EventSequence.of("q", "s", SubmitJob(created=0.0, job=job(i))))
    log.close()
    seg = os.path.join(d, sorted(os.listdir(d))[0])
    lines = open(seg, "rb").read().splitlines(keepends=True)
    rec = json.loads(lines[1])
    rec["s"]["q"] = "tampered"
    lines[1] = json.dumps(rec).encode() + b"\n"
    open(seg, "wb").writelines(lines)
    # Mid-log corruption must refuse to start, never truncate good records.
    with pytest.raises(CorruptLogError):
        FileEventLog(d)


def test_lost_trailing_newline_is_torn_tail(tmp_path):
    d = str(tmp_path / "log")
    log = FileEventLog(d)
    for i in range(3):
        log.publish(EventSequence.of("q", "s", SubmitJob(created=0.0, job=job(i))))
    log.close()
    seg = os.path.join(d, sorted(os.listdir(d))[0])
    data = open(seg, "rb").read()
    open(seg, "wb").write(data[:-1])  # crash lost the last newline
    log2 = FileEventLog(d)
    assert log2.end_offset == 2  # last record dropped, file clean
    log2.publish(EventSequence.of("q", "s", SubmitJob(created=9.0, job=job(9))))
    log2.close()
    assert FileEventLog(d).end_offset == 3


def test_segment_rollover(tmp_path):
    d = str(tmp_path / "log")
    log = FileEventLog(d, segment_size=4)
    for i in range(10):
        log.publish(EventSequence.of("q", "s", SubmitJob(created=0.0, job=job(i))))
    log.close()
    segs = [f for f in os.listdir(d) if f.startswith("seg-")]
    assert len(segs) >= 2
    log2 = FileEventLog(d, segment_size=4)
    assert log2.end_offset == 10


def test_control_plane_survives_restart(tmp_path):
    """Full-stack checkpoint/resume: run, stop, rebuild from disk."""
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.services.scheduler import SchedulerService
    from armada_tpu.services.submit import SubmitService
    from armada_tpu.core.types import QueueSpec

    d = str(tmp_path / "log")
    log = FileEventLog(d)
    sched = SchedulerService(SchedulingConfig(), log)
    submit = SubmitService(SchedulingConfig(), log, scheduler=sched)
    submit.create_queue(QueueSpec("team"))
    submit.submit("team", "set1", [job(i).with_(gang=None) for i in range(6)], now=0.0)
    sched.ingester.sync()
    assert len(sched.jobdb) == 6
    log.close()

    # "Restart": new log handle, new scheduler, replay.
    log2 = FileEventLog(d)
    sched2 = SchedulerService(SchedulingConfig(), log2)
    sched2.ingester.sync()
    assert len(sched2.jobdb) == 6
    assert all(
        j.state == JobState.QUEUED for j in sched2.jobdb.read_txn().all_jobs()
    )
    # queue registry replays too (control-plane events)
    submit2 = SubmitService(SchedulingConfig(), log2, scheduler=sched2)
    assert "team" in submit2.queues
    assert sched2._effective_queue("team").priority_factor == 1.0


def test_dedup_survives_restart(tmp_path):
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.core.types import QueueSpec
    from armada_tpu.services.submit import SubmitService

    d = str(tmp_path / "log")
    log = FileEventLog(d)
    submit = SubmitService(SchedulingConfig(), log)
    submit.create_queue(QueueSpec("team"))
    j = job(0).with_(
        gang=None, annotations={"armadaproject.io/deduplication-id": "once"}
    )
    ids1 = submit.submit("team", "s", [j], now=0.0)
    log.close()

    submit2 = SubmitService(SchedulingConfig(), FileEventLog(d))
    ids2 = submit2.submit(
        "team",
        "s",
        [job(1).with_(gang=None, annotations={"armadaproject.io/deduplication-id": "once"})],
        now=1.0,
    )
    assert ids1 == ids2  # dedup index rebuilt from the log


def test_settings_survive_restart(tmp_path):
    """Executor cordon and priority overrides are event-sourced: a fresh
    scheduler over the same durable log restores them (the reference's
    executor-settings/override tables from controlplaneevents)."""
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.services.scheduler import SchedulerService

    d = str(tmp_path / "log")
    log = FileEventLog(d)
    sched = SchedulerService(SchedulingConfig(), log)
    sched.set_executor_cordon("cluster-x", True)
    sched.set_executor_cordon("cluster-y", True)
    sched.set_executor_cordon("cluster-y", False)
    sched.set_priority_override("q1", 4.0)
    sched.set_priority_override("q2", 2.0)
    sched.set_priority_override("q2", None)
    log.close()

    sched2 = SchedulerService(SchedulingConfig(), FileEventLog(d))
    assert sched2.cordoned_executors == {"cluster-x"}
    assert sched2.priority_overrides == {"q1": 4.0}


def test_restart_does_not_grow_full_segment(tmp_path):
    """A restart with the last segment already at segment_size must roll a
    fresh segment instead of growing the full one (size bound honored)."""
    import os

    from armada_tpu.events.file_log import FileEventLog

    d = str(tmp_path / "log")
    log = FileEventLog(d, segment_size=4)
    for i in range(4):
        log.publish(EventSequence.of("q", "s", SubmitJob(created=0.0, job=job(i))))
    log.close()
    # Reopen (recovery counts 4 records in the live segment) and publish:
    log2 = FileEventLog(d, segment_size=4)
    log2.publish(EventSequence.of("q", "s", SubmitJob(created=1.0, job=job(9))))
    log2.close()
    segs = sorted(f for f in os.listdir(d) if f.startswith("seg-"))
    assert len(segs) == 2, segs
    counts = [
        sum(1 for _ in open(os.path.join(d, s))) for s in segs
    ]
    assert counts[0] == 4 and counts[1] == 1


def test_torn_tail_fuzz_every_byte_offset(tmp_path):
    """Fuzz torn-write recovery: truncate the final record at EVERY byte
    offset (including losing just the trailing newline) and assert clean
    recovery — all prior records intact, the torn record dropped, and the
    log appendable again."""
    d = str(tmp_path / "log")
    log = FileEventLog(d)
    for i in range(4):
        log.publish(
            EventSequence.of("q", "s", SubmitJob(created=float(i), job=job(i)))
        )
    log.close()
    seg = os.path.join(d, sorted(os.listdir(d))[0])
    with open(seg, "rb") as f:
        data = f.read()
    # Byte offset where the final record starts.
    body = data[:-1]  # strip the final newline to find the prior one
    last_start = body.rfind(b"\n") + 1
    prior = data[:last_start]

    for cut in range(last_start, len(data)):
        with open(seg, "wb") as f:
            f.write(data[:cut])
        recovered = FileEventLog(d)
        kept = 3 if cut < len(data) else 4
        assert recovered.end_offset == kept, f"cut at byte {cut}"
        entries = recovered.read(0, 100)
        assert [e.sequence.events[0].job.id for e in entries] == [
            f"j{i:03d}" for i in range(kept)
        ], f"prior records damaged at cut {cut}"
        # The tail is clean: appends land at the recovered offset.
        recovered.publish(
            EventSequence.of("q", "s", SubmitJob(created=9.0, job=job(9)))
        )
        assert recovered.end_offset == kept + 1
        recovered.close()
        # Restore the pristine file for the next offset.
        with open(seg, "wb") as f:
            f.write(data)
    # Sanity: the intact file still recovers all 4 records.
    assert prior  # the fuzz actually covered a non-empty prefix
    final = FileEventLog(d)
    assert final.end_offset == 4
    final.close()


def test_injected_torn_write_crash_recovery(tmp_path):
    """The chaos injector's torn write behaves like a crash: partial bytes
    stay on disk, recovery truncates them, and the retried publish lands
    at the same offset (services/chaos.CrashRecoveringLog)."""
    from armada_tpu.services.chaos import CrashRecoveringLog, FaultPlan, FaultSpec

    plan = FaultPlan(
        [FaultSpec("torn_log_write", "*", start=0.0, count=3, param=0.4)]
    )
    log = CrashRecoveringLog(str(tmp_path / "log"), plan, clock=lambda: 1.0)
    for i in range(6):
        log.publish(
            EventSequence.of("q", "s", SubmitJob(created=float(i), job=job(i)))
        )
    assert log.crashes == 3  # every budgeted tear fired and was recovered
    assert log.end_offset == 6
    log.close()
    clean = FileEventLog(str(tmp_path / "log"))
    assert clean.end_offset == 6
    ids = [e.sequence.events[0].job.id for e in clean.read(0, 100)]
    assert ids == [f"j{i:03d}" for i in range(6)]
    clean.close()
