"""Indicative gang pricing + bid-price provider.

Mirrors the scenario families of the reference's pricer tests
(internal/scheduler/scheduling/pricer/{node_scheduler,gang_pricer}_test.go,
internal/scheduler/pricing/bid_price_service_test.go) against the
vectorized pricer in solver/pricer.py and the provider in
services/pricing.py.
"""

import numpy as np

from armada_tpu.core.config import GangDefinition, PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, QueueSpec, RunningJob
from armada_tpu.services.pricing import (
    Bid,
    BidPriceSnapshot,
    ExternalBidPriceService,
    LocalBidPriceService,
    PRICE_BANDS,
    job_price_band,
    refresh_job_bids,
)
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.pricer import (
    REASON_CARDINALITY_ZERO,
    REASON_DOES_NOT_FIT,
    REASON_EXCEEDS_CAPACITY,
    REASON_GANG_DOES_NOT_FIT,
    REASON_NOT_INDEXED,
    price_gangs,
)

MKT = SchedulingConfig(
    priority_classes={"m": PriorityClass("m", 1000, preemptible=True)},
    default_priority_class="m",
    market_driven=True,
)


def node(i=0, cpu="8", labels=None):
    return NodeSpec(
        id=f"n{i}",
        pool="default",
        total_resources={"cpu": cpu, "memory": "32Gi"},
        labels=labels or {},
    )


def running(i, bid, node_id="n0", cpu="2"):
    return RunningJob(
        job=JobSpec(
            id=f"r{i:02d}",
            queue="q",
            requests={"cpu": cpu, "memory": "1Gi"},
            bid_prices={"default": bid},
        ),
        node_id=node_id,
        scheduled_at_priority=1000,
    )


def snap_of(nodes, running_jobs, queued=()):
    return build_round_snapshot(
        MKT, "default", nodes, [QueueSpec("q")], list(running_jobs), list(queued)
    )


def shape(cpu="2", size=1, **kw):
    return GangDefinition(size=size, resources={"cpu": cpu, "memory": "1Gi"}, **kw)


def one_price(snap, sh, **kw):
    res = price_gangs(snap, {"s": sh}, **kw)["s"]
    return res


# ---- node_scheduler_test.go family -----------------------------------------


def test_empty_node_prices_at_zero():
    res = one_price(snap_of([node()], []), shape())
    assert res.evaluated and res.schedulable and res.price == 0.0


def test_free_capacity_prices_at_zero_despite_running_jobs():
    # 8 cpu, 2 used -> a 2-cpu member still fits free.
    res = one_price(snap_of([node()], [running(0, 5.0)]), shape())
    assert res.schedulable and res.price == 0.0


def test_price_is_cheapest_eviction():
    # Full node: bids 1, 5, 9. A 2-cpu member needs one eviction -> 1.0.
    jobs = [running(i, b, cpu="2") for i, b in enumerate([5.0, 1.0, 9.0, 7.0])]
    res = one_price(snap_of([node()], jobs), shape())
    assert res.schedulable and res.price == 1.0


def test_price_is_last_evicted_bid_when_multiple_needed():
    # Full 8-cpu node, four 2-cpu jobs bidding 1,2,3,4; a 6-cpu member
    # evicts the three cheapest -> price 3.0 (the max of the evicted set).
    jobs = [running(i, float(i + 1), cpu="2") for i in range(4)]
    res = one_price(snap_of([node()], jobs), shape(cpu="6"))
    assert res.schedulable and res.price == 3.0


def test_cheaper_node_wins():
    # n0 full of bid-9 jobs, n1 full of bid-2 jobs -> price 2.0.
    jobs = [running(i, 9.0, "n0", cpu="4") for i in range(2)] + [
        running(10 + i, 2.0, "n1", cpu="4") for i in range(2)
    ]
    res = one_price(snap_of([node(0), node(1)], jobs), shape())
    assert res.schedulable and res.price == 2.0


def test_unschedulable_when_too_big_for_any_node():
    # Two 8-cpu nodes: a 10-cpu member exceeds every node's total but not
    # pool capacity -> does-not-fit, not exceeds-capacity.
    res = one_price(snap_of([node(0), node(1)], []), shape(cpu="10"))
    assert res.evaluated and not res.schedulable
    assert res.unschedulable_reason == REASON_DOES_NOT_FIT


def test_non_preemptible_running_jobs_price_at_sentinel():
    cfg_np = SchedulingConfig(
        priority_classes={
            "m": PriorityClass("m", 1000, preemptible=True),
            "hard": PriorityClass("hard", 2000, preemptible=False),
        },
        default_priority_class="m",
        market_driven=True,
    )
    full = [
        RunningJob(
            job=JobSpec(
                id="np0",
                queue="q",
                priority_class="hard",
                requests={"cpu": "8", "memory": "1Gi"},
                bid_prices={"default": 3.0},
            ),
            node_id="n0",
            scheduled_at_priority=2000,
        )
    ]
    snap = build_round_snapshot(
        cfg_np, "default", [node()], [QueueSpec("q")], full, []
    )
    res = price_gangs(snap, {"s": shape()})["s"]
    # pricing.NonPreemptibleRunningPrice: schedulable only at the sentinel.
    assert res.schedulable and res.price == 1_000_000.0


# ---- gang_pricer_test.go family --------------------------------------------


def test_gang_on_empty_nodes_prices_zero():
    res = one_price(snap_of([node(0), node(1)], []), shape(size=2, cpu="8"))
    assert res.schedulable and res.price == 0.0


def test_gang_price_is_max_over_members():
    # Two full nodes: n0 evictable at 1.0, n1 at 4.0. A 2-member 8-cpu gang
    # must take both -> price 4.0.
    jobs = [running(0, 1.0, "n0", cpu="8"), running(1, 4.0, "n1", cpu="8")]
    res = one_price(snap_of([node(0), node(1)], jobs), shape(size=2, cpu="8"))
    assert res.schedulable and res.price == 4.0


def test_gang_members_consume_state_sequentially():
    # n0 free, n1 half-full with a bid-3 job. A 2-member gang of 5-cpu
    # members: member one takes n0 at price 0 and leaves only 3 cpu there,
    # so member two must evict on n1 -> gang price 3.0. Without sequential
    # state updates both members would price 0 on n0.
    jobs = [running(0, 3.0, "n1", cpu="4")]
    res = one_price(snap_of([node(0), node(1)], jobs), shape(size=2, cpu="5"))
    assert res.schedulable and res.price == 3.0


def test_gang_unschedulable_within_capacity():
    # Pool capacity is fine (16 cpu for 12 requested) but no single node can
    # take the second 6-cpu member once memory on n1 is exhausted by an
    # unevictable... simpler: n1 is unschedulable, so only n0's 8 cpu are
    # actually placeable -> gang-does-not-fit, not exceeds-capacity
    # (capacity counts both nodes' totals).
    n1 = NodeSpec(
        id="n1", pool="default",
        total_resources={"cpu": "8", "memory": "32Gi"}, unschedulable=True,
    )
    res = one_price(snap_of([node(0), n1], []), shape(size=2, cpu="6"))
    assert not res.schedulable
    assert res.unschedulable_reason == REASON_GANG_DOES_NOT_FIT


def test_uniformity_groups_cheapest_zone_wins():
    cfg = SchedulingConfig(
        priority_classes={"m": PriorityClass("m", 1000, preemptible=True)},
        default_priority_class="m",
        market_driven=True,
        indexed_node_labels=("zone",),
    )
    nodes = [
        node(0, labels={"zone": "a"}),
        node(1, labels={"zone": "a"}),
        node(2, labels={"zone": "b"}),
        node(3, labels={"zone": "b"}),
    ]
    # Zone a full at bid 7; zone b full at bid 3.
    jobs = [running(i, 7.0, f"n{i}", cpu="8") for i in range(2)] + [
        running(2 + i, 3.0, f"n{2 + i}", cpu="8") for i in range(2)
    ]
    snap = build_round_snapshot(cfg, "default", nodes, [QueueSpec("q")], jobs, [])
    res = price_gangs(
        snap, {"s": shape(size=2, cpu="8", node_uniformity="zone")}
    )["s"]
    assert res.schedulable and res.price == 3.0


def test_uniformity_label_not_indexed():
    res = one_price(
        snap_of([node()], []), shape(node_uniformity="never-on-any-node")
    )
    assert not res.schedulable
    assert res.unschedulable_reason == REASON_NOT_INDEXED


def test_cardinality_zero_and_exceeds_capacity():
    snap = snap_of([node()], [])
    res = price_gangs(snap, {"z": shape(size=0), "big": shape(size=100, cpu="8")})
    assert res["z"].unschedulable_reason == REASON_CARDINALITY_ZERO
    assert res["big"].unschedulable_reason == REASON_EXCEEDS_CAPACITY


def test_round_headroom_check():
    # The round already scheduled up to the fraction cap -> exceeds capacity.
    snap = snap_of([node()], [])
    used = snap.factory.from_map({"cpu": "8", "memory": "32Gi"}, ceil=True)
    res = price_gangs(snap, {"s": shape()}, scheduled_this_round=used)["s"]
    assert not res.schedulable
    assert res.unschedulable_reason == REASON_EXCEEDS_CAPACITY


def test_selector_restricts_candidates():
    cfg = SchedulingConfig(
        priority_classes={"m": PriorityClass("m", 1000, preemptible=True)},
        default_priority_class="m",
        market_driven=True,
        indexed_node_labels=("tier",),
    )
    nodes = [node(0, labels={"tier": "gold"}), node(1)]
    jobs = [running(0, 2.0, "n0", cpu="8")]  # gold node full at bid 2
    snap = build_round_snapshot(
        cfg, "default", nodes, [QueueSpec("q")], jobs,
        # a queued job referencing the selector interns the (tier, gold) pair
        [JobSpec(id="sel", queue="q", requests={"cpu": "1", "memory": "1Gi"},
                 node_selector={"tier": "gold"})],
    )
    res = price_gangs(
        snap, {"s": GangDefinition(size=1,
                                   resources={"cpu": "2", "memory": "1Gi"},
                                   node_selector={"tier": "gold"})}
    )["s"]
    # n1 is free but unlabeled; the selector forces the gold node -> 2.0.
    assert res.schedulable and res.price == 2.0


def test_pricing_sees_post_round_state():
    # A round fills the node with a queued bid-6 job; the pricer must see
    # that capacity as consumed-but-evictable (the reference prices the
    # nodedb AFTER the round, preempting_queue_scheduler.go:637-646).
    from armada_tpu.solver.reference import ReferenceSolver

    queued = [
        JobSpec(id="big", queue="q", requests={"cpu": "8", "memory": "1Gi"},
                bid_prices={"default": 6.0})
    ]
    snap = snap_of([node()], [], queued)
    res = ReferenceSolver(snap).solve()
    assert res.scheduled_mask[snap.job_ids.index("big")]
    result = {
        "assigned_node": res.assigned_node,
        "scheduled_mask": res.scheduled_mask,
        "preempted_mask": res.preempted_mask,
    }
    pre = price_gangs(snap, {"s": shape()})["s"]
    post = price_gangs(snap, {"s": shape()}, result=result)["s"]
    assert pre.price == 0.0  # pre-round view: node still free
    assert post.schedulable and post.price == 6.0  # post-round: must evict


def test_pricing_has_no_side_effects():
    jobs = [running(i, float(i + 1), cpu="2") for i in range(4)]
    snap = snap_of([node()], jobs)
    before = snap.allocatable.copy()
    first = price_gangs(snap, {"a": shape(cpu="6")})
    second = price_gangs(snap, {"a": shape(cpu="6")})
    assert (snap.allocatable == before).all()
    assert first["a"] == second["a"]


# ---- pricing provider (bid_price_service_test.go family) --------------------


def test_local_bid_service_band_prices():
    svc = LocalBidPriceService(["default"], lambda: ["q1", "q2"])
    snap = svc.get_bid_prices()
    a = snap.get_price("q1", PRICE_BANDS["A"])["default"]
    h = snap.get_price("q2", PRICE_BANDS["H"])["default"]
    assert a == Bid(2.0, 2.0) and h == Bid(9.0, 9.0)


def test_changed_price_keys_diff():
    b1 = BidPriceSnapshot(
        id="1", timestamp=0.0,
        bids={("q", 1): {"p": Bid(1, 1)}, ("q", 2): {"p": Bid(2, 2)}},
    )
    b2 = BidPriceSnapshot(
        id="2", timestamp=1.0,
        bids={("q", 1): {"p": Bid(1, 1)}, ("q", 3): {"p": Bid(3, 3)}},
    )
    assert b2.changed_price_keys(b1) == {("q", 2), ("q", 3)}
    assert b2.changed_price_keys(None) == {("q", 1), ("q", 3)}
    assert b1.changed_price_keys(b1) == set()


def test_external_bid_service_fallback_phases():
    class FakeClient:
        def retrieve_bids(self):
            return {
                "queue_bids": {
                    "q": {"default": {1: {"queued": 5.0}}},
                },
                "fallback": {"q": {"default": {"queued": 1.0, "running": 2.0}}},
                "pool_resource_units": {"default": {"cpu": "1"}},
            }

    snap = ExternalBidPriceService(FakeClient()).get_bid_prices()
    # Band 1: queued from the band bid, running from the fallback.
    assert snap.get_price("q", 1)["default"] == Bid(5.0, 2.0)
    # Band 2 has no band bid: both phases from the fallback.
    assert snap.get_price("q", 2)["default"] == Bid(1.0, 2.0)
    assert snap.resource_units == {"default": {"cpu": "1"}}


def test_refresh_job_bids_touches_only_changed_keys():
    from armada_tpu.jobdb import JobDb
    from armada_tpu.jobdb.jobdb import Job

    db = JobDb()
    txn = db.write_txn()
    j_a = JobSpec(
        id="a", queue="q",
        requests={"cpu": "1"},
        annotations={"armadaproject.io/priceBand": "A"},
    )
    j_b = JobSpec(
        id="b", queue="q",
        requests={"cpu": "1"},
        annotations={"armadaproject.io/priceBand": "B"},
    )
    txn.upsert(Job(spec=j_a), Job(spec=j_b))
    txn.commit()
    assert job_price_band(j_a) == PRICE_BANDS["A"]

    first = BidPriceSnapshot(
        id="1", timestamp=0.0,
        bids={
            ("q", PRICE_BANDS["A"]): {"default": Bid(2.0, 2.5)},
            ("q", PRICE_BANDS["B"]): {"default": Bid(3.0, 3.5)},
        },
    )
    assert refresh_job_bids(db, first, None) == 2
    spec_a = db.read_txn().get("a").spec
    assert spec_a.bid_prices == {"default": (2.0, 2.5)}
    # The original spec object is never mutated in place (it is shared
    # with API threads); re-pricing installs a fresh spec via the txn.
    assert j_a.bid_prices == {}
    # Phase selection at snapshot build: queued bid for queued jobs.
    assert spec_a.bid_price("default") == 2.0
    assert spec_a.bid_price("default", running=True) == 2.5

    second = BidPriceSnapshot(
        id="2", timestamp=1.0,
        bids={
            ("q", PRICE_BANDS["A"]): {"default": Bid(2.0, 2.5)},  # unchanged
            ("q", PRICE_BANDS["B"]): {"default": Bid(9.0, 9.5)},
        },
    )
    assert refresh_job_bids(db, second, first) == 1
    txn2 = db.read_txn()
    assert txn2.get("b").spec.bid_prices == {"default": (9.0, 9.5)}
    assert txn2.get("a").spec.bid_prices == {"default": (2.0, 2.5)}

    # A job submitted under STABLE prices (no changed keys) must still be
    # priced — callers pass it via new_job_ids.
    j_c = JobSpec(
        id="c", queue="q",
        requests={"cpu": "1"},
        annotations={"armadaproject.io/priceBand": "A"},
    )
    txn3 = db.write_txn()
    txn3.upsert(Job(spec=j_c))
    txn3.commit()
    third = BidPriceSnapshot(id="3", timestamp=2.0, bids=second.bids)
    assert refresh_job_bids(db, third, second) == 0  # not known as new
    assert refresh_job_bids(db, third, second, new_job_ids=["c"]) == 1
    assert db.read_txn().get("c").spec.bid_prices == {"default": (2.0, 2.5)}


# ---- scheduler integration --------------------------------------------------


def test_scheduler_records_indicative_prices():
    from armada_tpu.events import EventSequence, InMemoryEventLog, SubmitJob
    from armada_tpu.services.scheduler import ExecutorHeartbeat, SchedulerService

    cfg = SchedulingConfig(
        priority_classes={"m": PriorityClass("m", 1000, preemptible=True)},
        default_priority_class="m",
        market_driven=True,
        gangs_to_price={
            "small": GangDefinition(size=1, resources={"cpu": "2", "memory": "1Gi"}),
            "huge": GangDefinition(size=64, resources={"cpu": "8", "memory": "1Gi"}),
        },
    )
    svc = SchedulerService(
        cfg,
        InMemoryEventLog(),
        queues=[QueueSpec("q")],
        bid_price_provider=LocalBidPriceService(["default"], lambda: ["q"]),
    )
    svc.report_executor(
        ExecutorHeartbeat("ex", "default", [node()], last_seen=1.0)
    )
    svc.log.publish(
        EventSequence.of(
            "q", "js",
            SubmitJob(created=1.0, job=JobSpec(
                id="j0", queue="q", jobset="js",
                requests={"cpu": "1", "memory": "1Gi"},
            )),
        )
    )
    svc.cycle(now=2.0)
    report = svc.reports.by_pool["default"]
    assert set(report.indicative_prices) == {"small", "huge"}
    assert report.indicative_prices["small"].schedulable
    assert report.indicative_prices["small"].price == 0.0
    assert not report.indicative_prices["huge"].schedulable
    assert "indicative gang small" in report.report_string()


def test_post_round_eviction_priced_at_running_phase_bid():
    # A job the round just scheduled must be priced for eviction at its
    # RUNNING-phase bid: the reference reads job.GetBidPrice on the
    # post-round jobdb, where a just-leased job resolves as running
    # (preempting_queue_scheduler.go:637-646 + jobdb getBidPrice).
    from armada_tpu.solver.reference import ReferenceSolver

    queued = [
        JobSpec(
            id="j0",
            queue="q",
            requests={"cpu": "8", "memory": "1Gi"},
            bid_prices={"default": (1.0, 10.0)},  # (queued, running)
        )
    ]
    snap = build_round_snapshot(MKT, "default", [node()], [QueueSpec("q")], [], queued)
    res = ReferenceSolver(snap).solve()
    assert res.scheduled_mask[snap.job_ids.index("j0")]
    result = {
        "assigned_node": res.assigned_node,
        "scheduled_mask": res.scheduled_mask,
        "preempted_mask": res.preempted_mask,
    }
    post = price_gangs(
        snap, {"s": GangDefinition(size=1, resources={"cpu": "8", "memory": "1Gi"})},
        result=result,
    )["s"]
    assert post.schedulable and post.price == 10.0


def test_external_bid_service_json_stringified_band_keys():
    # A response that round-tripped through JSON stringifies int dict keys;
    # band bids keyed "1" must still resolve, not fall to the fallback.
    class FakeClient:
        def retrieve_bids(self):
            return {
                "queue_bids": {"q": {"default": {"1": {"queued": 5.0, "running": 6.0}}}},
                "fallback": {"q": {"default": {"queued": 1.0, "running": 2.0}}},
            }

    snap = ExternalBidPriceService(FakeClient()).get_bid_prices()
    assert snap.get_price("q", 1)["default"] == Bid(5.0, 6.0)


def test_just_leased_nonpreemptible_priced_at_sentinel():
    # A queued NON-preemptible job the round just scheduled resolves to
    # NonPreemptibleRunningPrice in the post-round view (jobdb getBidPrice
    # returns the sentinel for any non-queued non-preemptible job).
    from armada_tpu.snapshot.round import NON_PREEMPTIBLE_RUNNING_PRICE
    from armada_tpu.solver.reference import ReferenceSolver

    cfg = SchedulingConfig(
        priority_classes={
            "np": PriorityClass("np", 2000, preemptible=False),
        },
        default_priority_class="np",
        market_driven=True,
    )
    queued = [
        JobSpec(
            id="j0",
            queue="q",
            priority_class="np",
            requests={"cpu": "8", "memory": "1Gi"},
            bid_prices={"default": (1.0, 10.0)},
        )
    ]
    snap = build_round_snapshot(cfg, "default", [node()], [QueueSpec("q")], [], queued)
    res = ReferenceSolver(snap).solve()
    assert res.scheduled_mask[snap.job_ids.index("j0")]
    assert snap.job_bid_running[0] == NON_PREEMPTIBLE_RUNNING_PRICE
    result = {
        "assigned_node": res.assigned_node,
        "scheduled_mask": res.scheduled_mask,
        "preempted_mask": res.preempted_mask,
    }
    post = price_gangs(
        snap, {"s": GangDefinition(size=1, resources={"cpu": "8", "memory": "1Gi"})},
        result=result,
    )["s"]
    # Only eviction candidate is the sentinel-priced job.
    assert post.schedulable and post.price == NON_PREEMPTIBLE_RUNNING_PRICE
