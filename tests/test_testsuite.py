"""The declarative testsuite runner: the full case library driven against
a local ControlPlane over gRPC (the reference's cmd/testsuite against
testsuite/testcases/{basic,gpu,preemption,reprioritization,categorization,
performance})."""

import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.services.grpc_api import ApiClient
from armada_tpu.services.server import ControlPlane


@pytest.fixture(scope="module")
def plane():
    config = SchedulingConfig(
        priority_classes={
            "ts-default": PriorityClass("ts-default", 1000, preemptible=True),
            "ts-low": PriorityClass("ts-low", 100, preemptible=True),
            "ts-high": PriorityClass("ts-high", 30000, preemptible=False),
        },
        default_priority_class="ts-default",
        protected_fraction_of_fair_share=0.0,
    )
    p = ControlPlane(
        config,
        cycle_period=0.05,
        fake_executors=[
            {
                "name": "ts-exec",
                "nodes": 6,
                "cpu": "16",
                "memory": "64Gi",
                "runtime": 3.0,
                "labels": {"zone": "z1"},
                "extra_resources": {"nvidia.com/gpu": "4"},
            }
        ],
    ).start()
    yield p
    p.stop()


CASES = [
    "basic",
    "gang",
    "gpu",
    "node_selector",
    "reprioritization",
    "categorization",
    "cancellation",
    "performance",
]


@pytest.mark.parametrize("case", CASES)
def test_testsuite_case(plane, case):
    from armada_tpu.testsuite import run_spec_file

    res = run_spec_file(f"testsuite_cases/{case}.yaml", ApiClient(plane.address))
    assert res.passed, f"{res.name}: {res.reason}"


def test_testsuite_preemption(plane):
    """The preemption family needs a full cluster: the low-PC victims fill
    it before the high-PC preemptor batch arrives."""
    from armada_tpu.testsuite import run_spec_file

    res = run_spec_file(
        "testsuite_cases/preemption.yaml", ApiClient(plane.address)
    )
    assert res.passed, f"{res.name}: {res.reason}"
    preempted = [
        jid
        for jid, evs in res.events_by_job.items()
        if "JobRunPreempted" in evs
    ]
    assert preempted, "no job was preempted by the high-PC batch"


def test_testsuite_detects_failure(plane, tmp_path):
    from armada_tpu.testsuite import run_spec_file

    spec = tmp_path / "impossible.yaml"
    spec.write_text(
        """
name: impossible
timeout: 3
queue: ts-imp
jobs:
  - count: 1
    requests: {cpu: "999", memory: 1Gi}
expectedEvents:
  - JobRunLeased
"""
    )
    res = run_spec_file(str(spec), ApiClient(plane.address))
    assert not res.passed
    assert "timeout" in res.reason


def test_load_tester(plane, capsys):
    from armada_tpu.clients.load_tester import main

    rc = main(
        [
            "--server",
            plane.address,
            "--queues",
            "2",
            "--jobs",
            "20",
            "--batch",
            "10",
            "--watch",
            "--timeout",
            "60",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert '"completed": 20' in out


def test_broadside(plane, capsys):
    import json

    from armada_tpu.clients.broadside import main

    rc = main(["--backend", "grpc", "--server", plane.address,
               "--duration", "2",
               "--ingest-actors", "1", "--query-actors", "2", "--batch", "5"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    report = json.loads(out)
    assert rc == 0 and report["backend"] == "grpc"
    assert all(
        report[op]["errors"] == 0
        for op in ("ingest", "get_jobs", "group_jobs", "job_details")
    )
    assert report["ingest"]["ops"] > 0
    assert report["get_jobs"]["ops"] > 0


def test_simulator_cli(tmp_path, capsys):
    from armada_tpu.sim.cli import main

    cluster = tmp_path / "cluster.yaml"
    cluster.write_text(
        """
name: c1
nodeTemplates:
  - count: 4
    cpu: "16"
    memory: 64Gi
"""
    )
    workload = tmp_path / "workload.yaml"
    workload.write_text(
        """
queues:
  - name: qa
    jobTemplates:
      - id: t
        number: 20
        cpu: "1"
        memory: 1Gi
        runtimeMinimum: 30
"""
    )
    rc = main(["--clusters", str(cluster), "--workload", str(workload), "--json"])
    assert rc == 0
    out = capsys.readouterr().out
    import json

    res = json.loads(out.strip().splitlines()[-1])
    assert res["finished_jobs"] == 20
