from fractions import Fraction

import numpy as np
import pytest

from armada_tpu.core.resources import ResourceListFactory, parse_quantity


def test_parse_quantity_forms():
    assert parse_quantity("100m") == Fraction(1, 10)
    assert parse_quantity("1") == 1
    assert parse_quantity("1.5Gi") == 3 * 2**29
    assert parse_quantity("2Ki") == 2048
    assert parse_quantity("2e3") == 2000
    assert parse_quantity(0.5) == Fraction(1, 2)
    assert parse_quantity(3) == 3
    assert parse_quantity("250M") == 250_000_000


def default_factory():
    return ResourceListFactory.create(
        [("memory", "1"), ("cpu", "1m"), ("ephemeral-storage", "1"), ("nvidia.com/gpu", "1")]
    )


def test_factory_scales():
    f = default_factory()
    # cpu resolution 1m -> scale -3 (store millicores); memory scale 0 (bytes)
    assert f.scales[f.index_of("cpu")] == -3
    assert f.scales[f.index_of("memory")] == 0


def test_from_map_rounding():
    f = default_factory()
    req = f.from_map({"cpu": "1500m", "memory": "1Gi"}, ceil=True)
    assert req[f.index_of("cpu")] == 1500
    assert req[f.index_of("memory")] == 2**30
    # sub-resolution quantities: requests round up, allocatable rounds down
    up = f.from_map({"cpu": "0.0001"}, ceil=True)
    down = f.from_map({"cpu": "0.0001"}, ceil=False)
    assert up[f.index_of("cpu")] == 1
    assert down[f.index_of("cpu")] == 0


def test_unknown_resource():
    f = default_factory()
    assert f.from_map({"fancy.io/widget": 3}, ceil=True).sum() == 0
    with pytest.raises(KeyError):
        f.from_map({"fancy.io/widget": 3}, ceil=True, strict=True)


def test_device_scaling_conservative():
    f = default_factory()
    mem = f.index_of("memory")
    # memory device lane is Mi by default
    host = np.zeros((2, f.num_resources), dtype=np.int64)
    host[0, mem] = 2**20 + 1  # just over 1Mi
    host[1, mem] = 2**21  # exactly 2Mi
    req = f.to_device(host, ceil=True)
    alloc = f.to_device(host, ceil=False)
    assert req[0, mem] == 2 and alloc[0, mem] == 1
    assert req[1, mem] == 2 and alloc[1, mem] == 2


def test_roundtrip_to_map():
    f = default_factory()
    vec = f.from_map({"cpu": "2", "memory": "1Ki"}, ceil=True)
    decoded = f.to_map(vec)
    assert decoded["cpu"] == 2
    assert decoded["memory"] == 1024
