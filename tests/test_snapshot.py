import numpy as np

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import (
    Gang,
    JobSpec,
    NodeSpec,
    QueueSpec,
    RunningJob,
    Taint,
    Toleration,
)
from armada_tpu.snapshot.round import NO_NODE, build_round_snapshot


def mk_nodes(n=4, cpu="32", mem="256Gi", **kw):
    return [
        NodeSpec(
            id=f"node-{i}",
            pool="default",
            total_resources={"cpu": cpu, "memory": mem},
            **kw,
        )
        for i in range(n)
    ]


def mk_job(i, queue="q", cpu="1", mem="1Gi", **kw):
    return JobSpec(
        id=f"job-{i:04d}",
        queue=queue,
        requests={"cpu": cpu, "memory": mem},
        submitted_ts=float(i),
        **kw,
    )


def test_snapshot_shapes_and_totals():
    cfg = SchedulingConfig()
    nodes = mk_nodes(4)
    queued = [mk_job(i) for i in range(10)]
    snap = build_round_snapshot(
        cfg, "default", nodes, [QueueSpec("q")], [], queued
    )
    assert snap.num_nodes == 4 and snap.num_jobs == 10 and snap.num_queues == 1
    # priorities: evicted row + 1000 (both default classes share priority 1000)
    assert list(snap.priorities) == [-1, 1000]
    cpu = snap.factory.index_of("cpu")
    assert snap.total_resources[cpu] == 4 * 32_000
    # no running jobs: allocatable == total on every priority row
    assert (snap.allocatable == snap.node_total[None]).all()


def test_running_job_binding():
    cfg = SchedulingConfig()
    nodes = mk_nodes(2)
    job = mk_job(0, priority_class="armada-preemptible")
    running = [RunningJob(job=job, node_id="node-1", scheduled_at_priority=1000)]
    snap = build_round_snapshot(
        cfg, "default", nodes, [QueueSpec("q")], running, []
    )
    cpu = snap.factory.index_of("cpu")
    n1 = snap.node_ids.index("node-1")
    evicted_row = snap.priority_row(-1)
    prio_row = snap.priority_row(1000)
    # bound at priority 1000: subtracted from rows <= 1000, i.e. both rows
    assert snap.allocatable[evicted_row, n1, cpu] == 32_000 - 1000
    assert snap.allocatable[prio_row, n1, cpu] == 32_000 - 1000
    assert snap.queue_allocated[0, cpu] == 1000
    assert snap.job_is_running[0] and snap.job_node[0] == n1


def test_taints_and_selectors():
    cfg = SchedulingConfig()
    tainted = NodeSpec(
        id="gpu-node",
        pool="default",
        taints=(Taint("gpu", "true", "NoSchedule"),),
        labels={"zone": "a"},
        total_resources={"cpu": "8", "memory": "32Gi"},
    )
    plain = NodeSpec(
        id="cpu-node",
        pool="default",
        labels={"zone": "b"},
        total_resources={"cpu": "8", "memory": "32Gi"},
    )
    tolerant = mk_job(0, tolerations=(Toleration(key="gpu", value="true"),))
    selective = mk_job(1, node_selector={"zone": "a"})
    impossible = mk_job(2, node_selector={"zone": "nowhere"})
    snap = build_round_snapshot(
        cfg, "default", [tainted, plain], [QueueSpec("q")], [],
        [tolerant, selective, impossible],
    )
    gpu_i = snap.node_ids.index("gpu-node")
    cpu_i = snap.node_ids.index("cpu-node")
    # taint bits: gpu node has the taint bit, job 0 tolerates it
    assert snap.node_taint_bits[gpu_i].any()
    assert not snap.node_taint_bits[cpu_i].any()
    assert (snap.job_tolerated[0] & snap.node_taint_bits[gpu_i]).any()
    # untolerated: job 1 on gpu node blocked
    assert (snap.node_taint_bits[gpu_i] & ~snap.job_tolerated[1]).any()
    # selector bits: job 1 requires zone=a which only gpu node carries
    sel = snap.job_selector[1]
    assert (sel & ~snap.node_label_bits[gpu_i]).sum() == 0
    assert (sel & ~snap.node_label_bits[cpu_i]).sum() != 0
    # unsatisfiable selector flagged
    assert not snap.job_possible[2]
    assert snap.job_possible[0] and snap.job_possible[1]


def test_gang_grouping():
    cfg = SchedulingConfig()
    gang = Gang(id="g1", cardinality=3)
    jobs = [mk_job(i, gang=gang) for i in range(3)] + [mk_job(3)]
    snap = build_round_snapshot(
        cfg, "default", mk_nodes(2), [QueueSpec("q")], [], jobs
    )
    assert snap.num_gangs == 2
    gang_sizes = np.diff(snap.gang_member_offsets)
    assert sorted(gang_sizes.tolist()) == [1, 3]
    g3 = int(np.argmax(gang_sizes == 3))
    assert snap.gang_complete[g3]
    cpu = snap.factory.index_of("cpu")
    assert snap.gang_total_req[g3, cpu] == 3000
    # gang becomes schedulable at its last member's rank
    members = snap.gang_members[
        snap.gang_member_offsets[g3] : snap.gang_member_offsets[g3 + 1]
    ]
    assert snap.gang_order[g3] == max(snap.job_order[m] for m in members)


def test_incomplete_gang_flagged():
    cfg = SchedulingConfig()
    gang = Gang(id="g1", cardinality=4)
    jobs = [mk_job(i, gang=gang) for i in range(2)]
    snap = build_round_snapshot(
        cfg, "default", mk_nodes(1), [QueueSpec("q")], [], jobs
    )
    g = int(snap.job_gang[0])
    assert not snap.gang_complete[g]


def test_queue_order_priority_then_time():
    cfg = SchedulingConfig()
    early_low = mk_job(0)  # priority 0, ts 0
    late_urgent = mk_job(1).with_(priority=-5)
    snap = build_round_snapshot(
        cfg, "default", mk_nodes(1), [QueueSpec("q")], [], [early_low, late_urgent]
    )
    # lower priority number schedules first
    assert snap.job_order[1] < snap.job_order[0]
