"""Node-affinity expressions (In/NotIn/Exists/DoesNotExist/Gt/Lt), with
kernel/oracle parity. NodeAffinityRequirementsMet in the reference
(nodematching.go:242-255)."""

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import (
    Affinity,
    JobSpec,
    MatchExpression,
    NodeSelectorTerm,
    NodeSpec,
    QueueSpec,
)
from armada_tpu.snapshot.round import build_round_snapshot
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round
from armada_tpu.solver.reference import ReferenceSolver


def nodes():
    return [
        NodeSpec(id="n-a1", pool="default", labels={"zone": "a", "gen": "7"},
                 total_resources={"cpu": "8", "memory": "32Gi"}),
        NodeSpec(id="n-a2", pool="default", labels={"zone": "a", "gen": "5"},
                 total_resources={"cpu": "8", "memory": "32Gi"}),
        NodeSpec(id="n-b1", pool="default", labels={"zone": "b", "gen": "6"},
                 total_resources={"cpu": "8", "memory": "32Gi"}),
        NodeSpec(id="n-x", pool="default", labels={},
                 total_resources={"cpu": "8", "memory": "32Gi"}),
    ]


def solve(jobs):
    snap = build_round_snapshot(
        SchedulingConfig(), "default", nodes(), [QueueSpec("q")], [], jobs
    )
    oracle = ReferenceSolver(snap).solve()
    out = solve_round(pad_device_round(prep_device_round(snap)))
    J = snap.num_jobs
    assert (oracle.assigned_node == out["assigned_node"][:J]).all()
    assert (oracle.scheduled_mask == out["scheduled_mask"][:J]).all()
    return snap, oracle


def aff_job(i, *terms):
    return JobSpec(
        id=f"j{i}", queue="q", requests={"cpu": "1", "memory": "1Gi"},
        submitted_ts=float(i),
        affinity=Affinity(terms=tuple(NodeSelectorTerm(expressions=t) for t in terms)),
    )


def placed(snap, res, jid):
    j = snap.job_ids.index(jid)
    assert res.scheduled_mask[j], f"{jid} not scheduled"
    return snap.node_ids[res.assigned_node[j]]


def test_in_operator():
    snap, res = solve([aff_job(0, (MatchExpression("zone", "In", ("b",)),))])
    assert placed(snap, res, "j0") == "n-b1"


def test_notin_matches_absent_key():
    # k8s NotIn matches nodes lacking the key too (labels.Requirement)
    snap, res = solve([aff_job(0, (MatchExpression("zone", "NotIn", ("a",)),))])
    assert placed(snap, res, "j0") in ("n-b1", "n-x")


def test_empty_term_matches_nothing():
    # k8s MatchNodeSelectorTerms: an empty term matches no objects
    snap, res = solve([aff_job(0, ())])
    assert res.scheduled_mask.sum() == 0


def test_unknown_operator_rejected_at_submission():
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.submit import SubmissionError, SubmitService

    submit = SubmitService(SchedulingConfig(), InMemoryEventLog())
    submit.create_queue(QueueSpec("q"))
    bad = aff_job(0, (MatchExpression("zone", "Equals", ("a",)),))
    with pytest.raises(SubmissionError):
        submit.submit("q", "s", [bad])


def test_exists_and_doesnotexist():
    snap, res = solve([aff_job(0, (MatchExpression("zone", "DoesNotExist"),))])
    assert placed(snap, res, "j0") == "n-x"
    snap, res = solve([aff_job(1, (MatchExpression("gen", "Exists"),))])
    assert placed(snap, res, "j1") in ("n-a1", "n-a2", "n-b1")


def test_gt_lt_numeric():
    snap, res = solve([aff_job(0, (MatchExpression("gen", "Gt", ("6",)),))])
    assert placed(snap, res, "j0") == "n-a1"  # gen 7 only
    snap, res = solve([aff_job(1, (MatchExpression("gen", "Lt", ("6",)),))])
    assert placed(snap, res, "j1") == "n-a2"  # gen 5 only


def test_terms_are_or_expressions_are_and():
    # (zone=a AND gen>6) OR (zone=b)
    snap, res = solve([
        aff_job(
            0,
            (MatchExpression("zone", "In", ("a",)), MatchExpression("gen", "Gt", ("6",))),
            (MatchExpression("zone", "In", ("b",)),),
        )
    ])
    assert placed(snap, res, "j0") in ("n-a1", "n-b1")


def test_unsatisfiable_affinity_blocks():
    snap, res = solve([aff_job(0, (MatchExpression("zone", "In", ("nowhere",)),))])
    assert res.scheduled_mask.sum() == 0


def test_affinity_groups_shared():
    jobs = [aff_job(i, (MatchExpression("zone", "In", ("b",)),)) for i in range(4)]
    snap, res = solve(jobs)
    # all share one affinity group
    groups = set(snap.job_affinity_group.tolist())
    assert groups == {0}
    assert res.scheduled_mask.sum() == 4
    assert all(
        snap.node_ids[res.assigned_node[j]] == "n-b1" for j in range(4)
    )


def test_affinity_over_grpc():
    from armada_tpu.services.grpc_api import job_spec_from_dict

    spec = job_spec_from_dict(
        {
            "requests": {"cpu": "1"},
            "affinity": [[{"key": "zone", "operator": "In", "values": ["b"]}]],
        }
    )
    assert spec.affinity.terms[0].expressions[0].key == "zone"
    assert spec.affinity.matches({"zone": "b"})
    assert not spec.affinity.matches({"zone": "a"})
