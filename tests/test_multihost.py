"""Two-level (hosts, chips) mesh: parity and mesh-shape edge cases.

The HierarchicalDist solve decomposes every shard-crossing collective
into ICI-within-host + DCN-across-hosts stages (solver/dist.py); these
tests pin the bit-exactness claims that make the decomposition safe:

  - a 2x4 mesh reproduces the single-device solve bit-for-bit on the
    mixed-fleet scenarios (away pools, a market pool, mixed gangs);
  - pad_nodes handles node counts that do not divide hosts*chips;
  - a degenerate single-host 2D mesh (1xN) equals the 1D N-mesh
    bit-for-bit (the host stage reduces over one element);
  - a 1x1 mesh equals LOCAL (both stages are identities);
  - CollectiveStats books the DCN bill as O(hosts x keys) per select,
    independent of the chip count.

The 8 virtual CPU devices come from conftest
(xla_force_host_platform_device_count=8): a 2x4 mesh in one process.
The multi-PROCESS version of the same assertions is the slow-marked
tests/test_dcn_dryrun.py."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from armada_tpu.parallel.mesh import make_node_mesh, node_sharded_solve, pad_nodes
from armada_tpu.parallel.multihost import (
    MeshSpec,
    hierarchical_sharded_solve,
    make_host_mesh,
    parse_mesh_spec,
    resolve_solver,
)
from armada_tpu.parallel.scenarios import mixed_fleet_rounds
from armada_tpu.solver.kernel import solve_round
from armada_tpu.solver.kernel_prep import pad_device_round, prep_device_round

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


@pytest.fixture(scope="module")
def mesh_2x4():
    return make_host_mesh(2, 4)


@pytest.fixture(scope="module")
def solve_2x4(mesh_2x4):
    return hierarchical_sharded_solve(mesh_2x4)


def _rounds(n_nodes=32, n_jobs=96):
    """Small mixed-fleet rounds: away pool + market pool + mixed gangs,
    the same generator the fleet-scale dryruns use. Extents are tuned
    for tier-1 wall clock on a 1-core box driving 8 virtual devices:
    fill loops dominate, so jobs stay low; the fleet-scale extents live
    in dryrun_multichip and the slow-marked DCN dryrun."""
    return mixed_fleet_rounds(n_nodes, n_jobs)


def _dev(snap, multiple):
    return pad_nodes(pad_device_round(prep_device_round(snap)), multiple)


def _assert_equal(a, b, label):
    for k, v in a.items():
        assert np.array_equal(
            np.asarray(b[k]), np.asarray(v), equal_nan=True
        ), f"{label}: {k} diverges"


def test_two_level_parity_mixed_fleet(solve_2x4):
    """2x4 hierarchy == single device, bit-for-bit, on away + market
    rounds with gangs and running jobs."""
    for label, snap in _rounds():
        dev = _dev(snap, 8)
        single = solve_round(dev)
        multi = solve_2x4(dev)
        _assert_equal(single, multi, f"2x4-{label}")
        assert int(np.asarray(single["scheduled_mask"]).sum()) > 0, label


def test_pad_nodes_indivisible(solve_2x4):
    """Node counts that do not divide hosts*chips=8: inert padding must
    not change any placement. One representative count tier-1; the
    (9, 50) sweep rides the slow marker per conftest policy."""
    for n_nodes in (21,):
        label, snap = _rounds(n_nodes=n_nodes, n_jobs=64)[0]
        dev = _dev(snap, 8)
        assert dev.node_total.shape[0] % 8 == 0
        _assert_equal(
            solve_round(dev), solve_2x4(dev), f"indivisible-{n_nodes}"
        )


@pytest.mark.slow
def test_pad_nodes_indivisible_sweep(solve_2x4):
    for n_nodes in (9, 50):
        label, snap = _rounds(n_nodes=n_nodes, n_jobs=64)[0]
        dev = _dev(snap, 8)
        _assert_equal(
            solve_round(dev), solve_2x4(dev), f"indivisible-{n_nodes}"
        )


def test_degenerate_single_host_equals_1d():
    """A 1x8 two-level mesh (host stage reduces over one element) must
    equal the 1D 8-shard mesh bit-for-bit — same winners, same order."""
    flat = node_sharded_solve(make_node_mesh(jax.devices()[:8]))
    degenerate = hierarchical_sharded_solve(make_host_mesh(1, 8))
    label, snap = _rounds(n_nodes=32, n_jobs=64)[0]
    dev = _dev(snap, 8)
    _assert_equal(flat(dev), degenerate(dev), "1x8-vs-1d")
    # The degenerate host axis books zero extra selects relative to the
    # flat path — but its DCN bill is O(1 host x keys): effectively free.
    assert degenerate.stats.per_select_dcn_scalars < (
        degenerate.stats.per_select_ici_scalars
    )


def test_1x1_mesh_equals_local():
    """A 1x1 mesh: both reduction stages are single-element — the
    sharded program must equal the LOCAL solve exactly."""
    one = hierarchical_sharded_solve(make_host_mesh(1, 1))
    label, snap = _rounds(n_nodes=12, n_jobs=24)[0]
    dev = _dev(snap, 1)
    _assert_equal(solve_round(dev), one(dev), "1x1-vs-local")


def test_collective_stats_dcn_scaling(solve_2x4):
    """The per-select DCN bill is one winner tuple per HOST —
    O(hosts x keys) scalars, the chip count cancels."""
    stats = solve_2x4.stats
    assert stats.n_hosts == 2 and stats.n_chips == 4
    assert stats.selects > 0 and stats.fills >= 0
    assert stats.per_select_dcn_scalars > 0
    # hosts x (keys + found + idx): per-select DCN traffic carries the
    # host fan-in (2), the ICI stage the chip fan-in (4).
    assert stats.per_select_dcn_scalars == (
        stats.per_select_ici_scalars // 2
    )
    assert 0 < stats.dcn_bytes < stats.ici_bytes


def test_parse_mesh_spec():
    assert parse_mesh_spec(8) == MeshSpec(1, 8)
    assert parse_mesh_spec("2x4") == MeshSpec(2, 4)
    assert parse_mesh_spec("2X4") == MeshSpec(2, 4)
    assert parse_mesh_spec((2, 4)) == MeshSpec(2, 4)
    assert parse_mesh_spec(MeshSpec(4, 2)) == MeshSpec(4, 2)
    assert parse_mesh_spec(Mesh(np.asarray(jax.devices()[:4]), ("nodes",))) \
        == MeshSpec(1, 4)
    assert parse_mesh_spec(make_host_mesh(2, 2)) == MeshSpec(2, 2)
    for bad in (0, -2, "0x4", "2x0", (2, -1), "nonsense"):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)


def test_resolve_solver_shapes():
    """The shared seam: int -> 1D path, "HxC" -> hierarchy, with the
    mesh shape and shard count surfaced for padding + metrics."""
    flat = resolve_solver(8)
    assert flat.n_shards == 8 and flat.mesh_shape == (8,)
    two = resolve_solver("2x4")
    assert two.n_shards == 8 and two.mesh_shape == (2, 4)
    assert two.stats.n_hosts == 2
    with pytest.raises(RuntimeError):
        resolve_solver("4x4")  # 16 devices on an 8-device platform


def test_mesh_metrics_surface():
    """The DCN cost-model gauges exist and render: mesh extent,
    per-kind collective sites, per-level bytes, per-select DCN scalars,
    per-host shard-solve wall clock."""
    from armada_tpu.services.metrics import SchedulerMetrics

    m = SchedulerMetrics()
    if m.registry is None:
        pytest.skip("prometheus_client unavailable")
    m.solve_mesh_extent.labels(axis="hosts").set(2)
    m.solve_mesh_extent.labels(axis="chips").set(4)
    m.solve_collective_sites.labels(kind="selects").set(78)
    m.solve_collective_bytes.labels(level="dcn").set(57_672_790)
    m.solve_dcn_scalars_per_select.set(14)
    m.shard_solve_time.labels(pool="default").observe(1.0)
    text = m.render().decode()
    for needle in (
        'scheduler_solve_mesh_extent{axis="hosts"} 2.0',
        'scheduler_solve_collective_sites{kind="selects"} 78.0',
        'scheduler_solve_collective_bytes{level="dcn"} 5.767279e+07',
        "scheduler_solve_dcn_scalars_per_select 14.0",
        'scheduler_shard_solve_seconds_count{pool="default"} 1.0',
    ):
        assert needle in text, needle


def test_make_host_mesh_validation():
    with pytest.raises(ValueError):
        make_host_mesh(3, 4)  # 12 > 8 devices
    with pytest.raises(ValueError):
        # a 1D mesh is not a (hosts, chips) mesh
        hierarchical_sharded_solve(make_node_mesh(jax.devices()[:8]))
