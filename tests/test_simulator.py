"""Simulation tests: whole cluster+workload scenarios through the real
scheduling path in virtual time (the reference's simulator_test.go model).
BASELINE config #1: 1 cluster, 1 queue, 1k CPU jobs x 100 nodes."""

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.sim import (
    ClusterSpec,
    JobTemplate,
    QueueSpecSim,
    Simulator,
    WorkloadSpec,
)
from armada_tpu.sim.simulator import NodeTemplate, ShiftedExponential


def test_basic_workload_completes():
    """Mirror of the reference basicWorkload on cpu_1_1_100: every job runs
    to completion."""
    sim = Simulator(
        [ClusterSpec("cluster-1", node_templates=(NodeTemplate(count=10, cpu="32"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "queue-a",
                    job_templates=(
                        JobTemplate(
                            id="basic",
                            number=50,
                            cpu="1",
                            memory="4Gi",
                            runtime=ShiftedExponential(minimum=60.0),
                        ),
                    ),
                ),
            )
        ),
        seed=1,
    )
    res = sim.run()
    assert res.finished_jobs == res.total_jobs == 50
    assert res.preemptions == 0
    # 50 one-cpu jobs on 320 cores: one wave, makespan ~ one runtime
    assert res.makespan < 300


def test_backlog_multiple_waves():
    sim = Simulator(
        [ClusterSpec("c", node_templates=(NodeTemplate(count=2, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "q",
                    job_templates=(
                        JobTemplate(
                            id="wave",
                            number=64,
                            cpu="1",
                            memory="1Gi",
                            runtime=ShiftedExponential(minimum=30.0),
                        ),
                    ),
                ),
            )
        ),
    )
    res = sim.run()
    assert res.finished_jobs == 64
    # 16 cores, 64 jobs x 30s -> at least 4 waves
    assert res.makespan >= 4 * 30.0 - 1


def test_two_queues_fair_progress():
    sim = Simulator(
        [ClusterSpec("c", node_templates=(NodeTemplate(count=4, cpu="16"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "alice",
                    job_templates=(
                        JobTemplate(id="a", number=40, cpu="2", memory="2Gi",
                                    runtime=ShiftedExponential(minimum=50.0)),
                    ),
                ),
                QueueSpecSim(
                    "bob",
                    job_templates=(
                        JobTemplate(id="b", number=40, cpu="2", memory="2Gi",
                                    runtime=ShiftedExponential(minimum=50.0)),
                    ),
                ),
            )
        ),
    )
    res = sim.run()
    assert res.finished_jobs == 80


def test_gang_workload():
    sim = Simulator(
        [ClusterSpec("c", node_templates=(NodeTemplate(count=8, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "q",
                    job_templates=(
                        JobTemplate(
                            id="gangs",
                            number=16,
                            cpu="8",
                            memory="4Gi",
                            gang_cardinality=4,
                            runtime=ShiftedExponential(minimum=60.0),
                        ),
                    ),
                ),
            )
        ),
    )
    res = sim.run()
    assert res.finished_jobs == 16


def test_preemption_under_contention():
    cfg = SchedulingConfig(
        priority_classes={
            "low": PriorityClass("low", 1000, preemptible=True),
            "high": PriorityClass("high", 30000, preemptible=False),
        },
        default_priority_class="low",
        protected_fraction_of_fair_share=0.5,
    )
    sim = Simulator(
        [ClusterSpec("c", node_templates=(NodeTemplate(count=2, cpu="8"),))],
        WorkloadSpec(
            queues=(
                QueueSpecSim(
                    "greedy",
                    job_templates=(
                        JobTemplate(id="long", number=16, cpu="1", memory="1Gi",
                                    runtime=ShiftedExponential(minimum=4000.0)),
                    ),
                ),
                QueueSpecSim(
                    "urgent",
                    job_templates=(
                        JobTemplate(id="hi", number=8, cpu="1", memory="1Gi",
                                    priority_class="high", submit_time=100.0,
                                    runtime=ShiftedExponential(minimum=60.0)),
                    ),
                ),
            )
        ),
        config=cfg,
        max_time=20_000.0,
    )
    res = sim.run()
    # Preemption is terminal (the reference fails preempted jobs; users
    # resubmit): urgent all succeed, preempted greedy jobs do not.
    assert res.preemptions > 0  # greedy got knocked back at t=100
    from armada_tpu.jobdb import JobState

    urgent_states = {
        jid: s for jid, s in res.events_by_job.items() if jid.startswith("urgent")
    }
    assert all(s == JobState.SUCCEEDED for s in urgent_states.values())
    assert res.finished_jobs == res.total_jobs - res.preemptions
