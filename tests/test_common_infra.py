"""Common infrastructure: health endpoints, payload compression, tracing,
config loading (common/{health,compress,observability,config} analogues)."""

import json
import time
import urllib.request

from armada_tpu.core.config import SchedulingConfig, load_config, validate_config
from armada_tpu.services.health import (
    FuncChecker,
    HeartbeatChecker,
    MultiChecker,
    StartupCompleteChecker,
    serve_health,
)
from armada_tpu.utils.compress import compress_obj, decompress_obj
from armada_tpu.utils.tracing import Tracer, profile_cpu


def test_health_endpoint_and_checkers():
    startup = StartupCompleteChecker()
    hb = HeartbeatChecker("cycle", timeout_s=60.0)
    multi = MultiChecker(startup, hb, FuncChecker("log", lambda: (True, "ok")))
    server, port = serve_health(multi, startup)
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/health/startup") as r:
            assert r.status == 503  # not started yet... urllib raises on 503
    except urllib.error.HTTPError as e:
        assert e.code == 503
    startup.mark_complete()
    hb.beat()
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/health") as r:
        body = json.loads(r.read())
        assert r.status == 200 and body["ok"]
        assert set(body["checks"]) == {"startup", "cycle", "log"}
    server.shutdown()


def test_heartbeat_checker_times_out():
    hb = HeartbeatChecker("cycle", timeout_s=0.01)
    time.sleep(0.05)
    ok, detail = hb.check()
    assert not ok and "last beat" in detail


def test_solver_ladder_checker_is_advisory():
    """A degraded ladder or recent rejections stay HEALTHY (restarting
    would discard the breaker state routing around the fault) but name
    the degraded rungs and point at `armadactl doctor`."""
    from armada_tpu.services.health import SolverLadderChecker

    class Degraded:
        def doctor_report(self):
            return {
                "ladder": [
                    {"rung": "LOCAL", "state": "closed"},
                    {"rung": "hotwindow:64", "state": "half-open"},
                ],
                "rejections": [{"cycle": 3}],
            }

    ok, detail = SolverLadderChecker(Degraded()).check()
    assert ok
    assert "hotwindow:64=half-open" in detail and "LOCAL" not in detail
    assert "1 recent round rejection" in detail and "doctor" in detail

    class Healthy:
        def doctor_report(self):
            return {"ladder": [{"rung": "oracle", "state": "closed"}],
                    "rejections": []}

    ok, detail = SolverLadderChecker(Healthy()).check()
    assert ok and "all solver rungs closed" in detail

    class NoLadder:
        doctor_report = None

    ok, detail = SolverLadderChecker(NoLadder()).check()
    assert ok and "no solve ladder" in detail


def test_compress_roundtrip_and_threshold():
    small = {"id": "x"}
    assert compress_obj(small) == small  # below threshold: unchanged
    big = {"data": "y" * 10_000}
    packed = compress_obj(big)
    assert "__zlib__" in packed
    assert len(json.dumps(packed)) < len(json.dumps(big)) // 5
    assert decompress_obj(packed) == big
    assert decompress_obj(small) == small


def test_tracer_spans_and_summary():
    tracer = Tracer()
    with tracer.span("cycle", pool="default"):
        with tracer.span("solve"):
            pass
    summary = tracer.summary()
    assert summary["cycle"]["count"] == 1
    assert summary["solve"]["count"] == 1
    assert tracer.finished[-1].name == "cycle"
    assert tracer.finished[0].parent == "cycle"


def test_profile_cpu(tmp_path):
    out = tmp_path / "profile.pstats"
    with profile_cpu(str(out)):
        sum(range(1000))
    import pstats

    stats = pstats.Stats(str(out))
    assert stats.total_calls >= 1


def test_load_config_env_override_and_validation(tmp_path):
    cfg_file = tmp_path / "config.yaml"
    cfg_file.write_text(
        "scheduling:\n  maxQueueLookback: 1234\n  enableFastFill: false\n"
    )
    cfg = load_config(
        str(cfg_file),
        env={"ARMADA__enableFastFill": "true", "IGNORED": "x"},
    )
    assert cfg.max_queue_lookback == 1234
    assert cfg.enable_fast_fill is True
    validate_config(SchedulingConfig())
    try:
        load_config(env={"ARMADA__defaultPriorityClassName": "ghost"})
        assert False, "expected validation failure"
    except ValueError as e:
        assert "priority class" in str(e)


def test_otlp_json_file_exporter(tmp_path):
    """Spans export in the OTLP/JSON resourceSpans shape with trace/span
    id propagation — the exporter the in-proc tracer plugs into
    (common/observability's OTel init analogue)."""
    import json

    from armada_tpu.utils.tracing import OtlpJsonFileExporter, Tracer

    path = str(tmp_path / "spans.otlp.jsonl")
    tracer = Tracer(exporter=OtlpJsonFileExporter(path), export_every=100)
    with tracer.span("cycle", pool="default") as outer:
        with tracer.span("solve") as inner:
            pass
    tracer.flush()

    lines = open(path).read().strip().splitlines()
    assert len(lines) == 1
    batch = json.loads(lines[0])
    resource = batch["resourceSpans"][0]
    svc = resource["resource"]["attributes"][0]
    assert svc["key"] == "service.name"
    spans = resource["scopeSpans"][0]["spans"]
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"cycle", "solve"}
    # Child joins the parent's trace and points at its span id.
    assert by_name["solve"]["traceId"] == by_name["cycle"]["traceId"]
    assert by_name["solve"]["parentSpanId"] == by_name["cycle"]["spanId"]
    assert by_name["cycle"]["parentSpanId"] == ""
    assert int(by_name["cycle"]["endTimeUnixNano"]) >= int(
        by_name["cycle"]["startTimeUnixNano"]
    )
    assert by_name["cycle"]["attributes"][0] == {
        "key": "pool", "value": {"stringValue": "default"},
    }


class _CollectExporter:
    def __init__(self):
        self.batches = []

    def export(self, spans):
        self.batches.append(list(spans))


class _BoomExporter:
    def export(self, spans):
        raise RuntimeError("collector down")


def test_tracer_flush_on_interval():
    """A low-traffic process must not hold spans hostage to the batch
    size: once export_interval_s elapses, the next finished span
    triggers a flush even far below export_every."""
    import time as _t

    exporter = _CollectExporter()
    tracer = Tracer(exporter=exporter, export_every=1000,
                    export_interval_s=0.05)
    with tracer.span("early"):
        pass
    assert exporter.batches == []  # within the interval, batch too small
    _t.sleep(0.06)
    with tracer.span("late"):
        pass
    assert len(exporter.batches) == 1
    assert [s.name for s in exporter.batches[0]] == ["early", "late"]


def test_tracer_atexit_drains_final_batch(monkeypatch):
    """Building a Tracer with an exporter registers its flush with
    atexit, so the final sub-batch is not lost at process exit."""
    import atexit

    registered = []
    monkeypatch.setattr(atexit, "register", registered.append)
    exporter = _CollectExporter()
    tracer = Tracer(exporter=exporter, export_every=1000)
    with tracer.span("tail"):
        pass
    assert exporter.batches == []
    assert registered == [tracer.flush]
    registered[0]()  # what atexit runs at interpreter shutdown
    assert [s.name for s in exporter.batches[0]] == ["tail"]


def test_tracer_exporter_failure_caps_pending_and_recovers():
    """A raising exporter must not grow _pending without bound (capped,
    oldest dropped) nor lose the batch silently once it heals; the
    finished ring buffer stays authoritative throughout."""
    tracer = Tracer(exporter=_BoomExporter(), export_every=1, keep=100,
                    max_pending=3)
    for i in range(8):
        with tracer.span(f"s{i}"):
            pass
    assert tracer.export_failures >= 1
    assert len(tracer._pending) == 3  # capped, not 8
    assert len(tracer.finished) == 8  # ring buffer unaffected
    # Collector heals: the retained tail drains on the next flush.
    healed = _CollectExporter()
    tracer.exporter = healed
    tracer.flush()
    assert [s.name for s in healed.batches[0]] == ["s5", "s6", "s7"]
    assert tracer._pending == []


def test_traceparent_parse_and_format():
    from armada_tpu.utils.tracing import (
        format_traceparent,
        parse_traceparent,
    )

    tp = format_traceparent("ab" * 16, "cd" * 8)
    assert tp == f"00-{'ab' * 16}-{'cd' * 8}-01"
    assert parse_traceparent(tp) == ("ab" * 16, "cd" * 8)
    assert parse_traceparent("") is None
    assert parse_traceparent(None) is None
    assert parse_traceparent("00-short-bad-01") is None
    # all-zero ids are invalid per the W3C spec
    assert parse_traceparent(f"00-{'0' * 32}-{'cd' * 8}-01") is None
    # a remote parent is adopted only when there is no local parent
    tracer = Tracer()
    with tracer.span("root", remote_parent=tp) as root:
        assert root.trace_id == "ab" * 16
        assert root.parent_id == "cd" * 8
        with tracer.span("child", remote_parent=None) as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
    # malformed headers start a fresh trace instead of failing the RPC
    with tracer.span("fresh", remote_parent="garbage") as fresh:
        assert fresh.trace_id not in ("", "ab" * 16)


def test_background_task_manager():
    """common/task BackgroundTaskManager semantics: interval between
    RETURNS, panic containment per task, join-on-stop with straggler
    reporting."""
    import threading
    import time as _t

    from armada_tpu.utils.tasks import BackgroundTaskManager

    mgr = BackgroundTaskManager()
    runs = {"ok": 0}

    def ok():
        runs["ok"] += 1

    def bad():
        raise RuntimeError("boom")

    mgr.register(ok, 0.01, "ok")
    mgr.register(bad, 0.01, "bad")
    deadline = _t.time() + 5
    while _t.time() < deadline and (
        runs["ok"] < 3 or mgr.stats()["bad"]["failures"] < 3
    ):
        _t.sleep(0.01)
    stats = mgr.stats()
    assert stats["ok"]["runs"] >= 3
    assert stats["bad"]["failures"] >= 3  # contained, siblings unaffected
    assert mgr.stop_all(timeout=2.0) == []
    n = stats["ok"]["runs"]
    _t.sleep(0.05)
    assert mgr.stats()["ok"]["runs"] <= n + 1  # actually stopped

    # A straggler (blocked task) is reported, not hung on forever.
    mgr2 = BackgroundTaskManager()
    release = threading.Event()
    mgr2.register(lambda: release.wait(30), 0.01, "stuck")
    _t.sleep(0.05)
    assert mgr2.stop_all(timeout=0.2) == ["stuck"]
    release.set()
