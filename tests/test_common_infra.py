"""Common infrastructure: health endpoints, payload compression, tracing,
config loading (common/{health,compress,observability,config} analogues)."""

import json
import time
import urllib.request

from armada_tpu.core.config import SchedulingConfig, load_config, validate_config
from armada_tpu.services.health import (
    FuncChecker,
    HeartbeatChecker,
    MultiChecker,
    StartupCompleteChecker,
    serve_health,
)
from armada_tpu.utils.compress import compress_obj, decompress_obj
from armada_tpu.utils.tracing import Tracer, profile_cpu


def test_health_endpoint_and_checkers():
    startup = StartupCompleteChecker()
    hb = HeartbeatChecker("cycle", timeout_s=60.0)
    multi = MultiChecker(startup, hb, FuncChecker("log", lambda: (True, "ok")))
    server, port = serve_health(multi, startup)
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/health/startup") as r:
            assert r.status == 503  # not started yet... urllib raises on 503
    except urllib.error.HTTPError as e:
        assert e.code == 503
    startup.mark_complete()
    hb.beat()
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/health") as r:
        body = json.loads(r.read())
        assert r.status == 200 and body["ok"]
        assert set(body["checks"]) == {"startup", "cycle", "log"}
    server.shutdown()


def test_heartbeat_checker_times_out():
    hb = HeartbeatChecker("cycle", timeout_s=0.01)
    time.sleep(0.05)
    ok, detail = hb.check()
    assert not ok and "last beat" in detail


def test_compress_roundtrip_and_threshold():
    small = {"id": "x"}
    assert compress_obj(small) == small  # below threshold: unchanged
    big = {"data": "y" * 10_000}
    packed = compress_obj(big)
    assert "__zlib__" in packed
    assert len(json.dumps(packed)) < len(json.dumps(big)) // 5
    assert decompress_obj(packed) == big
    assert decompress_obj(small) == small


def test_tracer_spans_and_summary():
    tracer = Tracer()
    with tracer.span("cycle", pool="default"):
        with tracer.span("solve"):
            pass
    summary = tracer.summary()
    assert summary["cycle"]["count"] == 1
    assert summary["solve"]["count"] == 1
    assert tracer.finished[-1].name == "cycle"
    assert tracer.finished[0].parent == "cycle"


def test_profile_cpu(tmp_path):
    out = tmp_path / "profile.pstats"
    with profile_cpu(str(out)):
        sum(range(1000))
    import pstats

    stats = pstats.Stats(str(out))
    assert stats.total_calls >= 1


def test_load_config_env_override_and_validation(tmp_path):
    cfg_file = tmp_path / "config.yaml"
    cfg_file.write_text(
        "scheduling:\n  maxQueueLookback: 1234\n  enableFastFill: false\n"
    )
    cfg = load_config(
        str(cfg_file),
        env={"ARMADA__enableFastFill": "true", "IGNORED": "x"},
    )
    assert cfg.max_queue_lookback == 1234
    assert cfg.enable_fast_fill is True
    validate_config(SchedulingConfig())
    try:
        load_config(env={"ARMADA__defaultPriorityClassName": "ghost"})
        assert False, "expected validation failure"
    except ValueError as e:
        assert "priority class" in str(e)
