"""SLO layer (services/slo.py, tools/slo_gate.py, soak --slo flags).

Burn-rate math and the multiwindow alert are unit-tested on a virtual
clock; the gate CLI must pass on the committed sim fixture and exit
non-zero when an override tightens an SLO under the fixture's recorded
latencies (the acceptance pair); the surfaces (`GET /api/slo`, the
SLOStatus RPC behind `armadactl slo`) serve the tracker's snapshot; and
a deliberately-breached SLO fails the front-door soak's gate.
"""

import json
import os
import sys
import urllib.request

import pytest

from armada_tpu.core.config import SchedulingConfig, SLOSpec
from armada_tpu.services.slo import DEFAULT_SLOS, SLOTracker

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, os.path.join(REPO, "tools"))

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "sim_steady.atrace")

FAST_SLO = SLOSpec(
    name="lat", signal="latency_seconds", threshold_s=1.0, objective=0.9,
    fast_burn_window_s=10.0, slow_burn_window_s=100.0,
    fast_burn_threshold=2.0, slow_burn_threshold=1.5,
)


def test_tracker_burn_rates_on_virtual_clock():
    """burn = error_rate / error_budget per window: 10 observations with
    2 bad in the fast window → error rate 0.2 against a 0.1 budget →
    burn 2.0; the slow window sees all 20 with 2 bad → burn 1.0."""
    t = SLOTracker((FAST_SLO,))
    for i in range(10):  # old good events, outside the fast window
        t.observe("latency_seconds", 0.1, now=float(i))
    for i in range(10):  # recent: 8 good + 2 bad
        value = 5.0 if i >= 8 else 0.1
        t.observe("latency_seconds", value, now=90.0 + i)
    burns = t.burn_rates(now=99.0)["lat"]
    assert burns["fast"] == pytest.approx(2.0)
    assert burns["slow"] == pytest.approx(1.0)
    snap = t.snapshot(now=99.0)["slos"][0]
    assert snap["observed"] == 20 and snap["bad"] == 2
    assert snap["compliance"] == pytest.approx(0.9)
    # fast >= 2.0 AND slow >= 1.5 is the alert; slow sits at 1.0 → no.
    assert not snap["alerting"]


def test_tracker_multiwindow_alert_memory_and_evaluate():
    """The gate remembers a mid-run multiwindow burn even when lifetime
    compliance recovers — and reports it as a breach."""
    t = SLOTracker((FAST_SLO,))
    # A dense burst of bad events: both windows burn past threshold.
    for i in range(10):
        t.observe("latency_seconds", 9.0, now=float(i))
    assert t.snapshot(now=9.0)["slos"][0]["breached_at"] is not None
    # A long good tail recovers lifetime compliance above the objective.
    for i in range(200):
        t.observe("latency_seconds", 0.1, now=20.0 + i)
    verdict = t.evaluate(now=220.0)
    snap = verdict["slos"][0]
    assert snap["compliance"] > FAST_SLO.objective
    assert not verdict["ok"]
    assert "multiwindow burn alert fired" in verdict["breaches"][0]


def test_tracker_unobserved_slo_never_breaches():
    t = SLOTracker(DEFAULT_SLOS)
    t.observe("round_seconds", 0.1, now=0.0)
    verdict = t.evaluate(now=1.0)
    assert verdict["ok"]
    observed = {s["name"]: s["observed"] for s in verdict["slos"]}
    assert observed["round-latency"] == 1
    assert observed["queue-wait"] == 0  # reported, never a breach


def test_config_declares_and_validates_slos():
    cfg = SchedulingConfig.from_dict({
        "slos": [
            {"name": "round-latency", "signal": "round_seconds",
             "thresholdSeconds": 2.0, "objective": 0.999,
             "fastBurnWindowSeconds": 60.0},
        ]
    })
    assert cfg.slos[0].threshold_s == 2.0
    assert cfg.slos[0].objective == 0.999
    assert cfg.slos[0].fast_burn_window_s == 60.0
    tracker = SLOTracker.from_config(cfg)
    assert tracker.slos == cfg.slos
    # Empty config → tracked defaults.
    assert SLOTracker.from_config(SchedulingConfig()).slos == DEFAULT_SLOS
    from armada_tpu.core.config import validate_config

    with pytest.raises(ValueError, match="error budget"):
        validate_config(SchedulingConfig(slos=(
            SLOSpec(name="x", signal="s", threshold_s=1.0, objective=1.0),
        )))
    with pytest.raises(ValueError, match="thresholdSeconds"):
        validate_config(SchedulingConfig(slos=(
            SLOSpec(name="x", signal="s", threshold_s=0.0),
        )))


# ---------------------------------------------------------------------------
# The gate CLI (acceptance pair: fixture passes, tightened SLO trips)


def test_slo_gate_passes_on_committed_fixture(capsys):
    from slo_gate import main as slo_gate_main

    assert slo_gate_main([FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "round-latency" in out and "OK" in out


def test_slo_gate_trips_on_tightened_slo(capsys):
    from slo_gate import main as slo_gate_main

    assert slo_gate_main([FIXTURE, "--override", "round-latency=1e-6"]) == 1
    assert "BREACH" in capsys.readouterr().out
    # Typo'd override names must not silently gate nothing.
    assert slo_gate_main([FIXTURE, "--override", "nosuch=1"]) == 2
    # Objective override too: keep the threshold, demand perfection the
    # fixture cannot deliver against a sub-ms threshold.
    assert (
        slo_gate_main([FIXTURE, "--override", "round-latency=0.001:0.5"]) == 1
    )


def test_slo_gate_reads_observation_documents(tmp_path, capsys):
    from slo_gate import main as slo_gate_main

    doc = {
        "observations": [
            {"signal": "frontdoor_submit_seconds", "value": 0.01, "now": i}
            for i in range(20)
        ]
    }
    path = tmp_path / "obs.json"
    path.write_text(json.dumps(doc))
    assert slo_gate_main([str(path)]) == 0
    bad = {
        "observations": [
            {"signal": "frontdoor_submit_seconds", "value": 9.0, "now": i}
            for i in range(20)
        ]
    }
    path.write_text(json.dumps(bad))
    assert slo_gate_main([str(path)]) == 1
    # No decodable observations is unusable, not a green gate.
    path.write_text(json.dumps({"observations": []}))
    assert slo_gate_main([str(path)]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# Surfaces: lookout, RPC, armadactl


def _scheduler_with_tracker():
    from armada_tpu.events import InMemoryEventLog
    from armada_tpu.services.scheduler import SchedulerService

    log = InMemoryEventLog()
    sched = SchedulerService(SchedulingConfig(), log)
    tracker = SLOTracker(DEFAULT_SLOS)
    tracker.observe("round_seconds", 0.2, now=1.0)
    tracker.observe("round_seconds", 9.0, now=2.0)
    sched.attach_slo(tracker)
    return sched, log


def test_lookout_api_slo_endpoint():
    from armada_tpu.services.lookout_http import LookoutHttpServer

    sched, _ = _scheduler_with_tracker()
    server = LookoutHttpServer(None, sched, None, port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/api/slo"
        ) as resp:
            doc = json.loads(resp.read())
        by_name = {s["name"]: s for s in doc["slos"]}
        assert by_name["round-latency"]["observed"] == 2
        assert by_name["round-latency"]["bad"] == 1
        assert by_name["round-latency"]["compliance"] == 0.5
    finally:
        server.stop()


def test_lookout_api_slo_503_when_detached():
    from armada_tpu.services.lookout_http import LookoutHttpServer

    sched, _ = _scheduler_with_tracker()
    sched.slo = None
    server = LookoutHttpServer(None, sched, None, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"http://127.0.0.1:{server.port}/api/slo")
        assert err.value.code == 503
    finally:
        server.stop()


def test_slo_status_rpc_and_armadactl(capsys):
    """SLOStatus over a real gRPC socket, raw client and `armadactl
    slo` rendering."""
    from armada_tpu.services.grpc_api import ApiClient, ApiServer

    sched, log = _scheduler_with_tracker()
    api = ApiServer(None, sched, None, log)
    server, port = api.serve(0)
    try:
        client = ApiClient(f"127.0.0.1:{port}")
        status = client.slo_status()
        by_name = {s["name"]: s for s in status["slos"]}
        assert by_name["round-latency"]["observed"] == 2
        from armada_tpu.clients.cli import main as cli_main

        cli_main(["--server", f"127.0.0.1:{port}", "slo"])
        out = capsys.readouterr().out
        assert "round-latency" in out and "1/2 good" in out
        cli_main(["--server", f"127.0.0.1:{port}", "slo", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert {s["name"] for s in doc["slos"]} == {
            "round-latency", "queue-wait", "frontdoor-p99"
        }
    finally:
        server.stop(None)


# ---------------------------------------------------------------------------
# Sim + soak integration (the CI wiring satellite)


def test_sim_attaches_tracker_and_observes_on_virtual_clock():
    from armada_tpu.sim.simulator import (
        ClusterSpec,
        JobTemplate,
        NodeTemplate,
        QueueSpecSim,
        Simulator,
        WorkloadSpec,
    )

    sim = Simulator(
        [ClusterSpec(name="c", node_templates=(NodeTemplate(count=2, cpu="8"),))],
        WorkloadSpec(queues=(
            QueueSpecSim(name="q", job_templates=(
                JobTemplate(id="t", number=4, cpu="2"),
            )),
        )),
        backend="oracle",
        cycle_interval=10.0,
        max_time=300.0,
        slo=True,
    )
    sim.run()
    verdict = sim.slo.evaluate()
    by_name = {s["name"]: s for s in verdict["slos"]}
    assert by_name["round-latency"]["observed"] > 0
    assert by_name["queue-wait"]["observed"] == 4
    # Oracle cycles are milliseconds and first leases land within a
    # couple of virtual cycles: the default objectives hold.
    assert verdict["ok"], verdict


def test_frontdoor_soak_slo_gate_trips_on_deliberate_breach():
    """The soak's --slo wiring: an impossibly tight submit-latency SLO
    must breach the gate (exit non-zero through main), while the same
    run under the committed SLO passes — and the seed doc exports the
    observation stream tools/slo_gate.py re-evaluates to the same
    verdict."""
    from frontdoor_soak import DEFAULTS, run_soak
    from slo_gate import main as slo_gate_main

    cfg = dict(DEFAULTS, jobs=200, tenants=8, shards=2)
    tight = (
        SLOSpec(name="frontdoor-p99", signal="frontdoor_submit_seconds",
                threshold_s=1e-9, objective=0.99),
    )
    doc = run_soak(0, cfg, slos=tight)
    assert any(b.startswith("slo:") for b in doc["breaches"]), doc["breaches"]
    assert doc["slo"]["ok"] is False
    ok_doc = run_soak(0, cfg, slos=True)
    assert not any(b.startswith("slo:") for b in ok_doc["breaches"])
    # Offline re-evaluation of the exported stream agrees.
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump({"observations": doc["slo"]["observations"]}, f)
    try:
        assert slo_gate_main(
            [f.name, "--override", "frontdoor-p99=1e-9"]
        ) == 1
        assert slo_gate_main([f.name]) == 0
    finally:
        os.unlink(f.name)


@pytest.mark.slow
def test_chaos_soak_slo_gate_trips_on_deliberate_breach():
    from chaos_soak import run_plan, soak_slos

    with pytest.raises(AssertionError, match="SLO breach"):
        run_plan(0, "oracle", 12, use_file_log=False,
                 slos=soak_slos(queue_wait_s=0.001))
    doc = run_plan(0, "oracle", 12, use_file_log=False, slos=soak_slos())
    assert doc["slo"]["ok"] is True
