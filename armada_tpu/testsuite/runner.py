"""Declarative e2e test suite: YAML specs of jobs + expected event sequences.

The reference's testsuite (/root/reference/internal/testsuite/app.go:36-82,
pkg/api/testspec.proto, testcases in testsuite/testcases/{basic,gpu,...}):
each spec declares jobs to submit and the ordered event types every job must
emit, with a timeout; an event watcher asserts the ordering. Same model:

  name: gang-basic
  timeout: 120
  queue: test-q
  jobs:
    - count: 4
      requests: {cpu: "1", memory: 1Gi}
      gang: {cardinality: 4}
  expectedEvents:
    - JobRunLeased
    - JobRunRunning
    - JobRunSucceeded
    - JobSucceeded

Specs run against any gRPC endpoint (a live cluster or a local ControlPlane).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import yaml

from ..services.grpc_api import ApiClient


@dataclass
class TestSpec:
    name: str
    queue: str
    jobs: list
    expected_events: list
    timeout: float = 120.0
    jobset: str = ""
    # Mid-test actions, e.g. {afterSeconds: 2, reprioritizeJobSet: 0}
    # (the reference's reprioritization testcases).
    actions: list = field(default_factory=list)

    @staticmethod
    def from_dict(doc: dict) -> "TestSpec":
        return TestSpec(
            name=doc.get("name", "unnamed"),
            queue=doc.get("queue", "test"),
            jobs=list(doc.get("jobs", [])),
            expected_events=list(doc.get("expectedEvents", [])),
            timeout=float(doc.get("timeout", 120.0)),
            jobset=doc.get("jobSetId", ""),
            actions=list(doc.get("actions", [])),
        )


@dataclass
class TestResult:
    name: str
    passed: bool
    reason: str = ""
    duration_s: float = 0.0
    events_by_job: dict = field(default_factory=dict)


def _expand_groups(spec: TestSpec) -> list[dict]:
    """Expand job groups, keeping per-group expected events and submit
    delays: [{jobs: [...], expected: [...], delay: s}]."""
    groups = []
    for i, item in enumerate(spec.jobs):
        count = int(item.get("count", 1))
        job = {
            "priority": item.get("priority", 0),
            "priority_class": item.get("priorityClassName", ""),
            "requests": item.get("requests", {}),
            "node_selector": item.get("nodeSelector", {}),
            "annotations": item.get("annotations", {}),
        }
        gang = item.get("gang")
        if gang:
            job["gang"] = {
                "id": gang.get("id", f"{spec.name}-gang-{i}"),
                "cardinality": int(gang.get("cardinality", count)),
            }
        groups.append(
            {
                "jobs": [dict(job) for _ in range(count)],
                "expected": list(
                    item.get("expectedEvents", spec.expected_events)
                ),
                "delay": float(item.get("submitDelaySeconds", 0.0)),
            }
        )
    return groups


class TestSuiteRunner:
    def __init__(self, client: ApiClient):
        self.client = client

    def run(self, spec: TestSpec) -> TestResult:
        started = time.time()
        jobset = spec.jobset or f"{spec.name}-{int(started)}"
        try:
            self.client.create_queue(spec.queue)
        except Exception:
            pass  # exists

        # Submit groups in declared order, honoring per-group delays (the
        # preemption cases submit the preemptor after the victim runs).
        groups = _expand_groups(spec)
        expected_by_job: dict[str, list] = {}
        observed: dict[str, list] = {}
        pending_actions = sorted(
            spec.actions, key=lambda a: float(a.get("afterSeconds", 0))
        )
        for group in groups:
            if group["delay"]:
                time.sleep(group["delay"])
            ids = self.client.submit_jobs(spec.queue, jobset, group["jobs"])
            for jid in ids:
                expected_by_job[jid] = group["expected"]
                observed[jid] = []

        deadline = started + spec.timeout
        cursor = 0
        while time.time() < deadline:
            while pending_actions and (
                time.time() - started
                >= float(pending_actions[0].get("afterSeconds", 0))
            ):
                action = pending_actions.pop(0)
                if "reprioritizeJobSet" in action:
                    self.client.reprioritize_jobs(
                        spec.queue,
                        jobset,
                        list(observed),
                        int(action["reprioritizeJobSet"]),
                    )
                elif "cancelJobSet" in action:
                    self.client.cancel_jobs(
                        spec.queue, jobset, cancel_jobset=True
                    )
            for event in self.client.watch_jobset(
                spec.queue, jobset, from_offset=cursor, watch=False
            ):
                cursor = max(cursor, event.get("offset", 0) + 1)
                jid = event.get("job_id", "")
                if jid in observed:
                    observed[jid].append(event["type"])
            if all(
                _is_subsequence(expected_by_job[jid], evs)
                for jid, evs in observed.items()
            ):
                return TestResult(
                    spec.name, True, duration_s=time.time() - started,
                    events_by_job=observed,
                )
            terminal_bad = [
                jid
                for jid, evs in observed.items()
                if any(t in ("JobErrors", "JobRunPreempted") for t in evs)
                and not _is_subsequence(expected_by_job[jid], evs)
                and "JobErrors" not in expected_by_job[jid]
                and "JobRunPreempted" not in expected_by_job[jid]
            ]
            if terminal_bad:
                return TestResult(
                    spec.name,
                    False,
                    reason=f"jobs failed unexpectedly: {terminal_bad[:5]} "
                    f"events={observed[terminal_bad[0]]}",
                    duration_s=time.time() - started,
                    events_by_job=observed,
                )
            time.sleep(0.25)
        missing = {
            jid: evs
            for jid, evs in observed.items()
            if not _is_subsequence(expected_by_job[jid], evs)
        }
        sample = next(iter(missing.items())) if missing else ("", [])
        return TestResult(
            spec.name,
            False,
            reason=f"timeout: {len(missing)} job(s) missing events; "
            f"sample {sample[0]}: got {sample[1]}, "
            f"want {expected_by_job.get(sample[0], spec.expected_events)}",
            duration_s=time.time() - started,
            events_by_job=observed,
        )


def _is_subsequence(expected: list, observed: list) -> bool:
    it = iter(observed)
    return all(any(o == e for o in it) for e in expected)


def run_spec_file(path: str, client: ApiClient) -> TestResult:
    with open(path) as f:
        doc = yaml.safe_load(f)
    return TestSuiteRunner(client).run(TestSpec.from_dict(doc))


def main(argv=None):
    import argparse
    import sys

    ap = argparse.ArgumentParser(prog="armada-tpu-testsuite")
    ap.add_argument("--server", default="127.0.0.1:50051")
    ap.add_argument("specs", nargs="+")
    args = ap.parse_args(argv)
    client = ApiClient(args.server)
    failed = 0
    for path in args.specs:
        res = run_spec_file(path, client)
        status = "PASS" if res.passed else f"FAIL ({res.reason})"
        print(f"{res.name}: {status} [{res.duration_s:.1f}s]")
        failed += 0 if res.passed else 1
    return 1 if failed else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
