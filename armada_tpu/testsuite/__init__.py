from .runner import TestSpec, TestSuiteRunner, run_spec_file

__all__ = ["TestSpec", "TestSuiteRunner", "run_spec_file"]
