"""Shadow solves over forked round state: plans, rollouts, the service.

A plan is produced by:

  1. forking the live round (`fork.py` — captured seam, jobdb fallback,
     or a recorded `.atrace` round for parity checks);
  2. applying the requested mutations to the fork (`mutations.py`);
  3. re-solving the mutated fork with the UNCHANGED production code
     path: a `ForkRollout` boots a REAL SchedulerService + FakeExecutors
     on a private virtual clock seeded with the fork's exact post-round
     state, and runs a bounded number of cycles under any solver spec
     (oracle / LOCAL / hotwindow[:W] / mesh "2x4");
  4. diffing the rollout's decisions against the live baseline into a
     structured `Plan`: displaced jobs and where they land, placements
     + ETA-in-rounds for injected gangs, per-queue/per-pool headroom,
     and (for drains) the predicted `DrainOutcome`.

Plans run on a bounded worker pool off the round thread; the pending
backlog is capped and excess requests fail fast with `WhatIfBusyError`
(RESOURCE_EXHAUSTED on the wire) — a planner burst must add zero
latency to live rounds (tests/test_whatif.py::test_planner_isolation).
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from dataclasses import replace as dc_replace

import numpy as np

from ..core.resources import parse_quantity
from ..events import EventSequence, InMemoryEventLog, JobRunLeased, JobRunPreempted, SubmitJob
from ..jobdb import JobState
from .fork import ForkCapture, ForkState, RoundFork, fork_from_scheduler, fork_from_trace
from .mutations import Mutation


class WhatIfBusyError(RuntimeError):
    """The planner's bounded queue is full: backpressure, not latency.
    Mapped to RESOURCE_EXHAUSTED on both gRPC wires."""


def resolve_rollout_solver(spec, backend: str, config):
    """(backend, mesh, config) for one solver spec string. `spec=None`
    inherits the forked scheduler's own backend, unsharded."""
    if spec in (None, "", "auto"):
        return ("oracle" if backend == "oracle" else "kernel"), None, config
    s = str(spec)
    if s.lower() == "oracle":
        return "oracle", None, config
    if s.upper() == "LOCAL":
        return "kernel", None, config
    if s.lower().startswith("hotwindow"):
        window = (
            int(s.split(":", 1)[1])
            if ":" in s
            else int(getattr(config, "hot_window_slots", 0)) or 4096
        )
        return (
            "kernel",
            None,
            dc_replace(config, hot_window_slots=window, hot_window_min_slots=0),
        )
    # Anything else is a mesh spelling ("8", "2x4", a tuple).
    return "kernel", s, config


@dataclass
class Plan:
    """Structured what-if outcome; every field JSON-able via to_dict."""

    kind: str  # "whatif" | "drain"
    pool: str
    solver: str
    rounds_simulated: int
    cycle_interval: float
    mutations: list = field(default_factory=list)  # mutation dicts
    baseline: dict = field(default_factory=dict)
    displaced: list = field(default_factory=list)
    injected: list = field(default_factory=list)
    headroom: dict = field(default_factory=dict)
    drain: dict | None = None
    # Fairness delta (armada_tpu/observe/fairness.py): per-queue
    # delivered-share/regret movement between the live round's ledger
    # and the rollout's settled ledger — which queues PAY for the plan
    # (drain/inject) and which gain.
    fairness_delta: dict = field(default_factory=dict)
    plan_seconds: float = 0.0

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "pool": self.pool,
            "solver": self.solver,
            "rounds_simulated": self.rounds_simulated,
            "cycle_interval": self.cycle_interval,
            "mutations": list(self.mutations),
            "baseline": dict(self.baseline),
            "displaced": list(self.displaced),
            "injected": list(self.injected),
            "headroom": dict(self.headroom),
            "drain": dict(self.drain) if self.drain is not None else None,
            "fairness_delta": dict(self.fairness_delta),
            "plan_seconds": self.plan_seconds,
        }

    def render(self) -> str:
        lines = [
            f"what-if plan ({self.kind}) · pool {self.pool} · solver "
            f"{self.solver} · {self.rounds_simulated} rounds simulated",
            f"baseline: {self.baseline.get('running', 0)} running, "
            f"{self.baseline.get('queued', 0)} queued on "
            f"{self.baseline.get('nodes', 0)} nodes",
        ]
        if self.drain is not None:
            d = self.drain
            rounds = d.get("rounds_to_drain")
            lines.append(
                f"drain {d.get('executor')}: "
                f"{len(d.get('completed', []))} complete voluntarily, "
                f"{len(d.get('preempted', []))} preempted, "
                f"{len(d.get('blocked', []))} blocked; "
                + (
                    f"drained in {rounds} rounds"
                    if rounds is not None
                    else "NOT drained within the horizon"
                )
            )
        for item in self.displaced:
            landed = item.get("landed_node")
            lines.append(
                f"  displaced {item['job_id']} ({item['from_node']}) -> "
                + (
                    f"{landed} at round {item.get('rounds_to_land')}"
                    if landed
                    else "no landing within the horizon"
                )
            )
        for g in self.injected:
            eta = g.get("eta_rounds")
            lines.append(
                f"  injected {g['name']} x{g['jobs']} (queue {g['queue']}): "
                + (
                    f"starts in {eta} round(s) on "
                    f"{len(g.get('nodes', []))} node(s)"
                    if eta is not None
                    else "does NOT start within the horizon"
                    + (f" — {g['reason']}" if g.get("reason") else "")
                )
            )
        pool_room = self.headroom.get("pool", {})
        if pool_room:
            free = ", ".join(
                f"{k}={v}" for k, v in sorted(pool_room.get("free", {}).items())
            )
            lines.append(f"headroom: {free}")
        delta_queues = self.fairness_delta.get("queues") or {}
        movers = sorted(
            (
                (name, d)
                for name, d in delta_queues.items()
                if abs(d.get("delta_delivered", 0.0)) > 1e-9
            ),
            key=lambda kv: kv[1].get("delta_delivered", 0.0),
        )
        if movers:
            lines.append("fairness delta (who pays):")
            for name, d in movers[:8]:
                lines.append(
                    f"  queue {name}: delivered "
                    f"{d.get('baseline_delivered', 0.0):.4f} -> "
                    f"{d.get('planned_delivered', 0.0):.4f} "
                    f"({d.get('delta_delivered', 0.0):+.4f})"
                )
        return "\n".join(lines)


class ForkRollout:
    """A real SchedulerService + FakeExecutors on a private virtual
    clock, seeded bit-for-bit from a ForkState. Multi-round rollouts
    drive the production cycle path (the sim.Simulator design), so the
    planner never models scheduling — it runs it."""

    def __init__(
        self,
        state: ForkState,
        *,
        solver=None,
        backend: str = "kernel",
        cycle_interval: float = 10.0,
        runtime_for=None,
        now: float = 0.0,
    ):
        from ..services.fake_executor import FakeExecutor
        from ..services.scheduler import SchedulerService

        self.state = state
        self.cycle_interval = float(cycle_interval)
        self.solver_label = str(solver) if solver not in (None, "") else (
            "oracle" if backend == "oracle" else "LOCAL"
        )
        backend, mesh, config = resolve_rollout_solver(solver, backend, state.config)
        self.log = InMemoryEventLog()
        # A far-future default keeps un-modeled jobs running for the whole
        # horizon: the planner is pessimistic about voluntary completion
        # unless the caller supplies remaining-runtime estimates.
        horizon = max(1e9, self.cycle_interval * 1e6)
        self._runtime_for = runtime_for or (lambda job_id: horizon)
        self._seed(now)
        self.scheduler = SchedulerService(
            config, self.log, backend=backend, mesh=mesh,
            queues=list(state.queues),
        )
        self.scheduler.cordoned_queues.update(state.cordoned_queues)
        self.scheduler.cordoned_executors.update(state.cordoned_executors)
        by_executor: dict[str, list] = {}
        for node in state.nodes:
            by_executor.setdefault(state.executor_of(node), []).append(node)
        self.executors = [
            FakeExecutor(
                name,
                self.log,
                self.scheduler,
                nodes=nodes,
                pool=state.pool,
                runtime_for=self._runtime_for,
            )
            for name, nodes in sorted(by_executor.items())
        ]
        self.leases: dict[str, tuple] = {}  # job_id -> (cycle, node, executor)
        self.preempts: dict[str, tuple] = {}  # job_id -> (cycle, reason)
        self.cycles = 0
        self._drains = []
        for name, deadline_s in state.drain_executors:
            self._drains.append(
                self.scheduler.drains.start(name, deadline_s=deadline_s)
            )

    def _seed(self, now: float) -> None:
        """Publish the fork state into the rollout's private log: every
        job's real spec (gang identity included), running jobs leased at
        their forked placements. The rollout scheduler's first sync then
        materializes exactly the forked jobdb view."""
        state = self.state
        for i, r in enumerate(state.running):
            spec = r.job
            self.log.publish(
                EventSequence.of(
                    spec.queue,
                    spec.jobset or "whatif",
                    SubmitJob(created=min(spec.submitted_ts, now), job=spec),
                    JobRunLeased(
                        created=r.leased_ts or now,
                        job_id=spec.id,
                        run_id=f"fork-run-{i:06d}",
                        executor=state.node_executor.get(r.node_id, "")
                        or next(
                            (
                                n.executor
                                for n in state.nodes
                                if n.id == r.node_id
                            ),
                            "",
                        ),
                        node_id=r.node_id,
                        pool=state.pool,
                        scheduled_at_priority=r.scheduled_at_priority,
                    ),
                )
            )
        for spec in state.queued:
            self.log.publish(
                EventSequence.of(
                    spec.queue,
                    spec.jobset or "whatif",
                    SubmitJob(created=min(spec.submitted_ts, now), job=spec),
                )
            )

    def attach_drain(self, executor: str, deadline_s: float | None = None):
        ctl = self.scheduler.drains.start(executor, deadline_s=deadline_s)
        self._drains.append(ctl)
        return ctl

    @property
    def drains(self):
        return self._drains

    def run(self, rounds: int, stop_when=None) -> None:
        t = 0.0
        for cycle in range(1, int(rounds) + 1):
            for ex in self.executors:
                ex.tick(t)
            seqs = self.scheduler.cycle(now=t)
            self.cycles = cycle
            for seq in seqs:
                for event in seq.events:
                    if isinstance(event, JobRunLeased):
                        self.leases[event.job_id] = (
                            cycle,
                            event.node_id,
                            event.executor,
                        )
                    elif isinstance(event, JobRunPreempted):
                        self.preempts[event.job_id] = (cycle, event.reason)
            for ex in self.executors:
                ex.tick(t)
            if stop_when is not None and stop_when(self):
                break
            t += self.cycle_interval

    # -- final-state reads ---------------------------------------------

    def job_state(self, job_id: str):
        job = self.scheduler.jobdb.get(job_id)
        return job.state if job is not None else None

    def headroom(self) -> dict:
        """Free capacity after the rollout settles: pool totals minus
        live allocations, plus per-queue allocation/fair-share from the
        last round report."""
        totals: dict[str, float] = {}
        for node in self.state.nodes:
            if node.unschedulable:
                continue
            if self.scheduler.cordoned_executors and (
                self.state.executor_of(node)
                in self.scheduler.cordoned_executors
            ):
                continue
            for name, qty in node.total_resources.items():
                totals[name] = totals.get(name, 0) + float(parse_quantity(qty))
        allocated: dict[str, float] = {}
        by_queue: dict[str, dict] = {}
        txn = self.scheduler.jobdb.read_txn()
        for job in txn.leased_jobs():
            bucket = by_queue.setdefault(job.queue, {})
            for name, qty in job.spec.requests.items():
                q = float(parse_quantity(qty))
                allocated[name] = allocated.get(name, 0) + q
                bucket[name] = bucket.get(name, 0) + q
        queues = {
            name: {"allocated": dict(alloc)} for name, alloc in by_queue.items()
        }
        report = self.scheduler.reports.latest_reports().get(self.state.pool)
        if report is not None:
            for qname, qr in report.queues.items():
                queues.setdefault(qname, {})["fair_share"] = qr.fair_share
                queues[qname]["actual_share"] = qr.actual_share
        return {
            "pool": {
                "total": totals,
                "allocated": allocated,
                "free": {
                    k: totals.get(k, 0) - allocated.get(k, 0) for k in totals
                },
            },
            "queues": queues,
        }


class WhatIfService:
    """The what-if planner's service face: bounded worker pool, plan
    history, drain start/status pass-through, parity checks."""

    def __init__(
        self,
        scheduler,
        *,
        metrics=None,
        workers: int | None = None,
        queue_depth: int | None = None,
        default_rounds: int | None = None,
        cycle_interval: float = 10.0,
        keep_recent: int = 32,
    ):
        self.scheduler = scheduler
        cfg = scheduler.config
        # Rollout cycles model the LIVE cycle cadence: rounds-to-drain
        # and ETA-in-rounds are honest only when the shadow clock ticks
        # like the real one (server.py passes its cycle_period).
        self.cycle_interval = float(cycle_interval)
        self.metrics = metrics if metrics is not None else scheduler.metrics
        self.default_rounds = int(
            default_rounds
            if default_rounds is not None
            else getattr(cfg, "whatif_default_rounds", 8)
        )
        self.queue_depth = int(
            queue_depth
            if queue_depth is not None
            else getattr(cfg, "whatif_queue_depth", 8)
        )
        n_workers = max(
            1, int(workers if workers is not None else getattr(cfg, "whatif_workers", 1))
        )
        self._pool = ThreadPoolExecutor(
            max_workers=n_workers, thread_name_prefix="whatif"
        )
        self._pending = 0
        self._lock = threading.Lock()
        self.capture = ForkCapture()
        scheduler.attach_fork_capture(self.capture)
        self.recent: deque = deque(maxlen=keep_recent)

    # -- bounded submission --------------------------------------------

    def _metric_ok(self) -> bool:
        return (
            self.metrics is not None
            and getattr(self.metrics, "registry", None) is not None
        )

    def _gauge_depth(self) -> None:
        if self._metric_ok():
            self.metrics.whatif_queue_depth.set(self._pending)

    def _run_bounded(self, kind: str, fn):
        """Run `fn` on the worker pool with backlog backpressure: the
        CALLER's thread blocks on the result (it's an RPC handler), the
        round thread never runs planner code, and a full backlog fails
        fast instead of queueing unboundedly."""
        with self._lock:
            if self._pending >= self.queue_depth:
                raise WhatIfBusyError(
                    f"what-if planner backlog full ({self._pending} pending, "
                    f"cap {self.queue_depth}); retry later"
                )
            self._pending += 1
            self._gauge_depth()

        def timed():
            t0 = _time.monotonic()
            try:
                return fn()
            finally:
                elapsed = _time.monotonic() - t0
                with self._lock:
                    self._pending -= 1
                    self._gauge_depth()
                if self._metric_ok():
                    self.metrics.whatif_plans.labels(kind=kind).inc()
                    self.metrics.whatif_plan_seconds.labels(kind=kind).observe(
                        elapsed
                    )

        return self._pool.submit(timed).result()

    # -- forks ----------------------------------------------------------

    def ensure_fork(self, pool: str | None = None) -> RoundFork:
        fork = self.capture.latest(pool)
        # A capture is only current if it came from one of the last two
        # cycles: in incremental-snapshot mode the seam skips rounds
        # (the capture would otherwise serve an arbitrarily stale
        # rebuild round's state), so stale captures fall back to a
        # fresh jobdb fork exactly like a missing one.
        if fork is not None and fork.cycle is not None and (
            self.scheduler.cycle_count - fork.cycle <= 1
        ):
            return fork
        return fork_from_scheduler(self.scheduler, pool)

    # -- planning -------------------------------------------------------

    def plan(
        self,
        mutations: list[Mutation],
        *,
        pool: str | None = None,
        solver=None,
        rounds: int | None = None,
        runtime_for=None,
        cycle_interval: float | None = None,
        kind: str = "whatif",
    ) -> Plan:
        # The fork build runs INSIDE the bounded worker too: before any
        # round is captured, fork_from_scheduler walks the whole jobdb —
        # a burst must be shed before that work, not after.
        return self._run_bounded(
            kind,
            lambda: self._plan_on_fork(
                self.ensure_fork(pool),
                mutations,
                solver=solver,
                rounds=rounds,
                runtime_for=runtime_for,
                cycle_interval=cycle_interval,
                kind=kind,
            ),
        )

    def plan_drain(
        self,
        executor: str,
        *,
        pool: str | None = None,
        solver=None,
        rounds: int | None = None,
        deadline_s: float | None = None,
        runtime_for=None,
        cycle_interval: float | None = None,
    ) -> Plan:
        from .mutations import DrainExecutor

        return self.plan(
            [DrainExecutor(name=executor, deadline_s=deadline_s)],
            pool=pool,
            solver=solver,
            rounds=rounds,
            runtime_for=runtime_for,
            cycle_interval=cycle_interval,
            kind="drain",
        )

    def _plan_on_fork(
        self,
        fork: RoundFork,
        mutations: list[Mutation],
        *,
        solver=None,
        rounds: int | None = None,
        runtime_for=None,
        cycle_interval: float | None = None,
        kind: str = "whatif",
    ) -> Plan:
        t0 = _time.monotonic()
        rounds = int(rounds if rounds is not None else self.default_rounds)
        state = fork.post_round_state()
        baseline_running = {r.job.id: r.node_id for r in state.running}
        baseline = {
            "running": len(state.running),
            "queued": len(state.queued),
            "nodes": len(state.nodes),
            "cycle": fork.cycle,
        }
        for m in mutations:
            m.apply(state)
        feasibility = self._injection_feasibility(state)
        interval = float(
            cycle_interval
            if cycle_interval is not None
            else self.cycle_interval
        )
        if state.drain_executors:
            # The horizon must COVER every drain's deadline (else the
            # dry-run predicts "nothing happens" about a deadline it
            # never reached), plus the requested rounds for requeue
            # landings. Bounded: the early-stop predicate ends the
            # rollout as soon as the drain completes and everything
            # displaced has landed.
            import math

            default_deadline = float(
                getattr(state.config, "drain_deadline_s", 0.0)
            )
            worst = max(
                default_deadline if dl is None else float(dl)
                for _, dl in state.drain_executors
            )
            rounds += min(int(math.ceil(worst / interval)) + 1, 1000)
        rollout = ForkRollout(
            state,
            solver=solver,
            backend=fork.backend,
            cycle_interval=interval,
            runtime_for=runtime_for,
        )

        injected = set(state.injected_job_ids)

        def goals_met(r: ForkRollout) -> bool:
            if any(d.state != "done" for d in r.drains):
                return False
            if injected and not all(j in r.leases for j in injected):
                return False
            displaced_pending = [
                jid
                for jid in r.preempts
                if jid in baseline_running and jid not in r.leases
            ]
            return not displaced_pending

        rollout.run(rounds, stop_when=goals_met)

        displaced = []
        surviving_nodes = {n.id for n in state.nodes}
        for jid, from_node in sorted(baseline_running.items()):
            pre = rollout.preempts.get(jid)
            lease = rollout.leases.get(jid)
            moved = pre is not None or (
                lease is not None and lease[1] != from_node
            )
            if not moved and from_node in surviving_nodes:
                continue
            displaced.append(
                {
                    "job_id": jid,
                    "from_node": from_node,
                    "reason": pre[1] if pre else "node removed from fork",
                    "landed_node": lease[1] if lease else None,
                    "rounds_to_land": lease[0] if lease else None,
                }
            )
        injected_out = self._injected_outcomes(state, rollout, feasibility)
        drain_doc = None
        if rollout.drains:
            # One drain per plan today; report the first controller.
            drain_doc = rollout.drains[0].outcome().to_dict()
        fairness_delta = self._fairness_delta(fork, rollout)
        plan = Plan(
            kind=kind,
            pool=fork.pool,
            solver=rollout.solver_label,
            rounds_simulated=rollout.cycles,
            cycle_interval=rollout.cycle_interval,
            mutations=[m.to_dict() for m in mutations],
            baseline=baseline,
            displaced=displaced,
            injected=injected_out,
            headroom=rollout.headroom(),
            drain=drain_doc,
            fairness_delta=fairness_delta,
            plan_seconds=round(_time.monotonic() - t0, 4),
        )
        self.recent.appendleft(plan.to_dict())
        return plan

    def _fairness_delta(self, fork: RoundFork, rollout: ForkRollout) -> dict:
        """Which queues pay: the live round's fairness ledger (the
        scheduler's tracker) vs the rollout's settled ledger (the
        rollout scheduler runs the same fairness observatory). Either
        side missing (no round yet / idle rollout) reports {}."""
        base_doc = None
        tracker = getattr(self.scheduler, "fairness", None)
        if tracker is not None:
            base_doc = tracker.latest(fork.pool)
        roll_tracker = getattr(rollout.scheduler, "fairness", None)
        plan_doc = roll_tracker.latest(fork.pool) if roll_tracker else None
        if not base_doc or not plan_doc:
            return {}

        def rows(doc):
            return {
                str(r["queue"]): r
                for r in (doc.get("ledger") or {}).get("queues", ())
            }

        base_rows, plan_rows = rows(base_doc), rows(plan_doc)
        queues = {}
        for name in sorted(base_rows.keys() | plan_rows.keys()):
            b = base_rows.get(name, {})
            p = plan_rows.get(name, {})
            b_del = float(b.get("delivered_share", 0.0))
            p_del = float(p.get("delivered_share", 0.0))
            queues[name] = {
                "baseline_delivered": b_del,
                "planned_delivered": p_del,
                "delta_delivered": p_del - b_del,
                "baseline_regret": float(b.get("regret", 0.0)),
                "planned_regret": float(p.get("regret", 0.0)),
            }
        payers = sorted(
            (n for n, d in queues.items() if d["delta_delivered"] < -1e-9),
            key=lambda n: queues[n]["delta_delivered"],
        )
        return {
            "baseline_jain": float(
                (base_doc.get("ledger") or {}).get("jain", 1.0)
            ),
            "planned_jain": float(
                (plan_doc.get("ledger") or {}).get("jain", 1.0)
            ),
            "queues": queues,
            "payers": payers,
        }

    def _injection_feasibility(self, state: ForkState) -> dict:
        """Static could-this-EVER-fit verdicts for injected jobs, through
        the SAME snapshot-build helper the SubmitChecker uses
        (services/submit_check.static_check) — checker and planner
        feasibility semantics cannot drift."""
        if not state.injected_job_ids:
            return {}
        from ..services.submit_check import static_check

        by_jobset: dict[str, list] = {}
        for spec in state.queued:
            if spec.id in set(state.injected_job_ids):
                by_jobset.setdefault(spec.jobset, []).append(spec)
        by_executor: dict[str, list] = {}
        for node in state.nodes:
            ex = state.executor_of(node)
            if ex in state.cordoned_executors:
                continue
            by_executor.setdefault(ex, []).append(node)
        verdicts = {}
        for jobset, jobs in by_jobset.items():
            reasons = []
            ok = False
            for name, nodes in sorted(by_executor.items()):
                result = static_check(state.config, state.pool, nodes, jobs)
                if result.schedulable:
                    ok = True
                    break
                reasons.append(f"{name}: {result.reason}")
            verdicts[jobset] = (ok, "" if ok else "; ".join(reasons))
        return verdicts

    def _injected_outcomes(
        self, state: ForkState, rollout: ForkRollout, feasibility: dict
    ) -> list:
        out = []
        # Group injected jobs by their synthetic jobset (one per
        # inject_gang mutation).
        by_set: dict[str, list] = {}
        for spec in state.queued:
            if spec.id in set(state.injected_job_ids):
                by_set.setdefault(spec.jobset, []).append(spec)
        for jobset, specs in sorted(by_set.items()):
            ids = [s.id for s in specs]
            leases = [rollout.leases.get(j) for j in ids]
            placed = all(le is not None for le in leases)
            eta = max(le[0] for le in leases) if placed else None
            nodes = sorted({le[1] for le in leases if le is not None})
            feasible, reason = feasibility.get(jobset, (True, ""))
            if placed:
                reason = ""
            elif not feasible:
                reason = f"never schedulable: {reason}"
            else:
                reason = self._unplaced_reason(rollout, ids) or (
                    "no capacity within the horizon"
                )
            gang = specs[0].gang
            out.append(
                {
                    "name": gang.id if gang is not None else jobset,
                    "queue": specs[0].queue,
                    "jobs": len(specs),
                    "gang_cardinality": gang.cardinality if gang else 0,
                    "eta_rounds": eta,
                    "nodes": nodes,
                    "feasible": bool(feasible),
                    "reason": reason,
                }
            )
        return out

    def _unplaced_reason(self, rollout: ForkRollout, ids: list) -> str:
        report = rollout.scheduler.reports.latest_reports().get(
            rollout.state.pool
        )
        if report is None:
            return ""
        for jid in ids:
            reason = report.job_reasons.get(jid)
            if reason:
                return reason
        return ""

    # -- drain execution (live control plane) ---------------------------

    def execute_drain(
        self, executor: str, *, deadline_s: float | None = None
    ) -> dict:
        """Start (or poll) a REAL drain on the live scheduler: the
        coordinator steps it once per scheduling cycle through the
        event path. Idempotent; returns the current status."""
        ctl = self.scheduler.drains.start(
            executor, deadline_s=deadline_s, metrics=self.metrics
        )
        return ctl.status()

    def drain_status(self, executor: str | None = None):
        return self.scheduler.drains.status(executor)

    # -- parity ---------------------------------------------------------

    def parity(
        self,
        *,
        pool: str | None = None,
        solver="LOCAL",
        fork: RoundFork | None = None,
        trace_path: str | None = None,
        round_i: int = 0,
        allow_foreign: bool = False,
    ) -> dict:
        """Bit-exact check: re-solve an UNMUTATED fork under `solver`
        and compare against the live decision stream (the replayer's
        compare on trace forks). The planner's isolation proof: shadow
        solves reproduce the live kernel's decisions exactly."""
        if fork is None:
            if trace_path is not None:
                fork = fork_from_trace(
                    trace_path, round_i, allow_foreign=allow_foreign
                )
            else:
                fork = self.capture.latest(pool)
        if fork is None:
            raise KeyError(
                "no captured round to check parity against (no round has "
                "solved since the planner attached)"
            )
        return self._run_bounded(
            "parity", lambda: parity_check(fork, solver)
        )


def parity_check(fork: RoundFork, solver="LOCAL") -> dict:
    """Solve the fork's exact DeviceRound under a solver spec and diff
    the decision stream against the recorded/live one."""
    from ..trace.replayer import compare_round, replay_solver

    label, solve = replay_solver(solver, fork.trace_header)
    dev = fork.device_round()
    t0 = _time.monotonic()
    out = solve(dev)
    solve_s = _time.monotonic() - t0
    if fork.trace_record is not None:
        divergences = compare_round(fork.trace_record, out)
    else:
        divergences = _compare_live(fork, out)
    return {
        "solver": label,
        "pool": fork.pool,
        "num_jobs": fork.num_jobs,
        "solve_s": round(solve_s, 4),
        "divergences": divergences,
        "ok": not divergences,
    }


def _compare_live(fork: RoundFork, out: dict) -> list:
    """compare_round's logic against a live captured result dict (the
    round fork's result arrays are already sliced to the unpadded
    prefix)."""
    recorded = fork.recorded_decisions() or {}
    J, Q = fork.num_jobs, fork.num_queues
    job_keys = (
        "assigned_node",
        "scheduled_priority",
        "scheduled_mask",
        "preempted_mask",
    )
    queue_keys = ("fair_share", "demand_capped_fair_share")
    divergences = []
    for key in job_keys + queue_keys:
        if key not in recorded or key not in out:
            continue
        n = J if key in job_keys else Q
        want = np.asarray(recorded[key])[:n]
        got = np.asarray(out[key])[:n]
        if not np.array_equal(want, got, equal_nan=True):
            where = [int(i) for i in np.flatnonzero(want != got)[:4]]
            divergences.append(
                {
                    "kind": "placement",
                    "key": key,
                    "detail": f"{key}[:{n}] differs at indices {where}",
                }
            )
    if fork.backend == "kernel" and "num_loops" in recorded and "num_loops" in out:
        want = int(np.asarray(recorded["num_loops"]))
        got = int(np.asarray(out["num_loops"]))
        if want != got:
            divergences.append(
                {
                    "kind": "loop_stream",
                    "key": "num_loops",
                    "detail": f"recorded {want} loops, replayed {got}",
                }
            )
    return divergences
