"""What-if planner: forked-snapshot shadow solves for drain
orchestration, gang ETA and capacity headroom.

The subsystem answers operational questions without touching the live
fleet: "what breaks if I drain executor X", "when would a 64-chip gang
start if I submitted it now", "how much headroom does pool P have" —
by forking the scheduler's last round state (`fork.py`), applying
composable hypothetical edits (`mutations.py`), and re-solving the
mutated fork with the UNCHANGED production kernel under any solver
spec (`planner.py`), diffing the decisions against the live round.
`drain.py` turns a drain plan into staged execution through the real
control-plane event path, with dry-run and execution required to agree
in a deterministic sim (tests/test_whatif.py).

Gavel-style what-if policy evaluation (PAPERS: arXiv:2008.09213) made
cheap by the solver's replay machinery: planner solves are bit-exact
with the live kernel on an unmutated fork, and run on a bounded worker
pool off the round thread (a planner burst adds zero live latency).
"""

from .drain import DrainController, DrainCoordinator
from .fork import ForkCapture, ForkState, RoundFork, fork_from_scheduler, fork_from_trace
from .mutations import Mutation, mutation_from_dict, mutations_from_dicts
from .planner import Plan, WhatIfBusyError, WhatIfService

__all__ = [
    "DrainController",
    "DrainCoordinator",
    "ForkCapture",
    "ForkState",
    "RoundFork",
    "fork_from_scheduler",
    "fork_from_trace",
    "Mutation",
    "mutation_from_dict",
    "mutations_from_dicts",
    "Plan",
    "WhatIfBusyError",
    "WhatIfService",
]
