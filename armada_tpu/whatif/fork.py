"""Consistent, immutable forks of the scheduler's round state.

Three fork sources, one `RoundFork` surface:

  - `ForkCapture` — the flight-recorder seam in
    `services/scheduler.py._schedule_pool`: right after a round solves,
    the scheduler hands the capture REFERENCES to the round's already-
    built inputs (NodeSpec/QueueSpec/RunningJob/JobSpec lists, the
    RoundSnapshot, the solver result arrays). Everything referenced is
    either frozen or freshly built per round and never mutated again,
    so capturing costs a handful of dict/set copies on the round
    thread — no extra array builds (the hard isolation requirement).
    Incremental-snapshot rounds share mutable state across cycles and
    are NOT captured; the planner falls back to a jobdb fork.

  - `fork_from_scheduler` — builds the round inputs from the live jobdb
    through the scheduler's own `_build_pool_inputs` (thread-safe jobdb
    reads). Runs on the planner worker, never the round thread.

  - `fork_from_trace` — reconstructs a recorded round from a flight-
    recorder `.atrace` bundle (bit-exact padded DeviceRound + decision
    stream). Supports the replayer-style parity compare and device-
    level node mutations; JobSpec-level mutations need a live fork.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field, replace


@dataclass
class ForkState:
    """The mutable working copy mutations edit and the rollout seeds.

    `running`/`queued` are the post-round state: the captured round's
    own decisions already applied (scheduled jobs bound, preempted jobs
    dropped), so a no-op mutation list re-solves to a fixed point."""

    pool: str
    config: object
    nodes: list = field(default_factory=list)
    queues: list = field(default_factory=list)
    running: list = field(default_factory=list)
    queued: list = field(default_factory=list)
    node_executor: dict = field(default_factory=dict)
    cordoned_queues: set = field(default_factory=set)
    cordoned_executors: set = field(default_factory=set)
    excluded_nodes: dict = field(default_factory=dict)
    # Mutation bookkeeping consumed by the planner's diff:
    injected_job_ids: list = field(default_factory=list)
    injected_gangs: list = field(default_factory=list)  # (gang_id, queue, card)
    drain_executors: list = field(default_factory=list)

    def executor_of(self, node) -> str:
        return (
            self.node_executor.get(node.id)
            or node.executor
            or "whatif-exec"
        )


@dataclass
class RoundFork:
    """One immutable fork. Fields not applicable to a source are None."""

    source: str  # "round" | "jobdb" | "trace"
    pool: str
    config: object = None
    cycle: int | None = None
    now: float | None = None
    backend: str = "kernel"
    # round/jobdb sources:
    snap: object = None  # RoundSnapshot (round source only)
    result: dict | None = None  # live solver output (round source only)
    inputs: tuple | None = None  # (nodes, queues, running, queued, excluded)
    node_executor: dict | None = None
    cordoned_queues: set = field(default_factory=set)
    cordoned_executors: set = field(default_factory=set)
    # trace source:
    trace_record: object = None  # trace.replayer.RoundRecord
    trace_header: dict | None = None

    # -- derived views --------------------------------------------------

    def device_round(self):
        """The padded DeviceRound the kernel solves — the recorded one
        for trace forks, re-prepped deterministically otherwise (prep is
        a pure function of the snapshot, so the result is bit-exact with
        what the live round solved)."""
        if self.trace_record is not None:
            return self.trace_record.device_round()
        if self.snap is None:
            raise ValueError(
                "fork has no snapshot: jobdb forks support planning only"
            )
        from ..solver.kernel_prep import pad_device_round, prep_device_round

        return pad_device_round(prep_device_round(self.snap))

    def recorded_decisions(self) -> dict | None:
        """The live decision stream to compare shadow solves against."""
        import numpy as np

        if self.trace_record is not None:
            return self.trace_record.decisions()
        if self.result is None:
            return None
        return {k: np.asarray(v) for k, v in self.result.items()
                if hasattr(v, "__len__") or isinstance(v, (int, float))}

    @property
    def num_jobs(self) -> int:
        if self.trace_record is not None:
            return self.trace_record.num_jobs
        return self.snap.num_jobs if self.snap is not None else 0

    @property
    def num_queues(self) -> int:
        if self.trace_record is not None:
            return self.trace_record.num_queues
        return self.snap.num_queues if self.snap is not None else 0

    def post_round_state(self) -> ForkState:
        """ForkState with this round's decisions applied (see ForkState).
        Requires JobSpec-level inputs (round/jobdb forks)."""
        if self.inputs is None:
            raise ValueError(
                f"{self.source} fork carries no JobSpec-level inputs; "
                "mutations/rollouts need a live (round or jobdb) fork"
            )
        import numpy as np

        from ..core.types import RunningJob

        nodes, queues, running, queued, excluded = self.inputs
        state = ForkState(
            pool=self.pool,
            config=self.config,
            nodes=list(nodes),
            queues=list(queues),
            node_executor=dict(self.node_executor or {}),
            cordoned_queues=set(self.cordoned_queues),
            cordoned_executors=set(self.cordoned_executors),
            excluded_nodes={k: list(v) for k, v in (excluded or {}).items()},
        )
        if self.result is None or self.snap is None:
            state.running = list(running)
            state.queued = list(queued)
            return state
        # Apply the captured round's own decisions so the fork is the
        # POST-round cluster: scheduled queued jobs become running at
        # their assigned nodes, preempted running jobs drop (terminal
        # under live round-preemption semantics).
        snap = self.snap
        scheduled = np.asarray(self.result["scheduled_mask"], bool)
        preempted = np.asarray(self.result["preempted_mask"], bool)
        assigned = np.asarray(self.result["assigned_node"])
        prio = np.asarray(self.result["scheduled_priority"])
        idx = {jid: j for j, jid in enumerate(snap.job_ids)}
        for r in running:
            j = idx.get(r.job.id)
            if j is not None and preempted[j]:
                continue
            state.running.append(r)
        for spec in queued:
            j = idx.get(spec.id)
            if j is not None and scheduled[j]:
                state.running.append(
                    RunningJob(
                        job=spec,
                        node_id=snap.node_ids[int(assigned[j])],
                        scheduled_at_priority=int(prio[j]),
                        leased_ts=float(self.now or 0.0),
                    )
                )
            else:
                state.queued.append(spec)
        return state


class ForkCapture:
    """Latest-round fork per pool, fed from the scheduler's round thread
    (references only — see module docstring) and read from the planner
    worker."""

    def __init__(self):
        self._latest: dict[str, RoundFork] = {}
        self._lock = threading.Lock()

    def capture(
        self,
        *,
        pool: str,
        cycle: int,
        now: float,
        config,
        snap,
        result,
        inputs,
        node_executor,
        cordoned_queues,
        cordoned_executors,
        backend: str,
    ) -> None:
        fork = RoundFork(
            source="round",
            pool=pool,
            config=config,
            cycle=cycle,
            now=now,
            backend=backend,
            snap=snap,
            result=result,
            inputs=inputs,
            node_executor=node_executor,
            cordoned_queues=cordoned_queues,
            cordoned_executors=cordoned_executors,
        )
        with self._lock:
            self._latest[pool] = fork

    def latest(self, pool: str | None = None) -> RoundFork | None:
        with self._lock:
            if pool is not None:
                return self._latest.get(pool)
            if len(self._latest) == 1:
                return next(iter(self._latest.values()))
            # Multiple pools: newest capture wins for pool-less asks.
            newest = None
            for fork in self._latest.values():
                if newest is None or (fork.cycle or 0) >= (newest.cycle or 0):
                    newest = fork
            return newest

    def pools(self) -> list[str]:
        with self._lock:
            return sorted(self._latest)


def fork_from_scheduler(scheduler, pool: str | None = None) -> RoundFork:
    """Fork the live jobdb state for one pool (planner-worker path: the
    jobdb is lock-protected, so this never races the round thread; it
    just costs a build the captured fork would have amortized)."""
    if pool is None:
        pools = {
            (n.pool or hb.pool)
            for hb in scheduler.executors.values()
            for n in hb.nodes
        }
        pool = sorted(pools)[0] if pools else (
            scheduler.config.pools[0].name if scheduler.config.pools
            else "default"
        )
    (
        nodes,
        queues,
        running,
        queued,
        node_executor,
        _txn,
        excluded_nodes,
    ) = scheduler._build_pool_inputs(pool)
    return RoundFork(
        source="jobdb",
        pool=pool,
        config=scheduler.config,
        cycle=scheduler.cycle_count,
        backend=scheduler.backend,
        inputs=(nodes, queues, running, queued, excluded_nodes),
        node_executor=dict(node_executor),
        cordoned_queues=set(scheduler.cordoned_queues),
        cordoned_executors=set(scheduler.cordoned_executors),
    )


def fork_from_trace(
    path: str, round_i: int = 0, *, allow_foreign: bool = False
) -> RoundFork:
    """Fork a recorded round from an `.atrace` bundle: the bit-exact
    padded DeviceRound + decision stream, for replayer-style parity
    checks (tier-1 smoke over tests/fixtures/sim_steady.atrace)."""
    from ..trace.replayer import check_target, load_trace

    trace = load_trace(path)
    check_target(trace.header, allow_foreign=allow_foreign)
    rounds = [r for r in trace.rounds if not r.truncated]
    if not rounds:
        raise ValueError(f"{path}: no untruncated rounds to fork")
    rec = rounds[min(round_i, len(rounds) - 1)]
    return RoundFork(
        source="trace",
        pool=rec.pool,
        backend=rec.backend,
        trace_record=rec,
        trace_header=trace.header,
    )


def cordon_node_in_fork(fork: RoundFork, node_id: str) -> RoundFork:
    """Device-level node cordon for trace forks: flips the node's
    unschedulable lane in the DeviceRound. (Live forks cordon through
    mutations.CordonNode on the NodeSpec list instead.)"""
    import dataclasses as _dc

    import numpy as np

    if fork.trace_record is None:
        raise ValueError("device-level cordon applies to trace forks only")
    dev = fork.device_round()
    ids = (fork.trace_record.raw.get("ids") or {}).get("nodes")
    if not ids or node_id not in ids:
        raise KeyError(f"node {node_id!r} not in the recorded id vocabulary")
    unsched = np.array(dev.node_unschedulable)
    unsched[ids.index(node_id)] = True
    mutated = _dc.replace(dev, node_unschedulable=unsched)
    out = replace(fork)
    out.device_round = lambda: mutated  # type: ignore[method-assign]
    return out
