"""Staged executor drain through the real control-plane event path.

A drain is: cordon the executor (event-sourced, no new placements) ->
wait for voluntary completion -> preempt stragglers once the deadline
passes, gang-aware (every live member of a touched gang is preempted
fleet-wide, so partial gangs are never stranded) -> done when the
executor holds no live runs. Preemptions publish
`JobRunPreempted(requeue=True, reason="drain ...")` — the run dies with
a preemption the job-trace timeline shows, the job returns to QUEUED
and reschedules off the cordoned executor on the next round.

The SAME `DrainController` runs in two places:

  - live: registered on `SchedulerService.drains` (DrainCoordinator),
    stepped once per scheduling cycle inside `_cycle_body`, its events
    published with the cycle's sequences (leader-gated);
  - shadow: attached to the what-if planner's fork rollout
    (`planner.ForkRollout`), stepped by the rollout's virtual cycles.

One code path for dry-run and execution is what makes plan/apply
parity a structural property instead of a modeling claim
(tests/test_whatif.py::test_drain_plan_apply_parity_*).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..events import EventSequence, JobRunPreempted
from ..jobdb import JobState

_LIVE = (JobState.LEASED, JobState.PENDING, JobState.RUNNING)


@dataclass
class DrainOutcome:
    """What a drain did (or is predicted to do). The parity contract:
    a dry-run's outcome must equal execution's, field for field, in a
    deterministic sim."""

    executor: str
    initial_jobs: tuple = ()
    completed: tuple = ()  # finished voluntarily before the deadline
    preempted: tuple = ()  # preempt-requeued at the deadline
    blocked: tuple = ()  # non-preemptible stragglers the drain cannot move
    landings: dict = field(default_factory=dict)  # job_id -> node re-leased to
    rounds_to_drain: int | None = None  # cycles until the executor emptied
    done: bool = False

    def to_dict(self) -> dict:
        return {
            "executor": self.executor,
            "initial_jobs": sorted(self.initial_jobs),
            "completed": sorted(self.completed),
            "preempted": sorted(self.preempted),
            "blocked": sorted(self.blocked),
            "landings": dict(sorted(self.landings.items())),
            "rounds_to_drain": self.rounds_to_drain,
            "done": self.done,
        }


class DrainController:
    """One executor's staged drain; step once per scheduling cycle."""

    def __init__(
        self,
        scheduler,
        executor: str,
        *,
        deadline_s: float | None = None,
        metrics=None,
    ):
        self.scheduler = scheduler
        self.executor = executor
        cfg = getattr(scheduler, "config", None)
        self.deadline_s = (
            float(deadline_s)
            if deadline_s is not None
            else float(getattr(cfg, "drain_deadline_s", 0.0))
        )
        self.metrics = metrics
        self.started: float | None = None
        self.rounds = 0
        self.state = "pending"  # pending -> draining -> done
        self._initial: set[str] | None = None
        self._completed: set[str] = set()
        self._preempted: set[str] = set()
        self._blocked: set[str] = set()
        self._landings: dict[str, str] = {}
        self._rounds_to_drain: int | None = None

    # -- stepping -------------------------------------------------------

    def _live_on_executor(self, txn) -> dict:
        return {
            job.id: job
            for job in txn.jobs_for_executor(self.executor)
            if job.latest_run is not None and job.state in _LIVE
        }

    def step(self, now: float) -> list[EventSequence]:
        """Advance the drain one cycle; returns event sequences for the
        cycle to publish (leader-gated with everything else)."""
        if self.state == "done":
            return []
        txn = self.scheduler.jobdb.read_txn()
        if self.started is None:
            self.started = now
            self.state = "draining"
            # Cordon first (event-sourced; idempotent no-op if already
            # cordoned): this cycle's round already skips the executor.
            self.scheduler.set_executor_cordon(self.executor, True)
        self.rounds += 1
        live = self._live_on_executor(txn)
        if self._initial is None:
            self._initial = set(live)
        # Voluntary completions: initial jobs that reached a terminal
        # success since the drain started.
        for jid in self._initial:
            if jid in self._completed or jid in self._preempted:
                continue
            job = txn.get(jid)
            if job is not None and job.state == JobState.SUCCEEDED:
                self._completed.add(jid)
                if self._metric_ok():
                    self.metrics.drain_jobs_completed.labels(
                        executor=self.executor
                    ).inc()
        # Requeue landings: preempted jobs re-leased elsewhere.
        for jid in self._preempted:
            if jid in self._landings:
                continue
            job = txn.get(jid)
            run = job.latest_run if job is not None else None
            if (
                job is not None
                and run is not None
                and job.state in _LIVE
                and run.executor != self.executor
            ):
                self._landings[jid] = run.node_id
        if not live:
            if self._rounds_to_drain is None:
                self._rounds_to_drain = self.rounds
            # Done only once every preempted job has landed (or cannot:
            # nothing queued-live left of it) — the outcome then carries
            # the full displacement map.
            pending_landing = [
                jid
                for jid in self._preempted
                if jid not in self._landings
                and (txn.get(jid) is not None
                     and not txn.get(jid).state.terminal)
            ]
            if not pending_landing:
                self.state = "done"
            return []
        if now - self.started < self.deadline_s:
            return []  # still inside the voluntary-completion window
        # Deadline passed: preempt-requeue the stragglers, gang-aware.
        return self._preempt_stragglers(txn, live, now)

    def _preempt_stragglers(self, txn, live: dict, now: float):
        by_jobset: dict[tuple, list] = {}
        handled: set[str] = set()
        for jid, job in sorted(live.items()):
            if jid in handled or jid in self._preempted:
                continue
            members = [job]
            if job.spec.gang is not None:
                # Never strand a partial gang: every live member goes,
                # wherever it runs — the whole gang reschedules together.
                members = [
                    m
                    for m in txn.gang_jobs(job.queue, job.spec.gang.id)
                    if m.state in _LIVE
                ]
            preemptible = all(
                self.scheduler.config.priority_class(
                    m.spec.priority_class
                ).preemptible
                for m in members
            )
            if not preemptible:
                for m in members:
                    handled.add(m.id)
                    self._blocked.add(m.id)
                continue
            for m in members:
                if m.id in handled or m.id in self._preempted:
                    continue
                handled.add(m.id)
                run = m.latest_run
                if run is None:
                    continue
                self._preempted.add(m.id)
                reason = f"drain {self.executor}: deadline reached"
                if run.executor != self.executor:
                    reason = (
                        f"drain {self.executor}: gang member of a "
                        "drained job"
                    )
                by_jobset.setdefault((m.queue, m.jobset), []).append(
                    JobRunPreempted(
                        created=now,
                        job_id=m.id,
                        run_id=run.id,
                        reason=reason,
                        requeue=True,
                    )
                )
                if self._metric_ok():
                    self.metrics.drain_jobs_preempted.labels(
                        executor=self.executor
                    ).inc()
        return [
            EventSequence.of(queue, jobset, *events)
            for (queue, jobset), events in sorted(by_jobset.items())
        ]

    def _metric_ok(self) -> bool:
        return (
            self.metrics is not None
            and getattr(self.metrics, "registry", None) is not None
        )

    # -- reads ----------------------------------------------------------

    def outcome(self) -> DrainOutcome:
        return DrainOutcome(
            executor=self.executor,
            initial_jobs=tuple(sorted(self._initial or ())),
            completed=tuple(sorted(self._completed)),
            preempted=tuple(sorted(self._preempted)),
            blocked=tuple(sorted(self._blocked)),
            landings=dict(self._landings),
            rounds_to_drain=self._rounds_to_drain,
            done=self.state == "done",
        )

    def status(self) -> dict:
        doc = self.outcome().to_dict()
        doc.update(
            state=self.state,
            started=self.started,
            rounds=self.rounds,
            deadline_s=self.deadline_s,
        )
        return doc


class DrainCoordinator:
    """Active drains on one scheduler; stepped by the cycle loop."""

    def __init__(self, scheduler):
        self.scheduler = scheduler
        self._drains: dict[str, DrainController] = {}

    def start(
        self, executor: str, *, deadline_s: float | None = None, metrics=None
    ) -> DrainController:
        """Begin (or return the already-active) drain for an executor.
        Idempotent: repeated ExecuteDrain calls poll the same drain."""
        existing = self._drains.get(executor)
        if existing is not None and existing.state != "done":
            if deadline_s is not None:
                # An explicit new deadline re-arms the active drain (an
                # operator escalating `--deadline-s 0` must not have the
                # request silently dropped in favor of the old window).
                existing.deadline_s = float(deadline_s)
            return existing
        ctl = DrainController(
            self.scheduler,
            executor,
            deadline_s=deadline_s,
            metrics=metrics
            if metrics is not None
            else getattr(self.scheduler, "metrics", None),
        )
        self._drains[executor] = ctl
        return ctl

    def step(self, now: float) -> list[EventSequence]:
        sequences: list[EventSequence] = []
        for ctl in self._drains.values():
            sequences += ctl.step(now)
        return sequences

    def status(self, executor: str | None = None):
        if executor is not None:
            ctl = self._drains.get(executor)
            return ctl.status() if ctl is not None else None
        return {name: ctl.status() for name, ctl in self._drains.items()}

    @property
    def active(self) -> list[str]:
        return [n for n, c in self._drains.items() if c.state != "done"]
