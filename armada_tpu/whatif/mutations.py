"""Composable hypothetical edits applied to a forked round state.

Each mutation is a small dataclass with `apply(state: ForkState)`; a
plan applies them in order, then re-solves. The wire shape (gRPC both
encodings, `armadactl whatif` flags, `GET /api/whatif` params) is a
list of dicts: `{"kind": "...", ...}` — `mutation_from_dict` is the
single decoder, so every surface accepts the same vocabulary:

  cordon_node / uncordon_node   {"name": node_id}
  remove_node                   {"name": node_id}
  add_nodes                     {"count": n, "cpu": "8", "memory": ...,
                                 "gpu": ..., "name": prefix,
                                 "executor": ..., "requests": {...}}
  drain_executor                {"name": executor}  (cordon + staged
                                 preempt-requeue inside the rollout —
                                 the same DrainController execution
                                 runs, whatif/drain.py)
  cordon_executor               {"name": executor}
  inject_gang / inject_jobs     {"queue": q, "count": n,
                                 "gang_cardinality": c, "cpu": ...,
                                 "memory": ..., "gpu": ...,
                                 "priority_class": ..., "requests": {...}}
  scale_queue                   {"name": q, "weight": w} or
                                {"name": q, "priority_factor": pf}
  policy                        {"policy": "proportional"} — flip the
                                forked pool's fairness policy
                                (solver/policy.py) and re-solve; the
                                plan's fairness_delta names the payers

Injected jobs are normalized through the SAME snapshot-build helper the
SubmitChecker uses (`services/submit_check.static_check`), so checker
and planner feasibility semantics cannot drift.
"""

from __future__ import annotations

import dataclasses
import itertools
from dataclasses import dataclass, field, replace as dc_replace

from ..core.types import Gang, JobSpec, NodeSpec, QueueSpec
from .fork import ForkState

_inject_counter = itertools.count()


class Mutation:
    """Base class; subclasses implement apply(state)."""

    kind = ""

    def apply(self, state: ForkState) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {"kind": self.kind}
        d.update(dataclasses.asdict(self))
        if d.get("uncordon"):
            # Round-trip the uncordon variants to their own kind so
            # to_dict() output feeds back through mutation_from_dict.
            d["kind"] = "un" + d["kind"]
        return d


def _requests_from(d: dict) -> dict:
    """Resource requests from either a full `requests` dict or the
    cpu/memory/gpu convenience scalars (the proto wire's shape)."""
    req = dict(d.get("requests") or {})
    if not req:
        if d.get("cpu"):
            req["cpu"] = str(d["cpu"])
        if d.get("memory"):
            req["memory"] = str(d["memory"])
        if d.get("gpu") and str(d.get("gpu")) not in ("0", ""):
            req["nvidia.com/gpu"] = str(d["gpu"])
    return req


@dataclass
class CordonNode(Mutation):
    kind = "cordon_node"
    name: str = ""
    uncordon: bool = False

    def apply(self, state: ForkState) -> None:
        found = False
        for i, node in enumerate(state.nodes):
            if node.id == self.name:
                state.nodes[i] = dc_replace(
                    node, unschedulable=not self.uncordon
                )
                found = True
        if not found:
            raise KeyError(f"node {self.name!r} not in the fork")


@dataclass
class RemoveNode(Mutation):
    kind = "remove_node"
    name: str = ""

    def apply(self, state: ForkState) -> None:
        before = len(state.nodes)
        state.nodes = [n for n in state.nodes if n.id != self.name]
        if len(state.nodes) == before:
            raise KeyError(f"node {self.name!r} not in the fork")
        state.node_executor.pop(self.name, None)
        # Jobs running on the removed node are displaced immediately:
        # they reappear queued (the reconciliation path would requeue
        # gang jobs; the hypothetical models the optimistic recovery).
        displaced = [r for r in state.running if r.node_id == self.name]
        state.running = [r for r in state.running if r.node_id != self.name]
        state.queued = [r.job for r in displaced] + state.queued


@dataclass
class AddNodes(Mutation):
    kind = "add_nodes"
    count: int = 1
    name: str = "whatif-node"
    executor: str = ""
    cpu: str = ""
    memory: str = ""
    gpu: str = ""
    requests: dict = field(default_factory=dict)
    labels: dict = field(default_factory=dict)

    def apply(self, state: ForkState) -> None:
        resources = _requests_from(
            {"requests": self.requests, "cpu": self.cpu or "8",
             "memory": self.memory or "128Gi", "gpu": self.gpu}
        )
        executor = self.executor or f"{self.name}-exec"
        for i in range(int(self.count)):
            nid = f"{self.name}-{i:05d}"
            state.nodes.append(
                NodeSpec(
                    id=nid,
                    name=nid,
                    executor=executor,
                    pool=state.pool,
                    labels=dict(self.labels),
                    total_resources=dict(resources),
                )
            )
            state.node_executor[nid] = executor


@dataclass
class CordonExecutor(Mutation):
    kind = "cordon_executor"
    name: str = ""
    uncordon: bool = False

    def apply(self, state: ForkState) -> None:
        if self.uncordon:
            state.cordoned_executors.discard(self.name)
        else:
            state.cordoned_executors.add(self.name)


@dataclass
class DrainExecutor(Mutation):
    """Drain = cordon now + staged preempt-requeue at the deadline,
    executed INSIDE the rollout by the same DrainController the live
    control plane runs (whatif/drain.py) — dry-run and execution share
    one code path by construction."""

    kind = "drain_executor"
    name: str = ""
    deadline_s: float | None = None

    def apply(self, state: ForkState) -> None:
        if self.name not in set(state.node_executor.values()) | {
            n.executor for n in state.nodes
        }:
            raise KeyError(f"executor {self.name!r} not in the fork")
        state.drain_executors.append((self.name, self.deadline_s))


@dataclass
class InjectGang(Mutation):
    kind = "inject_gang"
    queue: str = ""
    count: int = 1
    gang_cardinality: int = 0
    cpu: str = ""
    memory: str = ""
    gpu: str = ""
    priority_class: str = ""
    requests: dict = field(default_factory=dict)
    node_selector: dict = field(default_factory=dict)

    def apply(self, state: ForkState) -> None:
        if not self.queue:
            raise ValueError("inject_gang needs a queue")
        requests = _requests_from(
            {"requests": self.requests, "cpu": self.cpu or "1",
             "memory": self.memory or "1Gi", "gpu": self.gpu}
        )
        if not any(q.name == self.queue for q in state.queues):
            state.queues.append(QueueSpec(self.queue))
        serial = next(_inject_counter)
        card = int(self.gang_cardinality)
        gang = None
        if card > 0:
            gang_id = f"whatif-gang-{serial}"
            gang = Gang(id=gang_id, cardinality=card)
            state.injected_gangs.append((gang_id, self.queue, card))
        n = int(self.count) if card <= 0 else card
        # Hypothetical jobs sort AFTER every real queued job (newest
        # submission): submitted_ts past any live stamp.
        last_ts = max(
            [j.submitted_ts for j in state.queued]
            + [r.job.submitted_ts for r in state.running]
            + [0.0]
        )
        for i in range(n):
            jid = f"whatif-{serial}-{i:04d}"
            state.queued.append(
                JobSpec(
                    id=jid,
                    queue=self.queue,
                    jobset=f"whatif-{serial}",
                    priority_class=self.priority_class,
                    requests=dict(requests),
                    node_selector=dict(self.node_selector),
                    gang=gang,
                    submitted_ts=last_ts + 1.0 + serial,
                )
            )
            state.injected_job_ids.append(jid)


@dataclass
class ScaleQueue(Mutation):
    kind = "scale_queue"
    name: str = ""
    weight: float | None = None
    priority_factor: float | None = None

    def apply(self, state: ForkState) -> None:
        pf = self.priority_factor
        if pf is None:
            if self.weight is None or self.weight <= 0:
                raise ValueError("scale_queue needs weight or priority_factor")
            pf = 1.0 / float(self.weight)
        found = False
        for i, q in enumerate(state.queues):
            if q.name == self.name:
                state.queues[i] = QueueSpec(q.name, float(pf))
                found = True
        if not found:
            state.queues.append(QueueSpec(self.name, float(pf)))


@dataclass
class SetPolicy(Mutation):
    """Hypothetical fairness-policy flip for the forked pool: the
    rollout re-solves under the candidate objective (solver/policy.py),
    and the plan's fairness_delta names which queues pay for the flip.
    The live analogue is SchedulerService.set_fairness_policy."""

    kind = "policy"
    policy: str = ""

    def apply(self, state: ForkState) -> None:
        from ..solver import policy as fp

        spec = fp.normalize_spec(self.policy)  # ValueError on unknown
        if getattr(state.config, "market_driven", False) and (
            fp.spec_kind(spec) != "drf"
        ):
            raise ValueError(
                "market-driven pools price off the DRF dominant share; "
                f"cannot simulate policy {self.policy!r}"
            )
        pools = dict(getattr(state.config, "fairness_policy_pools", {}) or {})
        pools[state.pool] = fp.spec_to_str(spec)
        state.config = dc_replace(state.config, fairness_policy_pools=pools)


_KINDS = {
    "cordon_node": lambda d: CordonNode(name=d.get("name", d.get("node_id", ""))),
    "uncordon_node": lambda d: CordonNode(
        name=d.get("name", d.get("node_id", "")), uncordon=True
    ),
    "remove_node": lambda d: RemoveNode(name=d.get("name", d.get("node_id", ""))),
    "add_nodes": lambda d: AddNodes(
        count=int(d.get("count", 1) or 1),
        name=d.get("name") or "whatif-node",
        executor=d.get("executor", ""),
        cpu=str(d.get("cpu", "")),
        memory=str(d.get("memory", "")),
        gpu=str(d.get("gpu", "")),
        requests=dict(d.get("requests") or {}),
        labels=dict(d.get("labels") or {}),
    ),
    "cordon_executor": lambda d: CordonExecutor(name=d.get("name", "")),
    "uncordon_executor": lambda d: CordonExecutor(
        name=d.get("name", ""), uncordon=True
    ),
    "drain_executor": lambda d: DrainExecutor(
        name=d.get("name", d.get("executor", "")),
        deadline_s=(
            float(d["deadline_s"]) if d.get("deadline_s") is not None else None
        ),
    ),
    "inject_gang": lambda d: InjectGang(
        queue=d.get("queue", ""),
        count=int(d.get("count", 1) or 1),
        gang_cardinality=int(d.get("gang_cardinality", 0) or 0),
        cpu=str(d.get("cpu", "")),
        memory=str(d.get("memory", "")),
        gpu=str(d.get("gpu", "")),
        priority_class=d.get("priority_class", ""),
        requests=dict(d.get("requests") or {}),
        node_selector=dict(d.get("node_selector") or {}),
    ),
    "scale_queue": lambda d: ScaleQueue(
        name=d.get("name", d.get("queue", "")),
        weight=(float(d["weight"]) if d.get("weight") else None),
        priority_factor=(
            float(d["priority_factor"]) if d.get("priority_factor") else None
        ),
    ),
    "policy": lambda d: SetPolicy(
        policy=str(d.get("policy", d.get("name", "")))
    ),
}
_KINDS["inject_jobs"] = _KINDS["inject_gang"]


def mutation_from_dict(d: dict) -> Mutation:
    kind = d.get("kind", "")
    builder = _KINDS.get(kind)
    if builder is None:
        raise ValueError(
            f"unknown mutation kind {kind!r}; have {sorted(_KINDS)}"
        )
    return builder(d)


def mutations_from_dicts(items) -> list[Mutation]:
    return [mutation_from_dict(d) for d in items or ()]
