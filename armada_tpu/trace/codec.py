"""Serialisation of DeviceRound inputs and decision streams.

A `.atrace` bundle is append-only JSON lines: one header record, then
one record per recorded round. Numpy arrays travel as raw little-endian
bytes (base64) tagged with dtype + shape, so the decode is a bit-exact
reconstruction — not a float round-trip through decimal text. Each line
then rides through `utils.compress.compress_obj` (the lease-stream zlib
marker format), which is what keeps a committed fixture trace small.

Python-scalar fields of DeviceRound (the jit meta fields plus floats
like `global_tokens`) are encoded with their host type preserved: a
replayed round must hand the kernel EXACTLY the pytree the recorded
round did — a float that came back as a 0-d array would change weak-
type promotion inside the compiled program.
"""

from __future__ import annotations

import base64
import dataclasses
import json

import numpy as np

from ..solver.kernel_prep import DeviceRound
from ..utils.compress import compress_obj, decompress_obj

FORMAT = "atrace/1"


class TraceFormatError(ValueError):
    """The bundle does not decode under this build's trace schema."""


def encode_field(value):
    """JSON-encodable tagging of one DeviceRound field / decision value."""
    if isinstance(value, np.generic):
        # BEFORE the plain-scalar branch: np.float64 subclasses float, and
        # flattening it to a JSON number would decode as a weak-typed
        # Python float where the recorded pytree had a strong numpy
        # scalar (spot_price_cutoff) — a different jit signature.
        return encode_field(np.asarray(value))
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, tuple):
        return {"__tuple__": [encode_field(v) for v in value]}
    arr = np.asarray(value)
    # Little-endian on the wire whatever the host: '<' prefix pins it.
    dt = arr.dtype.newbyteorder("<")
    return {
        "__nd__": str(dt),
        "shape": list(arr.shape),
        "b64": base64.b64encode(np.ascontiguousarray(arr.astype(dt)).tobytes()).decode(),
    }


def decode_field(value):
    if isinstance(value, dict) and "__tuple__" in value:
        return tuple(decode_field(v) for v in value["__tuple__"])
    if isinstance(value, dict) and "__nd__" in value:
        raw = base64.b64decode(value["b64"])
        arr = np.frombuffer(raw, dtype=np.dtype(value["__nd__"]))
        # .copy(): frombuffer views are read-only; kernels and pad paths
        # expect ordinary writable host arrays. Also drops the explicit
        # byte-order tag back to native.
        arr = arr.reshape(value["shape"]).astype(np.dtype(value["__nd__"]).newbyteorder("=")).copy()
        if not value["shape"]:
            # 0-d payloads were numpy scalars (e.g. spot_price_cutoff).
            return arr[()]
        return arr
    return value


def encode_device_round(dev: DeviceRound) -> dict:
    return {
        f.name: encode_field(getattr(dev, f.name))
        for f in dataclasses.fields(DeviceRound)
    }


# Fields added after atrace/1 shipped, with the exact value every older
# bundle's rounds ran under. Decoding substitutes ONLY these — anything
# else missing is still a schema error. queue_deadline derives its Q
# from the decoded queue_weight.
_COMPAT_DEFAULTS = {
    "fairness_policy": lambda doc: ("drf",),
    # Solve-kernel selection (ops/pallas_kernels.py) postdates every
    # pre-pallas bundle; those rounds all ran the lax graph.
    "kernel_path": lambda doc: "lax",
    "queue_deadline": lambda doc: np.full(
        np.asarray(decode_field(doc["queue_weight"])).shape[0],
        np.inf,
        dtype=np.float64,
    ),
}


def decode_device_round(doc: dict) -> DeviceRound:
    fields = {f.name for f in dataclasses.fields(DeviceRound)}
    missing = fields - doc.keys()
    unknown = doc.keys() - fields
    defaulted = {k for k in missing if k in _COMPAT_DEFAULTS}
    missing -= defaulted
    if missing or unknown:
        raise TraceFormatError(
            "trace DeviceRound schema mismatch vs this build: "
            f"missing={sorted(missing)} unknown={sorted(unknown)} — "
            "re-record the trace against the current kernel inputs"
        )
    out = {k: decode_field(v) for k, v in doc.items()}
    for k in defaulted:
        out[k] = _COMPAT_DEFAULTS[k](doc)
    return DeviceRound(**out)


def encode_record(record: dict) -> str:
    """One .atrace line (zlib-wrapped when it pays off)."""
    return json.dumps(compress_obj(record, min_size=256), separators=(",", ":"))


def decode_record(line: str) -> dict:
    try:
        return decompress_obj(json.loads(line))
    except (json.JSONDecodeError, ValueError) as e:
        raise TraceFormatError(f"undecodable trace line: {e}") from e
