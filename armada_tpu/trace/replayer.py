"""Deterministic replay of recorded rounds + divergence classification.

The replayer reconstructs a recorded round's DeviceRound bit-for-bit
and re-solves it under any solver spec:

  - "LOCAL"          — the fused single-device kernel (solve_round)
  - "hotwindow[:W]"  — hot-window compacted pass 1 (window_min_slots=0
                       so compaction engages at any scale it can shrink)
  - "2x4", "8", 4    — the node-sharded mesh solve (parallel/multihost
                       resolve_solver spellings; 2D = HierarchicalDist)

and compares the decision stream against the recorded one. Divergences
classify as:

  placement          — any decision array differs (placements, evictions,
                       priorities, fair shares, spot price)
  loop_stream        — decisions identical but the pass-1 loop count
                       differs (the solver took a different path to the
                       same answer; kernel-recorded rounds only)
  fairness_ledger    — the fairness block (per-queue share ledger +
                       preemption attribution, observe/fairness.py)
                       recomputed from the round's own DeviceRound and
                       the REPLAYED decisions differs from the recorded
                       block: the replay delivered different shares or
                       attributed preemptions differently (rounds from
                       pre-fairness bundles simply lack the block)
  profile_regression — replayed solve wall clock beyond
                       `profile_threshold` x the recorded solve time
                       (opt-in: wall clocks only compare on one host)
  retrace            — XLA traced/compiled during a round whose shape
                       signature was already replayed under the same
                       solver (observe/xla.py telemetry): a warm cycle
                       must dispatch cached executables, so any compile
                       here is the silent-warm-recompile failure mode
  resident_drift     — two-bundle differential only (`diff_traces`): the
                       same scenario recorded under rebuild and under
                       device-resident snapshot mode
                       (snapshot/residency.py) disagrees — a solver
                       input leaf, a decision array, or the fairness
                       block differs between the paired rounds, meaning
                       the delta-applied resident round drifted from
                       the rebuilt-from-jobdb truth

Replay REFUSES a bundle whose target signature (host CPU features,
effective XLA target, x64 mode) differs from this process unless
explicitly overridden: silently diffing against decisions produced by
different arithmetic reports phantom divergences. The override is sound
for x64-recorded traces — exact int64/float64 decisions are
host-independent (the oracle-parity contract) — which is why committed
fixture traces replay everywhere; an x64-mode mismatch always refuses.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from .codec import TraceFormatError, decode_device_round, decode_field, decode_record
from .recorder import DECISION_KEYS

_JOB_KEYS = (
    "assigned_node",
    "scheduled_priority",
    "scheduled_mask",
    "preempted_mask",
)
_QUEUE_KEYS = ("fair_share", "demand_capped_fair_share", "uncapped_fair_share")

PERTURBATIONS = ("tiebreak",)


class TraceTargetMismatch(RuntimeError):
    """The bundle was recorded on a different target than this process."""


class CrossPolicyMismatch(RuntimeError):
    """Two bundles recorded under different fairness policies were
    handed to a bit-exact differential: every share and most placements
    legitimately differ, so a drift verdict would be meaningless. Only
    an EXPLICIT policy A/B (allow_cross_policy=True, or
    tools/policy_ab.py which compares scorecards rather than bits) may
    compare across policies."""


def trace_policies(trace: Trace) -> dict:
    """The fairness-policy stamp from a bundle's header (recorder
    config_summary). Pre-policy bundles read as all-DRF."""
    summary = (trace.header or {}).get("config_summary") or {}
    return {
        "default": str(summary.get("fairness_policy_default") or "drf"),
        "pools": {
            str(k): str(v)
            for k, v in (summary.get("fairness_policy_pools") or {}).items()
        },
    }


@dataclasses.dataclass
class RoundRecord:
    raw: dict

    def __getitem__(self, key):
        return self.raw[key]

    @property
    def pool(self) -> str:
        return self.raw.get("pool", "")

    @property
    def num_jobs(self) -> int:
        return int(self.raw["num_jobs"])

    @property
    def num_queues(self) -> int:
        return int(self.raw["num_queues"])

    @property
    def truncated(self) -> bool:
        return bool(self.raw.get("truncated", False))

    @property
    def backend(self) -> str:
        return str(self.raw.get("solver", {}).get("backend", "kernel"))

    def device_round(self):
        return decode_device_round(self.raw["dev"])

    def decisions(self) -> dict:
        return {k: decode_field(v) for k, v in self.raw["decisions"].items()}


@dataclasses.dataclass
class Trace:
    path: str
    header: dict
    rounds: list


def load_trace(path: str) -> Trace:
    header = None
    rounds = []
    with open(path) as f:
        for ln, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            record = decode_record(line)
            kind = record.get("kind")
            if kind == "header":
                if header is not None:
                    raise TraceFormatError(
                        f"{path}:{ln + 1}: second header record — the file "
                        "holds multiple recording sessions appended "
                        "together; later rounds would compare against the "
                        "first session's target/config/seeds. Re-record to "
                        "a fresh bundle (TraceRecorder replaces existing "
                        "files unless append=True)."
                    )
                header = record
                continue
            if kind == "round":
                rounds.append(RoundRecord(record))
                continue
            raise TraceFormatError(f"{path}:{ln + 1}: unknown record kind {kind!r}")
    if header is None:
        raise TraceFormatError(f"{path}: no header record — not an .atrace bundle")
    return Trace(path=path, header=header, rounds=rounds)


def check_target(header: dict, *, allow_foreign: bool = False) -> None:
    """Raise TraceTargetMismatch unless this process matches the
    bundle's recorded target signature (see module docstring)."""
    from .recorder import _target_signature

    recorded = header.get("target") or {}
    current = _target_signature()
    if bool(recorded.get("x64")) != current["x64"]:
        raise TraceTargetMismatch(
            f"trace was recorded with x64={recorded.get('x64')} but this "
            f"process runs x64={current['x64']}: decision arithmetic "
            "differs (approximate float32 vs exact float64 costs) — "
            "replay comparison would be meaningless. Re-record, or match "
            "ARMADA_TPU_X64."
        )
    mismatched = [
        k
        for k in ("host_cpu", "xla")
        if recorded.get(k) is not None and recorded.get(k) != current[k]
    ]
    if mismatched and not allow_foreign:
        detail = ", ".join(
            f"{k}: recorded {recorded.get(k)!r} != current {current[k]!r}"
            for k in mismatched
        )
        raise TraceTargetMismatch(
            f"trace target signature mismatch ({detail}): this bundle was "
            "recorded on a different host/toolchain, so its compiled "
            "decisions may be stale for this target. Pass "
            "allow_foreign=True (--allow-foreign) only for x64-recorded "
            "traces, whose exact decisions are host-independent."
        )


def replay_solver(spec, header: dict | None = None):
    """(label, dev -> numpy output dict) for one solver spec string."""
    from ..solver.kernel import solve_round

    label = str(spec)
    if label.upper() == "LOCAL":
        return "LOCAL", lambda dev: solve_round(dev)
    # Kernel paths are solver-spec dimensions of their own: each one is
    # a distinct compiled program (kernel_path is static jit meta), so
    # the replayer pins them separately — "blocked" / "pallas" / "lax"
    # solve LOCAL under that path; "pallas:2x4" runs the mesh spelling
    # through the pallas winner-exchange dist.
    kl = label.lower()
    if kl in ("lax", "blocked", "pallas", "native") or (
        kl.startswith(("pallas:", "blocked:"))
    ):
        import dataclasses as _dc

        path, _, meshspec = kl.partition(":")
        if path == "native":
            from ..ops.pallas_kernels import resolve_kernel_path

            path = resolve_kernel_path("native")
        if not meshspec:
            return (
                f"kernel:{path}",
                lambda dev: solve_round(
                    _dc.replace(dev, kernel_path=path)
                ),
            )
        from ..parallel.mesh import pad_nodes as _pad
        from ..parallel.multihost import resolve_solver as _rs

        run = _rs(meshspec, kernel_path=path)

        def solve_mesh_path(dev):
            dev = _dc.replace(dev, kernel_path=path)
            out = run(_pad(dev, run.n_shards))
            return {k: np.asarray(v) for k, v in out.items()}

        return f"kernel:{path}:mesh:{meshspec}", solve_mesh_path
    if label.lower().startswith("hotwindow"):
        if ":" in label:
            window = int(label.split(":", 1)[1])
        else:
            summary = (header or {}).get("config_summary") or {}
            window = int(summary.get("hot_window_slots") or 0) or max(
                4, 2 * int(summary.get("batch_fill_window") or 2)
            )
        return (
            f"hotwindow:{window}",
            lambda dev: solve_round(dev, window=window, window_min_slots=0),
        )
    # Anything else is a mesh spelling ("2x4", "8", an int, a tuple).
    from ..parallel.mesh import pad_nodes
    from ..parallel.multihost import resolve_solver

    run = resolve_solver(int(spec) if isinstance(spec, str) and spec.isdigit() else spec)

    def solve(dev):
        out = run(pad_nodes(dev, run.n_shards))
        return {k: np.asarray(v) for k, v in out.items()}

    return f"mesh:{label}", solve


def perturb_device_round(dev, kind: str):
    """A deliberately-buggy candidate kernel, simulated at the input
    seam: 'tiebreak' reverses the node-id tie-break ranking, the kind
    of silent ordering regression the replay gate exists to catch.
    Placements move wherever two nodes tie on the best-fit key."""
    if kind == "tiebreak":
        rank = np.asarray(dev.node_id_rank)
        return dataclasses.replace(
            dev, node_id_rank=(rank.max() - rank).astype(rank.dtype)
        )
    raise ValueError(f"unknown perturbation {kind!r}; have {PERTURBATIONS}")


def _first_diffs(a, b, limit=4):
    idx = np.flatnonzero(np.asarray(a) != np.asarray(b))[:limit]
    return [int(i) for i in idx]


def compare_fairness(rec: RoundRecord, dev, out: dict):
    """`fairness_ledger` divergence: recompute the canonical fairness
    block from the recorded DeviceRound + the REPLAYED output and diff
    it against the recorded block (both normalized through JSON — the
    recorded one crossed it, and doubles round-trip exactly). Returns a
    divergence dict or None; rounds without a recorded block (pre-
    fairness bundles) always pass."""
    import json

    recorded = rec.raw.get("fairness")
    if not recorded:
        return None
    from ..observe.fairness import ledger_from_device_round

    recomputed = ledger_from_device_round(
        dev, out, rec.num_jobs, rec.num_queues
    )
    want = json.loads(json.dumps(recorded, sort_keys=True))
    got = json.loads(json.dumps(recomputed, sort_keys=True))
    if want == got:
        return None
    details = []
    w_rows = (want.get("ledger") or {}).get("queues", [])
    g_rows = (got.get("ledger") or {}).get("queues", [])
    for q, (a, b) in enumerate(zip(w_rows, g_rows)):
        if a != b:
            fields = sorted(k for k in a.keys() | b.keys() if a.get(k) != b.get(k))
            details.append(f"queue[{q}] differs on {fields}")
            break
    if want.get("preemptions") != got.get("preemptions"):
        details.append("preemption attribution differs")
    for key in ("jain", "max_regret", "delivered_total"):
        if (want.get("ledger") or {}).get(key) != (got.get("ledger") or {}).get(key):
            details.append(f"{key} differs")
            break
    return {
        "kind": "fairness_ledger",
        "key": "fairness",
        "detail": "replayed fairness ledger diverges from the recorded "
        "block: " + ("; ".join(details) or "structural mismatch"),
    }


def _shape_signature(dev) -> tuple:
    """The (treedef, shapes, dtypes) signature that determines which
    compiled programs a DeviceRound dispatches to. Two rounds with the
    same signature must replay WITHOUT tracing or compiling anything:
    the first replay of each signature warms the jit caches, and any
    XLA activity on a later same-signature round is an unexpected warm
    retrace — the production failure mode where a drifted static arg
    quietly pays seconds of compile inside every 'warm' cycle."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(dev)
    return (
        str(treedef),
        tuple(
            (getattr(v, "shape", ()), str(getattr(v, "dtype", type(v).__name__)))
            for v in leaves
        ),
    )


def compare_round(rec: RoundRecord, out: dict, *, compare_loops: bool | None = None):
    """Divergences between a recorded round's decisions and a replayed
    output dict. Arrays compare on the UNPADDED prefix (the recorded
    round and the replay may pad differently); returns a list of
    {kind, key, detail} dicts, empty when bit-exact."""
    recorded = rec.decisions()
    J, Q = rec.num_jobs, rec.num_queues
    oracle = rec.backend == "oracle"
    if compare_loops is None:
        # Oracle loop accounting is not the kernel's (the parity suite
        # excludes num_loops); only kernel-recorded rounds pin the stream.
        compare_loops = not oracle
    ids = rec.raw.get("ids") or {}
    job_ids = ids.get("jobs")
    divergences = []
    for key in _JOB_KEYS + _QUEUE_KEYS:
        if key not in recorded or key not in out:
            continue
        n = J if key in _JOB_KEYS else Q
        want = np.asarray(recorded[key])[:n]
        got = np.asarray(out[key])[:n]
        if not np.array_equal(want, got, equal_nan=True):
            where = _first_diffs(want, got)
            detail = f"{key}[:{n}] differs at indices {where}"
            if key in _JOB_KEYS and job_ids:
                names = [job_ids[i] for i in where if i < len(job_ids)]
                detail += f" (jobs {names})"
            divergences.append({"kind": "placement", "key": key, "detail": detail})
    if "spot_price" in recorded and "spot_price" in out and not oracle:
        want = float(np.asarray(recorded["spot_price"]))
        got = float(np.asarray(out["spot_price"]))
        if not (want == got or (np.isnan(want) and np.isnan(got))):
            divergences.append(
                {
                    "kind": "placement",
                    "key": "spot_price",
                    "detail": f"spot_price {want} != {got}",
                }
            )
    if compare_loops and "num_loops" in recorded and "num_loops" in out:
        want = int(np.asarray(recorded["num_loops"]))
        got = int(np.asarray(out["num_loops"]))
        if want != got:
            same = "identical decisions via " if not divergences else ""
            divergences.append(
                {
                    "kind": "loop_stream",
                    "key": "num_loops",
                    "detail": f"{same}a different loop stream: recorded "
                    f"{want} loops, replayed {got}",
                }
            )
    return divergences


def _diff_device_rounds(dev_a, dev_b) -> list[str]:
    """Field names (with a short detail) where two padded DeviceRounds
    are not bit-identical. NaNs compare by bits, so a NaN payload equal
    on both sides does NOT read as drift."""
    diffs = []
    for f in dataclasses.fields(dev_a):
        a, b = getattr(dev_a, f.name), getattr(dev_b, f.name)
        if hasattr(a, "shape") or hasattr(b, "shape"):
            a, b = np.asarray(a), np.asarray(b)
            if a.shape != b.shape or a.dtype != b.dtype:
                diffs.append(
                    f"{f.name}: {a.dtype}{a.shape} != {b.dtype}{b.shape}"
                )
                continue
            ab, bb = a.tobytes(), b.tobytes()
            if ab != bb:
                raw = np.flatnonzero(
                    np.frombuffer(ab, np.uint8) != np.frombuffer(bb, np.uint8)
                )
                first = int(raw[0] // max(1, a.itemsize))
                diffs.append(f"{f.name}: first differing element flat[{first}]")
        else:
            same = a == b
            try:
                same = bool(same) or (np.isnan(a) and np.isnan(b))
            except (TypeError, ValueError):
                same = bool(same)
            if not same:
                diffs.append(f"{f.name}: {a!r} != {b!r}")
    return diffs


def diff_traces(
    trace_a: Trace,
    trace_b: Trace,
    *,
    max_rounds: int | None = None,
    allow_cross_policy: bool = False,
    log=None,
) -> dict:
    """Two-bundle differential: pair rounds of two recordings of the
    SAME scenario by (pool, cycle) and diff each pair bit-for-bit —
    every DeviceRound leaf the solver consumed, the unpadded decision
    stream (including num_loops and spot_price), and the fairness
    block. The intended use is the residency correctness gate: record
    one run with `snapshot_mode="incremental"` (rebuild/re-upload every
    cycle) and one with `snapshot_mode="resident"` (delta scatter
    updates into persistent device buffers); any difference means the
    delta-applied round drifted from the rebuilt truth and classifies
    as `resident_drift`. Rounds present in only one bundle are listed
    under "unmatched" and fail the gate too — a cycle that solved under
    one mode but not the other is itself a divergence.

    Returns {"pairs", "unmatched", "results", "divergences", "ok"}.

    Refuses bundles whose recorded fairness policies differ (header
    pinning): a cross-policy diff legitimately diverges everywhere, so
    the drift verdict means nothing. allow_cross_policy=True is the
    explicit A/B escape hatch (the result then carries both policy
    stamps); scorecard-level comparison lives in tools/policy_ab.py.
    """
    import json

    pol_a, pol_b = trace_policies(trace_a), trace_policies(trace_b)
    cross_policy = pol_a != pol_b
    if cross_policy and not allow_cross_policy:
        raise CrossPolicyMismatch(
            f"bundle {trace_a.path} was recorded under fairness policies "
            f"{pol_a} but {trace_b.path} under {pol_b}: a bit-exact "
            "differential across policies is meaningless. Pass "
            "allow_cross_policy=True only for an explicit policy A/B, "
            "or compare scorecards with tools/policy_ab.py."
        )

    def index(trace):
        by_key = {}
        for rec in trace.rounds:
            cyc = rec.raw.get("cycle")
            key = (rec.pool, cyc if cyc is not None else rec.raw.get("i"))
            by_key.setdefault(key, []).append(rec)
        return by_key

    a_idx, b_idx = index(trace_a), index(trace_b)
    unmatched = sorted(
        f"{pool}@cycle={cyc}"
        for pool, cyc in set(a_idx) ^ set(b_idx)
    )
    results = []
    by_kind: dict[str, int] = {}
    pairs = 0
    for key in sorted(set(a_idx) & set(b_idx), key=lambda k: (str(k[0]), str(k[1]))):
        for rec_a, rec_b in zip(a_idx[key], b_idx[key]):
            if max_rounds is not None and pairs >= max_rounds:
                break
            pairs += 1
            divergences = []
            for d in _diff_device_rounds(rec_a.device_round(), rec_b.device_round()):
                divergences.append(
                    {
                        "kind": "resident_drift",
                        "key": "dev",
                        "detail": f"solver input differs: {d}",
                    }
                )
            dec_a, dec_b = rec_a.decisions(), rec_b.decisions()
            J, Q = rec_a.num_jobs, rec_a.num_queues
            for dk in _JOB_KEYS + _QUEUE_KEYS + ("num_loops", "spot_price"):
                if dk not in dec_a or dk not in dec_b:
                    if dk in dec_a or dk in dec_b:
                        divergences.append(
                            {
                                "kind": "resident_drift",
                                "key": dk,
                                "detail": f"{dk} recorded in one bundle only",
                            }
                        )
                    continue
                n = J if dk in _JOB_KEYS else Q if dk in _QUEUE_KEYS else None
                want = np.asarray(dec_a[dk])[:n] if n else np.asarray(dec_a[dk])
                got = np.asarray(dec_b[dk])[:n] if n else np.asarray(dec_b[dk])
                if not np.array_equal(want, got, equal_nan=True):
                    divergences.append(
                        {
                            "kind": "resident_drift",
                            "key": dk,
                            "detail": f"decision {dk} differs at indices "
                            f"{_first_diffs(want, got)}",
                        }
                    )
            fair_a = json.loads(json.dumps(rec_a.raw.get("fairness"), sort_keys=True))
            fair_b = json.loads(json.dumps(rec_b.raw.get("fairness"), sort_keys=True))
            if fair_a != fair_b:
                divergences.append(
                    {
                        "kind": "resident_drift",
                        "key": "fairness",
                        "detail": "fairness ledger differs between bundles",
                    }
                )
            for d in divergences:
                by_kind[d["kind"]] = by_kind.get(d["kind"], 0) + 1
            results.append(
                {
                    "pool": key[0],
                    "cycle": key[1],
                    "solver_a": rec_a.raw.get("solver"),
                    "solver_b": rec_b.raw.get("solver"),
                    "divergences": divergences,
                }
            )
            if log:
                status = "OK" if not divergences else (
                    "DRIFT " + "; ".join(d["detail"] for d in divergences)
                )
                log(f"pool={key[0]} cycle={key[1]}: {status}")
    ok = not by_kind and not unmatched
    out = {
        "trace_a": trace_a.path,
        "trace_b": trace_b.path,
        "pairs": pairs,
        "unmatched": unmatched,
        "results": results,
        "divergences": by_kind,
        "ok": ok,
    }
    if cross_policy:
        out["cross_policy"] = True
        out["policy_a"] = pol_a
        out["policy_b"] = pol_b
    return out


def replay_trace(
    trace: Trace,
    *,
    solvers=("LOCAL",),
    max_rounds: int | None = None,
    profile_threshold: float | None = None,
    perturb: str | None = None,
    allow_foreign: bool = False,
    flag_retraces: bool = True,
    metrics=None,
    log=None,
) -> dict:
    """Replay a bundle under each solver spec; returns the gate report:

      {"rounds": n_replayed, "skipped": n, "results": [...],
       "divergences": {kind: count}, "ok": bool}

    Truncated rounds are skipped (a budget-cut decision stream is a
    wall-clock-dependent prefix, not a deterministic target). `metrics`
    (services.metrics.SchedulerMetrics) gets the replay-divergence
    counter bumped per divergence kind."""
    check_target(trace.header, allow_foreign=allow_foreign)
    from ..observe.xla import TELEMETRY

    # Warm-retrace audit (flag_retraces): the first replay of each
    # round-shape signature per solver warms the jit caches; any
    # trace/compile activity on a LATER round with an already-seen
    # signature is classified `retrace` — the silent warm-cycle compile
    # the observatory exists to catch. Telemetry installs lazily and is
    # a no-op counter source when jax.monitoring is unavailable.
    telemetry_live = TELEMETRY.install() if flag_retraces else False
    seen_shapes: dict[str, set] = {}
    resolved = [replay_solver(s, trace.header) for s in solvers]
    results = []
    by_kind: dict[str, int] = {}
    replayed = skipped = 0
    for rec in trace.rounds:
        if max_rounds is not None and replayed >= max_rounds:
            break
        if rec.truncated:
            skipped += 1
            if log:
                log(f"round {rec.raw.get('i')}: skipped (budget-truncated)")
            continue
        dev = rec.device_round()
        if perturb:
            dev = perturb_device_round(dev, perturb)
        replayed += 1
        for label, solve in resolved:
            warm = False
            if telemetry_live:
                sig = _shape_signature(dev)
                warm = sig in seen_shapes.setdefault(label, set())
                # Thread-scoped: a concurrent solve elsewhere in the
                # process must not read as this round's retrace.
                comp0 = TELEMETRY.thread_snapshot()
            t0 = time.monotonic()
            out = solve(dev)
            replay_s = time.monotonic() - t0
            divergences = compare_round(rec, out)
            fairness_div = compare_fairness(rec, dev, out)
            if fairness_div is not None:
                divergences.append(fairness_div)
            if telemetry_live:
                delta = TELEMETRY.delta_since(comp0, thread=True)
                seen_shapes[label].add(sig)
                if warm and (delta["compiles"] or delta["traces"]):
                    divergences.append(
                        {
                            "kind": "retrace",
                            "key": "xla",
                            "detail": "warm shape retraced: "
                            f"{delta['traces']} trace(s), "
                            f"{delta['compiles']} compile(s) "
                            f"({delta['compile_seconds']}s) on an "
                            "already-replayed round signature",
                        }
                    )
            if profile_threshold and rec.raw.get("solve_s") is not None:
                # The first solve of a (solver, shape) pays JIT compile;
                # the recorded solve_s is a warm steady-state number. Time
                # a SECOND solve so the comparison is warm-vs-warm, and
                # floor tiny recorded times so sub-ms rounds don't trip
                # on scheduler noise.
                t1 = time.monotonic()
                solve(dev)
                warm_s = time.monotonic() - t1
                base = max(float(rec.raw["solve_s"]), 0.01)
                if warm_s > base * profile_threshold:
                    divergences.append(
                        {
                            "kind": "profile_regression",
                            "key": "solve_s",
                            "detail": f"warm replay {warm_s:.3f}s > "
                            f"{profile_threshold:.2f}x recorded "
                            f"{base:.3f}s",
                        }
                    )
            for d in divergences:
                by_kind[d["kind"]] = by_kind.get(d["kind"], 0) + 1
                if (
                    metrics is not None
                    and getattr(metrics, "registry", None) is not None
                ):
                    metrics.trace_replay_divergences.labels(kind=d["kind"]).inc()
            results.append(
                {
                    "round": rec.raw.get("i"),
                    "pool": rec.pool,
                    "solver": label,
                    "replay_s": round(replay_s, 4),
                    "divergences": divergences,
                }
            )
            if log:
                status = "OK" if not divergences else (
                    "DIVERGED " + "; ".join(d["detail"] for d in divergences)
                )
                log(
                    f"round {rec.raw.get('i')} pool={rec.pool} "
                    f"solver={label}: {status}"
                )
    return {
        "trace": trace.path,
        "rounds": replayed,
        "skipped": skipped,
        "results": results,
        "divergences": by_kind,
        "ok": not by_kind,
    }
