"""The flight recorder: append scheduler rounds to an `.atrace` bundle.

One recorder = one bundle. The first write emits a header carrying the
trace format version, the `utils/platform.py` target signature (host
CPU features + effective XLA target + x64 mode — a replay on a foreign
host refuses instead of silently comparing against decisions compiled
for different arithmetic), the scheduling-config fingerprint, and any
RNG / fault-plan seeds the caller supplies. Every round record then
holds the bit-exact padded DeviceRound the solver saw plus the decision
stream it produced.

Hooked into `services/scheduler.py` (attach_trace_recorder),
`sim/simulator.py` (trace_path=...) and `bench.py` (BENCH_TRACE=...).
Recording must never fail a round: callers wrap record_round in a
try/except and log.
"""

from __future__ import annotations

import hashlib
import os
import time

import numpy as np

from .codec import FORMAT, encode_device_round, encode_field, encode_record

# The decision-stream keys a replayed solve is compared against. These
# are exactly solver/kernel.solve_round's array outputs: masks/nodes/
# priorities over the padded job axis, shares over the padded queue
# axis, the market spot price, and the pass-1 loop count (the loop
# stream — host-driven and fused drivers run loop-for-loop identical,
# tests/test_hotwindow.py).
DECISION_KEYS = (
    "assigned_node",
    "scheduled_priority",
    "scheduled_mask",
    "preempted_mask",
    "fair_share",
    "demand_capped_fair_share",
    "uncapped_fair_share",
    "spot_price",
    "num_loops",
)

# Above this many jobs the id vocabularies are dropped by default: a 1M
# job round's id lists dwarf the tensor payload and replay equality is
# index-based anyway (ids only prettify divergence reports).
AUTO_IDS_MAX_JOBS = 100_000


def config_fingerprint(config) -> str:
    """Stable digest of the scheduling config. repr of the (frozen)
    dataclass tree is deterministic per process and content-addressed
    enough for replay bookkeeping — the round inputs themselves are
    recorded bit-exactly, the fingerprint only labels them."""
    return hashlib.sha256(repr(config).encode()).hexdigest()[:16]


def _target_signature() -> dict:
    from ..utils import platform as plat

    try:
        import jax

        x64 = bool(jax.config.jax_enable_x64)
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        x64 = False
    return {
        "host_cpu": plat.host_cpu_signature(),
        "xla": plat.xla_target_signature(),
        "x64": x64,
    }


class TraceRecorder:
    def __init__(
        self,
        path: str,
        *,
        source: str = "scheduler",
        config=None,
        seeds: dict | None = None,
        meta: dict | None = None,
        record_ids: bool | None = None,
        max_rounds: int | None = None,
        append: bool = False,
    ):
        """One recorder = one bundle = one recording session. By default
        an existing file at `path` is REPLACED at the first write: a
        bundle holds exactly one header, and appending a new session
        under an old header would replay later rounds against the wrong
        target signature / config fingerprint / seeds (load_trace
        refuses multi-header bundles). append=True is for resuming the
        same logical session only."""
        self.path = path
        self.source = source
        self.seeds = dict(seeds or {})
        self.meta = dict(meta or {})
        self.record_ids = record_ids
        self.max_rounds = max_rounds
        self.rounds_recorded = 0
        self.bytes_written = 0
        self._config = config
        self._append = append
        self._header_written = False
        self._fh = None

    # -- plumbing ------------------------------------------------------

    def _open(self):
        if self._fh is None:
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            self._fh = open(self.path, "a" if self._append else "w")
        return self._fh

    def _write(self, record: dict, metrics=None, pool: str | None = None) -> int:
        line = encode_record(record) + "\n"
        fh = self._open()
        fh.write(line)
        fh.flush()
        n = len(line.encode())
        self.bytes_written += n
        if metrics is not None and getattr(metrics, "registry", None) is not None:
            metrics.trace_bytes_written.inc(n)
            if record.get("kind") == "round":
                metrics.trace_rounds_recorded.labels(pool=pool or "").inc()
        return n

    def _write_header(self, config, metrics=None):
        cfg = config if config is not None else self._config
        summary = {}
        if cfg is not None:
            summary = {
                "market_driven": bool(cfg.market_driven),
                "batch_fill_window": int(cfg.batch_fill_window),
                "hot_window_slots": int(getattr(cfg, "hot_window_slots", 0)),
                # The offline tuner's baseline vector needs the floor
                # too; older bundles lack the key (readers default it).
                "hot_window_min_slots": int(
                    getattr(cfg, "hot_window_min_slots", 0)
                ),
                "priority_classes": sorted(cfg.priority_classes),
                # Fairness policy (solver/policy.py): default + per-pool
                # map, so the replayer can refuse a cross-policy
                # comparison up front (each round's DeviceRound also
                # carries its own fairness_policy meta). Older bundles
                # lack the keys (pre-policy == DRF everywhere).
                "fairness_policy_default": str(
                    getattr(cfg, "fairness_policy_default", "drf")
                ),
                "fairness_policy_pools": dict(
                    getattr(cfg, "fairness_policy_pools", {})
                ),
            }
        self._write(
            {
                "kind": "header",
                "format": FORMAT,
                "created": time.time(),
                "source": self.source,
                "target": _target_signature(),
                "config_fingerprint": (
                    config_fingerprint(cfg) if cfg is not None else None
                ),
                "config_summary": summary,
                "seeds": self.seeds,
                "meta": self.meta,
                # Round observatory (armada_tpu/observe): rounds in this
                # bundle carry cost accounting in their profile blocks —
                # `transfer` (bytes up/down, donated buffers) and
                # `compiles` (trace/compile deltas) — so replay can diff
                # COST against the recording, not just decisions; plus
                # the fairness ledger + preemption attribution per round
                # (`fairness` blocks — a replay recomputation mismatch
                # is the fairness_ledger divergence kind). Older bundles
                # simply lack the keys (readers default absent).
                "observatory": {"transfer_ledger": True,
                                "compile_telemetry": True,
                                "fairness_ledger": True},
            },
            metrics=metrics,
        )
        self._header_written = True

    def wants_ids(self, num_jobs: int) -> bool:
        """Whether this bundle records id vocabularies at this round
        size — callers can skip BUILDING the O(J) id lists entirely."""
        if self.record_ids is None:
            return num_jobs <= AUTO_IDS_MAX_JOBS
        return bool(self.record_ids)

    # -- recording -----------------------------------------------------

    def record_round(
        self,
        *,
        pool: str,
        dev,
        decisions: dict,
        num_jobs: int,
        num_queues: int,
        config=None,
        cycle: int | None = None,
        now: float | None = None,
        solver: dict | None = None,
        truncated: bool = False,
        profile: dict | None = None,
        solve_s: float | None = None,
        ids: dict | None = None,
        fairness: dict | None = None,
        metrics=None,
    ) -> bool:
        """Append one round. `dev` is the padded DeviceRound exactly as
        handed to the solver; `decisions` the solver's output dict (any
        superset of DECISION_KEYS — extra keys like `profile` are taken
        from the explicit kwargs instead). Returns False when the
        bundle's max_rounds cap is reached."""
        if self.max_rounds is not None and self.rounds_recorded >= self.max_rounds:
            return False
        if not self._header_written:
            self._write_header(config, metrics=metrics)
        record_ids = self.wants_ids(num_jobs)
        record = {
            "kind": "round",
            "i": self.rounds_recorded,
            "pool": pool,
            "cycle": cycle,
            "now": now,
            "num_jobs": int(num_jobs),
            "num_queues": int(num_queues),
            "solver": dict(solver or {}),
            "truncated": bool(truncated),
            "profile": dict(profile) if profile else None,
            "solve_s": solve_s,
            "dev": encode_device_round(dev),
            "decisions": {
                k: encode_field(np.asarray(decisions[k]))
                for k in DECISION_KEYS
                if k in decisions
            },
            "ids": dict(ids) if (ids and record_ids) else None,
            # Fairness observatory (observe/fairness.py): the canonical
            # index-based per-round share ledger + preemption
            # attribution. Plain JSON (doubles round-trip exactly), so
            # a replay's recomputation from this record's own dev +
            # decisions compares bit-for-bit (the fairness_ledger
            # divergence kind).
            "fairness": dict(fairness) if fairness else None,
        }
        self._write(record, metrics=metrics, pool=pool)
        self.rounds_recorded += 1
        return True

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
