"""Policy A/B harness: replay a corpus under candidate fairness policies.

The rollout path for a fairness-policy flip (docs/operations.md,
"Rolling out a fairness policy"): every non-truncated round of a
flight-recorder bundle is RE-SOLVED under each candidate spec
(solver/policy.py) — the policy is swapped into the recorded
DeviceRound's static meta, so each candidate runs the exact round
inputs production saw — and the resulting decision streams are scored
with the same per-round ledger + scorecard aggregation the live
fairness observatory uses (observe/fairness.py). The output is one
scorecard per policy, side by side: Jain trajectory, per-queue
delivered share vs regret, starvation streaks, preemption counts.

This is the EXPLICIT cross-policy comparison: bit-exact differentials
between bundles recorded under different policies are refused
(`trace.replayer.CrossPolicyMismatch`), because shares legitimately
diverge; the A/B harness compares scorecards, not bits. Its scorecard
is also the evidence the control-plane divergence gate wants before a
live flip (SchedulerService.note_policy_shadow / set_fairness_policy).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .replayer import check_target, load_trace, replay_solver

# The default candidate slate: every known policy kind at its default
# parameters (tools/policy_ab.py and `armadactl policy ab` run these
# four unless told otherwise).
DEFAULT_CANDIDATES = ("drf", "proportional", "priority", "deadline")


def _policy_blocks(trace, spec, solve, max_rounds=None):
    """One name-resolved fairness block per non-truncated round,
    re-solved under `spec`."""
    from ..observe.fairness import ledger_from_device_round, resolve_names
    from ..solver import policy as fp

    spec = fp.normalize_spec(spec)
    blocks = []
    for rec in trace.rounds:
        if rec.truncated:
            continue
        if max_rounds is not None and len(blocks) >= max_rounds:
            break
        dev = rec.device_round()
        if spec[0] == "deadline" and dev.queue_deadline is None:
            # Pre-policy bundles carry no deadline vector: every queue
            # reads +inf (factor 1.0) and the candidate degrades to its
            # DRF waterfill instead of refusing the corpus.
            dev = dataclasses.replace(
                dev,
                queue_deadline=np.full(dev.queue_weight.shape[0], np.inf),
            )
        dev = dataclasses.replace(dev, fairness_policy=spec)
        out = solve(dev)
        block = ledger_from_device_round(
            dev, out, rec.num_jobs, rec.num_queues
        )
        ids = rec.raw.get("ids") or {}
        blocks.append(
            resolve_names(
                block,
                queue_names=ids.get("queues"),
                job_ids=ids.get("jobs"),
            )
        )
    return blocks


def ab_compare(
    paths,
    policies=DEFAULT_CANDIDATES,
    *,
    solver="LOCAL",
    allow_foreign: bool = False,
    max_rounds: int | None = None,
) -> dict:
    """Score every candidate policy over the given bundles.

    Returns {"solver": label, "inputs": [...], "policies":
    {policy_str: scorecard}} — scorecards are observe.fairness
    aggregate_scorecard documents, directly comparable across
    candidates because every one replays the same recorded rounds.
    """
    from ..observe.fairness import aggregate_scorecard
    from ..solver import policy as fp

    specs = [fp.normalize_spec(p) for p in policies]
    if not specs:
        raise ValueError("policy A/B needs at least one candidate policy")
    traces = []
    for path in paths:
        trace = load_trace(path)
        check_target(trace.header, allow_foreign=allow_foreign)
        traces.append(trace)
    label = None
    out: dict = {"inputs": [], "policies": {}}
    for spec in specs:
        blocks = []
        for trace in traces:
            label, solve = replay_solver(solver, trace.header)
            blocks += _policy_blocks(trace, spec, solve, max_rounds=max_rounds)
        if not blocks:
            raise ValueError(
                "no scoreable rounds in the given bundles (all truncated "
                "or empty)"
            )
        out["policies"][fp.spec_to_str(spec)] = aggregate_scorecard(blocks)
    out["solver"] = label
    out["inputs"] = [
        {
            "path": t.path,
            "rounds": sum(1 for r in t.rounds if not r.truncated),
            "recorded_policy": _recorded_policy(t),
        }
        for t in traces
    ]
    return out


def _recorded_policy(trace) -> str:
    from .replayer import trace_policies

    pol = trace_policies(trace)
    pools = set(pol["pools"].values())
    if not pools:
        return pol["default"]
    return "/".join(sorted(pools | {pol["default"]}))


def render_ab(result: dict) -> str:
    """The side-by-side operator view of an ab_compare document."""
    lines = []
    for meta in result.get("inputs", []):
        lines.append(
            f"{meta['path']}: {meta['rounds']} round(s), recorded under "
            f"{meta['recorded_policy']} (solver {result.get('solver')})"
        )
    header = (
        f"{'policy':<28} {'jain~':>8} {'jain_min':>9} {'regret^':>8} "
        f"{'starvedΣ':>9} {'preempt':>8}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    cards = result.get("policies", {})
    for name, card in cards.items():
        starved = sum(
            q.get("starved_rounds", 0) for q in card.get("queues", {}).values()
        )
        preempt = sum((card.get("preemptions_attributed") or {}).values())
        lines.append(
            f"{name:<28} {card['jain_mean']:>8.4f} {card['jain_min']:>9.4f} "
            f"{card['max_regret']:>8.4f} {starved:>9} {preempt:>8}"
        )
    queues = sorted(
        {q for card in cards.values() for q in card.get("queues", {})}
    )
    if queues and cards:
        lines.append("")
        lines.append("per-queue delivered share (max regret):")
        names = list(cards)
        head = f"{'queue':<16}" + "".join(f" {n:>24}" for n in names)
        lines.append(head)
        lines.append("-" * len(head))
        for q in queues:
            row = f"{q:<16}"
            for n in names:
                stat = cards[n].get("queues", {}).get(q) or {}
                cell = (
                    f"{stat.get('mean_delivered', 0.0):.4f} "
                    f"({stat.get('max_regret', 0.0):.4f})"
                )
                row += f" {cell:>24}"
            lines.append(row)
    return "\n".join(lines)
