"""Flight recorder: round-trace capture and deterministic replay.

A trace (`.atrace` bundle) is the production-shaped regression corpus
trace-driven evaluations are built on: each scheduler round's solver
inputs (the padded DeviceRound, bit-for-bit), the config fingerprint,
the RNG/fault-plan seeds, and the decision stream the solver produced
(placements, evictions, fair shares, pass-1 loop count, per-segment
profile). Record once — from the live service, the simulator, or the
bench — then replay the round under ANY solver spec (LOCAL fused,
"2x4" HierarchicalDist mesh, hot-window on/off) and diff placements
against the recorded decisions. `tools/replay_gate.py` turns that diff
into a CI gate for candidate kernels.
"""

from .codec import (
    TraceFormatError,
    decode_device_round,
    decode_record,
    encode_device_round,
    encode_record,
)
from .recorder import DECISION_KEYS, TraceRecorder
from .replayer import (
    TraceTargetMismatch,
    check_target,
    compare_round,
    load_trace,
    perturb_device_round,
    replay_solver,
    replay_trace,
)

__all__ = [
    "DECISION_KEYS",
    "TraceFormatError",
    "TraceRecorder",
    "TraceTargetMismatch",
    "check_target",
    "compare_round",
    "decode_device_round",
    "decode_record",
    "encode_device_round",
    "encode_record",
    "load_trace",
    "perturb_device_round",
    "replay_solver",
    "replay_trace",
]
