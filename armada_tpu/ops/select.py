"""Masked lexicographic argmin: the vectorized candidate selection.

The reference iterates nodes from least to most allocatable at the target
priority over the indexed resources, tie-broken by node id
(nodeiteration.go:170-185), and takes the first feasible one. Dense form:
among feasible nodes, take the lexicographic argmin of
(key_0, key_1, ..., id_rank) — computed by iterative mask refinement,
one masked-min reduction per key level. O(K * N), fully parallel, and
reduces cleanly across device shards (each shard returns its local winner;
a tiny cross-shard argmin picks the global one).

The same primitive picks the next queue in the candidate-gang loop (float
cost keys) — any total order expressible as a lexicographic key works.
"""

from __future__ import annotations

import jax.numpy as jnp


def _sentinel(dtype):
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


def masked_min(values, mask):
    """Min of values where mask, else the dtype's max sentinel."""
    return jnp.min(jnp.where(mask, values, _sentinel(values.dtype)))


def masked_keys(keys, mask):
    """Substitute each key's masked-out entries with its dtype's max
    sentinel, so masked entries sort last under any lexicographic order.

    The one shared helper for every sort/argmin call site that needs
    sentinel keys (dist._fill_sort, the fair-preemption walk order):
    callers used to each re-derive and re-broadcast their own sentinel
    per key per call, which both duplicated the pattern and let the
    sentinels drift (BIG vs iinfo.max) between sites."""
    return [jnp.where(mask, k, _sentinel(k.dtype)) for k in keys]


def masked_lexsort(keys, mask):
    """Indices sorting masked entries by lexicographic key (first key
    most significant); masked-out entries sort last."""
    mk = masked_keys(keys, mask)
    # jnp.lexsort: LAST key is primary -> reverse (ours is first-primary).
    return jnp.lexsort(tuple(reversed(mk)))


def lex_argmin(keys, mask):
    """Index of the lexicographically smallest entry among masked entries.

    keys: list of [N] arrays (int or float), most-significant first; the last
    key must be unique among masked entries (e.g. an id rank).
    Returns (index int32, found bool); index is 0 when nothing matches.
    """
    m = mask
    for k in keys:
        best = masked_min(k, m)
        m = m & (k == best)
    found = jnp.any(mask)
    idx = jnp.argmax(m)  # final key unique -> at most one bit set
    return jnp.where(found, idx, 0).astype(jnp.int32), found
