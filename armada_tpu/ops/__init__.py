from .select import lex_argmin, masked_min
from .bitset import bits_subset, bits_disjoint

__all__ = ["lex_argmin", "masked_min", "bits_subset", "bits_disjoint"]
