"""Pallas solve kernels: fused pass-1 scoring blocks + winner reduction.

The kernel seam (ROADMAP item 2): the fill loop's candidate-chain math —
feasibility masking, best-fit bin-pack caps, and the fused int64 K-key
packing — runs as one pass over VMEM-sized node blocks instead of a
chain of materialized [N] intermediates, and the hierarchical winner
exchange reduces gathered per-host tuples with a tree kernel instead of
`all_gather`+argmin.

Three executable paths share ONE scoring body (`_score_block`):

- ``blocked``: `_score_block` applied to the whole node axis as a single
  XLA block, plus the radix-threshold top-B selection (`fill_take`) that
  replaces the per-fill-loop `jnp.lexsort` — the measurable CPU win
  (the threshold walk is O(bits * N) sweeps + one B-sized sort, ~4x the
  65k-node single-key sort on this host).
- ``pallas``: the same body wrapped in `pl.pallas_call` over
  `BLOCK_NODES`-sized node blocks; runs under ``interpret=True``
  everywhere a TPU isn't attached, so CPU tier-1 asserts bit-exactness
  against the lax path block-for-block.
- ``native``: the pallas path compiled for a real TPU plus the ICI ring
  winner exchange (`make_async_remote_copy`), engaged only when
  `native_available()` — a TPU platform behind a healthy
  `utils/platform.relay_preflight` probe. Everywhere else it demotes to
  ``pallas`` so a config typo can't strand a pool.

Bit-exactness is structural, not numerical luck: every op here is
integer/bool (masking, `//`, clips, shifts), the per-node math has no
cross-block reduction, and the packed key is carried as a (hi, lo)
int32 pair — 31 payload bits each — whose recombination
``(hi << 31) | lo`` equals `kernel._pack_fill_keys`'s mixed-radix int64
exactly whenever the pack plan's bit widths sum to <= 62 (each width
<= 31, so no int32 shift overflows). TPU lanes never need an int64.

`CollectiveStats` booking: every pallas call site notes its block count
and VMEM-resident bytes, and the (tree or ring) winner exchange notes
its step count and DMA bytes, at trace time — the fabric cost model is
asserted on CPU even where the hardware isn't (tests/test_pallas_parity.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

# Pallas registers TPU lowering rules at import; where the "tpu" platform
# has been scrubbed from the registry (utils/platform._force_cpu pops the
# factory BEFORE its own pre-import in older orderings) the import itself
# raises. The lax/blocked paths owe nothing to pallas, so a failed import
# only demotes pallas->blocked in resolve_kernel_path.
try:
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover - platform-scrubbed interpreters
    pl = None

try:  # pragma: no cover - import surface depends on jaxlib build
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

KERNEL_PATHS = ("lax", "blocked", "pallas", "native")
PATH_ENV = "ARMADA_TPU_KERNEL_PATH"

# Node-axis block width for the pallas scoring kernel. Padded node counts
# are powers of two >= 8 (kernel_prep._pow2), so BLOCK_NODES always
# divides N or exceeds it; lane-width (128) aligned for the native path.
BLOCK_NODES = 1024

_HI_SHIFT = 31
_LO_MASK = (1 << 31) - 1
_I64_SENTINEL = (1 << 63) - 1


def native_available() -> bool:
    """True only where the native TPU path may engage: a TPU backend is
    attached AND the relay preflight probe reports a healthy fabric.
    Everywhere else (CPU tier-1, broken tunnel) the caller demotes to
    interpret mode, so the probe is the single gate between 'asserted on
    CPU' and 'executed on hardware'."""
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:  # pragma: no cover - backend probe must never raise
        return False
    from ..utils.platform import relay_preflight

    alive, _ = relay_preflight()
    return bool(alive)


def resolve_kernel_path(configured: str = "lax") -> str:
    """The effective solve kernel path for this process.

    ``ARMADA_TPU_KERNEL_PATH`` overrides config (the bench/probe A-B
    lever); unknown values fall back to the configured one rather than
    raising — kernel selection must never take a pool down. ``native``
    demotes to ``pallas`` (interpret mode) unless `native_available()`.
    """
    path = os.environ.get(PATH_ENV, "").strip() or str(configured or "lax")
    if path not in KERNEL_PATHS:
        path = configured if configured in KERNEL_PATHS else "lax"
    if path == "native" and not native_available():
        path = "pallas"
    if path == "pallas" and pl is None:
        path = "blocked"
    return path


def pack_plan(dev, n_shards: int):
    """Static bit widths of the fused fill key, or None when the fused
    path is ineligible (x64 off, or widths overflow the 62-bit budget).
    Mirrors `kernel._pack_fill_keys`'s gate exactly: same widths, same
    fallback — the blocked/pallas paths only engage where the lax path
    would have packed to one int64 too, so their keys are comparable
    bit-for-bit."""
    if not jax.config.jax_enable_x64:
        return None
    n_local = int(dev.node_id_rank.shape[0])
    rank_bits = max(1, (n_local * n_shards - 1).bit_length())
    bits = tuple(
        [max(1, int(b)) for b in dev.order_key_bits] + [rank_bits]
    )
    if sum(bits) > 62 or max(bits) > 31:
        return None
    return bits


def combine_hi_lo(hi, lo):
    """(hi, lo) int32 pair -> the packed int64 fill key."""
    return (hi.astype(jnp.int64) << _HI_SHIFT) | lo.astype(jnp.int64)


def kernel_info(path: str, n_nodes: int | None = None) -> dict:
    """Static kernel-selection facts for bench `extra.kernels` and the
    `scheduler_solve_kernel_info` gauge: the resolved path and the block
    geometry the pallas path would run with."""
    info = {"path": path, "block_nodes": BLOCK_NODES, "interpret": True}
    if path == "native":
        info["interpret"] = False
    if n_nodes:
        nb = min(int(n_nodes), BLOCK_NODES)
        info["blocks"] = max(1, int(n_nodes) // nb)
        info["block_shape"] = [nb]
    return info


# ---------------------------------------------------------------------------
# Fused pass-1 scoring
# ---------------------------------------------------------------------------


def _score_values(
    alloc0,
    node_total,
    node_taints,
    node_labels,
    node_rank,
    node_gid,
    unsched,
    aff_ok,
    tolerated,
    selector,
    req_fit,
    excl,
    job_ok,
    order_res_idx,
    order_res_resolution,
    bits,
    batch_window,
):
    """One node block's candidate-chain values: (fit0, caps, hi, lo).

    The single scoring body shared VERBATIM by the blocked path (whole
    node axis as one block) and the pallas kernel (per-VMEM-block), so
    the two can never drift; all ops are int/bool, so block decomposition
    is exact. int32 masks in/out keep the body legal for TPU lanes."""
    taints_ok = jnp.all((node_taints & ~tolerated[None, :]) == 0, axis=-1)
    sel_ok = jnp.all((selector[None, :] & ~node_labels) == 0, axis=-1)
    total_ok = jnp.all(req_fit[None, :] <= node_total, axis=-1)
    excl_ok = jnp.all(node_gid[:, None] != excl[None, :], axis=-1)
    static_ok = (
        taints_ok
        & sel_ok
        & total_ok
        & excl_ok
        & (aff_ok != 0)
        & (unsched == 0)
        & (job_ok != 0)
    )
    fit0 = static_ok & jnp.all(req_fit[None, :] <= alloc0, axis=-1)
    safe_req = jnp.maximum(req_fit, 1)
    caps = jnp.min(
        jnp.where(req_fit[None, :] > 0, alloc0 // safe_req[None, :], BIG_I32),
        axis=-1,
    )
    caps = jnp.clip(caps, 0, batch_window).astype(jnp.int32)
    hi = jnp.zeros(alloc0.shape[0], jnp.int32)
    lo = jnp.zeros(alloc0.shape[0], jnp.int32)
    n_order = len(bits) - 1
    for k in range(n_order):
        ri = order_res_idx[k]
        res = order_res_resolution[k]
        col = jax.lax.dynamic_index_in_dim(alloc0, ri, axis=1, keepdims=False)
        key = col // res
        b = bits[k]
        kc = jnp.clip(key, 0, (1 << b) - 1).astype(jnp.int32)
        hi = (hi << b) | (lo >> (_HI_SHIFT - b))
        lo = ((lo << b) & _LO_MASK) | kc
    b = bits[-1]
    kc = jnp.clip(node_rank, 0, (1 << b) - 1).astype(jnp.int32)
    hi = (hi << b) | (lo >> (_HI_SHIFT - b))
    lo = ((lo << b) & _LO_MASK) | kc
    return fit0.astype(jnp.int32), caps, hi, lo


# Plain numpy scalar, not a jnp constant: the pallas kernel body closes
# over it, and traced-array captures are rejected under shard_map.
BIG_I32 = np.int32(2**30)


def _score_kernel(
    alloc0_ref,
    total_ref,
    taints_ref,
    labels_ref,
    rank_ref,
    gid_ref,
    unsched_ref,
    aff_ref,
    tol_ref,
    sel_ref,
    req_ref,
    excl_ref,
    jobok_ref,
    oidx_ref,
    ores_ref,
    fit_ref,
    caps_ref,
    hi_ref,
    lo_ref,
    *,
    bits,
    batch_window,
):
    fit0, caps, hi, lo = _score_values(
        alloc0_ref[...],
        total_ref[...],
        taints_ref[...],
        labels_ref[...],
        rank_ref[...],
        gid_ref[...],
        unsched_ref[...],
        aff_ref[...],
        tol_ref[...],
        sel_ref[...],
        req_ref[...],
        excl_ref[...],
        jobok_ref[0],
        oidx_ref[...],
        ores_ref[...],
        bits,
        batch_window,
    )
    fit_ref[...] = fit0
    caps_ref[...] = caps
    hi_ref[...] = hi
    lo_ref[...] = lo


def _score_inputs(dev, alloc0, j, extra_sel):
    """Host/trace-side gathers shared by the blocked and pallas paths:
    the per-job scalars plus the one [N] gather (affinity words) that is
    cheaper outside the block grid than as an in-kernel word lookup."""
    n_idx = dev.node_gid
    a = dev.job_affinity_group[j]
    safe_a = jnp.clip(a, 0, dev.affinity_allowed.shape[0] - 1)
    aff_bits = dev.affinity_allowed[safe_a]
    aff_ok = (a < 0) | (
        (aff_bits[n_idx // 32] >> (n_idx % 32).astype(jnp.uint32)) & 1
    ).astype(bool)
    selector = dev.job_selector[j]
    if extra_sel is not None:
        selector = selector | extra_sel
    return (
        alloc0,
        dev.node_total,
        dev.node_taints,
        dev.node_labels,
        dev.node_id_rank,
        dev.node_gid,
        dev.node_unschedulable.astype(jnp.int32),
        aff_ok.astype(jnp.int32),
        dev.job_tolerated[j],
        selector,
        dev.job_req_fit[j],
        dev.job_excluded_nodes[j],
        dev.job_possible[j].astype(jnp.int32).reshape(1),
        dev.order_res_idx,
        dev.order_res_resolution,
    )


def fill_score(dev, dist, alloc0, j, path, bits, extra_sel=None):
    """The f0 candidate chain — (fit0 mask, per-node caps, [packed key])
    — computed by the blocked or pallas scoring body. Returns exactly
    what `kernel._pass_segment.f0_chain` returns on the lax path for the
    same inputs; `bits` is the (non-None) `pack_plan`. Books the call's
    block/VMEM footprint into `dist.stats` at trace time."""
    args = _score_inputs(dev, alloc0, j, extra_sel)
    B = int(dev.batch_window)
    if path == "blocked":
        fit0, caps, hi, lo = _score_values(*args, bits, B)
    else:
        fit0, caps, hi, lo = _pallas_score(args, bits, B)
        _book_pallas(dist, args)
    return fit0.astype(bool), caps, [combine_hi_lo(hi, lo)]


def _pallas_score(args, bits, batch_window):
    n = int(args[0].shape[0])
    nb = min(n, BLOCK_NODES)
    grid = (n // nb,)

    def node_vec(shape):
        return pl.BlockSpec((nb,) + shape[1:], lambda i: (i,) + (0,) * (len(shape) - 1))

    def replicated(shape):
        return pl.BlockSpec(shape, lambda i: (0,) * len(shape))

    node_major = (True, True, True, True, True, True, True, True)
    in_specs = []
    for arr, is_node in zip(args, node_major + (False,) * (len(args) - 8)):
        spec = node_vec(arr.shape) if is_node else replicated(arr.shape)
        in_specs.append(spec)
    out_shape = [jax.ShapeDtypeStruct((n,), jnp.int32)] * 4
    out_specs = [pl.BlockSpec((nb,), lambda i: (i,))] * 4
    kern = functools.partial(
        _score_kernel, bits=bits, batch_window=batch_window
    )
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=not native_available(),
    )(*args)


def _book_pallas(dist, args, outs_bytes=0):
    stats = getattr(dist, "stats", None)
    if stats is None or not hasattr(stats, "pallas_calls"):
        return
    n = int(args[0].shape[0])
    nb = min(n, BLOCK_NODES)
    blocks = n // nb
    per_block = 0
    for a in args:
        sz = int(np.prod(a.shape)) if a.ndim else 1
        if a.shape and a.shape[0] == n:
            sz = sz // blocks
        per_block += sz * jnp.dtype(a.dtype).itemsize
    per_block += 4 * nb * 4  # the four int32 output blocks
    stats.pallas_calls += 1
    stats.pallas_blocks += blocks
    stats.pallas_vmem_bytes += per_block + outs_bytes


# ---------------------------------------------------------------------------
# Blocked top-B selection (the fill sort replacement)
# ---------------------------------------------------------------------------


def fill_take(key, B, nbits=63):
    """Indices of the B smallest entries of a packed int64 key, in sort
    order — `jnp.lexsort((key,))[:B]` exactly, including the masked
    (sentinel) tail, via radix threshold selection: a bitwise binary
    search for the B-th smallest value (`nbits` O(N) sweeps), a cumsum
    compaction of the flagged entries (first-index tie order = stable
    sort order, keys below the threshold are unique), and one stable
    sort of the B survivors. ~4x the 65k-node lexsort on CPU, and every
    sweep is a block-decomposable elementwise pass — the same walk the
    native kernel tiles over VMEM. Returns (take, key[take])."""
    n = key.shape[0]
    want = min(int(B), n)
    wanti = jnp.int32(want)

    def bit_step(i, lo):
        mid = lo + (jnp.int64(1) << (nbits - 1 - i))
        cnt = jnp.sum((key < mid).astype(jnp.int32))
        return jnp.where(cnt >= wanti, lo, mid)

    lo = jax.lax.fori_loop(0, nbits, bit_step, jnp.int64(0))
    # The nbits-bit search space misses the sentinel; when fewer than
    # `want` keys are real the threshold must swallow the masked tail.
    cnt = jnp.sum((key <= lo).astype(jnp.int32))
    lo = jnp.where(cnt >= wanti, lo, jnp.int64(_I64_SENTINEL))
    flag = key <= lo
    rank = jnp.cumsum(flag.astype(jnp.int32)) - 1
    keep = flag & (rank < wanti)
    pos = jnp.where(keep, rank, want)
    take0 = jnp.zeros(want, jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop"
    )
    kv = key[take0]
    o = jnp.argsort(kv, stable=True)
    return take0[o], kv[o]


def fill_sort_path(keys, mask, B, path, nbits):
    """`dist._fill_sort` with a kernel-path escape hatch: the blocked
    selection engages only for the fused single-int64 key (where it is
    provably lexsort-exact); everything else — multi-key fallback,
    x64-off — keeps the lax sort. Returns (take, masked_keys_list)."""
    from .select import masked_keys

    mk = masked_keys(keys, mask)
    if (
        path in ("blocked", "pallas", "native")
        and nbits is not None
        and len(mk) == 1
        and mk[0].dtype == jnp.int64
    ):
        take, _ = fill_take(mk[0], B, nbits)
        return take, mk
    order = jnp.lexsort(tuple(reversed(mk)))
    return order[:B], mk


# ---------------------------------------------------------------------------
# Winner reduction (the hierarchical select exchange)
# ---------------------------------------------------------------------------


def _winner_kernel(rows_ref, out_ref, *, n_rows, n_keys):
    """Tree-reduce gathered winner tuples to one lexicographic minimum.

    rows: int32[P, n_keys + 2] — (notfound, keys..., gid) with P a power
    of two (padding rows are notfound with sentinel keys). log2(P)
    halving steps, each comparing the upper half against the lower and
    keeping the smaller tuple; ties (only possible between notfound
    rows) keep the LEFT row — first-index order, matching `lex_argmin`.
    """
    rows = rows_ref[...]
    h = n_rows // 2
    while h >= 1:
        a = rows[:h]
        b = jax.lax.dynamic_slice_in_dim(rows, h, h, axis=0)
        b_less = jnp.zeros((h,), bool)
        for c in range(n_keys, -1, -1):  # gid column excluded from compare
            lt = b[:, c] < a[:, c]
            eq = b[:, c] == a[:, c]
            b_less = lt | (eq & b_less)
        rows = jnp.where(b_less[:, None], b, a)
        h //= 2
    out_ref[...] = rows[0]


def winner_reduce(keys, found, gids, dist=None):
    """The host-level winner argmin as a pallas tree kernel.

    keys: list of int32[H] gathered per-host winner keys; found: bool[H];
    gids: int32[H]. Returns (gid, found) — exactly
    `lex_argmin(keys, found)` + gid pick: the last key is the globally
    unique node rank, so the found-row minimum is unique however the
    reduction associates. Runs interpreted off-TPU; on TPU the same
    kernel compiles natively (`tools/pallas_probe.py` smokes both)."""
    h = int(found.shape[0])
    p = 1 << max(0, (h - 1).bit_length())
    nf = jnp.where(found, jnp.int32(0), jnp.int32(1))
    sent = jnp.int32(np.iinfo(np.int32).max)
    cols = [nf]
    for k in keys:
        cols.append(jnp.where(found, k.astype(jnp.int32), sent))
    cols.append(gids.astype(jnp.int32))
    rows = jnp.stack(cols, axis=1)
    if p != h:
        pad = jnp.concatenate(
            [
                jnp.ones((p - h, 1), jnp.int32),
                jnp.full((p - h, len(keys)), sent, jnp.int32),
                jnp.zeros((p - h, 1), jnp.int32),
            ],
            axis=1,
        )
        rows = jnp.concatenate([rows, pad], axis=0)
    kern = functools.partial(
        _winner_kernel, n_rows=p, n_keys=len(keys)
    )
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((len(keys) + 2,), jnp.int32),
        interpret=not native_available(),
    )(rows)
    _book_winner(dist, p, len(keys))
    return out[-1], out[0] == 0


def _book_winner(dist, p, n_keys):
    stats = getattr(dist, "stats", None)
    if stats is None or not hasattr(stats, "ring_steps"):
        return
    steps = max(1, int(np.log2(max(p, 2))))
    stats.pallas_calls += 1
    stats.ring_steps += steps
    # Each tree/ring step moves one (notfound, keys, gid) tuple per
    # participating host pair; booked as the DMA payload of the exchange.
    stats.ring_bytes += steps * (n_keys + 2) * 4
    stats.pallas_vmem_bytes += p * (n_keys + 2) * 4


# ---------------------------------------------------------------------------
# Native ICI ring exchange (TPU only, preflight-gated)
# ---------------------------------------------------------------------------


def ring_winner_exchange(rows, axis_name, n_devices, collective_id=0):
    """One winner tuple per device, reduced around the ICI ring with
    `make_async_remote_copy`: each of the n-1 steps DMAs the running
    minimum to the right neighbour while the comparison of the previous
    arrival overlaps the copy — SNIPPETS.md's ring-permute shape applied
    to a lexicographic min instead of a gather.

    Engaged only behind `native_available()` (TPU + relay preflight);
    tier-1 never executes it, `tools/pallas_probe.py --native` smokes it
    on hardware, and the interpret-mode tree (`winner_reduce`) is the
    bit-exact stand-in everywhere else. rows: int32[n_keys + 2]."""
    if pltpu is None:  # pragma: no cover - jaxlib without pallas TPU
        raise RuntimeError("pallas TPU backend unavailable")
    width = int(rows.shape[0])

    def kern(in_ref, out_ref, comm_ref, send_sem, recv_sem):
        my_id = jax.lax.axis_index(axis_name)
        right = jax.lax.rem(my_id + 1, n_devices)
        out_ref[...] = in_ref[...]
        comm_ref[...] = in_ref[...]

        def step(_, best):
            copy = pltpu.make_async_remote_copy(
                src_ref=comm_ref,
                dst_ref=comm_ref,
                send_sem=send_sem,
                recv_sem=recv_sem,
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            copy.start()
            copy.wait()
            cand = comm_ref[...]
            b_less = jnp.zeros((), bool)
            for c in range(width - 2, -1, -1):
                lt = cand[c] < best[c]
                eq = cand[c] == best[c]
                b_less = lt | (eq & b_less)
            best = jnp.where(b_less, cand, best)
            comm_ref[...] = best
            return best

        best = jax.lax.fori_loop(0, n_devices - 1, step, in_ref[...])
        out_ref[...] = best

    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((width,), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((width,), jnp.int32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=pltpu.TPUCompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
    )(rows)
