"""Bitset predicates on uint32 word arrays (vectorized over leading axes).

These are the device-side forms of the matching predicates in
/root/reference/internal/scheduler/nodedb/nodematching.go: taint tolerance and
node-selector subset checks become single bitwise reductions per node.
"""

from __future__ import annotations

import jax.numpy as jnp


def bits_subset(required, available):
    """True where every set bit of `required` is set in `available`.

    required: [..., W]; available: [..., W] (broadcastable). Used for node
    selectors: job requires labels -> node must carry them all.
    """
    return jnp.all((required & ~available) == 0, axis=-1)


def bits_disjoint(a, b):
    """True where `a & b == 0` across all words. Used for taints: node's
    blocking taints must all be tolerated, i.e. taints & ~tolerated == 0."""
    return jnp.all((a & b) == 0, axis=-1)
