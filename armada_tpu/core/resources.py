"""Resource vocabulary and exact quantity arithmetic.

The design mirrors the role of the reference's resource factory
(/root/reference/internal/scheduler/internaltypes/resource_list_factory.go:20)
but is column-oriented from the start: a ResourceList here is a numpy int64
vector (or a batch of them), not a per-object struct. The factory fixes the
resource-name -> index mapping and, like the reference, converts Kubernetes
quantities to int64 at a per-resource power-of-ten scale derived from the
configured resolution (resource_list_factory.go:61-71). Node quantities round
down, job-request quantities round up, so scheduling stays conservative.

A second, coarser per-resource scale ("device scale") maps the exact int64
host values onto int32 device lanes for the TPU solve. int64 arithmetic is
slow on TPU; int32 with e.g. memory in MiB covers 2 PiB per node, far beyond
any real machine. Requests are ceil-scaled and allocatable floor-scaled so a
device-side "fits" never overstates capacity.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from fractions import Fraction

import numpy as np

try:  # C++ fast path (native/quantity.cpp); exact-Fraction fallback below.
    import _armada_native as _native
except ImportError:  # pragma: no cover
    _native = None


def ensure_native(timeout: float = 180.0) -> bool:
    """Build the C++ quantity parser (native/) if it isn't importable yet
    and load it; returns availability. The .so is a build artifact (not
    committed), so fresh checkouts compile it on first demand — callers on
    hot startup paths (bench, server boot) invoke this once up front."""
    global _native
    if _native is not None:
        return True
    import importlib
    import pathlib
    import subprocess
    import sys

    root = pathlib.Path(__file__).resolve().parents[2]
    ndir = root / "native"
    if not (ndir / "setup.py").exists():  # pragma: no cover
        return False
    try:
        subprocess.run(
            [sys.executable, "setup.py", "build_ext", "--inplace"],
            cwd=ndir,
            capture_output=True,
            timeout=timeout,
            check=True,
        )
        for so in ndir.glob("_armada_native*.so"):
            dest = root / so.name
            if not dest.exists():
                dest.write_bytes(so.read_bytes())
        if str(root) not in sys.path:
            sys.path.insert(0, str(root))
        _native = importlib.import_module("_armada_native")
        return True
    except Exception:  # pragma: no cover
        return False

# Binary and decimal suffixes accepted by Kubernetes resource quantities.
_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}


def parse_quantity(value) -> Fraction:
    """Parse a Kubernetes-style resource quantity into an exact Fraction.

    Accepts ints/floats ("1", 0.5) and strings ("100m", "1.5Gi", "2e3").
    """
    if isinstance(value, Fraction):
        return value
    if isinstance(value, (int, np.integer)):
        return Fraction(int(value))
    if isinstance(value, float):
        return Fraction(str(value))
    s = str(value).strip()
    if not s:
        raise ValueError("empty quantity")
    for suffix, mult in _BINARY.items():
        if s.endswith(suffix):
            return Fraction(s[: -len(suffix)]) * mult
    # Suffix check must precede scientific notation: "5E" is 5 exa,
    # while "5e3"/"5E3" (digit last) is scientific.
    if s[-1] in _DECIMAL and not s[-1].isdigit():
        return Fraction(s[:-1]) * _DECIMAL[s[-1]]
    if "e" in s or "E" in s:
        head, _, exp = s.partition("e" if "e" in s else "E")
        return Fraction(head) * Fraction(10) ** int(exp)
    return Fraction(s)


def _resolution_to_scale(resolution) -> int:
    """Power-of-ten scale for a resolution, as in resource_list_factory.go:66.

    "1m"/0.001 -> -3 (store millis), "1" -> 0, "100Mi" -> 8 (1e8 ~ 100Mi).
    Non-positive resolutions default to milli.
    """
    r = parse_quantity(resolution)
    if r <= 0:
        return -3
    return math.floor(math.log10(float(r)))


_factory_serial = 0


@dataclass(frozen=True)
class ResourceListFactory:
    """Fixed resource-name vocabulary with exact int64 host encoding.

    names[i] is the canonical resource at index i; host int64 values are the
    quantity divided by 10^scale[i]. device_scale[i] further divides host
    values for the int32 device tensors.
    """

    names: tuple[str, ...]
    scales: tuple[int, ...]  # power-of-ten per resource (host encoding)
    device_divisor: tuple[int, ...]  # host units per device unit (int32 lanes)
    # True for pool-level floating resources (not attached to nodes).
    floating: tuple[bool, ...] = ()
    name_to_index: dict[str, int] = field(default_factory=dict)
    # Process-unique id tagging rows cached on spec objects (see
    # encode_cached_batch); id() is unsafe across GC reuse.
    serial: int = 0

    @staticmethod
    def create(
        supported: list[tuple[str, object]],
        floating: list[tuple[str, object]] = (),
        device_divisors: dict[str, int] | None = None,
    ) -> "ResourceListFactory":
        """supported/floating: [(name, resolution)], mirroring
        supportedResourceTypes + floatingResourceTypes config."""
        names, scales = [], []
        floating = list(floating)
        floating_flags = []
        for name, resolution in list(supported) + floating:
            if name in names:
                raise ValueError(f"duplicate resource type {name!r}")
            names.append(name)
            scales.append(_resolution_to_scale(resolution))
            floating_flags.append(len(floating_flags) >= len(supported))
        divisors = []
        device_divisors = device_divisors or {}
        for name, scale in zip(names, scales):
            if name in device_divisors:
                divisors.append(int(device_divisors[name]))
            else:
                # Default: keep cpu-like milli resources as-is; compress
                # byte-like resources (scale 0 with huge ranges) to ~Mi.
                divisors.append(1 if scale != 0 else _default_divisor(name))
        global _factory_serial
        _factory_serial += 1
        factory = ResourceListFactory(
            names=tuple(names),
            scales=tuple(scales),
            device_divisor=tuple(divisors),
            floating=tuple(floating_flags),
            serial=_factory_serial,
        )
        factory.name_to_index.update({n: i for i, n in enumerate(names)})
        return factory

    def floating_mask(self) -> np.ndarray:
        return np.asarray(self.floating, dtype=bool)

    @property
    def num_resources(self) -> int:
        return len(self.names)

    def index_of(self, name: str) -> int:
        return self.name_to_index[name]

    # ---- host encoding (exact int64) ----

    def from_map(self, resources: dict, *, ceil: bool, strict: bool = False) -> np.ndarray:
        """Encode {name: quantity} into an int64 vector.

        ceil=True for job requests (round up), False for node allocatable
        (round down), mirroring FromJobResourceListFailOnUnknown vs
        FromNodeProto (resource_list_factory.go:87-120). Unknown resources are
        ignored unless strict.
        """
        out = np.zeros(self.num_resources, dtype=np.int64)
        for name, quantity in (resources or {}).items():
            i = self.name_to_index.get(name)
            if i is None:
                if strict:
                    raise KeyError(f"unknown resource {name!r}")
                continue
            scaled = parse_quantity(quantity) / (Fraction(10) ** self.scales[i])
            value = int(math.ceil(scaled) if ceil else math.floor(scaled))
            # Saturate: absurd quantities (e.g. "1Ei" at byte scale) clamp
            # rather than crash, matching the native parser.
            out[i] = min(max(value, -(2**63)), 2**63 - 1)
        return out

    def encode_requests_batch(self, requests: list, *, ceil: bool) -> np.ndarray:
        """Encode a batch of {name: quantity} dicts into int64[J, R].

        Distinct request shapes are parsed once (real workloads submit
        thousands of identical specs), via the native C++ parser when built
        (~100x the Fraction path; bit-identical exact int128 arithmetic,
        fuzz-tested), else the Fraction path.
        """
        J = len(requests)
        R = self.num_resources
        # Uniquify by item tuple: one parse per distinct request dict.
        keys = [
            tuple(sorted(r.items())) if r else () for r in requests
        ]
        uniq_idx: dict = {}
        uniq_reqs: list = []
        rows = np.empty(J, dtype=np.int64)
        for j, k in enumerate(keys):
            i = uniq_idx.get(k)
            if i is None:
                i = len(uniq_reqs)
                uniq_idx[k] = i
                uniq_reqs.append(requests[j])
            rows[j] = i
        parsed = self._encode_unique(uniq_reqs, ceil=ceil)
        return parsed[rows] if J else np.zeros((0, R), dtype=np.int64)

    def encode_cached_batch(self, objs: list, get, *, ceil: bool, tag: str) -> np.ndarray:
        """encode_requests_batch with a per-object row cache.

        The scheduler re-snapshots the same JobSpec/NodeSpec objects every
        cycle; their encoded rows never change, so each object carries its
        row (stored via object.__setattr__ — the spec dataclasses are
        frozen but not slotted), tagged with (factory serial, ceil, tag) so
        a different factory or rounding mode never reads a stale row. Warm
        cycles skip all quantity parsing: cost is one dict probe per
        object. `get(obj)` returns the {name: quantity} dict for misses."""
        J = len(objs)
        rows = np.empty((J, self.num_resources), dtype=np.int64)
        want = (self.serial, ceil, tag)
        misses: list = []
        miss_at: list = []
        for j, obj in enumerate(objs):
            cached = obj.__dict__.get("_enc_row")
            if cached is not None and cached[0] == want:
                rows[j] = cached[1]
            else:
                misses.append(obj)
                miss_at.append(j)
        if misses:
            enc = self.encode_requests_batch(
                [get(o) for o in misses], ceil=ceil
            )
            for k, obj in enumerate(misses):
                rows[miss_at[k]] = enc[k]
                # Copy: enc[k] is a view whose base is the full [misses, R]
                # batch; caching the view would pin the whole batch in
                # memory for as long as any one job object lives.
                object.__setattr__(obj, "_enc_row", (want, enc[k].copy()))
        return rows

    def _encode_unique(self, requests: list, *, ceil: bool) -> np.ndarray:
        U = len(requests)
        if _native is not None and U:
            try:
                raw = _native.encode_requests(
                    list(requests), list(self.names), list(self.scales), ceil
                )
                return (
                    np.frombuffer(raw, dtype=np.int64)
                    .reshape(U, self.num_resources)
                    .copy()
                )
            except (ValueError, TypeError):
                # The Fraction path accepts a slightly wider grammar (e.g.
                # Fraction instances, "1e3Ki"); fall back rather than let
                # parser strictness depend on whether the extension is built.
                pass
        out = np.zeros((U, self.num_resources), dtype=np.int64)
        for j, req in enumerate(requests):
            out[j] = self.from_map(req, ceil=ceil)
        return out

    def to_map(self, vec: np.ndarray) -> dict[str, Fraction]:
        """Decode an int64 vector back to {name: exact quantity}."""
        return {
            name: Fraction(int(vec[i])) * Fraction(10) ** self.scales[i]
            for i, name in enumerate(self.names)
            if vec[i] != 0
        }

    def zeros(self, *batch: int) -> np.ndarray:
        return np.zeros((*batch, self.num_resources), dtype=np.int64)

    # ---- device encoding (int32 lanes) ----

    def to_device(self, host_vals: np.ndarray, *, ceil: bool) -> np.ndarray:
        """Scale host int64 values to int32 device units.

        Requests ceil, allocatable floor: a device-side fit check is then
        always at least as strict as the exact host check.
        """
        div = np.asarray(self.device_divisor, dtype=np.int64)
        v = np.asarray(host_vals, dtype=np.int64)
        scaled = -((-v) // div) if ceil else v // div
        lo, hi = np.iinfo(np.int32).min, np.iinfo(np.int32).max
        return np.clip(scaled, lo, hi).astype(np.int32)


def _default_divisor(name: str) -> int:
    byte_like = ("memory", "storage", "disk", "ephemeral")
    if any(t in name for t in byte_like):
        return 2**20  # Mi
    return 1
