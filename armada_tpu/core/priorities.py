"""Priority classes and the priority axis of the allocatable tensor.

Mirrors the semantics of the reference's PriorityClass config type
(/root/reference/internal/common/types/ and config/scheduler/config.yaml:89-100)
and the EvictedPriority convention (-1: the row of the allocatable tensor that
counts *everything* bound, including evicted jobs, so that a fit at
EvictedPriority means "schedulable without preempting anyone").
"""

from __future__ import annotations

from dataclasses import dataclass, field

EVICTED_PRIORITY: int = -1
MIN_PRIORITY: int = -(2**31)


@dataclass(frozen=True)
class AwayNodeType:
    """Fallback scheduling target: a well-known node type (named taint set)
    the job may run on at a reduced priority (types.AwayNodeType in the
    reference; nodedb.go:487-501)."""

    priority: int
    well_known_node_type: str


@dataclass(frozen=True)
class PriorityClass:
    name: str
    priority: int
    preemptible: bool = False
    # Per-queue resource-fraction caps for jobs of this class
    # (maximumResourceFractionPerQueue in the reference config).
    maximum_resource_fraction_per_queue: dict[str, float] = field(default_factory=dict)
    # Per-pool overrides of the above.
    maximum_resource_fraction_per_queue_by_pool: dict[str, dict[str, float]] = field(
        default_factory=dict
    )
    # Ordered fallback targets tried after home scheduling fails.
    away_node_types: tuple = ()  # tuple[AwayNodeType, ...]


def priority_levels(priority_classes: dict[str, PriorityClass]) -> list[int]:
    """Distinct scheduling priorities, ascending, prefixed by EvictedPriority.

    This is the P axis of the allocatable[P, N, R] tensor; mirrors
    nodeDbPriorities in the reference nodedb. Away priorities are scheduling
    priorities too, so they get rows.
    """
    levels = {pc.priority for pc in priority_classes.values()}
    for pc in priority_classes.values():
        for away in pc.away_node_types:
            if away.priority <= EVICTED_PRIORITY:
                raise ValueError(
                    f"away priority {away.priority} of class {pc.name!r} must "
                    f"be greater than the evicted priority {EVICTED_PRIORITY}"
                )
            levels.add(away.priority)
    return [EVICTED_PRIORITY] + sorted(levels)
