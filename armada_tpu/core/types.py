"""Host-side domain objects: jobs, nodes, queues, taints/tolerations.

These are the API-level records that flow in from submissions and executor
snapshots; the snapshot package flattens batches of them into dense tensors.
They mirror the information content of the reference's jobdb.Job
(/root/reference/internal/scheduler/jobdb/job.go:23), internaltypes.Node
(internaltypes/node.go:26) and the queue API type, without the Go-specific
immutability machinery (columnar stores handle that here).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace

def _clean_price(x) -> float:
    try:
        v = float(x)
    except (TypeError, ValueError):
        return 0.0
    return v if math.isfinite(v) else 0.0


NO_SCHEDULE = "NoSchedule"
NO_EXECUTE = "NoExecute"
PREFER_NO_SCHEDULE = "PreferNoSchedule"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE

    @property
    def blocks_scheduling(self) -> bool:
        # PreferNoSchedule never blocks placement (soft preference).
        return self.effect in (NO_SCHEDULE, NO_EXECUTE)


@dataclass(frozen=True)
class ServiceConfig:
    """A service exposed for the job's pod (pkg/api job.Services;
    executor/job/submit.go creates the k8s Service owned by the pod).
    type: NodePort | Headless (the reference's ServiceType values)."""

    type: str = "NodePort"
    ports: tuple = ()  # of int

    @staticmethod
    def from_obj(s: dict) -> "ServiceConfig":
        """Canonical decode shared by every wire codec (JSON dict, event
        log, proto json_format, CLI YAML): int ports, so equal jobs
        decode identically across encodings."""
        return ServiceConfig(
            type=s.get("type", "NodePort"),
            ports=tuple(int(p) for p in s.get("ports") or ()),
        )


@dataclass(frozen=True)
class IngressConfig:
    """An ingress for the job's pod (pkg/api job.Ingress; created by the
    executor alongside the pod and garbage-collected with it)."""

    ports: tuple = ()  # of int
    annotations: tuple = ()  # of (key, value) pairs (hashable)
    tls_enabled: bool = False

    @staticmethod
    def from_obj(i: dict) -> "IngressConfig":
        """Canonical decode (see ServiceConfig.from_obj): annotations
        arrive as pairs or a map; stored sorted either way."""
        ann = i.get("annotations") or ()
        pairs = ann.items() if isinstance(ann, dict) else (
            tuple(kv) for kv in ann
        )
        return IngressConfig(
            ports=tuple(int(p) for p in i.get("ports") or ()),
            annotations=tuple(sorted(pairs)),
            tls_enabled=bool(i.get("tls_enabled", False)),
        )


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # "Equal" | "Exists"
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: Taint) -> bool:
        """Kubernetes toleration semantics (core/v1 Toleration.ToleratesTaint)."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key == "":
            # Empty key with Exists tolerates everything.
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass(frozen=True)
class MatchExpression:
    """One node-affinity requirement (core/v1 NodeSelectorRequirement)."""

    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: tuple = ()

    def matches(self, node_labels: dict) -> bool:
        value = node_labels.get(self.key)
        if self.operator == "In":
            return value is not None and str(value) in self.values
        if self.operator == "NotIn":
            # k8s labels.Requirement: NotIn matches when the key is absent.
            return value is None or str(value) not in self.values
        if self.operator == "Exists":
            return value is not None
        if self.operator == "DoesNotExist":
            return value is None
        if self.operator == "Gt":
            try:
                return value is not None and int(value) > int(self.values[0])
            except (ValueError, IndexError):
                return False
        if self.operator == "Lt":
            try:
                return value is not None and int(value) < int(self.values[0])
            except (ValueError, IndexError):
                return False
        # Unknown operators match nothing (submission validates upstream;
        # the scheduler must not crash on one malformed job).
        return False


@dataclass(frozen=True)
class NodeSelectorTerm:
    """AND of match expressions (one term of a NodeSelector)."""

    expressions: tuple = ()  # tuple[MatchExpression, ...]

    def matches(self, node_labels: dict) -> bool:
        # k8s MatchNodeSelectorTerms: a nil/empty term matches no objects.
        if not self.expressions:
            return False
        return all(e.matches(node_labels) for e in self.expressions)


@dataclass(frozen=True)
class Affinity:
    """requiredDuringSchedulingIgnoredDuringExecution node affinity:
    OR over terms (core/v1 NodeSelector; MatchNodeSelectorTerms in the
    reference, nodematching.go:242-255)."""

    terms: tuple = ()  # tuple[NodeSelectorTerm, ...]

    def matches(self, node_labels: dict) -> bool:
        if not self.terms:
            return True
        return any(t.matches(node_labels) for t in self.terms)


@dataclass(frozen=True)
class Gang:
    """Gang (all-or-nothing) membership, from job annotations in the
    reference (gangId/gangCardinality/gangNodeUniformityLabel)."""

    id: str
    cardinality: int
    node_uniformity_label: str = ""


@dataclass(frozen=True)
class JobSpec:
    """A schedulable job. requests: {resource: quantity}."""

    id: str
    queue: str
    jobset: str = ""
    # Pools this job may be scheduled in (job.Pools() in the reference);
    # empty = eligible for every pool. A pool's round only considers
    # queued jobs eligible for it (getQueuedJobs, scheduling_algo.go:533).
    pools: tuple = ()
    priority: int = 0  # within-queue ordering: lower schedules first
    priority_class: str = ""
    requests: dict = field(default_factory=dict)
    node_selector: dict = field(default_factory=dict)  # label -> required value
    tolerations: tuple[Toleration, ...] = ()
    affinity: Affinity | None = None
    gang: Gang | None = None
    submitted_ts: float = 0.0
    annotations: dict = field(default_factory=dict)
    # Market mode: bid price per pool (pkg/bidstore; job.GetBidPrice).
    bid_prices: dict = field(default_factory=dict)
    # Container command argv (podspec containers[0].command+args in the
    # reference). Empty = simulated runtime; a subprocess-backed executor
    # runs it as a real OS process.
    command: tuple = ()
    # Services/ingresses the executor creates alongside the pod
    # (pkg/api submit job.Services/job.Ingress; executor/job/submit.go).
    services: tuple = ()  # of ServiceConfig
    ingresses: tuple = ()  # of IngressConfig

    def bid_price(self, pool: str, *, running: bool = False) -> float:
        """Bid for this pool's given phase (see bid_price_pair)."""
        pair = self.bid_price_pair(pool)
        return pair[1] if running else pair[0]

    def bid_price_pair(self, pool: str) -> tuple[float, float]:
        """(queued, running) bids for this pool in one key lookup — the
        snapshot builder needs both phases per job (post-round pricing
        reads running-phase bids for just-leased jobs). Malformed or
        non-finite user-supplied values count as 0 (one bad annotation
        must not abort scheduling rounds or poison price ordering).
        Values may be scalars or (queued, running) phase pairs as written
        by the bid-price provider (pricing.Bid / jobdb job.getBidPrice
        phase selection)."""
        for key in (pool, ""):
            if key in self.bid_prices:
                v = self.bid_prices[key]
                if isinstance(v, (tuple, list)) and len(v) == 2:
                    return _clean_price(v[0]), _clean_price(v[1])
                p = _clean_price(v)
                return p, p
        p = _clean_price(self.annotations.get("armadaproject.io/bidPrice", 0.0))
        return p, p

    def with_(self, **kw) -> "JobSpec":
        return replace(self, **kw)


@dataclass(frozen=True)
class NodeSpec:
    """A worker node as reported by an executor."""

    id: str
    name: str = ""
    executor: str = ""
    pool: str = "default"
    taints: tuple[Taint, ...] = ()
    labels: dict = field(default_factory=dict)
    total_resources: dict = field(default_factory=dict)
    # Resources already used by pods outside the scheduler's control,
    # per priority level: {priority: {resource: qty}}.
    unallocatable_by_priority: dict = field(default_factory=dict)
    unschedulable: bool = False

    def label_value(self, key: str):
        return self.labels.get(key)


@dataclass(frozen=True)
class QueueSpec:
    name: str
    priority_factor: float = 1.0

    @property
    def weight(self) -> float:
        # weight = 1 / priorityFactor, as in the reference scheduling context
        # construction (scheduling_algo.go:411+).
        return 1.0 / max(self.priority_factor, 1e-9)


@dataclass(frozen=True)
class RunningJob:
    """A job currently bound to a node (input to round snapshots)."""

    job: JobSpec
    node_id: str
    scheduled_at_priority: int
    # When the active run was leased (market anti-churn ordering:
    # longer-running jobs reschedule first, comparison.go:148-153).
    leased_ts: float = 0.0
    # Cross-pool away job: its run belongs to a pool that borrows nodes
    # from the round's pool (run.pool in awayAllocationPools,
    # scheduling_algo.go:421-426,658-666). It accounts under the phantom
    # "<queue>-away" fairness bucket (context/util.go CalculateAwayQueueName)
    # and is an eviction candidate only when bound to one of this round's
    # nodes; unbound away jobs contribute allocation pressure only.
    away: bool = False
