from .resources import ResourceListFactory, parse_quantity
from .priorities import PriorityClass, EVICTED_PRIORITY
from .config import SchedulingConfig, PoolConfig, ResourceType

__all__ = [
    "ResourceListFactory",
    "parse_quantity",
    "PriorityClass",
    "EVICTED_PRIORITY",
    "SchedulingConfig",
    "PoolConfig",
    "ResourceType",
]
